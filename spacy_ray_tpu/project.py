"""Minimal spaCy-projects-style workflow runner (`project run`).

spaCy users orchestrate convert/train/evaluate chains with a
``project.yml`` of named commands and workflows; the reference repo's
README assumes that ecosystem around `spacy ray train`. This module
covers the core surface:

* ``project.yml`` with ``vars``, ``commands`` (name / script / deps /
  outputs / help) and ``workflows`` (name -> list of command names).
* ``${vars.x}`` interpolation in scripts/deps/outputs.
* make-style short-circuit: a command is SKIPPED when every declared
  output exists and is at least as new as every declared dep (spaCy
  skips on its own lockfile hashes; mtime is the dependency-tracking
  equivalent that needs no state file).
* ``--force`` reruns regardless; a failing script aborts the chain.

Assets/remote storage are intentionally absent (zero-egress image);
`deps` on local files cover the in-image need.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional

_VAR = re.compile(r"\$\{vars\.([A-Za-z0-9_]+)\}")


class ProjectError(ValueError):
    pass


def _interp(value: str, variables: Dict[str, Any]) -> str:
    def sub(m: "re.Match[str]") -> str:
        key = m.group(1)
        if key not in variables:
            raise ProjectError(
                f"undefined ${{vars.{key}}} (defined: {sorted(variables)})"
            )
        return str(variables[key])

    return _VAR.sub(sub, value)


def _str_list(raw: Dict[str, Any], key: str, name: str,
              variables: Dict[str, Any]) -> List[str]:
    """Interpolated list-of-strings field; a YAML scalar (a common slip,
    `script: echo hi`) must error, not be iterated character by character."""
    value = raw.get(key) or []
    if not isinstance(value, list) or not all(
        isinstance(s, str) for s in value
    ):
        raise ProjectError(
            f"command {name!r}: {key} must be a list of strings, "
            f"got {value!r}"
        )
    return [_interp(s, variables) for s in value]


def load_project(project_dir: Path) -> Dict[str, Any]:
    try:
        import yaml
    except ImportError as e:  # pragma: no cover - present in dev images
        raise ProjectError(
            "the project command needs PyYAML (declared in pyproject; "
            f"import failed: {e})"
        )

    path = project_dir / "project.yml"
    if not path.exists():
        raise ProjectError(f"no project.yml in {project_dir}")
    try:
        data = yaml.safe_load(path.read_text(encoding="utf8")) or {}
    except yaml.YAMLError as e:
        raise ProjectError(f"{path} is not valid YAML: {e}")
    if not isinstance(data, dict):
        raise ProjectError(f"{path} must hold a mapping")
    variables = data.get("vars") or {}
    commands: Dict[str, Dict[str, Any]] = {}
    for raw in data.get("commands") or []:
        if not isinstance(raw, dict) or "name" not in raw:
            raise ProjectError(f"command entries need a name: {raw!r}")
        name = raw["name"]
        if name in commands:
            raise ProjectError(f"duplicate command name {name!r}")
        commands[name] = {
            "name": name,
            "help": raw.get("help", ""),
            "script": _str_list(raw, "script", name, variables),
            "deps": _str_list(raw, "deps", name, variables),
            "outputs": _str_list(raw, "outputs", name, variables),
        }
    workflows: Dict[str, List[str]] = {}
    for wf_name, steps in (data.get("workflows") or {}).items():
        steps = list(steps or [])
        unknown = [s for s in steps if s not in commands]
        if unknown:
            raise ProjectError(
                f"workflow {wf_name!r} references unknown commands {unknown} "
                f"(have: {sorted(commands)})"
            )
        workflows[wf_name] = steps
    return {"vars": variables, "commands": commands, "workflows": workflows}


def _up_to_date(cmd: Dict[str, Any], project_dir: Path) -> bool:
    outputs = [project_dir / o for o in cmd["outputs"]]
    if not outputs or not all(o.exists() for o in outputs):
        return False
    deps = [project_dir / d for d in cmd["deps"]]
    missing = [d for d in deps if not d.exists()]
    if missing:
        raise ProjectError(
            f"command {cmd['name']!r} depends on missing file(s): "
            f"{[str(m) for m in missing]}"
        )
    newest_dep = max((d.stat().st_mtime for d in deps), default=0.0)
    oldest_out = min(o.stat().st_mtime for o in outputs)
    return oldest_out >= newest_dep


def run_command(cmd: Dict[str, Any], project_dir: Path,
                force: bool = False, dry: bool = False) -> bool:
    """Run one command's script lines. Returns True if executed, False if
    skipped as up-to-date. ``dry`` prints what WOULD run (after the same
    skip logic) without executing anything — spaCy's `project run --dry`."""
    if not force and _up_to_date(cmd, project_dir):
        print(f"[{cmd['name']}] up to date (outputs newer than deps); skipped")
        return False
    if dry:
        for line in cmd["script"]:
            print(f"[{cmd['name']}] (dry) $ {line}")
        return True
    # scripts invoking `python -m spacy_ray_tpu ...` must resolve to THIS
    # library even when it is not pip-installed (repo checkout run from an
    # arbitrary project_dir): export the package root on PYTHONPATH
    pkg_root = str(Path(__file__).parent.parent)
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        pkg_root + (os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    )
    for line in cmd["script"]:
        # a leading `python`/`python3` token means THIS interpreter
        # (spaCy's runner does the same): python3-only hosts have no
        # `python` shim — and `python3` is the more common spelling there —
        # and a PATH interpreter may not be the venv this package lives in
        for token in ("python3", "python"):
            if line == token or line.startswith(token + " "):
                line = sys.executable + line[len(token):]
                break
        print(f"[{cmd['name']}] $ {line}", flush=True)
        proc = subprocess.run(line, shell=True, cwd=str(project_dir), env=env)
        if proc.returncode != 0:
            raise ProjectError(
                f"command {cmd['name']!r} failed (exit {proc.returncode}) "
                f"on: {line}"
            )
    return True


def project_run(project_dir: Path, target: str, force: bool = False,
                dry: bool = False) -> int:
    """Run a named command or workflow. Returns count of commands executed
    (or, under ``dry``, that would have executed)."""
    project = load_project(project_dir)
    if target in project["workflows"]:
        names = project["workflows"][target]
    elif target in project["commands"]:
        names = [target]
    else:
        available = sorted(project["workflows"]) + sorted(project["commands"])
        raise ProjectError(
            f"no workflow or command {target!r} (available: {available})"
        )
    ran = 0
    for name in names:
        if run_command(project["commands"][name], project_dir, force=force,
                       dry=dry):
            ran += 1
    return ran


def main(argv: List[str]) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="spacy_ray_tpu project")
    sub = parser.add_subparsers(dest="subcommand", required=True)
    run_p = sub.add_parser("run", help="run a named command or workflow")
    run_p.add_argument("target")
    run_p.add_argument("project_dir", type=Path, nargs="?", default=Path("."))
    run_p.add_argument("--force", action="store_true",
                       help="rerun even when outputs are up to date")
    run_p.add_argument("--dry", action="store_true",
                       help="print what would run without executing")
    doc_p = sub.add_parser("document", help="print commands and workflows")
    doc_p.add_argument("project_dir", type=Path, nargs="?", default=Path("."))
    args = parser.parse_args(argv)

    try:
        if args.subcommand == "document":
            project = load_project(args.project_dir)
            print("Commands:")
            for name, cmd in project["commands"].items():
                print(f"  {name:20s} {cmd['help']}")
            print("Workflows:")
            for name, steps in project["workflows"].items():
                print(f"  {name:20s} {' -> '.join(steps)}")
            return 0
        ran = project_run(args.project_dir, args.target, force=args.force,
                          dry=args.dry)
        verb = "would execute" if args.dry else "executed"
        print(f"Done: {ran} command(s) {verb}")
        return 0
    except ProjectError as e:
        print(f"project error: {e}", file=sys.stderr)
        return 1
