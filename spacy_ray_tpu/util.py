"""Utilities: timers + synthetic data generation.

Timer/ManyTimer mirror the reference's instrumentation scaffolding
(reference util.py:9-38) but are actually wired: the loop and bench use them.
The synthetic corpus generator backs tests and bench.py (the reference pulls
a fashion-brands NER corpus in bin/get-data.sh; tests here must run
hermetically with zero egress).
"""

from __future__ import annotations

import random
import time
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional

from .pipeline.doc import Doc, Example, Span


class Timer:
    """Accumulating context-manager timer (reference util.py:9-29)."""

    def __init__(self, name: str = ""):
        self.name = name
        self.total = 0.0
        self.n = 0
        self._start: Optional[float] = None

    def __enter__(self) -> "Timer":
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        assert self._start is not None
        self.total += time.perf_counter() - self._start
        self.n += 1
        self._start = None

    @property
    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0


class ManyTimer:
    """Keyed timer registry (reference util.py:32-38)."""

    def __init__(self):
        self.timers: Dict[str, Timer] = {}

    def __call__(self, name: str) -> Timer:
        if name not in self.timers:
            self.timers[name] = Timer(name)
        return self.timers[name]

    def report(self) -> str:
        return "; ".join(
            f"{t.name}: total={t.total:.3f}s mean={t.mean*1000:.1f}ms n={t.n}"
            for t in self.timers.values()
        )


# ----------------------------------------------------------------------
# Synthetic corpora
# ----------------------------------------------------------------------

_POS_VOCAB = {
    "DET": ["the", "a", "an", "this", "that"],
    "NOUN": ["cat", "dog", "tree", "market", "chip", "tensor", "mesh", "house"],
    "VERB": ["runs", "jumps", "compiles", "shards", "eats", "sees", "builds"],
    "ADJ": ["green", "fast", "large", "tiny", "sharded", "parallel"],
    "ADV": ["quickly", "slowly", "very", "almost"],
    "PROPN": ["Alice", "Bob", "Jax", "Pallas", "Austin", "Tokyo"],
    "ADP": ["in", "on", "under", "over", "with"],
    "PRON": ["he", "she", "it", "they", "we"],
}

_ENT_LABELS = {
    "PERSON": ["Alice Smith", "Bob Jones", "Carol White"],
    "ORG": ["Acme Corp", "Globex Inc", "Initech LLC"],
    "GPE": ["Austin", "Tokyo", "Berlin", "Paris"],
}


def synth_tagged_doc(rng: random.Random, min_len: int = 4, max_len: int = 24) -> Doc:
    """A doc whose tags are recoverable from word identity (learnable)."""
    n = rng.randint(min_len, max_len)
    words: List[str] = []
    tags: List[str] = []
    pos_names = list(_POS_VOCAB)
    for _ in range(n):
        pos = rng.choice(pos_names)
        words.append(rng.choice(_POS_VOCAB[pos]))
        tags.append(pos)
    return Doc(words=words, tags=tags, pos=list(tags))


def synth_ner_doc(rng: random.Random, min_len: int = 5, max_len: int = 24) -> Doc:
    words: List[str] = []
    ents: List[Span] = []
    n_chunks = rng.randint(2, 6)
    for _ in range(n_chunks):
        if rng.random() < 0.4:
            label = rng.choice(list(_ENT_LABELS))
            ent_words = rng.choice(_ENT_LABELS[label]).split()
            start = len(words)
            words.extend(ent_words)
            ents.append(Span(start, len(words), label))
        else:
            for _ in range(rng.randint(1, 4)):
                pos = rng.choice(list(_POS_VOCAB))
                words.append(rng.choice(_POS_VOCAB[pos]))
    doc = Doc(words=words)
    doc.ents = ents
    return doc


def synth_parsed_doc(rng: random.Random) -> Doc:
    """Template-grammar sentence with a gold projective dependency tree.

    S -> NP VP [PUNCT]; NP -> DET ADJ* NOUN; VP -> VERB [NP] [ADV].
    Heads: DET/ADJ->NOUN, subj NOUN->VERB, obj NOUN->VERB, ADV->VERB,
    VERB=root. Always projective; structure recoverable from word identity.
    """

    words: List[str] = []
    tags: List[str] = []
    heads: List[int] = []
    deps: List[str] = []

    def emit(pos: str, dep: str, head: int = -100) -> int:
        words.append(rng.choice(_POS_VOCAB[pos]))
        tags.append(pos)
        heads.append(head)
        deps.append(dep)
        return len(words) - 1

    def np_() -> int:
        """Append an NP; returns noun index; dependents head to the noun."""
        start = len(words)
        if rng.random() < 0.7:
            emit("DET", "det")
        for _ in range(rng.randint(0, 2)):
            emit("ADJ", "amod")
        noun_i = emit("NOUN", "dep", -200)
        for k in range(start, noun_i):
            heads[k] = noun_i
        return noun_i

    subj = np_()
    verb_i = emit("VERB", "ROOT")
    heads[verb_i] = verb_i  # root: head = self
    heads[subj] = verb_i
    deps[subj] = "nsubj"
    if rng.random() < 0.7:
        obj = np_()
        heads[obj] = verb_i
        deps[obj] = "obj"
    if rng.random() < 0.5:
        i = emit("ADV", "advmod")
        heads[i] = verb_i
    if rng.random() < 0.6:
        words.append(".")
        tags.append("PUNCT")
        heads.append(verb_i)
        deps.append("punct")
    morphs = [f"Cat={t.title()}" for t in tags]
    sent_starts = [1 if i == 0 else -1 for i in range(len(words))]
    return Doc(
        words=words, tags=tags, pos=list(tags), heads=heads, deps=deps,
        morphs=morphs, sent_starts=sent_starts,
    )


def synth_textcat_doc(rng: random.Random) -> Doc:
    label = rng.choice(["SPORTS", "TECH", "FOOD"])
    topical = {
        "SPORTS": ["game", "team", "score", "win", "league", "ball"],
        "TECH": ["chip", "tensor", "compile", "code", "mesh", "kernel"],
        "FOOD": ["eat", "ham", "eggs", "bake", "sauce", "dish"],
    }
    words = [rng.choice(topical[label]) for _ in range(rng.randint(5, 15))]
    rng.shuffle(words)
    doc = Doc(words=words)
    doc.cats = {k: (1.0 if k == label else 0.0) for k in topical}
    return doc


def synth_spancat_doc(rng: random.Random) -> Doc:
    """NER-style doc whose entity spans live in doc.spans["sc"] (spancat
    gold: overlapping/nested spans allowed)."""
    doc = synth_ner_doc(rng)
    doc.spans["sc"] = list(doc.ents)
    doc.ents = []
    return doc


def synth_corpus(
    n_docs: int, kind: str = "tagger", seed: int = 0
) -> List[Example]:
    rng = random.Random(seed)
    makers = {
        "tagger": synth_tagged_doc,
        "ner": synth_ner_doc,
        "textcat": synth_textcat_doc,
        "parser": synth_parsed_doc,
        "spancat": synth_spancat_doc,
    }
    maker = makers[kind]
    return [Example.from_gold(maker(rng)) for _ in range(n_docs)]


def write_synth_jsonl(path, n_docs: int, kind: str = "tagger", seed: int = 0) -> None:
    import json

    from .training.corpus import _doc_to_json

    with open(path, "w", encoding="utf8") as f:
        for eg in synth_corpus(n_docs, kind, seed):
            f.write(json.dumps(_doc_to_json(eg.reference)) + "\n")
