"""Function registry: the plugin system behind ``@architectures = "..."`` config
references.

Capability parity with the registry surface the reference programs against
(reference train_cli.py:44-46 ``load_config`` + ``registry.resolve``;
worker.py:93 ``registry.resolve(config["training"], schema=...)``;
loggers.py:8 ``@registry.loggers("spacy-ray.ConsoleLogger.v1")``). The
reference delegates to thinc/spacy's catalogue-based registry; this is a
self-contained reimplementation with the same user-facing model:

* named registries (architectures, optimizers, schedules, loggers, readers,
  batchers, scorers, tokenizers, misc, callbacks),
* ``@registry.architectures("name.v1")`` decorator registration,
* resolution of config blocks whose ``@<registry>`` key names a registered
  factory, with nested blocks resolved bottom-up,
* user-code injection (``--code`` flag) simply imports a module that runs
  decorators at import time (reference worker.py:87 ``import_code``).
"""

from __future__ import annotations

import importlib
import importlib.util
import inspect
import sys
from pathlib import Path
from typing import Any, Callable, Dict, Iterable, Optional


class RegistryError(ValueError):
    pass


class _SubRegistry:
    """One named function table, e.g. ``registry.architectures``."""

    def __init__(self, namespace: str):
        self.namespace = namespace
        self._table: Dict[str, Callable] = {}

    def __call__(self, name: str, func: Optional[Callable] = None):
        """Decorator form: ``@registry.architectures("Foo.v1")``."""
        if func is not None:
            self.register(name, func)
            return func

        def decorator(f: Callable) -> Callable:
            self.register(name, f)
            return f

        return decorator

    def register(self, name: str, func: Callable) -> None:
        self._table[name] = func

    def get(self, name: str) -> Callable:
        if name not in self._table:
            available = ", ".join(sorted(self._table)) or "<empty>"
            raise RegistryError(
                f"Can't find '{name}' in registry {self.namespace}. "
                f"Available: {available}"
            )
        return self._table[name]

    def has(self, name: str) -> bool:
        return name in self._table

    def get_all(self) -> Dict[str, Callable]:
        return dict(self._table)

    def names(self) -> Iterable[str]:
        return sorted(self._table)


class Registry:
    """Top-level registry of registries.

    Namespaces mirror the slots the reference's config files address
    (``[training.logger]`` -> loggers, ``[training.optimizer]`` -> optimizers,
    ``@architectures`` in ``[components.*.model]`` blocks, corpus
    ``@readers``, ``[training.batcher]`` -> batchers).
    """

    NAMESPACES = (
        "architectures",
        "optimizers",
        "schedules",
        "loggers",
        "readers",
        "batchers",
        "scorers",
        "tokenizers",
        "factories",  # pipeline component factories ([components.X] factory = "...")
        "augmenters",
        "callbacks",
        "initializers",
        "misc",
    )

    def __init__(self):
        for ns in self.NAMESPACES:
            setattr(self, ns, _SubRegistry(ns))

    def get(self, namespace: str, name: str) -> Callable:
        return self._ns(namespace).get(name)

    def has(self, namespace: str, name: str) -> bool:
        if not hasattr(self, namespace):
            return False
        return self._ns(namespace).has(name)

    def _ns(self, namespace: str) -> _SubRegistry:
        sub = getattr(self, namespace, None)
        if not isinstance(sub, _SubRegistry):
            raise RegistryError(
                f"Unknown registry namespace '{namespace}'. "
                f"Available: {', '.join(self.NAMESPACES)}"
            )
        return sub

    # ------------------------------------------------------------------
    # Config-block resolution
    # ------------------------------------------------------------------
    def resolve(self, block: Any, *, validate: bool = True) -> Any:
        """Recursively resolve a config mapping.

        A dict containing a ``@<namespace>`` key is replaced by the result of
        calling the registered factory with the remaining keys as kwargs
        (nested dicts resolved first, bottom-up). Mirrors the semantics the
        reference relies on in spacy's ``registry.resolve``
        (reference worker.py:93-95).
        """
        return self._resolve_value(block, validate=validate)

    def _resolve_value(self, value: Any, *, validate: bool) -> Any:
        if isinstance(value, dict):
            ref_keys = [k for k in value if isinstance(k, str) and k.startswith("@")]
            resolved = {
                k: self._resolve_value(v, validate=validate)
                for k, v in value.items()
                if not (isinstance(k, str) and k.startswith("@"))
            }
            if not ref_keys:
                return resolved
            if len(ref_keys) > 1:
                raise RegistryError(
                    f"Config block has multiple registry references: {ref_keys}"
                )
            ref_key = ref_keys[0]
            namespace = ref_key[1:]
            name = value[ref_key]
            func = self.get(namespace, name)
            if validate:
                self._validate_args(func, resolved, namespace, name)
            return func(**resolved)
        if isinstance(value, list):
            return [self._resolve_value(v, validate=validate) for v in value]
        return value

    @staticmethod
    def _validate_args(func: Callable, kwargs: Dict[str, Any], namespace: str, name: str) -> None:
        try:
            sig = inspect.signature(func)
        except (TypeError, ValueError):  # builtins without signatures
            return
        has_var_kw = any(
            p.kind == inspect.Parameter.VAR_KEYWORD for p in sig.parameters.values()
        )
        if not has_var_kw:
            unknown = set(kwargs) - set(sig.parameters)
            if unknown:
                raise RegistryError(
                    f"Invalid argument(s) {sorted(unknown)} for "
                    f"@{namespace} = \"{name}\" "
                    f"(accepts: {sorted(sig.parameters)})"
                )
        missing = [
            p.name
            for p in sig.parameters.values()
            if p.default is inspect.Parameter.empty
            and p.kind
            in (
                inspect.Parameter.POSITIONAL_OR_KEYWORD,
                inspect.Parameter.KEYWORD_ONLY,
            )
            and p.name not in kwargs
        ]
        if missing:
            raise RegistryError(
                f"Missing required argument(s) {missing} for "
                f"@{namespace} = \"{name}\""
            )


registry = Registry()


def import_code(code_path: Optional[str]) -> None:
    """Import a user python file so its registry decorators run.

    Equivalent of the ``--code`` plumbing at reference train_cli.py:30 /
    worker.py:87 (``import_code`` from spacy.cli._util). Must run in every
    process that resolves configs.
    """
    if code_path is None:
        return
    path = Path(code_path)
    if not path.exists():
        raise FileNotFoundError(f"--code path not found: {code_path}")
    module_name = f"_user_code_{path.stem}"
    spec = importlib.util.spec_from_file_location(module_name, str(path))
    assert spec is not None and spec.loader is not None
    module = importlib.util.module_from_spec(spec)
    sys.modules[module_name] = module
    spec.loader.exec_module(module)
