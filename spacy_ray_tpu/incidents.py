"""Flight recorder + incident bundles: the forensic layer that turns
"something fired at 03:12" into an on-disk record an operator can read
the next morning.

Three producers write the SAME bundle format (``incidents/<utc-stamp>-
<source>/``):

* :class:`FlightRecorder` — each process keeps a bounded ring of recent
  metric snapshots next to the telemetry objects it already holds (the
  ``TraceBuffer`` span ring, the slow-request exemplars, the alert
  states). When an alert fires or an anomaly detector trips, ``trip()``
  retroactively dumps the last N seconds into a bundle — the data was
  already in memory; the incident only decides it is worth keeping.
* the **black box**: a recorder given a ``blackbox_path`` additionally
  persists its payload to that one file (atomic replace) every tick, so
  a process that dies by SIGKILL — which by definition cannot dump —
  still leaves its final pre-crash state on disk for whoever supervises
  it.
* :func:`write_crash_bundle` — the fleet supervisor's view of a dead
  replica: exit code/signal, the stdout/stderr tail it was already
  draining, the effective replica argv, the generation and last
  ``/healthz`` payloads the router had learned, plus the replica's
  black box and the router's own flight payload — so the bundle's
  merged timeline crosses the process boundary.

``telemetry postmortem <dir>`` renders a bundle as a human-readable
report: the manifest, the exit status, the alert states at capture, a
metric digest of the flight ring, the stderr tail, and a merged
cross-process timeline built with the SAME clock-anchor merge the live
trace collector uses (:func:`~.serving.tracecollect.merge_process_traces`
— one merge implementation, live or post-hoc).

Everything here is stdlib-only and jax-free; bundle layout is documented
in docs/OBSERVABILITY.md ("Alerting & incidents").
"""

from __future__ import annotations

import json
import os
import shutil
import signal as _signal
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "FlightRecorder",
    "write_crash_bundle",
    "find_bundle",
    "load_bundle",
    "render_postmortem",
    "render_bundle",
    "merged_bundle_trace",
]


def _slug(s: str) -> str:
    out = "".join(c if c.isalnum() or c in "-_" else "-" for c in str(s))
    return out.strip("-") or "incident"


def _stamp(unix_t: float) -> str:
    return time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(unix_t))


def _wall(unix_t: Optional[float]) -> str:
    if not isinstance(unix_t, (int, float)):
        return "-"
    frac = float(unix_t) - int(unix_t)
    return time.strftime(
        "%Y-%m-%d %H:%M:%S", time.gmtime(unix_t)
    ) + f".{int(frac * 1000):03d}Z"


def _atomic_write(path: Path, payload: Any) -> None:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(payload, default=str), encoding="utf8")
    tmp.replace(path)


_STAGING_LOCK = threading.Lock()
_STAGING_N = 0


def _publish_bundle(
    incident_dir: Path,
    unix_t: float,
    source: str,
    write: Callable[[Path], None],
) -> Path:
    """Build a bundle in a hidden staging dir, then RENAME it to its
    final ``<stamp>-<source>`` name: consumers polling the incidents
    root (a test, a CI artifact sweep, ``postmortem`` picking the
    newest) must never observe a half-written bundle — the dir appears
    with all of its files or not at all. The rename doubles as the
    collision check: two processes tripping the same fleet-wide source
    in the same second both publish (the loser retries with a suffix);
    a check-then-create would silently lose one side's dump."""
    global _STAGING_N
    incident_dir = Path(incident_dir)
    incident_dir.mkdir(parents=True, exist_ok=True)
    with _STAGING_LOCK:
        _STAGING_N += 1
        serial = _STAGING_N
    staging = incident_dir / f".staging-{os.getpid()}-{serial}"
    staging.mkdir()
    try:
        write(staging)
        base = f"{_stamp(unix_t)}-{_slug(source)}"
        n = 1
        while True:
            target = incident_dir / (base if n == 1 else f"{base}-{n}")
            try:
                staging.rename(target)
                return target
            except OSError:
                if target.exists():
                    n += 1
                    continue
                raise
    except BaseException:
        shutil.rmtree(staging, ignore_errors=True)
        raise


def exit_signal_name(rc: Optional[int]) -> Optional[str]:
    """Symbolic signal name for a negative Popen returncode (the
    subprocess convention: rc == -N means 'killed by signal N')."""
    if rc is None or rc >= 0:
        return None
    try:
        return _signal.Signals(-rc).name
    except ValueError:
        return f"signal {-rc}"


class FlightRecorder:
    """Bounded ring of metric snapshots + handles to the live telemetry
    objects, dumpable retroactively.

    ``record(snapshot)`` is the only periodic call (the owning process's
    observer ticker drives it); everything else happens on the rare trip
    path. Construction is gated on telemetry being enabled — with
    telemetry off the recorder does not exist and makes zero ring
    writes and zero incident I/O (guard-tested).
    """

    def __init__(
        self,
        *,
        incident_dir: Optional[Path] = None,
        blackbox_path: Optional[Path] = None,
        process_name: str = "process",
        capacity: int = 256,
        window_s: float = 300.0,
        min_trip_interval_s: float = 30.0,
        trace_tail_events: int = 5000,
        blackbox_interval_s: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
        unix: Callable[[], float] = time.time,
    ) -> None:
        self.incident_dir = (
            Path(incident_dir) if incident_dir is not None else None
        )
        self.blackbox_path = (
            Path(blackbox_path) if blackbox_path is not None else None
        )
        self.process_name = str(process_name)
        self.window_s = float(window_s)
        self.min_trip_interval_s = float(min_trip_interval_s)
        self.trace_tail_events = int(trace_tail_events)
        self.blackbox_interval_s = float(blackbox_interval_s)
        self.clock = clock
        self.unix = unix
        self._last_blackbox: Optional[float] = None
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._trace: Optional[Any] = None
        self._alerts_fn: Optional[Callable[[], Any]] = None
        self._exemplars_fn: Optional[Callable[[], Any]] = None
        self._last_trip: Optional[float] = None
        self.records = 0
        self.trips = 0
        self.suppressed = 0

    def attach(
        self,
        *,
        trace: Optional[Any] = None,
        alerts_fn: Optional[Callable[[], Any]] = None,
        exemplars_fn: Optional[Callable[[], Any]] = None,
    ) -> "FlightRecorder":
        """Late-bind the live telemetry objects whose state a dump
        captures (the span ring, the alert states, the exemplars)."""
        if trace is not None:
            self._trace = trace
        if alerts_fn is not None:
            self._alerts_fn = alerts_fn
        if exemplars_fn is not None:
            self._exemplars_fn = exemplars_fn
        return self

    # -- the periodic tick ---------------------------------------------
    def record(self, snapshot: Dict[str, Any]) -> None:
        """Append one metric snapshot to the ring (pruning past the time
        window) and, when a black-box path is configured, persist the
        payload atomically — the SIGKILL-survivable copy. The ring feeds
        every tick; the black-box FILE rewrites at most every
        ``blackbox_interval_s`` (first record always persists): the
        serialization is the expensive part, and crash evidence needs to
        be recent, not tick-fresh — the copy may lag the crash by up to
        the interval."""
        now = self.clock()
        with self._lock:
            self._ring.append(
                {
                    "t": round(now, 3),
                    "unix_time": round(self.unix(), 3),
                    "snapshot": snapshot,
                }
            )
            cutoff = now - self.window_s
            while self._ring and self._ring[0]["t"] < cutoff:
                self._ring.popleft()
            self.records += 1
            persist = self.blackbox_path is not None and (
                self._last_blackbox is None
                or now - self._last_blackbox >= self.blackbox_interval_s
            )
            if persist:
                self._last_blackbox = now
        if persist:
            try:
                _atomic_write(self.blackbox_path, self.payload())
            except OSError:
                pass  # a full disk must not take the serving path down

    def alert_hook(self) -> Callable[[Any, Any], Any]:
        """The canonical ``AlertEngine(on_firing=...)`` callback: dump a
        bundle named after the firing rule. ONE definition, so the three
        production wirings (serve CLI, fleet, trainer telemetry) cannot
        drift on the trip-call contract."""

        def hook(rule: Any, st: Any) -> Any:
            return self.trip(
                f"alert-{rule.name}",
                st.detail or rule.name,
                severity=rule.severity,
                value=st.value,
            )

        return hook

    # -- payload / dump -------------------------------------------------
    def payload(self) -> Dict[str, Any]:
        """Everything a bundle keeps: the snapshot ring plus the live
        trace buffer (with its clock anchor, so the postmortem's merge
        can place these spans on a wall-clock timeline), the alert
        states, and the slow-request exemplars."""
        with self._lock:
            snaps = list(self._ring)
        out: Dict[str, Any] = {
            "process": self.process_name,
            "written_unix": round(self.unix(), 3),
            "window_s": self.window_s,
            "snapshots": snaps,
        }
        if self._trace is not None:
            trace = self._trace.payload()
            events = trace.get("traceEvents") or []
            if len(events) > self.trace_tail_events:
                # bound what each payload (and thus every 2s black-box
                # rewrite) serializes: a full 100k-event span ring is
                # tens of MB of JSON per tick, and the postmortem only
                # reads the tail anyway — metadata rows (thread names)
                # are kept, the span tail capped
                meta = [e for e in events if e.get("ph") == "M"]
                rest = [e for e in events if e.get("ph") != "M"]
                trace["traceEvents"] = (
                    meta + rest[-self.trace_tail_events:]
                )
                trace["truncated_events"] = len(rest) - self.trace_tail_events
            trace["anchor"] = self._trace.anchor()
            out["trace"] = trace
        if self._alerts_fn is not None:
            try:
                out["alerts"] = self._alerts_fn()
            except Exception:
                out["alerts"] = None
        if self._exemplars_fn is not None:
            try:
                out["exemplars"] = self._exemplars_fn()
            except Exception:
                out["exemplars"] = None
        return out

    def trip(
        self, source: str, reason: str, **fields: Any
    ) -> Optional[Path]:
        """Dump the last N seconds into ``incidents/<stamp>-<source>/``.
        Rate-limited (``min_trip_interval_s``) so an alert storm or a
        firing-every-step detector writes ONE bundle, not hundreds; the
        bundle that exists already holds the window the storm happened
        in. Returns the bundle dir, or None (disabled / rate-limited)."""
        if self.incident_dir is None:
            return None
        now = self.clock()
        with self._lock:
            if (
                self._last_trip is not None
                and now - self._last_trip < self.min_trip_interval_s
            ):
                self.suppressed += 1
                return None
            self._last_trip = now
        unix_t = self.unix()

        def write(b: Path) -> None:
            _atomic_write(
                b / "incident.json",
                {
                    "source": source,
                    "reason": reason,
                    "process": self.process_name,
                    "unix_time": round(unix_t, 3),
                    **fields,
                },
            )
            _atomic_write(
                b / f"flight-{_slug(self.process_name)}.json",
                self.payload(),
            )

        try:
            bundle = _publish_bundle(self.incident_dir, unix_t, source, write)
        except OSError:
            return None
        self.trips += 1
        try:
            from .training.resilience import log_event

            log_event(
                "incident-bundle",
                f"{source}: flight-recorder dump written to {bundle}",
                source=source,
                bundle=str(bundle),
            )
        except Exception:
            pass
        return bundle


# ----------------------------------------------------------------------
# Crash postmortems (the fleet supervisor's producer)
# ----------------------------------------------------------------------


def write_crash_bundle(
    incident_dir: Path,
    *,
    process_name: str,
    rc: Optional[int],
    argv: Optional[Sequence[str]] = None,
    output_tail: Sequence[str] = (),
    generation: Optional[int] = None,
    health_history: Sequence[Dict[str, Any]] = (),
    blackbox_path: Optional[Path] = None,
    process_started_unix: Optional[float] = None,
    extra_flights: Optional[Dict[str, Dict[str, Any]]] = None,
    replica_id: Optional[int] = None,
    slot: Optional[int] = None,
    unix: Callable[[], float] = time.time,
) -> Path:
    """One dead process → one bundle. The supervisor calls this the
    moment it observes the exit, BEFORE restart bookkeeping wipes the
    handle (generation, tail): the restart keeps the fleet serving; this
    keeps the evidence.

    * ``incident.json`` — exit code + symbolic signal (SIGKILL et al.),
      the effective argv, generation, replica/slot identity;
    * ``stderr.txt`` — the supervised output tail (stderr is merged into
      stdout by the spawn, so this is the process's last words);
    * ``health.json`` — the last ``/healthz`` payloads the router saw;
    * ``flight-<name>.json`` — the dead process's black box (its final
      pre-crash span ring and metric snapshots), if one was configured
      and survived, plus any ``extra_flights`` (e.g. the router's own
      recorder payload — giving the postmortem a cross-process timeline).
    """
    unix_t = unix()
    source = (
        f"crash-replica-{replica_id}" if replica_id is not None else "crash"
    )

    def write(b: Path) -> None:
        # read the black box FIRST: its verdict belongs in the manifest.
        # A crash-looping successor that died before its recorder's
        # first persist leaves its PREDECESSOR's file on the slot —
        # presenting that as the dead process's final state would be a
        # forensic lie, so a payload written before this incarnation
        # spawned is skipped and named stale (1s slack for clock grain).
        blackbox_raw: Optional[str] = None
        blackbox_status = "absent"
        if blackbox_path is not None:
            try:
                raw = Path(blackbox_path).read_text(encoding="utf8")
                payload = json.loads(raw)
                written = payload.get("written_unix")
                if (
                    process_started_unix is not None
                    and isinstance(written, (int, float))
                    and written < process_started_unix - 1.0
                ):
                    blackbox_status = "stale-skipped (predates this process)"
                else:
                    blackbox_raw = raw
                    blackbox_status = "ok"
            except (OSError, ValueError):
                pass  # no black box survived: honest without it
        _atomic_write(
            b / "incident.json",
            {
                "source": "crash",
                "process": process_name,
                "unix_time": round(unix_t, 3),
                "replica_id": replica_id,
                "slot": slot,
                "exit_code": rc,
                "exit_signal": exit_signal_name(rc),
                "generation": generation,
                "argv": list(argv) if argv is not None else None,
                "blackbox": blackbox_status,
            },
        )
        (b / "stderr.txt").write_text(
            "\n".join(str(line) for line in output_tail) + "\n",
            encoding="utf8",
        )
        if health_history:
            _atomic_write(b / "health.json", list(health_history))
        if blackbox_raw is not None:
            payload = json.loads(blackbox_raw)
            name = _slug(str(payload.get("process") or process_name))
            (b / f"flight-{name}.json").write_text(
                blackbox_raw, encoding="utf8"
            )
        for name, payload in (extra_flights or {}).items():
            _atomic_write(b / f"flight-{_slug(name)}.json", payload)

    bundle = _publish_bundle(Path(incident_dir), unix_t, source, write)
    try:
        from .training.resilience import log_event

        log_event(
            "incident-bundle",
            f"crash postmortem for {process_name} (rc={rc}) written to "
            f"{bundle}",
            rc=rc,
            bundle=str(bundle),
        )
    except Exception:
        pass
    return bundle


# ----------------------------------------------------------------------
# Bundle reading + the `telemetry postmortem` report
# ----------------------------------------------------------------------


def find_bundle(path: Path) -> Path:
    """Resolve a postmortem target: either a bundle dir itself (holds
    ``incident.json``) or an incidents ROOT, in which case the newest
    bundle (lexicographic UTC-stamp dir names sort chronologically) is
    picked. Raises FileNotFoundError with an actionable message."""
    path = Path(path)
    if (path / "incident.json").is_file():
        return path
    if path.is_dir():
        bundles = sorted(
            d for d in path.iterdir()
            if d.is_dir()
            and not d.name.startswith(".")  # in-flight staging dirs
            and (d / "incident.json").is_file()
        )
        if bundles:
            return bundles[-1]
    raise FileNotFoundError(
        f"{path} is neither an incident bundle (no incident.json) nor a "
        "directory containing one"
    )


def load_bundle(bundle_dir: Path) -> Dict[str, Any]:
    bundle_dir = Path(bundle_dir)
    out: Dict[str, Any] = {
        "dir": str(bundle_dir),
        "incident": json.loads(
            (bundle_dir / "incident.json").read_text(encoding="utf8")
        ),
        "stderr": None,
        "health": None,
        "flights": [],
    }
    stderr = bundle_dir / "stderr.txt"
    if stderr.is_file():
        out["stderr"] = stderr.read_text(encoding="utf8")
    health = bundle_dir / "health.json"
    if health.is_file():
        try:
            out["health"] = json.loads(health.read_text(encoding="utf8"))
        except ValueError:
            pass
    for f in sorted(bundle_dir.glob("flight-*.json")):
        try:
            out["flights"].append(json.loads(f.read_text(encoding="utf8")))
        except ValueError:
            continue  # a torn flight file: skip it, keep the rest
    return out


def merged_bundle_trace(bundle: Dict[str, Any]) -> Dict[str, Any]:
    """Merge every flight payload's trace onto one wall-clock timeline —
    the SAME clock-anchor merge ``telemetry collect-trace`` runs against
    live endpoints, applied post-hoc to the bundle's frozen buffers."""
    from .serving.tracecollect import merge_process_traces

    processes = []
    for flight in bundle.get("flights") or []:
        trace = flight.get("trace")
        if not isinstance(trace, dict):
            continue
        processes.append(
            {
                "name": str(flight.get("process") or "process"),
                "trace": trace,
                "anchor": trace.get("anchor"),
            }
        )
    return merge_process_traces(processes)


def _counter_digest(snaps: List[Dict[str, Any]]) -> List[str]:
    """first→last movement of the headline counters across the flight
    ring — which signals were moving in the captured window."""
    if not snaps:
        return []
    first = (snaps[0].get("snapshot") or {})
    last = (snaps[-1].get("snapshot") or {})

    def counters(s: Dict[str, Any]) -> Dict[str, Any]:
        c = s.get("counters")
        if isinstance(c, dict):
            return c
        c = (s.get("router") or {}).get("counters")  # router composite
        return c if isinstance(c, dict) else {}

    c0, c1 = counters(first), counters(last)
    lines = []
    for key in sorted(set(c0) | set(c1)):
        v0, v1 = c0.get(key), c1.get(key)
        if not isinstance(v1, (int, float)):
            continue
        if isinstance(v0, (int, float)) and v1 != v0:
            lines.append(f"    {key:28s} {v0:g} -> {v1:g}")
        elif not isinstance(v0, (int, float)):
            lines.append(f"    {key:28s} {v1:g}")
    return lines


def render_postmortem(path: Path, *, timeline_events: int = 40) -> str:
    """The ``telemetry postmortem`` report from a path (resolve + load +
    render). Callers that already hold a loaded bundle (the CLI, which
    also merges the trace for ``--trace-out``) use
    :func:`render_bundle` directly and load once."""
    return render_bundle(
        load_bundle(find_bundle(Path(path))),
        timeline_events=timeline_events,
    )


def render_bundle(
    bundle: Dict[str, Any], *, timeline_events: int = 40
) -> str:
    """Pure loaded-bundle-in/text-out report renderer."""
    inc = bundle["incident"]
    lines: List[str] = [f"postmortem: {bundle['dir']}"]
    src = inc.get("source")
    lines.append(f"source: {src}  process: {inc.get('process')}")
    lines.append(f"time:   {_wall(inc.get('unix_time'))}")
    if src == "crash":
        sig = inc.get("exit_signal")
        lines.append(
            f"exit:   code {inc.get('exit_code')}"
            + (f" (killed by {sig})" if sig else "")
        )
        if inc.get("replica_id") is not None:
            lines.append(
                f"replica: id {inc.get('replica_id')}  "
                f"slot {inc.get('slot')}"
            )
    else:
        lines.append(f"reason: {inc.get('reason')}")
        # whatever the tripper stamped beyond the standard envelope —
        # the fleet divergence trip's worker/mode, an alert trip's
        # severity/value — is evidence, not metadata to drop
        extras = {
            k: v
            for k, v in inc.items()
            if k not in (
                "source", "reason", "process", "unix_time", "generation",
                "argv", "exit_code", "exit_signal", "replica_id", "slot",
            )
            and v is not None
        }
        if extras:
            lines.append(
                "detail: "
                + "  ".join(f"{k}={extras[k]}" for k in sorted(extras))
            )
    lines.append(f"generation: {inc.get('generation')}")
    if inc.get("argv"):
        lines.append("argv:   " + " ".join(str(a) for a in inc["argv"]))

    # alert states at capture (from any flight that recorded them)
    alert_rows = [
        row
        for flight in bundle["flights"]
        for row in (flight.get("alerts") or [])
        if isinstance(row, dict)
    ]
    active = [r for r in alert_rows if r.get("state") != "inactive"]
    if alert_rows:
        lines.append(
            f"-- alerts at capture ({len(active)} active of "
            f"{len(alert_rows)}) --"
        )
        for row in active or alert_rows[:3]:
            lines.append(
                f"    {row.get('state', '?'):8s} "
                f"{row.get('alert', '?')} [{row.get('severity', '?')}]  "
                f"{row.get('detail', '')}"
            )

    for flight in bundle["flights"]:
        snaps = flight.get("snapshots") or []
        if not snaps:
            continue
        span = (snaps[-1].get("unix_time") or 0) - (
            snaps[0].get("unix_time") or 0
        )
        lines.append(
            f"-- flight ring [{flight.get('process')}]: {len(snaps)} "
            f"snapshot(s) over {span:.1f}s --"
        )
        lines.extend(_counter_digest(snaps))

    if bundle.get("health"):
        last = bundle["health"][-1]
        lines.append(
            f"-- last health ({_wall(last.get('unix_time'))}) --"
        )
        lines.append(
            "    " + json.dumps(last.get("health"), sort_keys=True)[:240]
        )

    if bundle.get("stderr"):
        tail = bundle["stderr"].rstrip("\n").splitlines()
        lines.append(f"-- output tail ({len(tail)} line(s)) --")
        lines.extend(f"    {line}" for line in tail)

    merged = merged_bundle_trace(bundle)
    events = [
        e for e in merged.get("traceEvents") or [] if e.get("ph") != "M"
    ]
    if events:
        pid_names = {
            e.get("pid"): (e.get("args") or {}).get("name")
            for e in merged.get("traceEvents") or []
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        origin_us = float(
            (merged.get("otherData") or {}).get("epoch_origin_us") or 0.0
        )
        events.sort(key=lambda e: float(e.get("ts") or 0.0))
        shown = events[-int(timeline_events):]
        lines.append(
            f"-- timeline (last {len(shown)} of {len(events)} event(s), "
            f"{len(pid_names)} process track(s)) --"
        )
        for e in shown:
            wall = _wall((origin_us + float(e.get("ts") or 0.0)) / 1e6)
            who = pid_names.get(e.get("pid"), e.get("pid"))
            dur = e.get("dur")
            dur_txt = (
                f" ({float(dur) / 1e3:.1f}ms)"
                if isinstance(dur, (int, float))
                else ""
            )
            args = e.get("args") or {}
            note = ""
            for key in ("request_id", "step", "generation", "error"):
                if args.get(key) is not None:
                    note += f" {key}={args[key]}"
            lines.append(
                f"    {wall}  [{who}] {e.get('name')}{dur_txt}{note}"
            )
    else:
        lines.append("-- timeline: no trace in bundle --")
        skipped = (merged.get("otherData") or {}).get("skipped")
        if skipped:
            lines.append(f"    (skipped unanchored: {skipped})")
    return "\n".join(lines)
