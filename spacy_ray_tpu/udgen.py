"""Deterministic pseudo-UD corpus generator: realistic distributions for
end-to-end fixtures.

The synthetic corpora in util.py are uniform-vocabulary single-clause toys;
real corpora (the reference trains on OntoNotes/UD via `spacy convert`,
reference bin/get-data.sh:8-12) have zipfian vocabulary, multi-sentence
documents, punctuation, rare labels, and a non-projective tail. This
generator produces all of that deterministically (VERDICT r2 next #6) so CI
can run the full convert→train→evaluate→package→load loop against score
floors with zero egress:

* **Zipfian vocabulary**: ~2.4k word types; frequency ∝ 1/rank within each
  part of speech. Surface forms are synthesized from stable per-type
  syllables, so every run of a given seed sees the same words.
* **Morphology is systematic**: plural nouns take ``-s`` + ``Number=Plur``
  + tag NNS; past verbs take ``-ed`` + ``Tense=Past`` + tag VBD (3sg ``-s``
  / VBZ otherwise); lemma = the uninflected stem — so the edit-tree
  lemmatizer, tagger, and morphologizer all have learnable signal.
* **Grammar**: root verb with subject/object NPs (det + 0-2 adj + noun),
  optional PP (case+nmod) and advmod, sentence-final punct (dep ``punct``
  — exercising the scorer's punct exclusion).
* **Non-projectivity**: ~7% of sentences extrapose the subject's PP after
  the object, creating a crossing arc (the pseudo-projective pipeline's
  training case).
* **Rare labels**: a ``vocative`` dep (~0.7% of sentences) and a
  ``WORK_OF_ART`` entity (~3% of entity mentions) give the long-tail
  labels real corpora have.
* **Documents**: 1-6 sentences (up to ~120 tokens), ``sent_starts``
  annotated, entities over PROPN mentions with per-mention-type fixed
  labels.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from .pipeline.doc import Doc, Example, Span

_CONS = ["b", "d", "f", "g", "k", "l", "m", "n", "p", "r", "s", "t", "v", "z"]
_VOW = ["a", "e", "i", "o", "u"]


def _make_stem(type_id: int, n_syll: int) -> str:
    """Stable surface stem for a word-type id."""
    rng = random.Random(0xC0FFEE ^ type_id)
    return "".join(
        rng.choice(_CONS) + rng.choice(_VOW) for _ in range(n_syll)
    )


class _Lexicon:
    """Per-POS zipfian lexicons, fixed given the generator seed."""

    def __init__(self, rng: random.Random):
        def types(n: int, n_syll: int, prefix: int) -> List[str]:
            return [_make_stem(prefix * 100000 + i, n_syll) for i in range(n)]

        self.nouns = types(800, 2, 1)
        self.verbs = types(600, 2, 2)
        self.adjs = types(400, 2, 3)
        self.advs = types(200, 3, 4)
        self.dets = ["the", "a", "this", "that", "every"]
        self.adps = ["in", "on", "under", "near", "with", "from"]
        # proper nouns: two-word mentions; each mention type has a FIXED
        # entity label so the mapping is learnable
        self.propn: List[Tuple[List[str], str]] = []
        ent_labels = ["PERSON", "ORG", "GPE"]
        for i in range(120):
            first = _make_stem(500000 + i, 2).capitalize()
            second = _make_stem(600000 + i, 2).capitalize()
            # a rare WORK_OF_ART tail: one head-rank type (so the label
            # actually OCCURS, ~2-3% of mentions) plus a thin random tail
            if i == 7 or rng.random() < 0.02:
                label = "WORK_OF_ART"
            else:
                label = rng.choice(ent_labels)
            self.propn.append(([first, second], label))
        self._cums: Dict[int, List[float]] = {}

    def zipf(self, rng: random.Random, items: List[str]) -> str:
        """Pick with p ∝ 1/(rank+1) — exact zipf(s=1) via the harmonic
        cumulative distribution (cached per lexicon size)."""
        import bisect

        n = len(items)
        cum = self._cums.get(n)
        if cum is None:
            total = 0.0
            cum = []
            for r in range(n):
                total += 1.0 / (r + 1)
                cum.append(total)
            self._cums[n] = cum
        x = rng.random() * cum[-1]
        return items[bisect.bisect_left(cum, x)]


class _Sent:
    def __init__(self) -> None:
        self.words: List[str] = []
        self.tags: List[str] = []
        self.pos: List[str] = []
        self.heads: List[int] = []
        self.deps: List[str] = []
        self.lemmas: List[str] = []
        self.morphs: List[str] = []
        self.ents: List[Tuple[int, int, str]] = []

    def emit(
        self, word: str, tag: str, pos: str, dep: str, lemma: str, morph: str,
        head: int = -1,
    ) -> int:
        i = len(self.words)
        self.words.append(word)
        self.tags.append(tag)
        self.pos.append(pos)
        self.heads.append(head)
        self.deps.append(dep)
        self.lemmas.append(lemma)
        self.morphs.append(morph)
        return i


def _noun(rng: random.Random, lex: _Lexicon, s: _Sent, head_slot: int, dep: str) -> int:
    """det + adjs + noun (or a PROPN entity mention); returns the head index."""
    if rng.random() < 0.18:
        mention, label = lex.zipf(rng, lex.propn)
        start = len(s.words)
        idxs = [
            s.emit(w, "NNP", "PROPN", "compound" if k < len(mention) - 1 else dep,
                   w, "Number=Sing")
            for k, w in enumerate(mention)
        ]
        for k in idxs[:-1]:
            s.heads[k] = idxs[-1]
        s.heads[idxs[-1]] = head_slot
        s.ents.append((start, len(s.words), label))
        return idxs[-1]
    det = rng.choice(lex.dets)
    di = s.emit(det, "DT", "DET", "det", det, "")
    adj_idx = []
    for _ in range(rng.choice([0, 0, 0, 1, 1, 2])):
        a = lex.zipf(rng, lex.adjs)
        adj_idx.append(s.emit(a, "JJ", "ADJ", "amod", a, "Degree=Pos"))
    plural = rng.random() < 0.35
    stem = lex.zipf(rng, lex.nouns)
    ni = s.emit(
        stem + ("s" if plural else ""),
        "NNS" if plural else "NN",
        "NOUN",
        dep,
        stem,
        "Number=Plur" if plural else "Number=Sing",
        head=head_slot,
    )
    s.heads[di] = ni
    for k in adj_idx:
        s.heads[k] = ni
    return ni


def _pp(rng: random.Random, lex: _Lexicon, s: _Sent, attach_to: int) -> Tuple[int, int]:
    """case + nmod noun phrase attached to ``attach_to``; returns the token
    span (start, end) of the PP for extraposition bookkeeping."""
    start = len(s.words)
    adp = rng.choice(lex.adps)
    ci = s.emit(adp, "IN", "ADP", "case", adp, "")
    ni = _noun(rng, lex, s, attach_to, "nmod")
    s.heads[ci] = ni
    return start, len(s.words)


def _sentence(rng: random.Random, lex: _Lexicon, s: _Sent) -> None:
    """Append one sentence's tokens to ``s`` (indices are sentence-local
    until the caller rebases)."""
    base = len(s.words)
    # optional rare vocative opener
    if rng.random() < 0.007:
        mention, _label = lex.propn[rng.randrange(len(lex.propn))]
        # head=-2: patched to the clause root once it exists (UD vocative)
        vi = s.emit(mention[0], "NNP", "PROPN", "vocative", mention[0], "", head=-2)
        s.emit(",", ",", "PUNCT", "punct", ",", "", head=vi)
    extrapose = rng.random() < 0.07
    subj = _noun(rng, lex, s, -2, "nsubj")  # head patched to root below
    if not extrapose and rng.random() < 0.25:
        _pp(rng, lex, s, subj)
    third_sg = s.morphs[subj] == "Number=Sing"
    past = rng.random() < 0.5
    stem = lex.zipf(rng, lex.verbs)
    if past:
        form, tag, morph = stem + "ed", "VBD", "Tense=Past"
    elif third_sg:
        form, tag, morph = stem + "s", "VBZ", "Number=Sing|Person=3|Tense=Pres"
    else:
        form, tag, morph = stem, "VBP", "Tense=Pres"
    root = s.emit(form, tag, "VERB", "ROOT", stem, morph)
    s.heads[root] = root
    for i in range(base, root):
        if s.heads[i] == -2:
            s.heads[i] = root
    if rng.random() < 0.3:
        a = lex.zipf(rng, lex.advs)
        s.heads[s.emit(a, "RB", "ADV", "advmod", a, "")] = root
    _noun(rng, lex, s, root, "obj")
    if extrapose:
        # PP attached to the SUBJECT noun but positioned after the object:
        # root and obj sit inside the subject subtree's span without being
        # its descendants — non-projective
        _pp(rng, lex, s, subj)
    elif rng.random() < 0.2:
        _pp(rng, lex, s, root)
    s.heads[s.emit(".", ".", "PUNCT", "punct", ".", "")] = root


def synth_ud_doc(rng: random.Random, lex: _Lexicon, max_sents: int = 6) -> Doc:
    s = _Sent()
    sent_bounds: List[int] = []
    for _ in range(rng.randint(1, max_sents)):
        sent_bounds.append(len(s.words))
        _sentence(rng, lex, s)
    n = len(s.words)
    sent_starts = [-1] * n
    for b in sent_bounds:
        sent_starts[b] = 1
    doc = Doc(
        words=s.words,
        tags=s.tags,
        pos=s.pos,
        heads=s.heads,
        deps=s.deps,
        lemmas=s.lemmas,
        morphs=s.morphs,
        sent_starts=sent_starts,
        ents=[Span(a, b, label) for a, b, label in s.ents],
        ents_annotated=True,
    )
    return doc


def synth_ud_corpus(n_docs: int, seed: int = 0, max_sents: int = 6) -> List[Example]:
    """Deterministic pseudo-UD corpus (see module docstring)."""
    rng = random.Random(seed)
    lex = _Lexicon(random.Random(1234))  # lexicon fixed across seeds
    return [
        Example.from_gold(synth_ud_doc(rng, lex, max_sents=max_sents))
        for _ in range(n_docs)
    ]


def write_ud_jsonl(path, n_docs: int, seed: int = 0, max_sents: int = 6) -> None:
    import json

    from .training.corpus import _doc_to_json

    with open(path, "w", encoding="utf8") as f:
        for eg in synth_ud_corpus(n_docs, seed=seed, max_sents=max_sents):
            f.write(json.dumps(_doc_to_json(eg.reference)) + "\n")
