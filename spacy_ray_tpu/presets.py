"""Preset config strings: canonical pipeline shapes used by bench,
__graft_entry__, tests, and as user starting points (the role of
``spacy init config`` templates in the reference ecosystem)."""

CNN_TAGGER_CFG = """
[nlp]
lang = "en"
pipeline = ["tok2vec","tagger"]

[components.tok2vec]
factory = "tok2vec"

[components.tok2vec.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = {width}
depth = {depth}
embed_size = {embed_size}

[components.tagger]
factory = "tagger"

[components.tagger.model]
@architectures = "spacy.Tagger.v2"

[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = {width}
"""

TINY_TRF_TAGGER_CFG = """
[nlp]
lang = "en"
pipeline = ["transformer","tagger"]

[components.transformer]
factory = "transformer"

[components.transformer.model]
@architectures = "spacy_ray_tpu.TransformerEncoder.v1"
width = 32
depth = 2
n_heads = 4
ffn_mult = 2
dropout = 0.1
max_len = 64
embed_size = 256
remat = false

[components.tagger]
factory = "tagger"

[components.tagger.model]
@architectures = "spacy.Tagger.v2"

[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 32
"""
