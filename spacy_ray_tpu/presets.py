"""Preset config strings: canonical pipeline shapes used by bench,
__graft_entry__, tests, and the ``init-config`` CLI command (the role of
``spacy init config`` templates in the reference ecosystem)."""

# standard [paths]/[corpora]/[training] tail shared by init-config presets
_TRAINING_TAIL = """
[paths]
train = null
dev = null

[corpora.train]
@readers = "spacy.Corpus.v1"
path = ${{paths.train}}
shuffle = true

[corpora.dev]
@readers = "spacy.Corpus.v1"
path = ${{paths.dev}}

[training]
seed = 0
dropout = 0.1
accumulate_gradient = {accumulate_gradient}
patience = 1600
max_epochs = 0
max_steps = 20000
eval_frequency = 200
zero1 = {zero1}
update_sharding = "{update_sharding}"

[training.optimizer]
@optimizers = "Adam.v1"
learn_rate = 0.001
beta1 = 0.9
beta2 = 0.999
grad_clip = 1.0
use_averages = false

[training.batcher]
@batchers = "spacy.batch_by_words.v1"
size = 2000
tolerance = 0.2

[training.score_weights]
{score_weights}
"""


def _full(components: str, score_weights: str, accumulate_gradient: int = 1,
          zero1: bool = False, update_sharding: str = "auto") -> str:
    # update_sharding defaults to "auto" (arms "full" on accelerator
    # meshes with >1 data rank, honors a zero1 alias, stays replicated on
    # CPU); the trf preset pins "full" outright — it subsumes its old
    # zero1=true (state sharded in both; full also shards the apply,
    # bit-exactly vs replicated) at every mesh shape, degenerating
    # harmlessly to replicated on one device
    return components + _TRAINING_TAIL.format(
        accumulate_gradient=accumulate_gradient,
        zero1="true" if zero1 else "false",
        update_sharding=update_sharding,
        score_weights=score_weights,
    )

CNN_TAGGER_CFG = """
[nlp]
lang = "en"
pipeline = ["tok2vec","tagger"]

[components.tok2vec]
factory = "tok2vec"

[components.tok2vec.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = {width}
depth = {depth}
embed_size = {embed_size}

[components.tagger]
factory = "tagger"

[components.tagger.model]
@architectures = "spacy.Tagger.v2"

[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = {width}
"""

# ---------------------------------------------------------------------------
# init-config presets (full trainable configs, BASELINE.json config shapes)
# ---------------------------------------------------------------------------

_SM_COMPONENTS = """
[nlp]
lang = "en"
pipeline = ["tok2vec","tagger","parser","ner"]

[components.tok2vec]
factory = "tok2vec"

[components.tok2vec.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = 96
depth = 4
embed_size = 2000

[components.tagger]
factory = "tagger"

[components.tagger.model]
@architectures = "spacy.Tagger.v2"

[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 96

[components.parser]
factory = "parser"

[components.parser.model]
@architectures = "spacy.TransitionBasedParser.v2"
state_type = "parser"
hidden_width = 128
maxout_pieces = 2

[components.parser.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 96

[components.ner]
factory = "ner"

[components.ner.model]
@architectures = "spacy.TransitionBasedParser.v2"
state_type = "ner"
hidden_width = 128
maxout_pieces = 2

[components.ner.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 96
"""

_TRF_COMPONENTS = """
[nlp]
lang = "en"
pipeline = ["transformer","tagger","parser","ner"]

[components.transformer]
factory = "transformer"

[components.transformer.model]
@architectures = "spacy_ray_tpu.TransformerEncoder.v1"
width = 768
depth = 12
n_heads = 12
ffn_mult = 4
dropout = 0.1
max_len = 512
embed_size = 20000
remat = true

[components.tagger]
factory = "tagger"

[components.tagger.model]
@architectures = "spacy.Tagger.v2"

[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 768

[components.parser]
factory = "parser"

[components.parser.model]
@architectures = "spacy.TransitionBasedParser.v2"
state_type = "parser"
hidden_width = 128
maxout_pieces = 2

[components.parser.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 768

[components.ner]
factory = "ner"

[components.ner.model]
@architectures = "spacy.TransitionBasedParser.v2"
state_type = "ner"
hidden_width = 128
maxout_pieces = 2

[components.ner.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 768
"""

_SPANCAT_COMPONENTS = """
[nlp]
lang = "en"
pipeline = ["tok2vec","spancat","textcat_multilabel"]

[components.tok2vec]
factory = "tok2vec"

[components.tok2vec.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = 96
depth = 4
embed_size = 2000

[components.spancat]
factory = "spancat"
spans_key = "sc"
threshold = 0.5

[components.spancat.suggester]
@misc = "spacy.ngram_suggester.v1"
sizes = [1,2,3]

[components.spancat.model]
@architectures = "spacy.SpanCategorizer.v1"
hidden_size = 128

[components.spancat.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 96

[components.textcat_multilabel]
factory = "textcat_multilabel"

[components.textcat_multilabel.model]
@architectures = "spacy.TextCatReduce.v1"

[components.textcat_multilabel.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 96
"""

INIT_PRESETS = {
    "cnn": _full(
        CNN_TAGGER_CFG.format(width=96, depth=4, embed_size=2000),
        "tag_acc = 1.0",
    ),
    "sm": _full(
        _SM_COMPONENTS,
        "tag_acc = 0.33\ndep_las = 0.33\nents_f = 0.34",
    ),
    "trf": _full(
        _TRF_COMPONENTS,
        "tag_acc = 0.33\ndep_las = 0.33\nents_f = 0.34",
        accumulate_gradient=3,
        update_sharding="full",
    ),
    "spancat": _full(
        _SPANCAT_COMPONENTS,
        "spans_sc_f = 0.7\ncats_micro_f = 0.3",
    ),
}

TINY_TRF_TAGGER_CFG = """
[nlp]
lang = "en"
pipeline = ["transformer","tagger"]

[components.transformer]
factory = "transformer"

[components.transformer.model]
@architectures = "spacy_ray_tpu.TransformerEncoder.v1"
width = 32
depth = 2
n_heads = 4
ffn_mult = 2
dropout = 0.1
max_len = 64
embed_size = 256
remat = false

[components.tagger]
factory = "tagger"

[components.tagger.model]
@architectures = "spacy.Tagger.v2"

[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 32
"""


# ---------------------------------------------------------------------------
# init-config --pipeline composition (spacy `init config --pipeline` role)
# ---------------------------------------------------------------------------

_CNN_TRUNK = """
[components.{trunk}]
factory = "tok2vec"

[components.{trunk}.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = {width}
depth = 4
embed_size = 2000
"""

_TRF_TRUNK = """
[components.{trunk}]
factory = "transformer"

[components.{trunk}.model]
@architectures = "spacy_ray_tpu.TransformerEncoder.v1"
width = {width}
depth = 12
n_heads = 12
dropout = 0.1
max_len = 512
embed_size = 20000
"""

_LISTENER = """
[components.{name}.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = {width}
"""

_TAGGER_LIKE = """
[components.{name}]
factory = "{factory}"

[components.{name}.model]
@architectures = "spacy.Tagger.v2"
""" + _LISTENER

_PARSER_LIKE = """
[components.{name}]
factory = "{factory}"

[components.{name}.model]
@architectures = "spacy.TransitionBasedParser.v2"
state_type = "{state_type}"
hidden_width = 128
maxout_pieces = 2
""" + _LISTENER

_SPANCAT_BLOCK = """
[components.{name}]
factory = "spancat"
spans_key = "sc"
threshold = 0.5

[components.{name}.suggester]
@misc = "spacy.ngram_suggester.v1"
sizes = [1,2,3]

[components.{name}.model]
@architectures = "spacy.SpanCategorizer.v1"
hidden_size = 128
""" + _LISTENER

_TEXTCAT_BLOCK = """
[components.{name}]
factory = "{factory}"

[components.{name}.model]
@architectures = "spacy.TextCatReduce.v1"
""" + _LISTENER

_HOST_ONLY_BLOCK = """
[components.{name}]
factory = "{factory}"
"""

# component name -> (template, template kwargs beyond name/width)
COMPOSABLE = {
    "tagger": (_TAGGER_LIKE, {"factory": "tagger"}),
    "morphologizer": (_TAGGER_LIKE, {"factory": "morphologizer"}),
    "senter": (_TAGGER_LIKE, {"factory": "senter"}),
    "trainable_lemmatizer": (_TAGGER_LIKE, {"factory": "trainable_lemmatizer"}),
    "parser": (_PARSER_LIKE, {"factory": "parser", "state_type": "parser"}),
    "ner": (_PARSER_LIKE, {"factory": "ner", "state_type": "ner"}),
    "spancat": (_SPANCAT_BLOCK, {}),
    "textcat": (_TEXTCAT_BLOCK, {"factory": "textcat"}),
    "textcat_multilabel": (_TEXTCAT_BLOCK, {"factory": "textcat_multilabel"}),
    "lemmatizer": (_HOST_ONLY_BLOCK, {"factory": "lemmatizer"}),
    "entity_ruler": (_HOST_ONLY_BLOCK, {"factory": "entity_ruler"}),
    "attribute_ruler": (_HOST_ONLY_BLOCK, {"factory": "attribute_ruler"}),
}

_HOST_ONLY = {"lemmatizer", "entity_ruler", "attribute_ruler"}


def compose_pipeline_config(
    pipeline, trunk: str = "cnn", width: int = 0
) -> str:
    """Generate a full trainable config for an arbitrary component list over
    one shared trunk (spacy's ``init config --pipeline`` role). Score
    weights are left to the components' declared ``default_score_weights``
    (the training loop combines and normalizes them when the section is
    empty)."""
    if trunk not in ("cnn", "trf"):
        raise ValueError(f"trunk must be 'cnn' or 'trf', got {trunk!r}")
    unknown = [c for c in pipeline if c not in COMPOSABLE]
    if unknown:
        raise ValueError(
            f"Can't compose {unknown!r} (supported: {', '.join(sorted(COMPOSABLE))}; "
            "entity_linker needs a knowledge base — start from a full config)"
        )
    if not pipeline:
        raise ValueError("pipeline must name at least one component")
    dupes = sorted({c for c in pipeline if pipeline.count(c) > 1})
    if dupes:
        raise ValueError(
            f"duplicate component name(s) in --pipeline: {', '.join(dupes)} "
            "(each composable component can appear once)"
        )
    width = width or (96 if trunk == "cnn" else 768)
    trunk_name = "tok2vec" if trunk == "cnn" else "transformer"
    needs_trunk = any(c not in _HOST_ONLY for c in pipeline)
    names = ([trunk_name] if needs_trunk else []) + list(pipeline)
    parts = [
        "\n[nlp]\nlang = \"en\"\npipeline = ["
        + ",".join(f'"{n}"' for n in names)
        + "]\n"
    ]
    if needs_trunk:
        tmpl = _CNN_TRUNK if trunk == "cnn" else _TRF_TRUNK
        parts.append(tmpl.format(trunk=trunk_name, width=width))
    for comp in pipeline:
        tmpl, kwargs = COMPOSABLE[comp]
        parts.append(tmpl.format(name=comp, width=width, **kwargs))
    return _full(
        "".join(parts),
        "",  # empty: loop derives weights from component metadata
        accumulate_gradient=3 if trunk == "trf" else 1,
        update_sharding="full" if trunk == "trf" else "auto",
    )
