"""``telemetry top`` — a live, stdlib-only terminal dashboard over the
observability plane's HTTP endpoints.

Polls each given base URL's ``/metrics`` (the JSON form — the same
payload the autoscaler and canary guard consume) and renders one screen
per refresh: request rate (derived from counter deltas between polls,
the scraper's rate() in miniature), sliding-window p50/p99, batch
occupancy, queue depth, serving generation + swap count, typed rejects,
scrape failures, the host-resource columns every endpoint now carries
(cpu% / rss / open fds, from the ``process`` block), and — for a
trainer endpoint — step rate, words/s and the anomaly count.

Design for testability (the dashboard must not need a fleet to be
verified): the clock, the fetch function, and the output stream are all
injected; :func:`render` is a pure rows-in/text-out function and
:class:`TopModel` is pure delta arithmetic — unit tests drive both with
synthetic payloads and a fake clock (tests/test_observability.py).
"""

from __future__ import annotations

import sys
import time
from typing import Any, Callable, Dict, List, Optional, TextIO

__all__ = ["TopModel", "classify_payload", "render", "run_top"]

CLEAR = "\x1b[2J\x1b[H"


def classify_payload(payload: Dict[str, Any]) -> str:
    """Which kind of endpoint answered: ``router`` (fleet view),
    ``trainer`` (step histograms, or a trainer-fleet worker's ledger —
    a telemetry-off fleet worker serves counters + a ``fleet_worker``
    gauge and no histograms at all), or ``serving`` (a single
    replica)."""
    if "fleet" in payload:
        return "router"
    hists = payload.get("histograms") or {}
    if "step_seconds" in hists:
        return "trainer"
    if (payload.get("gauges") or {}).get("fleet_worker") is not None:
        return "trainer"
    return "serving"


def _get(d: Optional[Dict[str, Any]], *keys: str) -> Any:
    cur: Any = d
    for k in keys:
        if not isinstance(cur, dict):
            return None
        cur = cur.get(k)
    return cur


def _fmt_ms(v: Any) -> str:
    return f"{float(v) * 1e3:7.1f}ms" if isinstance(v, (int, float)) else "      -"


def _fmt_rate(v: Optional[float]) -> str:
    return f"{v:7.1f}/s" if isinstance(v, (int, float)) else "      -"


def _fmt_int(v: Any) -> str:
    return f"{int(v):,}" if isinstance(v, (int, float)) else "-"


def _fmt_pct(v: Any) -> str:
    return f"{float(v):.0f}%" if isinstance(v, (int, float)) else "-"


def _fmt_bytes(v: Any) -> str:
    if not isinstance(v, (int, float)):
        return "-"
    if v >= 1 << 30:
        return f"{v / (1 << 30):.2f}GB"
    return f"{v / (1 << 20):.0f}MB"


def _process_cols(payload: Dict[str, Any]) -> Dict[str, Any]:
    """The host-resource columns every row kind shares, from the
    payload's top-level ``process`` block (hoststats.ProcessSampler on
    each surface). Absent block = absent columns, honest dashes."""
    proc = payload.get("process")
    if not isinstance(proc, dict):
        return {"cpu_pct": None, "rss": None, "fds": None}
    return {
        "cpu_pct": proc.get("cpu_percent"),
        "rss": proc.get("rss_bytes"),
        "fds": proc.get("open_fds"),
    }


def _fmt_host(row: Dict[str, Any]) -> str:
    return (
        f"cpu {_fmt_pct(row.get('cpu_pct'))}  "
        f"rss {_fmt_bytes(row.get('rss'))}  "
        f"fd {_fmt_int(row.get('fds'))}"
    )


class TopModel:
    """Holds the previous poll's counters per URL and turns the current
    poll into a display row (rates = counter deltas / elapsed)."""

    def __init__(self) -> None:
        self._prev: Dict[str, Any] = {}  # url -> (t, counters dict)
        # consecutive failed scrapes per URL: a fleet worker exiting
        # mid-poll is COUNTED (and shown), never allowed to break the
        # refresh loop
        self._failures: Dict[str, int] = {}

    def _rates(
        self, url: str, counters: Dict[str, Any], now: float
    ) -> Dict[str, Optional[float]]:
        prev = self._prev.get(url)
        self._prev[url] = (now, dict(counters))
        if prev is None:
            return {}
        t_prev, prev_counters = prev
        dt = now - t_prev
        if dt <= 0:
            return {}
        out: Dict[str, Optional[float]] = {}
        for key, value in counters.items():
            if isinstance(value, (int, float)) and isinstance(
                prev_counters.get(key), (int, float)
            ):
                out[key] = max(float(value) - float(prev_counters[key]), 0.0) / dt
        return out

    def update(
        self, url: str, payload: Optional[Dict[str, Any]], now: float
    ) -> Dict[str, Any]:
        """One endpoint's display row. ``payload`` None = unreachable."""
        if payload is None:
            self._failures[url] = self._failures.get(url, 0) + 1
            return {
                "url": url, "kind": "down",
                "failures": self._failures[url],
            }
        self._failures[url] = 0
        kind = classify_payload(payload)
        if kind == "router":
            fleet = payload.get("fleet") or {}
            counters = dict(fleet.get("counters") or {})
            router = payload.get("router") or {}
            for k, v in (router.get("counters") or {}).items():
                counters[f"router.{k}"] = v
            # per-model counters (multi-model fleets) join the same
            # delta arithmetic under a "model.<name>." prefix, so each
            # model's req/s and quota-reject/s come for free
            by_model = fleet.get("by_model") or {}
            for mname, sub in by_model.items():
                if not isinstance(sub, dict):
                    continue
                for k, v in (sub.get("counters") or {}).items():
                    if isinstance(v, (int, float)):
                        counters[f"model.{mname}.{k}"] = v
            # the edge cache's ledger (router /metrics "cache" block —
            # the same surface the Zipfian bench record reads): lifetime
            # hit rate over hits+misses; None when the cache is off
            cache = payload.get("cache")
            cache_hit_rate = None
            if isinstance(cache, dict):
                hits = cache.get("cache_hits") or 0
                misses = cache.get("cache_misses") or 0
                if hits + misses > 0:
                    cache_hit_rate = hits / (hits + misses)
                else:
                    cache_hit_rate = 0.0
            rates = self._rates(url, counters, now)
            replicas = payload.get("replicas") or []
            # per-model rows: window p99 from the merged by_model view,
            # cache hit % from the per-model cache ledger, and the
            # resident-replica count from the probe-learned placement
            placement = payload.get("placement") or {}
            cache_by_model = (
                cache.get("by_model") if isinstance(cache, dict) else None
            ) or {}
            models: List[Dict[str, Any]] = []
            for mname in sorted(by_model):
                sub = by_model[mname] if isinstance(
                    by_model[mname], dict
                ) else {}
                ledger = cache_by_model.get(mname) or {}
                m_hits = ledger.get("hits") or 0
                m_misses = ledger.get("misses") or 0
                models.append({
                    "name": mname,
                    "req_s": rates.get(f"model.{mname}.requests"),
                    "p99": _get(sub, "slo_window", "request_latency_p99"),
                    "cache_hit_rate": (
                        m_hits / (m_hits + m_misses)
                        if (m_hits + m_misses) > 0 else None
                    ),
                    "hosts": sum(
                        1 for ms in placement.values()
                        if mname in (ms or [])
                    ),
                    "quota_s": rates.get(
                        f"model.{mname}.rejected_quota"
                    ),
                })
            return {
                "url": url,
                "kind": kind,
                "req_s": rates.get("router.requests"),
                "p50": _get(fleet, "slo_window", "request_latency_p50"),
                "p99": _get(fleet, "slo_window", "request_latency_p99"),
                "p99_worst": _get(
                    fleet, "slo_window", "request_latency_p99_worst"
                ),
                "queue_depth": _get(fleet, "gauges", "queue_depth", "sum"),
                "occupancy": _get(
                    fleet, "histograms", "batch_occupancy", "p50"
                ),
                "ready": sum(1 for r in replicas if r.get("ready")),
                "replicas": len(replicas),
                "generations": sorted(
                    {
                        str(r.get("generation"))
                        for r in replicas if r.get("ready")
                    }
                ),
                "swaps": sum(
                    int(r.get("swap_count") or 0) for r in replicas
                ),
                "reject_s": (
                    (rates.get("router.rejected_no_replica") or 0.0)
                    + (rates.get("router.rejected_draining") or 0.0)
                    + (rates.get("rejected_queue_full") or 0.0)
                    + (rates.get("deadline_exceeded") or 0.0)
                ) if rates else None,
                "scrape_failures": sum(
                    int(v) for v in (payload.get("scrape_failures") or {}).values()
                ),
                "cache_hit_rate": cache_hit_rate,
                "cache_bypasses": (
                    cache.get("cache_mixed_generation_bypasses")
                    if isinstance(cache, dict) else None
                ),
                # data plane (PR 20): fleet-wide padded-token share
                # from the engines' dispatch assembly, and conditional
                # (304) responses from the cache ledger
                "pad_share": _pad_share(counters),
                "not_modified": (
                    cache.get("cache_not_modified")
                    if isinstance(cache, dict) else None
                ),
                "quota_s": rates.get("rejected_quota"),
                "models": models,
                "alerts": payload.get("alerts"),
                **_process_cols(payload),
            }
        if kind == "trainer":
            counters = dict(payload.get("counters") or {})
            hists = payload.get("histograms") or {}
            # per-phase histogram SUMS are monotone like counters, so
            # feeding them through the same delta arithmetic yields
            # "seconds of phase X per wall second" — the apply-wait
            # share column is their ratio over all phases
            for name, h in hists.items():
                if (
                    name.startswith("phase_")
                    and isinstance(h, dict)
                    and isinstance(h.get("sum"), (int, float))
                ):
                    counters[f"hist.{name}.sum"] = float(h["sum"])
            rates = self._rates(url, counters, now)
            phase_rates = {
                k: v for k, v in rates.items()
                if k.startswith("hist.phase_") and isinstance(v, float)
            }
            apply_wait_pct = None
            if phase_rates:
                total = sum(phase_rates.values())
                wait = phase_rates.get("hist.phase_apply_wait_seconds.sum")
                if total > 0 and wait is not None:
                    apply_wait_pct = wait / total
                elif wait is not None:
                    apply_wait_pct = 0.0
            # fleet workers (training/fleet/) are trainers with a worker
            # id, a shard version, and the async plane's push/discard
            # counters — each worker is its own scrape URL, so the
            # per-worker columns come for free from per-row rates
            worker = _get(payload, "gauges", "fleet_worker")
            discard_rate = None
            push_s = rates.get("grad_pushed")
            recv_s = rates.get("grad_received")
            disc_s = rates.get("grad_discarded")
            if isinstance(recv_s, float) and isinstance(disc_s, float):
                discard_rate = disc_s / recv_s if recv_s > 0 else 0.0
            # the wire column: push MB/s actually sent plus the
            # compression ratio (uncompressed/actual) — same counter-
            # delta arithmetic, two more monotone series
            wire_push_bps = rates.get("wire_push_bytes")
            wire_push_raw_bps = rates.get("wire_push_bytes_uncompressed")
            wire_ratio = None
            if (
                isinstance(wire_push_bps, float)
                and isinstance(wire_push_raw_bps, float)
                and wire_push_bps > 0
            ):
                wire_ratio = wire_push_raw_bps / wire_push_bps
            return {
                "url": url,
                "kind": kind,
                "steps_s": rates.get("steps"),
                "words_s": rates.get("words"),
                "step_p50": _get(hists, "step_seconds", "p50"),
                "step_p95": _get(hists, "step_seconds", "p95"),
                "anomalies": counters.get("anomalies"),
                "compiles": _get(payload, "gauges", "compile_count"),
                "hbm_peak": _get(payload, "gauges", "hbm_peak_bytes"),
                "alerts": payload.get("alerts"),
                "worker": worker,
                "version": _get(payload, "gauges", "param_version"),
                "epoch": _get(payload, "gauges", "membership_epoch"),
                "evictions": counters.get("evictions"),
                "push_s": push_s,
                "discard_s": disc_s,
                "discard_rate": discard_rate,
                "apply_wait_pct": apply_wait_pct,
                "staleness_max": _get(hists, "staleness", "max"),
                "wire_push_bps": wire_push_bps,
                "wire_ratio": wire_ratio,
                **_process_cols(payload),
            }
        counters = dict(payload.get("counters") or {})
        # a multi-model replica's /metrics carries per-engine snapshots
        # under "models": same prefix trick as the router view
        replica_models = payload.get("models") or {}
        for mname, msnap in replica_models.items():
            if not isinstance(msnap, dict):
                continue
            for k, v in (msnap.get("counters") or {}).items():
                if isinstance(v, (int, float)):
                    counters[f"model.{mname}.{k}"] = v
        rates = self._rates(url, counters, now)
        models = []
        for mname in sorted(replica_models):
            msnap = replica_models[mname] if isinstance(
                replica_models[mname], dict
            ) else {}
            models.append({
                "name": mname,
                "req_s": rates.get(f"model.{mname}.requests"),
                "p99": _get(msnap, "slo_window", "request_latency_p99"),
                "cache_hit_rate": None,
                "hosts": None,
                "quota_s": rates.get(f"model.{mname}.rejected_quota"),
            })
        return {
            "url": url,
            "kind": kind,
            "req_s": rates.get("requests"),
            "p50": _get(payload, "slo_window", "request_latency_p50"),
            "p99": _get(payload, "slo_window", "request_latency_p99"),
            "queue_depth": _get(payload, "gauges", "queue_depth"),
            "occupancy": _get(payload, "gauges", "last_batch_occupancy"),
            "generation": payload.get("generation"),
            "swaps": payload.get("swap_count"),
            "reject_s": (
                (rates.get("rejected_queue_full") or 0.0)
                + (rates.get("rejected_draining") or 0.0)
                + (rates.get("deadline_exceeded") or 0.0)
            ) if rates else None,
            "exemplars": counters.get("slow_exemplars"),
            "pad_share": _pad_share(counters),
            "quota_s": rates.get("rejected_quota"),
            "models": models,
            "alerts": payload.get("alerts"),
            **_process_cols(payload),
        }


def _pad_share(counters: Dict[str, Any]) -> Optional[float]:
    """Lifetime padded-token share from the pad/real counter pair the
    engine's dispatch assembly exports; None before any batch ran (or
    against an older endpoint without the counters)."""
    pad = counters.get("pad_tokens")
    real = counters.get("real_tokens")
    if not isinstance(pad, (int, float)) or not isinstance(
        real, (int, float)
    ):
        return None
    total = pad + real
    return (pad / total) if total > 0 else None


def _fmt_alerts(block: Any) -> str:
    """The alert column: ``FIRING name[+k]`` when anything is firing,
    ``pending n`` while confirming, ``ok`` when the endpoint runs an
    alert engine with nothing active, ``-`` when it has none."""
    if not isinstance(block, dict):
        return "-"
    firing = int(block.get("firing") or 0)
    pending = int(block.get("pending") or 0)
    if firing:
        names = block.get("firing_names") or []
        first = names[0] if names else "?"
        more = f"+{firing - 1}" if firing > 1 else ""
        return f"FIRING {first}{more}"
    if pending:
        return f"pending {pending}"
    return "ok"


def _model_lines(row: Dict[str, Any], lines: List[str]) -> None:
    """Per-model sub-rows (multi-model serving): req/s, window p99,
    cache hit %, resident-replica count, quota-reject/s."""
    for m in row.get("models") or []:
        hr = m.get("cache_hit_rate")
        cache_s = f"{hr * 100:.0f}%" if isinstance(hr, float) else "-"
        hosts = m.get("hosts")
        hosts_s = _fmt_int(hosts) if hosts is not None else "-"
        lines.append(
            f"    model {m.get('name')}  "
            f"req {_fmt_rate(m.get('req_s'))}  "
            f"p99 {_fmt_ms(m.get('p99'))}  "
            f"cache {cache_s}  "
            f"hosts {hosts_s}  "
            f"429-quota {_fmt_rate(m.get('quota_s'))}"
        )


def render(rows: List[Dict[str, Any]], *, now_label: str = "") -> str:
    """Rows → one dashboard screen (pure; no I/O, no clock)."""
    lines = [f"srt telemetry top{('  ' + now_label) if now_label else ''}"]
    for row in rows:
        kind = row.get("kind")
        if kind == "down":
            n_fail = row.get("failures")
            tail = (
                f" ({int(n_fail)} failed scrape(s))"
                if isinstance(n_fail, (int, float)) and n_fail > 1
                else ""
            )
            lines.append(f"  {row['url']}: UNREACHABLE{tail}")
            continue
        if kind == "router":
            gens = ",".join(row.get("generations") or []) or "-"
            lines.append(
                f"  router  {row['url']}  "
                f"ready {row.get('ready')}/{row.get('replicas')}"
            )
            lines.append(
                f"    req {_fmt_rate(row.get('req_s'))}  "
                f"win p50 {_fmt_ms(row.get('p50'))}  "
                f"p99 {_fmt_ms(row.get('p99'))}  "
                f"worst {_fmt_ms(row.get('p99_worst'))}"
            )
            hr = row.get("cache_hit_rate")
            cache_s = f"{hr * 100:.0f}%" if isinstance(hr, float) else "-"
            ps = row.get("pad_share")
            pad_s = f"{ps * 100:.0f}%" if isinstance(ps, float) else "-"
            lines.append(
                f"    queue {_fmt_int(row.get('queue_depth'))}  "
                f"occ p50 {_fmt_int(row.get('occupancy'))}  "
                f"gen [{gens}]  swaps {_fmt_int(row.get('swaps'))}  "
                f"rej {_fmt_rate(row.get('reject_s'))}  "
                f"429-quota {_fmt_rate(row.get('quota_s'))}  "
                f"cache {cache_s}  "
                f"pad {pad_s}  "
                f"304 {_fmt_int(row.get('not_modified'))}  "
                f"scrape-fail {_fmt_int(row.get('scrape_failures'))}  "
                f"{_fmt_host(row)}  "
                f"alerts {_fmt_alerts(row.get('alerts'))}"
            )
            _model_lines(row, lines)
        elif kind == "trainer":
            worker = row.get("worker")
            tag = (
                f"  [fleet worker {int(worker)}]"
                if isinstance(worker, (int, float))
                else ""
            )
            lines.append(f"  trainer {row['url']}{tag}")
            lines.append(
                f"    steps {_fmt_rate(row.get('steps_s'))}  "
                f"words {_fmt_rate(row.get('words_s'))}  "
                f"step p50 {_fmt_ms(row.get('step_p50'))}  "
                f"p95 {_fmt_ms(row.get('step_p95'))}"
            )
            if isinstance(worker, (int, float)):
                dr = row.get("discard_rate")
                dr_s = f"{dr * 100:.0f}%" if isinstance(dr, float) else "-"
                aw = row.get("apply_wait_pct")
                aw_s = f"{aw * 100:.0f}%" if isinstance(aw, float) else "-"
                sm = row.get("staleness_max")
                sm_s = f"{int(sm)}" if isinstance(sm, (int, float)) else "-"
                wb = row.get("wire_push_bps")
                wb_s = (
                    f"{wb / 1e6:.2f}MB/s" if isinstance(wb, float) else "-"
                )
                wr = row.get("wire_ratio")
                wr_s = f"{wr:.1f}x" if isinstance(wr, float) else "-"
                lines.append(
                    f"    ver {_fmt_int(row.get('version'))}  "
                    f"epoch {_fmt_int(row.get('epoch'))}  "
                    f"evict {_fmt_int(row.get('evictions'))}  "
                    f"push {_fmt_rate(row.get('push_s'))}  "
                    f"disc {_fmt_rate(row.get('discard_s'))}  "
                    f"disc-rate {dr_s}  "
                    f"wait {aw_s}  "
                    f"stale-max {sm_s}  "
                    f"wire {wb_s} ({wr_s})"
                )
            lines.append(
                f"    anomalies {_fmt_int(row.get('anomalies'))}  "
                f"compiles {_fmt_int(row.get('compiles'))}  "
                f"{_fmt_host(row)}  "
                f"alerts {_fmt_alerts(row.get('alerts'))}"
            )
        else:
            lines.append(
                f"  replica {row['url']}  "
                f"gen {row.get('generation') if row.get('generation') is not None else '-'}"
                f"  swaps {_fmt_int(row.get('swaps'))}"
            )
            ps = row.get("pad_share")
            pad_s = f"{ps * 100:.0f}%" if isinstance(ps, float) else "-"
            lines.append(
                f"    req {_fmt_rate(row.get('req_s'))}  "
                f"win p50 {_fmt_ms(row.get('p50'))}  "
                f"p99 {_fmt_ms(row.get('p99'))}  "
                f"queue {_fmt_int(row.get('queue_depth'))}  "
                f"occ {_fmt_int(row.get('occupancy'))}  "
                f"rej {_fmt_rate(row.get('reject_s'))}  "
                f"429-quota {_fmt_rate(row.get('quota_s'))}  "
                f"pad {pad_s}  "
                f"slow-exemplars {_fmt_int(row.get('exemplars'))}  "
                f"{_fmt_host(row)}  "
                f"alerts {_fmt_alerts(row.get('alerts'))}"
            )
            _model_lines(row, lines)
    return "\n".join(lines) + "\n"


def _default_fetch(url: str, timeout_s: float) -> Optional[Dict[str, Any]]:
    from .serving.tracecollect import fetch_json

    try:
        status, payload = fetch_json(url, "/metrics", timeout_s)
    except OSError:
        return None
    return payload if status == 200 and isinstance(payload, dict) else None


def run_top(
    urls: List[str],
    *,
    interval_s: float = 2.0,
    iterations: Optional[int] = None,
    out: TextIO = sys.stdout,
    fetch: Callable[[str, float], Optional[Dict[str, Any]]] = _default_fetch,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    timeout_s: float = 5.0,
) -> int:
    """The poll-render loop. ``iterations=None`` runs until Ctrl-C."""
    model = TopModel()
    n = 0

    def poll(url: str) -> Optional[Dict[str, Any]]:
        # ANY scrape failure (transport OSError, a peer dying between
        # the status line and the body, torn JSON) is one endpoint's
        # "down" row this refresh — never the whole loop's crash
        try:
            return fetch(url, timeout_s)
        except Exception:
            return None

    try:
        while iterations is None or n < iterations:
            now = clock()
            rows = [model.update(u, poll(u), now) for u in urls]
            label = time.strftime("%H:%M:%S")
            out.write(CLEAR + render(rows, now_label=label))
            out.flush()
            n += 1
            if iterations is not None and n >= iterations:
                break
            sleep(interval_s)
    except KeyboardInterrupt:
        out.write("\n")
    return 0
