"""Config system: the ``config.cfg`` format the whole framework is driven by.

Capability parity with the config surface the reference preserves
(reference train_cli.py:44-46 ``load_config(config_path, overrides,
interpolate=False)``; worker.py:92 deferred ``config.interpolate()``;
train_cli.py:27,39 CLI dotted overrides via ``parse_config_overrides``).

Format (same shape as thinc/spacy configs):

* INI-style sections; dots nest: ``[components.tagger.model]``
* JSON-ish values: ``"str"``, ``1``, ``0.5``, ``true``/``false``, ``null``,
  ``["a", "b"]``, ``{"k": 1}``; bare words tolerated as strings
* variable interpolation ``${paths.train}`` resolved against the root,
  deferred until :meth:`Config.interpolate` is called
* registry references: a ``@architectures = "Name.v1"`` key marks the block
  for :meth:`Registry.resolve`
* dotted overrides: ``{"training.max_steps": 100}`` applied before
  interpolation, mirroring ``spacy ray train config.cfg --training.max_steps
  100`` (reference train_cli.py:27,39,44-46)
"""

from __future__ import annotations

import copy
import json
import re
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Tuple, Union

_VAR_RE = re.compile(r"\$\{([^}]+)\}")


class ConfigValidationError(ValueError):
    pass


def _parse_value(raw: str) -> Any:
    raw = raw.strip()
    if raw == "":
        return ""
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        pass
    # Python-literal fallbacks people write in configs
    lowered = raw.lower()
    if lowered in ("true", "false"):
        return lowered == "true"
    if lowered in ("null", "none"):
        return None
    # Bare word -> string (lenient, like thinc's fallback)
    return raw


def _format_value(value: Any) -> str:
    if isinstance(value, str):
        # Preserve interpolation expressions unquoted-compatible; thinc quotes
        # strings, and json.dumps gives us exactly that.
        return json.dumps(value)
    if isinstance(value, bool):
        return "true" if value else "false"
    if value is None:
        return "null"
    if isinstance(value, (list, tuple)):
        return json.dumps(list(value))
    if isinstance(value, dict):
        return json.dumps(value)
    if isinstance(value, float):
        return repr(value)
    return str(value)


class Config(dict):
    """Nested-dict config with parse/serialize/interpolate/override support.

    ``origin_path`` records the file this config was loaded from (set by
    :meth:`from_disk`, carried through interpolate/override/merge): the
    anchor for resolving RELATIVE paths inside the config — e.g.
    ``[initialize.components.<name>] labels`` — against the config's own
    directory instead of whatever CWD the process was launched from.
    """

    origin_path: Optional[Path] = None

    def __init__(self, data: Optional[Dict[str, Any]] = None):
        super().__init__()
        if data:
            self.update(copy.deepcopy(dict(data)))
        if isinstance(data, Config):
            self.origin_path = data.origin_path

    def _carry_origin(self, out: "Config") -> "Config":
        out.origin_path = self.origin_path
        return out

    # ------------------------------------------------------------------
    # Parsing / serialization
    # ------------------------------------------------------------------
    @classmethod
    def from_str(cls, text: str) -> "Config":
        root: Dict[str, Any] = {}
        section: Optional[Dict[str, Any]] = None
        pending_key: Optional[str] = None
        pending_lines: List[str] = []

        def flush_pending():
            nonlocal pending_key, pending_lines
            if pending_key is not None and section is not None:
                section[pending_key] = _parse_value("\n".join(pending_lines))
            pending_key, pending_lines = None, []

        for raw_line in text.splitlines():
            line = raw_line.strip()
            if not line or line.startswith("#") or line.startswith(";"):
                continue
            if line.startswith("[") and line.endswith("]"):
                flush_pending()
                path = line[1:-1].strip()
                section = cls._ensure_section(root, path.split("."))
                continue
            if "=" in line and not (pending_lines and _is_continuation(line)):
                flush_pending()
                key, _, raw_value = line.partition("=")
                key = key.strip()
                if section is None:
                    section = root
                pending_key = key
                pending_lines = [raw_value.strip()]
            elif pending_key is not None:
                # multi-line JSON value continuation
                pending_lines.append(line)
            else:
                raise ConfigValidationError(f"Can't parse config line: {raw_line!r}")
        flush_pending()
        return cls(root)

    @staticmethod
    def _ensure_section(root: Dict[str, Any], parts: List[str]) -> Dict[str, Any]:
        node = root
        for part in parts:
            nxt = node.get(part)
            if nxt is None:
                nxt = {}
                node[part] = nxt
            elif not isinstance(nxt, dict):
                raise ConfigValidationError(
                    f"Section path {'.'.join(parts)} collides with value key {part!r}"
                )
            node = nxt
        return node

    @classmethod
    def from_disk(cls, path: Union[str, Path]) -> "Config":
        config = cls.from_str(Path(path).read_text(encoding="utf8"))
        config.origin_path = Path(path)
        return config

    def to_str(self) -> str:
        lines: List[str] = []

        def emit(section: Dict[str, Any], path: Tuple[str, ...]):
            scalars = {
                k: v for k, v in section.items() if not isinstance(v, dict) or k.startswith("@")
            }
            subsections = {
                k: v for k, v in section.items() if isinstance(v, dict) and not k.startswith("@")
            }
            if path:
                lines.append(f"[{'.'.join(path)}]")
            for k, v in scalars.items():
                lines.append(f"{k} = {_format_value(v)}")
            if path or scalars:
                lines.append("")
            for k, v in subsections.items():
                emit(v, path + (k,))

        emit(self, ())
        return "\n".join(lines).strip() + "\n"

    def to_disk(self, path: Union[str, Path]) -> None:
        Path(path).write_text(self.to_str(), encoding="utf8")

    # ------------------------------------------------------------------
    # Interpolation
    # ------------------------------------------------------------------
    def interpolate(self) -> "Config":
        """Resolve ``${dotted.path}`` references against the root.

        Returns a new Config; deferred by default at load time, matching the
        reference's ``interpolate=False`` + later ``config.interpolate()``
        (reference train_cli.py:46, worker.py:92).
        """
        resolved = copy.deepcopy(dict(self))

        def lookup(dotted: str) -> Any:
            node: Any = resolved
            for part in dotted.split("."):
                if not isinstance(node, dict) or part not in node:
                    raise ConfigValidationError(
                        f"Can't interpolate ${{{dotted}}}: not found"
                    )
                node = node[part]
            return node

        def interp(value: Any, depth: int = 0) -> Any:
            if depth > 16:
                raise ConfigValidationError("Interpolation too deep (cycle?)")
            if isinstance(value, str):
                full = _VAR_RE.fullmatch(value)
                if full:
                    return interp(lookup(full.group(1)), depth + 1)
                return _VAR_RE.sub(
                    lambda m: str(interp(lookup(m.group(1)), depth + 1)), value
                )
            if isinstance(value, dict):
                return {k: interp(v, depth) for k, v in value.items()}
            if isinstance(value, list):
                return [interp(v, depth) for v in value]
            return value

        # Iterate until fixpoint over the whole tree (vars may reference vars).
        out = interp(resolved)
        return self._carry_origin(Config(out))

    # ------------------------------------------------------------------
    # Overrides / merge
    # ------------------------------------------------------------------
    def apply_overrides(self, overrides: Dict[str, Any]) -> "Config":
        out = Config(self)
        for dotted, value in overrides.items():
            node: Dict[str, Any] = out
            parts = dotted.split(".")
            for part in parts[:-1]:
                if part not in node or not isinstance(node[part], dict):
                    node[part] = {}
                node = node[part]
            node[parts[-1]] = value
        return out

    def merge(self, other: Dict[str, Any]) -> "Config":
        def deep_merge(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
            out = dict(a)
            for k, v in b.items():
                if k in out and isinstance(out[k], dict) and isinstance(v, dict):
                    out[k] = deep_merge(out[k], v)
                else:
                    out[k] = copy.deepcopy(v)
            return out

        return self._carry_origin(Config(deep_merge(dict(self), dict(other))))

    # ------------------------------------------------------------------
    def walk_sections(self) -> Iterator[Tuple[Tuple[str, ...], Dict[str, Any]]]:
        def walk(node: Dict[str, Any], path: Tuple[str, ...]):
            yield path, node
            for k, v in node.items():
                if isinstance(v, dict):
                    yield from walk(v, path + (k,))

        yield from walk(self, ())


def _is_continuation(line: str) -> bool:
    """Heuristic: a line inside a multi-line JSON value, not a new key."""
    stripped = line.strip()
    return stripped.startswith(("]", "}", '"', "'", "[", "{", ","))


def load_config(
    path: Union[str, Path],
    overrides: Optional[Dict[str, Any]] = None,
    *,
    interpolate: bool = False,
) -> Config:
    """Load a config file with optional dotted overrides.

    Signature mirrors the reference's use of ``spacy.util.load_config``
    (reference train_cli.py:44-46).
    """
    config = Config.from_disk(path)
    if overrides:
        config = config.apply_overrides(overrides)
    if interpolate:
        config = config.interpolate()
    return config


def parse_cli_overrides(args: List[str]) -> Dict[str, Any]:
    """Parse ``--training.max_steps 100 --paths.train x.jsonl`` style extras.

    Equivalent of spacy's ``parse_config_overrides`` used at reference
    train_cli.py:39.
    """
    overrides: Dict[str, Any] = {}
    i = 0
    while i < len(args):
        arg = args[i]
        if not arg.startswith("--"):
            raise ConfigValidationError(f"Expected --dotted.name, got {arg!r}")
        key = arg[2:]
        if "=" in key:
            key, _, raw = key.partition("=")
            overrides[key] = _parse_value(raw)
            i += 1
        else:
            if i + 1 >= len(args):
                raise ConfigValidationError(f"Override {arg!r} missing a value")
            overrides[key] = _parse_value(args[i + 1])
            i += 2
    return overrides
