"""Pallas int8 weight-only matmul for the serving precision overlay.

The serving overlay's ``--precision int8`` knob existed since PR 7 with
an honestly-refusing probe (``serving/overlay.py:_probe_int8`` — "no
int8 serving kernel on <backend>"). This module is that kernel: the
weights of the transformer trunk's dense matmuls are quantized ONCE at
overlay build time to int8 with per-output-channel symmetric scales
(``quantize_int8``), and the forward consumes them through this
pallas_call — the int8 block is dequantized IN-KERNEL (int8 -> f32 on
the VPU, one multiply by the channel scale after the dot) so HBM streams
the weights at 1/4 of their f32 byte volume while the MXU still
accumulates in f32. Activations stay in the compute dtype (weight-only
quantization: the activation distribution is input-dependent and NOT
quantized — SURVEY.md's serving-precision ladder, and the standard
weight-only serving recipe).

Why the memory shape matters: serving batches are small (continuous
admission dispatches at occupancy 2-8 on the committed records), so the
trunk matmuls are BANDWIDTH-bound — every dispatched batch re-streams
the whole weight matrix from HBM. Quartering the weight bytes is the
per-replica multiplier ROADMAP item 3a names; the arithmetic itself was
never the bottleneck at these occupancies.

Honesty rules (the flash-attention/fused-update discipline, verbatim):

* enabled ONLY by :func:`int8_probe` — compile + numeric validation vs
  the f32-dequant reference on the current backend; ``SRT_PALLAS_INT8=1``
  forces on (interpret-mode on non-TPU backends, so CPU tests and the
  forced bench arm run the REAL kernel body, interpreted), ``=0`` forces
  off; default auto-enables on TPU only.
* the probe's reason string is the overlay label's source of truth:
  "active (pallas)" only when the compiled kernel runs, "active (pallas
  interpret-mode, forced)" when interpreted, a typed refusal otherwise.
* shapes whose per-block VMEM working set exceeds the budget fall back
  to the jnp dequant matmul (same numbers, no kernel) — the same
  host-side guard as ``flash_attention.attention_vmem_ok``.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "quantize_int8",
    "dequantize_int8",
    "quantize_int8_np",
    "dequantize_int8_np",
    "reference_int8_matmul",
    "int8_matmul",
    "int8_matmul_enabled",
    "int8_probe",
]

BM = 128   # activation rows per grid step (MXU-aligned)
BN = 128   # output-channel block (lane-aligned)
KP = 128   # contraction dim padded to a lane multiple
# VMEM budget for one grid step: x block (f32) + w block (int8) + out +
# scale. K stays fully resident per step (encoder trunk K <= ~4k).
VMEM_INT8_BUDGET = 10 * 1024 * 1024

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_IMPORTED = True
except Exception:  # pragma: no cover
    _PALLAS_IMPORTED = False


# ------------------------------------------------------------ quantization


def quantize_int8(w: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-output-channel symmetric int8 quantization of a weight array
    whose LAST axis is the output channel: returns ``(q8, scale)`` with
    ``q8`` int8 in [-127, 127] and ``scale`` f32 per channel, such that
    ``q8 * scale ~= w`` with per-element error bounded by ``scale / 2``
    (round-to-nearest; test-enforced). Symmetric (no zero point): the
    dequant epilogue stays one multiply, and trunk weight distributions
    are zero-centered (glorot/normal init, weight decay)."""
    w = jnp.asarray(w, jnp.float32)
    reduce_axes = tuple(range(w.ndim - 1))
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes)
    scale = jnp.maximum(absmax / 127.0, 1e-12).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / scale), -127.0, 127.0).astype(jnp.int8)
    return q, scale


def dequantize_int8(q8: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """``q8 [..., N] int8, scale [N] f32 -> f32`` — the reference
    reconstruction the kernel's in-VMEM dequant must match."""
    return q8.astype(jnp.float32) * scale


def quantize_int8_np(arr) -> Tuple["np.ndarray", "np.ndarray"]:
    """Grad-shaped host-side twin of :func:`quantize_int8` for the
    trainer fleet's wire compression (training/fleet/wire.py): pure
    numpy (gradients are already host arrays on the push path — no
    device round trip), same symmetric per-channel semantics and the
    same test-pinned bound (per-element error <= scale / 2).

    Shape policy: rank >= 2 quantizes per-channel over the LAST axis
    (``scale`` shape ``(N,)``, exactly :func:`quantize_int8`); rank <= 1
    uses ONE per-tensor scale (``scale`` shape ``()``) — a per-element
    scale on a vector would cost 5 bytes/element against the 4 it
    replaces. Gradient leaves are any rank, weight matrices rank 2+."""
    import numpy as np

    a = np.ascontiguousarray(np.asarray(arr, dtype=np.float32))
    if a.ndim >= 2:
        reduce_axes = tuple(range(a.ndim - 1))
        absmax = np.max(np.abs(a), axis=reduce_axes) if a.size else np.zeros(
            a.shape[-1], np.float32
        )
    else:
        absmax = np.max(np.abs(a)) if a.size else np.float32(0.0)
    scale = np.maximum(
        np.asarray(absmax, np.float32) / np.float32(127.0), np.float32(1e-12)
    ).astype(np.float32)
    q = np.clip(np.rint(a / scale), -127.0, 127.0).astype(np.int8)
    return q, scale


def dequantize_int8_np(q8, scale) -> "np.ndarray":
    """Host-side reconstruction twin of :func:`dequantize_int8` —
    broadcasting covers both the per-channel (rank >= 2) and per-tensor
    (rank <= 1) scale shapes :func:`quantize_int8_np` emits."""
    import numpy as np

    return q8.astype(np.float32) * np.asarray(scale, np.float32)


def reference_int8_matmul(
    x: jnp.ndarray, q8: jnp.ndarray, scale: jnp.ndarray
) -> jnp.ndarray:
    """jnp fallback/reference: ``x [..., K] @ dequant(q8 [K, N]) -> [..., N]``
    in f32 — what the pallas kernel is validated against."""
    return x.astype(jnp.float32) @ dequantize_int8(q8, scale)


# ----------------------------------------------------------------- kernel


def _kernel(x_ref, wq_ref, s_ref, o_ref):
    # x [BM, K] f32, wq [K, BN] int8, s [1, BN] f32 -> o [BM, BN] f32.
    # Dequantize-in-kernel: the int8 block upcasts on the VPU; the scale
    # multiply lands on the f32 accumulator AFTER the dot (exact: scale
    # is constant per output column, so (x @ q) * s == x @ (q * s)).
    x = x_ref[...]
    w = wq_ref[...].astype(jnp.float32)
    acc = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    o_ref[...] = acc * s_ref[...]


_INTERPRET = False  # tests flip this to run the kernel body on CPU


def _pad_axis(a: jnp.ndarray, axis: int, mult: int, value=0) -> jnp.ndarray:
    pad = (-a.shape[axis]) % mult
    if not pad:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return jnp.pad(a, widths, constant_values=value)


def _int8_matmul_raw(
    x2: jnp.ndarray, q8: jnp.ndarray, scale: jnp.ndarray,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """[M, K] f32, [K, N] int8, [N] f32 -> [M, N] f32. Pads M/N/K to the
    block grid (zero rows/columns contribute nothing; padded scale
    columns are sliced away with their outputs)."""
    if interpret is None:
        # forced-on non-TPU backends (CPU tests, the forced bench arm)
        # run the same kernel body through the pallas interpreter — the
        # numbers are the kernel's, only the execution engine differs
        interpret = _INTERPRET or jax.default_backend() != "tpu"
    M, K = x2.shape
    N = q8.shape[1]
    xp = _pad_axis(_pad_axis(x2, 0, BM), 1, KP)
    wp = _pad_axis(_pad_axis(q8, 0, KP), 1, BN)
    sp = _pad_axis(scale.reshape(1, -1), 1, BN, value=1.0)
    Mp, Kp = xp.shape
    Np = wp.shape[1]
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((Mp, Np), jnp.float32),
        grid=(Mp // BM, Np // BN),
        in_specs=[
            pl.BlockSpec((BM, Kp), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Kp, BN), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BN), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((BM, BN), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(xp, wp, sp)
    return out[:M, :N]


def int8_vmem_ok(K: int) -> bool:
    """Whether one grid step's working set (x block f32 + w block int8 +
    out block f32 + scale row) fits the VMEM budget for contraction dim
    ``K`` (kept fully resident per step)."""
    Kp = ((K + KP - 1) // KP) * KP
    need = BM * Kp * 4 + Kp * BN * 1 + BM * BN * 4 + BN * 4
    return need <= VMEM_INT8_BUDGET


def int8_matmul(
    x: jnp.ndarray, q8: jnp.ndarray, scale: jnp.ndarray
) -> jnp.ndarray:
    """Weight-only int8 matmul: ``x [..., K]`` (any float dtype) times a
    quantized weight ``q8 [K, N] int8`` with per-channel ``scale [N]``;
    returns f32 ``[..., N]``. Uses the pallas kernel (compiled on TPU,
    interpreted where the probe armed it that way); contraction dims past
    the VMEM budget fall back to the jnp dequant matmul — identical
    numbers, no kernel."""
    lead = x.shape[:-1]
    K = x.shape[-1]
    x2 = x.reshape(-1, K).astype(jnp.float32)
    if not int8_vmem_ok(K):
        return reference_int8_matmul(x2, q8, scale).reshape(*lead, q8.shape[1])
    out = _int8_matmul_raw(x2, q8, scale)
    return out.reshape(*lead, q8.shape[1])


# ------------------------------------------------------------------ probe


# (env value, backend) -> (ok, reason); the env is part of the key so a
# test that flips SRT_PALLAS_INT8 re-probes instead of reading a stale
# verdict (the flash/fused probes cache one bool; this probe's verdict
# is backend- AND force-dependent because of interpret mode)
_PROBE_CACHE: dict = {}


def _numeric_probe(interpret: bool) -> bool:
    """Compile (interpret=False) or interpret (True) + validate the
    kernel against the dequant reference. The flag is EXPLICIT: the
    unforced TPU gate must prove the COMPILED kernel — letting the
    interpret fallback answer for it would pass the probe on hosts
    where the real kernel cannot lower."""
    r = jax.random.split(jax.random.PRNGKey(0), 2)
    w = jax.random.normal(r[0], (96, 160), jnp.float32) * 0.05
    x = jax.random.normal(r[1], (33, 96), jnp.float32)
    q8, scale = quantize_int8(w)
    got = jax.jit(
        lambda x_, q_, s_: _int8_matmul_raw(x_, q_, s_, interpret=interpret)
    )(x, q8, scale)
    want = reference_int8_matmul(x, q8, scale)
    return bool(jnp.allclose(got, want, atol=1e-4, rtol=1e-4))


def int8_probe(backend: Optional[str] = None) -> Tuple[bool, str]:
    """The serving overlay's int8 gate: ``(ok, reason)`` where the
    reason string is exactly what the overlay label carries.

    Policy (mirrors the bf16 auto policy's shape — accelerator-armed,
    CPU off unless forced — and the pallas probes' force knob):

    * ``SRT_PALLAS_INT8=0`` — refused everywhere.
    * ``SRT_PALLAS_INT8=1`` — probe runs anywhere; non-TPU backends run
      the kernel interpret-mode (the forced label says so).
    * unset — TPU only: the compiled kernel is probed and must validate;
      any other backend refuses (the CPU auto-OFF rule, test-enforced
      like bf16's).
    """
    if backend is None:
        backend = jax.default_backend()
    env = os.environ.get("SRT_PALLAS_INT8")
    key = (env, backend)
    if key in _PROBE_CACHE:
        return _PROBE_CACHE[key]
    if env == "0":
        ok, why = False, "SRT_PALLAS_INT8=0 — probe refused"
    elif not _PALLAS_IMPORTED:
        ok, why = False, f"pallas unavailable on {backend} — probe refused"
    elif env != "1" and backend != "tpu":
        ok, why = False, (
            f"int8 overlay OFF on {backend} unless forced "
            "(SRT_PALLAS_INT8=1 runs the interpret-mode kernel) — "
            "probe refused"
        )
    else:
        forced = env == "1"
        interpret = _INTERPRET or (forced and jax.default_backend() != "tpu")
        try:
            numerics_ok = _numeric_probe(interpret)
        except Exception:
            numerics_ok = False
        if not numerics_ok:
            ok, why = False, (
                f"int8 kernel probe failed on {backend} — probe refused"
            )
        elif interpret:
            ok, why = True, (
                "int8 kernel active (pallas interpret-mode, forced) "
                f"on {backend}"
            )
        else:
            ok, why = True, f"int8 kernel active (pallas) on {backend}"
    _PROBE_CACHE[key] = (ok, why)
    return ok, why


def int8_matmul_enabled() -> bool:
    """Convenience view of :func:`int8_probe` on the default backend."""
    return int8_probe()[0]
