"""Fused Adam/RAdam optimizer update: one traversal, probe-gated pallas kernel.

PERF.md Finding 1 (round 5) measured a ~3.3 s O(n_params) per-step floor on
the trf config, 44.6% of it the optimizer's elementwise fusions. The naive
path compiles optax's link-by-link chain (clip -> scale_by_adam -> decay ->
lr) into the step; this module provides the same math as ONE update:

* ``make_fused_transformation``: an optax-compatible transformation whose
  ``update`` computes the whole chain in a single pass per leaf and applies
  the update to the params directly (``applies_updates = True`` — the train
  step then skips its separate ``optax.apply_updates`` traversal). The
  state STRUCTURE is byte-identical to the reference chain's (init
  delegates to it), so checkpoints, ZeRO-1 shardings, and the
  ``fused_update`` knob can be flipped without invalidating resume state.
* a pallas TPU kernel for the per-leaf elementwise update (params, grads,
  mu, nu in; params', mu', nu' out, HBM-aliased via input_output_aliases)
  — probe-gated exactly like the flash-attention kernel: compiled and
  numerically validated against the XLA math at startup, forced with
  SRT_PALLAS_FUSED=1/0, auto-enabled on TPU only. CPU tests run it in
  interpret mode. Its perf claim is only as good as bench records that say
  ``"fused_update": "active (pallas)"``.

Numerical contract: the fused math mirrors the installed optax's exact
expressions (optax 0.2.3: ``scale_by_adam``/``scale_by_radam`` moment and
bias-correction forms, ``clip_by_global_norm``'s ``(g / gnorm) * clip``
select, ``add_decayed_weights``, ``scale_by_schedule``'s pre-increment
count, ``apply_updates``' ``p + u``) so per-leaf results agree with the
reference chain to 1 ulp — asserted by tests/test_fused_update.py.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_IMPORTED = True
except Exception:  # pragma: no cover
    _PALLAS_IMPORTED = False

# kernel block: BR rows x 128 lanes of f32 per grid step (1 MB/operand —
# well under VMEM with 5 inputs + 3 outputs resident)
LANES = 128
BLOCK_ROWS = 2048
# leaves smaller than this skip the pallas path: a kernel launch per tiny
# bias buys nothing (the XLA fallback fuses those fine)
MIN_KERNEL_SIZE = 16 * 1024


class FusedHyper(NamedTuple):
    """Static hyperparameters of one fused update (python floats — they
    specialize the compiled program, exactly like the optax chain)."""

    kind: str  # "adam" | "radam"
    b1: float
    b2: float
    eps: float
    grad_clip: float  # 0 = no clipping link
    l2_grad: float  # classic L2 added to grads BEFORE adam (0 = absent)
    l2_decay: float  # decoupled weight decay AFTER adam (0 = absent)
    radam_threshold: float = 5.0


# ---------------------------------------------------------------- leaf math


def _leaf_math(
    p: jnp.ndarray,
    g: jnp.ndarray,
    m: jnp.ndarray,
    v: jnp.ndarray,
    gnorm: jnp.ndarray,
    bc1: jnp.ndarray,
    bc2: jnp.ndarray,
    step_size: jnp.ndarray,
    ro: jnp.ndarray,
    rect: jnp.ndarray,
    hyper: FusedHyper,
    in_kernel: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One leaf's whole chain: clip -> (classic L2) -> moments -> bias
    correction -> (radam rectification) -> (decoupled decay) -> lr ->
    apply. Shared by the pallas kernel (on block refs) and the XLA
    fallback (on whole leaves) so the two paths cannot drift; the one
    divergence is the clip select form (below), asserted value-equal by
    the kernel probe."""
    if hyper.grad_clip > 0:
        # optax clip_by_global_norm, verbatim: SCALAR-predicate lax.select
        # (jnp.where would broadcast the predicate into a full elementwise
        # mask — a measurable extra pass at 134M params on CPU). Inside
        # the pallas kernel the block-local jnp.where lowers fine and the
        # scalar-pred select may not; values are identical either way.
        if in_kernel:
            g = jnp.where(
                gnorm < hyper.grad_clip, g, (g / gnorm) * hyper.grad_clip
            )
        else:
            g = jax.lax.select(
                gnorm < hyper.grad_clip, g, (g / gnorm) * hyper.grad_clip
            )
    if hyper.l2_grad:
        g = g + hyper.l2_grad * p
    m2 = (1 - hyper.b1) * g + hyper.b1 * m
    v2 = (1 - hyper.b2) * (g**2) + hyper.b2 * v
    mu_hat = m2 / bc1
    nu_hat = v2 / bc2
    if hyper.kind == "radam":
        # optax scale_by_radam: rectified update where ro >= threshold,
        # plain bias-corrected momentum otherwise (rect is NaN for
        # ro < 4 — jnp.where selects it away, mirroring optax)
        u = jnp.where(
            ro >= hyper.radam_threshold,
            rect * mu_hat / (jnp.sqrt(nu_hat) + hyper.eps),
            mu_hat,
        )
    else:
        u = mu_hat / (jnp.sqrt(nu_hat) + hyper.eps)
    if hyper.l2_decay:
        u = u + hyper.l2_decay * p
    u = step_size * u
    return p + u, m2, v2


# ------------------------------------------------------------ pallas kernel


def _update_kernel(scal_ref, p_ref, g_ref, m_ref, v_ref, op_ref, om_ref,
                   ov_ref, *, hyper: FusedHyper):
    # scal [6] SMEM: gnorm, bc1, bc2, step_size, ro, rect
    p2, m2, v2 = _leaf_math(
        p_ref[...],
        g_ref[...],
        m_ref[...],
        v_ref[...],
        scal_ref[0],
        scal_ref[1],
        scal_ref[2],
        scal_ref[3],
        scal_ref[4],
        scal_ref[5],
        hyper,
        in_kernel=True,
    )
    op_ref[...] = p2
    om_ref[...] = m2
    ov_ref[...] = v2


_INTERPRET = False  # tests flip this to run the kernel on CPU


def _kernel_leaf(p, g, m, v, scal, hyper: FusedHyper, interpret=None):
    """Run one leaf through the pallas kernel: ravel, zero-pad to a whole
    number of (BLOCK_ROWS, 128) blocks, grid over row blocks, un-pad."""
    interpret = _INTERPRET if interpret is None else interpret
    n = p.size
    shape = p.shape
    tile = BLOCK_ROWS * LANES
    padded = ((n + tile - 1) // tile) * tile
    rows = padded // LANES

    def prep(x):
        x = jnp.ravel(x)
        if padded != n:
            x = jnp.pad(x, (0, padded - n))
        return x.reshape(rows, LANES)

    kernel = functools.partial(_update_kernel, hyper=hyper)
    bspec = pl.BlockSpec((BLOCK_ROWS, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    sspec = pl.BlockSpec(memory_space=pltpu.SMEM)
    out = jax.ShapeDtypeStruct((rows, LANES), p.dtype)
    p2, m2, v2 = pl.pallas_call(
        kernel,
        out_shape=(out, out, out),
        grid=(rows // BLOCK_ROWS,),
        in_specs=[sspec, bspec, bspec, bspec, bspec],
        out_specs=(bspec, bspec, bspec),
        # alias p/m/v buffers into the outputs: the update is in-place in
        # HBM, the same no-new-allocation contract the donated XLA path has
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=interpret,
    )(scal, prep(p), prep(g), prep(m), prep(v))

    def unprep(x):
        return jnp.ravel(x)[:n].reshape(shape)

    return unprep(p2), unprep(m2), unprep(v2)


# ------------------------------------------------------------------- probe

_PROBED: Optional[bool] = None


def fused_kernel_enabled() -> bool:
    """One-time probe: compile the kernel and validate it against the XLA
    leaf math on the current backend; cache the verdict. SRT_PALLAS_FUSED=1
    forces on (any backend), =0 forces off; default auto-enables on TPU
    only — the same discipline as the flash-attention probe."""
    global _PROBED
    if _PROBED is not None:
        return _PROBED
    env = os.environ.get("SRT_PALLAS_FUSED")
    if env == "0" or not _PALLAS_IMPORTED:
        _PROBED = False
        return False
    if env != "1" and jax.default_backend() != "tpu":
        _PROBED = False
        return False
    try:
        _PROBED = _probe_kernel()
    except Exception:
        _PROBED = False
    return _PROBED


def _probe_kernel(interpret=None) -> bool:
    hyper = FusedHyper(
        kind="adam", b1=0.9, b2=0.999, eps=1e-8, grad_clip=1.0,
        l2_grad=0.0, l2_decay=0.01,
    )
    r = jax.random.split(jax.random.PRNGKey(7), 4)
    n = 4321  # deliberately not a tile multiple: exercises the padding
    p = jax.random.normal(r[0], (n,), jnp.float32)
    g = jax.random.normal(r[1], (n,), jnp.float32) * 0.1
    m = jax.random.normal(r[2], (n,), jnp.float32) * 0.01
    v = jnp.abs(jax.random.normal(r[3], (n,), jnp.float32)) * 0.01
    scal = jnp.asarray([2.3, 0.1, 0.001, -0.001, 6.0, 0.8], jnp.float32)
    got = jax.jit(
        lambda *a: _kernel_leaf(*a, hyper=hyper, interpret=interpret)
    )(p, g, m, v, scal)
    want = _leaf_math(p, g, m, v, *scal, hyper)
    return all(
        bool(jnp.allclose(a, b, atol=1e-6, rtol=1e-6))
        for a, b in zip(got, want)
    )


def fused_status(tx: Any, mesh: Any = None) -> str:
    """Honest-labeling string for bench records: what the optimizer update
    path ACTUALLY is (a CPU fallback must not masquerade as the kernel).

    ``mesh`` is the mesh the update was compiled under: the kernel gate
    (:func:`_single_mesh`) keeps pallas off multi-device meshes, and the
    label must agree with the gate — the record's mesh, not the contextvar
    at record time (unset outside the traced update)."""
    if not getattr(tx, "applies_updates", False):
        return "off (optax chain)"
    multi = mesh is not None and int(mesh.size) > 1
    if _PROBED is True and not multi:
        return "active (pallas)"
    probe = "multi-device mesh" if multi and _PROBED is True else (
        f"kernel probe: {jax.default_backend()}"
    )
    return f"active (xla, {probe})"


# ------------------------------------------------- fused transformation


def stable_global_norm(tree: Any) -> "jnp.ndarray":
    """Global L2 norm with a partitioner-proof computation.

    Under a multi-device mesh the SPMD partitioner is free to split a
    full-tree norm reduction into per-shard partial sums + psum, and it
    makes that choice per-program: the same norm compiles to different
    accumulation orders in the replicated vs full-update-sharding
    programs (and at different mesh shapes), drifting the grad-clip
    scale by an ulp and with it every updated parameter. Here every
    device instead computes the WHOLE reduction locally over its
    replicated copy inside ``shard_map`` (manual mode — GSPMD cannot
    re-partition the body), so the value is identical across
    ``update_sharding`` modes and across mesh shapes. Off-mesh (or on a
    single device) this is exactly ``optax.global_norm``, which keeps
    the fused==optax single-device bitwise tests intact.

    Callers must hand in grads that are logically replicated (the train
    step pins them with a ``with_sharding_constraint`` + barrier before
    the optimizer runs — parallel/step.py).
    """
    from ..parallel import context as pctx

    mesh = pctx.current_mesh()
    if mesh is None or int(mesh.size) == 1:
        return optax.global_norm(tree)
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    leaves = jax.tree_util.tree_leaves(tree)
    fn = shard_map(
        lambda *ls: optax.global_norm(ls),
        mesh=mesh,
        in_specs=tuple(P() for _ in leaves),
        out_specs=P(),
        check_rep=False,
    )
    return fn(*leaves)


def _single_mesh() -> bool:
    """Kernel gate: a pallas_call has no GSPMD partitioning rule, so under
    a multi-device mesh (replicated params / ZeRO-1 sharded moments) the
    update stays on the XLA path, which GSPMD partitions cleanly."""
    from ..parallel import context as pctx

    mesh = pctx.current_mesh()
    return mesh is None or int(mesh.size) == 1


class FusedTransformation:
    """optax-shaped transformation computing the whole chain in one pass.

    ``update(grads, state, params)`` returns ``(new_params, new_state)`` —
    NOT (updates, state): ``applies_updates`` tells the train step the
    ``optax.apply_updates`` traversal is already folded in. ``init`` and
    the state pytree structure delegate to the reference chain, so
    flipping the knob never invalidates checkpointed optimizer state.
    """

    applies_updates = True

    def __init__(
        self,
        reference_tx: optax.GradientTransformation,
        hyper: FusedHyper,
        lr_fn: Callable[[Any], Any],
        adam_idx: int,
        sched_idx: int,
    ):
        self.reference_tx = reference_tx
        self.hyper = hyper
        self.lr_fn = lr_fn
        self.adam_idx = adam_idx
        self.sched_idx = sched_idx

    def init(self, params):
        return self.reference_tx.init(params)

    def update(self, grads, state, params=None):
        if params is None:
            raise ValueError("fused update needs params (applies in place)")
        from optax._src import numerics

        hyper = self.hyper
        adam_state = state[self.adam_idx]
        sched_state = state[self.sched_idx]
        count_inc = numerics.safe_int32_increment(adam_state.count)
        # optax scale_by_schedule reads its count BEFORE incrementing
        step_size = jnp.float32(-1.0) * self.lr_fn(sched_state.count)
        bc1 = 1 - hyper.b1**count_inc
        bc2 = 1 - hyper.b2**count_inc
        # partitioner-proof norm: the clip scale must be the same VALUE in
        # every update-sharding mode and at every mesh shape, or the fused
        # update can never be bit-compared across them (see the function's
        # docstring; single-device this IS optax.global_norm)
        gnorm = (
            stable_global_norm(grads)
            if hyper.grad_clip > 0
            else jnp.float32(0.0)
        )
        if hyper.kind == "radam":
            ro_inf = 2.0 / (1 - hyper.b2) - 1
            b2t = hyper.b2**count_inc
            ro = ro_inf - 2 * count_inc * b2t / (1 - b2t)
            rect = jnp.sqrt(
                (ro - 4)
                * (ro - 2)
                * ro_inf
                / ((ro_inf - 4) * (ro_inf - 2) * ro)
            )
        else:
            ro = jnp.float32(0.0)
            rect = jnp.float32(0.0)

        use_kernel = fused_kernel_enabled() and _single_mesh()
        scal = None
        if use_kernel:
            scal = jnp.stack(
                [
                    jnp.asarray(gnorm, jnp.float32),
                    jnp.asarray(bc1, jnp.float32),
                    jnp.asarray(bc2, jnp.float32),
                    jnp.asarray(step_size, jnp.float32),
                    jnp.asarray(ro, jnp.float32),
                    jnp.asarray(rect, jnp.float32),
                ]
            )

        def leaf(p, g, m, v):
            if (
                use_kernel
                and p.dtype == jnp.float32
                and p.size >= MIN_KERNEL_SIZE
            ):
                return _kernel_leaf(p, g, m, v, scal, hyper)
            return _leaf_math(
                p, g, m, v, gnorm, bc1, bc2, step_size, ro, rect, hyper
            )

        out = jax.tree_util.tree_map(leaf, params, grads, adam_state.mu,
                                     adam_state.nu)
        is_triple = lambda x: isinstance(x, tuple)  # noqa: E731
        new_params = jax.tree_util.tree_map(
            lambda t: t[0], out, is_leaf=is_triple
        )
        new_mu = jax.tree_util.tree_map(lambda t: t[1], out, is_leaf=is_triple)
        new_nu = jax.tree_util.tree_map(lambda t: t[2], out, is_leaf=is_triple)

        from optax._src.transform import ScaleByAdamState, ScaleByScheduleState

        new_state = list(state)
        new_state[self.adam_idx] = ScaleByAdamState(
            count=count_inc, mu=new_mu, nu=new_nu
        )
        new_state[self.sched_idx] = ScaleByScheduleState(
            count=numerics.safe_int32_increment(sched_state.count)
        )
        return new_params, tuple(new_state)


def make_fused_transformation(
    *,
    kind: str,
    lr_fn: Callable[[Any], Any],
    b1: float,
    b2: float,
    eps: float,
    grad_clip: float = 0.0,
    l2_grad: float = 0.0,
    l2_decay: float = 0.0,
    adam_idx: int,
    sched_idx: int,
    reference_tx: optax.GradientTransformation,
) -> FusedTransformation:
    if kind not in ("adam", "radam"):
        raise ValueError(f"unknown fused optimizer kind {kind!r}")
    hyper = FusedHyper(
        kind=kind, b1=float(b1), b2=float(b2), eps=float(eps),
        grad_clip=float(grad_clip or 0.0), l2_grad=float(l2_grad or 0.0),
        l2_decay=float(l2_decay or 0.0),
    )
    return FusedTransformation(
        reference_tx, hyper, lr_fn, int(adam_idx), int(sched_idx)
    )
