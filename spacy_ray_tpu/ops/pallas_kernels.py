"""Pallas TPU kernels for ops XLA fuses poorly (SURVEY.md §7.1: "pallas only
where profiling shows XLA fusion fails (likely: ragged gather for hash
embeds)").

``hash_embed_lookup``: the HashEmbed inner op — gather 4 rows per token from
the embedding table and sum them. The XLA lowering materializes a
[tokens, 4, width] gather intermediate in HBM; this kernel keeps the table
resident in VMEM (typical tables: 2000 x 96 fp32 = 768KB, well under the
~16MB budget), streams id blocks through SMEM (bounded at TOKEN_BLOCK*16B
regardless of batch shape), and accumulates rows in-register.

Differentiation: pallas_call has no automatic VJP, so the kernel carries a
``jax.custom_vjp`` whose backward is the standard scatter-add of the output
cotangent into the table rows (a jnp ``.at[ids].add`` — XLA lowers this
well); the probe validates BOTH forward and gradient numerics before
enabling.

Safety: enabled only by a one-time startup probe (compile + numeric check on
the current backend), silently falling back to the jnp path otherwise.
Force with SRT_PALLAS=1/0.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

TOKEN_BLOCK = 256
VMEM_TABLE_BUDGET = 8 * 1024 * 1024  # bytes of VMEM we allow the table


def _reference_lookup(table: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    """jnp fallback: [rows, D], [N, 4] -> [N, D]."""
    return jnp.sum(jnp.take(table, ids, axis=0), axis=-2)


def _table_grad(ids: jnp.ndarray, ct: jnp.ndarray, rows: int) -> jnp.ndarray:
    """Backward of the gather-sum: scatter-add cotangent into table rows.

    ids [N, 4], ct [N, D] -> [rows, D].
    """
    updates = jnp.broadcast_to(ct[:, None, :], (ct.shape[0], 4, ct.shape[1]))
    zeros = jnp.zeros((rows, ct.shape[1]), ct.dtype)
    return zeros.at[ids].add(updates)


try:  # pallas imports can fail on exotic builds; treat as "unavailable"
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_IMPORTED = True
except Exception:  # pragma: no cover
    _PALLAS_IMPORTED = False


def _kernel(ids_ref, table_ref, out_ref):
    """One grid step: TOKEN_BLOCK tokens; ids block lives in SMEM."""
    import jax.lax as lax

    def body(t, _):
        r0 = ids_ref[t, 0]
        r1 = ids_ref[t, 1]
        r2 = ids_ref[t, 2]
        r3 = ids_ref[t, 3]
        out_ref[t, :] = (
            table_ref[r0, :] + table_ref[r1, :] + table_ref[r2, :] + table_ref[r3, :]
        )
        return 0

    lax.fori_loop(0, TOKEN_BLOCK, body, 0)


def _pallas_lookup_raw(
    table: jnp.ndarray, ids: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """[rows, D] fp32, [N, 4] int32 -> [N, D]. N must be a TOKEN_BLOCK multiple."""
    n = ids.shape[0]
    D = table.shape[1]
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((n, D), table.dtype),
        grid=(n // TOKEN_BLOCK,),
        in_specs=[
            # per-step id block in SMEM: bounded regardless of batch shape
            pl.BlockSpec((TOKEN_BLOCK, 4), lambda i: (i, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),  # whole table resident
        ],
        out_specs=pl.BlockSpec(
            (TOKEN_BLOCK, D), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(ids, table)


@jax.custom_vjp
def _pallas_lookup(table: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    return _pallas_lookup_raw(table, ids)


def _pallas_lookup_fwd(table, ids):
    return _pallas_lookup_raw(table, ids), (ids, table.shape[0])


def _pallas_lookup_bwd(res, ct):
    ids, rows = res
    return _table_grad(ids, ct, rows), None


_pallas_lookup.defvjp(_pallas_lookup_fwd, _pallas_lookup_bwd)


_PROBED: Optional[bool] = None


def pallas_enabled() -> bool:
    """One-time probe: compile + numerically validate forward AND gradient
    on the default backend; cache the verdict."""
    global _PROBED
    if _PROBED is not None:
        return _PROBED
    env = os.environ.get("SRT_PALLAS")
    if env == "0" or not _PALLAS_IMPORTED:
        _PROBED = False
        return False
    if env != "1" and jax.default_backend() != "tpu":
        _PROBED = False  # default: only auto-enable on real TPU
        return False
    try:
        table = jax.random.normal(jax.random.PRNGKey(0), (64, 96), jnp.float32)
        ids = jax.random.randint(
            jax.random.PRNGKey(1), (2 * TOKEN_BLOCK, 4), 0, 64
        ).astype(jnp.int32)
        got = jax.jit(_pallas_lookup)(table, ids)
        want = _reference_lookup(table, ids)
        fwd_ok = bool(jnp.allclose(got, want, atol=1e-5))
        g_got = jax.grad(lambda t: jnp.sum(jnp.sin(_pallas_lookup(t, ids))))(table)
        g_want = jax.grad(lambda t: jnp.sum(jnp.sin(_reference_lookup(t, ids))))(table)
        grad_ok = bool(jnp.allclose(g_got, g_want, atol=1e-4))
        _PROBED = fwd_ok and grad_ok
    except Exception:
        _PROBED = False
    return _PROBED


# HBM budget for the one-hot counts operand ([tokens, rows] elements) —
# beyond it the plain gather's [tokens, 4, D] intermediate is cheaper
ONEHOT_LOOKUP_MAX_BYTES = 64 * 1024 * 1024


def hash_embed_lookup(table: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    """Gather-sum 4 rows per key: table [rows, D], ids [..., 4] -> [..., D].

    Uses the pallas kernel when the startup probe enabled it and the table
    fits the VMEM budget. On TPU without the kernel (probe failed/forced
    off), small tables use a one-hot count-matrix matmul instead of the
    gather (TPU gathers serialize; summing the 4 one-hots gives a count
    row, and counts @ table == the multiplicity-weighted row sum). Plain
    jnp gather otherwise (CPU, big tables).
    """
    lead_shape = ids.shape[:-1]
    if (
        pallas_enabled()
        and table.dtype == jnp.float32
        and table.nbytes <= VMEM_TABLE_BUDGET
    ):
        flat_ids = ids.reshape(-1, 4).astype(jnp.int32)
        n = flat_ids.shape[0]
        pad = (-n) % TOKEN_BLOCK
        if pad:
            flat_ids = jnp.pad(flat_ids, ((0, pad), (0, 0)))
        out = _pallas_lookup(table, flat_ids)
        if pad:
            out = out[:n]
        return out.reshape(*lead_shape, table.shape[1])
    counts_bytes = (ids.size // 4) * table.shape[0] * table.dtype.itemsize
    if (
        jax.default_backend() == "tpu"
        and counts_bytes <= ONEHOT_LOOKUP_MAX_BYTES
    ):
        counts = jnp.sum(
            jax.nn.one_hot(ids.astype(jnp.int32), table.shape[0],
                           dtype=table.dtype),
            axis=-2,
        )  # [..., rows]
        return counts @ table
    return _reference_lookup(table, ids.astype(jnp.int32))
