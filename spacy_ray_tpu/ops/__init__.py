"""JaxOps: XLA/pallas kernel layer (the NumpyOps/CupyOps equivalent, SURVEY.md §2.3)."""

from .ops import (  # noqa: F401
    seq2col,
    maxout,
    layer_norm,
    mish,
    gelu,
    dropout,
    masked_softmax_cross_entropy,
    masked_sigmoid_bce,
    masked_accuracy,
    mean_pool,
    max_pool,
)
from .hashing import (  # noqa: F401
    murmur3_x86_128_u64,
    hash_embed_ids,
    hash_string_u64,
    split_u64,
)
