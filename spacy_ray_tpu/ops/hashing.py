"""Hashing kernels for hash-embedding tables, in pure jnp (TPU-friendly).

Capability parity: the reference's models embed tokens via thinc's
``HashEmbed`` layers, whose row lookup is murmurhash-based feature hashing
supplied by the native murmurhash C dependency (reference setup.cfg:31-33
transitively; SURVEY.md §2.3). Here the same capability is an in-kernel
MurmurHash3 x86_128 implemented with 32-bit integer ops only, so it runs on
the TPU VPU (no 64-bit int support needed) and fuses into the embedding
gather under XLA.

The x86_128 variant is used (not x64_128) because it needs only 32-bit
multiplies and rotates. Keys are 64-bit token ids passed as two uint32 halves.
Each key yields four 32-bit hashes; HashEmbed gathers and sums the four rows
(collision mitigation, same scheme thinc uses).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

_C1 = np.uint32(0x239B961B)
_C2 = np.uint32(0xAB0E9789)
_C3 = np.uint32(0x38B34AE5)
_C4 = np.uint32(0xA1E38B93)


def _rotl32(x, r: int):
    return (x << r) | (x >> (32 - r))


def _fmix32(h):
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def murmur3_x86_128_u64(key_lo, key_hi, seed: int):
    """MurmurHash3 x86_128 of an 8-byte key given as two uint32 words.

    Args:
      key_lo, key_hi: uint32 arrays (low/high 32 bits of the 64-bit key).
      seed: python int seed.
    Returns:
      tuple of four uint32 arrays (h1, h2, h3, h4), same shape as inputs.
    """
    key_lo = key_lo.astype(jnp.uint32)
    key_hi = key_hi.astype(jnp.uint32)
    seed_u = jnp.uint32(seed & 0xFFFFFFFF)
    h1 = h2 = h3 = h4 = jnp.broadcast_to(seed_u, key_lo.shape)

    # tail processing for len=8: k1 = block0 (lo), k2 = block1 (hi), k3=k4=0
    k1 = key_lo * jnp.uint32(_C1)
    k1 = _rotl32(k1, 15)
    k1 = k1 * jnp.uint32(_C2)
    h1 = h1 ^ k1

    k2 = key_hi * jnp.uint32(_C2)
    k2 = _rotl32(k2, 16)
    k2 = k2 * jnp.uint32(_C3)
    h2 = h2 ^ k2

    # finalization, length = 8 bytes
    length = jnp.uint32(8)
    h1 = h1 ^ length
    h2 = h2 ^ length
    h3 = h3 ^ length
    h4 = h4 ^ length

    h1 = h1 + h2 + h3 + h4
    h2 = h2 + h1
    h3 = h3 + h1
    h4 = h4 + h1

    h1 = _fmix32(h1)
    h2 = _fmix32(h2)
    h3 = _fmix32(h3)
    h4 = _fmix32(h4)

    h1 = h1 + h2 + h3 + h4
    h2 = h2 + h1
    h3 = h3 + h1
    h4 = h4 + h1
    return h1, h2, h3, h4


def hash_embed_ids(keys_u64_2x32, seed: int, n_rows: int):
    """Map 64-bit keys to 4 row indices each, for HashEmbed gather-sum.

    Args:
      keys_u64_2x32: uint32 array of shape [..., 2] — (lo, hi) halves.
      seed: table seed.
      n_rows: number of rows in the embedding table.
    Returns:
      uint32 array of shape [..., 4] of row indices in [0, n_rows).
    """
    lo = keys_u64_2x32[..., 0]
    hi = keys_u64_2x32[..., 1]
    h1, h2, h3, h4 = murmur3_x86_128_u64(lo, hi, seed)
    ids = jnp.stack([h1, h2, h3, h4], axis=-1)
    return (ids % jnp.uint32(n_rows)).astype(jnp.int32)


# ----------------------------------------------------------------------
# Host-side reference implementation (numpy) — the oracle for tests, and
# the string->u64 key hash used by the Vocab when the C++ extension is
# unavailable.
# ----------------------------------------------------------------------


def murmur3_x86_128_u64_np(key_lo: np.ndarray, key_hi: np.ndarray, seed: int):
    with np.errstate(over="ignore"):
        key_lo = key_lo.astype(np.uint32)
        key_hi = key_hi.astype(np.uint32)

        def rotl(x, r):
            return ((x << np.uint32(r)) | (x >> np.uint32(32 - r))).astype(np.uint32)

        def fmix(h):
            h = h ^ (h >> np.uint32(16))
            h = (h * np.uint32(0x85EBCA6B)).astype(np.uint32)
            h = h ^ (h >> np.uint32(13))
            h = (h * np.uint32(0xC2B2AE35)).astype(np.uint32)
            h = h ^ (h >> np.uint32(16))
            return h

        seed_u = np.uint32(seed & 0xFFFFFFFF)
        h1 = np.full(key_lo.shape, seed_u, dtype=np.uint32)
        h2 = h1.copy()
        h3 = h1.copy()
        h4 = h1.copy()

        k1 = (key_lo * _C1).astype(np.uint32)
        k1 = rotl(k1, 15)
        k1 = (k1 * _C2).astype(np.uint32)
        h1 = h1 ^ k1

        k2 = (key_hi * _C2).astype(np.uint32)
        k2 = rotl(k2, 16)
        k2 = (k2 * _C3).astype(np.uint32)
        h2 = h2 ^ k2

        length = np.uint32(8)
        h1 ^= length
        h2 ^= length
        h3 ^= length
        h4 ^= length
        h1 = (h1 + h2 + h3 + h4).astype(np.uint32)
        h2 = (h2 + h1).astype(np.uint32)
        h3 = (h3 + h1).astype(np.uint32)
        h4 = (h4 + h1).astype(np.uint32)
        h1 = fmix(h1)
        h2 = fmix(h2)
        h3 = fmix(h3)
        h4 = fmix(h4)
        h1 = (h1 + h2 + h3 + h4).astype(np.uint32)
        h2 = (h2 + h1).astype(np.uint32)
        h3 = (h3 + h1).astype(np.uint32)
        h4 = (h4 + h1).astype(np.uint32)
        return h1, h2, h3, h4


def hash_string_u64(s: str, seed: int = 0) -> int:
    """Stable 64-bit hash of a string (host side), for Vocab key assignment.

    Pure-python MurmurHash3 x86_128 over the utf-8 bytes, truncated to 64
    bits. Replaced by the C++ extension when available (see native/).
    Stable across processes — fixes the fragile per-process ``(node_id,
    name)`` key identity the reference relies on (reference util.py:6,53-54;
    SURVEY.md §2.4).
    """
    data = s.encode("utf8")
    h = _murmur3_x86_128_bytes(data, seed)
    return h & 0xFFFFFFFFFFFFFFFF


def _murmur3_x86_128_bytes(data: bytes, seed: int) -> int:
    def rotl(x, r):
        return ((x << r) | (x >> (32 - r))) & 0xFFFFFFFF

    def fmix(h):
        h ^= h >> 16
        h = (h * 0x85EBCA6B) & 0xFFFFFFFF
        h ^= h >> 13
        h = (h * 0xC2B2AE35) & 0xFFFFFFFF
        h ^= h >> 16
        return h

    c1, c2, c3, c4 = 0x239B961B, 0xAB0E9789, 0x38B34AE5, 0xA1E38B93
    h1 = h2 = h3 = h4 = seed & 0xFFFFFFFF
    length = len(data)
    nblocks = length // 16
    for i in range(nblocks):
        block = data[i * 16 : (i + 1) * 16]
        k1 = int.from_bytes(block[0:4], "little")
        k2 = int.from_bytes(block[4:8], "little")
        k3 = int.from_bytes(block[8:12], "little")
        k4 = int.from_bytes(block[12:16], "little")
        k1 = rotl((k1 * c1) & 0xFFFFFFFF, 15)
        k1 = (k1 * c2) & 0xFFFFFFFF
        h1 ^= k1
        h1 = rotl(h1, 19)
        h1 = (h1 + h2) & 0xFFFFFFFF
        h1 = (h1 * 5 + 0x561CCD1B) & 0xFFFFFFFF
        k2 = rotl((k2 * c2) & 0xFFFFFFFF, 16)
        k2 = (k2 * c3) & 0xFFFFFFFF
        h2 ^= k2
        h2 = rotl(h2, 17)
        h2 = (h2 + h3) & 0xFFFFFFFF
        h2 = (h2 * 5 + 0x0BCAA747) & 0xFFFFFFFF
        k3 = rotl((k3 * c3) & 0xFFFFFFFF, 17)
        k3 = (k3 * c4) & 0xFFFFFFFF
        h3 ^= k3
        h3 = rotl(h3, 15)
        h3 = (h3 + h4) & 0xFFFFFFFF
        h3 = (h3 * 5 + 0x96CD1C35) & 0xFFFFFFFF
        k4 = rotl((k4 * c4) & 0xFFFFFFFF, 18)
        k4 = (k4 * c1) & 0xFFFFFFFF
        h4 ^= k4
        h4 = rotl(h4, 13)
        h4 = (h4 + h1) & 0xFFFFFFFF
        h4 = (h4 * 5 + 0x32AC3B17) & 0xFFFFFFFF

    tail = data[nblocks * 16 :]
    k1 = k2 = k3 = k4 = 0
    t = len(tail)
    if t >= 13:
        k4 = int.from_bytes(tail[12:t].ljust(4, b"\0"), "little")
    if t >= 9:
        k3 = int.from_bytes(tail[8:min(t, 12)].ljust(4, b"\0"), "little")
    if t >= 5:
        k2 = int.from_bytes(tail[4:min(t, 8)].ljust(4, b"\0"), "little")
    if t >= 1:
        k1 = int.from_bytes(tail[0:min(t, 4)].ljust(4, b"\0"), "little")
    if k4:
        k4 = rotl((k4 * c4) & 0xFFFFFFFF, 18)
        k4 = (k4 * c1) & 0xFFFFFFFF
        h4 ^= k4
    if k3:
        k3 = rotl((k3 * c3) & 0xFFFFFFFF, 17)
        k3 = (k3 * c4) & 0xFFFFFFFF
        h3 ^= k3
    if k2:
        k2 = rotl((k2 * c2) & 0xFFFFFFFF, 16)
        k2 = (k2 * c3) & 0xFFFFFFFF
        h2 ^= k2
    if k1:
        k1 = rotl((k1 * c1) & 0xFFFFFFFF, 15)
        k1 = (k1 * c2) & 0xFFFFFFFF
        h1 ^= k1

    h1 ^= length
    h2 ^= length
    h3 ^= length
    h4 ^= length
    h1 = (h1 + h2 + h3 + h4) & 0xFFFFFFFF
    h2 = (h2 + h1) & 0xFFFFFFFF
    h3 = (h3 + h1) & 0xFFFFFFFF
    h4 = (h4 + h1) & 0xFFFFFFFF
    h1 = fmix(h1)
    h2 = fmix(h2)
    h3 = fmix(h3)
    h4 = fmix(h4)
    h1 = (h1 + h2 + h3 + h4) & 0xFFFFFFFF
    h2 = (h2 + h1) & 0xFFFFFFFF
    return (h2 << 32) | h1


def split_u64(keys: np.ndarray) -> np.ndarray:
    """uint64 array -> [..., 2] uint32 (lo, hi) for device-side hashing."""
    keys = keys.astype(np.uint64)
    lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    return np.stack([lo, hi], axis=-1)
