"""Pallas TPU flash attention for the transformer trunk.

The trunk's single-chip attention path was ``jax.nn.dot_product_attention``
(models/transformer.py), whose XLA lowering materializes the [B, H, T, T]
score tensor in HBM. This kernel computes exact attention without ever
writing scores to HBM: per (batch, head, query-block) grid step it keeps the
whole K/V for that head resident in VMEM, forms a [BQ, T] score block
in-register, softmaxes, and contracts straight into the output block — the
standard flash-attention memory shape (O(T) HBM traffic instead of O(T²)),
sized for encoder sequence lengths (VMEM budget checked host-side, jnp
fallback beyond it).

Backward is a second pallas kernel via ``jax.custom_vjp`` (pallas_call has
no automatic VJP): it recomputes the probability block from the saved
logsumexp and accumulates dK/dV across query-block grid steps (TPU grids
execute sequentially, so revisiting an output block is the idiomatic
accumulation pattern).

Like the hash-embed kernel (ops/pallas_kernels.py), a one-time startup
probe compiles and numerically validates forward AND gradients on the
current backend before enabling; force with SRT_PALLAS_ATTN=1/0. The
capability matched is the reference ecosystem's fused attention (torch SDPA
inside its transformer dependency); the implementation is TPU-first.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

BQ = 128  # query block (MXU-aligned)
NEG = -1e30
# VMEM budget for one (b, h) slice of K + V + score block before fallback
VMEM_ATTN_BUDGET = 10 * 1024 * 1024

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_IMPORTED = True
except Exception:  # pragma: no cover
    _PALLAS_IMPORTED = False


def reference_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """Dense reference: q/k/v [B, T, H, Dh], mask [B, T] bool -> [B, T, H, Dh]."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    scores = jnp.where(mask[:, None, None, :], scores, NEG)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)


# ---------------------------------------------------------------- kernels


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, *, scale):
    # q [1,1,BQ,DP]  k/v [1,1,T,DP]  bias [1,T]  -> o [1,1,BQ,DP], lse [1,1,BQ]
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [BQ, T]
    s = s * scale + bias_ref[0][None, :]
    m = jnp.max(s, axis=-1)  # [BQ]
    p = jnp.exp(s - m[:, None])
    l = jnp.sum(p, axis=-1)  # [BQ]
    o = jnp.dot(
        p.astype(v.dtype), v, preferred_element_type=jnp.float32
    ) / l[:, None]
    o_ref[0, 0] = o.astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l)


def _bwd_kernel(
    q_ref, k_ref, v_ref, bias_ref, do_ref, o_ref, lse_ref, dlse_ref,
    dq_ref, dk_ref, dv_ref, *, scale,
):
    # grid (B, H, nq); dk/dv blocks are revisited across the q-block axis
    @pl.when(pl.program_id(2) == 0)
    def _init():
        dk_ref[0, 0] = jnp.zeros_like(dk_ref[0, 0])
        dv_ref[0, 0] = jnp.zeros_like(dv_ref[0, 0])

    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0].astype(jnp.float32)
    o = o_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]  # [BQ]
    dlse = dlse_ref[0, 0]  # [BQ] cotangent of the logsumexp output

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    s = s * scale + bias_ref[0][None, :]
    p = jnp.exp(s - lse[:, None])  # [BQ, T] softmax probs (recomputed)

    delta = jnp.sum(do * o, axis=-1)  # [BQ]
    dp = jax.lax.dot_general(
        do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [BQ, T]
    # d(lse)/d(s_j) = p_j, so the lse cotangent folds straight into ds —
    # this is what lets the ring-attention block merge differentiate
    # through each block's logsumexp
    ds = p * (dp - delta[:, None] + dlse[:, None]) * scale  # [BQ, T] fp32
    ds16 = ds.astype(q.dtype)

    dq_ref[0, 0] = jnp.dot(
        ds16, k, preferred_element_type=jnp.float32
    ).astype(dq_ref.dtype)
    dk_ref[0, 0] += jax.lax.dot_general(
        ds16, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(dk_ref.dtype)
    dv_ref[0, 0] += jax.lax.dot_general(
        p.astype(do_ref.dtype), do.astype(do_ref.dtype),
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    ).astype(dv_ref.dtype)


# ------------------------------------------------------- pallas_call wrappers


_INTERPRET = False  # tests flip this to run the kernels on CPU


def _fwd_raw(q, k, v, bias, *, scale, interpret=None):
    # q/k/v [B, H, T, DP], bias [B, T]; T % BQ == 0, DP % 128 == 0
    interpret = _INTERPRET if interpret is None else interpret
    B, H, T, DP = q.shape
    nq = T // BQ
    kernel = functools.partial(_fwd_kernel, scale=scale)
    qspec = pl.BlockSpec((1, 1, BQ, DP), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM)
    kvspec = pl.BlockSpec((1, 1, T, DP), lambda b, h, i: (b, h, 0, 0),
                          memory_space=pltpu.VMEM)
    bspec = pl.BlockSpec((1, T), lambda b, h, i: (b, 0),
                         memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, H, T, DP), q.dtype),
            jax.ShapeDtypeStruct((B, H, T), jnp.float32),
        ),
        grid=(B, H, nq),
        in_specs=[qspec, kvspec, kvspec, bspec],
        out_specs=(
            qspec,
            pl.BlockSpec((1, 1, BQ), lambda b, h, i: (b, h, i),
                         memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(q, k, v, bias)


def _bwd_raw(q, k, v, bias, do, o, lse, dlse, *, scale, interpret=None):
    interpret = _INTERPRET if interpret is None else interpret
    B, H, T, DP = q.shape
    nq = T // BQ
    kernel = functools.partial(_bwd_kernel, scale=scale)
    qspec = pl.BlockSpec((1, 1, BQ, DP), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM)
    kvspec = pl.BlockSpec((1, 1, T, DP), lambda b, h, i: (b, h, 0, 0),
                          memory_space=pltpu.VMEM)
    bspec = pl.BlockSpec((1, T), lambda b, h, i: (b, 0),
                         memory_space=pltpu.VMEM)
    lspec = pl.BlockSpec((1, 1, BQ), lambda b, h, i: (b, h, i),
                         memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, H, T, DP), q.dtype),   # dq
            jax.ShapeDtypeStruct((B, H, T, DP), jnp.float32),  # dk (accum)
            jax.ShapeDtypeStruct((B, H, T, DP), jnp.float32),  # dv (accum)
        ),
        grid=(B, H, nq),
        in_specs=[qspec, kvspec, kvspec, bspec, qspec, qspec, lspec, lspec],
        out_specs=(qspec, kvspec, kvspec),
        interpret=interpret,
    )(q, k, v, bias, do, o, lse, dlse)


def _make_flash(scale: float):
    """Differentiable flash attention for one (static) softmax scale — the
    scale must come from the REAL head dim, not the zero-padded kernel DP,
    so the host wrapper passes it down explicitly. Output-only view of
    :func:`_make_flash_lse`; JAX supplies a zero cotangent for the dropped
    lse output, which the shared backward folds in at no cost."""
    fl = _make_flash_lse(scale)
    return lambda q, k, v, bias: fl(q, k, v, bias)[0]


@functools.lru_cache(maxsize=None)
def _make_flash_lse(scale: float):
    """Like :func:`_make_flash` but also RETURNS the per-query logsumexp, with
    a VJP that accepts its cotangent — the building block for ring attention,
    whose online merge of per-ring-block partial results is a differentiable
    function of each block's (output, logsumexp) pair."""

    @jax.custom_vjp
    def fl(q, k, v, bias):
        return _fwd_raw(q, k, v, bias, scale=scale)

    def fl_fwd(q, k, v, bias):
        o, lse = _fwd_raw(q, k, v, bias, scale=scale)
        return (o, lse), (q, k, v, bias, o, lse)

    def fl_bwd(res, cts):
        q, k, v, bias, o, lse = res
        do, dlse = cts
        dq, dk, dv = _bwd_raw(q, k, v, bias, do, o, lse, dlse, scale=scale)
        return dq, dk.astype(k.dtype), dv.astype(v.dtype), None

    fl.defvjp(fl_fwd, fl_bwd)
    return fl


# ------------------------------------------------------------- host wrapper


def _pad_to(x: jnp.ndarray, axis: int, mult: int, value=0.0) -> jnp.ndarray:
    pad = (-x.shape[axis]) % mult
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _dp(head_dim: int) -> int:
    """Kernel head dim: the real head dim zero-padded up to a lane multiple."""
    return max(((head_dim + 127) // 128) * 128, 128)


def _to_kernel_layout(x: jnp.ndarray) -> jnp.ndarray:
    """[B, T, H, Dh] trunk layout -> [B, H, Tp, DP] kernel layout; zero
    head-dim padding leaves scores and output columns exact."""
    return _pad_to(_pad_to(x.transpose(0, 2, 1, 3), 3, _dp(x.shape[-1])), 2, BQ)


def _mask_to_bias(mask: jnp.ndarray) -> jnp.ndarray:
    """[B, T] bool key-padding mask -> [B, Tp] additive fp32 bias."""
    bias = jnp.where(mask, 0.0, NEG).astype(jnp.float32)
    return _pad_to(bias, 1, BQ, value=NEG)


def flash_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """Exact masked attention, pallas-fused. q/k/v [B, T, H, Dh] (the trunk's
    layout), mask [B, T] bool (key padding). Returns [B, T, H, Dh] in q.dtype.
    """
    B, T, H, Dh = q.shape
    o = _make_flash(1.0 / (Dh ** 0.5))(
        _to_kernel_layout(q), _to_kernel_layout(k), _to_kernel_layout(v),
        _mask_to_bias(mask),
    )
    return o[:, :, :T, :Dh].transpose(0, 2, 1, 3)


def attention_vmem_ok(T: int, DP: int, dtype_bytes: int = 2) -> bool:
    """Whether one (b, h) slice (K + V + fp32 score block) fits the budget."""
    Tp = ((T + BQ - 1) // BQ) * BQ
    kv = 2 * Tp * DP * dtype_bytes
    scores = BQ * Tp * 4
    return kv + scores + 2 * BQ * DP * 4 <= VMEM_ATTN_BUDGET


_PROBED: Optional[bool] = None


def flash_attention_enabled() -> bool:
    """One-time probe: compile + validate forward AND gradients vs the dense
    reference on the current backend; cache the verdict. SRT_PALLAS_ATTN=1
    forces on (any backend), =0 forces off; default auto-enables on TPU only.
    """
    global _PROBED
    if _PROBED is not None:
        return _PROBED
    env = os.environ.get("SRT_PALLAS_ATTN")
    if env == "0" or not _PALLAS_IMPORTED:
        _PROBED = False
        return False
    if env != "1" and jax.default_backend() != "tpu":
        _PROBED = False
        return False
    try:
        r = jax.random.split(jax.random.PRNGKey(0), 4)
        B, T, H, Dh = 2, 192, 2, 64
        q = jax.random.normal(r[0], (B, T, H, Dh), jnp.bfloat16)
        k = jax.random.normal(r[1], (B, T, H, Dh), jnp.bfloat16)
        v = jax.random.normal(r[2], (B, T, H, Dh), jnp.bfloat16)
        mask = jnp.arange(T)[None, :] < jnp.array([T, T - 57])[:, None]

        got = jax.jit(flash_attention)(q, k, v, mask)
        want = reference_attention(q, k, v, mask)
        m = mask[:, :, None, None]
        fwd_ok = bool(
            jnp.allclose(
                jnp.where(m, got.astype(jnp.float32), 0),
                jnp.where(m, want.astype(jnp.float32), 0),
                atol=2e-2,
            )
        )

        def loss(fn, q, k, v):
            out = fn(q, k, v, mask).astype(jnp.float32)
            return jnp.sum(jnp.where(m, out, 0.0) ** 2)

        g_got = jax.grad(functools.partial(loss, flash_attention), (0, 1, 2))(q, k, v)
        g_want = jax.grad(functools.partial(loss, reference_attention), (0, 1, 2))(q, k, v)
        grad_ok = all(
            bool(jnp.allclose(a.astype(jnp.float32), b.astype(jnp.float32),
                              atol=5e-2, rtol=5e-2))
            for a, b in zip(g_got, g_want)
        )
        _PROBED = fwd_ok and grad_ok
    except Exception:
        _PROBED = False
    return _PROBED


def _sharded_flash_attention(q, k, v, mask, mesh):
    """Run the pallas kernel per device shard via partial-manual shard_map.

    A pallas_call has no GSPMD partitioning rule, so under an automatically-
    partitioned jit it would force replication of the global q/k/v. But
    attention is INDEPENDENT per (batch row, head): manual over the data
    and model axes, each device runs the kernel on its own [B/d, T, H/m, Dh]
    shard with zero communication — exact. Returns None when the layout
    doesn't divide (caller falls back to XLA attention)."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.smap import CHECK_KW, PARTIAL_MANUAL, shard_map

    if not PARTIAL_MANUAL:
        return None
    B, T, H, _ = q.shape
    axes = [a for a in ("data", "model") if int(mesh.shape.get(a, 1)) > 1]
    if not axes:
        return None
    d = int(mesh.shape.get("data", 1))
    m = int(mesh.shape.get("model", 1))
    if B % d or H % m:
        return None
    data_ax = "data" if d > 1 else None
    model_ax = "model" if m > 1 else None
    qkv_spec = P(data_ax, None, model_ax, None)
    mask_spec = P(data_ax, None)
    sm_mesh = mesh
    try:  # inside another partial-manual region, use the ambient mesh
        from jax.sharding import get_abstract_mesh

        am = get_abstract_mesh()
        if am is not None and all(a in (am.shape or {}) for a in axes):
            sm_mesh = am
    except Exception:  # pragma: no cover - API drift
        pass

    fn = functools.partial(
        shard_map,
        mesh=sm_mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        axis_names=frozenset(axes),
        **{CHECK_KW: False},
    )(flash_attention)
    return fn(q, k, v, mask)


def attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """Attention entry point for the trunk: pallas flash kernel when the
    probe enabled it and the shape fits VMEM, else XLA's fused
    ``jax.nn.dot_product_attention``. Under a multi-device mesh the kernel
    runs per-shard inside a partial-manual shard_map over the data/model
    axes (_sharded_flash_attention); layouts that don't divide fall back
    to XLA attention, which partitions cleanly."""
    from ..parallel import context as pctx

    mesh = pctx.current_mesh()
    if flash_attention_enabled() and attention_vmem_ok(q.shape[1], _dp(q.shape[-1])):
        if mesh is None or mesh.size == 1:
            return flash_attention(q, k, v, mask)
        out = _sharded_flash_attention(q, k, v, mask, mesh)
        if out is not None:
            return out
    return jax.nn.dot_product_attention(q, k, v, mask=mask[:, None, None, :])
