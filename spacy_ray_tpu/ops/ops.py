"""JaxOps: the XLA-compiled NLP kernel set.

Capability parity with the native ops stack the reference's models run on —
thinc's ``NumpyOps`` (Cython) / ``CupyOps`` (CUDA) selected at reference
worker.py:17,97-99,254-262 (SURVEY.md §2.3). Instead of per-op handwritten
kernels, every op here is a pure jnp function designed so XLA fuses it into
the surrounding matmuls on the MXU:

* ``seq2col`` — window concatenation for CNN encoders, expressed as pad+shift
  so it lowers to cheap slices rather than gathers;
* ``maxout`` — piecewise-linear activation with the pieces dimension laid out
  innermost for a single large MXU matmul;
* masked reductions / losses over padded [B, T] batches (static shapes — no
  ragged arrays inside jit).

All functions operate on padded dense batches with explicit boolean masks.
Dtype policy: params float32, activations cast to ``compute_dtype``
(bfloat16 by default on TPU) at matmul boundaries.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def seq2col(X: jnp.ndarray, window: int, mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Concatenate each position's window of neighbors.

    Args:
      X: [B, T, D] (or [T, D]).
      window: half-window size nW; output feature dim = (2*nW+1)*D.
      mask: optional [B, T] validity mask; out-of-window / padded neighbors
        contribute zeros (matching zero-padding semantics at sequence edges).
    Returns:
      [B, T, (2*nW+1)*D]
    """
    squeeze = X.ndim == 2
    if squeeze:
        X = X[None]
        mask = mask[None] if mask is not None else None
    B, T, D = X.shape
    if mask is not None:
        X = X * mask[..., None].astype(X.dtype)
    pieces = []
    for offset in range(-window, window + 1):
        if offset < 0:
            piece = jnp.pad(X[:, : T + offset], ((0, 0), (-offset, 0), (0, 0)))
        elif offset > 0:
            piece = jnp.pad(X[:, offset:], ((0, 0), (0, offset), (0, 0)))
        else:
            piece = X
        pieces.append(piece)
    out = jnp.concatenate(pieces, axis=-1)
    if squeeze:
        out = out[0]
    return out


def maxout(X: jnp.ndarray, W: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Maxout layer: max over P affine pieces.

    Args:
      X: [..., nI]
      W: [nI, nO * nP] — pieces innermost so the matmul is one MXU call.
      b: [nO, nP]
    Returns:
      [..., nO]
    """
    nO, nP = b.shape
    h = jnp.einsum("...i,io->...o", X, W)
    h = h.reshape(h.shape[:-1] + (nO, nP)) + b
    return jnp.max(h, axis=-1)


def layer_norm(X: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    mu = jnp.mean(X, axis=-1, keepdims=True)
    var = jnp.var(X, axis=-1, keepdims=True)
    out = (X - mu) * jax.lax.rsqrt(var + eps)
    return out * scale + bias


def mish(X: jnp.ndarray) -> jnp.ndarray:
    return X * jnp.tanh(jax.nn.softplus(X))


def gelu(X: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.gelu(X, approximate=True)


def dropout(rng: jax.Array, X: jnp.ndarray, rate: float, train: bool) -> jnp.ndarray:
    if not train or rate <= 0.0:
        return X
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, X.shape)
    return jnp.where(mask, X / keep, 0.0)


# ----------------------------------------------------------------------
# Masked losses / metrics over padded batches
# ----------------------------------------------------------------------


def masked_softmax_cross_entropy(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    mask: jnp.ndarray,
    label_smoothing: float = 0.0,
) -> jnp.ndarray:
    """Mean CE over valid positions. logits [B,T,C], labels [B,T] int, mask [B,T]."""
    logits = logits.astype(jnp.float32)
    n_classes = logits.shape[-1]
    logp = jax.nn.log_softmax(logits, axis=-1)
    onehot = jax.nn.one_hot(labels, n_classes, dtype=jnp.float32)
    if label_smoothing > 0.0:
        onehot = onehot * (1.0 - label_smoothing) + label_smoothing / n_classes
    ce = -jnp.sum(onehot * logp, axis=-1)
    mask_f = mask.astype(jnp.float32)
    total = jnp.sum(ce * mask_f)
    denom = jnp.maximum(jnp.sum(mask_f), 1.0)
    return total / denom


def masked_sigmoid_bce(
    logits: jnp.ndarray, labels: jnp.ndarray, mask: Optional[jnp.ndarray] = None
) -> jnp.ndarray:
    """Mean binary CE; logits/labels [..., C]; mask broadcastable over leading dims."""
    logits = logits.astype(jnp.float32)
    labels = labels.astype(jnp.float32)
    per = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    if mask is not None:
        mask_f = mask.astype(jnp.float32)
        while mask_f.ndim < per.ndim:
            mask_f = mask_f[..., None]
        total = jnp.sum(per * mask_f)
        denom = jnp.maximum(jnp.sum(mask_f) * per.shape[-1] / max(mask_f.shape[-1], 1), 1.0)
        return total / denom
    return jnp.mean(per)


def masked_accuracy(logits: jnp.ndarray, labels: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    pred = jnp.argmax(logits, axis=-1)
    correct = (pred == labels).astype(jnp.float32) * mask.astype(jnp.float32)
    return jnp.sum(correct) / jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)


def mean_pool(X: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """[B, T, D], [B, T] -> [B, D] mean over valid positions."""
    mask_f = mask.astype(X.dtype)[..., None]
    total = jnp.sum(X * mask_f, axis=1)
    denom = jnp.maximum(jnp.sum(mask_f, axis=1), 1.0)
    return total / denom


def max_pool(X: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    neg = jnp.finfo(X.dtype).min
    masked = jnp.where(mask[..., None], X, neg)
    out = jnp.max(masked, axis=1)
    # all-padding rows -> 0
    any_valid = jnp.any(mask, axis=1)[..., None]
    return jnp.where(any_valid, out, 0.0)
