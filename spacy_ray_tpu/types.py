"""Shared array-container types crossing the host/device boundary.

All containers are pytrees (chex dataclasses) of statically-shaped padded
arrays — the jit-friendly replacement for the ragged ``List[Doc]`` batches
that flow through the reference's training loop (reference worker.py:170-189
via spacy's ``create_train_batches``).
"""

from __future__ import annotations

from typing import Optional

import chex
import jax.numpy as jnp


@chex.dataclass
class Padded:
    """A padded batch of token vectors: X [B, T, D], mask [B, T] bool."""

    X: jnp.ndarray
    mask: jnp.ndarray

    @property
    def width(self) -> int:
        return self.X.shape[-1]


@chex.dataclass
class TokenBatch:
    """Device-side featurized token batch.

    attr_keys: [B, T, n_attrs, 2] uint32 — 64-bit lexical-attribute hash keys
      (NORM/PREFIX/SUFFIX/SHAPE...) split into (lo, hi) uint32 halves, hashed
      host-side by the Vocab (see pipeline/vocab.py), re-hashed on device per
      embedding table (ops/hashing.py).
    mask: [B, T] bool — True on real tokens.
    """

    attr_keys: jnp.ndarray
    mask: jnp.ndarray
    #: [B, T] int32 static-vector rows (-1 = OOV); None when the pipeline
    #: has no vectors asset loaded
    vector_rows: Optional[jnp.ndarray] = None

    @property
    def batch_size(self) -> int:
        return self.attr_keys.shape[0]

    @property
    def seq_len(self) -> int:
        return self.attr_keys.shape[1]
