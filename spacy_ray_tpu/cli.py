"""CLI: ``python -m spacy_ray_tpu train config.cfg [overrides]``.

Capability parity with the reference CLI (reference train_cli.py:23-53:
``spacy ray train <config> --n-workers --address --gpu-id --code --output
--verbose`` + dotted config overrides). Mapping:

* ``--n-workers N`` -> mesh data-axis size (actor count at reference
  train_cli.py:72-82);
* ``--address`` -> ``--coordinator`` (jax.distributed coordinator address;
  Ray cluster address at train_cli.py:28);
* ``--gpu-id`` -> ``--device`` (tpu/cpu; reference train_cli.py:29 + GPU
  setup at :43);
* ``--code`` -> same semantics: imported before config resolution in every
  process (reference train_cli.py:30, worker.py:87);
* ``--output`` -> WIRED to best/last checkpoints (the reference accepts and
  drops it, TODO at train_cli.py:41 — SURVEY.md §2.4);
* ``--verbose`` -> log level (train_cli.py:42).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional

logger = logging.getLogger("spacy_ray_tpu")


def _setup_device(device: str) -> None:
    """Select the compute platform (the reference's setup_gpu/--gpu-id path,
    train_cli.py:29,43).

    Uses jax.config.update, not env vars: images whose sitecustomize imports
    jax at interpreter boot have already locked in the env-var value by the
    time the CLI runs.
    """
    if device == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    elif device == "gpu":
        # reference --gpu-id surface (train_cli.py:29): pin the platform so
        # a CUDA-capable jax install fails loudly if no GPU is present
        # instead of silently training on CPU; device *selection* within
        # the platform stays with JAX (CUDA_VISIBLE_DEVICES for pinning)
        import jax

        prev = jax.config.jax_platforms
        jax.config.update("jax_platforms", "cuda")
        try:
            # init now: a missing backend raises opaquely later. In a
            # process whose backends are ALREADY initialized, jax returns
            # the cached platform instead of raising — check what we got.
            devs = jax.devices()
            if not devs or devs[0].platform not in ("gpu", "cuda"):
                raise RuntimeError(
                    f"got {devs[0].platform if devs else 'no'} devices"
                )
        except Exception as e:
            # restore: the CLI exits anyway, but an embedding process (or
            # the test suite) must not be left pinned to a dead platform
            jax.config.update("jax_platforms", prev)
            raise SystemExit(
                "--device gpu: no usable CUDA backend in this jax install "
                f"({type(e).__name__}: {e})"
            )
    # tpu: default jax platform selection


def _init_distributed(coordinator: Optional[str], num_processes: Optional[int], process_id: Optional[int]) -> None:
    """Multi-host init (the reference's ray.init(address=...) equivalent,
    train_cli.py:66-71): jax.distributed over ICI/DCN (SURVEY.md §5.8)."""
    if coordinator:
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )


# grace period before a relayed/probe shutdown escalates SIGTERM → SIGKILL
SHUTDOWN_GRACE_S = 10.0


def _supervise_train(argv: List[str], max_restarts: int) -> int:
    """``train --max-restarts N``: run training as a child process and
    relaunch it on nonzero exit (crash, watchdog kill, injected fault),
    resuming from the last intact checkpoint generation. Signals to the
    supervisor relay to the child with SIGTERM → SIGKILL escalation after
    a grace period — the same helper the relay probe uses."""
    from .training.resilience import Supervisor

    child_args = _strip_flags(argv, ["--max-restarts"])

    def build_cmd(attempt: int) -> List[str]:
        cmd = [sys.executable, "-m", "spacy_ray_tpu", "train"] + child_args
        if attempt > 0 and "--resume" not in cmd:
            cmd.append("--resume")  # recover from the last intact checkpoint
        return cmd

    return Supervisor(build_cmd, max_restarts, grace_s=SHUTDOWN_GRACE_S).run()


def _strip_flags(argv: List[str], flags: List[str]) -> List[str]:
    """Remove ``--flag value`` / ``--flag=value`` pairs from an argv."""
    out: List[str] = []
    skip_next = False
    for a in argv:
        if skip_next:
            skip_next = False
            continue
        if a in flags:
            skip_next = True
            continue
        if any(a.startswith(f + "=") for f in flags):
            continue
        out.append(a)
    return out


def _run_fleet_coordinator(argv: List[str], args) -> int:
    """``train --fleet-workers N`` (no worker id): this process never
    touches jax — it spawns N pinned worker subprocesses (each rerunning
    this argv plus ``--fleet-worker-id k``) and supervises restarts with
    ``--resume`` (training/fleet/coordinator.py)."""
    from .training.fleet.coordinator import run_fleet

    # coordinator-only flags must not reach the children: --max-restarts
    # would nest a per-child supervisor chain, --cpu-cores is resolved
    # HERE into per-worker taskset masks
    child_argv = _strip_flags(argv, ["--max-restarts", "--cpu-cores"])
    cpu_cores: Optional[List[str]] = None
    if args.cpu_cores and args.device == "cpu":
        if args.cpu_cores.strip().lower() == "auto":
            cpu_cores = [str(c) for c in sorted(os.sched_getaffinity(0))]
        else:
            cpu_cores = [
                m.strip() for m in args.cpu_cores.split(",") if m.strip()
            ]
    return run_fleet(
        child_argv,
        n_workers=args.fleet_workers,
        max_restarts=args.max_restarts,
        cpu_cores=cpu_cores,
        # fleet default, NOT the 10s serving grace: a preemption must
        # outlive worker 0's distributed checkpoint commit
    )


def train_command(argv: List[str]) -> int:
    # allow_abbrev=False: an abbreviated --max-restart would parse as
    # supervisor mode yet escape the exact-spelling strip in
    # _supervise_train, so every child would re-supervise a grandchild
    # with the same argv — an unbounded supervisor chain
    parser = argparse.ArgumentParser(
        prog="spacy_ray_tpu train", description="Train a pipeline from a config.",
        allow_abbrev=False,
    )
    parser.add_argument("config_path", type=Path)
    parser.add_argument("--n-workers", type=int, default=None, dest="n_workers")
    parser.add_argument("--coordinator", type=str, default=None,
                        help="jax.distributed coordinator address (multi-host)")
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--device", type=str, default="tpu", choices=["tpu", "cpu", "gpu"])
    parser.add_argument("--code", type=Path, default=None)
    parser.add_argument("--output", "-o", type=Path, default=None)
    parser.add_argument("--resume", action="store_true")
    parser.add_argument("--max-restarts", type=int, default=0, dest="max_restarts",
                        help="supervisor mode: relaunch the training child up "
                        "to N times on nonzero exit, resuming from the last "
                        "intact checkpoint (0 = train in-process)")
    parser.add_argument("--profile", type=Path, default=None,
                        help="write a jax.profiler trace of the [training] "
                        "profile_window steps (default 5-15) here")
    parser.add_argument("--metrics-dir", type=Path, default=None,
                        dest="metrics_dir",
                        help="enable telemetry: metrics.jsonl + Chrome trace "
                        "+ anomaly detectors land here (overrides "
                        "[training] metrics_dir; see docs/OBSERVABILITY.md)")
    parser.add_argument("--metrics-port", type=int, default=None,
                        dest="metrics_port",
                        help="serve the trainer's telemetry over HTTP on "
                        "this port (/metrics JSON or ?format=prometheus, "
                        "/healthz clock anchor, /trace) — requires "
                        "telemetry on via --metrics-dir/[training] "
                        "metrics_dir; overrides [training] metrics_port. "
                        "Binds 127.0.0.1 unless [training] metrics_host "
                        "(or --training.metrics_host) says otherwise")
    parser.add_argument("--fleet-workers", type=int, default=0,
                        dest="fleet_workers",
                        help="asynchronous trainer fleet: spawn N worker "
                        "PROCESSES exchanging gradients/params over HTTP "
                        "with parameter ownership, quorum apply, and "
                        "staleness discard (training/fleet/; TUNING.md "
                        "§19). 0 = the in-mesh synchronous loop")
    parser.add_argument("--quorum", type=int, default=0,
                        help="fleet: gradients from this many distinct "
                        "workers trigger an owner's optimizer apply "
                        "(0 = auto: all-but-one, min 1 — one crashed "
                        "peer cannot stall the fleet)")
    parser.add_argument("--max-staleness", type=int, default=1,
                        dest="max_staleness",
                        help="fleet: accept gradients stamped up to S "
                        "shard versions behind the owner's current; "
                        "staler pushes are discarded and counted "
                        "(srt_training_grad_discarded_total)")
    parser.add_argument("--fleet-base-port", type=int, default=None,
                        dest="fleet_base_port",
                        help="fleet: worker k's peer+telemetry endpoint "
                        "binds base+k (default 47200)")
    parser.add_argument("--fleet-worker-id", type=int, default=None,
                        dest="fleet_worker_id",
                        help="(internal) run as fleet worker K — the "
                        "coordinator appends this; setting it by hand "
                        "runs one worker of a hand-assembled fleet")
    parser.add_argument("--cpu-cores", type=str, default="auto",
                        dest="cpu_cores",
                        help="fleet coordinator on --device cpu: taskset "
                        "-c core masks cycled per worker ('auto' = "
                        "round-robin over this process's affinity set, "
                        "'' = unpinned)")
    parser.add_argument("--grad-compression", type=str, default="auto",
                        dest="grad_compression",
                        choices=("auto", "f32", "bf16", "int8"),
                        help="fleet: wire codec for gradient pushes "
                        "(TUNING.md §20). auto = int8 with error "
                        "feedback where the convergence suite has run, "
                        "bf16 elsewhere; per-peer negotiated, so mixed "
                        "fleets degrade to f32 instead of erroring")
    parser.add_argument("--param-delta-window", type=int, default=4,
                        dest="param_delta_window",
                        help="fleet: owners retain K versions of "
                        "compressed param deltas so a puller at most K "
                        "versions behind ships a delta frame instead of "
                        "its full slice; 0 = full pulls only. Window "
                        "misses degrade to full pulls (RESILIENCE.md)")
    parser.add_argument("--peer-lease-s", type=float, default=60.0,
                        dest="peer_lease_s",
                        help="fleet: elastic-membership lease — a peer "
                        "silent on /healthz for this long AND missing 3 "
                        "consecutive probes is evicted by the acting "
                        "lead; survivors re-shard its parameters at the "
                        "next membership epoch (RESILIENCE.md "
                        "'Ownership failover'). 0 disables eviction "
                        "(frozen membership)")
    parser.add_argument("--verbose", "-V", action="store_true")
    args, extra = parser.parse_known_args(argv)

    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.ERROR)
    # resilience events (resume anomalies, retries, preemption, checkpoint
    # fallback) must reach the operator even without -V — they used to be
    # bare prints; now they flow through this logger (+ the jsonl logger)
    logging.getLogger("spacy_ray_tpu.training").setLevel(
        logging.INFO if args.verbose else logging.WARNING
    )

    if args.fleet_workers > 0 and args.fleet_worker_id is None:
        # fleet coordinator mode: jax-free parent spawning N pinned
        # worker subprocesses, each rerunning this argv with its own
        # --fleet-worker-id; --max-restarts becomes the PER-WORKER
        # restart cap (crashed workers rejoin with --resume)
        return _run_fleet_coordinator(argv, args)

    if args.max_restarts > 0:
        # supervisor mode: this process never touches jax — it only spawns,
        # relays signals to, and relaunches the training child
        return _supervise_train(argv, args.max_restarts)

    _setup_device(args.device)
    _init_distributed(args.coordinator, args.num_processes, args.process_id)

    from .config import load_config, parse_cli_overrides
    from .registry import import_code

    import_code(str(args.code) if args.code else None)
    overrides = parse_cli_overrides(extra)
    config = load_config(args.config_path, overrides, interpolate=False)

    from .training.loop import train

    fleet_kwargs = None
    if args.fleet_worker_id is not None:
        if args.fleet_workers <= 0:
            parser.error("--fleet-worker-id requires --fleet-workers N")
        from .training.fleet.worker import DEFAULT_FLEET_BASE_PORT

        fleet_kwargs = {
            "worker_id": args.fleet_worker_id,
            "n_workers": args.fleet_workers,
            "quorum": args.quorum,
            "max_staleness": args.max_staleness,
            "base_port": (
                args.fleet_base_port
                if args.fleet_base_port is not None
                else DEFAULT_FLEET_BASE_PORT
            ),
            "grad_compression": args.grad_compression,
            "param_delta_window": args.param_delta_window,
            "peer_lease_s": args.peer_lease_s,
        }

    nlp, result = train(
        config,
        output_path=args.output,
        n_workers=args.n_workers,
        resume=args.resume,
        profile_dir=args.profile,
        metrics_dir=args.metrics_dir,
        metrics_port=args.metrics_port,
        fleet=fleet_kwargs,
    )
    if result.interrupted:
        from .training.resilience import RC_PREEMPTED

        if args.output is not None:
            print(
                f"Interrupted at step {result.final_step} — checkpoint "
                f"written; rerun with --resume to continue (exit {RC_PREEMPTED})"
            )
        else:
            print(
                f"Interrupted at step {result.final_step} — NO checkpoint "
                f"(no --output given); progress is lost (exit {RC_PREEMPTED})"
            )
        return RC_PREEMPTED
    if fleet_kwargs is not None and fleet_kwargs["worker_id"] != 0:
        # non-lead fleet workers don't evaluate — a best_score of -1
        # here would read as a failed run
        fl = getattr(result, "fleet", {}) or {}
        print(
            f"Done. fleet worker {fleet_kwargs['worker_id']}: "
            f"steps={result.final_step} shard version={fl.get('version')} "
            f"words/sec={result.wps:,.0f}"
        )
    else:
        print(
            f"Done. steps={result.final_step} best_score={result.best_score:.4f} "
            f"(step {result.best_step}) words/sec={result.wps:,.0f}"
        )
    for comp_name in nlp.pipe_names:
        stats = getattr(nlp.components[comp_name], "oracle_stats", None)
        if stats and (stats["projectivized"] or stats["skipped"]):
            print(
                f"[{comp_name}] collation: {stats['docs']} doc-passes, "
                f"{stats['projectivized']} pseudo-projectivized, "
                f"{stats['skipped']} skipped (unusable trees)"
            )
    return 0


def evaluate_command(argv: List[str]) -> int:
    parser = argparse.ArgumentParser(prog="spacy_ray_tpu evaluate")
    parser.add_argument("model_path", type=Path)
    parser.add_argument("data_path", type=Path)
    parser.add_argument("--device", type=str, default="tpu", choices=["tpu", "cpu", "gpu"])
    parser.add_argument(
        "--output", type=Path, default=None,
        help="write the metrics as JSON (spaCy's `evaluate --output` surface)",
    )
    args = parser.parse_args(argv)
    _setup_device(args.device)

    from .pipeline.language import Pipeline
    from .training.corpus import Corpus

    nlp = Pipeline.from_disk(args.model_path)
    examples = list(Corpus(args.data_path)())
    scores = nlp.evaluate(examples)
    for key, value in sorted(scores.items()):
        if isinstance(value, dict):
            # per-type tables (ents_per_type, cats_f_per_type, ...)
            for sub, prf in sorted(value.items()):
                line = "  ".join(f"{m}={prf[m]:.4f}" for m in ("p", "r", "f"))
                print(f"{key:24s} {sub:14s} {line}")
        elif value is None:
            print(f"{key:24s} -")  # no gold annotation for this metric
        else:
            print(f"{key:24s} {value:.4f}")
    if args.output is not None:
        import json

        args.output.parent.mkdir(parents=True, exist_ok=True)
        args.output.write_text(
            json.dumps(scores, indent=2, sort_keys=True, default=float) + "\n",
            encoding="utf8",
        )
        print(f"metrics written to {args.output}")
    return 0


def convert_command(argv: List[str]) -> int:
    """Convert jsonl/conllu corpora into the binary corpus format (the
    reference's data path runs `spacy convert`, bin/get-data.sh:8-12)."""
    parser = argparse.ArgumentParser(prog="spacy_ray_tpu convert")
    parser.add_argument("input_path", type=Path)
    parser.add_argument("output_path", type=Path)
    args = parser.parse_args(argv)

    from .training.corpus import DocBin, _iter_path

    try:
        docs = list(_iter_path(args.input_path))
    except Exception as e:  # corrupt inputs raise zlib/msgpack/Key errors too
        print(f"Could not read {args.input_path}: {e}", file=sys.stderr)
        return 1
    if args.output_path.suffix == ".spacy":
        # the real spaCy DocBin byte format (readable by spaCy itself)
        from .training.spacy_docbin import write_docbin

        write_docbin(args.output_path, docs)
    else:
        DocBin(docs).to_disk(args.output_path)
    print(f"Wrote {len(docs)} docs to {args.output_path}")
    return 0


def init_config_command(argv: List[str]) -> int:
    """Write a ready-to-train config (spacy's `init config` role): either a
    named preset, or an arbitrary `--pipeline` component list composed over
    a shared trunk (spacy's `init config --pipeline` surface)."""
    from .presets import INIT_PRESETS, compose_pipeline_config

    parser = argparse.ArgumentParser(prog="spacy_ray_tpu init-config")
    parser.add_argument("output_path", type=Path)
    parser.add_argument(
        "--preset",
        default=None,
        choices=sorted(INIT_PRESETS),
        help="cnn: tagger-only CNN tok2vec; sm: tagger+parser+ner shared CNN; "
        "trf: RoBERTa-base-shape transformer pipeline; spancat: spancat+textcat",
    )
    parser.add_argument(
        "--pipeline", default=None,
        help="comma-separated component list composed over one shared trunk "
        "(e.g. tagger,parser,ner,entity_ruler); mutually exclusive with "
        "--preset",
    )
    parser.add_argument(
        "--trunk", default="cnn", choices=["cnn", "trf"],
        help="shared trunk for --pipeline: CNN tok2vec or transformer",
    )
    parser.add_argument(
        "--width", type=int, default=0,
        help="trunk width for --pipeline (default: 96 cnn / 768 trf)",
    )
    args = parser.parse_args(argv)
    if args.preset and args.pipeline:
        print("--preset and --pipeline are mutually exclusive", file=sys.stderr)
        return 1
    from .config import Config

    if args.pipeline:
        try:
            text = compose_pipeline_config(
                [c.strip() for c in args.pipeline.split(",") if c.strip()],
                trunk=args.trunk,
                width=args.width,
            )
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 1
        label = f"pipeline [{args.pipeline}] over {args.trunk} trunk"
    else:
        text = INIT_PRESETS[args.preset or "cnn"]
        label = f"{args.preset or 'cnn'!r} preset"
    cfg = Config.from_str(text)  # parse = validate
    args.output_path.write_text(cfg.to_str(), encoding="utf8")
    print(f"Wrote {label} to {args.output_path}")
    return 0


def assemble_command(argv: List[str]) -> int:
    """`assemble` — build a pipeline from a config WITHOUT training and save
    it (spaCy's `spacy assemble`): the path for rule/lookup-only pipelines
    (entity_ruler, attribute_ruler, lemmatizer) and for materializing
    sourced-component combinations."""
    parser = argparse.ArgumentParser(
        prog="spacy_ray_tpu assemble",
        description="Build a pipeline from a config without training; "
        "initializes components (labels from [initialize] data when "
        "present, else empty) and writes the pipeline to output.",
    )
    parser.add_argument("config_path", type=Path)
    parser.add_argument("output_path", type=Path)
    parser.add_argument("--device", type=str, default="cpu", choices=["tpu", "cpu", "gpu"])
    parser.add_argument("--code", type=Path, default=None)
    args, extra = parser.parse_known_args(argv)
    _setup_device(args.device)

    from .config import load_config, parse_cli_overrides
    from .pipeline.language import Pipeline
    from .registry import import_code, registry

    import_code(str(args.code) if args.code else None)
    overrides = parse_cli_overrides(extra)
    config = load_config(args.config_path, overrides, interpolate=False).interpolate()
    nlp = Pipeline.from_config(config)

    get_examples = None
    corpora_cfg = config.get("corpora", {})
    train_name = (config.get("training") or {}).get("train_corpus", "corpora.train")
    parts = str(train_name).split(".")
    block = (
        corpora_cfg.get(parts[1])
        if len(parts) == 2 and parts[0] == "corpora"
        else None
    )
    if block is not None:
        try:
            corpus = registry.resolve(dict(block))
            get_examples = lambda: iter(corpus())  # noqa: E731
        except Exception as e:
            print(
                f"note: train corpus unavailable ({e}); assembling without "
                "initialize data — trainable components get empty label sets",
                file=sys.stderr,
            )
    nlp.initialize(get_examples, seed=0)
    nlp.to_disk(args.output_path)
    print(f"Assembled pipeline ({', '.join(nlp.pipe_names)}) -> {args.output_path}")
    return 0


def _check_arch_names(block, registry, where: str) -> None:
    """Recursively verify @-references resolve to registered callables and
    that non-@ keys are accepted argument names — without calling anything."""
    if not isinstance(block, dict):
        return
    ref_keys = [k for k in block if k.startswith("@")]
    for k in ref_keys:
        namespace = k[1:]
        func = registry.get(namespace, block[k])  # raises if unknown
        # the SAME name/arity validation resolve applies at train time —
        # one implementation, so debug-config can't drift from it
        args = {a: v for a, v in block.items() if not a.startswith("@")}
        registry._validate_args(func, args, namespace, block[k])
    for key, sub in block.items():
        if isinstance(sub, dict):
            _check_arch_names(sub, registry, f"{where}.{key}")


def debug_config_command(argv: List[str]) -> int:
    """`debug config` — resolve every block of a config and report what's
    wrong (or print the resolved summary), without touching any data."""
    parser = argparse.ArgumentParser(prog="spacy_ray_tpu debug-config")
    parser.add_argument("config_path", type=Path)
    parser.add_argument("--code", type=Path, default=None)
    args, extra = parser.parse_known_args(argv)

    from .config import load_config, parse_cli_overrides
    from .registry import import_code, registry

    import_code(str(args.code) if args.code else None)
    overrides = parse_cli_overrides(extra)
    try:
        config = load_config(args.config_path, overrides, interpolate=False)
        config = config.interpolate()
    except Exception as e:
        print(f"[config] INVALID: {e}", file=sys.stderr)
        return 1
    problems = 0
    nlp_block = config.get("nlp") or {}
    pipeline = list(nlp_block.get("pipeline") or [])
    comps = config.get("components") or {}
    for name in pipeline:
        block = comps.get(name)
        if block is None:
            print(f"[components.{name}] MISSING (listed in nlp.pipeline)",
                  file=sys.stderr)
            problems += 1
            continue
        if "source" in block:
            print(f"[components.{name}] sourced from {block['source']!r}")
            continue
        try:
            factory = block.get("factory")
            registry.get("factories", factory)
            # validate architecture names + argument names WITHOUT invoking
            # the factories: eager construction would run model-building
            # code that legitimately needs runtime context (loaded vectors,
            # devices) and must not decide config validity
            _check_arch_names(block.get("model"), registry, f"components.{name}.model")
            print(f"[components.{name}] ok (factory={factory})")
        except Exception as e:
            print(f"[components.{name}] INVALID: {e}", file=sys.stderr)
            problems += 1
    for section in ("corpora", "training", "pretraining", "initialize"):
        if section in config and config[section]:
            print(f"[{section}] present ({len(dict(config[section]))} keys)")
    extra_comps = sorted(set(comps) - set(pipeline))
    if extra_comps:
        print(f"note: components defined but not in nlp.pipeline: {extra_comps}")
    if problems:
        print(f"{problems} problem(s) found", file=sys.stderr)
        return 1
    print("Config OK")
    return 0


def debug_data_command(argv: List[str]) -> int:
    """Corpus sanity report (spaCy's `debug data` role): doc/token counts,
    annotation coverage, label distributions, length histogram, and
    parser-specific warnings (non-projective trees are skipped by the
    arc-eager oracle)."""
    parser = argparse.ArgumentParser(prog="spacy_ray_tpu debug-data")
    parser.add_argument("data_path", type=Path)
    parser.add_argument("--limit", type=int, default=0)
    args = parser.parse_args(argv)

    from collections import Counter

    from .pipeline.nonproj import is_projective, projectivize
    from .training.corpus import Corpus

    examples = list(Corpus(args.data_path, limit=args.limit)())
    n_docs = len(examples)
    n_tokens = sum(len(eg) for eg in examples)
    lengths = sorted(len(eg) for eg in examples)
    have = Counter()
    tag_labels, dep_labels, ent_labels, cat_labels = Counter(), Counter(), Counter(), Counter()
    nonproj = 0
    parsed_trees = []
    for eg in examples:
        ref = eg.reference
        if ref.tags:
            have["tags"] += 1
            tag_labels.update(t for t in ref.tags if t)
        if ref.heads and ref.deps:
            have["deps"] += 1
            dep_labels.update(d for d in ref.deps if d)
            parsed_trees.append((ref.heads, ref.deps))
            if not is_projective(ref.heads):
                nonproj += 1
        if ref.ents:
            have["ents"] += 1
            ent_labels.update(s.label for s in ref.ents)
        if ref.cats:
            have["cats"] += 1
            cat_labels.update(ref.cats)
        if ref.spans:
            have["spans"] += 1
        if ref.sent_starts:
            have["sent_starts"] += 1
        if ref.morphs:
            have["morphs"] += 1

    def pct(n):
        return f"{100 * n / n_docs:.1f}%" if n_docs else "0%"

    print(f"docs: {n_docs}   tokens: {n_tokens}")
    if lengths:
        print(
            f"doc length: min={lengths[0]} p50={lengths[len(lengths) // 2]} "
            f"p95={lengths[int(len(lengths) * 0.95)]} max={lengths[-1]}"
        )
    print("annotation coverage:", {k: pct(v) for k, v in sorted(have.items())})
    for name, counter in [
        ("tags", tag_labels), ("deps", dep_labels), ("ents", ent_labels), ("cats", cat_labels)
    ]:
        if counter:
            top = ", ".join(f"{l}({c})" for l, c in counter.most_common(12))
            print(f"{name} labels ({len(counter)}): {top}")
    if parsed_trees:
        # the EXACT check training collation applies: projectivize, then the
        # arc-eager oracle (a doc can pass the crossing test yet still be
        # oracle-unreachable, e.g. cyclic heads from bad annotation)
        from .pipeline.nonproj import is_decorated
        from .pipeline.transition import gold_oracle

        base_ids = {l: i for i, l in enumerate(sorted(dep_labels))}
        lifted = unusable = 0
        for heads, deps in parsed_trees:
            res = projectivize(heads, deps)
            if res is None:
                unusable += 1
                continue
            proj_heads, deco, n_lifted = res
            extra = sorted(
                {d for d in deco if is_decorated(d) and d not in base_ids}
            )
            if extra:
                ids_map = dict(base_ids)
                for d in extra:
                    ids_map[d] = len(ids_map)
            else:
                ids_map = base_ids
            ids = [ids_map.get(d, 0) for d in deco]
            if gold_oracle(proj_heads, ids, len(ids_map)) is None:
                unusable += 1
            elif n_lifted:
                lifted += 1
        if nonproj or unusable:
            print(
                f"non-projective trees: {nonproj}/{len(parsed_trees)} parsed "
                f"docs — {lifted} trainable via pseudo-projective lifting "
                f"(label decoration); unusable trees (skipped at training): "
                f"{unusable}"
            )
    if n_docs == 0:
        print("WARNING: corpus is empty")
        return 1
    return 0


def _load_plugins() -> None:
    """Import packages registered under the `spacy_ray_tpu_plugins` entry
    point so their @registry decorators run (the reference's setuptools
    plugin mechanism, setup.cfg:35-41)."""
    try:
        from importlib.metadata import entry_points

        for ep in entry_points(group="spacy_ray_tpu_plugins"):
            try:
                ep.load()
            except Exception as e:  # a broken plugin must not kill the CLI
                print(f"warning: plugin {ep.name!r} failed to load: {e}", file=sys.stderr)
    except Exception:
        pass


def pretrain_command(argv: List[str]) -> int:
    """`pretrain` — tok2vec pretraining from the config's [pretraining]
    block (spaCy's `spacy pretrain` surface); weights go to --output and
    load back via [initialize] init_tok2vec."""
    parser = argparse.ArgumentParser(
        prog="spacy_ray_tpu pretrain",
        description="Pretrain the tok2vec/transformer trunk on raw text "
        "([pretraining] config block); load results with "
        "[initialize] init_tok2vec.",
    )
    parser.add_argument("config_path", type=Path)
    parser.add_argument("output_dir", type=Path)
    parser.add_argument("--n-workers", type=int, default=None, dest="n_workers")
    parser.add_argument("--device", type=str, default="tpu", choices=["tpu", "cpu", "gpu"])
    parser.add_argument("--code", type=Path, default=None)
    parser.add_argument("--verbose", "-V", action="store_true")
    args, extra = parser.parse_known_args(argv)

    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.ERROR)
    _setup_device(args.device)

    from .config import load_config, parse_cli_overrides
    from .registry import import_code

    import_code(str(args.code) if args.code else None)
    overrides = parse_cli_overrides(extra)
    config = load_config(args.config_path, overrides, interpolate=False)

    from .training.pretrain import pretrain

    stats = pretrain(config, args.output_dir, n_workers=args.n_workers)
    print(
        f"Pretraining done. steps={stats['steps']} loss={stats['loss']:.4f} "
        f"words={stats['words']:,} -> {stats['output']}"
    )
    return 0


def package_command(argv: List[str]) -> int:
    """`package` — wrap a trained pipeline directory into an installable
    Python package (spaCy's `spacy package` surface)."""
    parser = argparse.ArgumentParser(
        prog="spacy_ray_tpu package",
        description="Package a saved pipeline as an installable Python "
        "project; load it back with spacy_ray_tpu.load(name).",
    )
    parser.add_argument("model_dir", type=Path)
    parser.add_argument("output_dir", type=Path)
    parser.add_argument("--name", type=str, default="pipeline")
    parser.add_argument("--version", type=str, default="0.0.0")
    parser.add_argument(
        "--build", type=str, default="none", choices=["none", "sdist", "wheel"]
    )
    parser.add_argument("--force", "-f", action="store_true",
                        help="overwrite an existing package directory")
    args = parser.parse_args(argv)

    from .packaging import package

    project = package(
        args.model_dir,
        args.output_dir,
        name=args.name,
        version=args.version,
        build=args.build,
        force=args.force,
    )
    print(f"Package written to {project}")
    if args.build != "none":
        dist = project / "dist"
        for f in sorted(dist.iterdir()):
            print(f"  built: {f}")
    return 0


def init_vectors_command(argv: List[str]) -> int:
    """`init-vectors` — convert word2vec-text / glove-text / .npz embeddings
    into the vectors.npz format `[initialize] vectors` loads (spaCy's
    `spacy init vectors` surface)."""
    parser = argparse.ArgumentParser(
        prog="spacy_ray_tpu init-vectors",
        description="Convert word embeddings (word2vec/glove text, optionally "
        ".gz, or an npz with words+vectors) for [initialize] vectors.",
    )
    parser.add_argument("input_path", type=Path)
    parser.add_argument("output_path", type=Path)
    parser.add_argument("--truncate", type=int, default=0,
                        help="keep only the first N rows (0 = all)")
    args = parser.parse_args(argv)

    import gzip

    import numpy as np

    from .pipeline.vectors import Vectors

    if args.input_path.suffix == ".npz":
        vec = Vectors.from_disk(args.input_path)
        words, table = list(vec.key_to_row), vec.table
        if args.truncate:
            words, table = words[: args.truncate], table[: args.truncate]
    else:
        opener = gzip.open if args.input_path.suffix == ".gz" else open
        words, rows = [], []
        with opener(args.input_path, "rt", encoding="utf8") as f:
            first = f.readline()
            parts = first.split()
            if len(parts) == 2 and all(p.isdigit() for p in parts):
                pass  # word2vec "N D" header line
            elif len(parts) >= 2:
                # glove-style: no header; first line is already a row
                words.append(parts[0])
                rows.append(np.asarray(parts[1:], dtype=np.float32))
            # else: empty/blank first line -> fall through; the "No vectors
            # found" check below reports cleanly
            for line in f:
                if args.truncate and len(words) >= args.truncate:
                    break
                parts = line.split()
                if len(parts) < 2:
                    continue
                words.append(parts[0])
                rows.append(np.asarray(parts[1:], dtype=np.float32))
        if not rows:
            print("No vectors found in input", file=sys.stderr)
            return 1
        widths = {r.shape[0] for r in rows}
        if len(widths) != 1:
            print(f"Inconsistent vector widths in input: {sorted(widths)}",
                  file=sys.stderr)
            return 1
        table = np.stack(rows)
    Vectors(words, table).to_disk(args.output_path)
    print(
        f"Wrote {len(words)} vectors (dim {table.shape[1]}) to "
        f"{args.output_path}; use via [initialize] vectors = "
        f"\"{args.output_path}\""
    )
    return 0


def parse_command(argv: List[str], prog: str = "parse") -> int:
    """Bulk parallel inference: annotate a corpus with a trained pipeline —
    the ``spacy ray parse`` command the reference advertises as planned
    (reference README.md:15 "we expect to add `spacy ray pretrain` and
    `spacy ray parse` as well"); also exposed as ``apply`` (spaCy's name
    for the same operation). Prediction batches shard over the mesh's
    ``data`` axis (every local device busy); under multi-host each process
    parses a round-robin shard of the input and writes its own output
    part, so throughput scales with hosts like the training loop does."""
    import time

    parser = argparse.ArgumentParser(prog=f"spacy_ray_tpu {prog}")
    parser.add_argument("model_path", type=Path)
    parser.add_argument("input_path", type=Path,
                        help=".jsonl/.conllu/.msgdoc/.spacy corpus, or .txt "
                        "with one raw text per line")
    parser.add_argument("output_path", type=Path,
                        help=".spacy (DocBin) or .jsonl output; multi-host "
                        "runs write one .partN per process")
    parser.add_argument("--device", type=str, default="tpu",
                        choices=["tpu", "cpu", "gpu"])
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--n-workers", type=int, default=None,
                        help="data-axis size for sharded prediction "
                        "(default: all local devices)")
    parser.add_argument("--coordinator", type=str, default=None,
                        help="jax.distributed coordinator address (multi-host)")
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    args = parser.parse_args(argv)
    _setup_device(args.device)
    _init_distributed(args.coordinator, args.num_processes, args.process_id)

    import jax

    from .parallel.mesh import build_mesh
    from .pipeline.language import Pipeline

    nlp = Pipeline.from_disk(args.model_path)

    # ---- stream input as bare (unannotated) docs ----
    # read/predict/write chunk-by-chunk: a genuinely bulk corpus — the
    # command's whole purpose — must not be materialized doc-by-doc on the
    # host (round-4 advisor finding). Only the .spacy writer keeps state
    # across chunks, and that is packed attribute rows, not Doc objects.
    import itertools
    import os as _os

    if args.input_path.suffix == ".txt":

        def _txt_docs():
            with open(args.input_path, encoding="utf8") as f:
                for line in f:
                    if line.strip():
                        yield nlp.tokenizer(line.rstrip("\n"))

        doc_iter = _txt_docs()
    else:
        from .training.corpus import _iter_path

        # strip any gold annotation: parse writes the MODEL's predictions
        doc_iter = (d.copy_shell() for d in _iter_path(args.input_path))

    # count docs BEFORE rank sharding: an empty round-robin slice on a
    # non-empty corpus (world > n_docs) is a legitimate empty part file,
    # not the corpus-empty error
    seen = {"total": 0}

    def _counted(it):
        for d in it:
            seen["total"] += 1
            yield d

    doc_iter = _counted(doc_iter)
    rank, world = jax.process_index(), jax.process_count()
    if world > 1:
        doc_iter = itertools.islice(doc_iter, rank, None, world)

    out = args.output_path
    if world > 1:
        out = out.with_name(f"{out.stem}.part{rank}{out.suffix}")
    out.parent.mkdir(parents=True, exist_ok=True)
    out_tmp = out.with_name(out.name + ".tmp")

    # one streaming writer per output family: text formats share a handle
    # (.jsonl plain, .msgdoc gzip lines), .spacy goes through the
    # incremental DocBinWriter. Everything lands in a .tmp first and is
    # promoted on success — a mid-corpus failure must not leave a
    # well-formed-looking truncated artifact at the final path.
    text_f = docbin_writer = None
    if out.suffix == ".spacy":
        from .training.spacy_docbin import DocBinWriter

        docbin_writer = DocBinWriter()
    elif out.suffix == ".jsonl":
        text_f = open(out_tmp, "w", encoding="utf8")
    else:
        import gzip

        text_f = gzip.open(out_tmp, "wt", encoding="utf8")

    mesh = build_mesh(n_data=args.n_workers) if jax.process_count() == 1 else None
    n_docs = n_words = 0
    seconds = 0.0
    try:
        while True:
            chunk = list(itertools.islice(doc_iter, args.batch_size))
            if not chunk:
                break
            t0 = time.perf_counter()
            nlp.predict_docs(chunk, batch_size=args.batch_size, mesh=mesh)
            seconds += time.perf_counter() - t0
            n_docs += len(chunk)
            n_words += sum(len(d) for d in chunk)
            if text_f is not None:
                import json

                from .training.corpus import _doc_to_json

                for d in chunk:
                    text_f.write(json.dumps(_doc_to_json(d)) + "\n")
            else:
                for d in chunk:
                    docbin_writer.add(d)
    except BaseException:
        if text_f is not None:
            text_f.close()
            out_tmp.unlink(missing_ok=True)
        raise
    if text_f is not None:
        text_f.close()
    if seen["total"] == 0:
        out_tmp.unlink(missing_ok=True)
        print(f"No documents in {args.input_path}", file=sys.stderr)
        return 1
    if docbin_writer is not None:
        docbin_writer.finalize(out_tmp)
    _os.replace(out_tmp, out)
    print(
        f"Parsed {n_docs} docs ({n_words} words) in {seconds:.1f}s "
        f"({n_words / max(seconds, 1e-9):,.0f} words/s) -> {out}"
    )
    return 0


def find_threshold_command(argv: List[str]) -> int:
    """Sweep a component's decision threshold against dev data and report
    the best value — spaCy's `find-threshold` surface for spancat /
    textcat_multilabel / entity_linker-style thresholded components."""
    parser = argparse.ArgumentParser(prog="spacy_ray_tpu find-threshold")
    parser.add_argument("model_path", type=Path)
    parser.add_argument("data_path", type=Path)
    parser.add_argument("pipe_name", type=str)
    parser.add_argument("--threshold-key", type=str, default="threshold",
                        help="component attribute to sweep")
    parser.add_argument("--scores-key", type=str, default=None,
                        help="score metric to maximize (default: the "
                        "component's positively-weighted default score)")
    parser.add_argument("--n-trials", type=int, default=11)
    parser.add_argument("--device", type=str, default="tpu",
                        choices=["tpu", "cpu", "gpu"])
    args = parser.parse_args(argv)
    _setup_device(args.device)

    from .pipeline.language import Pipeline
    from .training.corpus import Corpus

    nlp = Pipeline.from_disk(args.model_path)
    if args.pipe_name not in nlp.pipe_names:
        print(
            f"No component {args.pipe_name!r} in pipeline "
            f"(have: {', '.join(nlp.pipe_names)})", file=sys.stderr,
        )
        return 1
    comp = nlp.components[args.pipe_name]
    current = getattr(comp, args.threshold_key, None)
    if not isinstance(current, (int, float)) or isinstance(current, bool):
        print(
            f"[components.{args.pipe_name}] has no numeric attribute "
            f"{args.threshold_key!r} to sweep "
            f"(found: {type(current).__name__})", file=sys.stderr,
        )
        return 1
    scores_key = args.scores_key
    if scores_key is None:
        positive = [
            k for k, v in (getattr(comp, "default_score_weights", None) or {}).items()
            if v and v > 0
        ]
        if not positive:
            print(
                f"--scores-key required: [components.{args.pipe_name}] "
                "declares no default score weights", file=sys.stderr,
            )
            return 1
        scores_key = positive[0]

    examples = list(Corpus(args.data_path)())
    if not examples:
        print(f"No documents in {args.data_path}", file=sys.stderr)
        return 1

    # forward ONCE: the swept attribute is consumed host-side in
    # set_annotations/score, so device outputs are identical across
    # trials — only re-annotate + re-score per threshold. Consequence:
    # scores_key must be produced by the swept component itself.
    docs = [eg.reference.copy_shell() for eg in examples]
    chunks = list(
        nlp.predict_chunks(docs, batch_size=128, only=[args.pipe_name])
    )
    for eg, doc in zip(examples, docs):
        eg.predicted = doc

    n = max(int(args.n_trials), 2)
    best = (None, -1.0)
    try:
        for i in range(n):
            t = i / (n - 1)
            setattr(comp, args.threshold_key, t)
            for chunk, lengths, outputs in chunks:
                comp.set_annotations(chunk, outputs.get(args.pipe_name), lengths)
            scores = comp.score(examples)
            value = scores.get(scores_key)
            if value is None and i == 0 and scores_key not in scores:
                print(
                    f"{scores_key!r} is not produced by "
                    f"[components.{args.pipe_name}] (its scores: "
                    f"{', '.join(sorted(scores))}) — find-threshold sweeps one "
                    "component's own metric", file=sys.stderr,
                )
                return 1
            shown = f"{value:.4f}" if value is not None else "-"
            print(f"threshold={t:.3f}  {scores_key}={shown}")
            if value is not None and value > best[1]:
                best = (t, float(value))
    finally:
        # the sweep must not leave the component at its last trial value
        # (t=1.0): an in-process save after this call would persist an
        # arbitrary threshold (round-4 advisor finding)
        setattr(comp, args.threshold_key, current)
    if best[0] is None:
        print(f"{scores_key} was None at every threshold (no gold "
              "annotation for this metric in the dev data?)", file=sys.stderr)
        return 1
    print(
        f"Best: {args.threshold_key}={best[0]:.3f} ({scores_key}={best[1]:.4f}) "
        f"— set [components.{args.pipe_name}] {args.threshold_key} = {best[0]:.3f}"
    )
    return 0


def info_command(argv: List[str]) -> int:
    """Environment + install diagnostics (spacy's `info` role). Deliberately
    does NOT initialize the jax backend by default: on relay-attached
    images a wedged accelerator tunnel makes backend init hang forever
    (see devices.py). `--probe` checks reachability from a throwaway
    subprocess with a timeout instead."""
    import os
    import platform as _platform

    parser = argparse.ArgumentParser(prog="spacy_ray_tpu info")
    parser.add_argument(
        "--probe", action="store_true",
        help="probe accelerator reachability (subprocess, 60s timeout)",
    )
    parser.add_argument(
        "--markdown", action="store_true",
        help="print the environment block as a markdown table "
        "(spaCy's issue-report format)",
    )
    parser.add_argument("model_path", nargs="?", type=Path, default=None,
                        help="optional: show a saved pipeline's metadata")
    args = parser.parse_args(argv)

    from . import __version__

    import jax

    rows = [
        ("spacy-ray-tpu", __version__),
        ("python", f"{_platform.python_version()} ({_platform.system()})"),
        ("jax", jax.__version__),
        ("JAX_PLATFORMS", os.environ.get("JAX_PLATFORMS", "(unset)")),
        ("XLA_FLAGS", os.environ.get("XLA_FLAGS", "(unset)")),
    ]
    if args.markdown:
        print("| field | value |")
        print("|---|---|")
        for key, value in rows:
            print(f"| {key} | {value} |")
    else:
        for key, value in rows:
            print(f"{key:16s} {value}")
    if args.probe:
        import subprocess

        # the probe child also resolves the [training] update_sharding
        # "auto" gate for the probed topology — the same honest-label
        # discipline as fused_update: what the knob would ACTUALLY do
        # there, not what was requested
        p = subprocess.Popen(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print(d[0].platform, len(d)); "
             "from spacy_ray_tpu.parallel.step import "
             "resolve_update_sharding as r, update_sharding_status as s; "
             "from spacy_ray_tpu.parallel.mesh import build_mesh; "
             "m = build_mesh(n_data=len(d)); "
             "print(s(r('auto', n_data=len(d), "
             "backend=d[0].platform), m)); "
             # the fleet wire codec resolves the same way on the probed
             # backend (no compile — pure policy over the committed
             # convergence evidence, training/fleet/wire.py)
             "from spacy_ray_tpu.training.fleet.wire import "
             "resolve_grad_compression as rg; "
             "gc = rg('auto', d[0].platform); "
             "print(gc[0] + ' (' + gc[1] + ')')"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        try:
            out, _ = p.communicate(timeout=60)
            if p.returncode == 0 and out.strip():
                lines = out.strip().splitlines()
                platform_name, n = lines[0].split()
                print(f"accelerator      reachable: {platform_name} x{n}")
                if len(lines) > 1:
                    print(f"update_sharding  auto -> {lines[1].strip()}")
                if len(lines) > 2:
                    print(f"grad_compression auto -> {lines[2].strip()}")
                # the int8 precision-overlay resolution is evidence, not
                # policy (the probe COMPILES + validates the pallas
                # matmul on the probed backend) — so it gets its OWN
                # child and timeout: a slow kernel compile must not
                # swallow the reachability/update_sharding lines above,
                # and its timeout must not read as "backend unreachable"
                p2 = subprocess.Popen(
                    [sys.executable, "-c",
                     "import jax; d = jax.devices(); "
                     "from spacy_ray_tpu.serving.overlay import "
                     "resolve_precision as rp; "
                     "res = rp('int8', d[0].platform); "
                     "print(res[0] + ' (' + res[1] + ')')"],
                    stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                    text=True,
                )
                try:
                    out2, _ = p2.communicate(timeout=60)
                    if p2.returncode == 0 and out2.strip():
                        print("precision        int8 -> "
                              f"{out2.strip().splitlines()[-1].strip()}")
                    else:
                        print("precision        int8 -> unresolved "
                              "(probe child failed)")
                except subprocess.TimeoutExpired:
                    from .training.resilience import terminate_with_grace

                    terminate_with_grace(p2, grace_s=SHUTDOWN_GRACE_S)
                    print("precision        int8 -> unresolved "
                          "(kernel probe exceeded 60s)")
            else:
                print("accelerator      UNREACHABLE (backend init failed)")
        except subprocess.TimeoutExpired:
            # SIGTERM first (relay clients get a chance to detach cleanly),
            # but a child wedged in backend init can ignore it forever —
            # escalate to SIGKILL after the grace period instead of
            # hanging the probe (the same helper the supervisor uses)
            from .training.resilience import terminate_with_grace

            terminate_with_grace(p, grace_s=SHUTDOWN_GRACE_S)
            print("accelerator      UNREACHABLE (backend init hung >60s)")
    if args.model_path is not None:
        import json

        meta_path = args.model_path / "meta.json"
        if not meta_path.exists():
            print(f"\nNo pipeline at {args.model_path} (missing meta.json)",
                  file=sys.stderr)
            return 1
        meta = json.loads(meta_path.read_text(encoding="utf8"))
        print(f"\npipeline         {meta.get('lang', '?')}/{meta.get('name', '?')}")
        print(f"version          {meta.get('version', '?')}")
        print(f"components       {', '.join(meta.get('pipeline', []))}")
    return 0


def debug_model_command(argv: List[str]) -> int:
    """Inspect a config's resolved model shapes (spacy's `debug model`
    role): initialize the pipeline from the training corpus (labels need
    gold data) and print every parameter path, shape, dtype, and
    per-component totals."""
    parser = argparse.ArgumentParser(prog="spacy_ray_tpu debug-model")
    parser.add_argument("config_path", type=Path)
    parser.add_argument("component", nargs="?", default=None,
                        help="restrict output to one component")
    parser.add_argument("--device", type=str, default="cpu",
                        choices=["tpu", "cpu", "gpu"],
                        help="default cpu: shape inspection needs no accelerator")
    parser.add_argument("--code", type=Path, default=None)
    # split dotted overrides out BEFORE argparse: the optional positional
    # `component` would otherwise swallow an override's value
    override_args: List[str] = []
    rest: List[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("--") and "." in a.split("=", 1)[0]:
            override_args.append(a)
            if "=" not in a and i + 1 < len(argv):
                override_args.append(argv[i + 1])
                i += 1
        else:
            rest.append(a)
        i += 1
    args = parser.parse_args(rest)
    extra = override_args
    _setup_device(args.device)

    import numpy as np

    from .config import load_config, parse_cli_overrides
    from .pipeline.language import Pipeline
    from .registry import import_code, registry
    from .training.loop import resolve_dot_name, resolve_training

    import_code(str(args.code) if args.code else None)
    config = load_config(args.config_path, parse_cli_overrides(extra),
                         interpolate=False).interpolate()
    T = resolve_training(config)
    resolved_corpora = {
        name: registry.resolve(block)
        for name, block in config.get("corpora", {}).items()
    }
    train_corpus = resolve_dot_name(config, resolved_corpora, T["train_corpus"])
    nlp = Pipeline.from_config(config)
    nlp.initialize(train_corpus, seed=int(T.get("seed") or 0))

    if args.component is not None and args.component not in nlp.pipe_names:
        print(
            f"No component {args.component!r} (have: {', '.join(nlp.pipe_names)})",
            file=sys.stderr,
        )
        return 1

    from .models.core import param_paths

    grand_total = 0
    for name in nlp.pipe_names:
        if args.component and name != args.component:
            continue
        comp_params = nlp.params.get(name)
        comp = nlp.components[name]
        if comp_params is None:
            print(f"[{name}] (host-side component, no device parameters)")
            continue
        print(f"[{name}] labels={len(comp.labels)}")
        total = 0
        import jax

        flat = {
            path: leaf
            for path, leaf in zip(
                param_paths(comp_params), jax.tree_util.tree_leaves(comp_params)
            )
        }
        for path, leaf in sorted(flat.items()):
            n = int(np.prod(leaf.shape)) if leaf.shape else 1
            total += n
            print(f"  {path:48s} {str(tuple(leaf.shape)):20s} {leaf.dtype} {n:,}")
        grand_total += total
        print(f"  [{name}] total: {total:,} params")
    print(f"TOTAL: {grand_total:,} params")
    return 0


def fill_config_command(argv: List[str]) -> int:
    """Complete a partial config with every [training] default and validate
    the result (spacy's `init fill-config` role): the written file shows
    explicitly what a bare config would train with — seed, dropout,
    patience, eval_frequency, batcher, optimizer, logger — instead of
    relying on invisible defaults."""
    parser = argparse.ArgumentParser(prog="spacy_ray_tpu fill-config")
    parser.add_argument("base_path", type=Path, help="partial config")
    parser.add_argument("output_path", type=Path, help="filled config")
    args, extra = parser.parse_known_args(argv)

    from .config import Config, load_config, parse_cli_overrides
    from .training.loop import (
        DEFAULT_TRAINING,
        DEFAULT_TRAINING_BLOCKS,
        resolve_training,
    )

    config = load_config(args.base_path, parse_cli_overrides(extra),
                         interpolate=False)
    raw_training = dict(config.get("training", {}))
    if "paths" not in config:
        # a partial config may interpolate ${paths.*} without declaring
        # the section; fill it before validation like `train` overrides do
        config = config.merge({"paths": {"train": None, "dev": None}})
    resolve_training(config.interpolate())  # validates keys/types loudly
    filled_training = dict(DEFAULT_TRAINING)
    filled_training.update(raw_training)
    # registry sub-blocks every run resolves implicitly when absent
    for key, block in DEFAULT_TRAINING_BLOCKS.items():
        filled_training.setdefault(key, dict(block))
    merged = dict(config)
    merged["training"] = filled_training
    merged.setdefault("paths", {"train": None, "dev": None})
    out_cfg = Config(merged)
    Config.from_str(out_cfg.to_str())  # round-trip = validate serialization
    args.output_path.write_text(out_cfg.to_str(), encoding="utf8")
    added = sorted(set(filled_training) - set(raw_training))
    print(f"Filled {args.base_path} -> {args.output_path} "
          f"(added: {', '.join(added) if added else 'nothing'})")
    return 0


def debug_diff_command(argv: List[str]) -> int:
    """spaCy's `debug diff-config` role: classify every [training] key of
    a config against the defaults a bare config trains with (the same
    table fill-config writes) — customized / redundant restatement of a
    default / implicit default — so a reviewer sees at a glance what a
    config actually changes."""
    parser = argparse.ArgumentParser(prog="spacy_ray_tpu debug-diff-config")
    parser.add_argument("config_path", type=Path)
    args, extra = parser.parse_known_args(argv)

    from .config import load_config, parse_cli_overrides
    from .training.loop import (
        DEFAULT_TRAINING,
        DEFAULT_TRAINING_BLOCKS,
        resolve_training,
    )

    config = load_config(args.config_path, parse_cli_overrides(extra),
                         interpolate=False)
    if "paths" not in config:
        config = config.merge({"paths": {"train": None, "dev": None}})
    interpolated = config.interpolate()
    resolve_training(interpolated)  # loud validation first
    # classify INTERPOLATED values: `dropout = ${vars.drop}` must compare
    # by what it resolves to, not the template string
    raw = dict(interpolated.get("training", {}))
    defaults: Dict[str, Any] = {**DEFAULT_TRAINING, **DEFAULT_TRAINING_BLOCKS}
    rows = []
    for key in sorted(set(raw) | set(defaults)):
        if key in raw and key not in defaults:
            rows.append((key, "customized", raw[key], "-"))
        elif key in raw and raw[key] != defaults[key]:
            rows.append((key, "customized", raw[key], defaults[key]))
        elif key in raw:
            rows.append((key, "redundant (= default)", raw[key], defaults[key]))
        else:
            rows.append((key, "implicit default", "-", defaults[key]))
    width = max(len(r[0]) for r in rows)
    print(f"{'[training] key':{width}s}  {'status':22s} value (default)")
    for key, status, value, default in rows:
        shown = value if value != "-" else default
        suffix = f" (default: {default})" if status == "customized" and default != "-" else ""
        print(f"{key:{width}s}  {status:22s} {shown}{suffix}")
    n_custom = sum(1 for r in rows if r[1] == "customized")
    n_redund = sum(1 for r in rows if r[1].startswith("redundant"))
    print(f"\n{n_custom} customized, {n_redund} redundant, "
          f"{len(rows) - n_custom - n_redund} implicit defaults")
    return 0


def init_labels_command(argv: List[str]) -> int:
    """spaCy's `init labels` surface: collect every trainable component's
    label set from the training corpus ONCE and write one JSON file per
    component. Point the config at them via
    ``[initialize.components.<name>] labels = "<dir>/<name>.json"`` —
    later runs skip corpus label collection and the class ORDER is frozen
    (a grown corpus can no longer silently renumber classes between
    train/resume)."""
    import json

    parser = argparse.ArgumentParser(prog="spacy_ray_tpu init-labels")
    parser.add_argument("config_path", type=Path)
    parser.add_argument("output_dir", type=Path)
    parser.add_argument("--code", type=Path, default=None)
    parser.add_argument("--device", type=str, default="cpu",
                        choices=["tpu", "cpu", "gpu"],
                        help="label collection is host-side; cpu default")
    args, extra = parser.parse_known_args(argv)
    _setup_device(args.device)

    from .config import load_config, parse_cli_overrides
    from .registry import import_code, registry
    from .training.loop import resolve_dot_name, resolve_training

    import_code(str(args.code) if args.code else None)
    config = load_config(args.config_path, parse_cli_overrides(extra),
                         interpolate=False).interpolate()
    T = resolve_training(config)
    corpora_cfg = config.get("corpora", {})
    resolved = {n: registry.resolve(b) for n, b in corpora_cfg.items()}
    train_corpus = resolve_dot_name(config, resolved, T["train_corpus"])

    from .pipeline.language import LABEL_SAMPLE_LIMIT, Pipeline

    nlp = Pipeline.from_config(config)
    sample = []
    for i, eg in enumerate(train_corpus()):
        if i >= LABEL_SAMPLE_LIMIT:  # Pipeline.initialize's cap, shared
            break
        sample.append(eg)
    if not sample:
        print("Training corpus is empty — no labels to collect",
              file=sys.stderr)
        return 1
    args.output_dir.mkdir(parents=True, exist_ok=True)
    written = []
    for name in nlp.pipe_names:
        if name in nlp.sourced_components:
            # initialize ignores a labels override for sourced components
            # (their labels came with the saved model) — writing a file
            # here would advertise a pin that can never take effect
            print(f"[components.{name}] sourced: labels come with the "
                  "saved component; skipped")
            continue
        comp = nlp.components[name]
        comp.add_labels_from(sample)
        comp.finish_labels()
        if not comp.labels:
            continue  # host-only / label-free components have nothing to pin
        out = args.output_dir / f"{name}.json"
        out.write_text(json.dumps(comp.labels, indent=2) + "\n",
                       encoding="utf8")
        print(f"[components.{name}] {len(comp.labels)} labels -> {out}")
        written.append(name)
    if written:
        print(
            "Use in the config:\n"
            + "".join(
                f'[initialize.components.{name}]\nlabels = '
                f'"{args.output_dir / (name + ".json")}"\n'
                for name in written
            )
        )
    else:
        print("No component produced labels from this corpus")
    return 0


def debug_profile_command(argv: List[str]) -> int:
    """spaCy's `debug profile` surface: cProfile bulk inference over a
    corpus and print the hottest host-side functions. Device compute shows
    up as opaque `block_until_ready`/execute frames — use
    `train --profile` (jax.profiler) for the device-side picture; this
    command is for finding HOST bottlenecks (tokenization, collation,
    decode, annotation)."""
    import cProfile
    import pstats

    parser = argparse.ArgumentParser(prog="spacy_ray_tpu debug-profile")
    parser.add_argument("model_path", type=Path)
    parser.add_argument("data_path", type=Path)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--n-rows", type=int, default=25,
                        help="how many rows of the cumtime table to print")
    parser.add_argument("--device", type=str, default="tpu",
                        choices=["tpu", "cpu", "gpu"])
    args = parser.parse_args(argv)
    _setup_device(args.device)

    from .pipeline.language import Pipeline
    from .training.corpus import Corpus

    nlp = Pipeline.from_disk(args.model_path)
    examples = list(Corpus(args.data_path)())
    if not examples:
        print(f"No documents in {args.data_path}", file=sys.stderr)
        return 1
    docs = [eg.reference.copy_shell() for eg in examples]
    # un-profiled warmup pass: compile time would otherwise dominate the
    # table and hide the steady-state host cost
    nlp.predict_docs([d.copy_shell() for d in docs], batch_size=args.batch_size)

    profiler = cProfile.Profile()
    profiler.enable()
    try:
        nlp.predict_docs(docs, batch_size=args.batch_size)
    finally:
        # a raised predict must not leave the process-wide C profiling
        # hook installed (in-process callers: every later call runs
        # profiled and a second Profile().enable() raises)
        profiler.disable()
    stats = pstats.Stats(profiler, stream=sys.stdout)
    stats.strip_dirs().sort_stats("cumulative").print_stats(args.n_rows)
    return 0


def benchmark_command(argv: List[str]) -> int:
    """``benchmark speed`` / ``benchmark accuracy`` — spaCy's `spacy
    benchmark` surface. `speed` times bulk inference on a corpus with
    warmup, reporting median words/s over N repetitions with min/max (the
    same dispersion discipline as bench.py); `accuracy` is `evaluate`
    under its spaCy-CLI name."""
    import time

    if argv and argv[0] == "accuracy":
        return evaluate_command(argv[1:])
    if not argv or argv[0] != "speed":
        print("Usage: spacy_ray_tpu benchmark {speed,accuracy} "
              "<model> <data> ...", file=sys.stderr)
        return 1
    parser = argparse.ArgumentParser(prog="spacy_ray_tpu benchmark speed")
    parser.add_argument("model_path", type=Path)
    parser.add_argument("data_path", type=Path)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--n-reps", type=int, default=3)
    parser.add_argument("--warmup", type=int, default=1,
                        help="un-timed full passes first (compile + cache)")
    parser.add_argument("--device", type=str, default="tpu",
                        choices=["tpu", "cpu", "gpu"])
    args = parser.parse_args(argv[1:])
    _setup_device(args.device)

    from .pipeline.language import Pipeline
    from .training.corpus import Corpus

    nlp = Pipeline.from_disk(args.model_path)
    examples = list(Corpus(args.data_path)())
    if not examples:
        print(f"No documents in {args.data_path}", file=sys.stderr)
        return 1
    n_words = sum(len(eg.reference) for eg in examples)

    def one_pass():
        docs = [eg.reference.copy_shell() for eg in examples]
        t0 = time.perf_counter()
        nlp.predict_docs(docs, batch_size=args.batch_size)
        return time.perf_counter() - t0

    import statistics

    for _ in range(max(args.warmup, 0)):
        one_pass()
    rates = sorted(n_words / one_pass() for _ in range(max(args.n_reps, 1)))
    median = statistics.median(rates)
    print(
        f"Benchmark: {len(examples)} docs, {n_words} words, "
        f"batch_size={args.batch_size}, reps={len(rates)}"
    )
    print(
        f"words/s: median {median:,.0f}  min {rates[0]:,.0f}  "
        f"max {rates[-1]:,.0f}"
    )
    return 0


def telemetry_command(argv: List[str]) -> int:
    """``telemetry`` — offline and live observability tools, all jax-free
    (safe on any host):

    * ``summarize <metrics.jsonl | run-dir>`` — digest a telemetry file
      (training rows: step-time percentiles, device gauges, per-stage
      breakdown; serving rows: SLO window, rejects, by-generation split;
      trainer-fleet rows: counters, phase share, staleness digest;
      anomaly digest) or a whole fleet run directory;
    * ``top <url>...`` — live terminal dashboard polling ``/metrics`` on
      replica / router / trainer endpoints (req/s, window p50/p99,
      occupancy, queue depth, generation, swap count, anomalies);
    * ``collect-trace <url>... --out FILE`` — merge the Perfetto trace
      buffers of router, replicas (auto-discovered from a router URL),
      and trainer into ONE timeline file via their /healthz clock
      anchors (docs/OBSERVABILITY.md "Distributed tracing").
    * ``postmortem <dir>`` — render an incident bundle (an alert-fired
      flight-recorder dump or a crash postmortem) as a human-readable
      report: exit status/signal, config, stderr tail, alert states,
      metric digest, and a merged cross-process timeline built with the
      same clock-anchor merge collect-trace uses. Given the incidents
      ROOT, renders the newest bundle.
    * ``report <run-dir>`` — digest a training run directory (the
      trainer fleet's per-worker ledgers + metrics.jsonl files, or a
      single-process run's metrics.jsonl) into ONE markdown report:
      per-worker loss trajectories, the phase-share table,
      staleness/discard histograms, quorum-wait/apply timing, and the
      alert/anomaly timeline (docs/OBSERVABILITY.md "Training fleet").
    * ``ledger list|show|diff|regress`` — the run ledger: cross-run
      performance history from BENCH_SESSION.jsonl (and run dirs),
      normalized by (spec, platform, shape, config labels). ``diff``
      compares two records against their own noise evidence and
      refuses cross-platform pairs; ``regress`` judges fresh records
      against the latest clean committed baseline and exits nonzero
      only on a confirmed regression (docs/OBSERVABILITY.md "Host
      resources & the run ledger").
    """
    usage = ("Usage: spacy_ray_tpu telemetry "
             "{summarize <metrics.jsonl-or-run-dir> | top <url>... | "
             "collect-trace [<url>...] [--fleet-base-port N --workers K] "
             "--out FILE | "
             "postmortem <bundle-or-incidents-dir> | "
             "report <run-dir> [--out FILE] | "
             "ledger {list|show|diff|regress} [--session FILE] ...}")
    if not argv or argv[0] not in (
        "summarize", "top", "collect-trace", "postmortem", "report",
        "ledger",
    ):
        print(usage, file=sys.stderr)
        return 1
    sub, rest = argv[0], argv[1:]
    if sub == "ledger":
        parser = argparse.ArgumentParser(
            prog="spacy_ray_tpu telemetry ledger"
        )
        parser.add_argument("action",
                            choices=("list", "show", "diff", "regress"))
        parser.add_argument("selectors", nargs="*", metavar="SEL",
                            help="show: a record NAME; diff: exactly two "
                            "selectors, each NAME[@IDX] (chronological "
                            "index into that name's history, default -1 "
                            "= newest) or a path to a records .jsonl "
                            "(its last record); list: optional NAME "
                            "filters")
        parser.add_argument("--session", type=Path,
                            default=Path("BENCH_SESSION.jsonl"),
                            help="the committed bench session file — the "
                            "ledger's history (default "
                            "./BENCH_SESSION.jsonl)")
        parser.add_argument("--run-dir", type=Path, action="append",
                            default=[], dest="run_dirs",
                            help="also ingest a telemetry run directory "
                            "as ledger rows (repeatable)")
        parser.add_argument("--record", type=Path, default=None,
                            help="regress: fresh record file (jsonl) to "
                            "judge against the session history; without "
                            "it, each key's newest session record is "
                            "judged against its own predecessors")
        parser.add_argument("--floor", type=float, default=None,
                            help="noise-band floor as a ratio (default "
                            "0.05): deltas inside max(floor, rep "
                            "dispersion, reprobe slack) are never "
                            "verdicts")
        parser.add_argument("--json-out", type=Path, default=None,
                            help="diff/regress: also write the verdict "
                            "as JSON (the bench-gate CI artifact)")
        args = parser.parse_args(rest)

        from .training import runledger as rl

        floor = args.floor if args.floor is not None else rl.NOISE_FLOOR

        def _write_json(payload: dict) -> None:
            if args.json_out is None:
                return
            args.json_out.parent.mkdir(parents=True, exist_ok=True)
            args.json_out.write_text(
                json.dumps(payload, indent=2, sort_keys=True) + "\n",
                encoding="utf8",
            )
            print(f"verdict written to {args.json_out}", file=sys.stderr)

        def _pick(rows, sel: str):
            # a selector is either a records file (take its last row)
            # or NAME[@IDX] into the loaded history
            p = Path(sel)
            if p.is_file():
                file_rows, _ = rl.ingest_session(p)
                if not file_rows:
                    raise rl.LedgerError(f"no ledger rows in {sel}")
                return file_rows[-1]
            name, _, idx_s = sel.partition("@")
            hist = [r for r in rows if r["name"] == name]
            if not hist:
                raise rl.LedgerError(
                    f"no ledger rows named {name!r} "
                    f"(try: telemetry ledger list --session {args.session})"
                )
            try:
                return hist[int(idx_s) if idx_s else -1]
            except (IndexError, ValueError):
                raise rl.LedgerError(
                    f"bad index {idx_s!r} for {name!r} "
                    f"({len(hist)} record(s) in history)"
                )

        try:
            rows, skipped = rl.ingest_session(args.session)
            for rd in args.run_dirs:
                rows.extend(rl.ingest_run_dir(rd))
            if args.action == "list":
                if args.selectors:
                    rows = [r for r in rows if r["name"] in args.selectors]
                print(rl.render_rows(rows, skipped=skipped))
                return 0
            if args.action == "show":
                if len(args.selectors) != 1:
                    parser.error("show takes exactly one record NAME")
                print(rl.render_history(rows, args.selectors[0]))
                return 0
            if args.action == "diff":
                if len(args.selectors) != 2:
                    parser.error("diff takes exactly two selectors "
                                 "(NAME[@IDX] or a records file)")
                d = rl.diff_rows(
                    _pick(rows, args.selectors[0]),
                    _pick(rows, args.selectors[1]),
                    floor=floor,
                )
                print(rl.render_diff(d))
                _write_json(d)
                return 0
            # regress
            if args.record is not None:
                fresh, _ = rl.ingest_session(args.record)
                pool = rows
            else:
                by_key: dict = {}
                for r in rows:
                    by_key.setdefault(rl.row_key(r), []).append(r)
                fresh = [h[-1] for h in by_key.values()]
                pool = [r for h in by_key.values() for r in h[:-1]]
            if not fresh:
                print("no fresh records to judge", file=sys.stderr)
                return 2
            verdicts = rl.regress(fresh, pool, floor=floor)
            print(rl.render_verdicts(verdicts))
            _write_json({
                "floor": floor,
                "session": str(args.session),
                "verdicts": verdicts,
            })
            return 1 if any(
                v["verdict"] == "regression" for v in verdicts
            ) else 0
        except rl.LedgerError as e:
            print(str(e), file=sys.stderr)
            return 2
        except OSError as e:
            print(str(e), file=sys.stderr)
            return 2
    if sub == "report":
        parser = argparse.ArgumentParser(
            prog="spacy_ray_tpu telemetry report"
        )
        parser.add_argument("run_dir", type=Path,
                            help="a training run's output directory "
                            "(fleet-worker-*.json ledgers + metrics/, "
                            "or a plain metrics.jsonl run)")
        parser.add_argument("--metrics-dir", type=Path, default=None,
                            dest="metrics_dir",
                            help="where the run's telemetry landed "
                            "(default: <run-dir>/metrics)")
        parser.add_argument("--out", type=Path, default=None,
                            help="also write the markdown report here")
        args = parser.parse_args(rest)

        from .training.report import build_run_report

        try:
            report = build_run_report(args.run_dir, args.metrics_dir)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 1
        except OSError as e:
            print(f"Cannot read {args.run_dir}: {e}", file=sys.stderr)
            return 1
        print(report)
        if args.out is not None:
            try:
                args.out.parent.mkdir(parents=True, exist_ok=True)
                args.out.write_text(report, encoding="utf8")
            except OSError as e:
                print(f"Cannot write {args.out}: {e}", file=sys.stderr)
                return 1
            print(f"run report written to {args.out}", file=sys.stderr)
        return 0
    if sub == "postmortem":
        parser = argparse.ArgumentParser(
            prog="spacy_ray_tpu telemetry postmortem"
        )
        parser.add_argument("bundle", type=Path,
                            help="an incident bundle directory "
                            "(incidents/<stamp>-<source>/) or the "
                            "incidents root (newest bundle is rendered)")
        parser.add_argument("--trace-out", type=Path, default=None,
                            help="also write the bundle's merged "
                            "cross-process Chrome trace here (open in "
                            "ui.perfetto.dev)")
        args = parser.parse_args(rest)

        from .incidents import (
            find_bundle,
            load_bundle,
            merged_bundle_trace,
            render_bundle,
        )

        try:
            # load ONCE: the report and the optional --trace-out merge
            # share the same loaded bundle (flight files can be MBs)
            bundle = load_bundle(find_bundle(args.bundle))
            print(render_bundle(bundle))
        except FileNotFoundError as e:
            print(str(e), file=sys.stderr)
            return 1
        except (OSError, ValueError) as e:
            print(f"Cannot render {args.bundle}: {e}", file=sys.stderr)
            return 1
        if args.trace_out is not None:
            from .serving.tracecollect import write_merged_trace

            try:
                merged = merged_bundle_trace(bundle)
                path = write_merged_trace(merged, args.trace_out)
            except OSError as e:
                print(
                    f"Cannot write {args.trace_out}: {e}", file=sys.stderr
                )
                return 1
            print(f"merged bundle trace written to {path}")
        return 0
    if sub == "summarize":
        parser = argparse.ArgumentParser(
            prog="spacy_ray_tpu telemetry summarize"
        )
        parser.add_argument("metrics_path", type=Path,
                            help="metrics.jsonl written by a [training] "
                            "metrics_dir / train --metrics-dir run or a "
                            "serve --metrics-dir run — or a trainer-fleet "
                            "RUN DIRECTORY (fleet-worker-*.json ledgers "
                            "+ metrics/fleet-worker-*/metrics.jsonl)")
        args = parser.parse_args(rest)

        from .training.telemetry import summarize_metrics

        try:
            print(summarize_metrics(args.metrics_path))
        except OSError as e:
            # FileNotFound, IsADirectory (the metrics DIR), permissions
            print(f"Cannot read {args.metrics_path}: {e}", file=sys.stderr)
            return 1
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 1
        return 0
    if sub == "top":
        parser = argparse.ArgumentParser(prog="spacy_ray_tpu telemetry top")
        parser.add_argument("urls", nargs="+", metavar="URL",
                            help="endpoint base URLs (router, replica, or "
                            "trainer --metrics-port), e.g. "
                            "http://127.0.0.1:8090")
        parser.add_argument("--interval-s", type=float, default=2.0)
        parser.add_argument("--iterations", type=int, default=None,
                            help="stop after N refreshes (default: until "
                            "Ctrl-C)")
        args = parser.parse_args(rest)

        from .top import run_top

        return run_top(
            args.urls, interval_s=args.interval_s,
            iterations=args.iterations,
        )
    parser = argparse.ArgumentParser(
        prog="spacy_ray_tpu telemetry collect-trace"
    )
    parser.add_argument("urls", nargs="*", metavar="URL",
                        help="endpoint base URLs; a fleet router URL "
                        "auto-discovers its replicas")
    parser.add_argument("--out", type=Path, required=True,
                        help="merged Chrome-trace JSON output path "
                        "(open in ui.perfetto.dev)")
    parser.add_argument("--no-discover", action="store_true",
                        help="do not expand a router URL into its "
                        "replicas")
    parser.add_argument("--fleet-base-port", type=int, default=None,
                        dest="fleet_base_port",
                        help="TRAINER fleet: scrape worker k's endpoint "
                        "at <fleet-host>:base+k for k in 0..workers-1 "
                        "(a trainer fleet has no router to discover "
                        "through; matches train --fleet-base-port)")
    parser.add_argument("--workers", type=int, default=None,
                        help="trainer fleet worker count (with "
                        "--fleet-base-port)")
    parser.add_argument("--fleet-host", default="127.0.0.1",
                        dest="fleet_host",
                        help="trainer fleet host (default 127.0.0.1)")
    args = parser.parse_args(rest)

    from .serving.tracecollect import (
        collect_fleet_traces,
        fleet_worker_urls,
        write_merged_trace,
    )

    urls = list(args.urls)
    if (args.fleet_base_port is None) != (args.workers is None):
        parser.error("--fleet-base-port and --workers go together")
    if args.workers is not None and args.workers <= 0:
        parser.error(f"--workers must be positive, got {args.workers}")
    if args.fleet_base_port is not None:
        urls.extend(
            fleet_worker_urls(
                args.fleet_base_port, args.workers, host=args.fleet_host
            )
        )
    if not urls:
        parser.error(
            "give endpoint URLs, or --fleet-base-port N --workers K "
            "for a trainer fleet"
        )
    merged = collect_fleet_traces(urls, discover=not args.no_discover)
    info = merged.get("otherData") or {}
    if not info.get("merged_from"):
        print(
            "no traces collected "
            f"(skipped: {info.get('skipped')}) — are the endpoints up "
            "with telemetry enabled?",
            file=sys.stderr,
        )
        return 1
    path = write_merged_trace(merged, args.out)
    n = sum(
        1 for e in merged["traceEvents"] if e.get("ph") != "M"
    )
    print(
        f"merged {n} event(s) from {len(info['merged_from'])} process(es) "
        f"into {path}"
        + (f" (skipped: {info['skipped']})" if info.get("skipped") else "")
    )
    return 0


def serve_command(argv: List[str]) -> int:
    """``serve`` — online inference over HTTP with dynamic micro-batching
    (docs/SERVING.md): load a saved pipeline, warm the (B, T) bucket
    programs, then serve ``/v1/parse`` until SIGTERM, which triggers a
    graceful drain (stop admitting, finish in-flight batches, exit 0)."""
    from .serving.engine import SERVING_DEFAULTS

    parser = argparse.ArgumentParser(
        prog="spacy_ray_tpu serve",
        description="Serve a saved pipeline as a JSON HTTP API "
        "(/v1/parse, /healthz, /metrics) with dynamic micro-batching.",
    )
    parser.add_argument("model_path", type=Path)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8080,
                        help="0 = ephemeral; the bound port is printed in "
                        "the 'serving on http://...' banner")
    parser.add_argument("--device", type=str, default="tpu",
                        choices=["tpu", "cpu", "gpu"])
    parser.add_argument("--max-batch", type=int,
                        default=SERVING_DEFAULTS["max_batch_docs"],
                        help="max docs coalesced into one device batch")
    parser.add_argument("--batching",
                        choices=["continuous", "window"],
                        default=SERVING_DEFAULTS["batching"],
                        help="admission discipline: 'continuous' (default) "
                        "admits queued requests into the next dispatch's "
                        "free slots immediately — the in-flight batch is "
                        "the coalescing window; 'window' is the classic "
                        "size-or-deadline rule bounded by --window-ms")
    parser.add_argument("--continuous", action="store_const",
                        const="continuous", dest="batching",
                        help="alias for --batching continuous")
    parser.add_argument("--max-wait-ms", "--window-ms", type=float,
                        dest="max_wait_ms",
                        default=SERVING_DEFAULTS["max_wait_s"] * 1e3,
                        help="window mode only: coalescing window from the "
                        "first queued request (added latency bound); "
                        "ignored under continuous admission")
    parser.add_argument("--precision",
                        choices=["auto", "f32", "bf16", "int8"],
                        default=SERVING_DEFAULTS["precision"],
                        help="serving precision overlay (docs/SERVING.md): "
                        "'auto' arms a bf16 trunk overlay on accelerators "
                        "and resolves f32 on CPU (emulated bf16 is a "
                        "measured pessimization there); 'bf16' forces the "
                        "overlay; 'int8' arms the weight-only pallas "
                        "dequant-in-kernel overlay where the probe "
                        "passes (TPU; CPU only under SRT_PALLAS_INT8=1, "
                        "interpret-mode) and serves f32 with an honest "
                        "refusal label everywhere else")
    parser.add_argument("--queue-size", type=int,
                        default=SERVING_DEFAULTS["max_queue_docs"],
                        help="bounded admission queue (docs); beyond it "
                        "requests are rejected 429")
    parser.add_argument("--timeout-ms", type=float,
                        default=SERVING_DEFAULTS["timeout_s"] * 1e3,
                        help="default per-request deadline (clients may "
                        "lower it per call via timeout_ms)")
    parser.add_argument("--max-doc-len", type=int,
                        default=SERVING_DEFAULTS["max_doc_len"],
                        help="longest admissible doc in tokens (the warmed "
                        "shape cap; longer docs are rejected 413)")
    parser.add_argument("--drain-timeout-s", type=float, default=30.0)
    parser.add_argument("--watch", type=Path, default=None, metavar="CKPT_DIR",
                        help="live continuous learning (docs/SERVING.md): "
                        "poll this TrainCheckpoint directory (a training "
                        "run's <output>/last-model) and hot-swap each new "
                        "digest-verified generation at a dispatch boundary "
                        "— zero dropped requests, torn generations "
                        "skipped, instant rollback via POST /admin/rollback")
    parser.add_argument("--watch-interval-s", type=float, default=2.0,
                        help="checkpoint-directory poll interval")
    parser.add_argument("--swap-dir", type=Path, action="append",
                        default=[], dest="swap_dirs", metavar="CKPT_DIR",
                        help="checkpoint directory POST /admin/swap may "
                        "load generations from (repeatable; --watch is "
                        "allowed implicitly). With neither, admin swaps "
                        "are refused 403 — an open port must not accept "
                        "arbitrary client-supplied weight paths")
    parser.add_argument("--no-warmup", action="store_true",
                        help="skip the bucket compile sweep (first requests "
                        "then pay compiles — testing only)")
    parser.add_argument("--model-manifest", type=Path, default=None,
                        help="multi-model serving (docs/SERVING.md "
                        "'Multi-model fleet'): a JSON manifest of model "
                        "name -> pipeline dir (plus SLO classes and tenant "
                        "quotas). Requests route by /v1/models/<name>/parse "
                        "or the X-SRT-Model header; /v1/parse keeps serving "
                        "the manifest's default model. The positional "
                        "model_path is ignored — the manifest's default "
                        "model path is authoritative")
    parser.add_argument("--resident-models", type=int, default=2,
                        help="multi-model only: how many warmed engines "
                        "this replica keeps resident at once (LRU eviction "
                        "past this; the default model is pinned)")
    parser.add_argument("--no-telemetry", action="store_true",
                        help="disable the SLO metrics/trace surface "
                        "entirely (zero telemetry calls; /metrics reports "
                        "disabled)")
    parser.add_argument("--metrics-dir", type=Path, default=None,
                        help="write serving_trace.json + a final metrics "
                        "snapshot here on shutdown")
    parser.add_argument("--incidents-dir", type=Path, default=None,
                        help="arm the flight recorder (docs/OBSERVABILITY.md "
                        "'Alerting & incidents'): when an alert fires, the "
                        "recent metric-snapshot ring + span ring are dumped "
                        "to <dir>/<utc-stamp>-<source>/ for `telemetry "
                        "postmortem`; alert transitions append to "
                        "<dir>/alerts.jsonl")
    parser.add_argument("--blackbox", type=Path, default=None,
                        help="persist the flight-recorder payload to this "
                        "file (atomic replace, rate-limited to ~10s between "
                        "rewrites — crash evidence may lag by up to that) — "
                        "the SIGKILL-survivable copy a fleet supervisor "
                        "folds into the crash postmortem bundle")
    parser.add_argument("--alert-p99-ms", type=float, default=500.0,
                        help="sliding-window p99 target the default "
                        "'serving-latency-slo' alert rule fires against "
                        "(the error-budget burn-rate rule is independent "
                        "of it)")
    parser.add_argument("--observe-interval-s", type=float, default=2.0,
                        help="cadence of the diagnosis tick (alert rule "
                        "evaluation, flight-recorder ring feed, black-box "
                        "persistence)")
    parser.add_argument("--verbose", "-V", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.ERROR)
    logging.getLogger("spacy_ray_tpu.training").setLevel(
        logging.INFO if args.verbose else logging.WARNING
    )
    _setup_device(args.device)

    from .pipeline.language import Pipeline
    from .serving.engine import InferenceEngine, ServingTelemetry
    from .serving.server import Server

    # multi-model serving: registry + admission from the manifest; the
    # residency manager owns every engine beyond the pinned default
    registry = None
    residency = None
    admission = None
    if args.model_manifest is not None:
        from .serving.multimodel import (
            AdmissionController,
            ModelRegistry,
            ResidencyManager,
        )

        registry = ModelRegistry.from_manifest(args.model_manifest)
        admission = AdmissionController(registry)

    class_weights = (
        registry.class_weights() if registry is not None else None
    )

    def _build_engine(path: Path, mtel) -> "InferenceEngine":
        return InferenceEngine(
            Pipeline.from_disk(path),
            max_batch_docs=args.max_batch,
            max_wait_s=max(args.max_wait_ms, 0.0) / 1e3,
            max_queue_docs=args.queue_size,
            timeout_s=max(args.timeout_ms, 1.0) / 1e3,
            max_doc_len=args.max_doc_len,
            batching=args.batching,
            precision=args.precision,
            telemetry=mtel,
            class_weights=class_weights,
        )

    default_path = args.model_path
    if registry is not None:
        default_path = Path(registry.spec(registry.default_model).path)
    tel = None if args.no_telemetry else ServingTelemetry()
    engine = _build_engine(default_path, tel)
    if registry is not None:

        def _engine_factory(spec) -> "InferenceEngine":
            # each resident model gets its OWN telemetry (per-model
            # /metrics blocks) and its own warmed bucket programs —
            # loads happen on a request thread, never the dispatch one
            mtel = None if args.no_telemetry else ServingTelemetry()
            e = _build_engine(Path(spec.path), mtel)
            if not args.no_warmup:
                e.warmup()
            e.start()
            return e

        residency = ResidencyManager(
            registry,
            _engine_factory,
            capacity=max(args.resident_models, 1),
            evict_drain_s=min(args.drain_timeout_s, 10.0),
            pinned={registry.default_model},
        )
        # the default engine is adopted, not factory-loaded: the server
        # lifecycle warms and starts it (listener-first banner intact)
        residency.adopt(registry.default_model, engine)
    print(f"serving batching={engine.batching} "
          f"precision={engine.overlay.label}"
          + (
              f" models={','.join(registry.names())} "
              f"default={registry.default_model}"
              if registry is not None else ""
          ),
          flush=True)
    watcher = None
    if args.watch is not None:
        from .serving.live import CheckpointWatcher

        def _swap(stamp: int, state: dict, _engine=engine) -> None:
            _engine.swap_params(state["params"], stamp, source="watch")

        watcher = CheckpointWatcher(
            args.watch, _swap, interval_s=args.watch_interval_s
        )
    # diagnosis layer: AlertEngine always rides along with telemetry
    # (alert state is a handful of floats); the FlightRecorder only when
    # an incidents dir / black box is configured. With --no-telemetry
    # NEITHER is constructed — zero rule evaluations, zero ring writes,
    # zero incident I/O (guard-tested).
    alerts = None
    recorder = None
    if tel is not None:
        from .alerting import AlertEngine, default_serving_rules
        from .incidents import FlightRecorder

        if args.incidents_dir is not None or args.blackbox is not None:
            recorder = FlightRecorder(
                incident_dir=args.incidents_dir,
                blackbox_path=args.blackbox,
                process_name=f"replica-pid{os.getpid()}",
            )
        alerts = AlertEngine(
            default_serving_rules(p99_target_s=args.alert_p99_ms / 1e3),
            sink_path=(
                args.incidents_dir / "alerts.jsonl"
                if args.incidents_dir is not None else None
            ),
            on_firing=(
                recorder.alert_hook() if recorder is not None else None
            ),
            source="replica",
        )
        if recorder is not None:
            recorder.attach(
                trace=tel.trace,
                alerts_fn=alerts.states,
                exemplars_fn=tel.exemplars,
            )
    server = Server(
        engine, args.host, args.port,
        telemetry=tel, drain_timeout_s=args.drain_timeout_s,
        watcher=watcher, swap_dirs=[str(d) for d in args.swap_dirs],
        alerts=alerts, recorder=recorder,
        observe_interval_s=args.observe_interval_s,
        registry=registry, residency=residency, admission=admission,
    )
    # listener-first: the banner (and thus the bound port) appears before
    # the warmup sweep, so a fleet supervisor can probe /healthz — which
    # reports 503 "warming" until every bucket program is compiled
    rc = server.run(warmup_engine=not args.no_warmup)
    if tel is not None and args.metrics_dir is not None:
        import json
        import time as _time

        args.metrics_dir.mkdir(parents=True, exist_ok=True)
        tel.trace.flush(args.metrics_dir / "serving_trace.json")
        from .training.telemetry import sanitize_json

        snap = tel.snapshot()
        snap["generation"] = engine.serving_generation
        snap["swap_count"] = engine.swap_count
        (args.metrics_dir / "serving_metrics.json").write_text(
            json.dumps(sanitize_json(snap), indent=2) + "\n",
            encoding="utf8",
        )
        # the same snapshot as a `kind: "serving"` row in metrics.jsonl,
        # so `telemetry summarize` digests serving runs with the exact
        # file contract training runs use
        with open(
            args.metrics_dir / "metrics.jsonl", "a", encoding="utf8"
        ) as f:
            f.write(json.dumps(sanitize_json(
                {"kind": "serving", "unix_time": _time.time(), **snap}
            )) + "\n")
        print(f"serving telemetry written to {args.metrics_dir}", flush=True)
    if rc == 0:
        print("drained; exiting 0", flush=True)
    else:
        # the failure path must not carry the success word: in-flight
        # work was abandoned at the drain timeout
        print(f"drain timed out after {args.drain_timeout_s:.0f}s — "
              f"in-flight work abandoned; exiting {rc}", flush=True)
    return rc


def serve_fleet_command(argv: List[str]) -> int:
    """``serve-fleet`` — horizontally-scaled serving (docs/SERVING.md
    "Fleet"): a router process load-balancing ``/v1/parse`` over N
    ``serve`` replica subprocesses with health-probed rotation, crash
    restarts with backoff, optional SLO-driven autoscaling, and a
    fleet-wide SIGTERM drain (router stops admitting, replicas finish
    in-flight work, exit 0).

    This process never imports jax — it only spawns, probes, and proxies;
    every device interaction lives in the replicas."""
    parser = argparse.ArgumentParser(
        prog="spacy_ray_tpu serve-fleet",
        description="Serve a saved pipeline from N engine replicas behind "
        "one load-balancing router (/v1/parse, /healthz, /metrics).",
    )
    parser.add_argument("model_path", type=Path)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8090,
                        help="router port (0 = ephemeral; printed in the "
                        "'fleet serving on http://...' banner)")
    parser.add_argument("--device", type=str, default="tpu",
                        choices=["tpu", "cpu", "gpu"],
                        help="device each replica pins (replicas are "
                        "separate processes; see --visible-devices)")
    parser.add_argument("--replicas", type=int, default=2,
                        help="initial replica count")
    parser.add_argument("--min-replicas", type=int, default=1)
    parser.add_argument("--max-replicas", type=int, default=4)
    parser.add_argument("--base-port", type=int, default=0,
                        help="replica ports: 0 = ephemeral (parsed from "
                        "each replica's banner), N = N + replica_id")
    parser.add_argument("--visible-devices", type=str, default=None,
                        help="comma-separated visible-device masks cycled "
                        "per replica (sets CUDA_VISIBLE_DEVICES or "
                        "--visible-devices-env in each replica's env)")
    parser.add_argument("--visible-devices-env", type=str,
                        default="CUDA_VISIBLE_DEVICES")
    parser.add_argument("--cpu-cores", type=str, default=None,
                        help="--device cpu only: 'auto' or comma-separated "
                        "taskset -c core masks cycled per replica (e.g. "
                        "'0-3,4-7' gives replica 0 cores 0-3). The CPU "
                        "value of --visible-devices: without masks, "
                        "co-scheduled replicas each spawn an nproc-wide "
                        "XLA pool and thrash (measured NEGATIVE scaling); "
                        "'auto' resolves to one core per replica, "
                        "round-robin over this process's affinity set")
    # per-replica serving knobs, passed through to each `serve` child
    parser.add_argument("--max-batch", type=int, default=None)
    parser.add_argument("--max-wait-ms", "--window-ms", type=float,
                        dest="max_wait_ms", default=None)
    parser.add_argument("--queue-size", type=int, default=None)
    parser.add_argument("--timeout-ms", type=float, default=None)
    parser.add_argument("--max-doc-len", type=int, default=None)
    parser.add_argument("--batching",
                        choices=["continuous", "window"], default=None,
                        help="replica admission discipline (None = the "
                        "serve default, continuous)")
    parser.add_argument("--continuous", action="store_const",
                        const="continuous", dest="batching",
                        help="alias for --batching continuous")
    parser.add_argument("--precision",
                        choices=["auto", "f32", "bf16", "int8"], default=None,
                        help="replica serving precision overlay (None = "
                        "the serve default, auto — bf16 on accelerators, "
                        "f32 on CPU)")
    parser.add_argument("--model-manifest", type=Path, default=None,
                        help="multi-model fleet (docs/SERVING.md "
                        "'Multi-model fleet'): every replica serves the "
                        "models in this JSON manifest (name -> pipeline "
                        "dir, SLO classes, tenant quotas); the router "
                        "resolves /v1/models/<name>/parse and X-SRT-Model "
                        "and routes within the replicas hosting the model. "
                        "The positional model_path is ignored by replicas "
                        "— the manifest is authoritative")
    parser.add_argument("--resident-models", type=int, default=None,
                        help="multi-model only: per-replica warmed-engine "
                        "hot-set size (LRU eviction past it; the default "
                        "model is pinned)")
    # router knobs
    parser.add_argument("--cache-mb", type=float, default=32.0,
                        help="router response cache budget in MB, keyed by "
                        "input-text hash and stamped with the serving "
                        "generation (default ON at 32MB — heavy real "
                        "traffic is Zipfian; 0 = off); hit/miss/stale/"
                        "bypass counters in /metrics")
    parser.add_argument("--probe-interval-s", type=float, default=0.5,
                        help="how often the router re-probes each "
                        "replica's /healthz")
    parser.add_argument("--length-routing", action="store_true",
                        help="length-bucket affinity routing: steer "
                        "similar doc lengths to the same replica (within "
                        "the least-outstanding/model-hosting candidates) "
                        "so device batches fill their bucket instead of "
                        "padding to the longest straggler; pays on skewed "
                        "length mixtures with >1 replica (TUNING.md §24); "
                        "pad share lands in /metrics as "
                        "srt_serving_pad_tokens_total / "
                        "srt_serving_real_tokens_total")
    # live continuous learning (docs/SERVING.md "Continuous learning",
    # TUNING.md §14)
    parser.add_argument("--watch", type=Path, default=None,
                        metavar="CKPT_DIR",
                        help="poll this TrainCheckpoint directory (a "
                        "training run's <output>/last-model); each new "
                        "digest-verified generation is canaried onto "
                        "--canary-fraction of the replicas (router splits "
                        "traffic by generation), then promoted fleet-wide "
                        "or auto-rolled-back by the guard")
    parser.add_argument("--watch-interval-s", type=float, default=2.0)
    parser.add_argument("--canary-fraction", type=float, default=0.25,
                        help="fraction of replicas (and of traffic) a new "
                        "generation canaries on before promote/rollback; "
                        "<=0 or >=1 disables the canary phase (direct "
                        "rollout to every replica)")
    parser.add_argument("--guard-p99-frac", type=float, default=1.5,
                        help="rollback when canary window p99 exceeds this "
                        "multiple of the baseline's")
    parser.add_argument("--guard-error-rate", type=float, default=0.02,
                        help="rollback when the canary's error rate "
                        "exceeds this (and the baseline's)")
    parser.add_argument("--guard-min-samples", type=int, default=20,
                        help="minimum canary requests / window samples "
                        "before any verdict")
    parser.add_argument("--guard-verdict-timeout-s", type=float,
                        default=120.0,
                        help="a canary with no verdict after this long is "
                        "rolled back (ship on evidence, not silence)")
    # autoscaler knobs (TUNING.md §12)
    parser.add_argument("--autoscale", action="store_true",
                        help="enable the SLO-driven autoscaler (scale "
                        "between --min/--max-replicas on p99 vs "
                        "--p99-target-ms and queue pressure)")
    parser.add_argument("--p99-target-ms", type=float, default=500.0)
    parser.add_argument("--autoscale-interval-s", type=float, default=2.0)
    parser.add_argument("--up-consecutive", type=int, default=3,
                        help="breaching observations required to scale up")
    parser.add_argument("--down-consecutive", type=int, default=10,
                        help="idle observations required to scale down")
    parser.add_argument("--cooldown-s", type=float, default=30.0,
                        help="minimum seconds between scaling decisions")
    parser.add_argument("--drain-timeout-s", type=float, default=60.0,
                        help="fleet drain budget: router in-flight wait + "
                        "per-replica graceful stop")
    parser.add_argument("--ready-timeout-s", type=float, default=300.0)
    parser.add_argument("--incidents-dir", type=Path, default=None,
                        help="arm the fleet-wide flight recorder "
                        "(docs/OBSERVABILITY.md 'Alerting & incidents'): "
                        "alert firings dump router/replica flight bundles "
                        "here, every replica persists a SIGKILL-survivable "
                        "black box under <dir>/blackbox/, and a crashed "
                        "replica leaves a crash postmortem bundle (exit "
                        "signal, stderr tail, config, generation, pre-crash "
                        "span ring) readable via `telemetry postmortem`")
    parser.add_argument("--observe-interval-s", type=float, default=2.0,
                        help="cadence of the diagnosis tick (alert rule "
                        "evaluation + flight-recorder ring feed)")
    parser.add_argument("--no-telemetry", action="store_true",
                        help="disable router + replica telemetry (zero "
                        "telemetry calls fleet-wide)")
    parser.add_argument("--verbose", "-V", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.ERROR)
    for name in ("spacy_ray_tpu.training", "spacy_ray_tpu.serving"):
        logging.getLogger(name).setLevel(
            logging.INFO if args.verbose else logging.WARNING
        )
    if args.min_replicas < 1 or args.replicas < 1:
        print("--replicas/--min-replicas must be >= 1", file=sys.stderr)
        return 2
    if not (args.min_replicas <= args.replicas <= args.max_replicas):
        print(
            f"--replicas {args.replicas} must lie within --min-replicas "
            f"{args.min_replicas} .. --max-replicas {args.max_replicas}",
            file=sys.stderr,
        )
        return 2

    from .serving.fleet import Fleet, FleetConfig

    cpu_cores: Optional[List[str]] = None
    if args.cpu_cores:
        if args.device != "cpu":
            print("--cpu-cores only applies to --device cpu; ignoring",
                  file=sys.stderr)
        elif args.cpu_cores.strip().lower() == "auto":
            cpu_cores = [str(c) for c in sorted(os.sched_getaffinity(0))]
        else:
            cpu_cores = [m.strip() for m in args.cpu_cores.split(",")
                         if m.strip()]

    config = FleetConfig(
        model_path=str(args.model_path),
        host=args.host,
        port=args.port,
        device=args.device,
        replicas=args.replicas,
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        queue_size=args.queue_size,
        timeout_ms=args.timeout_ms,
        max_doc_len=args.max_doc_len,
        batching=args.batching,
        precision=args.precision,
        model_manifest=(
            str(args.model_manifest)
            if args.model_manifest is not None else None
        ),
        resident_models=args.resident_models,
        base_port=args.base_port,
        visible_devices=(
            [m.strip() for m in args.visible_devices.split(",") if m.strip()]
            if args.visible_devices else None
        ),
        visible_devices_env=args.visible_devices_env,
        cpu_cores=cpu_cores,
        cache_mb=args.cache_mb,
        probe_interval_s=args.probe_interval_s,
        length_routing=args.length_routing,
        watch_dir=str(args.watch) if args.watch is not None else None,
        watch_interval_s=args.watch_interval_s,
        canary_fraction=args.canary_fraction,
        guard_p99_frac=args.guard_p99_frac,
        guard_error_rate=args.guard_error_rate,
        guard_min_samples=args.guard_min_samples,
        guard_verdict_timeout_s=args.guard_verdict_timeout_s,
        autoscale=args.autoscale,
        p99_target_ms=args.p99_target_ms,
        autoscale_interval_s=args.autoscale_interval_s,
        up_consecutive=args.up_consecutive,
        down_consecutive=args.down_consecutive,
        cooldown_s=args.cooldown_s,
        drain_timeout_s=args.drain_timeout_s,
        ready_timeout_s=args.ready_timeout_s,
        incidents_dir=(
            str(args.incidents_dir)
            if args.incidents_dir is not None else None
        ),
        observe_interval_s=args.observe_interval_s,
        telemetry=not args.no_telemetry,
    )
    rc = Fleet(config).run()
    if rc == 0:
        print("fleet drained; exiting 0", flush=True)
    else:
        print("fleet drain incomplete (router timeout or nonzero replica "
              f"exit) — exiting {rc}", flush=True)
    return rc


def train_and_serve_command(argv: List[str]) -> int:
    """``train-and-serve`` — the continuous-learning loop as one command
    (docs/SERVING.md "Continuous learning"): spawn a ``train`` subprocess
    writing checkpoint generations into ``<output>/last-model``, and a
    serving fleet that watches that directory and hot-swaps each new
    digest-verified generation (canary + guard when replicas > 1)
    without dropping a request. SIGTERM drains BOTH: the trainer
    checkpoints out (exit 75 = preempted-clean), the fleet finishes
    in-flight work — exit 0 iff both were clean."""
    parser = argparse.ArgumentParser(
        prog="spacy_ray_tpu train-and-serve",
        description="Run training and a hot-swapping serving fleet "
        "against one checkpoint directory, under one lifecycle.",
    )
    parser.add_argument("config_path", type=Path)
    parser.add_argument("--output", "-o", type=Path, required=True,
                        help="training output dir; the fleet watches "
                        "<output>/last-model for generations")
    parser.add_argument("--model", type=Path, default=None,
                        help="serve this model dir from t=0 (e.g. the "
                        "previous run's best-model). Default: wait for "
                        "this run's first best-model save and bootstrap "
                        "from a snapshot of it")
    parser.add_argument("--bootstrap-timeout-s", type=float, default=600.0,
                        help="--model unset: how long to wait for the "
                        "first best-model save before giving up")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8090)
    parser.add_argument("--device", type=str, default="tpu",
                        choices=["tpu", "cpu", "gpu"],
                        help="device for the trainer AND each serving "
                        "replica (separate processes; on one-device "
                        "hosts run --device cpu serving next to an "
                        "accelerator trainer via --serve-device)")
    parser.add_argument("--serve-device", type=str, default=None,
                        choices=["tpu", "cpu", "gpu"],
                        help="override the replicas' device (default: "
                        "--device)")
    parser.add_argument("--replicas", type=int, default=1)
    parser.add_argument("--base-port", type=int, default=0)
    parser.add_argument("--cpu-cores", type=str, default=None,
                        help="serve-fleet's --cpu-cores, applied to the "
                        "replicas ('auto' = one core per replica)")
    parser.add_argument("--max-batch", type=int, default=None)
    parser.add_argument("--max-doc-len", type=int, default=None)
    parser.add_argument("--batching",
                        choices=["continuous", "window"], default=None)
    parser.add_argument("--precision",
                        choices=["auto", "f32", "bf16", "int8"], default=None)
    parser.add_argument("--watch-interval-s", type=float, default=2.0)
    parser.add_argument("--canary-fraction", type=float, default=0.25)
    parser.add_argument("--guard-p99-frac", type=float, default=1.5)
    parser.add_argument("--guard-error-rate", type=float, default=0.02)
    parser.add_argument("--guard-min-samples", type=int, default=20)
    parser.add_argument("--guard-verdict-timeout-s", type=float,
                        default=120.0)
    parser.add_argument("--drain-timeout-s", type=float, default=60.0)
    parser.add_argument("--no-telemetry", action="store_true")
    parser.add_argument("--train-arg", action="append", default=[],
                        dest="train_args", metavar="ARG",
                        help="extra argument appended to the train "
                        "subprocess command (repeatable), e.g. "
                        "--train-arg=--max-restarts --train-arg=2")
    parser.add_argument("--verbose", "-V", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.ERROR)
    for name in ("spacy_ray_tpu.training", "spacy_ray_tpu.serving"):
        logging.getLogger(name).setLevel(
            logging.INFO if args.verbose else logging.WARNING
        )
    serve_device = args.serve_device or args.device

    from .serving.fleet import FleetConfig
    from .serving.live import TrainAndServe

    cpu_cores: Optional[List[str]] = None
    if args.cpu_cores and serve_device == "cpu":
        if args.cpu_cores.strip().lower() == "auto":
            cpu_cores = [str(c) for c in sorted(os.sched_getaffinity(0))]
        else:
            cpu_cores = [m.strip() for m in args.cpu_cores.split(",")
                         if m.strip()]

    output = args.output
    train_cmd = [
        sys.executable, "-m", "spacy_ray_tpu", "train",
        str(args.config_path), "--output", str(output),
        "--device", args.device,
    ] + list(args.train_args)
    train_env = {"JAX_PLATFORMS": "cpu"} if args.device == "cpu" else None

    config = FleetConfig(
        model_path=str(args.model) if args.model is not None else "",
        host=args.host,
        port=args.port,
        device=serve_device,
        replicas=args.replicas,
        min_replicas=1,
        max_replicas=max(args.replicas, 1),
        max_batch=args.max_batch,
        max_doc_len=args.max_doc_len,
        batching=args.batching,
        precision=args.precision,
        base_port=args.base_port,
        cpu_cores=cpu_cores,
        watch_dir=str(output / "last-model"),
        watch_interval_s=args.watch_interval_s,
        canary_fraction=args.canary_fraction,
        guard_p99_frac=args.guard_p99_frac,
        guard_error_rate=args.guard_error_rate,
        guard_min_samples=args.guard_min_samples,
        guard_verdict_timeout_s=args.guard_verdict_timeout_s,
        drain_timeout_s=args.drain_timeout_s,
        telemetry=not args.no_telemetry,
    )
    rc = TrainAndServe(
        train_cmd,
        config,
        output_dir=output,
        train_env=train_env,
        bootstrap_timeout_s=args.bootstrap_timeout_s,
    ).run()
    if rc == 0:
        print("train-and-serve: exiting 0", flush=True)
    else:
        print(f"train-and-serve: incomplete drain or trainer failure — "
              f"exiting {rc}", flush=True)
    return rc


def _project_command(argv: List[str]) -> int:
    """spaCy-projects-style workflow runner (`project run` / `project
    document`); implementation in project.py."""
    from .project import main as project_main

    return project_main(argv)


COMMANDS = {
    "train": train_command,
    "pretrain": pretrain_command,
    "parse": parse_command,
    # spaCy's name for bulk annotation; same command, correctly-named help
    "apply": lambda argv: parse_command(argv, prog="apply"),
    "debug-profile": debug_profile_command,
    "serve": serve_command,
    "serve-fleet": serve_fleet_command,
    "train-and-serve": train_and_serve_command,
    "telemetry": telemetry_command,
    "find-threshold": find_threshold_command,
    "info": info_command,
    "debug-model": debug_model_command,
    "fill-config": fill_config_command,
    "evaluate": evaluate_command,
    "benchmark": benchmark_command,
    "convert": convert_command,
    "init-config": init_config_command,
    "init-labels": init_labels_command,
    "init-vectors": init_vectors_command,
    "assemble": assemble_command,
    "debug-data": debug_data_command,
    "debug-config": debug_config_command,
    "debug-diff-config": debug_diff_command,
    "project": _project_command,
    "package": package_command,
}


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(f"Usage: python -m spacy_ray_tpu {{{','.join(COMMANDS)}}} ...")
        return 0
    command = argv[0]
    if command not in COMMANDS:
        print(f"Unknown command {command!r}. Available: {', '.join(COMMANDS)}", file=sys.stderr)
        return 1
    _load_plugins()
    return COMMANDS[command](argv[1:])


if __name__ == "__main__":
    raise SystemExit(main())
