"""Model architectures (registered in the ``architectures`` registry)."""

from .core import Model, Context, chain, residual, clone, count_params, param_paths  # noqa: F401
from . import layers  # noqa: F401
from . import tok2vec  # noqa: F401  (registers spacy.HashEmbedCNN.v2 etc.)
from . import heads  # noqa: F401  (registers spacy.Tagger.v2 etc.)
from . import parser  # noqa: F401  (registers spacy.TransitionBasedParser.v2)
from . import transformer  # noqa: F401  (registers spacy_ray_tpu.TransformerEncoder.v1)
