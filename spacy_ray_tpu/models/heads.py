"""Per-component head architectures: tagger, textcat, morphologizer-style.

Registered under the canonical ``spacy.*`` architecture names used by the
configs the reference trains (reference worker.py:91 resolves these via
spacy's registry; SURVEY.md §5.6). Heads consume the tok2vec output
(:class:`Padded`) either from an inline tok2vec sublayer or from the shared
upstream component via ``spacy.Tok2VecListener.v1`` (the listener/upstream
sharing pattern — SURVEY.md §7 "Transformer sharing across components").
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from ..registry import registry
from ..ops import ops as O
from ..types import Padded, TokenBatch
from .core import Context, Model, chain, glorot_uniform
from .layers import Linear


@registry.architectures("spacy.Tok2VecListener.v1")
def Tok2VecListener(width: int, upstream: str = "*") -> Model:
    """Placeholder layer standing in for the shared tok2vec component.

    The pipeline feeds the upstream component's Padded output directly into
    any head whose model tree contains a listener (pipeline/language.py wires
    this; gradient flows back into the shared trunk because the whole
    pipeline loss is one jitted function — the functional equivalent of
    spaCy's listener backprop relay).
    """

    def init_fn(rng):
        return {}

    def apply_fn(params, x: Padded, ctx: Context) -> Padded:
        if not isinstance(x, Padded):
            raise TypeError(
                "Tok2VecListener expected the upstream tok2vec output (Padded); "
                "did the pipeline forget to run the shared tok2vec?"
            )
        return x

    return Model(
        "tok2vec_listener",
        init_fn,
        apply_fn,
        dims={"nO": width},
        meta={"listener": True, "upstream": upstream},
    )


def _has_listener(model: Model) -> bool:
    return any(m.meta.get("listener") for m in model.walk())


@registry.architectures("spacy.Tagger.v1")
@registry.architectures("spacy.Tagger.v2")
def Tagger(tok2vec: Model, nO: Optional[int] = None, normalize: bool = False) -> Model:
    """Softmax tagger head: tok2vec → linear(nO). Loss/decode live in the
    component (pipeline/components/tagger.py)."""
    width = tok2vec.dims.get("nO")
    if nO is None:
        # Resolution happens again at Pipeline.initialize() with label count
        # injected; constructing with nO=1 placeholder is never trained.
        nO = 1
    head = chain(tok2vec, Linear(width, nO, name="output"), name="tagger_model")
    head.dims.update({"nO": nO, "width": width})
    head.meta["has_listener"] = _has_listener(tok2vec)
    return head


@registry.architectures("spacy.TextCatReduce.v1")
def TextCatReduce(
    tok2vec: Model,
    nO: Optional[int] = None,
    exclusive_classes: bool = False,
    use_reduce_first: bool = False,
    use_reduce_last: bool = False,
    use_reduce_max: bool = True,
    use_reduce_mean: bool = True,
) -> Model:
    """Doc classifier: tok2vec → masked pooling (mean/max/first/last concat)
    → linear(nO). Sigmoid vs softmax is applied by the component depending on
    ``exclusive_classes``."""
    width = tok2vec.dims.get("nO")
    n_pools = sum([use_reduce_first, use_reduce_last, use_reduce_max, use_reduce_mean])
    if n_pools == 0:
        raise ValueError("TextCatReduce: enable at least one reduction")
    if nO is None:
        nO = 1

    def init_fn(rng):
        import jax

        r1, r2 = jax.random.split(rng)
        return {
            "tok2vec": tok2vec.init(r1),
            "W": glorot_uniform(r2, (width * n_pools, nO)),
            "b": jnp.zeros((nO,)),
        }

    def apply_fn(params, x: Any, ctx: Context) -> jnp.ndarray:
        # .get: a listener tok2vec has no params and is pruned from the tree
        h: Padded = tok2vec.apply(params.get("tok2vec", {}), x, ctx)
        pools = []
        mask = h.mask
        if use_reduce_first:
            first = h.X[:, 0, :]
            pools.append(first)
        if use_reduce_last:
            lengths = jnp.maximum(jnp.sum(mask.astype(jnp.int32), axis=1) - 1, 0)
            last = jnp.take_along_axis(h.X, lengths[:, None, None], axis=1)[:, 0, :]
            pools.append(last)
        if use_reduce_max:
            pools.append(O.max_pool(h.X, mask))
        if use_reduce_mean:
            pools.append(O.mean_pool(h.X, mask))
        feats = jnp.concatenate(pools, axis=-1)
        return feats @ params["W"] + params["b"]

    m = Model(
        "textcat_model",
        init_fn,
        apply_fn,
        dims={"nO": nO, "width": width},
        layers=[tok2vec],
        meta={
            "has_listener": _has_listener(tok2vec),
            "exclusive_classes": exclusive_classes,
        },
    )
    return m


@registry.architectures("spacy.TextCatBOW.v2")
@registry.architectures("spacy.TextCatBOW.v3")
def TextCatBOW(
    exclusive_classes: bool = False,
    ngram_size: int = 1,
    no_output_layer: bool = False,
    nO: Optional[int] = None,
    length: int = 262144,
) -> Model:
    """Hashed n-gram bag-of-words classifier (spaCy's sparse linear
    textcat, the default fast architecture). No tok2vec: consumes the
    TokenBatch directly — each unigram (and bigram, for ngram_size >= 2)
    hashes to a row of a [length, nO] weight table; the doc score is the
    mean of its n-gram rows. TPU-shaped as a masked gather + segment sum
    (no sparse ops needed).

    ``nO`` may be left unset (the stock spaCy config shape): the output
    dim is read from ``dims`` at INIT time, so a wrapping TextCatEnsemble
    or the owning component fills it in before params exist — spaCy's
    dim-inference, without a second resolution pass."""
    n = max(int(ngram_size), 1)
    dims = {"nO": nO}  # None until a parent fills it; read lazily below

    def init_fn(rng):
        out = dims.get("nO") or 1
        # sparse-linear convention: start at zero so untouched rows stay
        # exactly neutral (a random init would inject noise per rare ngram)
        return {"W": jnp.zeros((length, out)), "b": jnp.zeros((out,))}

    def apply_fn(params, tokens: TokenBatch, ctx: Context) -> jnp.ndarray:
        # NORM hash halves (collate attr order: NORM first)
        lo = tokens.attr_keys[:, :, 0, 0].astype(jnp.uint32)  # [B, T]
        hi = tokens.attr_keys[:, :, 0, 1].astype(jnp.uint32)
        mask = tokens.mask
        L = jnp.uint32(length)
        nO_now = params["W"].shape[-1]
        scores = jnp.zeros((lo.shape[0], nO_now), jnp.float32)
        count = jnp.zeros((lo.shape[0], 1), jnp.float32)
        prev = (lo ^ (hi >> jnp.uint32(1)))
        gram_mask = mask
        for k in range(n):
            if k > 0:
                # roll in the next token's hash for (k+1)-grams
                nxt_lo = jnp.roll(lo, -k, axis=1)
                prev = prev * jnp.uint32(2654435761) + nxt_lo
                gram_mask = gram_mask & jnp.roll(mask, -k, axis=1)
                gram_mask = gram_mask.at[:, -k:].set(False)
            idx = (prev % L).astype(jnp.int32)  # [B, T]
            rows = params["W"][idx]  # [B, T, nO]
            m = gram_mask.astype(jnp.float32)[..., None]
            scores = scores + jnp.sum(rows * m, axis=1)
            count = count + jnp.sum(m, axis=1)
        return scores / jnp.maximum(count, 1.0) + params["b"]

    return Model(
        "textcat_bow",
        init_fn,
        apply_fn,
        dims=dims,
        meta={"has_listener": False, "exclusive_classes": exclusive_classes},
    )


@registry.architectures("spacy.TextCatEnsemble.v2")
def TextCatEnsemble(
    tok2vec: Model,
    linear_model: Model,
    nO: Optional[int] = None,
) -> Model:
    """spaCy's default textcat: a neural (tok2vec + pooling) classifier
    summed with a sparse linear (BOW) classifier."""
    if _has_listener(tok2vec):
        raise ValueError(
            "spacy.TextCatEnsemble.v2 needs an INLINE tok2vec here: its "
            "linear_model reads raw token features, which a listener-fed "
            "head never receives. Put a full tok2vec block under "
            "[components.textcat.model.tok2vec] instead of a listener."
        )
    neural = TextCatReduce(tok2vec, nO=nO)
    if nO is None:
        nO = neural.dims["nO"]
    lm_nO = linear_model.dims.get("nO")
    if lm_nO is None:
        # stock spaCy config shape: the linear block omits nO — fill the
        # label count in before init creates its params
        linear_model.dims["nO"] = nO
    elif lm_nO != nO:
        raise ValueError(
            f"TextCatEnsemble: linear_model nO={lm_nO} != {nO} labels — "
            "omit nO in the [linear_model] block to inherit the label count"
        )

    def init_fn(rng):
        import jax

        r1, r2 = jax.random.split(rng)
        return {"neural": neural.init(r1), "linear": linear_model.init(r2)}

    def apply_fn(params, x: Any, ctx: Context) -> jnp.ndarray:
        c1, c2 = ctx.split()
        a = neural.apply(params.get("neural", {}), x, c1)
        b = linear_model.apply(params.get("linear", {}), x, c2)
        return a + b

    return Model(
        "textcat_ensemble",
        init_fn,
        apply_fn,
        dims={"nO": nO},
        layers=[neural, linear_model],
        meta={
            # listener tok2vecs are rejected above, so never a listener
            "has_listener": False,
            "exclusive_classes": neural.meta.get("exclusive_classes", False),
        },
    )


@registry.architectures("spacy.TextCatCNN.v2")
def TextCatCNN(
    tok2vec: Model,
    exclusive_classes: bool = False,
    nO: Optional[int] = None,
) -> Model:
    """CNN tok2vec + mean pooling + linear — spaCy's TextCatCNN surface,
    expressed through TextCatReduce."""
    return TextCatReduce(
        tok2vec,
        nO=nO,
        exclusive_classes=exclusive_classes,
        use_reduce_max=False,
        use_reduce_mean=True,
    )


@registry.architectures("spacy.EntityLinker.v1")
@registry.architectures("spacy.EntityLinker.v2")
def EntityLinker(tok2vec: Model, nO: Optional[int] = None) -> Model:
    """Entity-linking encoder: tok2vec → linear projection into the KB's
    entity-vector space. Mention pooling, candidate scoring, and decode live
    in the component (pipeline/components/nel.py) — the projection is the
    only dense compute, so it is all that runs on device."""
    width = tok2vec.dims.get("nO")
    if nO is None:
        # Re-resolved at Pipeline.initialize() with the KB's
        # entity_vector_length injected; nO=1 placeholder is never trained.
        nO = 1
    head = chain(tok2vec, Linear(width, nO, name="project"), name="entity_linker_model")
    head.dims.update({"nO": nO, "width": width})
    head.meta["has_listener"] = _has_listener(tok2vec)
    return head
