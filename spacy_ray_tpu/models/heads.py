"""Per-component head architectures: tagger, textcat, morphologizer-style.

Registered under the canonical ``spacy.*`` architecture names used by the
configs the reference trains (reference worker.py:91 resolves these via
spacy's registry; SURVEY.md §5.6). Heads consume the tok2vec output
(:class:`Padded`) either from an inline tok2vec sublayer or from the shared
upstream component via ``spacy.Tok2VecListener.v1`` (the listener/upstream
sharing pattern — SURVEY.md §7 "Transformer sharing across components").
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from ..registry import registry
from ..ops import ops as O
from ..types import Padded, TokenBatch
from .core import Context, Model, chain, glorot_uniform
from .layers import Linear


@registry.architectures("spacy.Tok2VecListener.v1")
def Tok2VecListener(width: int, upstream: str = "*") -> Model:
    """Placeholder layer standing in for the shared tok2vec component.

    The pipeline feeds the upstream component's Padded output directly into
    any head whose model tree contains a listener (pipeline/language.py wires
    this; gradient flows back into the shared trunk because the whole
    pipeline loss is one jitted function — the functional equivalent of
    spaCy's listener backprop relay).
    """

    def init_fn(rng):
        return {}

    def apply_fn(params, x: Padded, ctx: Context) -> Padded:
        if not isinstance(x, Padded):
            raise TypeError(
                "Tok2VecListener expected the upstream tok2vec output (Padded); "
                "did the pipeline forget to run the shared tok2vec?"
            )
        return x

    return Model(
        "tok2vec_listener",
        init_fn,
        apply_fn,
        dims={"nO": width},
        meta={"listener": True, "upstream": upstream},
    )


def _has_listener(model: Model) -> bool:
    return any(m.meta.get("listener") for m in model.walk())


@registry.architectures("spacy.Tagger.v2")
def Tagger(tok2vec: Model, nO: Optional[int] = None, normalize: bool = False) -> Model:
    """Softmax tagger head: tok2vec → linear(nO). Loss/decode live in the
    component (pipeline/components/tagger.py)."""
    width = tok2vec.dims.get("nO")
    if nO is None:
        # Resolution happens again at Pipeline.initialize() with label count
        # injected; constructing with nO=1 placeholder is never trained.
        nO = 1
    head = chain(tok2vec, Linear(width, nO, name="output"), name="tagger_model")
    head.dims.update({"nO": nO, "width": width})
    head.meta["has_listener"] = _has_listener(tok2vec)
    return head


@registry.architectures("spacy.TextCatReduce.v1")
def TextCatReduce(
    tok2vec: Model,
    nO: Optional[int] = None,
    exclusive_classes: bool = False,
    use_reduce_first: bool = False,
    use_reduce_last: bool = False,
    use_reduce_max: bool = True,
    use_reduce_mean: bool = True,
) -> Model:
    """Doc classifier: tok2vec → masked pooling (mean/max/first/last concat)
    → linear(nO). Sigmoid vs softmax is applied by the component depending on
    ``exclusive_classes``."""
    width = tok2vec.dims.get("nO")
    n_pools = sum([use_reduce_first, use_reduce_last, use_reduce_max, use_reduce_mean])
    if n_pools == 0:
        raise ValueError("TextCatReduce: enable at least one reduction")
    if nO is None:
        nO = 1

    def init_fn(rng):
        import jax

        r1, r2 = jax.random.split(rng)
        return {
            "tok2vec": tok2vec.init(r1),
            "W": glorot_uniform(r2, (width * n_pools, nO)),
            "b": jnp.zeros((nO,)),
        }

    def apply_fn(params, x: Any, ctx: Context) -> jnp.ndarray:
        # .get: a listener tok2vec has no params and is pruned from the tree
        h: Padded = tok2vec.apply(params.get("tok2vec", {}), x, ctx)
        pools = []
        mask = h.mask
        if use_reduce_first:
            first = h.X[:, 0, :]
            pools.append(first)
        if use_reduce_last:
            lengths = jnp.maximum(jnp.sum(mask.astype(jnp.int32), axis=1) - 1, 0)
            last = jnp.take_along_axis(h.X, lengths[:, None, None], axis=1)[:, 0, :]
            pools.append(last)
        if use_reduce_max:
            pools.append(O.max_pool(h.X, mask))
        if use_reduce_mean:
            pools.append(O.mean_pool(h.X, mask))
        feats = jnp.concatenate(pools, axis=-1)
        return feats @ params["W"] + params["b"]

    m = Model(
        "textcat_model",
        init_fn,
        apply_fn,
        dims={"nO": nO, "width": width},
        layers=[tok2vec],
        meta={
            "has_listener": _has_listener(tok2vec),
            "exclusive_classes": exclusive_classes,
        },
    )
    return m
