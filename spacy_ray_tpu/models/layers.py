"""Primitive layers over :class:`~spacy_ray_tpu.types.Padded` sequences.

These are the building blocks the architecture registry composes (the role
thinc's Linear/Maxout/LayerNorm/HashEmbed play for the reference's models —
supplied there by native NumpyOps/CupyOps kernels, SURVEY.md §2.3; here by
XLA on the MXU).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..ops import ops as O
from ..ops import hashing
from ..ops.pallas_kernels import hash_embed_lookup
from ..types import Padded, TokenBatch
from .core import Context, Model, glorot_uniform, normal_init


def Linear(nI: int, nO: int, name: str = "linear") -> Model:
    def init_fn(rng):
        return {"W": glorot_uniform(rng, (nI, nO)), "b": jnp.zeros((nO,))}

    def apply_fn(params, x: Padded, ctx: Context) -> Padded:
        X = jnp.einsum("...i,io->...o", x.X, params["W"]) + params["b"]
        return Padded(X=X, mask=x.mask)

    return Model(name, init_fn, apply_fn, dims={"nI": nI, "nO": nO})


def Maxout(nI: int, nO: int, nP: int = 3, name: str = "maxout") -> Model:
    def init_fn(rng):
        return {
            "W": glorot_uniform(rng, (nI, nO * nP)),
            "b": jnp.zeros((nO, nP)),
        }

    def apply_fn(params, x: Padded, ctx: Context) -> Padded:
        X = O.maxout(x.X, params["W"], params["b"])
        return Padded(X=X, mask=x.mask)

    return Model(name, init_fn, apply_fn, dims={"nI": nI, "nO": nO, "nP": nP})


def LayerNorm(nO: int, name: str = "norm") -> Model:
    def init_fn(rng):
        return {"g": jnp.ones((nO,)), "b": jnp.zeros((nO,))}

    def apply_fn(params, x: Padded, ctx: Context) -> Padded:
        return Padded(X=O.layer_norm(x.X, params["g"], params["b"]), mask=x.mask)

    return Model(name, init_fn, apply_fn, dims={"nI": nO, "nO": nO})


def Dropout(rate: float, name: str = "dropout") -> Model:
    def init_fn(rng):
        return {}

    def apply_fn(params, x: Padded, ctx: Context) -> Padded:
        r = ctx.dropout_rate(rate)
        if ctx.train and ctx.rng is not None and r > 0:
            return Padded(X=O.dropout(ctx.rng, x.X, r, True), mask=x.mask)
        return x

    return Model(name, init_fn, apply_fn)


def Seq2Col(window: int, nI: int, name: str = "seq2col") -> Model:
    def init_fn(rng):
        return {}

    def apply_fn(params, x: Padded, ctx: Context) -> Padded:
        return Padded(X=O.seq2col(x.X, window, x.mask), mask=x.mask)

    nO = nI * (2 * window + 1)
    return Model(name, init_fn, apply_fn, dims={"nI": nI, "nO": nO})


def HashEmbed(
    width: int,
    rows: int,
    seed: int,
    attr_index: int,
    name: str = "hash_embed",
) -> Model:
    """Feature-hashing embedding table: 4 murmur hashes per key, rows summed.

    The XLA-native equivalent of thinc HashEmbed (native murmurhash dep of
    the reference, SURVEY.md §2.3): gathers 4 rows per token from a
    [rows, width] table using in-kernel murmur3 x86_128 of the 64-bit
    attribute key.
    """

    def init_fn(rng):
        return {"E": normal_init(rng, (rows, width), stddev=width ** -0.5)}

    def apply_fn(params, batch: TokenBatch, ctx: Context) -> Padded:
        keys = batch.attr_keys[..., attr_index, :]  # [B, T, 2]
        ids = hashing.hash_embed_ids(keys, seed, rows)  # [B, T, 4]
        X = hash_embed_lookup(params["E"], ids)  # pallas on TPU, jnp elsewhere
        mask_f = batch.mask[..., None].astype(X.dtype)
        return Padded(X=X * mask_f, mask=batch.mask)

    return Model(name, init_fn, apply_fn, dims={"nO": width, "rows": rows})


def StaticVectors(width: int, name: str = "static_vectors") -> Model:
    """Frozen pretrained vectors -> trainable linear projection to `width`.

    The table comes from the active vectors context (pipeline/vectors.py)
    and is stored as a stop_gradient\'d parameter: frozen in training, but a
    real array argument to the compiled step (a traced-in constant would be
    re-embedded into every shape-bucket executable).
    """
    from ..pipeline.vectors import current_vectors

    vectors = current_vectors()
    if vectors is None:
        raise ValueError(
            "include_static_vectors=true but no vectors are loaded — set "
            "[initialize] vectors = \"path.npz\" (or Pipeline.load_vectors)"
        )
    host_table = vectors.table  # numpy; becomes a frozen param at init

    def init_fn(rng):
        # The table lives in params rather than being closure-captured (a
        # traced-in constant would be duplicated into every compiled
        # executable). The "frozen_" key prefix is the framework convention
        # marking leaves the optimizer must skip entirely (optax.masked in
        # the loop: no updates, no decay, no Adam moments).
        return {
            "frozen_table": jnp.asarray(host_table),
            "W": glorot_uniform(rng, (host_table.shape[1], width)),
        }

    def apply_fn(params, batch: TokenBatch, ctx: Context) -> Padded:
        rows = batch.vector_rows
        if rows is None:
            raise ValueError(
                "TokenBatch has no vector_rows — the pipeline that collated "
                "this batch has no vectors loaded"
            )
        table = jax.lax.stop_gradient(params["frozen_table"])
        safe = jnp.clip(rows, 0, table.shape[0] - 1)
        vecs = jnp.take(table, safe, axis=0)  # [B, T, Dv]
        vecs = vecs * (rows >= 0)[..., None].astype(vecs.dtype)  # OOV -> 0
        X = vecs @ params["W"]
        return Padded(X=X, mask=batch.mask)

    return Model(name, init_fn, apply_fn, dims={"nO": width, "nV": len(vectors)})


def ConcatPadded(*layers: Model, name: str = "concat") -> Model:
    """Apply layers to the same input, concat features."""

    def init_fn(rng):
        rngs = jax.random.split(rng, len(layers))
        return {f"{i}_{l.name}": l.init(rngs[i]) for i, l in enumerate(layers)}

    def apply_fn(params, x, ctx: Context):
        outs = []
        mask = None
        for i, l in enumerate(layers):
            ctx, sub = ctx.split()
            out = l.apply(params.get(f"{i}_{l.name}", {}), x, sub)
            outs.append(out.X)
            mask = out.mask
        return Padded(X=jnp.concatenate(outs, axis=-1), mask=mask)

    nO = sum(l.dims.get("nO", 0) for l in layers)
    return Model(name, init_fn, apply_fn, dims={"nO": nO}, layers=list(layers))
