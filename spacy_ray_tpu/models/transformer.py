"""Transformer trunk: RoBERTa-base-shape encoder, TPU-first.

Capability parity with the reference ecosystem's shared transformer backbone
(en_core_web_trf: RoBERTa-base feeding tagger/parser/NER via listeners —
BASELINE.json config #4; the reference trains it through the same loop,
worker.py:91/176-189). Differences, deliberate and TPU-native:

* Pretrained HF checkpoint loading is gated (zero-egress environment);
  the trunk trains from scratch. Sub-word information comes from the
  MultiHashEmbed featurizer (NORM/PREFIX/SUFFIX/SHAPE) instead of BPE
  wordpieces, so there is no wordpiece↔token alignment problem at all —
  one vector per token throughout.
* bfloat16 matmuls on the MXU, fp32 layernorm/softmax accumulation,
  fp32 params.
* Attention on a single chip uses the pallas flash kernel
  (ops/flash_attention.py, probe-gated; ``jax.nn.dot_product_attention``
  fallback); with a ``context`` mesh axis the same layer switches to ring
  attention over ICI (parallel/ring_attention.py, SURVEY.md §5.7 —
  first-class here although the reference has none).
* Tensor parallelism: head and FFN dims carry sharding constraints over
  the ``model`` mesh axis when TP is enabled (parallel/context.py).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..registry import registry
from ..ops import ops as O
from ..types import Padded, TokenBatch
from ..parallel import context as pctx
from .core import Context, Model, glorot_uniform, normal_init
from .tok2vec import MultiHashEmbed, ATTRS


def _maybe_shard(x: jnp.ndarray, spec: P) -> jnp.ndarray:
    """Apply a sharding constraint when a mesh is active (no-op otherwise;
    axes of size 1 in the mesh make the constraint a no-op too)."""
    mesh = pctx.current_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, spec)
    )


def transformer_layer_params(rng, width: int, ffn: int, n_experts: int = 0):
    r = jax.random.split(rng, 6)
    scale = 0.02
    params = {
        "qkv_W": normal_init(r[0], (width, 3 * width), scale),
        "qkv_b": jnp.zeros((3 * width,)),
        "o_W": normal_init(r[1], (width, width), scale),
        "o_b": jnp.zeros((width,)),
        "ln1_g": jnp.ones((width,)),
        "ln1_b": jnp.zeros((width,)),
        "ln2_g": jnp.ones((width,)),
        "ln2_b": jnp.zeros((width,)),
    }
    if n_experts > 0:
        # mixture-of-experts FFN (switch-style): E expert FFNs + a router
        params.update(
            router_W=normal_init(r[4], (width, n_experts), scale),
            e_W1=normal_init(r[2], (n_experts, width, ffn), scale),
            e_b1=jnp.zeros((n_experts, ffn)),
            e_W2=normal_init(r[3], (n_experts, ffn, width), scale),
            e_b2=jnp.zeros((n_experts, width)),
        )
    else:
        params.update(
            ffn_W1=normal_init(r[2], (width, ffn), scale),
            ffn_b1=jnp.zeros((ffn,)),
            ffn_W2=normal_init(r[3], (ffn, width), scale),
            ffn_b2=jnp.zeros((width,)),
        )
    return params


def _moe_ffn(p, h: jnp.ndarray, token_mask: jnp.ndarray, *,
             capacity_factor: float, compute_dtype):
    """Switch-transformer top-1 MoE FFN over flattened tokens.

    h [N, D] (post-LN), token_mask [N] bool. Experts are EXPERT-PARALLEL:
    the leading E dim of the dispatched activations carries a sharding
    constraint over the ``model`` mesh axis, so GSPMD places each expert's
    FFN on its own device group and inserts the all_to_alls (SURVEY.md
    §2.2 row EP — absent from the reference, first-class here).

    Returns (out [N, D] fp32, aux load-balancing loss scalar). Tokens
    routed past an expert's capacity are dropped (contribute zero), the
    standard switch behavior.
    """
    N, D = h.shape
    E = p["e_W1"].shape[0]
    F = p["e_W1"].shape[2]
    maskf = token_mask.astype(jnp.float32)

    logits = (h @ p["router_W"]).astype(jnp.float32)  # [N, E] fp32 routing
    probs = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(probs, axis=-1)  # [N]
    gate = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]  # [N]

    onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32) * maskf[:, None]
    capacity = max(int(capacity_factor * N / max(E, 1)), 1)
    # arrival position of each token in its expert's queue
    pos = jnp.cumsum(onehot, axis=0) - onehot  # [N, E]
    pos_tok = jnp.sum(pos * onehot, axis=-1)  # [N]
    keep = (pos_tok < capacity) & token_mask
    disp = onehot * keep.astype(jnp.float32)[:, None]  # [N, E]
    pos_oh = jax.nn.one_hot(pos_tok.astype(jnp.int32), capacity, dtype=jnp.float32)
    dispatch = (disp[:, :, None] * pos_oh[:, None, :]).astype(compute_dtype)  # [N, E, C]

    h16 = h.astype(compute_dtype)
    x_e = jnp.einsum("nec,nd->ecd", dispatch, h16)  # [E, C, D]
    x_e = _maybe_shard(x_e, P("model", None, None))
    inner = jnp.einsum("ecd,edf->ecf", x_e, p["e_W1"].astype(compute_dtype))
    inner = inner + p["e_b1"].astype(compute_dtype)[:, None, :]
    inner = _maybe_shard(inner, P("model", None, None))
    inner = O.gelu(inner)
    y_e = jnp.einsum("ecf,efd->ecd", inner, p["e_W2"].astype(compute_dtype))
    y_e = y_e + p["e_b2"].astype(compute_dtype)[:, None, :]
    y = jnp.einsum("nec,ecd->nd", dispatch, y_e).astype(jnp.float32)
    y = y * gate[:, None]

    # switch load-balancing loss: E * sum_e fraction_routed_e * mean_prob_e
    denom = jnp.maximum(jnp.sum(maskf), 1.0)
    frac = jnp.sum(onehot, axis=0) / denom  # [E]
    mean_prob = jnp.sum(probs * maskf[:, None], axis=0) / denom  # [E]
    aux = jnp.float32(E) * jnp.sum(frac * mean_prob)
    return y, aux


# Leaves the bf16 parameter shadow covers: every weight/bias the layer
# stack casts to the compute dtype each step (matmul operands + the biases
# added to matmul outputs). LN params and the router stay f32 (they feed
# fp32 ops), embeddings/positions are consumed in f32 by the embed path.
SHADOW_LEAF_NAMES = frozenset({
    "qkv_W", "qkv_b", "o_W", "o_b",
    "ffn_W1", "ffn_b1", "ffn_W2", "ffn_b2",
    "e_W1", "e_b1", "e_W2", "e_b2",
})

# Trunk leaves that stay f32 BY DESIGN (they feed fp32 ops): layer norms
# and the MoE router. A layer leaf in neither set is UNKNOWN to the
# shadow scheme — a serving precision overlay must refuse rather than
# ship a tree it only half understands (serving/overlay.py).
TRUNK_F32_LEAF_NAMES = frozenset({
    "ln1_g", "ln1_b", "ln2_g", "ln2_b", "router_W",
})

# Leaves the int8 weight-only serving overlay quantizes: the DENSE 2-D
# matmul weights (the bandwidth-bound operands a small serving batch
# re-streams from HBM every dispatch). Biases stay f32 (weight-only),
# and the MoE expert weights are deliberately NOT covered — they flow
# through einsum contractions the int8 kernel does not implement, and
# an "int8" label over a trunk whose parameter mass stays f32 would be
# a false claim (the overlay REFUSES MoE trunks instead; test-enforced).
INT8_LEAF_NAMES = frozenset({"qkv_W", "o_W", "ffn_W1", "ffn_W2"})
INT8_UNSUPPORTED_LEAF_NAMES = frozenset({"e_W1", "e_W2"})


def shadow_coverage(params) -> "Tuple[int, List[str]]":
    """Audit a param tree against the shadow scheme: returns
    ``(n_eligible, unknown)`` where ``n_eligible`` counts f32 trunk
    leaves :func:`build_param_shadow` would overlay and ``unknown``
    lists the paths of ``layer_i`` leaves in neither SHADOW_LEAF_NAMES
    nor TRUNK_F32_LEAF_NAMES. Non-empty ``unknown`` means the overlay's
    coverage claim would be false for this model — callers fall back to
    f32 with an honest label instead of serving a partial overlay."""
    eligible = 0
    unknown: List[str] = []

    def rec(node, in_layer, path):
        nonlocal eligible
        for k, v in node.items():
            if isinstance(v, dict):
                rec(v, in_layer or str(k).startswith("layer_"), path + (str(k),))
            elif in_layer:
                if k in SHADOW_LEAF_NAMES:
                    if jnp.asarray(v).dtype == jnp.float32:
                        eligible += 1
                elif k not in TRUNK_F32_LEAF_NAMES:
                    unknown.append("/".join(path + (str(k),)))

    rec(params, False, ())
    return eligible, unknown


def build_param_shadow(params, dtype=jnp.bfloat16):
    """Nested sub-tree of ``params`` holding ``dtype`` copies of every
    transformer matmul weight (SHADOW_LEAF_NAMES under a ``layer_i`` dict).

    The train step overlays this shadow onto the f32 master params for the
    forward/backward pass: the layer stack's per-step (and, under remat,
    per-backward) ``astype(compute_dtype)`` of the whole trunk becomes a
    no-op, replaced by ONE incremental refresh of the shadow inside the
    same jitted update (parallel/step.py). Returns None when nothing
    qualifies (no transformer trunk in the tree)."""

    def rec(node, in_layer):
        out = {}
        for k, v in node.items():
            if isinstance(v, dict):
                sub = rec(v, in_layer or str(k).startswith("layer_"))
                if sub:
                    out[k] = sub
            elif (
                in_layer
                and k in SHADOW_LEAF_NAMES
                and jnp.asarray(v).dtype == jnp.float32
            ):
                out[k] = v.astype(dtype)
        return out

    return rec(params, False) or None


def int8_unsupported_leaves(params) -> "List[str]":
    """Paths of trunk leaves the int8 overlay cannot cover (MoE expert
    weights). Non-empty means :func:`build_int8_overlay` must not run:
    the overlay would quantize the dense shell of a model whose weight
    mass lives in the experts, and the label would lie."""
    out: List[str] = []

    def rec(node, in_layer, path):
        for k, v in node.items():
            if isinstance(v, dict):
                rec(v, in_layer or str(k).startswith("layer_"), path + (str(k),))
            elif in_layer and k in INT8_UNSUPPORTED_LEAF_NAMES:
                out.append("/".join(path + (str(k),)))

    rec(params, False, ())
    return out


def build_int8_overlay(params) -> "Tuple[Any, int]":
    """The int8 weight-only serving overlay: a copy of ``params`` where
    every f32 INT8_LEAF_NAMES leaf under a ``layer_i`` dict is replaced
    by ``{"q8": int8 [K, N], "scale": f32 [N]}`` (per-output-channel
    symmetric quantization, ops/int8_matmul.py). Everything else — LNs,
    biases, embeddings, heads — is the SAME array object as the master
    tree (no copies). Returns ``(tree, n_quantized)``.

    The layer forward consumes these dict leaves through ``_wdot``; the
    dict structure is part of the jit trace, so a hot-swap that
    re-quantizes a new generation (same structure, same dtypes) reuses
    every warmed program — zero post-swap compiles, test-enforced."""
    from ..ops.int8_matmul import quantize_int8

    n = 0

    def rec(node, in_layer):
        nonlocal n
        out = {}
        for k, v in node.items():
            if isinstance(v, dict):
                out[k] = rec(v, in_layer or str(k).startswith("layer_"))
            elif (
                in_layer
                and k in INT8_LEAF_NAMES
                and jnp.asarray(v).dtype == jnp.float32
            ):
                q8, scale = quantize_int8(v)
                out[k] = {"q8": q8, "scale": scale}
                n += 1
            else:
                out[k] = v
        return out

    return rec(params, False), n


def _wdot(h: jnp.ndarray, leaf, compute_dtype) -> jnp.ndarray:
    """Trunk weight matmul that understands the two leaf encodings: a
    plain array (cast to the compute dtype — the training/bf16 path) or
    an int8 serving-overlay dict (``{"q8", "scale"}`` — dequantize-in-
    kernel pallas matmul, f32 accumulation, downcast to the compute
    dtype so the surrounding arithmetic is dtype-identical either way).
    The isinstance check runs at trace time: each param-tree structure
    compiles once, exactly like a dtype change would."""
    if isinstance(leaf, dict):
        from ..ops.int8_matmul import int8_matmul

        return int8_matmul(h, leaf["q8"], leaf["scale"]).astype(compute_dtype)
    return h @ leaf.astype(compute_dtype)


def pipeline_shadow_dtype(nlp) -> Optional[Any]:
    """bfloat16 when some transformer trunk in the pipeline resolves its
    compute dtype to bf16 (the only case a bf16 shadow is numerics-
    preserving), else None — the ``[training] bf16_shadow = "auto"``
    decision point."""
    for comp in nlp.components.values():
        model = getattr(comp, "model", None)
        if model is None:
            continue
        for m in model.walk():
            name = m.meta.get("compute_dtype_name")
            if name and _resolve_compute_dtype(name) == jnp.bfloat16:
                return jnp.bfloat16
    return None


def _resolve_compute_dtype(name: str):
    """Matmul compute dtype: "auto" picks bfloat16 on accelerators (native
    MXU dtype) and float32 on CPU, where bf16 buys nothing (the matmul
    microbench runs at identical GFLOP/s in both dtypes) and the
    activation/weight casts cost real time (profile_trf.py measured the
    f32 path 15% faster at B=8/T=64 — PERF.md §MFU)."""
    if name == "auto":
        return (
            jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16
        )
    table = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}
    if name not in table:
        raise ValueError(
            "compute_dtype must be one of ['auto', 'bfloat16', 'float32'], "
            f"got {name!r}"
        )
    return table[name]


def apply_transformer_layer(
    p,
    X: jnp.ndarray,
    mask: jnp.ndarray,
    rng: Optional[jax.Array],
    *,
    n_heads: int,
    dropout: float,
    train: bool,
    n_experts: int = 0,
    capacity_factor: float = 1.25,
    compute_dtype=jnp.bfloat16,
):
    """Pre-LN encoder layer. X [B, T, D] fp32, mask [B, T] bool.

    Returns (X, aux) — aux is the MoE router's load-balancing loss (0.0
    for the dense FFN). Keyword args are static (bound with
    functools.partial before jax.checkpoint, so the checkpointed callable
    takes only pytrees).
    """
    B, T, D = X.shape
    H = n_heads
    Dh = D // H
    use_dropout = train and rng is not None and dropout > 0
    if use_dropout:
        rng1, rng2 = jax.random.split(rng)

    # ---- attention ----
    h = O.layer_norm(X, p["ln1_g"], p["ln1_b"])
    h16 = h.astype(compute_dtype)
    qkv = _wdot(h16, p["qkv_W"], compute_dtype) + p["qkv_b"].astype(compute_dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(x):
        return x.reshape(B, T, H, Dh)

    q, k, v = heads(q), heads(k), heads(v)
    # full-layout constraints (batch over data, seq over context, heads over
    # model) — partial specs make the partitioner re-materialize
    qkv_spec = P("data", "context", "model", None)
    q = _maybe_shard(q, qkv_spec)
    k = _maybe_shard(k, qkv_spec)
    v = _maybe_shard(v, qkv_spec)

    if pctx.context_parallel_active():
        from ..parallel.ring_attention import ring_attention

        attn = ring_attention(q, k, v, mask)
    else:
        # pallas flash kernel when the startup probe enabled it (TPU),
        # XLA's fused dot_product_attention otherwise
        from ..ops.flash_attention import attention

        attn = attention(q, k, v, mask)
    attn = attn.reshape(B, T, D)
    out = _wdot(attn, p["o_W"], compute_dtype) + p["o_b"].astype(compute_dtype)
    out = out.astype(jnp.float32)
    if use_dropout:
        out = O.dropout(rng1, out, dropout, True)
    X = X + out

    # ---- ffn (dense or mixture-of-experts) ----
    h = O.layer_norm(X, p["ln2_g"], p["ln2_b"])
    aux = jnp.float32(0.0)
    if n_experts > 0:
        out2d, aux = _moe_ffn(
            p,
            h.reshape(B * T, D),
            mask.reshape(B * T),
            capacity_factor=capacity_factor,
            compute_dtype=compute_dtype,
        )
        out = out2d.reshape(B, T, D)
    else:
        h16 = h.astype(compute_dtype)
        inner = _wdot(h16, p["ffn_W1"], compute_dtype) + p["ffn_b1"].astype(compute_dtype)
        inner = _maybe_shard(inner, P("data", "context", "model"))
        inner = O.gelu(inner)
        out = _wdot(inner, p["ffn_W2"], compute_dtype) + p["ffn_b2"].astype(compute_dtype)
        out = out.astype(jnp.float32)
    if use_dropout:
        out = O.dropout(rng2, out, dropout, True)
    return X + out, aux


def _stack_layer_params(params, depth: int):
    """Stack the per-layer param dicts into leaves with a leading [depth]
    dim. Storage stays per-layer ("layer_i" keys — the checkpoint and
    pretrained-loader schema); stacking happens at apply time, costing one
    HBM copy of the trunk per step (~0.3 ms for RoBERTa-base at HBM
    bandwidth — noise next to the step) in exchange for a compiled program
    with ONE layer body instead of `depth` copies."""
    import jax.tree_util as jtu

    return jtu.tree_map(
        lambda *xs: jnp.stack(xs), *[params[f"layer_{i}"] for i in range(depth)]
    )


def _scan_layer_stack(layer_fn, stacked, X, mask, key, depth: int):
    """Run the stacked layers as one lax.scan, accumulating the aux loss.
    Per-layer rng = fold_in(key, layer_index) — the SAME derivation the
    pipelined stage body uses, so the two paths stay in lockstep."""

    def body(carry, inp):
        x, aux_sum = carry
        lp, li = inp
        y, aux = layer_fn(lp, x, mask, jax.random.fold_in(key, li))
        return (y, aux_sum + aux), None

    (X, aux_total), _ = jax.lax.scan(
        body, (X, jnp.float32(0.0)), (stacked, jnp.arange(depth))
    )
    return X, aux_total


def _pipelined_layers(
    params, X, mask, ctx, layer_fn, *, depth: int, n_microbatches: int
):
    """Run the layer stack under GPipe pipeline parallelism
    (parallel/pipeline.py). Stacks the per-layer param dicts into leaves
    with a leading [depth] dim (sharded over 'pipe' by the pipeline), and
    splits the batch into microbatches along dim 0.

    With partial-manual shard_map (jax >= 0.7) the stage body keeps its
    automatic axes, so TP constraints compose with PP — and ring attention
    nests as a second partial-manual region (manual over `context` only,
    parallel/ring_attention.py), so PP x CP works too. On older jax (fully
    manual fallback) the context axis cannot join a pipe mesh.
    """
    from ..parallel import pipeline as ppl
    from ..parallel.smap import PARTIAL_MANUAL

    if pctx.context_parallel_active() and not PARTIAL_MANUAL:
        raise ValueError(
            "pipe x context needs partial-manual shard_map (newer jax) so "
            "the ring-attention region can nest inside the pipeline region "
            "— use pipe x data (x model) on this jax"
        )
    if pctx.tp_active() and not PARTIAL_MANUAL:
        raise ValueError(
            "pipe x model needs partial-manual shard_map (newer jax); "
            "this jax only supports pipe x data"
        )
    mesh = pctx.current_mesh()
    S = int(mesh.shape["pipe"])
    if depth % S != 0:
        raise ValueError(f"depth {depth} not divisible by {S} pipeline stages")
    B = X.shape[0]
    d = int(mesh.shape.get("data", 1))
    # each microbatch is sharded over the data axis, so M must divide B/d
    # (keeping every microbatch's size a multiple of d)
    per_data = max(B // d, 1)
    requested = n_microbatches or 2 * S
    M = min(requested, per_data)
    while M > 1 and per_data % M != 0:
        M -= 1
    if n_microbatches and M != n_microbatches:
        import warnings

        warnings.warn(
            f"pp_microbatches={n_microbatches} cannot divide the per-data-"
            f"shard batch ({per_data}); using {M} microbatches instead "
            f"(pipeline bubble {(S - 1) / (M + S - 1):.0%})",
            stacklevel=2,
        )
    stacked = _stack_layer_params(params, depth)
    mb = X.reshape(M, B // M, *X.shape[1:])
    mb_mask = mask.reshape(M, B // M, mask.shape[1])
    ctx, sub = ctx.split()
    rng = sub.rng if sub.rng is not None else jax.random.PRNGKey(0)
    layers_per_stage = depth // S

    # with partial-manual shard_map the body keeps automatic data/model
    # axes, so TP constraints inside the layers still apply — keep the
    # mesh active; the fully-manual fallback must disable constraints
    keep_mesh = PARTIAL_MANUAL

    def stage_fn(local_params, x, m, key):
        # this stage's layers, sequentially. Fold the stage index into the
        # key: without it every stage would reuse the same per-tick
        # dropout masks on different microbatches
        key = jax.random.fold_in(key, jax.lax.axis_index("pipe"))
        with pctx.use_mesh(mesh if keep_mesh else None):
            return _scan_layer_stack(
                layer_fn, local_params, x, m, key, layers_per_stage
            )

    out, aux_total = ppl.spmd_pipeline(stage_fn, stacked, mb, mb_mask, rng)
    return out.reshape(B, *X.shape[1:]), aux_total


@registry.architectures("spacy_ray_tpu.TransformerEncoder.v1")
def TransformerEncoder(
    width: int = 768,
    depth: int = 12,
    n_heads: int = 12,
    ffn_mult: int = 4,
    dropout: float = 0.1,
    max_len: int = 512,
    embed_size: int = 10000,
    remat: bool = True,
    remat_policy: str = "dots",
    compute_dtype: str = "auto",
    init_weights: Optional[str] = None,
    pp_microbatches: int = 0,
    n_experts: int = 0,
    expert_capacity_factor: float = 1.25,
    router_aux_weight: float = 0.01,
    scan_layers: bool = True,
) -> Model:
    """Hash-embed featurized transformer trunk (tok2vec-compatible output).

    ``n_experts > 0`` replaces each layer's dense FFN with a switch-style
    top-1 mixture of experts (expert-parallel over the ``model`` mesh
    axis); ``router_aux_weight`` scales the load-balancing loss added to
    training via the Context aux sink.

    ``compute_dtype``: matmul dtype for the attention/FFN blocks —
    "auto" (default) = bfloat16 on accelerators, float32 on CPU (bf16 is
    a cast-overhead-only cost there; see _resolve_compute_dtype);
    layernorm/softmax always accumulate in fp32 either way.

    ``remat=True`` wraps each layer in jax.checkpoint — rematerialize
    activations in backward to trade FLOPs for HBM (the standard TPU
    memory/bandwidth tradeoff for deep trunks). ``remat_policy`` picks
    WHAT is saved: "dots" (default) saves weight-matmul outputs and
    recomputes only cheap elementwise/norm/attention-score work — ~25%
    fewer backward FLOPs than full recompute for a modest HBM cost;
    "all_dots" additionally saves batched (attention) matmuls; "nothing"
    is full recompute (the pre-round-4 behavior, minimum memory).

    ``pp_microbatches``: microbatch count for pipeline parallelism; used
    only when the active mesh has a ``pipe`` axis > 1 (0 = auto: 2x the
    stage count, a reasonable bubble/memory tradeoff).

    ``init_weights``: path to a local .npz (native schema) or .safetensors
    (native or HuggingFace-encoder keys, remapped) checkpoint to start the
    trunk from — see models/pretrained.py for the key schema. Every tensor
    is shape-checked; keys absent from the file keep their random init.

    ``scan_layers=True`` runs the (homogeneous) layer stack as ONE
    ``lax.scan`` over stacked per-layer params instead of an unrolled
    Python loop: the compiled program contains one layer body instead of
    ``depth`` copies (~8x smaller HLO for RoBERTa-base — compile time and
    compile-server memory scale with program size). Per-layer dropout rng
    derives from fold_in(key, layer_index) on both paths.
    """
    if width % n_heads != 0:
        raise ValueError(f"width {width} not divisible by n_heads {n_heads}")
    ffn = width * ffn_mult
    embed = MultiHashEmbed(width=width, attrs=list(ATTRS),
                           rows=[embed_size] + [embed_size // 2] * 3)

    def init_fn(rng):
        rngs = jax.random.split(rng, depth + 2)
        params = {
            "embed": embed.init(rngs[0]),
            "pos": normal_init(rngs[1], (max_len, width), 0.02),
            "ln_f_g": jnp.ones((width,)),
            "ln_f_b": jnp.zeros((width,)),
        }
        for i in range(depth):
            params[f"layer_{i}"] = transformer_layer_params(
                rngs[i + 2], width, ffn, n_experts=n_experts
            )
        if init_weights:
            from .pretrained import load_trunk_weights

            params = load_trunk_weights(params, init_weights)
        return params

    def apply_fn(params, batch: TokenBatch, ctx: Context) -> Padded:
        emb: Padded = embed.apply(params["embed"], batch, ctx)
        T = emb.X.shape[1]
        if T > max_len:
            import warnings

            warnings.warn(
                f"sequence length {T} exceeds transformer max_len {max_len}; "
                "positions beyond max_len reuse the last positional embedding "
                "(set a larger max_len or bound doc length via corpus "
                "max_length)",
                stacklevel=2,
            )
        pos_idx = jnp.minimum(jnp.arange(T), params["pos"].shape[0] - 1)
        X = emb.X + params["pos"][pos_idx][None, :, :]
        mask = emb.mask
        if pctx.context_parallel_active():
            # sequence-parallel layout: T sharded over the context axis
            X = _maybe_shard(X, P("data", "context", None))
            mask = _maybe_shard(mask, P("data", "context"))

        from functools import partial as _partial

        layer_fn = _partial(
            apply_transformer_layer,
            n_heads=n_heads,
            dropout=ctx.dropout_rate(dropout),
            train=ctx.train,
            n_experts=n_experts,
            capacity_factor=expert_capacity_factor,
            compute_dtype=_resolve_compute_dtype(compute_dtype),
        )
        if remat:
            # checkpointed callable takes only pytree args (p, X, mask, rng)
            policies = {
                "nothing": None,  # full recompute
                "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                "all_dots": jax.checkpoint_policies.dots_saveable,
            }
            if remat_policy not in policies:
                raise ValueError(
                    f"remat_policy must be one of {sorted(policies)}, "
                    f"got {remat_policy!r}"
                )
            policy = policies[remat_policy]
            layer_fn = (
                jax.checkpoint(layer_fn, policy=policy)
                if policy is not None
                else jax.checkpoint(layer_fn)
            )
        if pctx.pipeline_active():
            X, aux_total = _pipelined_layers(
                params, X, mask, ctx, layer_fn, depth=depth,
                n_microbatches=pp_microbatches,
            )
        elif scan_layers and depth > 1:
            # one scanned layer body instead of `depth` unrolled copies —
            # same math, ~depth-x smaller compiled program
            ctx, sub = ctx.split()
            key = sub.rng if sub.rng is not None else jax.random.PRNGKey(0)
            X, aux_total = _scan_layer_stack(
                layer_fn, _stack_layer_params(params, depth), X, mask, key,
                depth,
            )
        else:
            aux_total = jnp.float32(0.0)
            for i in range(depth):
                ctx, sub = ctx.split()
                X, aux = layer_fn(params[f"layer_{i}"], X, mask, sub.rng)
                aux_total = aux_total + aux
        if n_experts > 0:
            ctx.add_aux_loss(jnp.float32(router_aux_weight) * aux_total)
        X = O.layer_norm(X, params["ln_f_g"], params["ln_f_b"])
        return Padded(X=X * mask[..., None].astype(X.dtype), mask=mask)

    return Model(
        "transformer_encoder",
        init_fn,
        apply_fn,
        dims={"nO": width, "depth": depth, "n_heads": n_heads},
        layers=[embed],
        # the bf16-shadow decision point (pipeline_shadow_dtype) resolves
        # this at loop-setup time — "auto" depends on the backend
        meta={"compute_dtype_name": compute_dtype},
    )


@registry.architectures("spacy-transformers.TransformerModel.v3")
def HFTransformerModel(
    name: str = "roberta-base",
    get_spans=None,
    tokenizer_config: Optional[dict] = None,
    transformer_config: Optional[dict] = None,
) -> Model:
    """Reference-ecosystem config compatibility (spacy-transformers'
    registered name). ``name`` must be a LOCAL path to a .safetensors or
    .npz checkpoint (this environment is zero-egress — hub names can't be
    downloaded); the encoder weights are remapped into the native RoBERTa-
    base-shape trunk via models/pretrained.py. A bare hub name raises with
    that guidance."""
    from pathlib import Path

    if not Path(name).exists():
        raise NotImplementedError(
            f"{name!r} is not a local file, and downloading HuggingFace "
            "checkpoints is impossible in this zero-egress environment. "
            "Point `name` at a local .safetensors/.npz checkpoint, or use "
            '@architectures "spacy_ray_tpu.TransformerEncoder.v1" with '
            "init_weights=<path> (same RoBERTa-base shape)."
        )
    cfg = dict(transformer_config or {})
    return TransformerEncoder(
        width=int(cfg.get("width", 768)),
        depth=int(cfg.get("depth", 12)),
        n_heads=int(cfg.get("n_heads", 12)),
        max_len=int(cfg.get("max_len", 512)),
        init_weights=name,
    )
