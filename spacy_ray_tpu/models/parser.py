"""TransitionBasedParser architecture: state2vec MLP + on-device greedy decode.

Capability parity with spaCy's ``TransitionBasedParser.v2`` architecture
(the model of the reference's parser/NER pipes, trained via reference
worker.py:91/176-189; native Cython ``nn_parser.pyx`` machinery per
SURVEY.md §2.3). TPU-first design per SURVEY.md §7 option (a):

* TRAINING: zero dynamic control flow. The host precomputes teacher-forced
  state features (pipeline/transition.py); the model is
  ``gather token vectors at [B, S, F] indices → maxout hidden → linear
  actions`` — two large batched MXU matmuls over the whole doc×step grid.
* DECODE (parser): fixed-length ``lax.scan`` arc-eager state machine with
  masked-action argmax — stacks/buffers/heads as dense int arrays, jnp ops
  only, vectorized over the batch.
* DECODE (NER): BILUO logits are position-only, so they're one batched
  matmul; the scan only walks the constraint automaton (open-entity state)
  over precomputed logits.

Action encodings follow pipeline/transition.py (parser) and
pipeline/components/ner.py (BILUO).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..registry import registry
from ..pipeline import transition as T
from ..types import Padded
from .core import Context, Model, glorot_uniform
from ..ops import ops as O

PARSER_N_FEATURES = T.N_FEATURES
NER_N_FEATURES = 5  # token window [t-2, t-1, t, t+1, t+2]


def ner_window_features(Tlen: int, lengths: jnp.ndarray) -> jnp.ndarray:
    """[B, T, 5] window indices [t-2 .. t+2], -1 outside [0, length).

    Single source of truth for the NER feature layout — used by both the
    training targets (host) and the jit decode path.
    """
    grid = (
        jnp.arange(Tlen)[None, :, None]
        + jnp.array([-2, -1, 0, 1, 2])[None, None, :]
    )
    lengths = jnp.asarray(lengths)
    return jnp.where(
        (grid >= 0) & (grid < lengths[:, None, None]), grid, -1
    ).astype(jnp.int32)


# HBM budget for the one-hot operand ([*feats.shape, T] elements, live
# across fwd+bwd as an einsum residual) — beyond it the vmap gather wins
ONEHOT_GATHER_MAX_BYTES = 128 * 1024 * 1024


def _gather(X: jnp.ndarray, feats: jnp.ndarray) -> jnp.ndarray:
    """X [B, T, D], feats [B, S, F] -> [B, S, F, D], -1 slots zeroed.

    On TPU a batched row gather lowers to serialized dynamic-slices; for
    the doc-length Ts this model sees, re-expressing it as a one-hot
    einsum puts the work on the MXU instead (the standard TPU gather
    rewrite: B*S*F*T*D MACs, trivially saturating the systolic array,
    and -1 slots fall out as all-zero one-hot rows — no separate mask).
    """
    Tlen = X.shape[1]
    onehot_bytes = feats.size * Tlen * X.dtype.itemsize
    if onehot_bytes <= ONEHOT_GATHER_MAX_BYTES and jax.default_backend() == "tpu":
        # one_hot(-1) == all zeros, so invalid slots zero themselves.
        # feats may be [B, S, F] (training grid) or [B, F] (decode step):
        # the ellipsis spans whatever lies between batch and the T axis.
        onehot = jax.nn.one_hot(feats, Tlen, dtype=X.dtype)  # [B, ..., T]
        return jnp.einsum("b...t,btd->b...d", onehot, X)
    safe = jnp.clip(feats, 0, Tlen - 1).astype(jnp.int32)

    def per_row(Xrow, frow):  # [T, D], [S, F]
        return Xrow[frow]  # [S, F, D]

    out = jax.vmap(per_row)(X, safe)
    mask = (feats >= 0)[..., None].astype(X.dtype)
    return out * mask


class ParserModelFns:
    """Pure functions bound to static dims; stored in Model.meta."""

    def __init__(self, n_feats: int, width: int, hidden: int, pieces: int, n_actions: int):
        self.n_feats = n_feats
        self.width = width
        self.hidden = hidden
        self.pieces = pieces
        self.n_actions = n_actions

    def init(self, rng: jax.Array) -> Dict[str, Any]:
        r1, r2, r3 = jax.random.split(rng, 3)
        return {
            "hidden_W": glorot_uniform(r1, (self.n_feats * self.width, self.hidden * self.pieces)),
            "hidden_b": jnp.zeros((self.hidden, self.pieces)),
            "out_W": glorot_uniform(r2, (self.hidden, self.n_actions)),
            "out_b": jnp.zeros((self.n_actions,)),
        }

    def logits(self, params: Dict[str, Any], state_vecs: jnp.ndarray) -> jnp.ndarray:
        """state_vecs [..., F*D] -> [..., n_actions]."""
        h = O.maxout(state_vecs, params["hidden_W"], params["hidden_b"])
        return h @ params["out_W"] + params["out_b"]

    def step_logits(self, params, X, feats):
        """X [B,T,D], feats [B,S,F] -> [B,S,nA] (training path, fully batched)."""
        vecs = _gather(X, feats)  # [B, S, F, D]
        B, S = vecs.shape[:2]
        flat = vecs.reshape(B, S, self.n_feats * self.width)
        return self.logits(params, flat)


@registry.architectures("spacy.TransitionBasedParser.v1")
@registry.architectures("spacy.TransitionBasedParser.v2")
def TransitionBasedParser(
    tok2vec: Model,
    state_type: str = "parser",
    extra_state_tokens: bool = False,
    hidden_width: int = 64,
    maxout_pieces: int = 2,
    use_upper: bool = True,
    nO: Optional[int] = None,
) -> Model:
    """nO = number of actions (injected at Pipeline.initialize from labels)."""
    width = tok2vec.dims.get("nO")
    n_feats = PARSER_N_FEATURES if state_type == "parser" else NER_N_FEATURES
    n_act = nO if nO else 3
    fns = ParserModelFns(n_feats, width, hidden_width, maxout_pieces, n_act)

    def init_fn(rng):
        r1, r2 = jax.random.split(rng)
        return {"tok2vec": tok2vec.init(r1), "upper": fns.init(r2)}

    def apply_fn(params, x, ctx: Context):
        """x = (inputs_for_tok2vec, feats [B,S,F]) -> [B,S,nA] logits."""
        inputs, feats = x
        t2v: Padded = tok2vec.apply(params.get("tok2vec", {}), inputs, ctx)
        return fns.step_logits(params["upper"], t2v.X, feats)

    has_listener = any(m.meta.get("listener") for m in tok2vec.walk())
    m = Model(
        f"transition_model_{state_type}",
        init_fn,
        apply_fn,
        dims={"nO": n_act, "width": width, "hidden": hidden_width, "n_feats": n_feats},
        layers=[tok2vec],
        meta={
            "has_listener": has_listener,
            "state_type": state_type,
            "fns": fns,
        },
    )
    return m


# ----------------------------------------------------------------------
# Device decode: arc-eager greedy under lax.scan
# ----------------------------------------------------------------------


def _arc_eager_machine(Tlen: int, lengths_n: jnp.ndarray, n_labels: int, n_act: int):
    """Vectorized arc-eager state machine over a leading dim N (= batch for
    greedy decode, batch*beam for beam decode). Returns the state ops as a
    dict of pure functions."""
    N = lengths_n.shape[0]
    nidx = jnp.arange(N)

    def init_state():
        return {
            "stack": jnp.full((N, Tlen + 1), -1, jnp.int32),
            "sp": jnp.zeros((N,), jnp.int32),
            "buf": jnp.zeros((N,), jnp.int32),
            "heads": jnp.full((N, Tlen), -2, jnp.int32),
            "labels": jnp.zeros((N, Tlen), jnp.int32),
            "lc0": jnp.full((N, Tlen), -1, jnp.int32),
            "lc1": jnp.full((N, Tlen), -1, jnp.int32),
            "rc0": jnp.full((N, Tlen), -1, jnp.int32),
            "rc1": jnp.full((N, Tlen), -1, jnp.int32),
        }

    def peek(st, depth):
        idx = st["sp"] - depth
        ok = idx >= 1
        return jnp.where(ok, st["stack"][nidx, jnp.clip(idx - 1, 0, Tlen)], -1)

    def features(st):
        s0 = peek(st, 0)
        s1 = peek(st, 1)
        s2 = peek(st, 2)
        b = st["buf"]
        b0 = jnp.where(b < lengths_n, b, -1)
        b1 = jnp.where(b + 1 < lengths_n, b + 1, -1)
        b2 = jnp.where(b + 2 < lengths_n, b + 2, -1)
        s0c = jnp.clip(s0, 0, Tlen - 1)
        s1c = jnp.clip(s1, 0, Tlen - 1)
        s0l = jnp.where(s0 >= 0, st["lc0"][nidx, s0c], -1)
        s0r = jnp.where(s0 >= 0, st["rc0"][nidx, s0c], -1)
        s1l = jnp.where(s1 >= 0, st["lc0"][nidx, s1c], -1)
        s1r = jnp.where(s1 >= 0, st["rc0"][nidx, s1c], -1)
        s0l2 = jnp.where(s0 >= 0, st["lc1"][nidx, s0c], -1)
        s0r2 = jnp.where(s0 >= 0, st["rc1"][nidx, s0c], -1)
        return jnp.stack(
            [s0, s1, s2, b0, b1, b2, s0l, s0r, s1l, s1r, s0l2, s0r2], axis=1
        )  # [N, 12]

    def valid_mask(st):
        has_b0 = st["buf"] < lengths_n
        has_s0 = st["sp"] >= 1
        s0 = peek(st, 0)
        s0c = jnp.clip(s0, 0, Tlen - 1)
        s0_has_head = has_s0 & (st["heads"][nidx, s0c] != -2)
        shift_ok = has_b0
        # cleanup: when buffer is empty, REDUCE pops anything (ROOT-escape)
        reduce_ok = (has_s0 & s0_has_head) | (has_s0 & ~has_b0)
        la_ok = has_s0 & has_b0 & ~s0_has_head
        ra_ok = has_s0 & has_b0
        mask = jnp.zeros((N, n_act), bool)
        mask = mask.at[:, T.SHIFT].set(shift_ok)
        mask = mask.at[:, T.REDUCE].set(reduce_ok)
        la_cols = 2 + 2 * jnp.arange(n_labels)
        ra_cols = 3 + 2 * jnp.arange(n_labels)
        mask = mask.at[:, la_cols].set(la_ok[:, None])
        mask = mask.at[:, ra_cols].set(ra_ok[:, None])
        return mask

    def apply_action(st, action, active):
        is_shift = (action == T.SHIFT) & active
        is_reduce = (action == T.REDUCE) & active
        arc = action >= 2
        is_la = arc & ((action - 2) % 2 == 0) & active
        is_ra = arc & ((action - 2) % 2 == 1) & active
        label = jnp.where(arc, (action - 2) // 2, 0).astype(jnp.int32)
        s0 = peek(st, 0)
        s0c = jnp.clip(s0, 0, Tlen - 1)
        b0 = st["buf"]
        b0c = jnp.clip(b0, 0, Tlen - 1)

        push = is_shift | is_ra
        pop = is_reduce | is_la

        # ROOT-escape on REDUCE of a headless token
        s0_headless = st["heads"][nidx, s0c] == -2
        heads = st["heads"]
        heads = heads.at[nidx, s0c].set(
            jnp.where(
                is_reduce & s0_headless & (s0 >= 0), -1, heads[nidx, s0c]
            )
        )
        # LEFT-ARC: head(s0) = b0
        heads = heads.at[nidx, s0c].set(
            jnp.where(is_la & (s0 >= 0), b0, heads[nidx, s0c])
        )
        labels_arr = st["labels"]
        labels_arr = labels_arr.at[nidx, s0c].set(
            jnp.where(is_la & (s0 >= 0), label, labels_arr[nidx, s0c])
        )
        # RIGHT-ARC: head(b0) = s0 (or ROOT if stack empty — masked anyway)
        ra_head = jnp.where(st["sp"] >= 1, s0, -1)
        heads = heads.at[nidx, b0c].set(
            jnp.where(is_ra, ra_head, heads[nidx, b0c])
        )
        labels_arr = labels_arr.at[nidx, b0c].set(
            jnp.where(is_ra, label, labels_arr[nidx, b0c])
        )

        # child bookkeeping (dep < head -> left chain, else right chain)
        def upd_children(lc0, lc1, rc0, rc1, head, dep, on):
            hc = jnp.clip(head, 0, Tlen - 1)
            left = dep < head
            old_l0 = lc0[nidx, hc]
            new_l0 = jnp.where(on & left & ((old_l0 == -1) | (dep < old_l0)), dep, old_l0)
            new_l1 = jnp.where(
                on & left & ((old_l0 == -1) | (dep < old_l0)), old_l0, lc1[nidx, hc]
            )
            new_l1 = jnp.where(
                on & left & ~((old_l0 == -1) | (dep < old_l0))
                & ((lc1[nidx, hc] == -1) | (dep < lc1[nidx, hc])),
                dep,
                new_l1,
            )
            old_r0 = rc0[nidx, hc]
            new_r0 = jnp.where(on & ~left & ((old_r0 == -1) | (dep > old_r0)), dep, old_r0)
            new_r1 = jnp.where(
                on & ~left & ((old_r0 == -1) | (dep > old_r0)), old_r0, rc1[nidx, hc]
            )
            new_r1 = jnp.where(
                on & ~left & ~((old_r0 == -1) | (dep > old_r0))
                & ((rc1[nidx, hc] == -1) | (dep > rc1[nidx, hc])),
                dep,
                new_r1,
            )
            on_h = on & (head >= 0)
            lc0 = lc0.at[nidx, hc].set(jnp.where(on_h, new_l0, lc0[nidx, hc]))
            lc1 = lc1.at[nidx, hc].set(jnp.where(on_h, new_l1, lc1[nidx, hc]))
            rc0 = rc0.at[nidx, hc].set(jnp.where(on_h, new_r0, rc0[nidx, hc]))
            rc1 = rc1.at[nidx, hc].set(jnp.where(on_h, new_r1, rc1[nidx, hc]))
            return lc0, lc1, rc0, rc1

        lc0, lc1, rc0, rc1 = st["lc0"], st["lc1"], st["rc0"], st["rc1"]
        lc0, lc1, rc0, rc1 = upd_children(lc0, lc1, rc0, rc1, b0, s0, is_la & (s0 >= 0))
        lc0, lc1, rc0, rc1 = upd_children(lc0, lc1, rc0, rc1, ra_head, b0, is_ra)

        sp = st["sp"]
        stack = st["stack"]
        # pop then (maybe) push
        sp_after_pop = jnp.where(pop, sp - 1, sp)
        stack = stack.at[nidx, jnp.clip(sp_after_pop, 0, Tlen)].set(
            jnp.where(push, b0, stack[nidx, jnp.clip(sp_after_pop, 0, Tlen)])
        )
        sp_new = jnp.where(push, sp_after_pop + 1, sp_after_pop)
        buf_new = jnp.where(is_shift | is_ra, st["buf"] + 1, st["buf"])
        return {
            "stack": stack,
            "sp": sp_new,
            "buf": buf_new,
            "heads": heads,
            "labels": labels_arr,
            "lc0": lc0,
            "lc1": lc1,
            "rc0": rc0,
            "rc1": rc1,
        }

    return {
        "init": init_state,
        "features": features,
        "valid_mask": valid_mask,
        "apply_action": apply_action,
    }


def decode_parser(
    fns: ParserModelFns,
    upper_params: Dict[str, Any],
    X: jnp.ndarray,
    lengths: jnp.ndarray,
    n_labels: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy arc-eager decode on device.

    X [B, T, D] tok2vec output; lengths [B] true lengths.
    Returns (heads [B, T] int32 with ROOT as self-index, labels [B, T]).
    """
    B, Tlen, D = X.shape
    n_act = fns.n_actions
    NEG = jnp.float32(-1e9)
    m = _arc_eager_machine(Tlen, lengths, n_labels, n_act)

    def body(st, _):
        done = (st["buf"] >= lengths) & (st["sp"] == 0)
        feats = m["features"](st)  # [B, 12]
        vecs = _gather(X, feats[:, None, :])  # [B, 1, F, D]
        flat = vecs.reshape(B, fns.n_feats * fns.width)
        logits = fns.logits(upper_params, flat)  # [B, nA]
        mask = m["valid_mask"](st)
        masked = jnp.where(mask, logits, NEG)
        action = jnp.argmax(masked, axis=-1).astype(jnp.int32)
        st = m["apply_action"](st, action, ~done)
        return st, None

    n_steps = 2 * Tlen + 2
    final, _ = jax.lax.scan(body, m["init"](), None, length=n_steps)
    heads = final["heads"]
    # ROOT (-1) and never-attached (-2) -> self (Doc convention)
    self_idx = jnp.arange(Tlen)[None, :].repeat(B, axis=0)
    heads = jnp.where(heads < 0, self_idx, heads)
    return heads, final["labels"]


def decode_parser_beam(
    fns: ParserModelFns,
    upper_params: Dict[str, Any],
    X: jnp.ndarray,
    lengths: jnp.ndarray,
    n_labels: int,
    beam_width: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Beam-search arc-eager decode (scored by summed action log-probs).

    The reference ecosystem's parser offers beam alongside greedy; here the
    beam lives as an extra leading dim on the same vectorized state machine
    — states flattened to [B*K], top-k re-selection per step, all under one
    ``lax.scan``.
    """
    K = int(beam_width)
    if K <= 1:
        return decode_parser(fns, upper_params, X, lengths, n_labels)
    B, Tlen, D = X.shape
    n_act = fns.n_actions
    NEG = jnp.float32(-1e9)
    lengths_n = jnp.repeat(lengths, K)  # [B*K]
    m = _arc_eager_machine(Tlen, lengths_n, n_labels, n_act)
    bidx = jnp.arange(B)

    def gather_beams(st, beam_idx):
        """beam_idx [B, K] source-beam per new slot -> reindexed state."""
        flat_src = (bidx[:, None] * K + beam_idx).reshape(-1)  # [B*K]

        return jax.tree_util.tree_map(lambda a: a[flat_src], st)

    def body(carry, _):
        st, scores = carry  # scores [B, K]
        done = ((st["buf"] >= lengths_n) & (st["sp"] == 0)).reshape(B, K)
        feats = m["features"](st)  # [B*K, F]
        # gather against the UN-replicated X: beams of one sentence share it,
        # so fold the beam dim into the feature dim instead of copying X K
        # times ([B, K*F] gather -> [B*K, F, D])
        vecs = _gather(X, feats.reshape(B, K * fns.n_feats))
        flat = vecs.reshape(B * K, fns.n_feats * fns.width)
        logits = fns.logits(upper_params, flat)
        mask = m["valid_mask"](st)
        masked = jnp.where(mask, logits.astype(jnp.float32), NEG)
        logp = jax.nn.log_softmax(masked, axis=-1).reshape(B, K, n_act)
        logp = jnp.where(mask.reshape(B, K, n_act), logp, NEG)
        cand = scores[:, :, None] + logp  # [B, K, nA]
        # finished beams contribute exactly ONE candidate (no-op, action 0)
        # carrying their score forward
        noop = jnp.full((B, K, n_act), NEG)
        noop = noop.at[:, :, 0].set(scores)
        cand = jnp.where(done[:, :, None], noop, cand)
        flat_cand = cand.reshape(B, K * n_act)
        new_scores, top = jax.lax.top_k(flat_cand, K)  # [B, K]
        src_beam = (top // n_act).astype(jnp.int32)
        action = (top % n_act).astype(jnp.int32)
        st = gather_beams(st, src_beam)
        done_sel = jnp.take_along_axis(done, src_beam, axis=1).reshape(-1)
        st = m["apply_action"](st, action.reshape(-1), ~done_sel)
        return (st, new_scores), None

    init_scores = jnp.full((B, K), NEG).at[:, 0].set(0.0)  # identical-beam fix
    n_steps = 2 * Tlen + 2
    (final, scores), _ = jax.lax.scan(
        body, (m["init"](), init_scores), None, length=n_steps
    )
    best = jnp.argmax(scores, axis=1)  # [B]
    flat_best = bidx * K + best
    heads = final["heads"][flat_best]
    labels = final["labels"][flat_best]
    self_idx = jnp.arange(Tlen)[None, :].repeat(B, axis=0)
    heads = jnp.where(heads < 0, self_idx, heads)
    return heads, labels


def decode_biluo_viterbi(
    logits: jnp.ndarray, lengths: jnp.ndarray, n_labels: int
) -> jnp.ndarray:
    """EXACT max-sum decode over the BILUO constraint automaton.

    The automaton has 1 + n_labels states (outside, inside-label-i); the
    chain structure makes exact Viterbi an O(T * n_labels) ``lax.scan`` —
    strictly better than greedy (which can open an entity it later regrets).
    Returns action ids [B, T] (same encoding as ``decode_biluo``).
    """
    B, Tlen, nA = logits.shape
    if n_labels == 0:
        return jnp.zeros((B, Tlen), jnp.int32)
    NEG = jnp.float32(-1e30)
    lab = jnp.arange(n_labels)
    B_cols = 1 + 4 * lab
    I_cols = 2 + 4 * lab
    L_cols = 3 + 4 * lab
    U_cols = 4 + 4 * lab
    lg = logits.astype(jnp.float32)

    def fwd(carry, t):
        dp_out, dp_in = carry  # [B], [B, L]
        sc = lg[:, t, :]  # [B, nA]
        is_last = (t + 1) >= lengths  # [B]
        # entering "outside": stay-O / U-i from outside, or L-i closing i
        stay_o = dp_out + sc[:, 0]
        u_best = dp_out[:, None] + sc[:, U_cols]  # [B, L]
        u_max = jnp.max(u_best, axis=1)
        u_arg = jnp.argmax(u_best, axis=1)
        close = dp_in + sc[:, L_cols]  # [B, L]
        close_max = jnp.max(close, axis=1)
        close_arg = jnp.argmax(close, axis=1)
        out_cands = jnp.stack([stay_o, u_max, close_max], axis=1)
        new_out = jnp.max(out_cands, axis=1)
        out_choice = jnp.argmax(out_cands, axis=1)  # 0=O, 1=U, 2=L
        out_action = jnp.where(
            out_choice == 0,
            0,
            jnp.where(out_choice == 1, U_cols[u_arg], L_cols[close_arg]),
        ).astype(jnp.int32)
        # entering "inside i": B-i from outside (not at last token) or I-i
        # continuing (not at last token — an entity must close by doc end)
        open_i = dp_out[:, None] + sc[:, B_cols]  # [B, L]
        cont_i = dp_in + sc[:, I_cols]
        not_last = ~is_last[:, None]
        open_i = jnp.where(not_last, open_i, NEG)
        cont_i = jnp.where(not_last, cont_i, NEG)
        new_in = jnp.maximum(open_i, cont_i)
        in_action = jnp.where(open_i >= cont_i, B_cols[None, :], I_cols[None, :]).astype(
            jnp.int32
        )
        # inactive (padded) positions carry state through unchanged
        active = (t < lengths)[:, None]
        new_in = jnp.where(active, new_in, dp_in)
        new_out = jnp.where(active[:, 0], new_out, dp_out)
        return (new_out, new_in), (out_action, in_action)

    init = (jnp.zeros((B,), jnp.float32), jnp.full((B, n_labels), NEG))
    (final_out, _), (out_actions, in_actions) = jax.lax.scan(
        fwd, init, jnp.arange(Tlen)
    )
    # out_actions [T, B], in_actions [T, B, L]

    def bwd(state, t):
        # state: current automaton state entering position t from the right
        # (-1 = outside, i = inside label i); emit the action taken AT t
        act_out = out_actions[t]  # [B]
        act_in = jnp.take_along_axis(
            in_actions[t], jnp.clip(state, 0, n_labels - 1)[:, None], axis=1
        )[:, 0]
        outside = state < 0
        action = jnp.where(outside, act_out, act_in)
        active = t < lengths
        action = jnp.where(active, action, 0)
        # previous state (entering position t): determined by the action type
        arc = action >= 1
        kind = jnp.where(arc, (action - 1) % 4, -1)  # 0=B,1=I,2=L,3=U
        label = jnp.where(arc, (action - 1) // 4, 0)
        # B: prev outside; I: prev inside(label); L: prev inside(label);
        # U/O: prev outside
        prev = jnp.where((kind == 1) | (kind == 2), label, -1).astype(jnp.int32)
        prev = jnp.where(active, prev, state)
        return prev, action

    start = jnp.full((B,), -1, jnp.int32)  # sequences must END outside
    _, actions_rev = jax.lax.scan(
        bwd, start, jnp.arange(Tlen - 1, -1, -1)
    )
    return actions_rev[::-1].T  # [B, T]


def decode_biluo(
    logits: jnp.ndarray, lengths: jnp.ndarray, n_labels: int
) -> jnp.ndarray:
    """Constrained greedy BILUO decode over precomputed logits.

    logits [B, T, nA] with action encoding O=0, B=1+4i, I=2+4i, L=3+4i,
    U=4+4i. Returns action ids [B, T]. The scan carries only the
    open-entity automaton state (-1 = outside).
    """
    B, Tlen, nA = logits.shape
    if n_labels == 0:  # no entity labels seen in training data: all-O
        return jnp.zeros((B, Tlen), jnp.int32)
    NEG = jnp.float32(-1e9)
    lab = jnp.arange(n_labels)
    B_cols = 1 + 4 * lab
    I_cols = 2 + 4 * lab
    L_cols = 3 + 4 * lab
    U_cols = 4 + 4 * lab

    bidx = jnp.arange(B)

    def body(open_lab, t):
        lg = logits[:, t, :]  # [B, nA]
        outside = open_lab < 0
        inside = ~outside
        is_last = (t + 1) >= lengths
        mask = jnp.zeros((B, nA), bool)
        # outside: O, U-i always; B-i only if not last token (needs an L)
        mask = mask.at[:, 0].set(outside)
        mask = mask.at[:, U_cols].set(outside[:, None])
        mask = mask.at[:, B_cols].set((outside & ~is_last)[:, None])
        # inside open label k: only I-k (if not last) or L-k
        open_c = jnp.clip(open_lab, 0, n_labels - 1)
        mask = mask.at[bidx, I_cols[open_c]].max(inside & ~is_last)
        mask = mask.at[bidx, L_cols[open_c]].max(inside)
        masked = jnp.where(mask, lg, NEG)
        act = jnp.argmax(masked, axis=-1).astype(jnp.int32)
        # new automaton state
        opens = (act >= 1) & ((act - 1) % 4 == 0)  # B-i
        conts = (act >= 2) & ((act - 2) % 4 == 0)  # I-i
        new_open = jnp.where(opens, (act - 1) // 4, jnp.where(conts, open_lab, -1))
        return new_open, act

    _, actions = jax.lax.scan(body, jnp.full((B,), -1, jnp.int32), jnp.arange(Tlen))
    return actions.T  # [B, T]