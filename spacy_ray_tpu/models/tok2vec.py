"""Token-to-vector architectures: MultiHashEmbed + CNN window encoder.

These are the registered ``@architectures`` the config files reference — the
same names the reference's configs use for its pipeline models (trained by
reference worker.py:91 ``init_nlp`` → thinc layers; SURVEY.md §2.3 row
"Thinc ops"). Registered under the canonical ``spacy.*`` names so a config
written for the reference resolves unchanged.

TPU notes: the embedding is 4-row murmur gather-sum fused by XLA; the encoder
is depth× [seq2col → maxout → layernorm → residual] where seq2col lowers to
pad+shift slices (no gather), keeping the hot path as three large MXU
matmuls per layer.
"""

from __future__ import annotations

from typing import List, Optional

from ..registry import registry
from ..ops.hashing import hash_string_u64
from .core import Model, chain, residual
from .layers import (
    ConcatPadded,
    Dropout,
    HashEmbed,
    LayerNorm,
    Maxout,
    Seq2Col,
)

# Canonical ordering of lexical attributes in TokenBatch.attr_keys
# (pipeline/vocab.py featurizes in this order).
ATTRS = ("NORM", "PREFIX", "SUFFIX", "SHAPE")


def attr_index(attr: str) -> int:
    try:
        return ATTRS.index(attr.upper())
    except ValueError:
        raise ValueError(f"Unknown attr {attr!r}; supported: {ATTRS}")


@registry.architectures("spacy.MultiHashEmbed.v2")
def MultiHashEmbed(
    width: int,
    attrs: Optional[List[str]] = None,
    rows: Optional[List[int]] = None,
    include_static_vectors: bool = False,
) -> Model:
    """Embed tokens by hashing multiple lexical attributes into tables.

    Per attr: HashEmbed(width, rows[i]); concatenated and mixed by a Maxout
    projection to `width` + LayerNorm, matching the capability of the
    reference's embedding stack.
    """
    if attrs is None:
        attrs = list(ATTRS)
    if rows is None:
        rows = [5000] + [2500] * (len(attrs) - 1)
    if len(rows) != len(attrs):
        raise ValueError(f"len(rows) != len(attrs): {rows} vs {attrs}")
    embeds = [
        HashEmbed(
            width,
            int(r),
            seed=hash_string_u64(f"hashembed-{a}-{i}") & 0x7FFFFFFF,
            attr_index=attr_index(a),
            name=f"embed_{a.lower()}",
        )
        for i, (a, r) in enumerate(zip(attrs, rows))
    ]
    n_inputs = len(attrs)
    if include_static_vectors:
        from .layers import StaticVectors

        embeds.append(StaticVectors(width))
        n_inputs += 1
    concat = ConcatPadded(*embeds, name="embeds")
    mix = chain(
        concat,
        Maxout(width * n_inputs, width, nP=3, name="mix"),
        LayerNorm(width),
        name="multi_hash_embed",
    )
    mix.dims.update({"nO": width})
    return mix


@registry.architectures("spacy.MultiHashEmbed.v1")
def MultiHashEmbedV1(
    width: int,
    rows: int = 7000,
    also_embed_subwords: bool = True,
    also_use_static_vectors: bool = False,
) -> Model:
    """v1 signature adapter: a single row count + subword flag maps onto
    the v2 attr/rows form (NORM at full rows; PREFIX/SUFFIX/SHAPE at half
    when subwords are embedded)."""
    if also_embed_subwords:
        attrs = ["NORM", "PREFIX", "SUFFIX", "SHAPE"]
        row_list = [rows, rows // 2, rows // 2, rows // 2]
    else:
        attrs = ["NORM"]
        row_list = [rows]
    return MultiHashEmbed(
        width,
        attrs=attrs,
        rows=row_list,
        include_static_vectors=also_use_static_vectors,
    )


@registry.architectures("spacy.MaxoutWindowEncoder.v1")
@registry.architectures("spacy.MaxoutWindowEncoder.v2")
def MaxoutWindowEncoder(
    width: int,
    window_size: int = 1,
    maxout_pieces: int = 3,
    depth: int = 4,
) -> Model:
    """depth × residual[seq2col(window) → maxout → layernorm]."""

    def block(i: int) -> Model:
        return residual(
            chain(
                Seq2Col(window_size, width),
                Maxout(width * (2 * window_size + 1), width, nP=maxout_pieces),
                LayerNorm(width),
                name=f"cnn_{i}",
            ),
            name=f"res_{i}",
        )

    layers = [block(i) for i in range(depth)]
    enc = chain(*layers, name="maxout_window_encoder")
    enc.dims.update({"nI": width, "nO": width})
    return enc


@registry.architectures("spacy.TorchBiLSTMEncoder.v1")
def TorchBiLSTMEncoder(width: int, depth: int = 2, dropout: float = 0.0) -> Model:
    raise NotImplementedError(
        "BiLSTM encoder is not provided on TPU; use spacy.MaxoutWindowEncoder.v2 "
        "or the transformer backbone (data-dependent recurrence maps poorly to XLA)."
    )


@registry.architectures("spacy.Tok2Vec.v1")
@registry.architectures("spacy.Tok2Vec.v2")
def Tok2Vec(embed: Model, encode: Model) -> Model:
    t2v = chain(embed, encode, name="tok2vec")
    t2v.dims.update({"nO": encode.dims.get("nO", embed.dims.get("nO", 0))})
    return t2v


@registry.architectures("spacy.HashEmbedCNN.v1")
@registry.architectures("spacy.HashEmbedCNN.v2")
def HashEmbedCNN(
    width: int,
    depth: int,
    embed_size: int,
    window_size: int = 1,
    maxout_pieces: int = 3,
    subword_features: bool = True,
    pretrained_vectors: Optional[str] = None,
    dropout: Optional[float] = None,
) -> Model:
    """The standard CNN tok2vec (BASELINE.json config #1's backbone)."""
    attrs = list(ATTRS) if subword_features else ["NORM"]
    rows = [embed_size] + [embed_size // 2] * (len(attrs) - 1)
    embed = MultiHashEmbed(
        width=width, attrs=attrs, rows=rows,
        include_static_vectors=bool(pretrained_vectors),
    )
    layers = [embed]
    if dropout:
        layers.append(Dropout(dropout))
    encode = MaxoutWindowEncoder(
        width=width,
        window_size=window_size,
        maxout_pieces=maxout_pieces,
        depth=depth,
    )
    layers.append(encode)
    t2v = chain(*layers, name="hash_embed_cnn")
    t2v.dims.update({"nO": width})
    return t2v
