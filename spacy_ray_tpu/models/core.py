"""Functional model core: named layers over jnp pytrees.

Capability parity with the thinc ``Model`` tree the reference's param
plumbing walks (reference util.py:41-75 ``set_params_proxy`` /
``divide_params`` over ``model.walk()``; SURVEY.md §2.1). Design differences,
deliberately TPU-first:

* A model is a pair of pure functions ``init(rng) -> params`` and
  ``apply(params, x, ctx) -> y``; params are nested dicts of jnp arrays.
* Parameter identity is the **path string** in the params pytree
  ("embed/norm/b"), stable across processes — fixing the fragile per-process
  ``(node.id, name)`` identity of the reference (reference util.py:6,53-54;
  SURVEY.md §2.4 "Key identity is fragile").
* There is no mutable parameter server / proxy hook: distribution happens by
  sharding the params pytree under GSPMD, not by intercepting get_param
  (reference proxies.py:86-109 becomes a sharding annotation).
* Initialization takes explicit dimensions from the config (no lazy shape
  inference), so every shape is static under jit.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp


Params = Dict[str, Any]


@dataclass
class Context:
    """Per-call context threaded through apply: dropout rng, train flag,
    and an optional auxiliary-loss sink (``aux_losses``) that layers with
    regularizer terms (e.g. the MoE router's load-balancing loss) append
    to during tracing; the loss builder sums it into the total.

    ``dropout`` is the global training-time dropout override: when set,
    every dropout site uses it in place of its architecture-configured
    rate — the equivalent of spaCy's ``set_dropout_rate(model, drop)``
    call with ``[training] dropout`` before each update (reference
    worker.py:181 passes it into ``train_while_improving``). ``None``
    (the predict path and direct ``apply`` calls) keeps each layer's own
    configured rate."""

    train: bool = False
    rng: Optional[jax.Array] = None
    aux_losses: Optional[list] = None
    dropout: Optional[float] = None

    def split(self) -> Tuple["Context", "Context"]:
        if self.rng is None:
            return self, self
        r1, r2 = jax.random.split(self.rng)
        return (
            Context(self.train, r1, self.aux_losses, self.dropout),
            Context(self.train, r2, self.aux_losses, self.dropout),
        )

    def dropout_rate(self, configured: float) -> float:
        """The effective dropout rate at a site whose architecture default
        is ``configured`` (static Python float — resolved at trace time)."""
        return self.dropout if self.dropout is not None else configured

    def add_aux_loss(self, value) -> None:
        if self.aux_losses is not None:
            self.aux_losses.append(value)


@dataclass
class Model:
    """A named pure-function layer.

    ``init(rng) -> params``; ``apply(params, x, ctx) -> y``.
    ``dims`` records static dimensions ("nI", "nO", ...) for introspection
    and head wiring. ``layers`` are the children (for walk()).
    """

    name: str
    init_fn: Callable[[jax.Array], Params]
    apply_fn: Callable[[Params, Any, Context], Any]
    dims: Dict[str, int] = field(default_factory=dict)
    layers: List["Model"] = field(default_factory=list)
    meta: Dict[str, Any] = field(default_factory=dict)

    def init(self, rng: jax.Array) -> Params:
        return self.init_fn(rng)

    def apply(self, params: Params, x: Any, ctx: Optional[Context] = None) -> Any:
        return self.apply_fn(params, x, ctx or Context())

    def __call__(self, params: Params, x: Any, ctx: Optional[Context] = None) -> Any:
        return self.apply(params, x, ctx)

    def walk(self) -> Iterator["Model"]:
        """DFS over the model tree, like thinc's ``Model.walk()``
        (reference util.py:44, 62)."""
        yield self
        for layer in self.layers:
            yield from layer.walk()

    def get_dim(self, name: str) -> int:
        if name not in self.dims:
            raise KeyError(f"Model {self.name} has no dim {name!r}; has {self.dims}")
        return self.dims[name]


def prune_empty(params: Params) -> Params:
    """Drop empty sub-dicts (param-less layers) for a canonical pytree
    structure — save/load (npz) can't represent empty dicts, and optax
    states must structurally match params, so the canonical form never
    contains them. ``apply`` tolerates the missing keys via .get()."""
    if not isinstance(params, dict):
        return params
    out = {}
    for k, v in params.items():
        if isinstance(v, dict):
            pruned = prune_empty(v)
            if pruned:
                out[k] = pruned
        else:
            out[k] = v
    return out


def param_paths(params: Params, prefix: str = "") -> List[str]:
    """Flatten a params pytree into stable '/'-joined path strings."""
    out: List[str] = []
    if isinstance(params, dict):
        for k in sorted(params):
            sub = prefix + ("/" if prefix else "") + str(k)
            out.extend(param_paths(params[k], sub))
    else:
        out.append(prefix)
    return out


def count_params(params: Params) -> int:
    leaves = jax.tree_util.tree_leaves(params)
    return int(sum(x.size for x in leaves if hasattr(x, "size")))


# ----------------------------------------------------------------------
# Initializers
# ----------------------------------------------------------------------


def glorot_uniform(rng: jax.Array, shape: Tuple[int, ...], dtype=jnp.float32) -> jnp.ndarray:
    fan_in, fan_out = shape[0], shape[-1]
    limit = (6.0 / (fan_in + fan_out)) ** 0.5
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


def normal_init(rng: jax.Array, shape: Tuple[int, ...], stddev: float, dtype=jnp.float32) -> jnp.ndarray:
    return jax.random.normal(rng, shape, dtype) * stddev


def zeros(shape: Tuple[int, ...], dtype=jnp.float32) -> jnp.ndarray:
    return jnp.zeros(shape, dtype)


def ones(shape: Tuple[int, ...], dtype=jnp.float32) -> jnp.ndarray:
    return jnp.ones(shape, dtype)


# ----------------------------------------------------------------------
# Combinators
# ----------------------------------------------------------------------


def _child_key(i: int, layer: Model) -> str:
    return f"{i}_{layer.name}"


def chain(*layers: Model, name: str = "chain") -> Model:
    """Feed-forward composition. Params keyed '{i}_{childname}'."""

    def init_fn(rng: jax.Array) -> Params:
        rngs = jax.random.split(rng, len(layers))
        return {
            _child_key(i, layer): layer.init(rngs[i]) for i, layer in enumerate(layers)
        }

    def apply_fn(params: Params, x: Any, ctx: Context) -> Any:
        for i, layer in enumerate(layers):
            ctx, sub = ctx.split()
            x = layer.apply(params.get(_child_key(i, layer), {}), x, sub)
        return x

    dims = {}
    if layers and "nI" in layers[0].dims:
        dims["nI"] = layers[0].dims["nI"]
    if layers and "nO" in layers[-1].dims:
        dims["nO"] = layers[-1].dims["nO"]
    return Model(name, init_fn, apply_fn, dims=dims, layers=list(layers))


def residual(layer: Model, name: str = "residual") -> Model:
    def init_fn(rng: jax.Array) -> Params:
        return {"inner": layer.init(rng)}

    def apply_fn(params: Params, x: Any, ctx: Context) -> Any:
        out = layer.apply(params.get("inner", {}), x, ctx)
        # generic over raw arrays and Padded-style containers with .X
        if hasattr(out, "X") and hasattr(x, "X"):
            return type(out)(X=x.X + out.X, mask=out.mask)
        return x + out

    return Model(name, init_fn, apply_fn, dims=dict(layer.dims), layers=[layer])


def clone(layer_factory: Callable[[int], Model], n: int, name: str = "clone") -> Model:
    """n independent copies (distinct params), chained."""
    layers = [layer_factory(i) for i in range(n)]
    return chain(*layers, name=name)
