"""Local-file pretrained weight loading for the transformer trunk.

The reference ecosystem starts en_core_web_trf from a pretrained RoBERTa
checkpoint (BASELINE.json config #4; the reference trains whatever the
config names, reference worker.py:91). This environment is zero-egress, so
downloading is impossible — but a LOCAL file must load the moment an asset
exists in-image (VERDICT r1 missing #3). Two formats:

* ``.npz`` — the native schema. Keys are '/'-joined paths into the trunk's
  param tree, exactly what ``save_trunk_params`` writes:

      pos                     [max_len, width]   positional embeddings
      ln_f_g, ln_f_b          [width]            final layernorm
      layer_{i}/qkv_W         [width, 3*width]   fused q,k,v projection
      layer_{i}/qkv_b         [3*width]
      layer_{i}/o_W           [width, width]     attention output
      layer_{i}/o_b           [width]
      layer_{i}/ln1_g|ln1_b   [width]            pre-attention layernorm
      layer_{i}/ffn_W1        [width, ffn]
      layer_{i}/ffn_b1        [ffn]
      layer_{i}/ffn_W2        [ffn, width]
      layer_{i}/ffn_b2        [width]
      layer_{i}/ln2_g|ln2_b   [width]            pre-FFN layernorm
      embed/...               hash-embed featurizer tables (optional)

* ``.safetensors`` — parsed with a built-in reader (the format is an 8-byte
  little-endian header length + JSON header + raw buffer; no dependency).
  If the key set looks like a HuggingFace RoBERTa/BERT encoder
  (``encoder.layer.N.attention...``), it is remapped to the native schema:
  q/k/v weights are fused into qkv_W (transposed: torch Linear stores
  [out, in]), FFN and layernorm weights map by position. NOTE this trunk
  is pre-LN while BERT/RoBERTa are post-LN, and the input featurizer is
  hash-embed rather than BPE — an HF remap is a warm start for the encoder
  stack, not an exact port; the embedding block always stays native.

Every merged tensor is shape-checked; a mismatch is an error, not a warning.
"""

from __future__ import annotations

import json
import struct
from pathlib import Path
from typing import Any, Dict, List, Tuple

import numpy as np

_SAFETENSORS_DTYPES = {
    "F64": np.float64,
    "F32": np.float32,
    "F16": np.float16,
    "I64": np.int64,
    "I32": np.int32,
    "I16": np.int16,
    "I8": np.int8,
    "U8": np.uint8,
    "BOOL": np.bool_,
}


def read_safetensors(path) -> Dict[str, np.ndarray]:
    """Minimal safetensors reader (header-JSON + raw little-endian buffer)."""
    raw = Path(path).read_bytes()
    if len(raw) < 8:
        raise ValueError(f"{path}: not a safetensors file (too short)")
    (header_len,) = struct.unpack("<Q", raw[:8])
    header = json.loads(raw[8 : 8 + header_len].decode("utf8"))
    buf = raw[8 + header_len :]
    out: Dict[str, np.ndarray] = {}
    for name, meta in header.items():
        if name == "__metadata__":
            continue
        dtype_name = meta["dtype"]
        if dtype_name == "BF16":
            import ml_dtypes

            dtype = ml_dtypes.bfloat16
        else:
            dtype = _SAFETENSORS_DTYPES.get(dtype_name)
            if dtype is None:
                raise ValueError(f"{path}: unsupported dtype {dtype_name} for {name}")
        start, end = meta["data_offsets"]
        arr = np.frombuffer(buf[start:end], dtype=dtype).reshape(meta["shape"])
        if dtype_name in ("F64", "F16", "BF16"):
            arr = arr.astype(np.float32)  # params are fp32 in this trunk
        out[name] = arr
    return out


def write_safetensors(path, tensors: Dict[str, np.ndarray]) -> None:
    """Minimal safetensors writer (float32/ints; the reader's inverse)."""
    inv = {np.dtype(v): k for k, v in _SAFETENSORS_DTYPES.items()}
    header: Dict[str, Any] = {}
    offset = 0
    blobs: List[bytes] = []
    for name in sorted(tensors):
        arr = np.ascontiguousarray(tensors[name])
        dtype_name = inv.get(arr.dtype)
        if dtype_name is None:
            raise ValueError(f"unsupported dtype {arr.dtype} for {name}")
        blob = arr.tobytes()
        header[name] = {
            "dtype": dtype_name,
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + len(blob)],
        }
        offset += len(blob)
        blobs.append(blob)
    hj = json.dumps(header).encode("utf8")
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hj)))
        f.write(hj)
        for blob in blobs:
            f.write(blob)


def load_flat(path) -> Dict[str, np.ndarray]:
    """Load a checkpoint file into a flat {key: array} dict.

    A directory (the standard HF save layout) resolves to its
    ``model.safetensors``."""
    path = Path(path)
    if path.is_dir():
        inner = path / "model.safetensors"
        if not inner.exists():
            raise ValueError(
                f"{path} is a directory without model.safetensors; point at "
                "the checkpoint file itself (.npz or .safetensors)"
            )
        path = inner
    if path.suffix == ".npz":
        with np.load(str(path)) as data:
            return {k: data[k] for k in data.files}
    if path.suffix == ".safetensors":
        return read_safetensors(path)
    raise ValueError(
        f"Unsupported checkpoint format {path.suffix!r} (want .npz or .safetensors)"
    )


def _flatten(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            sub = f"{prefix}/{k}" if prefix else str(k)
            out.update(_flatten(tree[k], sub))
    else:
        out[prefix] = np.asarray(tree)
    return out


def save_trunk_params(path, trunk_params: Any) -> None:
    """Write trunk params as the native .npz schema (see module docstring)."""
    np.savez(str(path), **_flatten(trunk_params))


def looks_like_hf_encoder(flat: Dict[str, np.ndarray]) -> bool:
    return any(".attention.self.query.weight" in k for k in flat)


def hf_encoder_to_native(
    flat: Dict[str, np.ndarray], native_pos_rows: "int | None" = None
) -> Dict[str, np.ndarray]:
    """Remap HuggingFace BERT/RoBERTa encoder keys to the native schema.

    Torch Linear weights are [out, in] and are transposed; q, k, v fuse
    into qkv_W/qkv_b. Embedding-block keys are dropped (the native trunk
    featurizes with hash embeddings). Positional embeddings are taken if
    present; RoBERTa checkpoints (detected from the key prefix) skip the 2
    pad-reserved leading rows, BERT keeps all rows.
    """

    def find(suffix: str):
        for k, v in flat.items():
            if k.endswith(suffix):
                return v
        return None

    out: Dict[str, np.ndarray] = {}
    # RoBERTa reserves position rows 0-1 for padding (positions start at 2);
    # BERT does not. Detectable from the model-prefix in the key names.
    is_roberta = any("roberta" in k.lower() for k in flat)
    i = 0
    while True:
        pre = None
        for cand in (f"encoder.layer.{i}.", f"roberta.encoder.layer.{i}."):
            if any(k.startswith(cand) for k in flat):
                pre = cand
                break
        if pre is None:
            break
        q_w = flat[pre + "attention.self.query.weight"].T
        k_w = flat[pre + "attention.self.key.weight"].T
        v_w = flat[pre + "attention.self.value.weight"].T
        out[f"layer_{i}/qkv_W"] = np.concatenate([q_w, k_w, v_w], axis=1)
        out[f"layer_{i}/qkv_b"] = np.concatenate(
            [
                flat[pre + "attention.self.query.bias"],
                flat[pre + "attention.self.key.bias"],
                flat[pre + "attention.self.value.bias"],
            ]
        )
        out[f"layer_{i}/o_W"] = flat[pre + "attention.output.dense.weight"].T
        out[f"layer_{i}/o_b"] = flat[pre + "attention.output.dense.bias"]
        out[f"layer_{i}/ln1_g"] = flat[pre + "attention.output.LayerNorm.weight"]
        out[f"layer_{i}/ln1_b"] = flat[pre + "attention.output.LayerNorm.bias"]
        out[f"layer_{i}/ffn_W1"] = flat[pre + "intermediate.dense.weight"].T
        out[f"layer_{i}/ffn_b1"] = flat[pre + "intermediate.dense.bias"]
        out[f"layer_{i}/ffn_W2"] = flat[pre + "output.dense.weight"].T
        out[f"layer_{i}/ffn_b2"] = flat[pre + "output.dense.bias"]
        out[f"layer_{i}/ln2_g"] = flat[pre + "output.LayerNorm.weight"]
        out[f"layer_{i}/ln2_b"] = flat[pre + "output.LayerNorm.bias"]
        i += 1
    if i == 0:
        raise ValueError("no encoder.layer.N.* keys found in HF checkpoint")
    pos = find("position_embeddings.weight")
    if pos is not None:
        # the target row count disambiguates prefix-less exports: a table
        # exactly 2 rows longer than the trunk's is RoBERTa-style (2 pad-
        # reserved rows), an exact match is BERT-style; otherwise fall back
        # to the key-prefix heuristic
        if native_pos_rows is not None and pos.shape[0] == native_pos_rows + 2:
            pos = pos[2:]
        elif native_pos_rows is not None and pos.shape[0] == native_pos_rows:
            pass
        elif is_roberta and pos.shape[0] > 2:
            pos = pos[2:]
        out["pos"] = pos
    return out


def merge_pretrained(
    params: Dict[str, Any], flat_loaded: Dict[str, np.ndarray]
) -> Tuple[Dict[str, Any], Dict[str, List[str]]]:
    """Merge loaded tensors into a freshly initialized trunk param tree.

    Returns (new_params, report) where report lists 'loaded', 'missing'
    (param present, no tensor in file — stays at its random init) and
    'unused' (tensor in file with no matching param). Shape mismatches
    raise ValueError naming the key and both shapes.
    """
    import jax.numpy as jnp

    flat_params = _flatten(params)
    loaded: List[str] = []
    unused = [k for k in flat_loaded if k not in flat_params]
    missing = [k for k in flat_params if k not in flat_loaded]
    merged_flat: Dict[str, np.ndarray] = {}
    for key, cur in flat_params.items():
        if key in flat_loaded:
            new = np.asarray(flat_loaded[key], dtype=np.float32)
            if tuple(new.shape) != tuple(cur.shape):
                # pos tables may legitimately differ in length: truncate or
                # keep-random-tail, but only for the leading (length) dim
                if key == "pos" and new.shape[1:] == cur.shape[1:]:
                    n = min(new.shape[0], cur.shape[0])
                    out = np.array(cur, dtype=np.float32)
                    out[:n] = new[:n]
                    merged_flat[key] = out
                    loaded.append(key)
                    continue
                raise ValueError(
                    f"pretrained tensor {key!r} has shape {tuple(new.shape)}, "
                    f"param expects {tuple(cur.shape)}"
                )
            merged_flat[key] = new
            loaded.append(key)
        else:
            merged_flat[key] = np.asarray(cur)

    root: Dict[str, Any] = {}
    for path, arr in merged_flat.items():
        parts = path.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(arr)
    report = {"loaded": loaded, "missing": missing, "unused": unused}
    return root, report


def load_trunk_weights(params: Dict[str, Any], path) -> Dict[str, Any]:
    """Load + (maybe) remap + shape-checked merge; prints a one-line report."""
    flat = load_flat(path)
    if looks_like_hf_encoder(flat):
        pos = params.get("pos")
        flat = hf_encoder_to_native(
            flat, native_pos_rows=None if pos is None else int(pos.shape[0])
        )
    merged, report = merge_pretrained(params, flat)
    if not report["loaded"]:
        sample = ", ".join(sorted(flat)[:5])
        raise ValueError(
            f"no tensors in {path} matched the trunk schema — the file's "
            f"keys (e.g. {sample}) are neither the native layout "
            "(models/pretrained.py docstring) nor a recognizable "
            "BERT/RoBERTa encoder; refusing to train from scratch when "
            "pretrained weights were requested"
        )
    print(
        f"[transformer] loaded {len(report['loaded'])} tensors from {path} "
        f"({len(report['missing'])} left at init, "
        f"{len(report['unused'])} unused in file)",
        flush=True,
    )
    return merged
