"""Token-pattern matcher shared by entity_ruler and attribute_ruler.

Capability parity with spaCy's Matcher pattern language (the rule engine the
reference ecosystem's ruler pipes are built on — SURVEY.md §2.3 "spaCy
core"; host-side by design, like all preprocessing here):

* token keys: ``TEXT``, ``LOWER``, ``TAG``, ``POS``, ``LEMMA``, ``SHAPE``,
  ``LENGTH``, ``IS_DIGIT``, ``IS_ALPHA``, ``IS_TITLE``, ``IS_UPPER``,
  ``IS_LOWER``, ``IS_PUNCT``. TAG/POS/LEMMA read the doc's annotations, so
  rules using them must run AFTER the components that set them (pipe order
  is the user's contract, as in spaCy).
* values: a literal, or a predicate dict with any of
  ``REGEX`` (re.search), ``IN``, ``NOT_IN``, ``==``, ``!=``, ``>=``,
  ``<=``, ``>``, ``<`` — e.g. ``{"LOWER": {"IN": ["inc", "corp"]}}``,
  ``{"LENGTH": {">=": 10}}``, ``{"TEXT": {"REGEX": "^[A-Z]{2,4}$"}}``.
* ``OP``: ``1`` (default), ``?``, ``*``, ``+``, ``!`` (negate, one token),
  ``{n}``, ``{n,m}``, ``{n,}``, ``{,m}``.

Matching is greedy with backtracking; ``match_pattern`` returns the longest
match end. Patterns are validated eagerly (``validate_token_patterns``) so
misconfigured rules fail at config/load time, not at the first token.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

from .vocab import shape_of

_PRED_OPS = ("REGEX", "IN", "NOT_IN", "==", "!=", ">=", "<=", ">", "<")
_BOOL_KEYS = {
    "IS_DIGIT": str.isdigit,
    "IS_ALPHA": str.isalpha,
    "IS_TITLE": str.istitle,
    "IS_UPPER": str.isupper,
    "IS_LOWER": str.islower,
    "IS_PUNCT": lambda w: bool(w) and all(not c.isalnum() for c in w),
}
_DOC_KEYS = ("TEXT", "LOWER", "TAG", "POS", "LEMMA", "SHAPE", "LENGTH")
SUPPORTED_TOKEN_KEYS = _DOC_KEYS + tuple(_BOOL_KEYS) + ("OP",)

_OP_RE = re.compile(r"^(!|\?|\*|\+|1|\{\d+\}|\{\d+,\d*\}|\{,\d+\})$")


def _op_bounds(op: str) -> Tuple[int, Optional[int], bool]:
    """(min_reps, max_reps or None=unbounded, negate)."""
    if op == "1":
        return 1, 1, False
    if op == "!":
        return 1, 1, True
    if op == "?":
        return 0, 1, False
    if op == "*":
        return 0, None, False
    if op == "+":
        return 1, None, False
    m = _OP_RE.match(op)
    if m and op.startswith("{"):
        body = op[1:-1]
        if "," not in body:
            n = int(body)
            return n, n, False
        lo_s, hi_s = body.split(",", 1)
        lo = int(lo_s) if lo_s else 0
        hi = int(hi_s) if hi_s else None
        return lo, hi, False
    raise ValueError(f"Unsupported OP {op!r}")


def validate_token_patterns(patterns) -> None:
    """Eager validation of token-pattern lists: keys, OP syntax, predicate
    dicts (REGEX must compile, IN/NOT_IN must be sequences). Shared by both
    rulers so bad rules fail at config/deserialize time."""
    for pattern in patterns:
        if isinstance(pattern, str):
            continue
        for tok in pattern:
            for key, want in tok.items():
                if key == "OP":
                    op = str(want)
                    if not _OP_RE.match(op):
                        raise ValueError(
                            f"Unsupported OP {want!r}; supported: "
                            "1 ? * + ! {n} {n,m} {n,} {,m}"
                        )
                    _op_bounds(op)  # range syntax must parse
                    continue
                if key not in SUPPORTED_TOKEN_KEYS:
                    raise ValueError(
                        f"Unsupported token-pattern key {key!r}; "
                        f"supported: {sorted(SUPPORTED_TOKEN_KEYS)}"
                    )
                if isinstance(want, dict):
                    for pop, arg in want.items():
                        if pop not in _PRED_OPS:
                            raise ValueError(
                                f"Unsupported predicate {pop!r} for {key}; "
                                f"supported: {_PRED_OPS}"
                            )
                        if pop == "REGEX":
                            re.compile(arg)  # must compile now, not mid-match
                        elif pop in ("IN", "NOT_IN"):
                            if not isinstance(arg, (list, tuple, set)):
                                raise ValueError(
                                    f"{key}.{pop} wants a list, got "
                                    f"{type(arg).__name__}"
                                )
                        elif pop in (">=", "<=", ">", "<", "==", "!="):
                            # the comparison runs against this key's value
                            # type at match time — a mismatch there would be
                            # a TypeError mid-inference, so reject it NOW
                            if key == "LENGTH" and not isinstance(
                                arg, (int, float)
                            ):
                                raise ValueError(
                                    f"LENGTH.{pop} wants a number, got "
                                    f"{type(arg).__name__}"
                                )
                            if key != "LENGTH" and pop in (">=", "<=", ">", "<") and not isinstance(arg, str):
                                raise ValueError(
                                    f"{key}.{pop} wants a string, got "
                                    f"{type(arg).__name__}"
                                )


def _attr_value(doc, i: int, key: str):
    w = doc.words[i]
    if key == "TEXT":
        return w
    if key == "LOWER":
        return w.lower()
    if key == "SHAPE":
        return shape_of(w)
    if key == "LENGTH":
        return len(w)
    if key == "TAG":
        return (doc.tags[i] if doc.tags else "") or ""
    if key == "POS":
        return (doc.pos[i] if doc.pos else "") or ""
    if key == "LEMMA":
        return (doc.lemmas[i] if doc.lemmas else "") or ""
    fn = _BOOL_KEYS.get(key)
    if fn is not None:
        return fn(w)
    raise ValueError(f"Unsupported token-pattern key {key!r}")


def _value_matches(actual, want) -> bool:
    if isinstance(want, dict):
        for op, arg in want.items():
            if op == "REGEX":
                ok = re.search(arg, str(actual)) is not None
            elif op == "IN":
                ok = actual in arg
            elif op == "NOT_IN":
                ok = actual not in arg
            elif op == "==":
                ok = actual == arg
            elif op == "!=":
                ok = actual != arg
            elif op == ">=":
                ok = actual >= arg
            elif op == "<=":
                ok = actual <= arg
            elif op == ">":
                ok = actual > arg
            elif op == "<":
                ok = actual < arg
            else:
                raise ValueError(f"Unsupported predicate {op!r}")
            if not ok:
                return False
        return True
    if isinstance(want, bool):
        return bool(actual) == want
    return actual == want


def token_matches(doc, i: int, constraint: Dict[str, Any]) -> bool:
    """Does token i of doc satisfy every (non-OP) key of the constraint?"""
    for key, want in constraint.items():
        if key == "OP":
            continue
        if not _value_matches(_attr_value(doc, i, key), want):
            return False
    return True


def match_pattern(doc, pattern: List[Dict[str, Any]], start: int) -> Optional[int]:
    """Match ``pattern`` at ``start``; returns the end (exclusive) of the
    LONGEST match, or None. Greedy with backtracking."""
    n = len(doc.words)

    def rec(pi: int, wi: int) -> Optional[int]:
        if pi == len(pattern):
            return wi
        tok = pattern[pi]
        lo, hi, neg = _op_bounds(str(tok.get("OP", "1")))

        def ok(i: int) -> bool:
            if i >= n:
                return False
            m = token_matches(doc, i, tok)
            return (not m) if neg else m

        limit = (n - wi) if hi is None else min(hi, n - wi)
        cnt = 0
        while cnt < limit and ok(wi + cnt):
            cnt += 1
        for take in range(cnt, lo - 1, -1):
            got = rec(pi + 1, wi + take)
            if got is not None:
                return got
        return None

    return rec(0, start)
