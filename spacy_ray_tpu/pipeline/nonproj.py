"""Pseudo-projective dependency transformation (Nivre & Nilsson 2005).

The arc-eager machine (pipeline/transition.py) can only build projective
trees, but real treebanks contain non-projective arcs; spaCy — the parser
stack the reference actually trains (SURVEY.md §2.3 "spaCy core",
``nn_parser.pyx`` + ``nonproj.pyx``) — handles them by projectivizing gold
trees before oracle extraction and undoing the transform at decode. Same
scheme here, in the N&N "head" encoding:

* ``projectivize``: repeatedly lift the smallest non-projective arc to the
  grandparent until the tree is projective. Every lifted dependent's label
  is decorated ``childlabel||headlabel``, recording the label of its
  ORIGINAL head so decode can find the attachment point again.
* ``deprojectivize``: for each decorated token, search the current head's
  subtree for the nearest token carrying ``headlabel`` and reattach there.

Head convention: ``heads[i] == i`` marks a root token (this repo's Doc
convention, training/corpus.py).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

DELIMITER = "||"


def decompose_label(label: str) -> Tuple[str, str]:
    """'advmod||conj' -> ('advmod', 'conj'); undecorated -> (label, '')."""
    if DELIMITER in label:
        a, b = label.split(DELIMITER, 1)
        return a, b
    return label, ""


def is_decorated(label: str) -> bool:
    return DELIMITER in label


def _valid_heads(heads: Sequence[int]) -> bool:
    n = len(heads)
    return all(0 <= h < n for h in heads)


def _is_nonproj_arc(d: int, heads: Sequence[int]) -> bool:
    h = heads[d]
    if h == d:
        return False
    lo, hi = (h, d) if h < d else (d, h)
    for k in range(lo + 1, hi):
        hk = heads[k]
        # a root inside the span counts as non-projective too: its virtual
        # ROOT arc (from position -1) necessarily crosses (h, d)
        if hk == k or hk < lo or hk > hi:
            return True
    return False


def _smallest_nonproj_arc(heads: Sequence[int]) -> Optional[int]:
    best, best_size = None, None
    for d, h in enumerate(heads):
        if h == d:
            continue
        if _is_nonproj_arc(d, heads):
            size = abs(h - d)
            if best is None or size < best_size:
                best, best_size = d, size
    return best


def is_projective(heads: Sequence[int]) -> bool:
    """Strict projectivity: crossing arcs AND roots covered by another arc's
    span count as non-projective (both are unreachable for the arc-eager
    machine, whose virtual ROOT sits left of the sentence). Malformed input
    (out-of-range heads) is 'not projective' rather than an exception."""
    if not _valid_heads(heads):
        return False
    return _smallest_nonproj_arc(heads) is None


def projectivize(
    heads: Sequence[int], labels: Sequence[str]
) -> Optional[Tuple[List[int], List[str], int]]:
    """Lift non-projective arcs until the tree is projective.

    Returns (proj_heads, decorated_labels, n_lifted), or None if lifting
    failed to converge (malformed input: cycles, out-of-range heads).
    n_lifted == 0 means the tree was already projective (labels returned
    unchanged).
    """
    n = len(heads)
    if not _valid_heads(heads):
        return None
    proj = list(heads)
    lifted = set()
    max_iter = n * n + 10
    for _ in range(max_iter):
        d = _smallest_nonproj_arc(proj)
        if d is None:
            break
        h = proj[d]
        if not (0 <= h < n):
            return None
        gp = proj[h]
        # lift to the grandparent; when the head is itself a root, the
        # dependent becomes a root (its virtual-ROOT arc can't cross)
        proj[d] = d if gp == h else gp
        lifted.add(d)
    else:
        return None  # didn't converge within the bound
    deco = list(labels)
    for d in lifted:
        head_label = labels[heads[d]]
        # an empty head label can't guide reattachment — leave the lifted
        # arc undecorated (still trainable, just not recoverable) rather
        # than emit a dangling "label||"
        if head_label:
            deco[d] = f"{labels[d]}{DELIMITER}{head_label}"
    return proj, deco, len(lifted)


def _subtree(root: int, heads: Sequence[int]) -> List[int]:
    """All strict descendants of ``root`` (child edges from heads[])."""
    n = len(heads)
    children: List[List[int]] = [[] for _ in range(n)]
    for d, h in enumerate(heads):
        if h != d and 0 <= h < n:
            children[h].append(d)
    out: List[int] = []
    stack = list(children[root])
    while stack:
        k = stack.pop()
        out.append(k)
        stack.extend(children[k])
    return out


def deprojectivize(
    heads: Sequence[int], labels: Sequence[str]
) -> Tuple[List[int], List[str]]:
    """Undo the pseudo-projective transform on a PREDICTED tree.

    For each token whose label is decorated ``child||headlabel``: search the
    current head's subtree (the lift moved the token to an ancestor of its
    true head, so the true head is below) for the nearest token labeled
    ``headlabel`` and reattach. The decoration is stripped regardless; an
    unmatched search leaves the head where the parser put it.
    """
    n = len(heads)
    new_heads = list(heads)
    new_labels = list(labels)
    for d in range(n):
        if not is_decorated(labels[d]):
            continue
        base, head_label = decompose_label(labels[d])
        new_labels[d] = base  # strip the decoration unconditionally
        if not head_label:
            continue
        h = new_heads[d]
        # never reattach a token into its own subtree (would create a cycle)
        own = set(_subtree(d, new_heads))
        if h == d:  # lifted all the way to root: search the whole sentence
            candidates = [k for k in range(n) if k != d and k not in own]
        else:
            candidates = [
                k for k in _subtree(h, new_heads) if k != d and k not in own
            ]
        best = None
        for k in candidates:
            if decompose_label(labels[k])[0] == head_label:
                if best is None or abs(k - d) < abs(best - d):
                    best = k
        if best is not None:
            new_heads[d] = best
    return new_heads, new_labels
