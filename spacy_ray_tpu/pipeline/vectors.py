"""Static word vectors: pretrained embedding table support.

Capability parity with spaCy's vectors asset (``include_static_vectors`` in
MultiHashEmbed; vectors live on the Vocab there). Format: an .npz with
``words`` (unicode array) and ``vectors`` [N, D] float32 — zero-egress
environments generate their own (e.g. from a local embedding dump).

Device side: the table is closure-captured into the embedding layer as an
XLA constant (NOT a parameter: static vectors are frozen by definition, and
keeping them out of the params pytree keeps checkpoints and optimizer state
small). A trainable linear projection maps vector dim -> model width.

The active table is installed in a context (like parallel/context.py's mesh)
so architecture factories can reach it during config resolution, where no
vocab handle exists.
"""

from __future__ import annotations

import contextvars
from contextlib import contextmanager
from pathlib import Path
from typing import Dict, Iterator, Optional, Sequence, Union

import numpy as np


class Vectors:
    def __init__(self, words: Sequence[str], table: np.ndarray):
        if len(words) != table.shape[0]:
            raise ValueError(f"{len(words)} words vs {table.shape[0]} vector rows")
        table = np.asarray(table, dtype=np.float32)
        # dedupe (keep first occurrence) so save/load roundtrips: a dict of
        # N-1 unique words over an N-row table would crash on reload
        seen: Dict[str, int] = {}
        keep: list = []
        for i, w in enumerate(words):
            if w not in seen:
                seen[w] = len(keep)
                keep.append(i)
        if len(keep) != len(words):
            table = table[np.asarray(keep)]
        self.table = table
        self.key_to_row: Dict[str, int] = seen

    @property
    def width(self) -> int:
        return int(self.table.shape[1])

    def __len__(self) -> int:
        return self.table.shape[0]

    def row_of(self, word: str) -> int:
        """Row index or -1 (OOV -> zero vector)."""
        r = self.key_to_row.get(word)
        if r is None:
            r = self.key_to_row.get(word.lower(), -1)
        return r

    def rows_of(self, words: Sequence[str]) -> np.ndarray:
        return np.array([self.row_of(w) for w in words], dtype=np.int32)

    @classmethod
    def from_disk(cls, path: Union[str, Path]) -> "Vectors":
        with np.load(str(path), allow_pickle=False) as data:
            words = [str(w) for w in data["words"]]
            table = data["vectors"]
        return cls(words, table)

    def to_disk(self, path: Union[str, Path]) -> None:
        words = np.array(list(self.key_to_row), dtype=np.str_)
        order = np.argsort([self.key_to_row[w] for w in words])
        np.savez(str(path), words=words[order], vectors=self.table)


_ACTIVE: "contextvars.ContextVar[Optional[Vectors]]" = contextvars.ContextVar(
    "spacy_ray_tpu_vectors", default=None
)


def current_vectors() -> Optional[Vectors]:
    return _ACTIVE.get()


@contextmanager
def use_vectors(vectors: Optional[Vectors]) -> Iterator[None]:
    token = _ACTIVE.set(vectors)
    try:
        yield
    finally:
        _ACTIVE.reset(token)
