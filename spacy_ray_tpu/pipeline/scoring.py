"""Shared scorers with spaCy's exact Scorer semantics.

The reference evaluates through spaCy's ``Scorer`` (reference
worker.py:209-217 ``create_evaluation_callback`` → ``nlp.evaluate``), so
F1-parity requires pinning the same conventions (SURVEY.md §7 "Scorer
parity"; VERDICT r2 missing #3). The conventions implemented here, each
covered by a golden-file test in tests/test_scorer_golden.py:

* **Zero division** → 0.0 inside a PRF (spaCy PRFScore divides with a
  +1e-100 epsilon; exact 0.0 here), but **no gold annotation at all** →
  ``None`` for the whole key (spaCy returns None so the score is excluded
  from the weighted final score rather than dragging it to 0).
* **Unannotated docs are skipped** in span scoring — a predicted entity on
  a doc with no gold entity annotation is NOT a false positive (spaCy
  checks ``doc.has_annotation("ENT_IOB")`` per doc). An annotated doc with
  zero entities DOES count its predictions as false positives.
* **Per-type PRF** next to the micro scores (spaCy's ``ents_per_type``):
  a span is credited to its gold/predicted label's bucket.
* **Dependency scoring ignores punctuation**: tokens whose gold dep label
  lowercases to ``p`` or ``punct`` are excluded from UAS/LAS (spaCy
  ``Scorer.score_deps(..., ignore_labels=("p", "punct"))``); labels
  compare lowercased.
* **Sentence boundaries score as spans**: a sentence is correct only when
  BOTH its start and its end are correct (spaCy scores ``sents_f`` via
  ``score_spans`` over ``doc.sents``), not per-boundary-token.
* **Morph per-feat PRF** (spaCy ``morph_per_feat``): each ``Feat=Val``
  pair scores independently across aligned tokens.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from .doc import Doc, Example, Span


class PRF:
    """tp/fp/fn accumulator with spaCy PRFScore's zero-division → 0.0."""

    __slots__ = ("tp", "fp", "fn")

    def __init__(self) -> None:
        self.tp = 0
        self.fp = 0
        self.fn = 0

    @property
    def precision(self) -> float:
        return self.tp / (self.tp + self.fp) if self.tp + self.fp else 0.0

    @property
    def recall(self) -> float:
        return self.tp / (self.tp + self.fn) if self.tp + self.fn else 0.0

    @property
    def fscore(self) -> float:
        p, r = self.precision, self.recall
        return 2 * p * r / (p + r) if p + r else 0.0

    def score_sets(self, pred: set, gold: set) -> None:
        self.tp += len(pred & gold)
        self.fp += len(pred - gold)
        self.fn += len(gold - pred)

    def to_dict(self) -> Dict[str, float]:
        return {"p": self.precision, "r": self.recall, "f": self.fscore}


def score_spans(
    examples: Sequence[Example],
    prefix: str,
    getter: Callable[[Doc], Iterable[Span]],
    has_annotation: Callable[[Doc], bool],
    labeled: bool = True,
) -> Dict[str, object]:
    """Micro + per-type PRF over (start, end[, label]) exact matches.

    Keys: ``{prefix}_p/r/f`` (None when NO gold doc has the annotation)
    and ``{prefix}_per_type`` ({label: {p, r, f}}). Docs where
    ``has_annotation(gold)`` is False are skipped entirely (their
    predictions are neither correct nor false positives) — spaCy
    ``Scorer.score_spans`` semantics."""
    micro = PRF()
    per_type: Dict[str, PRF] = {}
    any_annotation = False
    for eg in examples:
        if not has_annotation(eg.reference):
            continue
        any_annotation = True
        gold = {
            (s.start, s.end, s.label if labeled else "")
            for s in getter(eg.reference)
        }
        pred = {
            (s.start, s.end, s.label if labeled else "")
            for s in getter(eg.predicted)
        }
        micro.score_sets(pred, gold)
        labels = {t[2] for t in gold | pred}
        for label in labels:
            bucket = per_type.setdefault(label, PRF())
            bucket.score_sets(
                {t for t in pred if t[2] == label},
                {t for t in gold if t[2] == label},
            )
    if not any_annotation:
        out: Dict[str, object] = {
            f"{prefix}_p": None,
            f"{prefix}_r": None,
            f"{prefix}_f": None,
        }
        if labeled:
            out[f"{prefix}_per_type"] = None
        return out
    out = {
        f"{prefix}_p": micro.precision,
        f"{prefix}_r": micro.recall,
        f"{prefix}_f": micro.fscore,
    }
    if labeled:
        out[f"{prefix}_per_type"] = {
            label: prf.to_dict() for label, prf in sorted(per_type.items())
        }
        # flat aliases so [training.score_weights] and the console logger
        # can address per-type scores without nested lookups
        for label, prf in per_type.items():
            out[f"{prefix}_f_{label}"] = prf.fscore
    return out


def score_token_acc(
    examples: Sequence[Example],
    key: str,
    getter: Callable[[Doc], Optional[List[str]]],
) -> Dict[str, Optional[float]]:
    """Token-level accuracy; positions with missing (falsy) gold are
    excluded from the denominator; ``None`` when no gold annotation exists
    anywhere (spaCy ``Scorer.score_token_attr``)."""
    correct = 0
    total = 0
    for eg in examples:
        gold = getter(eg.reference) or []
        pred = getter(eg.predicted) or []
        for i, g in enumerate(gold):
            if not g:
                continue
            total += 1
            if i < len(pred) and pred[i] == g:
                correct += 1
    if total == 0:
        return {key: None}
    return {key: correct / total}


DEP_IGNORE_LABELS = ("p", "punct")


def score_deps(
    examples: Sequence[Example],
    ignore_labels: Tuple[str, ...] = DEP_IGNORE_LABELS,
) -> Dict[str, Optional[float]]:
    """UAS/LAS with spaCy's ``score_deps`` conventions: each side drops
    tokens whose OWN dep label lowercases into ``ignore_labels`` (gold set
    by gold label, pred set by predicted label — a gold-punct token
    mis-predicted as ``nsubj`` IS a false positive); labels compare
    lowercased; the unlabeled (UAS) sets are the labeled sets minus the
    label field; ``None`` when no doc has gold heads."""
    unlabeled = PRF()
    labeled = PRF()
    per_dep: Dict[str, PRF] = {}
    any_annotation = False
    for eg in examples:
        gold_heads = eg.reference.heads
        if not gold_heads:
            continue
        any_annotation = True
        gold_deps = eg.reference.deps or [""] * len(gold_heads)
        pred_heads = eg.predicted.heads or []
        pred_deps = eg.predicted.deps or [""] * len(pred_heads)
        gold_l = set()
        for i, (h, d) in enumerate(zip(gold_heads, gold_deps)):
            d = (d or "").lower()
            if d in ignore_labels:
                continue
            gold_l.add((i, h, d))
        pred_l = set()
        for i, h in enumerate(pred_heads):
            if i >= len(gold_heads):
                break
            d = (pred_deps[i] if i < len(pred_deps) else "") or ""
            d = d.lower()
            if d in ignore_labels:
                continue
            pred_l.add((i, h, d))
        labeled.score_sets(pred_l, gold_l)
        unlabeled.score_sets(
            {t[:2] for t in pred_l}, {t[:2] for t in gold_l}
        )
        for dep in {t[2] for t in gold_l | pred_l}:
            bucket = per_dep.setdefault(dep, PRF())
            bucket.score_sets(
                {t for t in pred_l if t[2] == dep},
                {t for t in gold_l if t[2] == dep},
            )
    if not any_annotation:
        return {"dep_uas": None, "dep_las": None, "dep_las_per_type": None}
    return {
        "dep_uas": unlabeled.fscore,
        "dep_las": labeled.fscore,
        "dep_las_per_type": {
            dep: prf.to_dict() for dep, prf in sorted(per_dep.items())
        },
    }


def sentence_spans(sent_starts: Optional[List[int]], n: int) -> List[Span]:
    """Sentence (start, end) spans from per-token 1/-1/0 markers. Token 0
    always opens a sentence (spaCy's Doc.sents convention)."""
    if not sent_starts or n == 0:
        return []
    starts = [0] + [i for i in range(1, min(n, len(sent_starts))) if sent_starts[i] == 1]
    starts = sorted(set(starts))
    ends = starts[1:] + [n]
    return [Span(s, e, "") for s, e in zip(starts, ends)]


def score_sents(examples: Sequence[Example]) -> Dict[str, Optional[float]]:
    """``sents_p/r/f`` over whole sentence spans — both boundaries must be
    right (spaCy scores sentences via ``score_spans(examples, "sents")``,
    NOT per boundary token)."""
    return {
        k.replace("sents_spans", "sents"): v
        for k, v in score_spans(
            examples,
            "sents_spans",
            lambda d: sentence_spans(d.sent_starts, len(d)),
            has_annotation=lambda d: bool(d.sent_starts)
            and any(v != 0 for v in d.sent_starts),
            labeled=False,
        ).items()
    }


def parse_feats(morph: str) -> Dict[str, str]:
    """'Number=Sing|Person=3' -> {'Number': 'Sing', 'Person': '3'}."""
    out: Dict[str, str] = {}
    if not morph:
        return out
    for part in morph.split("|"):
        k, _, v = part.partition("=")
        if k:
            out[k] = v
    return out


def score_morph_per_feat(
    examples: Sequence[Example],
) -> Dict[str, object]:
    """spaCy's ``morph_per_feat``: independent PRF per UD feature across
    aligned tokens with gold morph annotation."""
    per_feat: Dict[str, PRF] = {}
    any_annotation = False
    for eg in examples:
        gold_morphs = eg.reference.morphs or []
        pred_morphs = eg.predicted.morphs or []
        for i, gm in enumerate(gold_morphs):
            if not gm:
                continue
            any_annotation = True
            gold_feats = parse_feats(gm)
            pred_feats = parse_feats(pred_morphs[i] if i < len(pred_morphs) else "")
            for feat in set(gold_feats) | set(pred_feats):
                prf = per_feat.setdefault(feat, PRF())
                gset = {(i, feat, gold_feats[feat])} if feat in gold_feats else set()
                pset = {(i, feat, pred_feats[feat])} if feat in pred_feats else set()
                prf.score_sets(pset, gset)
    if not any_annotation:
        return {"morph_per_feat": None}
    return {
        "morph_per_feat": {
            feat: prf.to_dict() for feat, prf in sorted(per_feat.items())
        }
    }


def rank_auc(gold: List[int], scores: List[float]) -> Optional[float]:
    """ROC AUC via the rank statistic (Mann-Whitney U) — the probability a
    random positive outranks a random negative, ties counted half. None
    when only one class is present (sklearn/spaCy convention: undefined)."""
    pos = [s for g, s in zip(gold, scores) if g]
    neg = [s for g, s in zip(gold, scores) if not g]
    if not pos or not neg:
        return None
    wins = 0.0
    for ps in pos:
        for ns in neg:
            if ps > ns:
                wins += 1.0
            elif ps == ns:
                wins += 0.5
    return wins / (len(pos) * len(neg))
