"""Pipeline: the ``nlp`` object — config-built component container.

Capability parity with the spaCy ``Language`` object the reference replicates
per worker (reference worker.py:91 ``init_nlp``; nlp.update inside
``train_while_improving`` worker.py:176-189; serialization worker.py:219-222).
TPU-first differences:

* The whole multi-component forward+loss is ONE pure function
  (``make_loss_fn``) so jit compiles tok2vec trunk + every head + their
  gradient sum into a single XLA program — the listener gradient relay and
  "summed gradients into shared trunk" fall out of autodiff for free.
* Collation lowers ragged Example batches into bucketed, statically-shaped
  padded arrays (SURVEY.md §7 "Ragged/variable-length batching").
* Frozen components (reference worker.py:186-187 semantics) are excluded via
  ``stop_gradient`` on their param subtree.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..models.core import Context, Params
from ..registry import registry
from ..training.batcher import bucket_batch_size, bucket_length, DEFAULT_LENGTH_BUCKETS
from ..types import TokenBatch
from .components.base import Component
from .components.tok2vec import Tok2VecComponent
from .doc import Doc, Example
from .tokenizer import Tokenizer
from .vectors import Vectors, use_vectors
from .vocab import Vocab

# Cap on gold examples scanned for label collection; the init-labels CLI
# must use the SAME cap so its files reproduce initialize's collection.
LABEL_SAMPLE_LIMIT = 10000


def resolve_config_path(config: Optional[Config], raw: Any) -> Path:
    """Resolve a path found INSIDE a config. Relative paths anchor to the
    config file's own directory (``Config.origin_path``) — a config
    written next to its assets (labels files, vectors, source model dirs,
    pretrained trunk weights) must work from any CWD. CWD-relative stays
    as a fallback so pre-existing setups that relied on it keep
    resolving."""
    p = Path(raw)
    if p.is_absolute():
        return p
    origin = getattr(config, "origin_path", None) if config is not None else None
    if origin is not None:
        anchored = Path(origin).parent / p
        if anchored.exists() or not p.exists():
            return anchored
    return p


class Pipeline:
    def __init__(
        self,
        lang: str = "en",
        components: Optional[Dict[str, Component]] = None,
        pipe_names: Optional[List[str]] = None,
        config: Optional[Config] = None,
    ):
        self.lang = lang
        self.vocab = Vocab()
        self.tokenizer = Tokenizer()
        self.components: Dict[str, Component] = components or {}
        self.pipe_names: List[str] = pipe_names or list(self.components)
        self.config: Config = config or Config()
        self.params: Optional[Params] = None
        self.frozen_components: List[str] = []
        self.annotating_components: List[str] = []
        self.sourced_components: Dict[str, str] = {}
        self.vectors: Optional[Vectors] = None
        self.length_buckets: Sequence[int] = DEFAULT_LENGTH_BUCKETS
        self._jit_forward = None  # cached compiled forward (predict path)

    # ------------------------------------------------------------------
    # Construction
    # ------------------------------------------------------------------
    @classmethod
    def from_config(cls, config: Config) -> "Pipeline":
        """Build the pipeline skeleton from an interpolated config."""
        nlp_cfg = config.get("nlp", {})
        lang = nlp_cfg.get("lang", "en")
        pipe_names = list(nlp_cfg.get("pipeline", []))
        comp_cfgs = config.get("components", {})
        components: Dict[str, Component] = {}
        sourced: Dict[str, str] = {}
        sourced_vectors = None  # adopted from the first vector-ful source
        src_cache: Dict[str, "Pipeline"] = {}  # one load per source dir
        for name in pipe_names:
            if name not in comp_cfgs:
                raise ValueError(f"Pipeline names component {name!r} but no [components.{name}]")
            block = dict(comp_cfgs[name])
            source = block.pop("source", None)
            if source is not None:
                # spaCy's `source = "model_dir"`: reuse a trained component
                # (config + labels + params) from a saved pipeline
                if block:
                    raise ValueError(
                        f"[components.{name}] mixes source = {source!r} with other "
                        f"keys {sorted(block)} — a sourced component can't be "
                        "overridden; drop `source` or the extra keys"
                    )
                if source not in src_cache:
                    src_cache[source] = cls.from_disk(
                        resolve_config_path(config, source)
                    )
                src_nlp = src_cache[source]
                if name not in src_nlp.components:
                    raise ValueError(
                        f"[components.{name}] source {source!r} has no component "
                        f"{name!r} (has: {src_nlp.pipe_names})"
                    )
                components[name] = src_nlp.components[name]
                sourced[name] = source
                # host-side components (lemmatizer) may have no params entry
                components[name]._sourced_params = (src_nlp.params or {}).get(name, {})
                if src_nlp.vectors is not None:
                    if sourced_vectors is None:
                        sourced_vectors = src_nlp.vectors
                    elif sourced_vectors is not src_nlp.vectors and (
                        sourced_vectors.table.shape != src_nlp.vectors.table.shape
                        or not np.array_equal(
                            sourced_vectors.table, src_nlp.vectors.table
                        )
                    ):
                        raise ValueError(
                            f"[components.{name}] source {source!r} carries a "
                            "different vectors table than an earlier source — "
                            "sourced components must share one vectors asset"
                        )
                # Rewrite the config block to the source's CONCRETE block so
                # the saved combined model reloads without the source dir
                # (its params travel in our params.npz anyway).
                import copy as _copy

                src_block = src_nlp.config.get("components", {}).get(name)
                if src_block:
                    config["components"][name] = _copy.deepcopy(src_block)
                continue
            factory_name = block.pop("factory", None)
            if factory_name is None:
                raise ValueError(f"[components.{name}] missing 'factory'")
            factory = registry.get("factories", factory_name)
            model_cfg = block.pop("model", None)
            if model_cfg is None:
                import inspect

                sig = inspect.signature(factory)
                model_param = sig.parameters.get("model")
                if model_param is None or model_param.default is inspect.Parameter.empty:
                    raise ValueError(f"[components.{name}] missing model block")
                # model-less (host-side) components like the lemmatizer
                components[name] = factory(name=name, **block)
            else:
                components[name] = factory(name=name, model=model_cfg, **block)
        nlp = cls(lang=lang, components=components, pipe_names=pipe_names, config=config)
        nlp.sourced_components = sourced
        if sourced_vectors is not None:
            nlp.vectors = sourced_vectors
        training = config.get("training", {})
        nlp.frozen_components = list(training.get("frozen_components", []) or [])
        nlp.annotating_components = list(training.get("annotating_components", []) or [])
        return nlp

    @property
    def tok2vec_name(self) -> Optional[str]:
        for name in self.pipe_names:
            if isinstance(self.components[name], Tok2VecComponent):
                return name
        return None

    def head_names(self) -> List[str]:
        t2v = self.tok2vec_name
        return [n for n in self.pipe_names if n != t2v]

    def _resolve_config_path(self, raw: Any) -> Path:
        return resolve_config_path(self.config, raw)

    # ------------------------------------------------------------------
    # Initialization
    # ------------------------------------------------------------------
    def initialize(
        self,
        get_examples: Optional[Callable[[], Iterable[Example]]] = None,
        *,
        seed: int = 0,
        label_sample_limit: int = LABEL_SAMPLE_LIMIT,
    ) -> Params:
        """Collect labels from gold data, build models, init params.

        The equivalent of spacy's ``init_nlp`` run per-worker at reference
        worker.py:91 (here it runs once; params are replicated by sharding).
        """
        init_cfg = self.config.get("initialize", {}) if self.config else {}
        init_components = init_cfg.get("components", {}) or {}
        if get_examples is not None:
            sample: List[Example] = []
            for i, eg in enumerate(get_examples()):
                if i >= label_sample_limit:
                    break
                sample.append(eg)
            for name in self.pipe_names:
                if name in self.sourced_components:
                    continue  # sourced: labels came with the saved component
                comp = self.components[name]
                labels_path = (init_components.get(name) or {}).get("labels")
                if labels_path:
                    # [initialize.components.<name>] labels = "<path>.json":
                    # precomputed label set (the `init-labels` CLI output,
                    # spaCy's `init labels` surface) — skips data collection
                    # and freezes the label ORDER, so e.g. resuming against
                    # a grown corpus can't silently renumber classes
                    loaded = json.loads(
                        self._resolve_config_path(labels_path).read_text(
                            encoding="utf8"
                        )
                    )
                    if (
                        not isinstance(loaded, list)
                        or not loaded
                        or not all(isinstance(l, str) for l in loaded)
                    ):
                        raise ValueError(
                            f"[initialize.components.{name}] labels file "
                            f"{labels_path!r} must hold a non-empty JSON "
                            "list of strings (write it with the "
                            "init-labels command)"
                        )
                    if len(set(loaded)) != len(loaded):
                        dupes = sorted(
                            {l for l in loaded if loaded.count(l) > 1}
                        )
                        raise ValueError(
                            f"[initialize.components.{name}] labels file "
                            f"{labels_path!r} contains duplicates {dupes}: "
                            "the head would be sized by the padded count "
                            "while classes silently collapse"
                        )
                    # saved labels are already in final (finished) order;
                    # finish_labels is NOT re-run — e.g. the edit-tree
                    # lemmatizer keeps its identity label first
                    comp.labels = list(loaded)
                    continue
                comp.add_labels_from(sample)
                comp.finish_labels()
        # vectors asset ([initialize] vectors = "path.npz", spaCy semantics);
        # an explicit config path WINS over vectors adopted from a source
        vectors_path = init_cfg.get("vectors")
        if vectors_path:
            self.vectors = Vectors.from_disk(
                self._resolve_config_path(vectors_path)
            )
        rng = jax.random.PRNGKey(seed)
        params: Dict[str, Any] = {}
        with use_vectors(self.vectors):
            for name in self.pipe_names:
                comp = self.components[name]
                if name in self.sourced_components:
                    # model already built by from_disk; reuse trained params
                    if comp._sourced_params:
                        params[name] = comp._sourced_params
                    continue
                comp.build_model()
                rng, sub = jax.random.split(rng)
                comp_params = comp.init_params(sub)
                if comp_params:  # host-only components have no params; empty
                    params[name] = comp_params  # dicts break pytree matching
        # [initialize] init_tok2vec: pretrained trunk weights from the
        # `pretrain` command (spaCy's init_tok2vec semantics — the trunk
        # starts from pretraining, heads stay freshly initialized)
        init_t2v = init_cfg.get("init_tok2vec")
        if init_t2v:
            t2v_name = self.tok2vec_name
            if t2v_name is None or t2v_name not in params:
                raise ValueError(
                    "[initialize] init_tok2vec is set but the pipeline has "
                    "no tok2vec/transformer trunk with parameters"
                )
            from ..training.checkpoint import _flatten, load_params

            loaded = load_params(self._resolve_config_path(init_t2v))
            have = {k: tuple(v.shape) for k, v in _flatten(params[t2v_name]).items()}
            got = {k: tuple(v.shape) for k, v in _flatten(loaded).items()}
            if have != got:
                missing = sorted(set(have) - set(got))[:5]
                extra = sorted(set(got) - set(have))[:5]
                mismatched = sorted(
                    k for k in set(have) & set(got) if have[k] != got[k]
                )[:5]
                raise ValueError(
                    f"init_tok2vec weights at {init_t2v!r} do not match the "
                    f"{t2v_name!r} trunk this config builds "
                    f"(missing={missing}, unexpected={extra}, "
                    f"shape-mismatched={mismatched}); pretrain with the same "
                    "trunk architecture settings"
                )
            params[t2v_name] = loaded
        # Width compatibility: a (possibly sourced) listening head must match
        # the trunk width, or jit fails later with an opaque shape error.
        t2v = self.tok2vec_name
        if t2v is not None:
            trunk_w = self.components[t2v].model.dims.get("nO")
            for name in self.head_names():
                comp = self.components[name]
                if comp.model is None:
                    continue
                head_w = (comp.model.dims or {}).get("width")
                if comp.listens and trunk_w and head_w and head_w != trunk_w:
                    src = self.sourced_components.get(name)
                    hint = f" (sourced from {src!r})" if src else ""
                    raise ValueError(
                        f"Component {name!r}{hint} expects tok2vec width "
                        f"{head_w} but the pipeline trunk {t2v!r} produces "
                        f"{trunk_w}"
                    )
        self.params = params
        self._jit_forward = None  # models rebuilt -> stale closure
        return params

    # ------------------------------------------------------------------
    # Collation: List[Example] -> statically-shaped device batch
    # ------------------------------------------------------------------
    def collate(
        self,
        examples: List[Example],
        *,
        with_targets: bool = True,
        pad_batch_to: Optional[int] = None,
        pad_len_to: Optional[int] = None,
        host: bool = False,
    ) -> Dict[str, Any]:
        """Lower ragged Examples into a statically-shaped padded batch.

        ``host=True`` keeps every leaf a NUMPY array (no ``jnp.asarray``,
        which on CPU already commits the data to a jax buffer): the
        parallel collation pool runs this on worker threads and the
        consumer thread alone performs the ``device_put`` (see
        training/collate_pool.py for the threading contract)."""
        as_array = np.asarray if host else jnp.asarray
        lengths = [len(eg) for eg in examples]
        max_len = max(lengths) if lengths else 1
        T = pad_len_to or bucket_length(max_len, self.length_buckets)
        B = pad_batch_to or bucket_batch_size(len(examples))
        n_attrs = 4
        attr_keys = np.zeros((B, T, n_attrs, 2), dtype=np.uint32)
        mask = np.zeros((B, T), dtype=bool)
        vec_rows = (
            np.full((B, T), -1, dtype=np.int32) if self.vectors is not None else None
        )
        # Per-doc feature cache: corpora materialize Example objects once and
        # re-iterate them every epoch, so each doc's [len, n_attrs, 2] keys
        # are computed exactly once; docs not yet cached are featurized in
        # ONE flat vocab call (one native hash batch). Steady-state epochs
        # reduce to slice-copies into the padded batch.
        doc_feats: List[Optional[np.ndarray]] = [
            getattr(eg, "_feat_cache", None) for eg in examples
        ]
        uncached = [i for i, f in enumerate(doc_feats) if f is None]
        if uncached:
            flat_words = [w for i in uncached for w in examples[i].reference.words]
            flat_feats = self.vocab.featurize(flat_words)
            offset = 0
            for i in uncached:
                n = len(examples[i].reference.words)
                arr = flat_feats[offset : offset + n]
                offset += n
                examples[i]._feat_cache = arr
                doc_feats[i] = arr
        for i, feats in enumerate(doc_feats):
            n = min(len(feats), T)
            attr_keys[i, :n] = feats[:n]
            mask[i, :n] = True
            if vec_rows is not None:
                vec_rows[i, :n] = self.vectors.rows_of(
                    examples[i].reference.words[:T]
                )
        batch: Dict[str, Any] = {
            "tokens": TokenBatch(
                attr_keys=as_array(attr_keys),
                mask=as_array(mask),
                vector_rows=as_array(vec_rows) if vec_rows is not None else None,
            ),
            "n_words": int(sum(min(l, T) for l in lengths)),
            "lengths": lengths,
        }
        if with_targets:
            targets: Dict[str, Any] = {}
            for name in self.head_names():
                comp = self.components[name]
                t = comp.make_targets(examples, B, T)
                if t:
                    targets[name] = {k: as_array(v) for k, v in t.items()}
            batch["targets"] = targets
        return batch

    # ------------------------------------------------------------------
    # Pure loss (jit-traceable)
    # ------------------------------------------------------------------
    def make_loss_fn(self, dropout: Optional[float] = None) -> Callable:
        """Returns loss_fn(params, tokens, targets, rng) -> (loss, metrics).

        ``dropout``: global training dropout override (``[training] dropout``,
        spaCy semantics — reference worker.py:181 passes it into
        ``train_while_improving``, where ``set_dropout_rate`` overrides every
        dropout node's architecture rate). ``None`` keeps per-architecture
        rates (the pre-round-3 behavior, and the behavior of direct calls)."""
        t2v_name = self.tok2vec_name
        head_names = self.head_names()
        components = self.components
        frozen = set(self.frozen_components)
        drop = None if dropout is None else float(dropout)

        def loss_fn(params: Params, tokens: TokenBatch, targets: Dict[str, Any], rng):
            metrics: Dict[str, Any] = {}
            total = jnp.float32(0.0)
            t2v_out = None
            aux_sink: List[Any] = []  # e.g. MoE router load-balancing loss
            if t2v_name is not None:
                t2v_params = params[t2v_name]
                if t2v_name in frozen:
                    t2v_params = jax.lax.stop_gradient(t2v_params)
                rng, sub = jax.random.split(rng)
                t2v_out = components[t2v_name].forward(
                    t2v_params, tokens,
                    Context(train=True, rng=sub, aux_losses=aux_sink, dropout=drop),
                )
            for name in head_names:
                comp = components[name]
                if not comp.trainable or name not in targets:
                    continue
                comp_params = params[name]
                if name in frozen:
                    comp_params = jax.lax.stop_gradient(comp_params)
                inputs = t2v_out if comp.listens else tokens
                rng, sub = jax.random.split(rng)
                # heads with an inline (non-listener) tok2vec may embed an
                # MoE trunk themselves — give them the same aux sink
                loss, comp_metrics = comp.loss(
                    comp_params, inputs, targets[name],
                    Context(train=True, rng=sub, aux_losses=aux_sink, dropout=drop),
                )
                metrics[f"loss_{name}"] = loss
                # namespace per component: shared base classes emit the same
                # metric keys (e.g. tag_acc_batch) and would clobber
                metrics.update({f"{name}_{k}": v for k, v in comp_metrics.items()})
                total = total + loss
            if aux_sink and (t2v_name is None or t2v_name not in frozen):
                aux_total = jnp.float32(0.0)
                for a in aux_sink:
                    aux_total = aux_total + a
                metrics["loss_aux"] = aux_total
                total = total + aux_total
            return total, metrics

        return loss_fn

    def make_forward_fn(self, only: Optional[Sequence[str]] = None) -> Callable:
        """Returns forward(params, tokens) -> {component: output} (eval mode).

        ``only``: compute just the listed head components (plus the trunk) —
        the annotating_components path uses this so a training-time
        annotation pass doesn't pay for the downstream heads it discards."""
        t2v_name = self.tok2vec_name
        head_names = self.head_names()
        if only is not None:
            head_names = [n for n in head_names if n in set(only)]
        components = self.components

        def forward(params: Params, tokens: TokenBatch):
            outputs: Dict[str, Any] = {}
            t2v_out = None
            if t2v_name is not None:
                t2v_out = components[t2v_name].forward(
                    params[t2v_name], tokens, Context(train=False)
                )
                outputs[t2v_name] = t2v_out
            for name in head_names:
                comp = components[name]
                if comp.model is None:
                    continue  # host-side components have no device forward
                inputs = t2v_out if comp.listens else tokens
                outputs[name] = comp.forward(params[name], inputs, Context(train=False))
            return outputs

        return forward

    # ------------------------------------------------------------------
    # Prediction / evaluation (host orchestration)
    # ------------------------------------------------------------------
    def predict_docs(
        self,
        docs: List[Doc],
        params: Optional[Params] = None,
        batch_size: int = 128,
        mesh=None,
        annotate: Optional[List[str]] = None,
        pad_batch_to: Optional[int] = None,
        pad_len_to: Optional[int] = None,
    ) -> List[Doc]:
        """Batched prediction. With ``mesh`` (single-process), eval batches
        are sharded over the ``data`` axis so prediction uses every device
        instead of computing replicated — eval time scales down with the
        mesh instead of stalling the loop (VERDICT r1 weak #10).

        ``annotate``: restrict ``set_annotations`` to the listed components
        (the training loop's ``[training] annotating_components`` path —
        reference worker.py:187 passes the list into
        ``train_while_improving`` so downstream components train against
        upstream predictions). ``None`` annotates with every component.

        ``pad_batch_to``/``pad_len_to``: pin the padded (B, T) instead of
        deriving it from the chunk — the serving engine dispatches with
        the coalesced bucket pinned so a live request can only ever hit a
        shape its warmup sweep already compiled."""
        params = params if params is not None else self.params
        assert params is not None, "Pipeline not initialized"
        shard_eval = (
            mesh is not None
            and int(mesh.shape.get("data", 1)) > 1
            and jax.process_count() == 1  # multi-host gather not worth it
        )
        n_data = int(mesh.shape["data"]) if shard_eval else 1
        # cache keyed on decode-affecting component settings, so e.g.
        # changing parser.beam_width or ner.decode takes effect immediately,
        # plus the ``annotate`` restriction (the annotating pass compiles a
        # trunk+annotators-only program; interleaving it with full eval must
        # not retrace either one). The mesh is NOT part of the key: the same
        # jitted callable serves sharded and unsharded inputs (jax keeps one
        # executable per input sharding internally), so eval/inference
        # interleaving never rebuilds the trace
        decode_sig = (
            tuple(
                (name, getattr(self.components[name], "beam_width", None),
                 getattr(self.components[name], "decode", None))
                for name in self.pipe_names
            ),
            tuple(sorted(annotate)) if annotate is not None else None,
        )
        if self._jit_forward is None:
            self._jit_forward = {}
        if decode_sig not in self._jit_forward:
            # evict entries traced under DIFFERENT decode settings (stale),
            # keeping other `annotate` restrictions alive — the training
            # loop alternates annotation and eval programs every step
            for k in list(self._jit_forward):
                if k[0] != decode_sig[0]:
                    del self._jit_forward[k]
            self._jit_forward[decode_sig] = jax.jit(
                self.make_forward_fn(only=decode_sig[1])
            )
        forward = self._jit_forward[decode_sig]
        for chunk, lengths, outputs in self._forward_chunks(
            docs, params, forward, batch_size, shard_eval, n_data, mesh,
            pad_batch_to=pad_batch_to, pad_len_to=pad_len_to,
        ):
            for name in self.head_names():
                if annotate is not None and name not in annotate:
                    continue
                self.components[name].set_annotations(
                    chunk, outputs.get(name), lengths
                )
        return docs

    def _forward_chunks(
        self, docs, params, forward, batch_size, shard_eval, n_data, mesh,
        pad_batch_to=None, pad_len_to=None,
    ):
        for start in range(0, len(docs), batch_size):
            chunk = docs[start : start + batch_size]
            examples = [Example.from_gold(d) for d in chunk]
            if shard_eval:
                B = pad_batch_to or bucket_batch_size(len(examples))
                B = ((B + n_data - 1) // n_data) * n_data
                batch = self.collate(
                    examples, with_targets=False, pad_batch_to=B,
                    pad_len_to=pad_len_to,
                )
                from ..parallel.step import place_batch

                tokens = place_batch(batch["tokens"], mesh)
            else:
                batch = self.collate(
                    examples, with_targets=False,
                    pad_batch_to=pad_batch_to, pad_len_to=pad_len_to,
                )
                tokens = batch["tokens"]
            outputs = forward(params, tokens)
            lengths = [min(len(d), batch["tokens"].seq_len) for d in chunk]
            yield chunk, lengths, outputs

    def predict_chunks(
        self,
        docs: List[Doc],
        params: Optional[Params] = None,
        batch_size: int = 128,
        only: Optional[List[str]] = None,
    ):
        """Forward WITHOUT annotating: yields (chunk, lengths, outputs)
        per batch. Callers that sweep host-side decode settings (the
        find-threshold CLI) forward ONCE and re-run set_annotations many
        times — the device outputs don't depend on the swept attribute."""
        params = params if params is not None else self.params
        assert params is not None, "Pipeline not initialized"
        forward = jax.jit(self.make_forward_fn(only=only))
        yield from self._forward_chunks(
            docs, params, forward, batch_size, False, 1, None
        )

    def __call__(self, text: str) -> Doc:
        doc = self.tokenizer(text)
        self.predict_docs([doc])
        return doc

    def pipe(self, texts: Iterable[str], batch_size: int = 128) -> Iterable[Doc]:
        """Bulk inference over raw texts (spaCy's nlp.pipe surface)."""
        chunk: List[Doc] = []
        for text in texts:
            chunk.append(self.tokenizer(text))
            if len(chunk) >= batch_size:
                yield from self.predict_docs(chunk, batch_size=batch_size)
                chunk = []
        if chunk:
            yield from self.predict_docs(chunk, batch_size=batch_size)

    def evaluate(
        self,
        examples: List[Example],
        params: Optional[Params] = None,
        batch_size: int = 128,
        mesh=None,
    ) -> Dict[str, float]:
        """Predict over dev data and score — the per-worker evaluation the
        reference runs via ``create_evaluation_callback`` (reference
        worker.py:209-217)."""
        params = params if params is not None else self.params
        docs = [eg.reference.copy_shell() for eg in examples]
        # use_gold_ents (spaCy's entity_linker semantics): seed prediction
        # shells with gold mention BOUNDARIES (never kb ids) so a linker
        # without an upstream mention producer is evaluable. NEVER seed when
        # any component writes doc.ents itself — preset gold spans would
        # leak into the ner/entity_ruler predictions and inflate ents_f
        if any(
            getattr(self.components[n], "use_gold_ents", False)
            for n in self.pipe_names
        ) and not any(
            self.components[n].sets_ents for n in self.pipe_names
        ):
            from .doc import Span

            for eg, doc in zip(examples, docs):
                if not doc.ents:
                    doc.ents = [
                        Span(s.start, s.end, s.label)
                        for s in eg.reference.ents
                    ]
        self.predict_docs(docs, params, batch_size=batch_size, mesh=mesh)
        for eg, doc in zip(examples, docs):
            eg.predicted = doc
        scores: Dict[str, float] = {}
        for name in self.head_names():
            scores.update(self.components[name].score(examples))
        return scores

    # ------------------------------------------------------------------
    # Serialization (the nlp.to_disk path, reference worker.py:219-222)
    # ------------------------------------------------------------------
    def meta(self) -> Dict[str, Any]:
        from .. import __version__

        nlp_cfg = self.config.get("nlp", {}) if self.config else {}
        return {
            "lang": self.lang,
            "name": nlp_cfg.get("name", "pipeline"),
            "version": nlp_cfg.get("version", "0.0.0"),
            "spacy_ray_tpu_version": __version__,
            "pipeline": self.pipe_names,
            "labels": {name: self.components[name].labels for name in self.pipe_names},
        }

    def component_data(self) -> Dict[str, Any]:
        """Host-side component state (e.g. lemmatizer lookup tables) —
        saved as its own artifact so meta.json stays small."""
        return {
            name: comp.table_data()
            for name, comp in self.components.items()
            if hasattr(comp, "table_data")
        }

    def to_disk(self, path) -> None:
        from ..training import checkpoint

        path = Path(path)
        path.mkdir(parents=True, exist_ok=True)
        (path / "config.cfg").write_text(self.config.to_str(), encoding="utf8")
        (path / "meta.json").write_text(json.dumps(self.meta(), indent=2), encoding="utf8")
        extras = self.component_data()
        if extras:
            (path / "components.json").write_text(
                json.dumps(extras), encoding="utf8"
            )
        for name, comp in self.components.items():
            # binary component payloads (e.g. the entity_linker KB) ship as
            # sidecar files — JSON-encoding dense vectors into
            # components.json would bloat every best-model save
            if hasattr(comp, "save_binary"):
                comp.save_binary(path, name)
        if self.vectors is not None:
            self.vectors.to_disk(path / "vectors.npz")
        assert self.params is not None
        checkpoint.save_params(path / "params.npz", self.params)

    @classmethod
    def from_disk(cls, path) -> "Pipeline":
        from ..training import checkpoint

        path = Path(path)
        # from_disk (not from_str): origin_path makes relative in-config
        # paths (source / labels / vectors) resolve against the saved
        # model directory from any CWD
        config = Config.from_disk(path / "config.cfg")
        config = config.interpolate()
        nlp = cls.from_config(config)
        meta = json.loads((path / "meta.json").read_text(encoding="utf8"))
        for name, labels in meta.get("labels", {}).items():
            if name in nlp.components:
                nlp.components[name].labels = labels
        comp_data_path = path / "components.json"
        if comp_data_path.exists():
            for name, data in json.loads(
                comp_data_path.read_text(encoding="utf8")
            ).items():
                comp = nlp.components.get(name)
                if comp is not None and hasattr(comp, "load_table_data"):
                    comp.load_table_data(data)
        for name, comp in nlp.components.items():
            if hasattr(comp, "load_binary"):
                comp.load_binary(path, name)
        if (path / "vectors.npz").exists():
            nlp.vectors = Vectors.from_disk(path / "vectors.npz")
        with use_vectors(nlp.vectors):
            for name in nlp.pipe_names:
                nlp.components[name].build_model()
        nlp.params = checkpoint.load_params(path / "params.npz")
        return nlp
