"""Arc-eager transition system: host-side oracle + state features.

Capability parity with the transition-based dependency parser the reference
trains (spaCy's ``nn_parser.pyx`` Cython state machine, SURVEY.md §2.3 row
"spaCy core"; §7 hard part #1 "Transition-based parser under XLA").

TPU-first split (SURVEY.md §7 option (a)):

* TRAINING is teacher-forced: the gold action sequence and the state-feature
  token indices at every step are deterministic given the gold tree, so this
  module precomputes them HOST-SIDE as dense int arrays. The device never
  runs the state machine during training — it gathers tok2vec rows at the
  precomputed feature indices and classifies actions, one big batched matmul
  per doc-step grid (MXU-friendly; no lax.scan in the training path at all).
* DECODE runs on device as a fixed-length ``lax.scan`` with masked actions
  (models/parser.py) — same state arrays, jnp ops only.

Action encoding (arc-eager):
  0 = SHIFT, 1 = REDUCE, 2+2i = LEFT-ARC(label_i), 3+2i = RIGHT-ARC(label_i)

State features (12 token slots, -1 = absent → zero vector after gather):
  s0, s1, s2 (stack top three), b0, b1, b2 (buffer front three),
  s0.l (leftmost child), s0.r (rightmost child), s1.l, s1.r,
  s0.l2 (second-leftmost), s0.r2
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

N_FEATURES = 12

SHIFT = 0
REDUCE = 1


def n_actions(n_labels: int) -> int:
    return 2 + 2 * n_labels


def left_arc(label_id: int) -> int:
    return 2 + 2 * label_id


def right_arc(label_id: int) -> int:
    return 3 + 2 * label_id


def action_label(action: int) -> int:
    """label id of an arc action (undefined for SHIFT/REDUCE)."""
    return (action - 2) // 2


def is_left_arc(action: int) -> bool:
    return action >= 2 and (action - 2) % 2 == 0


def is_right_arc(action: int) -> bool:
    return action >= 2 and (action - 2) % 2 == 1


class ParseState:
    """Mutable arc-eager state over one sentence (host side, numpy ints).

    ROOT is the virtual index -1 sitting at the bottom of the stack; tokens
    whose gold head is themselves (our Doc convention for root, see
    training/corpus.py conllu reader) are attached to ROOT.
    """

    def __init__(self, n: int):
        self.n = n
        self.stack: List[int] = []  # ROOT implicit below stack[0]
        self.buffer = 0  # index of b0; buffer is [buffer, n)
        self.heads = np.full(n, -2, dtype=np.int64)  # -2 = unattached, -1 = ROOT
        self.labels = np.zeros(n, dtype=np.int64)
        self.lchild = np.full((n, 2), -1, dtype=np.int64)  # two leftmost children
        self.rchild = np.full((n, 2), -1, dtype=np.int64)  # two rightmost children

    # ------------------------------------------------------------------
    def is_terminal(self) -> bool:
        return self.buffer >= self.n and len(self.stack) == 0

    def _add_arc(self, head: int, dep: int, label: int) -> None:
        self.heads[dep] = head
        self.labels[dep] = label
        if head >= 0:
            if dep < head:
                l0, l1 = self.lchild[head]
                if l0 == -1 or dep < l0:
                    self.lchild[head] = (dep, l0)
                elif l1 == -1 or dep < l1:
                    self.lchild[head, 1] = dep
            else:
                r0, r1 = self.rchild[head]
                if r0 == -1 or dep > r0:
                    self.rchild[head] = (dep, r0)
                elif r1 == -1 or dep > r1:
                    self.rchild[head, 1] = dep

    def valid_mask(self, n_labels: int) -> np.ndarray:
        """Boolean [n_actions] mask of structurally valid actions."""
        mask = np.zeros(n_actions(n_labels), dtype=bool)
        has_b0 = self.buffer < self.n
        has_s0 = len(self.stack) > 0
        s0_has_head = has_s0 and self.heads[self.stack[-1]] != -2
        if has_b0:
            mask[SHIFT] = True
        if has_s0 and s0_has_head:
            mask[REDUCE] = True
        if has_s0 and has_b0 and not s0_has_head:
            for i in range(n_labels):
                mask[left_arc(i)] = True
        if has_b0:
            if has_s0:
                for i in range(n_labels):
                    mask[right_arc(i)] = True
        # Dead-end escape: if buffer exhausted but stack non-empty, allow
        # REDUCE of headless tokens by attaching to ROOT implicitly at end.
        if not mask.any() and has_s0:
            mask[REDUCE] = True
        return mask

    def apply(self, action: int) -> None:
        if action == SHIFT:
            self.stack.append(self.buffer)
            self.buffer += 1
        elif action == REDUCE:
            s0 = self.stack.pop()
            if self.heads[s0] == -2:  # dead-end escape: default to ROOT
                self._add_arc(-1, s0, 0)
        elif is_left_arc(action):
            s0 = self.stack.pop()
            self._add_arc(self.buffer, s0, action_label(action))
        elif is_right_arc(action):
            b0 = self.buffer
            head = self.stack[-1] if self.stack else -1
            self._add_arc(head, b0, action_label(action))
            self.stack.append(b0)
            self.buffer += 1
        else:
            raise ValueError(f"unknown action {action}")

    def features(self) -> np.ndarray:
        """[N_FEATURES] token indices (-1 = absent)."""
        f = np.full(N_FEATURES, -1, dtype=np.int64)
        st = self.stack
        if len(st) >= 1:
            f[0] = st[-1]
        if len(st) >= 2:
            f[1] = st[-2]
        if len(st) >= 3:
            f[2] = st[-3]
        for k in range(3):
            if self.buffer + k < self.n:
                f[3 + k] = self.buffer + k
        if len(st) >= 1:
            s0 = st[-1]
            f[6] = self.lchild[s0, 0]
            f[7] = self.rchild[s0, 0]
            f[10] = self.lchild[s0, 1]
            f[11] = self.rchild[s0, 1]
        if len(st) >= 2:
            s1 = st[-2]
            f[8] = self.lchild[s1, 0]
            f[9] = self.rchild[s1, 0]
        return f


def is_projective(heads: Sequence[int]) -> bool:
    """Single source of truth lives in pipeline/nonproj.py (strict variant:
    crossing arcs and covered roots are both non-projective — both are
    unreachable for this machine). Re-exported here for the oracle's guard."""
    from .nonproj import is_projective as _isp

    return _isp(heads)


def gold_oracle(
    heads: Sequence[int], label_ids: Sequence[int], n_labels: int
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Static arc-eager oracle: teacher-forced training data for one doc.

    Returns (actions [S], features [S, N_FEATURES], valid [S, n_actions])
    or None if the tree is unusable (the standard arc-eager restriction:
    non-projective arcs are unreachable; such docs are skipped for parser
    training, matching the projective-only capability of greedy arc-eager).

    ``heads[i] == i`` marks the root token (attached to virtual ROOT via the
    final REDUCE escape).
    """
    n = len(heads)
    gold_heads = [(-1 if heads[i] == i else heads[i]) for i in range(n)]
    if not is_projective(heads):
        return None
    state = ParseState(n)
    actions: List[int] = []
    feats: List[np.ndarray] = []
    valids: List[np.ndarray] = []
    max_steps = 4 * n + 4
    while not state.is_terminal() and len(actions) < max_steps:
        feats.append(state.features())
        valids.append(state.valid_mask(n_labels))
        action = _oracle_action(state, gold_heads, label_ids, n_labels)
        if action is None or not valids[-1][action]:
            return None  # oracle stuck (shouldn't happen on projective trees)
        actions.append(action)
        state.apply(action)
    if not state.is_terminal():
        return None
    # verify replay reproduced the gold tree (sanity: oracle correctness)
    ok = all(
        state.heads[d] == gold_heads[d]
        for d in range(n)
    )
    if not ok:
        return None
    return (
        np.asarray(actions, dtype=np.int64),
        np.stack(feats).astype(np.int64),
        np.stack(valids),
    )


def _oracle_action(
    state: ParseState, gold_heads: List[int], label_ids: Sequence[int], n_labels: int
) -> Optional[int]:
    """Static arc-eager oracle (Nivre-style priority):

    1. LEFT-ARC  if gold head of s0 is b0 (and s0 headless)
    2. RIGHT-ARC if gold head of b0 is s0
    3. REDUCE    if s0 is attached, has no remaining gold dependents in the
                 buffer, and popping it is needed: b0's gold head (or a gold
                 dependent of b0) lies strictly below s0 in the stack / ROOT
    4. SHIFT     otherwise
    """
    st = state.stack
    b0 = state.buffer if state.buffer < state.n else None
    s0 = st[-1] if st else None
    if b0 is None:
        return REDUCE if s0 is not None else None
    if s0 is not None:
        if gold_heads[s0] == b0 and state.heads[s0] == -2:
            return left_arc(label_ids[s0])
        if gold_heads[b0] == s0:
            return right_arc(label_ids[b0])
        if state.heads[s0] != -2:
            s0_done = all(
                gold_heads[k] != s0 for k in range(state.buffer, state.n)
            )
            below = set(st[:-1])
            below.add(-1)  # virtual ROOT
            need_pop = gold_heads[b0] in below or any(
                i >= 0 and gold_heads[i] == b0 for i in below
            )
            if s0_done and need_pop:
                return REDUCE
    return SHIFT


def decode_feature_update(heads_row: np.ndarray) -> None:  # pragma: no cover
    """Placeholder: device decode maintains child arrays in jnp (parser.py)."""
