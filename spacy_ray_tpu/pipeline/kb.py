"""Knowledge base for entity linking.

Capability parity with spaCy's ``KnowledgeBase`` (the ``entity_linker``
component's candidate store; part of the spaCy core surface the reference
trains against, SURVEY.md §2.3 "spaCy core"). Host-side by design: alias →
candidate lookup is a tiny dictionary operation that happens at collation
and decode time; only the dense mention-encoding math belongs on device
(components/nel.py).

Storage: entity ids with frequencies and a dense vector per entity, plus
alias tables mapping surface forms to candidate entities with prior
probabilities. Serialized as one ``.npz`` (vectors + a JSON payload for the
string tables) — portable, no pickle.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Sequence, Tuple, Union

import numpy as np


@dataclass
class Candidate:
    """One candidate entity for a mention: id, prior P(entity|alias), vector."""

    entity: str
    prior: float
    vector: np.ndarray
    freq: float = 0.0


class KnowledgeBase:
    def __init__(self, entity_vector_length: int):
        self.entity_vector_length = int(entity_vector_length)
        self._ids: List[str] = []
        self._row: Dict[str, int] = {}
        self._freqs: List[float] = []
        self._vectors: List[np.ndarray] = []
        # alias -> parallel lists (entity row, prior), sorted by prior desc
        self._aliases: Dict[str, List[Tuple[int, float]]] = {}

    # ------------------------------------------------------------- build
    def add_entity(self, entity: str, freq: float, vector) -> None:
        vec = np.asarray(vector, dtype=np.float32).reshape(-1)
        if vec.shape[0] != self.entity_vector_length:
            raise ValueError(
                f"entity {entity!r}: vector length {vec.shape[0]} != "
                f"kb entity_vector_length {self.entity_vector_length}"
            )
        if entity in self._row:
            raise ValueError(f"entity {entity!r} already in KB")
        self._row[entity] = len(self._ids)
        self._ids.append(entity)
        self._freqs.append(float(freq))
        self._vectors.append(vec)

    def add_alias(
        self, alias: str, entities: Sequence[str], probabilities: Sequence[float]
    ) -> None:
        if len(entities) != len(probabilities):
            raise ValueError("entities and probabilities must align")
        total = float(sum(probabilities))
        if total > 1.0 + 1e-6:
            raise ValueError(
                f"alias {alias!r}: prior probabilities sum to {total} > 1"
            )
        rows = []
        for ent, p in zip(entities, probabilities):
            if ent not in self._row:
                raise ValueError(f"alias {alias!r}: unknown entity {ent!r}")
            rows.append((self._row[ent], float(p)))
        rows.sort(key=lambda rp: -rp[1])
        self._aliases[alias] = rows

    # ------------------------------------------------------------ lookup
    def __len__(self) -> int:
        return len(self._ids)

    @property
    def entities(self) -> List[str]:
        return list(self._ids)

    @property
    def aliases(self) -> List[str]:
        return list(self._aliases)

    def vector_of(self, entity: str) -> np.ndarray:
        return self._vectors[self._row[entity]]

    def candidates(self, mention: str) -> List[Candidate]:
        """Candidates for a mention surface form, highest prior first
        (falls back to the lowercased alias, mirroring vector lookup)."""
        rows = self._aliases.get(mention)
        if rows is None:
            rows = self._aliases.get(mention.lower())
        if not rows:
            return []
        return [
            Candidate(
                entity=self._ids[r],
                prior=p,
                vector=self._vectors[r],
                freq=self._freqs[r],
            )
            for r, p in rows
        ]

    # ------------------------------------------------------------- disk
    @staticmethod
    def _norm(path: Union[str, Path]) -> str:
        """np.savez appends '.npz' to suffix-less names but np.load does
        not — normalize so to_disk/from_disk agree on the same file."""
        p = str(path)
        return p if p.endswith(".npz") else p + ".npz"

    def to_disk(self, path: Union[str, Path]) -> None:
        meta = {
            "entity_vector_length": self.entity_vector_length,
            "ids": self._ids,
            "freqs": self._freqs,
            "aliases": {
                a: [[r, p] for r, p in rows] for a, rows in self._aliases.items()
            },
        }
        vectors = (
            np.stack(self._vectors)
            if self._vectors
            else np.zeros((0, self.entity_vector_length), np.float32)
        )
        np.savez(
            self._norm(path),
            vectors=vectors,
            meta=np.frombuffer(
                json.dumps(meta).encode("utf8"), dtype=np.uint8
            ),
        )

    @classmethod
    def from_disk(cls, path: Union[str, Path]) -> "KnowledgeBase":
        with np.load(cls._norm(path), allow_pickle=False) as data:
            meta = json.loads(bytes(data["meta"]).decode("utf8"))
            vectors = np.asarray(data["vectors"], dtype=np.float32)
        kb = cls(meta["entity_vector_length"])
        for ent, freq, vec in zip(meta["ids"], meta["freqs"], vectors):
            kb.add_entity(ent, freq, vec)
        for alias, rows in meta["aliases"].items():
            kb._aliases[alias] = [(int(r), float(p)) for r, p in rows]
        return kb
