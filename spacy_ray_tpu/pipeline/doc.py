"""Doc / Span / Example: host-side annotation containers.

Capability parity with the spaCy ``Doc``/``Example`` objects that flow
through the reference's training loop (reference worker.py:8-16 imports;
SURVEY.md §2.3 row "spaCy core" — Doc/Vocab are native Cython there, and
explicitly host-side I/O-bound structures in the TPU design). These are
plain Python containers: the device never sees them — the batcher lowers
them to padded arrays.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class Span:
    """A labeled token-slice [start, end) of a doc. ``kb_id`` carries the
    knowledge-base link for entity linking ("" = unlinked / NIL)."""

    start: int
    end: int
    label: str
    kb_id: str = ""

    def __iter__(self):
        yield from (self.start, self.end, self.label)


@dataclass
class Doc:
    """A tokenized text with optional gold/predicted annotations."""

    words: List[str]
    spaces: Optional[List[bool]] = None
    # token-level
    tags: Optional[List[str]] = None  # fine-grained POS
    pos: Optional[List[str]] = None  # coarse UPOS
    heads: Optional[List[int]] = None  # dependency head index per token
    deps: Optional[List[str]] = None  # dependency label per token
    lemmas: Optional[List[str]] = None
    morphs: Optional[List[str]] = None  # UD FEATS string per token
    sent_starts: Optional[List[int]] = None  # 1/-1/0 per token
    # span-level
    ents: List[Span] = field(default_factory=list)  # named entities
    spans: Dict[str, List[Span]] = field(default_factory=dict)  # spancat groups
    # doc-level
    cats: Dict[str, float] = field(default_factory=dict)
    # tri-state entity-annotation marker (spaCy's has_annotation("ENT_IOB")):
    # True = annotated (empty ents means "no entities here" — predictions
    # count as false positives), False = unannotated (the scorer skips the
    # doc entirely), None = infer: annotated iff ents is non-empty. The
    # DocBin reader sets it explicitly from the ENT_IOB column's 0-vs-2
    # missing/O distinction.
    ents_annotated: Optional[bool] = None

    @property
    def has_ents_annotation(self) -> bool:
        if self.ents_annotated is not None:
            return self.ents_annotated
        return bool(self.ents)

    def __len__(self) -> int:
        return len(self.words)

    @property
    def text(self) -> str:
        if self.spaces is None:
            return " ".join(self.words)
        return "".join(
            w + (" " if sp else "") for w, sp in zip(self.words, self.spaces)
        )

    def ents_biluo(self) -> List[str]:
        """Render entity spans as per-token BILUO tags (O outside)."""
        tags = ["O"] * len(self.words)
        for span in self.ents:
            if span.end <= span.start:
                continue
            if span.end - span.start == 1:
                tags[span.start] = f"U-{span.label}"
            else:
                tags[span.start] = f"B-{span.label}"
                for i in range(span.start + 1, span.end - 1):
                    tags[i] = f"I-{span.label}"
                tags[span.end - 1] = f"L-{span.label}"
        return tags

    @staticmethod
    def spans_from_biluo(tags: List[str]) -> List[Span]:
        spans: List[Span] = []
        start, label = None, None
        for i, tag in enumerate(tags):
            if tag == "O" or tag == "-":
                start, label = None, None
                continue
            prefix, _, lab = tag.partition("-")
            if prefix == "U":
                spans.append(Span(i, i + 1, lab))
                start, label = None, None
            elif prefix == "B":
                start, label = i, lab
            elif prefix == "I":
                if start is None or lab != label:
                    start, label = None, None  # malformed; drop
            elif prefix == "L":
                if start is not None and lab == label:
                    spans.append(Span(start, i + 1, lab))
                start, label = None, None
        return spans

    def copy_shell(self) -> "Doc":
        """A prediction shell: same tokens, no annotations."""
        return Doc(words=list(self.words), spaces=list(self.spaces) if self.spaces else None)


@dataclass
class Example:
    """Paired (predicted, reference) docs, mirroring spacy's Example
    (consumed by the loop at reference worker.py:176-189)."""

    predicted: Doc
    reference: Doc

    @classmethod
    def from_gold(cls, gold: Doc) -> "Example":
        return cls(predicted=gold.copy_shell(), reference=gold)

    def __len__(self) -> int:
        return len(self.reference)
