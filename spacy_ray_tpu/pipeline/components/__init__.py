"""Pipeline components (registered in the ``factories`` registry)."""

from .base import Component  # noqa: F401
from . import tok2vec  # noqa: F401
from . import tagger  # noqa: F401
from . import textcat  # noqa: F401
from . import parser  # noqa: F401
from . import ner  # noqa: F401
from . import spancat  # noqa: F401
from . import token_classifiers  # noqa: F401
from . import lemmatizer  # noqa: F401
from . import entity_ruler  # noqa: F401
from . import attribute_ruler  # noqa: F401
from . import nel  # noqa: F401
from . import edit_tree_lemmatizer  # noqa: F401
