"""Dependency parser component (arc-eager, teacher-forced training).

Capability parity with spaCy's ``parser`` pipe trained by the reference
(reference worker.py:91/176-189; SURVEY.md §2.3 "spaCy core", §7 hard part
#1). Training lowers each gold tree to a precomputed (actions, state
features, valid masks) grid host-side (pipeline/transition.py) — the device
loss is one batched classification over the doc×step grid. Decode runs the
arc-eager machine under ``lax.scan`` on device (models/parser.py).

Scores: UAS/LAS (``dep_uas``/``dep_las``), matching spaCy's scorer keys for
the parity targets in BASELINE.md.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np
import jax
import jax.numpy as jnp

from ...registry import registry
from ...models.core import Context, Params
from ...models.parser import decode_parser, decode_parser_beam
from ...pipeline import nonproj
from ...pipeline import transition as T
from ...pipeline.doc import Doc, Example
from ...types import Padded, TokenBatch
from .base import Component


class ParserComponent(Component):

    default_score_weights = {"dep_uas": 0.5, "dep_las": 0.5}

    def __init__(self, name, model_cfg, beam_width: int = 1):
        super().__init__(name, model_cfg)
        self.beam_width = int(beam_width)
        # collation-time oracle accounting (reported by debug-data and the
        # CLI train summary; the reference's spaCy stack handles these docs
        # via pseudo-projective lifting, nonproj.pyx — silent drops capped
        # LAS with no diagnostic, VERDICT r1 #5)
        self.oracle_stats = {"docs": 0, "projectivized": 0, "skipped": 0}
        # make_targets may run concurrently on collation-pool workers
        # ([training] collate_workers): counter merges must be atomic
        import threading

        self._stats_lock = threading.Lock()
        self._warned_skip = False

    def add_labels_from(self, examples) -> None:
        labels = set(self.labels)
        for eg in examples:
            ref = eg.reference
            if ref.deps:
                labels.update(d for d in ref.deps if d)
                if ref.heads:
                    # decorated labels produced by pseudo-projective lifting
                    # must be in the inventory before the action space is
                    # sized (they are real LEFT/RIGHT-ARC labels at train
                    # and decode time)
                    res = nonproj.projectivize(ref.heads, ref.deps)
                    if res is not None and res[2] > 0:
                        labels.update(
                            l for l in res[1] if nonproj.is_decorated(l)
                        )
        self.labels = list(labels)

    def build_model(self):
        cfg = dict(self.model_cfg)
        cfg["nO"] = T.n_actions(len(self.labels))
        model = registry.resolve(cfg)
        self.model = model
        self.listens = bool(model.meta.get("has_listener"))
        return model

    # ------------------------------------------------------------------
    def make_targets(self, examples: List[Example], B: int, Tlen: int) -> Dict[str, np.ndarray]:
        label_ids = {label: i for i, label in enumerate(self.labels)}
        n_act = T.n_actions(len(self.labels))
        S = 2 * Tlen + 2
        actions = np.zeros((B, S), dtype=np.int32)
        feats = np.full((B, S, T.N_FEATURES), -1, dtype=np.int32)
        valid = np.zeros((B, S, n_act), dtype=bool)
        step_mask = np.zeros((B, S), dtype=bool)
        # per-call counters, merged under the lock at the end: this method
        # runs concurrently on collation-pool worker threads
        batch_stats = {"docs": 0, "projectivized": 0, "skipped": 0}
        labels_sig = tuple(self.labels)
        for i, eg in enumerate(examples):
            ref = eg.reference
            if not ref.heads or not ref.deps or len(ref) > Tlen:
                continue
            # oracle simulation is the collation hot path: memoize per
            # Example (the corpus reuses Example objects across epochs).
            # The key hashes the gold annotations so an augmenter mutating
            # reference heads/deps in place can never serve a stale oracle.
            memo_key = (labels_sig, hash((tuple(ref.heads), tuple(ref.deps))))
            cached = getattr(eg, "_oracle_cache", None)
            if cached is not None and cached[0] == memo_key:
                out, lifted = cached[1]
            else:
                res = nonproj.projectivize(ref.heads, ref.deps)
                if res is None:  # malformed tree (cycle / bad head index)
                    out, lifted = None, 0
                else:
                    proj_heads, deco_deps, lifted = res
                    # a decorated combo outside the label-sample window falls
                    # back to its undecorated base label (still supervises
                    # the arc; the decoration just isn't recoverable) rather
                    # than training against an arbitrary id
                    ids = [
                        label_ids.get(
                            d, label_ids.get(nonproj.decompose_label(d)[0], 0)
                        )
                        for d in deco_deps
                    ]
                    out = T.gold_oracle(proj_heads, ids, len(self.labels))
                try:
                    eg._oracle_cache = (memo_key, (out, lifted))
                except AttributeError:
                    pass
            batch_stats["docs"] += 1
            if lifted:
                batch_stats["projectivized"] += 1
            if out is None:  # oracle-unreachable even after lifting: skip
                batch_stats["skipped"] += 1
                if not self._warned_skip:
                    import sys

                    print(
                        f"[{self.name}] warning: dropped a doc whose gold tree "
                        "is unusable even after pseudo-projective lifting; "
                        "run debug-data for corpus-wide counts",
                        file=sys.stderr,
                    )
                    self._warned_skip = True
                continue
            acts, f, v = out
            s = min(len(acts), S)
            actions[i, :s] = acts[:s]
            feats[i, :s] = f[:s]
            valid[i, :s] = v[:s]
            step_mask[i, :s] = True
        with self._stats_lock:
            for key, count in batch_stats.items():
                self.oracle_stats[key] += count
        return {
            "actions": actions,
            "feats": feats,
            "valid": valid,
            "step_mask": step_mask,
        }

    # ------------------------------------------------------------------
    def loss(self, params: Params, inputs: Any, targets: Dict[str, Any], ctx: Context):
        logits = self.model.apply(params, (inputs, targets["feats"]), ctx)
        NEG = jnp.float32(-1e9)
        masked_logits = jnp.where(targets["valid"], logits, NEG)
        logp = jax.nn.log_softmax(masked_logits.astype(jnp.float32), axis=-1)
        gold = jax.nn.one_hot(targets["actions"], logits.shape[-1], dtype=jnp.float32)
        ce = -jnp.sum(gold * logp, axis=-1)
        mask_f = targets["step_mask"].astype(jnp.float32)
        loss = jnp.sum(ce * mask_f) / jnp.maximum(jnp.sum(mask_f), 1.0)
        pred = jnp.argmax(masked_logits, axis=-1)
        acc = jnp.sum((pred == targets["actions"]) * mask_f) / jnp.maximum(
            jnp.sum(mask_f), 1.0
        )
        return loss, {"parse_action_acc": acc}

    # ------------------------------------------------------------------
    def forward(self, params: Params, inputs: Any, ctx: Context):
        fns = self.model.meta["fns"]
        if isinstance(inputs, Padded):
            t2v = inputs
            if not self.listens:
                raise TypeError("parser got Padded input but has its own tok2vec")
        else:
            tok2vec = self.model.layers[0]
            t2v = tok2vec.apply(params.get("tok2vec", {}), inputs, ctx)
        lengths = jnp.sum(t2v.mask.astype(jnp.int32), axis=1)
        if self.beam_width > 1:
            heads, labels = decode_parser_beam(
                fns, params["upper"], t2v.X, lengths, len(self.labels),
                self.beam_width,
            )
        else:
            heads, labels = decode_parser(
                fns, params["upper"], t2v.X, lengths, len(self.labels)
            )
        return {"heads": heads, "labels": labels}

    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        heads = np.asarray(outputs["heads"])
        labels = np.asarray(outputs["labels"])
        for i, doc in enumerate(docs):
            n = lengths[i]
            doc.heads = [int(h) for h in heads[i, :n]]
            doc.deps = [
                self.labels[l] if self.labels else "dep" for l in labels[i, :n]
            ]
            # undo pseudo-projective lifting: decorated labels point back to
            # the original attachment site (must run BEFORE the ROOT rewrite,
            # which would erase the decoration)
            if any(nonproj.is_decorated(d) for d in doc.deps):
                doc.heads, doc.deps = nonproj.deprojectivize(doc.heads, doc.deps)
            # ROOT-attached tokens (head == self) get the root label
            for j in range(n):
                if doc.heads[j] == j:
                    doc.deps[j] = "ROOT"

    def score(self, examples: List[Example]) -> Dict[str, float]:
        from ..scoring import score_deps

        # spaCy Scorer.score_deps semantics: gold-punct tokens excluded
        # from UAS/LAS, labels compared lowercased, None when no gold parse
        return score_deps(examples)


@registry.factories("parser")
def make_parser(
    name: str, model: Dict[str, Any], beam_width: int = 1
) -> ParserComponent:
    return ParserComponent(name, model, beam_width=beam_width)
