"""Named entity recognizer: BILUO transition system (push-down automaton).

Capability parity with spaCy's ``ner`` pipe (BiluoPushDown transition
system over the same nn_parser machinery, SURVEY.md §2.3) as trained by the
reference. TPU-first: the BILUO action at each token depends only on the
token position and the open-entity automaton state, so

* training is one batched window-feature classification over [B, T]
  (teacher-forced gold actions = the BILUO tags — no scan);
* decode precomputes all logits in one matmul and runs only the constraint
  automaton under ``lax.scan`` (models/parser.py ``decode_biluo``).

Action encoding: O=0, B-i=1+4i, I-i=2+4i, L-i=3+4i, U-i=4+4i.
Scores: ``ents_p``/``ents_r``/``ents_f`` (exact-span match, spaCy scorer
semantics) + per-type F.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np
import jax.numpy as jnp

from ...registry import registry
from ...models.core import Context, Params
from ...models.parser import NER_N_FEATURES, decode_biluo, decode_biluo_viterbi, ner_window_features
from ...ops import ops as O
from ...pipeline.doc import Doc, Example, Span
from ...types import Padded
from .base import Component


def n_ner_actions(n_labels: int) -> int:
    return 1 + 4 * n_labels


def biluo_action_id(tag: str, label_ids: Dict[str, int]) -> int:
    if tag == "O" or tag == "-":
        return 0
    prefix, _, label = tag.partition("-")
    i = label_ids.get(label)
    if i is None:  # label outside the initialize()-sampled set: treat as O
        return 0
    return {"B": 1, "I": 2, "L": 3, "U": 4}[prefix] + 4 * i


def action_to_biluo(action: int, labels: List[str]) -> str:
    if action == 0:
        return "O"
    prefix = ["B", "I", "L", "U"][(action - 1) % 4]
    return f"{prefix}-{labels[(action - 1) // 4]}"




class NERComponent(Component):

    default_score_weights = {"ents_f": 1.0, "ents_p": 0.0, "ents_r": 0.0}

    sets_ents = True
    def __init__(self, name, model_cfg, decode: str = "viterbi"):
        super().__init__(name, model_cfg)
        if decode not in ("viterbi", "greedy"):
            raise ValueError(f"ner decode must be viterbi|greedy, got {decode!r}")
        self.decode = decode

    def add_labels_from(self, examples) -> None:
        labels = set(self.labels)
        for eg in examples:
            for span in eg.reference.ents:
                labels.add(span.label)
        self.labels = list(labels)

    def build_model(self):
        cfg = dict(self.model_cfg)
        cfg["nO"] = n_ner_actions(len(self.labels))
        model = registry.resolve(cfg)
        self.model = model
        self.listens = bool(model.meta.get("has_listener"))
        return model

    def make_targets(self, examples: List[Example], B: int, Tlen: int) -> Dict[str, np.ndarray]:
        label_ids = {label: i for i, label in enumerate(self.labels)}
        actions = np.zeros((B, Tlen), dtype=np.int32)
        mask = np.zeros((B, Tlen), dtype=bool)
        lengths = []
        for i, eg in enumerate(examples):
            ref = eg.reference
            n = min(len(ref), Tlen)
            lengths.append(n)
            tags = ref.ents_biluo()
            for t in range(n):
                actions[i, t] = biluo_action_id(tags[t], label_ids)
                mask[i, t] = True
        while len(lengths) < B:
            lengths.append(0)
        feats = np.asarray(ner_window_features(Tlen, np.asarray(lengths)))
        return {"actions": actions, "feats": feats, "ner_mask": mask}

    def loss(self, params: Params, inputs: Any, targets: Dict[str, Any], ctx: Context):
        logits = self.model.apply(params, (inputs, targets["feats"]), ctx)
        loss = O.masked_softmax_cross_entropy(
            logits, targets["actions"], targets["ner_mask"]
        )
        acc = O.masked_accuracy(logits, targets["actions"], targets["ner_mask"])
        return loss, {"ner_action_acc": acc}

    def forward(self, params: Params, inputs: Any, ctx: Context):
        if isinstance(inputs, Padded):
            t2v = inputs
        else:
            tok2vec = self.model.layers[0]
            t2v = tok2vec.apply(params.get("tok2vec", {}), inputs, ctx)
        B, Tlen, _ = t2v.X.shape
        lengths_arr = jnp.sum(t2v.mask.astype(jnp.int32), axis=1)
        feats = ner_window_features(Tlen, lengths_arr)
        fns = self.model.meta["fns"]
        logits = fns.step_logits(params["upper"], t2v.X, feats)
        decode_fn = (
            decode_biluo_viterbi if self.decode == "viterbi" else decode_biluo
        )
        actions = decode_fn(logits, lengths_arr, len(self.labels))
        return {"actions": actions}

    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        actions = np.asarray(outputs["actions"])
        for i, doc in enumerate(docs):
            n = lengths[i]
            tags = [action_to_biluo(int(a), self.labels) for a in actions[i, :n]]
            model_ents = Doc.spans_from_biluo(tags)
            if doc.ents:
                # respect entities preset by earlier components (e.g. an
                # entity_ruler placed before ner, spaCy semantics): keep
                # them and add only non-overlapping model entities
                claimed = {j for e in doc.ents for j in range(e.start, e.end)}
                model_ents = [
                    m
                    for m in model_ents
                    if not (set(range(m.start, m.end)) & claimed)
                ]
                doc.ents = sorted(doc.ents + model_ents, key=lambda s: s.start)
            else:
                doc.ents = model_ents

    def score(self, examples: List[Example]) -> Dict[str, float]:
        from ..scoring import score_spans

        # spaCy Scorer.score_spans semantics: docs without gold entity
        # annotation are skipped entirely (predictions there are NOT false
        # positives — Doc.has_ents_annotation carries the DocBin 0-vs-2
        # missing/O distinction); per-type PRF beside the micro scores;
        # None when no gold doc is annotated
        return score_spans(
            examples,
            "ents",
            lambda d: d.ents,
            has_annotation=lambda d: d.has_ents_annotation,
        )


@registry.factories("ner")
def make_ner(name: str, model: Dict[str, Any], decode: str = "viterbi") -> NERComponent:
    return NERComponent(name, model, decode=decode)
