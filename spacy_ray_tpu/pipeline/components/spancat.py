"""Span categorizer: ngram span suggester + multilabel span scorer.

Capability parity with spaCy's ``spancat`` pipe (BASELINE.json config #5).
TPU-first: the ngram span grid is STATIC given the padded length — for
sizes (1..k) the candidate set is k slices of the token axis — so span
representations are shifted-slice stacks (mean+max pooled), one batched
matmul scores every candidate, and validity is a mask. No ragged span
lists ever reach the device.

Spans may overlap (multilabel sigmoid, like the reference's spancat).
Scores: ``spans_{key}_f/p/r`` (exact span+label match).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ...registry import registry
from ...models.core import Context, Model, Params, glorot_uniform
from ...ops import ops as O
from ...pipeline.doc import Doc, Example, Span
from ...types import Padded
from .base import Component


@registry.misc("spacy.ngram_suggester.v1")
def ngram_suggester(sizes: List[int]):
    return {"sizes": [int(s) for s in sizes]}


@registry.misc("spacy.ngram_range_suggester.v1")
def ngram_range_suggester(min_size: int = 1, max_size: int = 3):
    """spaCy's range form: all ngram sizes in [min_size, max_size]."""
    if min_size < 1:
        raise ValueError(f"min_size must be >= 1, got {min_size}")
    if max_size < min_size:
        raise ValueError(f"max_size {max_size} < min_size {min_size}")
    return {"sizes": list(range(int(min_size), int(max_size) + 1))}


def span_grid(Tlen: int, sizes: List[int]) -> List[Tuple[int, int]]:
    """Static candidate list [(start, size)] for a padded length."""
    out = []
    for s in sizes:
        for start in range(Tlen - s + 1):
            out.append((start, s))
    return out


def span_reprs(X: jnp.ndarray, sizes: List[int]) -> jnp.ndarray:
    """X [B, T, D] -> [B, n_spans, 2D]: [mean; max] over each ngram span.

    Built from shifted slices (static shapes, no gathers).
    """
    B, Tlen, D = X.shape
    reprs = []
    for s in sizes:
        n = Tlen - s + 1
        if n <= 0:
            continue
        stack = jnp.stack([X[:, k : k + n, :] for k in range(s)], axis=2)
        # [B, n, s, D]
        mean = jnp.mean(stack, axis=2)
        mx = jnp.max(stack, axis=2)
        reprs.append(jnp.concatenate([mean, mx], axis=-1))
    return jnp.concatenate(reprs, axis=1)  # [B, n_spans, 2D]


@registry.architectures("spacy.SpanCategorizer.v1")
def SpanCategorizer(
    tok2vec: Model,
    reducer: Optional[Dict] = None,
    scorer: Optional[Dict] = None,
    suggester: Optional[Dict] = None,
    hidden_size: int = 128,
    nO: Optional[int] = None,
) -> Model:
    width = tok2vec.dims.get("nO")
    n_labels = nO if nO else 1
    sizes = (suggester or {}).get("sizes", [1, 2, 3])

    def init_fn(rng):
        r1, r2, r3 = jax.random.split(rng, 3)
        return {
            "tok2vec": tok2vec.init(r1),
            "hidden_W": glorot_uniform(r2, (2 * width, hidden_size)),
            "hidden_b": jnp.zeros((hidden_size,)),
            "out_W": glorot_uniform(r3, (hidden_size, n_labels)),
            "out_b": jnp.zeros((n_labels,)),
        }

    def apply_fn(params, x, ctx: Context) -> jnp.ndarray:
        t2v: Padded = tok2vec.apply(params.get("tok2vec", {}), x, ctx)
        reprs = span_reprs(t2v.X, sizes)  # [B, n_spans, 2D]
        h = O.gelu(reprs @ params["hidden_W"] + params["hidden_b"])
        return h @ params["out_W"] + params["out_b"]  # [B, n_spans, n_labels]

    has_listener = any(m.meta.get("listener") for m in tok2vec.walk())
    return Model(
        "spancat_model",
        init_fn,
        apply_fn,
        dims={"nO": n_labels, "width": width},
        layers=[tok2vec],
        meta={"has_listener": has_listener, "sizes": sizes},
    )


class SpanCatComponent(Component):
    def __init__(
        self,
        name: str,
        model_cfg: Dict[str, Any],
        spans_key: str = "sc",
        threshold: float = 0.5,
        max_positive: Optional[int] = None,
    ):
        super().__init__(name, model_cfg)
        self.spans_key = spans_key
        self.threshold = threshold
        self.max_positive = max_positive
        # per-instance: the score keys carry the configured spans_key
        self.default_score_weights = {
            f"spans_{spans_key}_f": 1.0,
            f"spans_{spans_key}_p": 0.0,
            f"spans_{spans_key}_r": 0.0,
        }

    def add_labels_from(self, examples) -> None:
        labels = set(self.labels)
        for eg in examples:
            for span in eg.reference.spans.get(self.spans_key, []):
                labels.add(span.label)
        self.labels = list(labels)

    @property
    def sizes(self) -> List[int]:
        assert self.model is not None
        return self.model.meta["sizes"]

    def make_targets(self, examples: List[Example], B: int, Tlen: int) -> Dict[str, np.ndarray]:
        label_ids = {label: i for i, label in enumerate(self.labels)}
        sizes = self.sizes if self.model else [1, 2, 3]
        grid = span_grid(Tlen, sizes)
        grid_index = {sp: i for i, sp in enumerate(grid)}
        n_spans = len(grid)
        n_labels = max(len(self.labels), 1)
        target = np.zeros((B, n_spans, n_labels), dtype=np.float32)
        mask = np.zeros((B, n_spans), dtype=bool)
        for i, eg in enumerate(examples):
            ref = eg.reference
            n = min(len(ref), Tlen)
            for j, (start, size) in enumerate(grid):
                if start + size <= n:
                    mask[i, j] = True
            for span in ref.spans.get(self.spans_key, []):
                size = span.end - span.start
                j = grid_index.get((span.start, size))
                li = label_ids.get(span.label)
                if j is not None and li is not None:
                    target[i, j, li] = 1.0
        return {"span_target": target, "span_mask": mask}

    def loss(self, params: Params, inputs: Any, targets: Dict[str, Any], ctx: Context):
        logits = self.model.apply(params, inputs, ctx)  # [B, n_spans, n_labels]
        loss = O.masked_sigmoid_bce(logits, targets["span_target"], targets["span_mask"])
        return loss, {}

    def forward(self, params: Params, inputs: Any, ctx: Context):
        logits = self.model.apply(params, inputs, ctx)
        return {"probs": jax.nn.sigmoid(logits.astype(jnp.float32))}

    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        probs = np.asarray(outputs["probs"])  # [B, n_spans, n_labels]
        grid = span_grid(self._grid_T(probs.shape[1]), self.sizes)
        for i, doc in enumerate(docs):
            n = lengths[i]
            found: List[Span] = []
            for j, (start, size) in enumerate(grid):
                if start + size > n:
                    continue
                # labels over threshold for THIS span, best-first;
                # max_positive limits labels per span (spaCy semantics)
                over = [
                    (float(probs[i, j, li]), label)
                    for li, label in enumerate(self.labels)
                    if probs[i, j, li] >= self.threshold
                ]
                over.sort(reverse=True)
                if self.max_positive:
                    over = over[: self.max_positive]
                for _, label in over:
                    found.append(Span(start, start + size, label))
            doc.spans[self.spans_key] = found

    def _grid_T(self, n_spans: int) -> int:
        """Invert len(span_grid(T, sizes)) = sum(T - s + 1) for T."""
        sizes = self.sizes
        k = len(sizes)
        # n_spans = k*T - sum(sizes) + k  =>  T = (n_spans + sum(sizes) - k) / k
        return (n_spans + sum(sizes) - k) // k

    def score(self, examples: List[Example]) -> Dict[str, float]:
        from ..scoring import score_spans

        key = self.spans_key
        # spaCy semantics: docs without the spans key are skipped (their
        # predictions aren't false positives); key-present-but-empty counts
        return score_spans(
            examples,
            f"spans_{key}",
            lambda d: d.spans.get(key, []),
            has_annotation=lambda d: key in d.spans,
        )


@registry.factories("spancat")
def make_spancat(
    name: str,
    model: Dict[str, Any],
    spans_key: str = "sc",
    threshold: float = 0.5,
    max_positive: Optional[int] = None,
    suggester: Optional[Dict] = None,
) -> SpanCatComponent:
    if suggester is not None:
        # thread the suggester's sizes into the model config block
        model = dict(model)
        model.setdefault("suggester", suggester)
    return SpanCatComponent(
        name, model, spans_key=spans_key, threshold=threshold, max_positive=max_positive
    )
