"""Text classification components: ``textcat`` (exclusive) and
``textcat_multilabel`` (BASELINE.json config #5).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np
import jax
import jax.numpy as jnp

from ...registry import registry
from ...models.core import Context, Params
from ...ops import ops as O
from ...pipeline.doc import Doc, Example
from .base import Component


class TextCatComponent(Component):

    default_score_weights = {"cats_score": 1.0}

    def __init__(self, name: str, model_cfg: Dict[str, Any], exclusive: bool, threshold: float = 0.5):
        super().__init__(name, model_cfg)
        self.exclusive = exclusive
        self.threshold = threshold

    def add_labels_from(self, examples) -> None:
        labels = set(self.labels)
        for eg in examples:
            labels.update(eg.reference.cats.keys())
        self.labels = list(labels)

    def make_targets(self, examples: List[Example], B: int, T: int) -> Dict[str, np.ndarray]:
        label_ids = {label: i for i, label in enumerate(self.labels)}
        cats = np.zeros((B, len(self.labels)), dtype=np.float32)
        mask = np.zeros((B,), dtype=bool)
        for i, eg in enumerate(examples):
            if eg.reference.cats:
                mask[i] = True
                for label, value in eg.reference.cats.items():
                    if label in label_ids:
                        cats[i, label_ids[label]] = float(value)
        return {"cats": cats, "cats_mask": mask}

    def loss(self, params: Params, inputs: Any, targets: Dict[str, Any], ctx: Context):
        logits = self.model.apply(params, inputs, ctx)  # [B, C]
        cats = targets["cats"]
        mask = targets["cats_mask"].astype(jnp.float32)
        if self.exclusive:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            per = -jnp.sum(cats * logp, axis=-1)
            loss = jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        else:
            loss = O.masked_sigmoid_bce(logits, cats, targets["cats_mask"])
        return loss, {}

    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        logits = np.asarray(outputs, dtype=np.float32)
        if self.exclusive:
            probs = np.exp(logits - logits.max(-1, keepdims=True))
            probs = probs / probs.sum(-1, keepdims=True)
        else:
            probs = 1.0 / (1.0 + np.exp(-logits))
        for i, doc in enumerate(docs):
            doc.cats = {label: float(probs[i, j]) for j, label in enumerate(self.labels)}

    def score(self, examples: List[Example]) -> Dict[str, float]:
        # spaCy Scorer.score_cats surface: micro P/R/F over per-label
        # decisions (gold positive at 0.5, prediction at the component
        # threshold), macro F, per-type PRF (cats_f_per_type), macro ROC
        # AUC (rank statistic; labels with one gold class are undefined and
        # excluded), accuracy for mutually-exclusive cats. Docs with no
        # gold cats are skipped; all keys None when none are annotated.
        from ..scoring import PRF, rank_auc

        micro = PRF()
        per_label: Dict[str, PRF] = {l: PRF() for l in self.labels}
        gold_by_label: Dict[str, List[int]] = {l: [] for l in self.labels}
        score_by_label: Dict[str, List[float]] = {l: [] for l in self.labels}
        correct = total = 0
        any_annotation = False
        for eg in examples:
            gold = eg.reference.cats
            pred = eg.predicted.cats
            if not gold:
                continue
            any_annotation = True
            if self.exclusive:
                total += 1
                g = max(gold, key=gold.get)
                p = max(pred, key=pred.get) if pred else None
                correct += int(g == p)
            for label in self.labels:
                gv = gold.get(label, 0.0) >= 0.5
                pv = pred.get(label, 0.0) >= self.threshold
                gold_by_label[label].append(int(gv))
                score_by_label[label].append(float(pred.get(label, 0.0)))
                prf = per_label[label]
                if pv and gv:
                    micro.tp += 1
                    prf.tp += 1
                elif pv:
                    micro.fp += 1
                    prf.fp += 1
                elif gv:
                    micro.fn += 1
                    prf.fn += 1
        if not any_annotation:
            return {
                "cats_micro_p": None,
                "cats_micro_r": None,
                "cats_micro_f": None,
                "cats_macro_f": None,
                "cats_macro_auc": None,
                "cats_f_per_type": None,
                "cats_score": None,
            }
        aucs = [
            a
            for a in (
                rank_auc(gold_by_label[l], score_by_label[l]) for l in self.labels
            )
            if a is not None
        ]
        out = {
            "cats_micro_p": micro.precision,
            "cats_micro_r": micro.recall,
            "cats_micro_f": micro.fscore,
            "cats_macro_f": (
                float(np.mean([per_label[l].fscore for l in self.labels]))
                if self.labels
                else 0.0
            ),
            "cats_macro_auc": float(np.mean(aucs)) if aucs else None,
            "cats_f_per_type": {
                l: per_label[l].to_dict() for l in sorted(per_label)
            },
            "cats_score": micro.fscore,
        }
        if self.exclusive and total:
            out["cats_acc"] = correct / total
            out["cats_score"] = out["cats_acc"]
        return out


@registry.factories("textcat")
def make_textcat(name: str, model: Dict[str, Any], threshold: float = 0.5) -> TextCatComponent:
    return TextCatComponent(name, model, exclusive=True, threshold=threshold)


@registry.factories("textcat_multilabel")
def make_textcat_multilabel(
    name: str, model: Dict[str, Any], threshold: float = 0.5
) -> TextCatComponent:
    return TextCatComponent(name, model, exclusive=False, threshold=threshold)
