"""Text classification components: ``textcat`` (exclusive) and
``textcat_multilabel`` (BASELINE.json config #5).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np
import jax
import jax.numpy as jnp

from ...registry import registry
from ...models.core import Context, Params
from ...ops import ops as O
from ...pipeline.doc import Doc, Example
from .base import Component


class TextCatComponent(Component):
    def __init__(self, name: str, model_cfg: Dict[str, Any], exclusive: bool, threshold: float = 0.5):
        super().__init__(name, model_cfg)
        self.exclusive = exclusive
        self.threshold = threshold

    def add_labels_from(self, examples) -> None:
        labels = set(self.labels)
        for eg in examples:
            labels.update(eg.reference.cats.keys())
        self.labels = list(labels)

    def make_targets(self, examples: List[Example], B: int, T: int) -> Dict[str, np.ndarray]:
        label_ids = {label: i for i, label in enumerate(self.labels)}
        cats = np.zeros((B, len(self.labels)), dtype=np.float32)
        mask = np.zeros((B,), dtype=bool)
        for i, eg in enumerate(examples):
            if eg.reference.cats:
                mask[i] = True
                for label, value in eg.reference.cats.items():
                    if label in label_ids:
                        cats[i, label_ids[label]] = float(value)
        return {"cats": cats, "cats_mask": mask}

    def loss(self, params: Params, inputs: Any, targets: Dict[str, Any], ctx: Context):
        logits = self.model.apply(params, inputs, ctx)  # [B, C]
        cats = targets["cats"]
        mask = targets["cats_mask"].astype(jnp.float32)
        if self.exclusive:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            per = -jnp.sum(cats * logp, axis=-1)
            loss = jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        else:
            loss = O.masked_sigmoid_bce(logits, cats, targets["cats_mask"])
        return loss, {}

    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        logits = np.asarray(outputs, dtype=np.float32)
        if self.exclusive:
            probs = np.exp(logits - logits.max(-1, keepdims=True))
            probs = probs / probs.sum(-1, keepdims=True)
        else:
            probs = 1.0 / (1.0 + np.exp(-logits))
        for i, doc in enumerate(docs):
            doc.cats = {label: float(probs[i, j]) for j, label in enumerate(self.labels)}

    def score(self, examples: List[Example]) -> Dict[str, float]:
        # micro-F over label decisions at threshold; accuracy for exclusive
        tp = fp = fn = 0
        correct = total = 0
        per_label_tp = {l: 0 for l in self.labels}
        per_label_fp = {l: 0 for l in self.labels}
        per_label_fn = {l: 0 for l in self.labels}
        for eg in examples:
            gold = eg.reference.cats
            pred = eg.predicted.cats
            if not gold:
                continue
            if self.exclusive:
                total += 1
                g = max(gold, key=gold.get)
                p = max(pred, key=pred.get) if pred else None
                correct += int(g == p)
            for label in self.labels:
                gv = gold.get(label, 0.0) >= 0.5
                pv = pred.get(label, 0.0) >= self.threshold
                if pv and gv:
                    tp += 1
                    per_label_tp[label] += 1
                elif pv:
                    fp += 1
                    per_label_fp[label] += 1
                elif gv:
                    fn += 1
                    per_label_fn[label] += 1
        micro_p = tp / (tp + fp) if tp + fp else 0.0
        micro_r = tp / (tp + fn) if tp + fn else 0.0
        micro_f = 2 * micro_p * micro_r / (micro_p + micro_r) if micro_p + micro_r else 0.0
        macro_fs = []
        for label in self.labels:
            ltp, lfp, lfn = per_label_tp[label], per_label_fp[label], per_label_fn[label]
            p = ltp / (ltp + lfp) if ltp + lfp else 0.0
            r = ltp / (ltp + lfn) if ltp + lfn else 0.0
            macro_fs.append(2 * p * r / (p + r) if p + r else 0.0)
        out = {
            "cats_micro_f": micro_f,
            "cats_macro_f": float(np.mean(macro_fs)) if macro_fs else 0.0,
            "cats_score": micro_f,
        }
        if self.exclusive and total:
            out["cats_acc"] = correct / total
            out["cats_score"] = out["cats_acc"]
        return out


@registry.factories("textcat")
def make_textcat(name: str, model: Dict[str, Any], threshold: float = 0.5) -> TextCatComponent:
    return TextCatComponent(name, model, exclusive=True, threshold=threshold)


@registry.factories("textcat_multilabel")
def make_textcat_multilabel(
    name: str, model: Dict[str, Any], threshold: float = 0.5
) -> TextCatComponent:
    return TextCatComponent(name, model, exclusive=False, threshold=threshold)
