"""Pipeline component protocol.

The functional counterpart of spaCy's ``TrainablePipe`` components that the
reference trains (reference worker.py:91 ``init_nlp`` builds them;
worker.py:176-189 ``nlp.update`` runs them; SURVEY.md §2.3 row "spaCy
core"). Split cleanly across the host/device boundary:

* host: label collection at initialize, target collation to padded arrays,
  annotation decode, scoring;
* device: a pure ``loss(params, inputs, targets, ctx)`` and pure
  ``forward(params, inputs, ctx)``, both jit-traceable.

Components are created from config blocks by ``@registry.factories``
factories (the ``factory = "tagger"`` key in ``[components.tagger]``).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

import jax
import numpy as np

from ...models.core import Context, Model, Params
from ...pipeline.doc import Doc, Example
from ...registry import registry


class Component:
    """Base class; subclasses override the protocol methods."""

    #: does this component's model contain a Tok2VecListener?
    listens: bool = False
    #: does this component WRITE doc.ents at prediction time? (gates
    #: use_gold_ents seeding in evaluate: gold mention boundaries are only
    #: safe to seed when nothing upstream produces mentions itself)
    sets_ents: bool = False
    #: does this component produce a trainable loss?
    trainable: bool = True
    #: default [training] score weights contributed by this component when
    #: the config declares none — spaCy's per-factory default_score_weights
    #: metadata (combined and normalized in the loop, spacy
    #: util.combine_score_weights semantics). Keys are OUR emitted score
    #: keys; 0.0 marks a score that's reported but unweighted.
    default_score_weights: Dict[str, float] = {}

    def __init__(self, name: str, model_cfg: Dict[str, Any]):
        self.name = name
        self.model_cfg = dict(model_cfg)
        self.model: Optional[Model] = None
        self.labels: List[str] = []

    # -------------------------- initialize ---------------------------
    def add_labels_from(self, examples: Iterable[Example]) -> None:
        """Collect the label set from gold data (host, once)."""

    def finish_labels(self) -> None:
        self.labels = sorted(set(self.labels))

    def build_model(self) -> Model:
        """Resolve the model config block (with nO injected) into a Model."""
        cfg = dict(self.model_cfg)
        if self.labels and "nO" in self._label_dim_keys():
            cfg["nO"] = len(self.labels)
            # any direct sub-block that explicitly declares `nO = null`
            # shares the component's output dim (spaCy fills these by dim
            # inference at init — e.g. TextCatEnsemble's linear_model);
            # here the label count is known before resolution
            for key, sub in list(cfg.items()):
                if (
                    isinstance(sub, dict)
                    and "@architectures" in sub
                    and "nO" in sub
                    and sub["nO"] is None
                ):
                    sub = dict(sub)
                    sub["nO"] = len(self.labels)
                    cfg[key] = sub
        model = registry.resolve(cfg)
        if not isinstance(model, Model):
            raise TypeError(f"[components.{self.name}.model] did not resolve to a Model")
        self.model = model
        self.listens = bool(model.meta.get("has_listener"))
        return model

    def _label_dim_keys(self) -> Tuple[str, ...]:
        return ("nO",)

    def init_params(self, rng: jax.Array) -> Params:
        assert self.model is not None, "build_model() first"
        from ...models.core import prune_empty

        return prune_empty(self.model.init(rng))

    # --------------------------- collate -----------------------------
    def make_targets(self, examples: List[Example], B: int, T: int) -> Dict[str, np.ndarray]:
        """Lower gold annotations to padded arrays for the device loss."""
        return {}

    # ---------------------------- device -----------------------------
    def loss(
        self,
        params: Params,
        inputs: Any,
        targets: Dict[str, Any],
        ctx: Context,
    ):
        """Pure loss: returns (scalar loss, metrics dict). jit-traced."""
        raise NotImplementedError

    def forward(self, params: Params, inputs: Any, ctx: Context):
        """Pure forward for prediction. jit-traced."""
        assert self.model is not None
        return self.model.apply(params, inputs, ctx)

    # ----------------------------- host ------------------------------
    def set_annotations(self, docs: List[Doc], outputs: Any, lengths: List[int]) -> None:
        """Decode device outputs into doc annotations."""

    def score(self, examples: List[Example]) -> Dict[str, float]:
        return {}
