"""Attribute ruler: pattern-triggered token attribute overrides (host side).

Capability parity with spaCy's ``attribute_ruler`` pipe (rule engine for
token-level exceptions — e.g. force TAG/POS/LEMMA/MORPH on specific
constructions after the statistical components run). Pure host-side; shares
the token-pattern matcher with the entity_ruler.

Pattern entries: ``{"patterns": [[{"LOWER": "who"}], [{"LOWER": "whom"}]],
"attrs": {"TAG": "PRON", "LEMMA": "who"}, "index": 0}`` — every match of any
listed token pattern sets the attrs on the matched token at ``index``
(supports negative indices into the match, spaCy semantics).

Patterns use the full shared matcher language (pipeline/matcher.py),
including TAG/POS-keyed constraints — the common spaCy use of retagging by
POS context, e.g. ``[{"TAG": "VBZ"}, {"LOWER": "not"}]``. Such rules read
the doc's predicted tags, so place the component after the tagger in
``[nlp] pipeline``.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

from ...registry import registry
from ...pipeline.doc import Doc, Example
from ..matcher import match_pattern, validate_token_patterns
from .base import Component

_ATTR_FIELDS = {
    "TAG": "tags",
    "POS": "pos",
    "LEMMA": "lemmas",
    "MORPH": "morphs",
}


class AttributeRulerComponent(Component):
    trainable = False
    listens = False

    def __init__(
        self,
        name: str,
        model_cfg: Optional[Dict[str, Any]] = None,
        patterns: Optional[List[Dict[str, Any]]] = None,
    ):
        super().__init__(name, model_cfg or {})
        self.patterns: List[Dict[str, Any]] = []
        if patterns:
            self.add_patterns(patterns)

    @staticmethod
    def _validate(patterns: Iterable[Dict[str, Any]]) -> None:
        """Fail at CONFIG time, not at the first matching token."""
        for rule in patterns:
            for attr in rule.get("attrs", {}):
                if attr.upper() not in _ATTR_FIELDS:
                    raise ValueError(
                        f"Unsupported attribute {attr!r}; "
                        f"supported: {sorted(_ATTR_FIELDS)}"
                    )
            validate_token_patterns(rule.get("patterns", []))

    def add_patterns(self, patterns: Iterable[Dict[str, Any]]) -> None:
        patterns = list(patterns)
        self._validate(patterns)
        self.patterns.extend(patterns)

    # host-only
    def build_model(self):
        self.model = None
        return None

    def init_params(self, rng):
        return {}

    def add_labels_from(self, examples) -> None:
        pass

    def finish_labels(self) -> None:
        self.labels = []

    def forward(self, params, inputs, ctx):
        return None

    @staticmethod
    def _ensure_field(doc: Doc, field: str) -> List[str]:
        values = getattr(doc, field)
        if values is None:
            values = [""] * len(doc)
            setattr(doc, field, values)
        return values

    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        for doc in docs:
            # match-all-THEN-apply (spaCy AttributeRuler semantics): TAG/POS-
            # keyed patterns must see the doc's ORIGINAL annotations for every
            # match, not annotations this very pass already rewrote
            pending: List[tuple] = []
            for rule in self.patterns:
                # attrs pre-validated at config time: resolve fields once
                field_values = [
                    (_ATTR_FIELDS[attr.upper()], value)
                    for attr, value in rule.get("attrs", {}).items()
                ]
                index = int(rule.get("index", 0))
                for pattern in rule.get("patterns", []):
                    for start in range(len(doc.words)):
                        end = match_pattern(doc, pattern, start)
                        if end is None or end <= start:
                            continue
                        span_len = end - start
                        ti = index if index >= 0 else span_len + index
                        if not (0 <= ti < span_len):
                            # spaCy raises for out-of-range index (E1001);
                            # a silent skip would hide rule typos
                            raise ValueError(
                                f"attribute_ruler rule index {index} is out "
                                f"of range for a {span_len}-token match at "
                                f"tokens {start}:{end}"
                            )
                        pending.append((start + ti, field_values))
            for tok, field_values in pending:
                for field, value in field_values:
                    self._ensure_field(doc, field)[tok] = value

    def score(self, examples: List[Example]) -> Dict[str, float]:
        return {}

    # serialization (components.json)
    def table_data(self) -> Dict[str, Any]:
        return {"patterns": self.patterns}

    def load_table_data(self, data: Dict[str, Any]) -> None:
        patterns = list(data.get("patterns", []))
        self._validate(patterns)
        self.patterns = patterns


@registry.factories("attribute_ruler")
def make_attribute_ruler(
    name: str,
    model: Optional[Dict[str, Any]] = None,
    patterns: Optional[List[Dict[str, Any]]] = None,
) -> AttributeRulerComponent:
    return AttributeRulerComponent(name, model, patterns=patterns)
