"""Entity ruler: pattern-based entity annotation (host side).

Capability parity with spaCy's ``entity_ruler`` pipe (rule engine; pure
host-side preprocessing per the SURVEY.md §2.3 host/device split). Patterns:

* phrase patterns: ``{"label": "ORG", "pattern": "Acme Corp"}`` — the phrase
  is run through the pipeline tokenizer and matched case-SENSITIVELY on the
  token sequence (use a token pattern with ``LOWER`` for case-insensitive)
* token patterns: ``{"label": "CITY", "pattern": [{"LOWER": "new"},
  {"LOWER": "york"}]}`` — the full shared matcher language
  (pipeline/matcher.py): TEXT/LOWER/TAG/POS/LEMMA/SHAPE/LENGTH/IS_* keys,
  literal or predicate values (REGEX, IN, NOT_IN, comparisons), and OP
  ``? * + ! {n} {n,m} {n,} {,m}``

Longest match wins; overlapping matches resolved left-to-right longest-first.
``overwrite_ents`` controls whether rule matches replace model entities or
only fill unclaimed tokens. Patterns serialize with the pipeline
(components.json).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

from ...registry import registry
from ...pipeline.doc import Doc, Example, Span
from ...pipeline.tokenizer import Tokenizer
from ..matcher import (  # noqa: F401  (validate_token_patterns re-exported)
    SUPPORTED_TOKEN_KEYS,
    match_pattern,
    validate_token_patterns,
)
from .base import Component

_PATTERN_TOKENIZER = Tokenizer()  # stateless; shared for phrase patterns


class EntityRulerComponent(Component):
    sets_ents = True
    trainable = False
    listens = False

    def __init__(
        self,
        name: str,
        model_cfg: Optional[Dict[str, Any]] = None,
        patterns: Optional[List[Dict[str, Any]]] = None,
        overwrite_ents: bool = False,
    ):
        super().__init__(name, model_cfg or {})
        self.patterns: List[Dict[str, Any]] = []
        self._compiled: List[Tuple[str, List[Dict[str, Any]]]] = []
        self.overwrite_ents = overwrite_ents
        if patterns:
            self.add_patterns(patterns)

    def add_patterns(self, patterns: Iterable[Dict[str, Any]]) -> None:
        patterns = list(patterns)
        validate_token_patterns(p["pattern"] for p in patterns)
        self.patterns.extend(patterns)
        self.finish_labels()

    # host-only
    def build_model(self):
        self.model = None
        return None

    def init_params(self, rng):
        return {}

    def add_labels_from(self, examples) -> None:
        pass

    def finish_labels(self) -> None:
        self.labels = sorted({p["label"] for p in self.patterns})
        # pre-tokenize phrase patterns ONCE (add/load time), not per doc:
        # self.patterns keeps the user's original form for serialization
        self._compiled = []
        for pat in self.patterns:
            pattern = pat["pattern"]
            if isinstance(pattern, str):
                # tokenize the phrase the same way docs are tokenized, so
                # phrases with punctuation ("U.S.", "Coca-Cola") can match
                pattern = [
                    {"TEXT": w} for w in _PATTERN_TOKENIZER(pattern).words
                ]
            self._compiled.append((pat["label"], pattern))

    def _find_matches(self, doc: Doc) -> List[Span]:
        words = doc.words
        matches: List[Tuple[int, int, str]] = []
        for label, pattern in self._compiled:
            for start in range(len(words)):
                end = match_pattern(doc, pattern, start)
                if end is not None and end > start:
                    matches.append((start, end, label))
        # longest-first, then leftmost; drop overlaps
        matches.sort(key=lambda m: (-(m[1] - m[0]), m[0]))
        taken = [False] * len(words)
        out: List[Span] = []
        for start, end, label in matches:
            if any(taken[start:end]):
                continue
            for i in range(start, end):
                taken[i] = True
            out.append(Span(start, end, label))
        out.sort(key=lambda s: s.start)
        return out

    def forward(self, params, inputs, ctx):
        return None  # host-side only

    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        for doc in docs:
            matches = self._find_matches(doc)
            if self.overwrite_ents:
                primary, secondary = matches, doc.ents  # rules win
            else:
                primary, secondary = doc.ents, matches  # model ents win
            claimed = {i for e in primary for i in range(e.start, e.end)}
            merged = list(primary) + [
                m
                for m in secondary
                if not (set(range(m.start, m.end)) & claimed)
            ]
            doc.ents = sorted(merged, key=lambda s: s.start)

    def score(self, examples: List[Example]) -> Dict[str, float]:
        return {}

    # serialization (components.json)
    def table_data(self) -> Dict[str, Any]:
        return {"patterns": self.patterns, "overwrite_ents": self.overwrite_ents}

    def load_table_data(self, data: Dict[str, Any]) -> None:
        patterns = list(data.get("patterns", []))
        # a hand-edited/corrupted components.json must fail here, eagerly,
        # like add_patterns does — not at the first matching token
        validate_token_patterns(p["pattern"] for p in patterns)
        self.patterns = patterns
        self.overwrite_ents = bool(data.get("overwrite_ents", False))
        self.finish_labels()


@registry.factories("entity_ruler")
def make_entity_ruler(
    name: str,
    model: Optional[Dict[str, Any]] = None,
    patterns: Optional[List[Dict[str, Any]]] = None,
    overwrite_ents: bool = False,
) -> EntityRulerComponent:
    return EntityRulerComponent(
        name, model, patterns=patterns, overwrite_ents=overwrite_ents
    )
