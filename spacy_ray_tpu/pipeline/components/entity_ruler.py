"""Entity ruler: pattern-based entity annotation (host side).

Capability parity with spaCy's ``entity_ruler`` pipe (rule engine; pure
host-side preprocessing per the SURVEY.md §2.3 host/device split). Patterns:

* phrase patterns: ``{"label": "ORG", "pattern": "Acme Corp"}`` — the phrase
  is run through the pipeline tokenizer and matched case-SENSITIVELY on the
  token sequence (use a token pattern with ``LOWER`` for case-insensitive)
* token patterns: ``{"label": "CITY", "pattern": [{"LOWER": "new"},
  {"LOWER": "york"}]}`` — each dict constrains one token: TEXT, LOWER,
  IS_DIGIT, IS_ALPHA, SHAPE, and OP ("?", "*", "+") for optional/repeats

Longest match wins; overlapping matches resolved left-to-right longest-first.
``overwrite_ents`` controls whether rule matches replace model entities or
only fill unclaimed tokens. Patterns serialize with the pipeline
(components.json).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

from ...registry import registry
from ...pipeline.doc import Doc, Example, Span
from ...pipeline.tokenizer import Tokenizer
from ...pipeline.vocab import shape_of
from .base import Component

_PATTERN_TOKENIZER = Tokenizer()  # stateless; shared for phrase patterns

SUPPORTED_TOKEN_KEYS = ("TEXT", "LOWER", "IS_DIGIT", "IS_ALPHA", "IS_TITLE", "SHAPE", "OP")
SUPPORTED_OPS = ("1", "?", "*", "+")


def validate_token_patterns(patterns) -> None:
    """Config-time validation of token-pattern lists (key + OP names);
    shared by entity_ruler and attribute_ruler so misconfigured rules fail
    before training/inference rather than at the first matching token."""
    for pattern in patterns:
        if isinstance(pattern, str):
            continue
        for tok in pattern:
            for key in tok:
                if key not in SUPPORTED_TOKEN_KEYS:
                    raise ValueError(
                        f"Unsupported token-pattern key {key!r}; "
                        f"supported: {sorted(SUPPORTED_TOKEN_KEYS)}"
                    )
            if str(tok.get("OP", "1")) not in SUPPORTED_OPS:
                raise ValueError(
                    f"Unsupported OP {tok.get('OP')!r}; supported: {SUPPORTED_OPS}"
                )


def _token_matches(constraint: Dict[str, Any], word: str) -> bool:
    for key, want in constraint.items():
        if key == "OP":
            continue
        if key == "TEXT":
            ok = word == want
        elif key == "LOWER":
            ok = word.lower() == want
        elif key == "IS_DIGIT":
            ok = word.isdigit() == bool(want)
        elif key == "IS_ALPHA":
            ok = word.isalpha() == bool(want)
        elif key == "IS_TITLE":
            ok = word.istitle() == bool(want)
        elif key == "SHAPE":
            ok = shape_of(word) == want
        else:
            raise ValueError(f"Unsupported token-pattern key {key!r}")
        if not ok:
            return False
    return True


def _match_token_pattern(
    pattern: List[Dict[str, Any]], words: List[str], start: int
) -> Optional[int]:
    """Match `pattern` at `start`; returns end index (exclusive) of the
    LONGEST match or None. Supports OP: "?", "*", "+" per token constraint."""

    def rec(pi: int, wi: int) -> Optional[int]:
        if pi == len(pattern):
            return wi
        tok = pattern[pi]
        op = tok.get("OP", "1")
        if op == "1":
            if wi < len(words) and _token_matches(tok, words[wi]):
                return rec(pi + 1, wi + 1)
            return None
        if op == "?":
            if wi < len(words) and _token_matches(tok, words[wi]):
                longer = rec(pi + 1, wi + 1)
                if longer is not None:
                    return longer
            return rec(pi + 1, wi)
        if op in ("*", "+"):
            # greedy: consume as many as possible, then backtrack
            max_wi = wi
            while max_wi < len(words) and _token_matches(tok, words[max_wi]):
                max_wi += 1
            min_needed = wi + 1 if op == "+" else wi
            for end in range(max_wi, min_needed - 1, -1):
                if op == "+" and end == wi:
                    break
                got = rec(pi + 1, end)
                if got is not None:
                    return got
            return None
        raise ValueError(f"Unsupported OP {op!r}")

    return rec(0, start)


class EntityRulerComponent(Component):
    trainable = False
    listens = False

    def __init__(
        self,
        name: str,
        model_cfg: Optional[Dict[str, Any]] = None,
        patterns: Optional[List[Dict[str, Any]]] = None,
        overwrite_ents: bool = False,
    ):
        super().__init__(name, model_cfg or {})
        self.patterns: List[Dict[str, Any]] = []
        self.overwrite_ents = overwrite_ents
        if patterns:
            self.add_patterns(patterns)

    def add_patterns(self, patterns: Iterable[Dict[str, Any]]) -> None:
        patterns = list(patterns)
        validate_token_patterns(p["pattern"] for p in patterns)
        self.patterns.extend(patterns)
        self.finish_labels()

    # host-only
    def build_model(self):
        self.model = None
        return None

    def init_params(self, rng):
        return {}

    def add_labels_from(self, examples) -> None:
        pass

    def finish_labels(self) -> None:
        self.labels = sorted({p["label"] for p in self.patterns})

    def _find_matches(self, words: List[str]) -> List[Span]:
        matches: List[Tuple[int, int, str]] = []
        for pat in self.patterns:
            label = pat["label"]
            pattern = pat["pattern"]
            if isinstance(pattern, str):
                # tokenize the phrase the same way docs are tokenized, so
                # phrases with punctuation ("U.S.", "Coca-Cola") can match
                pattern = [
                    {"TEXT": w} for w in _PATTERN_TOKENIZER(pattern).words
                ]
            for start in range(len(words)):
                end = _match_token_pattern(pattern, words, start)
                if end is not None and end > start:
                    matches.append((start, end, label))
        # longest-first, then leftmost; drop overlaps
        matches.sort(key=lambda m: (-(m[1] - m[0]), m[0]))
        taken = [False] * len(words)
        out: List[Span] = []
        for start, end, label in matches:
            if any(taken[start:end]):
                continue
            for i in range(start, end):
                taken[i] = True
            out.append(Span(start, end, label))
        out.sort(key=lambda s: s.start)
        return out

    def forward(self, params, inputs, ctx):
        return None  # host-side only

    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        for doc in docs:
            matches = self._find_matches(doc.words)
            if self.overwrite_ents:
                primary, secondary = matches, doc.ents  # rules win
            else:
                primary, secondary = doc.ents, matches  # model ents win
            claimed = {i for e in primary for i in range(e.start, e.end)}
            merged = list(primary) + [
                m
                for m in secondary
                if not (set(range(m.start, m.end)) & claimed)
            ]
            doc.ents = sorted(merged, key=lambda s: s.start)

    def score(self, examples: List[Example]) -> Dict[str, float]:
        return {}

    # serialization (components.json)
    def table_data(self) -> Dict[str, Any]:
        return {"patterns": self.patterns, "overwrite_ents": self.overwrite_ents}

    def load_table_data(self, data: Dict[str, Any]) -> None:
        patterns = list(data.get("patterns", []))
        # a hand-edited/corrupted components.json must fail here, eagerly,
        # like add_patterns does — not at the first matching token
        validate_token_patterns(p["pattern"] for p in patterns)
        self.patterns = patterns
        self.overwrite_ents = bool(data.get("overwrite_ents", False))
        self.finish_labels()


@registry.factories("entity_ruler")
def make_entity_ruler(
    name: str,
    model: Optional[Dict[str, Any]] = None,
    patterns: Optional[List[Dict[str, Any]]] = None,
    overwrite_ents: bool = False,
) -> EntityRulerComponent:
    return EntityRulerComponent(
        name, model, patterns=patterns, overwrite_ents=overwrite_ents
    )
