"""The shared tok2vec trunk component.

Capability parity with spaCy's ``tok2vec`` pipe: one trunk feeding every
listener-equipped head, gradients summed into the trunk because the whole
pipeline loss is a single differentiable function (the functional version of
the listener backprop relay; SURVEY.md §7 "Transformer sharing across
components" — the same wiring serves the transformer trunk).
"""

from __future__ import annotations

from typing import Any, Dict

from ...registry import registry
from ...models.core import Context, Params
from ...types import TokenBatch
from .base import Component


class Tok2VecComponent(Component):
    trainable = False  # no loss of its own; trained via listeners

    def loss(self, params, inputs, targets, ctx):
        raise RuntimeError("tok2vec has no standalone loss")

    def forward(self, params: Params, inputs: TokenBatch, ctx: Context):
        assert self.model is not None
        return self.model.apply(params, inputs, ctx)


@registry.factories("tok2vec")
def make_tok2vec(name: str, model: Dict[str, Any]) -> Tok2VecComponent:
    return Tok2VecComponent(name, model)


@registry.factories("transformer")
def make_transformer(
    name: str, model: Dict[str, Any], max_batch_items: int = 4096
) -> Tok2VecComponent:
    """The shared transformer trunk is a tok2vec-protocol component: heads
    listen to it exactly like the CNN trunk (spacy's `transformer` pipe +
    TransformerListener collapse to the same listener wiring here)."""
    return Tok2VecComponent(name, model)
