"""``entity_linker``: disambiguate entity mentions against a knowledge base.

Capability parity with spaCy's ``entity_linker`` pipe (spaCy core surface,
SURVEY.md §2.3; the reference trains whatever components the config names,
reference worker.py:91). The split is TPU-first:

* DEVICE: the only dense math — project tok2vec states into the KB's
  entity-vector space ([B, T, D], models/heads.py EntityLinker arch), and
  at training time pool mention encodings with a cumulative-sum gather
  (O(1) per mention, no ragged loops) and score K padded candidates per
  mention with one einsum. Statically shaped [B, M, K, D] throughout; the
  mention axis M buckets to powers of two to keep recompiles bounded.
* HOST: candidate lookup (a dict hit in pipeline/kb.py, at collation and
  decode), argmax + NIL-threshold decode over a handful of candidates per
  mention, and scoring.

Training uses gold mention spans whose gold KB id appears among the top-K
prior-ranked candidates (spaCy's EL trains the same way); prediction links
whatever ``doc.ents`` an upstream ``ner``/``entity_ruler`` produced earlier
in the same pipeline pass.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...models.core import Context, Params
from ...registry import registry
from ...types import Padded
from ..doc import Doc, Example
from ..kb import KnowledgeBase
from .base import Component

NEG = -1e30


def _mention_text(doc: Doc, start: int, end: int) -> str:
    """Canonical surface form for KB alias lookup: space-joined words (the
    same form on the training and decode paths, so priors line up)."""
    return " ".join(doc.words[start:end])


def _bucket_mentions(n: int) -> int:
    m = 2
    while m < n:
        m *= 2
    return m


class EntityLinkerComponent(Component):

    default_score_weights = {"nel_micro_f": 1.0, "nel_micro_p": 0.0, "nel_micro_r": 0.0}

    def __init__(
        self,
        name: str,
        model_cfg: Dict[str, Any],
        *,
        n_candidates: int = 8,
        threshold: float = 0.0,
        use_prior: bool = True,
        use_gold_ents: bool = True,
        kb_path: Optional[str] = None,
    ):
        super().__init__(name, model_cfg)
        self.n_candidates = int(n_candidates)
        self.threshold = float(threshold)
        self.use_prior = bool(use_prior)
        # evaluation seeds prediction shells with gold mention boundaries
        # (spaCy's use_gold_ents) so a linker-only pipeline is evaluable;
        # turn off when an upstream ner should supply the mentions
        self.use_gold_ents = bool(use_gold_ents)
        self.kb_path = kb_path
        self.kb: Optional[KnowledgeBase] = None

    # ------------------------------------------------------------- setup
    def set_kb(self, kb: KnowledgeBase) -> None:
        self.kb = kb

    def add_labels_from(self, examples) -> None:
        # EL has no label set; this initialize hook is where the KB loads
        if self.kb is None and self.kb_path:
            self.kb = KnowledgeBase.from_disk(self.kb_path)

    def build_model(self):
        if self.kb is None and self.kb_path:
            self.kb = KnowledgeBase.from_disk(self.kb_path)
        if self.kb is None:
            raise ValueError(
                f"entity_linker {self.name!r} has no knowledge base: set "
                "kb_path in [components." + self.name + "] or call set_kb() "
                "before initialize"
            )
        self.model_cfg = dict(self.model_cfg)
        self.model_cfg["nO"] = self.kb.entity_vector_length
        return super().build_model()

    # ----------------------------------------------------------- collate
    def _training_mentions(self, eg: Example) -> List[tuple]:
        """(start, end, gold_kb_id) spans to supervise. Gold ents by
        default; with ``use_gold_ents = false`` the mentions an upstream
        ``[training] annotating_components`` ner predicted onto
        ``eg.predicted`` (spaCy's EL-under-annotating-ner training setup;
        reference worker.py:187 threads the list into
        ``train_while_improving``), each supervised by boundary-matching
        against gold — predicted spans with no gold match are skipped. A doc
        with no predicted ents contributes no mentions (spaCy semantics: EL
        with use_gold_ents = false trains on doc.ents as-is)."""
        if self.use_gold_ents:
            return [(s.start, s.end, s.kb_id) for s in eg.reference.ents]
        gold = {(s.start, s.end): s.kb_id for s in eg.reference.ents if s.kb_id}
        return [
            (s.start, s.end, gold.get((s.start, s.end), ""))
            for s in eg.predicted.ents
        ]

    def make_targets(self, examples: List[Example], B: int, T: int) -> Dict[str, np.ndarray]:
        assert self.kb is not None
        K = self.n_candidates
        D = self.kb.entity_vector_length
        per_doc: List[List[tuple]] = []
        m_max = 1
        for eg in examples[:B]:
            rows = []
            for start, end, kb_id in self._training_mentions(eg):
                if not kb_id or end > T or end <= start:
                    continue
                cands = self.kb.candidates(
                    _mention_text(eg.reference, start, end)
                )[:K]
                gold = next(
                    (i for i, c in enumerate(cands) if c.entity == kb_id), None
                )
                if gold is None:
                    continue  # gold entity not reachable through top-K priors
                rows.append((start, end, gold, cands))
            per_doc.append(rows)
            m_max = max(m_max, len(rows))
        M = _bucket_mentions(m_max)
        m_start = np.zeros((B, M), np.int32)
        m_end = np.ones((B, M), np.int32)
        m_mask = np.zeros((B, M), bool)
        gold_idx = np.zeros((B, M), np.int32)
        cand_vecs = np.zeros((B, M, K, D), np.float32)
        cand_mask = np.zeros((B, M, K), bool)
        for i, rows in enumerate(per_doc):
            for j, (s, e, gold, cands) in enumerate(rows[:M]):
                m_start[i, j] = s
                m_end[i, j] = e
                m_mask[i, j] = True
                gold_idx[i, j] = gold
                for k, c in enumerate(cands):
                    cand_vecs[i, j, k] = c.vector
                    cand_mask[i, j, k] = True
        return {
            "nel_start": m_start,
            "nel_end": m_end,
            "nel_mask": m_mask,
            "nel_gold": gold_idx,
            "nel_cand_vecs": cand_vecs,
            "nel_cand_mask": cand_mask,
        }

    # ------------------------------------------------------------ device
    @staticmethod
    def _pool_mentions(X: jnp.ndarray, start: jnp.ndarray, end: jnp.ndarray) -> jnp.ndarray:
        """Mean of X[b, s:e] per mention via a cumulative-sum gather:
        X [B, T, D], start/end [B, M] -> [B, M, D]. No dynamic shapes."""
        B, T, D = X.shape
        csz = jnp.concatenate(
            [jnp.zeros((B, 1, D), X.dtype), jnp.cumsum(X, axis=1)], axis=1
        )  # [B, T+1, D]
        take = lambda idx: jnp.take_along_axis(  # noqa: E731
            csz, idx[..., None].astype(jnp.int32), axis=1
        )
        total = take(end) - take(start)  # [B, M, D]
        length = jnp.maximum((end - start)[..., None], 1).astype(X.dtype)
        return total / length

    def loss(self, params: Params, inputs: Any, targets: Dict[str, Any], ctx: Context):
        proj: Padded = self.model.apply(params, inputs, ctx)
        X = proj.X.astype(jnp.float32)
        enc = self._pool_mentions(X, targets["nel_start"], targets["nel_end"])
        scores = jnp.einsum(
            "bmd,bmkd->bmk", enc, targets["nel_cand_vecs"].astype(jnp.float32)
        )
        scores = jnp.where(targets["nel_cand_mask"], scores, NEG)
        logp = jax.nn.log_softmax(scores, axis=-1)
        nll = -jnp.take_along_axis(logp, targets["nel_gold"][..., None], axis=-1)[..., 0]
        mask = targets["nel_mask"].astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        loss = jnp.sum(nll * mask) / denom
        acc = jnp.sum((jnp.argmax(logp, -1) == targets["nel_gold"]) * mask) / denom
        return loss, {"nel_acc": acc}

    # ------------------------------------------------------------- host
    def set_annotations(self, docs: List[Doc], outputs: Any, lengths: List[int]) -> None:
        assert self.kb is not None
        X = np.asarray(outputs.X, dtype=np.float32)  # [B, T, D]
        for i, doc in enumerate(docs):
            L = lengths[i]
            for span in doc.ents:
                span.kb_id = ""
                if span.end > L or span.end <= span.start:
                    continue
                cands = self.kb.candidates(
                    _mention_text(doc, span.start, span.end)
                )[: self.n_candidates]
                if not cands:
                    continue
                enc = X[i, span.start : span.end].mean(axis=0)
                scores = np.array([float(enc @ c.vector) for c in cands])
                if self.use_prior:
                    scores = scores + np.log(
                        np.array([c.prior for c in cands]) + 1e-8
                    )
                probs = np.exp(scores - scores.max())
                probs = probs / probs.sum()
                best = int(np.argmax(probs))
                if probs[best] >= self.threshold:
                    span.kb_id = cands[best].entity

    # ------------------------------------------------------- serialization
    # settings travel in components.json; the KB itself is a binary npz
    # sidecar ({name}.kb.npz next to params.npz) — JSON-encoding dense
    # entity vectors would bloat every best-model save
    def table_data(self) -> Dict[str, Any]:
        return {
            "n_candidates": self.n_candidates,
            "threshold": self.threshold,
            "use_prior": self.use_prior,
            "use_gold_ents": self.use_gold_ents,
        }

    def load_table_data(self, data: Dict[str, Any]) -> None:
        self.n_candidates = int(data.get("n_candidates", self.n_candidates))
        self.threshold = float(data.get("threshold", self.threshold))
        self.use_prior = bool(data.get("use_prior", self.use_prior))
        self.use_gold_ents = bool(data.get("use_gold_ents", self.use_gold_ents))

    def save_binary(self, path, name: str) -> None:
        assert self.kb is not None
        self.kb.to_disk(Path(path) / f"{name}.kb.npz")

    def load_binary(self, path, name: str) -> None:
        kb_file = Path(path) / f"{name}.kb.npz"
        if kb_file.exists():
            self.kb = KnowledgeBase.from_disk(kb_file)

    def score(self, examples: List[Example]) -> Dict[str, float]:
        """Micro P/R/F over non-NIL links (spaCy's nel_micro_* semantics:
        a link is correct when a predicted span with the same boundaries
        carries the same KB id)."""
        tp = fp = fn = 0
        for eg in examples:
            gold = {
                (s.start, s.end): s.kb_id for s in eg.reference.ents if s.kb_id
            }
            pred = {
                (s.start, s.end): s.kb_id for s in eg.predicted.ents if s.kb_id
            }
            for key, kb_id in pred.items():
                if gold.get(key) == kb_id:
                    tp += 1
                else:
                    fp += 1
            for key, kb_id in gold.items():
                if pred.get(key) != kb_id:
                    fn += 1
        p = tp / (tp + fp) if tp + fp else 0.0
        r = tp / (tp + fn) if tp + fn else 0.0
        f = 2 * p * r / (p + r) if p + r else 0.0
        return {
            "nel_micro_p": p,
            "nel_micro_r": r,
            "nel_micro_f": f,
            "nel_score": f,
        }


@registry.factories("entity_linker")
def make_entity_linker(
    name: str,
    model: Dict[str, Any],
    n_candidates: int = 8,
    threshold: float = 0.0,
    use_prior: bool = True,
    use_gold_ents: bool = True,
    kb_path: Optional[str] = None,
) -> EntityLinkerComponent:
    return EntityLinkerComponent(
        name,
        model,
        n_candidates=n_candidates,
        threshold=threshold,
        use_prior=use_prior,
        use_gold_ents=use_gold_ents,
        kb_path=kb_path,
    )
