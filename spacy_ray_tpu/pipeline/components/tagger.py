"""Tagger component: per-token softmax classification (POS tags).

The first BASELINE.json config's head ("tagger-only CNN tok2vec"). Gold tags
come from Doc.tags; scoring is token accuracy (``tag_acc``), matching the
scorer key spaCy reports for parity checks.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np
import jax.numpy as jnp

from ...registry import registry
from ...models.core import Context, Params
from ...ops import ops as O
from ...pipeline.doc import Doc, Example
from .base import Component


class TaggerComponent(Component):

    default_score_weights = {"tag_acc": 1.0}

    def add_labels_from(self, examples) -> None:
        labels = set(self.labels)
        for eg in examples:
            if eg.reference.tags:
                labels.update(t for t in eg.reference.tags if t)
        self.labels = list(labels)

    def make_targets(self, examples: List[Example], B: int, T: int) -> Dict[str, np.ndarray]:
        label_ids = {label: i for i, label in enumerate(self.labels)}
        tags = np.zeros((B, T), dtype=np.int32)
        mask = np.zeros((B, T), dtype=bool)
        # per-Example target cache (examples recur every epoch; the label
        # set is fixed after initialize — the key invalidates the cache on
        # any label change, and is value-based: an id()-based key could
        # alias a freed component's address)
        cache_key = tuple(self.labels)
        for i, eg in enumerate(examples):
            ref = eg.reference
            if not ref.tags:
                continue
            cached = getattr(eg, "_tag_target_cache", None)
            if cached is None or cached[0] != cache_key:
                ids = np.zeros(len(ref.tags), dtype=np.int32)
                valid = np.zeros(len(ref.tags), dtype=bool)
                for j, tag in enumerate(ref.tags):
                    idx = label_ids.get(tag)
                    if idx is not None:
                        ids[j] = idx
                        valid[j] = True
                eg._tag_target_cache = cached = (cache_key, ids, valid)
            _, ids, valid = cached
            n = min(len(ids), T)
            tags[i, :n] = ids[:n]
            mask[i, :n] = valid[:n]
        return {"tags": tags, "tag_mask": mask}

    def loss(self, params: Params, inputs: Any, targets: Dict[str, Any], ctx: Context):
        logits = self.model.apply(params, inputs, ctx).X
        loss = O.masked_softmax_cross_entropy(
            logits, targets["tags"], targets["tag_mask"]
        )
        acc = O.masked_accuracy(logits, targets["tags"], targets["tag_mask"])
        return loss, {"tag_acc_batch": acc}

    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        pred = np.asarray(jnp.argmax(outputs.X, axis=-1))
        for i, doc in enumerate(docs):
            n = lengths[i]
            doc.tags = [self.labels[t] for t in pred[i, :n]]

    def score(self, examples: List[Example]) -> Dict[str, float]:
        from ..scoring import score_token_acc

        # spaCy Scorer.score_token_attr semantics: missing gold positions
        # excluded; None (not 0.0) when no gold tags exist anywhere
        return score_token_acc(examples, "tag_acc", lambda d: d.tags)


@registry.factories("tagger")
def make_tagger(name: str, model: Dict[str, Any]) -> TaggerComponent:
    return TaggerComponent(name, model)
