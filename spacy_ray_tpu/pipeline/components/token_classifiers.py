"""Additional per-token classifier components: morphologizer + senter.

Capability parity with spaCy's ``morphologizer`` and ``senter`` pipes (part
of the pipeline family the reference trains through its config-driven loop;
both are per-token classification heads over the shared tok2vec, like the
tagger). They reuse the tagger machinery with different gold attributes:

* morphologizer: label = "POS|FEATS" combination string (spaCy semantics);
  sets doc.pos and doc.morphs. Score: ``pos_acc``, ``morph_acc``.
* senter: binary sentence-start decisions; sets doc.sent_starts.
  Score: ``sents_f`` (boundary P/R/F over start positions, excluding
  token 0 which is trivially a start).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np
import jax.numpy as jnp

from ...registry import registry
from ...pipeline.doc import Doc, Example
from .base import Component
from .tagger import TaggerComponent


class MorphologizerComponent(TaggerComponent):
    @staticmethod
    def _gold_label(doc: Doc, i: int) -> str:
        pos = doc.pos[i] if doc.pos else ""
        morph = doc.morphs[i] if doc.morphs else ""
        if not pos and not morph:
            return ""
        return f"{pos}|{morph}" if morph else pos

    def add_labels_from(self, examples) -> None:
        labels = set(self.labels)
        for eg in examples:
            ref = eg.reference
            if ref.pos or ref.morphs:
                for i in range(len(ref)):
                    label = self._gold_label(ref, i)
                    if label:
                        labels.add(label)
        self.labels = list(labels)

    def make_targets(self, examples: List[Example], B: int, T: int) -> Dict[str, np.ndarray]:
        label_ids = {label: i for i, label in enumerate(self.labels)}
        tags = np.zeros((B, T), dtype=np.int32)
        mask = np.zeros((B, T), dtype=bool)
        for i, eg in enumerate(examples):
            ref = eg.reference
            if not (ref.pos or ref.morphs):
                continue
            for j in range(min(len(ref), T)):
                label = self._gold_label(ref, j)
                if label in label_ids:
                    tags[i, j] = label_ids[label]
                    mask[i, j] = True
        return {"tags": tags, "tag_mask": mask}

    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        pred = np.asarray(jnp.argmax(outputs.X, axis=-1))
        for i, doc in enumerate(docs):
            n = lengths[i]
            pos, morphs = [], []
            for t in pred[i, :n]:
                label = self.labels[t] if self.labels else ""
                p, _, m = label.partition("|")
                pos.append(p)
                morphs.append(m)
            doc.pos = pos
            doc.morphs = morphs

    def score(self, examples: List[Example]) -> Dict[str, float]:
        pos_correct = morph_correct = total = 0
        for eg in examples:
            ref, pred = eg.reference, eg.predicted
            if not (ref.pos or ref.morphs):
                continue
            n = min(len(ref), len(pred.pos or []))
            for i in range(n):
                gold = self._gold_label(ref, i)
                if not gold:
                    continue
                total += 1
                gp, _, gm = gold.partition("|")
                if pred.pos and pred.pos[i] == gp:
                    pos_correct += 1
                pm = pred.morphs[i] if pred.morphs else ""
                if pm == gm:
                    morph_correct += 1
        return {
            "pos_acc": pos_correct / total if total else 0.0,
            "morph_acc": morph_correct / total if total else 0.0,
        }


class SenterComponent(TaggerComponent):
    """Binary sentence-start classifier. Labels fixed: ["I", "S"]."""

    def add_labels_from(self, examples) -> None:
        self.labels = ["I", "S"]

    def finish_labels(self) -> None:
        self.labels = ["I", "S"]

    def make_targets(self, examples: List[Example], B: int, T: int) -> Dict[str, np.ndarray]:
        tags = np.zeros((B, T), dtype=np.int32)
        mask = np.zeros((B, T), dtype=bool)
        for i, eg in enumerate(examples):
            ref = eg.reference
            if not ref.sent_starts:
                continue
            for j, s in enumerate(ref.sent_starts[:T]):
                tags[i, j] = 1 if s == 1 else 0
                mask[i, j] = s != 0  # 0 = unannotated
        return {"tags": tags, "tag_mask": mask}

    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        pred = np.asarray(jnp.argmax(outputs.X, axis=-1))
        for i, doc in enumerate(docs):
            n = lengths[i]
            starts = [1 if t == 1 else -1 for t in pred[i, :n]]
            if starts:
                starts[0] = 1  # first token always starts a sentence
            doc.sent_starts = starts

    def score(self, examples: List[Example]) -> Dict[str, float]:
        tp = fp = fn = 0
        for eg in examples:
            gold = eg.reference.sent_starts
            pred = eg.predicted.sent_starts
            if not gold or not pred:
                continue
            n = min(len(gold), len(pred))
            # skip position 0: trivially a start
            g = {i for i in range(1, n) if gold[i] == 1}
            p = {i for i in range(1, n) if pred[i] == 1}
            tp += len(g & p)
            fp += len(p - g)
            fn += len(g - p)
        prec = tp / (tp + fp) if tp + fp else 0.0
        rec = tp / (tp + fn) if tp + fn else 0.0
        f = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        return {"sents_p": prec, "sents_r": rec, "sents_f": f}


@registry.factories("morphologizer")
def make_morphologizer(name: str, model: Dict[str, Any]) -> MorphologizerComponent:
    return MorphologizerComponent(name, model)


@registry.factories("senter")
def make_senter(name: str, model: Dict[str, Any]) -> SenterComponent:
    return SenterComponent(name, model)
