"""Additional per-token classifier components: morphologizer + senter.

Capability parity with spaCy's ``morphologizer`` and ``senter`` pipes (part
of the pipeline family the reference trains through its config-driven loop;
both are per-token classification heads over the shared tok2vec, like the
tagger). They reuse the tagger machinery with different gold attributes:

* morphologizer: label = "POS|FEATS" combination string (spaCy semantics);
  sets doc.pos and doc.morphs. Score: ``pos_acc``, ``morph_acc``.
* senter: binary sentence-start decisions; sets doc.sent_starts.
  Score: ``sents_f`` (boundary P/R/F over start positions, excluding
  token 0 which is trivially a start).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np
import jax.numpy as jnp

from ...registry import registry
from ...pipeline.doc import Doc, Example
from .base import Component
from .tagger import TaggerComponent


class MorphologizerComponent(TaggerComponent):

    default_score_weights = {"pos_acc": 0.5, "morph_acc": 0.5}
    @staticmethod
    def _gold_label(doc: Doc, i: int) -> str:
        pos = doc.pos[i] if doc.pos else ""
        morph = doc.morphs[i] if doc.morphs else ""
        if not pos and not morph:
            return ""
        return f"{pos}|{morph}" if morph else pos

    def add_labels_from(self, examples) -> None:
        labels = set(self.labels)
        for eg in examples:
            ref = eg.reference
            if ref.pos or ref.morphs:
                for i in range(len(ref)):
                    label = self._gold_label(ref, i)
                    if label:
                        labels.add(label)
        self.labels = list(labels)

    def make_targets(self, examples: List[Example], B: int, T: int) -> Dict[str, np.ndarray]:
        label_ids = {label: i for i, label in enumerate(self.labels)}
        tags = np.zeros((B, T), dtype=np.int32)
        mask = np.zeros((B, T), dtype=bool)
        for i, eg in enumerate(examples):
            ref = eg.reference
            if not (ref.pos or ref.morphs):
                continue
            for j in range(min(len(ref), T)):
                label = self._gold_label(ref, j)
                if label in label_ids:
                    tags[i, j] = label_ids[label]
                    mask[i, j] = True
        return {"tags": tags, "tag_mask": mask}

    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        pred = np.asarray(jnp.argmax(outputs.X, axis=-1))
        for i, doc in enumerate(docs):
            n = lengths[i]
            pos, morphs = [], []
            for t in pred[i, :n]:
                label = self.labels[t] if self.labels else ""
                p, _, m = label.partition("|")
                pos.append(p)
                morphs.append(m)
            doc.pos = pos
            doc.morphs = morphs

    def score(self, examples: List[Example]) -> Dict[str, float]:
        from ..scoring import score_morph_per_feat, score_token_acc

        # spaCy morphologizer surface: pos_acc + morph_acc (exact FEATS
        # string) + morph_per_feat (independent PRF per UD feature); each
        # None when that gold layer is absent everywhere
        out: Dict[str, Any] = {}
        out.update(score_token_acc(examples, "pos_acc", lambda d: d.pos))
        out.update(score_token_acc(examples, "morph_acc", lambda d: d.morphs))
        out.update(score_morph_per_feat(examples))
        return out


class SenterComponent(TaggerComponent):
    """Binary sentence-start classifier. Labels fixed: ["I", "S"]."""

    default_score_weights = {"sents_f": 1.0, "sents_p": 0.0, "sents_r": 0.0}

    def add_labels_from(self, examples) -> None:
        self.labels = ["I", "S"]

    def finish_labels(self) -> None:
        self.labels = ["I", "S"]

    def make_targets(self, examples: List[Example], B: int, T: int) -> Dict[str, np.ndarray]:
        tags = np.zeros((B, T), dtype=np.int32)
        mask = np.zeros((B, T), dtype=bool)
        for i, eg in enumerate(examples):
            ref = eg.reference
            if not ref.sent_starts:
                continue
            for j, s in enumerate(ref.sent_starts[:T]):
                tags[i, j] = 1 if s == 1 else 0
                mask[i, j] = s != 0  # 0 = unannotated
        return {"tags": tags, "tag_mask": mask}

    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        pred = np.asarray(jnp.argmax(outputs.X, axis=-1))
        for i, doc in enumerate(docs):
            n = lengths[i]
            starts = [1 if t == 1 else -1 for t in pred[i, :n]]
            if starts:
                starts[0] = 1  # first token always starts a sentence
            doc.sent_starts = starts

    def score(self, examples: List[Example]) -> Dict[str, float]:
        from ..scoring import score_sents

        # spaCy scores sentences as SPANS (both boundaries must match),
        # not per boundary token — Scorer.score_spans over doc.sents
        return score_sents(examples)


@registry.factories("morphologizer")
def make_morphologizer(name: str, model: Dict[str, Any]) -> MorphologizerComponent:
    return MorphologizerComponent(name, model)


@registry.factories("senter")
def make_senter(name: str, model: Dict[str, Any]) -> SenterComponent:
    return SenterComponent(name, model)
