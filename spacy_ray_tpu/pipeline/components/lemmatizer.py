"""Lemmatizer: host-side, lookup and rule modes.

Capability parity with spaCy's ``lemmatizer`` pipe (rule/lookup host-side
preprocessing — SURVEY.md §2.3 places Doc-level string work on the host).
No device compute.

* ``lookup`` (default): at initialize, build (word, pos) -> lemma and
  word -> lemma tables from the gold corpus by majority count; prediction
  is a dictionary lookup with suffix-strip fallbacks.
* ``rule``: spaCy's rule-lemmatizer algorithm — per-POS exception table,
  then per-POS suffix rewrite rules validated against a lemma INDEX (a
  rewrite counts only if it lands on a known lemma). Ships a built-in
  English morphy-style rule set + core irregulars (spaCy loads these from
  spacy-lookups-data; this image is zero-egress, so a compact built-in
  plus config-supplied ``tables_path`` JSON covers the surface), and the
  index extends itself from gold lemmas at initialize.

Score: ``lemma_acc``.
"""

from __future__ import annotations

import json
from collections import Counter, defaultdict
from typing import Any, Dict, List, Optional, Tuple

from ...registry import registry
from ...pipeline.doc import Doc, Example
from .base import Component

_SUFFIX_RULES = [
    ("ies", "y"),
    ("sses", "ss"),
    ("ing", ""),
    ("ed", ""),
    ("s", ""),
]

# Built-in English rule tables (WordNet-morphy shape, the same structure
# spaCy's EnglishLemmatizer consumes from spacy-lookups-data)
_EN_RULES: Dict[str, List[List[str]]] = {
    "NOUN": [
        ["ses", "s"], ["ves", "f"], ["xes", "x"], ["zes", "z"],
        ["ches", "ch"], ["shes", "sh"], ["men", "man"], ["ies", "y"],
        ["s", ""],
    ],
    "VERB": [
        ["ies", "y"], ["ees", "ee"], ["es", "e"], ["es", ""],
        ["ied", "y"], ["ed", "e"], ["ed", ""], ["ing", "e"], ["ing", ""],
        ["s", ""],
    ],
    "ADJ": [["er", ""], ["est", ""], ["er", "e"], ["est", "e"], ["ier", "y"], ["iest", "y"]],
    "ADV": [],
}

_EN_EXCEPTIONS: Dict[str, Dict[str, str]] = {
    "VERB": {
        "am": "be", "are": "be", "is": "be", "was": "be", "were": "be",
        "been": "be", "being": "be", "has": "have", "had": "have",
        "having": "have", "does": "do", "did": "do", "done": "do",
        "goes": "go", "went": "go", "gone": "go", "said": "say",
        "made": "make", "took": "take", "taken": "take", "came": "come",
        "saw": "see", "seen": "see", "got": "get", "gotten": "get",
        "knew": "know", "known": "know", "thought": "think",
        "gave": "give", "given": "give", "found": "find", "told": "tell",
        "became": "become", "left": "leave", "felt": "feel", "put": "put",
        "brought": "bring", "began": "begin", "begun": "begin",
        "kept": "keep", "held": "hold", "wrote": "write", "written": "write",
        "stood": "stand", "heard": "hear", "let": "let", "meant": "mean",
        "set": "set", "met": "meet", "ran": "run", "paid": "pay",
        "sat": "sit", "spoke": "speak", "spoken": "speak", "lay": "lie",
        "led": "lead", "read": "read", "grew": "grow", "grown": "grow",
        "lost": "lose", "fell": "fall", "fallen": "fall", "sent": "send",
        "built": "build", "understood": "understand", "drew": "draw",
        "drawn": "draw", "broke": "break", "broken": "break",
        "spent": "spend", "cut": "cut", "rose": "rise", "risen": "rise",
        "drove": "drive", "driven": "drive", "bought": "buy",
        "wore": "wear", "worn": "wear", "chose": "choose", "chosen": "choose",
    },
    "NOUN": {
        "men": "man", "women": "woman", "children": "child", "people": "person",
        "teeth": "tooth", "feet": "foot", "mice": "mouse", "geese": "goose",
        "oxen": "ox", "lives": "life", "wives": "wife", "knives": "knife",
        "leaves": "leaf", "halves": "half", "selves": "self",
        "criteria": "criterion", "phenomena": "phenomenon", "data": "datum",
        "analyses": "analysis", "theses": "thesis", "crises": "crisis",
        "indices": "index", "matrices": "matrix",
    },
    "ADJ": {
        "better": "good", "best": "good", "worse": "bad", "worst": "bad",
        "further": "far", "furthest": "far", "farther": "far", "farthest": "far",
    },
    "ADV": {"better": "well", "best": "well", "worse": "badly", "worst": "badly"},
}


class LemmatizerComponent(Component):

    default_score_weights = {"lemma_acc": 1.0}

    trainable = False
    listens = False

    def __init__(
        self,
        name: str,
        model_cfg: Optional[Dict[str, Any]] = None,
        mode: str = "lookup",
        tables_path: Optional[str] = None,
    ):
        super().__init__(name, model_cfg or {})
        if mode not in ("lookup", "rule"):
            raise ValueError(f"lemmatizer mode must be lookup/rule, got {mode!r}")
        self.mode = mode
        self.table: Dict[Tuple[str, str], str] = {}
        self.word_table: Dict[str, str] = {}
        # rule mode: per-POS rewrite rules / exceptions / valid-lemma index
        self.rules: Dict[str, List[List[str]]] = {
            p: [list(r) for r in rs] for p, rs in _EN_RULES.items()
        }
        self.exceptions: Dict[str, Dict[str, str]] = {
            p: dict(t) for p, t in _EN_EXCEPTIONS.items()
        }
        self.index: Dict[str, set] = {p: set() for p in self.rules}
        if tables_path:
            self._load_tables_file(tables_path)

    def _load_tables_file(self, path: str) -> None:
        """User tables (JSON: {"rules": {POS: [[suf, repl]...]}, "exceptions":
        {POS: {form: lemma}}, "index": {POS: [lemma...]}}) REPLACE the
        built-in English tables per key present — the spacy-lookups-data
        extension point."""
        from pathlib import Path

        if not Path(path).exists():
            # a model trained with tables_path must stay loadable where the
            # file is absent: from_disk re-runs this factory BEFORE
            # load_table_data restores the serialized (authoritative) tables
            import warnings

            warnings.warn(
                f"lemmatizer tables_path {path!r} not found; using built-in "
                "tables (serialized model tables, if any, load afterwards)"
            )
            return
        data = json.loads(Path(path).read_text(encoding="utf8"))
        if "rules" in data:
            self.rules = {p: [list(r) for r in rs] for p, rs in data["rules"].items()}
        if "exceptions" in data:
            self.exceptions = {p: dict(t) for p, t in data["exceptions"].items()}
        if "index" in data:
            self.index = {p: set(v) for p, v in data["index"].items()}
        for p in self.rules:
            self.index.setdefault(p, set())

    # host-only: no model/params
    def build_model(self):
        self.model = None
        return None

    def init_params(self, rng):
        return {}

    def add_labels_from(self, examples) -> None:
        counts: Dict[Tuple[str, str], Counter] = defaultdict(Counter)
        word_counts: Dict[str, Counter] = defaultdict(Counter)
        for eg in examples:
            ref = eg.reference
            if not ref.lemmas:
                continue
            for i, lemma in enumerate(ref.lemmas):
                if not lemma:
                    continue
                pos = ref.pos[i] if ref.pos else ""
                if self.mode == "rule":
                    if pos in self.index:
                        # gold lemmas extend the validation index
                        self.index[pos].add(lemma.lower())
                    continue
                word = ref.words[i].lower()
                counts[(word, pos)][lemma] += 1
                word_counts[word][lemma] += 1
        if self.mode == "lookup":
            self.table = {k: c.most_common(1)[0][0] for k, c in counts.items()}
            self.word_table = {
                w: c.most_common(1)[0][0] for w, c in word_counts.items()
            }

    def finish_labels(self) -> None:
        pass

    def lemmatize_rule(self, word: str, pos: str) -> str:
        """spaCy's rule-lemmatizer algorithm: exceptions first; a form
        already in the index IS a lemma; else apply suffix rules and keep
        the first rewrite the index validates, falling back to the first
        rewrite at all, else the form itself."""
        low = word.lower()
        exc = self.exceptions.get(pos, {})
        if low in exc:
            return exc[low]
        rules = self.rules.get(pos)
        if rules is None:  # POS with no rule table (PUNCT, PROPN, ...)
            return low
        index = self.index.get(pos, set())
        if low in index:
            return low
        first_rewrite: Optional[str] = None
        for suffix, repl in rules:
            if low.endswith(suffix) and len(low) > len(suffix):
                form = low[: -len(suffix)] + repl
                if form in index:
                    return form
                if first_rewrite is None:
                    first_rewrite = form
        return first_rewrite if first_rewrite is not None else low

    def lemmatize(self, word: str, pos: str = "") -> str:
        if self.mode == "rule":
            return self.lemmatize_rule(word, pos)
        low = word.lower()
        hit = self.table.get((low, pos)) or self.word_table.get(low)
        if hit:
            return hit
        for suffix, repl in _SUFFIX_RULES:
            if low.endswith(suffix) and len(low) > len(suffix) + 2:
                return low[: -len(suffix)] + repl
        return low

    # annotate directly (no device output)
    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        for doc in docs:
            pos_list = doc.pos or [""] * len(doc)
            doc.lemmas = [
                self.lemmatize(w, pos_list[i] if i < len(pos_list) else "")
                for i, w in enumerate(doc.words)
            ]

    def forward(self, params, inputs, ctx):
        return None  # host-side only

    def score(self, examples: List[Example]) -> Dict[str, float]:
        from ..scoring import score_token_acc

        # spaCy lemma_acc: exact (case-sensitive) match, missing gold
        # excluded, None when no gold lemmas exist anywhere
        return score_token_acc(examples, "lemma_acc", lambda d: d.lemmas)

    # ------------------------------------------------------------------
    # serialization: the tables must survive to_disk/from_disk
    def table_data(self) -> Dict[str, Any]:
        data: Dict[str, Any] = {
            "mode": self.mode,
            "table": [[w, p, l] for (w, p), l in self.table.items()],
            "word_table": self.word_table,
        }
        if self.mode == "rule":  # lookup models never consult these
            data["rules"] = self.rules
            data["exceptions"] = self.exceptions
            data["index"] = {p: sorted(v) for p, v in self.index.items()}
        return data

    def load_table_data(self, data: Dict[str, Any]) -> None:
        self.mode = data.get("mode", "lookup")
        self.table = {(w, p): l for w, p, l in data.get("table", [])}
        self.word_table = dict(data.get("word_table", {}))
        if "rules" in data:
            self.rules = {p: [list(r) for r in rs] for p, rs in data["rules"].items()}
        if "exceptions" in data:
            self.exceptions = {p: dict(t) for p, t in data["exceptions"].items()}
        if "index" in data:
            self.index = {p: set(v) for p, v in data["index"].items()}


@registry.factories("lemmatizer")
def make_lemmatizer(
    name: str,
    model: Optional[Dict[str, Any]] = None,
    mode: str = "lookup",
    tables_path: Optional[str] = None,
) -> LemmatizerComponent:
    return LemmatizerComponent(name, model, mode=mode, tables_path=tables_path)
