"""Lookup lemmatizer: host-side, trained from gold lemma counts.

Capability parity with spaCy's lookup-mode ``lemmatizer`` pipe (rule/lookup
host-side preprocessing — SURVEY.md §2.3 places Doc-level string work on the
host). No device compute: at initialize it builds (word, pos) -> lemma and
word -> lemma tables from the gold corpus by majority count; prediction is a
dictionary lookup with suffix-strip fallbacks. Score: ``lemma_acc``.
"""

from __future__ import annotations

from collections import Counter, defaultdict
from typing import Any, Dict, List, Optional, Tuple

from ...registry import registry
from ...pipeline.doc import Doc, Example
from .base import Component

_SUFFIX_RULES = [
    ("ies", "y"),
    ("sses", "ss"),
    ("ing", ""),
    ("ed", ""),
    ("s", ""),
]


class LemmatizerComponent(Component):
    trainable = False
    listens = False

    def __init__(self, name: str, model_cfg: Optional[Dict[str, Any]] = None, mode: str = "lookup"):
        super().__init__(name, model_cfg or {})
        self.mode = mode
        self.table: Dict[Tuple[str, str], str] = {}
        self.word_table: Dict[str, str] = {}

    # host-only: no model/params
    def build_model(self):
        self.model = None
        return None

    def init_params(self, rng):
        return {}

    def add_labels_from(self, examples) -> None:
        counts: Dict[Tuple[str, str], Counter] = defaultdict(Counter)
        word_counts: Dict[str, Counter] = defaultdict(Counter)
        for eg in examples:
            ref = eg.reference
            if not ref.lemmas:
                continue
            for i, lemma in enumerate(ref.lemmas):
                if not lemma:
                    continue
                word = ref.words[i].lower()
                pos = ref.pos[i] if ref.pos else ""
                counts[(word, pos)][lemma] += 1
                word_counts[word][lemma] += 1
        self.table = {k: c.most_common(1)[0][0] for k, c in counts.items()}
        self.word_table = {w: c.most_common(1)[0][0] for w, c in word_counts.items()}

    def finish_labels(self) -> None:
        pass

    def lemmatize(self, word: str, pos: str = "") -> str:
        low = word.lower()
        hit = self.table.get((low, pos)) or self.word_table.get(low)
        if hit:
            return hit
        for suffix, repl in _SUFFIX_RULES:
            if low.endswith(suffix) and len(low) > len(suffix) + 2:
                return low[: -len(suffix)] + repl
        return low

    # annotate directly (no device output)
    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        for doc in docs:
            pos_list = doc.pos or [""] * len(doc)
            doc.lemmas = [
                self.lemmatize(w, pos_list[i] if i < len(pos_list) else "")
                for i, w in enumerate(doc.words)
            ]

    def forward(self, params, inputs, ctx):
        return None  # host-side only

    def score(self, examples: List[Example]) -> Dict[str, float]:
        correct = total = 0
        for eg in examples:
            gold = eg.reference.lemmas
            pred = eg.predicted.lemmas
            if not gold or not pred:
                continue
            for g, p in zip(gold, pred):
                if not g:
                    continue
                total += 1
                correct += int(g.lower() == p.lower())
        return {"lemma_acc": correct / total if total else 0.0}

    # ------------------------------------------------------------------
    # serialization: the tables must survive to_disk/from_disk
    def table_data(self) -> Dict[str, Any]:
        return {
            "mode": self.mode,
            "table": [[w, p, l] for (w, p), l in self.table.items()],
            "word_table": self.word_table,
        }

    def load_table_data(self, data: Dict[str, Any]) -> None:
        self.mode = data.get("mode", "lookup")
        self.table = {(w, p): l for w, p, l in data.get("table", [])}
        self.word_table = dict(data.get("word_table", {}))


@registry.factories("lemmatizer")
def make_lemmatizer(
    name: str, model: Optional[Dict[str, Any]] = None, mode: str = "lookup"
) -> LemmatizerComponent:
    return LemmatizerComponent(name, model, mode=mode)
