"""``trainable_lemmatizer``: neural lemmatization over induced edit trees.

Capability parity with spaCy's ``trainable_lemmatizer`` (EditTreeLemmatizer;
part of the spaCy pipeline family the reference trains through its
config-driven loop). The split is the framework's standard one:

* HOST, at initialize: induce an edit tree per (form, lemma) pair —
  recursive longest-common-substring decomposition with substitution
  leaves, the same structure spaCy uses — and keep trees seen at least
  ``min_tree_freq`` times as the label set.
* DEVICE: a per-token classifier over tree labels (reuses the tagger's
  loss machinery — one Linear over the shared tok2vec, masked CE), so
  training is the same MXU-friendly batched classification as tagging.
* HOST, at decode: for each token try the top-``top_k`` scoring trees in
  order and apply the first one that matches the form (a tree is partial:
  substitution leaves must match their original string and length
  constraints must hold); fall back to the identity.

Score: ``lemma_acc`` (same key as the rule/lookup lemmatizer).
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from ...registry import registry
from ...pipeline.doc import Doc, Example
from .tagger import TaggerComponent

# An edit tree is nested tuples:
#   ("s", orig, subst)                      substitution leaf
#   ("m", pfx_len, sfx_len, left, right)    match node: the middle
#       (longest common substring) is kept verbatim; left transforms the
#       first pfx_len chars, right the last sfx_len chars (None = empty)
Tree = Union[Tuple, None]


def _lcs(a: str, b: str) -> Tuple[int, int, int]:
    """(start_a, start_b, length) of the longest common substring."""
    best = (0, 0, 0)
    if not a or not b:
        return best
    prev = [0] * (len(b) + 1)
    for i in range(1, len(a) + 1):
        cur = [0] * (len(b) + 1)
        for j in range(1, len(b) + 1):
            if a[i - 1] == b[j - 1]:
                cur[j] = prev[j - 1] + 1
                if cur[j] > best[2]:
                    best = (i - cur[j], j - cur[j], cur[j])
        prev = cur
    return best


def build_tree(form: str, lemma: str) -> Tree:
    """Induce the edit tree transforming ``form`` into ``lemma``."""
    if form == lemma:
        return None  # identity
    sa, sb, n = _lcs(form, lemma)
    if n == 0:
        return ("s", form, lemma)
    left = build_tree(form[:sa], lemma[:sb])
    right = build_tree(form[sa + n :], lemma[sb + n :])
    return ("m", sa, len(form) - sa - n, left, right)


def apply_tree(tree: Tree, form: str) -> Optional[str]:
    """Apply; None when the tree does not match the form."""
    if tree is None:
        return form
    if tree[0] == "s":
        return tree[2] if form == tree[1] else None
    _, pfx, sfx, left, right = tree
    if pfx + sfx > len(form):
        return None
    mid = form[pfx : len(form) - sfx] if sfx else form[pfx:]
    lp = apply_tree(left, form[:pfx])
    if lp is None:
        return None
    rp = apply_tree(right, form[len(form) - sfx :] if sfx else "")
    if rp is None:
        return None
    return lp + mid + rp


def tree_key(tree: Tree) -> str:
    return json.dumps(tree, separators=(",", ":"), ensure_ascii=False)


def tree_from_key(key: str) -> Tree:
    def tup(x):
        return tuple(tup(v) for v in x) if isinstance(x, list) else x

    return tup(json.loads(key))


class EditTreeLemmatizerComponent(TaggerComponent):

    default_score_weights = {"lemma_acc": 1.0}

    def __init__(
        self,
        name: str,
        model_cfg: Dict[str, Any],
        *,
        min_tree_freq: int = 3,
        top_k: int = 3,
        overwrite: bool = True,
    ):
        super().__init__(name, model_cfg)
        self.min_tree_freq = int(min_tree_freq)
        self.top_k = int(top_k)
        self.overwrite = bool(overwrite)

    # labels[0] is always the identity tree ("null"), the decode fallback
    def add_labels_from(self, examples) -> None:
        counts: Counter = Counter()
        for eg in examples:
            ref = eg.reference
            if not ref.lemmas:
                continue
            for i, lemma in enumerate(ref.lemmas):
                if not lemma:
                    continue
                counts[tree_key(build_tree(ref.words[i], lemma))] += 1
        ident = tree_key(None)
        kept = {k for k, c in counts.items() if c >= self.min_tree_freq}
        kept.discard(ident)
        self.labels = list(set(self.labels) | kept)

    def finish_labels(self) -> None:
        ident = tree_key(None)
        rest = sorted(l for l in self.labels if l != ident)
        self.labels = [ident] + rest

    @property
    def trees(self) -> List[Tree]:
        """Decoded trees, rebuilt whenever labels change — from_disk
        restores labels by plain assignment (language.py), so the decoded
        list derives lazily instead of trusting a hook to run."""
        if getattr(self, "_trees_for", None) is not self.labels:
            self._trees = [tree_from_key(k) for k in self.labels]
            self._trees_for = self.labels
        return self._trees

    def make_targets(self, examples: List[Example], B: int, T: int) -> Dict[str, np.ndarray]:
        label_ids = {label: i for i, label in enumerate(self.labels)}
        tags = np.zeros((B, T), dtype=np.int32)
        mask = np.zeros((B, T), dtype=bool)
        # per-Example cache, as in TaggerComponent.make_targets: examples
        # recur every epoch and tree induction is an O(|form|*|lemma|) DP
        # per token — induce once, key on the (fixed-after-init) label set
        cache_key = tuple(self.labels)
        for i, eg in enumerate(examples):
            ref = eg.reference
            if not ref.lemmas:
                continue
            cached = getattr(eg, "_etl_target_cache", None)
            if cached is None or cached[0] != cache_key:
                ids = np.zeros(len(ref.lemmas), dtype=np.int32)
                valid = np.zeros(len(ref.lemmas), dtype=bool)
                for j, lemma in enumerate(ref.lemmas):
                    if not lemma:
                        continue
                    tid = label_ids.get(tree_key(build_tree(ref.words[j], lemma)))
                    if tid is not None:
                        ids[j] = tid
                        valid[j] = True
                eg._etl_target_cache = cached = (cache_key, ids, valid)
            _, ids, valid = cached
            n = min(len(ids), T)
            tags[i, :n] = ids[:n]
            mask[i, :n] = valid[:n]
        return {"tags": tags, "tag_mask": mask}

    def set_annotations(self, docs: List[Doc], outputs, lengths: List[int]) -> None:
        logits = np.asarray(outputs.X, dtype=np.float32)  # [B, T, L]
        k = min(self.top_k, logits.shape[-1])
        # top-k per token, best first (argpartition then sort the slice)
        part = np.argpartition(-logits, k - 1, axis=-1)[..., :k]
        order = np.take_along_axis(logits, part, axis=-1).argsort(axis=-1)[..., ::-1]
        topk = np.take_along_axis(part, order, axis=-1)  # [B, T, k]
        for i, doc in enumerate(docs):
            if doc.lemmas and not self.overwrite:
                continue
            n = lengths[i]
            lemmas = []
            for j in range(n):
                form = doc.words[j]
                out = None
                for tid in topk[i, j]:
                    out = apply_tree(self.trees[tid], form)
                    if out:  # empty string = no-match (spaCy semantics)
                        break
                    out = None
                lemmas.append(out if out else form)
            lemmas += list(doc.words[n:])  # tokens beyond the padded length
            doc.lemmas = lemmas

    def score(self, examples: List[Example]) -> Dict[str, float]:
        from ..scoring import score_token_acc

        # spaCy lemma_acc semantics (exact match, None when unannotated)
        return score_token_acc(examples, "lemma_acc", lambda d: d.lemmas)


@registry.factories("trainable_lemmatizer")
def make_trainable_lemmatizer(
    name: str,
    model: Dict[str, Any],
    min_tree_freq: int = 3,
    top_k: int = 3,
    overwrite: bool = True,
) -> EditTreeLemmatizerComponent:
    return EditTreeLemmatizerComponent(
        name,
        model,
        min_tree_freq=min_tree_freq,
        top_k=top_k,
        overwrite=overwrite,
    )
