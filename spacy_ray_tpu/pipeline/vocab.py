"""Vocab + lexical featurization: strings → stable 64-bit hash keys.

Capability parity with spaCy's Vocab/StringStore (native murmurhash/preshed
C deps of the reference, SURVEY.md §2.3 rows "spaCy core" / "murmurhash").
Host-side: each token is mapped to its lexical attribute strings
(NORM, PREFIX, SUFFIX, SHAPE — the HashEmbed feature set), each attribute
string is murmur-hashed to a stable uint64 key, and the keys ship to device
as [T, n_attrs, 2]-uint32 arrays (device re-hashes per table:
ops/hashing.py). Uses the C++ native extension when built (native/), with a
pure-Python fallback.

Hash keys are content-derived and therefore identical on every host —
replacing the reference's per-process node-id param keys (reference
util.py:6,53-54) and its reliance on identical construction order
(SURVEY.md §2.4).
"""

from __future__ import annotations

import re
from functools import lru_cache
from typing import Dict, List, Sequence

import numpy as np

from ..models.tok2vec import ATTRS
from ..ops.hashing import hash_string_u64, split_u64

_DIGIT_RE = re.compile(r"\d")


def norm_of(word: str) -> str:
    return word.lower()


def prefix_of(word: str, n: int = 1) -> str:
    return word[:n]


def suffix_of(word: str, n: int = 3) -> str:
    return word[-n:]


@lru_cache(maxsize=2 ** 17)
def shape_of(word: str) -> str:
    """Word shape: 'Xxxx', 'dd', 'xx-xx' — capped run-length like spaCy."""
    out = []
    last = ""
    run = 0
    for ch in word:
        if ch.isalpha():
            sym = "X" if ch.isupper() else "x"
        elif ch.isdigit():
            sym = "d"
        else:
            sym = ch
        if sym == last:
            run += 1
            if run < 4:
                out.append(sym)
        else:
            out.append(sym)
            last = sym
            run = 1
    return "".join(out)


class StringStore:
    """Bidirectional string <-> uint64 hash map (host side)."""

    def __init__(self):
        self._map: Dict[int, str] = {}

    def add(self, s: str) -> int:
        key = hash_string_u64(s)
        self._map[key] = s
        return key

    def __getitem__(self, key: int) -> str:
        return self._map[key]

    def __contains__(self, key: int) -> bool:
        return key in self._map

    def __len__(self) -> int:
        return len(self._map)


class Vocab:
    """Featurizer with a per-token LRU cache.

    ``featurize(words) -> uint32 [T, n_attrs, 2]`` (lo, hi halves of the
    uint64 attribute-hash keys).
    """

    def __init__(self):
        self.strings = StringStore()
        self._cache: Dict[str, np.ndarray] = {}

    def token_features(self, word: str) -> np.ndarray:
        feats = self._cache.get(word)
        if feats is None:
            attrs = self._attr_strings(word)
            keys = np.array([hash_string_u64(a) for a in attrs], dtype=np.uint64)
            lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            hi = (keys >> np.uint64(32)).astype(np.uint32)
            feats = np.stack([lo, hi], axis=-1)  # [n_attrs, 2]
            if len(self._cache) < 2 ** 20:
                self._cache[word] = feats
        return feats

    @staticmethod
    def _attr_strings(word: str) -> List[str]:
        # Order must match models.tok2vec.ATTRS
        return [
            "norm=" + norm_of(word),
            "pre=" + prefix_of(word),
            "suf=" + suffix_of(word),
            "shape=" + shape_of(word),
        ]

    def featurize(self, words: Sequence[str]) -> np.ndarray:
        if not words:
            return np.zeros((0, len(ATTRS), 2), dtype=np.uint32)
        # batch-hash all uncached words through the native extension
        # (11x the pure-Python path; see native/)
        uncached = [w for w in set(words) if w not in self._cache]
        direct: Dict[str, np.ndarray] = {}
        if uncached:
            from ..native import hash_strings_u64

            attr_strings: List[str] = []
            for w in uncached:
                attr_strings.extend(self._attr_strings(w))
            keys = hash_strings_u64(attr_strings).reshape(len(uncached), len(ATTRS))
            feats_all = split_u64(keys)  # [n, n_attrs, 2]
            for i, w in enumerate(uncached):
                if len(self._cache) < 2 ** 20:
                    self._cache[w] = feats_all[i]
                else:  # cache full: serve this batch without caching
                    direct[w] = feats_all[i]
        return np.stack(
            [direct[w] if w in direct else self._cache[w] for w in words]
        )
