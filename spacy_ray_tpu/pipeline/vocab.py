"""Vocab + lexical featurization: strings → stable 64-bit hash keys.

Capability parity with spaCy's Vocab/StringStore (native murmurhash/preshed
C deps of the reference, SURVEY.md §2.3 rows "spaCy core" / "murmurhash").
Host-side: each token is mapped to its lexical attribute strings
(NORM, PREFIX, SUFFIX, SHAPE — the HashEmbed feature set), each attribute
string is murmur-hashed to a stable uint64 key, and the keys ship to device
as [T, n_attrs, 2]-uint32 arrays (device re-hashes per table:
ops/hashing.py). Uses the C++ native extension when built (native/), with a
pure-Python fallback.

Hash keys are content-derived and therefore identical on every host —
replacing the reference's per-process node-id param keys (reference
util.py:6,53-54) and its reliance on identical construction order
(SURVEY.md §2.4).
"""

from __future__ import annotations

import re
from functools import lru_cache
from typing import Dict, List, Sequence

import numpy as np

from ..models.tok2vec import ATTRS
from ..ops.hashing import hash_string_u64, split_u64

_DIGIT_RE = re.compile(r"\d")


def norm_of(word: str) -> str:
    return word.lower()


def prefix_of(word: str, n: int = 1) -> str:
    return word[:n]


def suffix_of(word: str, n: int = 3) -> str:
    return word[-n:]


@lru_cache(maxsize=2 ** 17)
def shape_of(word: str) -> str:
    """Word shape: 'Xxxx', 'dd', 'xx-xx' — capped run-length like spaCy."""
    out = []
    last = ""
    run = 0
    for ch in word:
        if ch.isalpha():
            sym = "X" if ch.isupper() else "x"
        elif ch.isdigit():
            sym = "d"
        else:
            sym = ch
        if sym == last:
            run += 1
            if run < 4:
                out.append(sym)
        else:
            out.append(sym)
            last = sym
            run = 1
    return "".join(out)


class StringStore:
    """Bidirectional string <-> uint64 hash map (host side)."""

    def __init__(self):
        self._map: Dict[int, str] = {}

    def add(self, s: str) -> int:
        key = hash_string_u64(s)
        self._map[key] = s
        return key

    def __getitem__(self, key: int) -> str:
        return self._map[key]

    def __contains__(self, key: int) -> bool:
        return key in self._map

    def __len__(self) -> int:
        return len(self._map)


class Vocab:
    """Featurizer with a bounded per-token cache.

    ``featurize(words) -> uint32 [T, n_attrs, 2]`` (lo, hi halves of the
    uint64 attribute-hash keys).

    Cached features live as rows of ONE contiguous array and the cache maps
    word -> row index, so a batch lookup is a single fancy-index gather —
    not an ``np.stack`` over thousands of tiny per-word arrays (the
    collation hot spot: this path runs once per token per batch and sits on
    the host side of the e2e words/sec rate).
    """

    CACHE_MAX = 2 ** 20  # rows (= 32 MB of uint32 features at 4 attrs)

    def __init__(self):
        import threading

        self.strings = StringStore()
        self._index: Dict[str, int] = {}
        self._rows = np.zeros((1024, len(ATTRS), 2), dtype=np.uint32)
        self._n_rows = 0
        # the prefetch producer and the eval path may featurize concurrently;
        # row-append is a compound read-modify-write and needs the lock.
        # The common all-cached path stays lock-free because of WRITE
        # ORDERING under the lock: a row's data is fully written into
        # `_rows` BEFORE its index is published in `_index` (and growth
        # rebinds `_rows` to a copy, never shrinking it), so any index a
        # lock-free reader can observe already has valid row data behind
        # it. Do not publish indices before their rows are written.
        self._append_lock = threading.Lock()

    def token_features(self, word: str) -> np.ndarray:
        return self.featurize([word])[0]

    @staticmethod
    def _attr_strings(word: str) -> List[str]:
        # Order must match models.tok2vec.ATTRS
        return [
            "norm=" + norm_of(word),
            "pre=" + prefix_of(word),
            "suf=" + suffix_of(word),
            "shape=" + shape_of(word),
        ]

    def _compute_feats(self, words: List[str]) -> np.ndarray:
        """Batch-hash through the native extension (11x the pure-Python
        path; see native/). [len(words), n_attrs, 2] uint32."""
        from ..native import hash_strings_u64

        attr_strings: List[str] = []
        for w in words:
            attr_strings.extend(self._attr_strings(w))
        keys = hash_strings_u64(attr_strings).reshape(len(words), len(ATTRS))
        return split_u64(keys)

    def _append_rows(self, feats: np.ndarray) -> int:
        k = feats.shape[0]
        while self._n_rows + k > self._rows.shape[0]:
            self._rows = np.concatenate([self._rows, np.zeros_like(self._rows)])
        start = self._n_rows
        self._rows[start : start + k] = feats
        self._n_rows = start + k
        return start

    def featurize(self, words: Sequence[str]) -> np.ndarray:
        n = len(words)
        if not n:
            return np.zeros((0, len(ATTRS), 2), dtype=np.uint32)
        index = self._index
        idx = np.empty(n, dtype=np.intp)
        missing_pos: List[int] = []
        for i, w in enumerate(words):
            j = index.get(w)
            if j is None:
                missing_pos.append(i)
                idx[i] = 0  # patched below
            else:
                idx[i] = j
        overflow: Dict[str, np.ndarray] = {}
        if missing_pos:
            with self._append_lock:
                # another thread may have cached some of these meanwhile
                uniq = list(
                    dict.fromkeys(
                        words[i] for i in missing_pos if words[i] not in index
                    )
                )
                if uniq:
                    feats_all = self._compute_feats(uniq)
                    room = max(self.CACHE_MAX - self._n_rows, 0)
                    if room:
                        start = self._append_rows(feats_all[:room])
                        for k, w in enumerate(uniq[:room]):
                            index[w] = start + k
                    for k in range(room, len(uniq)):  # cache full (rare)
                        overflow[uniq[k]] = feats_all[k]
            for i in missing_pos:
                j = index.get(words[i])
                idx[i] = j if j is not None else 0
        result = self._rows[idx]
        if overflow:
            for i in missing_pos:
                feats = overflow.get(words[i])
                if feats is not None:
                    result[i] = feats
        return result
