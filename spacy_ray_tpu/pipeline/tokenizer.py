"""Rule-based word tokenizer (host side).

Capability parity with spaCy's native tokenizer (Cython, SURVEY.md §2.3 row
"spaCy core"): splits raw text into Doc tokens. Training corpora are usually
pre-tokenized (the reference's data flow converts jsonl with `spacy convert`,
reference bin/get-data.sh:1-13), so this is the inference-path entry point.
Registered in the ``tokenizers`` registry so configs can swap it.
"""

from __future__ import annotations

import re
from typing import List

from ..registry import registry
from .doc import Doc

# token = word chars (incl. unicode letters/digits/apostrophes-in-word) | single punct
_TOKEN_RE = re.compile(
    r"""
    \d+(?:[.,]\d+)*          # numbers, incl. 1,000.5
  | \w+(?:[''’]\w+)*         # words with internal apostrophes
  | [^\w\s]                  # any single punctuation mark
    """,
    re.VERBOSE | re.UNICODE,
)

_SUFFIXES = ("'s", "'S", "’s", "’S", "n't", "N'T", "'ll", "'re", "'ve", "'m", "'d")


class Tokenizer:
    def __init__(self):
        pass

    def __call__(self, text: str) -> Doc:
        words: List[str] = []
        spaces: List[bool] = []
        for m in _TOKEN_RE.finditer(text):
            token = m.group(0)
            end = m.end()
            # split common English clitics off word tokens
            pieces = self._split_clitics(token)
            for i, piece in enumerate(pieces):
                words.append(piece)
                if i < len(pieces) - 1:
                    spaces.append(False)
                else:
                    spaces.append(end < len(text) and text[end : end + 1].isspace())
        return Doc(words=words, spaces=spaces)

    @staticmethod
    def _split_clitics(token: str) -> List[str]:
        for suf in _SUFFIXES:
            if len(token) > len(suf) and token.endswith(suf):
                return [token[: -len(suf)], token[-len(suf) :]]
        return [token]


@registry.tokenizers("spacy.Tokenizer.v1")
def create_tokenizer() -> Tokenizer:
    return Tokenizer()
