"""Rule-based word tokenizer (host side), spaCy-architecture.

Capability parity with spaCy's native tokenizer (Cython, SURVEY.md §2.3 row
"spaCy core"). Same algorithm shape as spacy/tokenizer.pyx:

1. split the text on whitespace into chunks;
2. per chunk, repeatedly: exact-match special cases (tokenizer exceptions:
   contractions, abbreviations), then ``token_match`` (URLs, emails,
   numbers — kept whole), then strip one PREFIX, then one SUFFIX, and
   finally split the remainder on INFIXES.

Rules are data (regex fragments + an exceptions dict), overridable via the
constructor, so languages/domains can re-rule it the way spaCy's per-
language ``TOKENIZER_PREFIXES``/``_SUFFIXES``/``_INFIXES`` do. Training
corpora are usually pre-tokenized (the reference converts with `spacy
convert`, reference bin/get-data.sh:1-13); this is the inference-path entry
point (``nlp("...")`` / ``nlp.pipe``). Registered in the ``tokenizers``
registry so configs can swap it.

Invariant: token texts concatenate exactly to the chunk text (exceptions
must preserve spelling, e.g. "don't" -> ["do", "n't"]), so ``spaces``
always reconstructs the original text.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence

from ..registry import registry
from .doc import Doc

_QUOTES = "\"'``''‘’“”«»„"
_OPENERS = r"\(\[\{<"
_CLOSERS = r"\)\]\}>"
_CURRENCY = "$£€¥₹₩"

DEFAULT_PREFIXES: Sequence[str] = (
    rf"[{_OPENERS}]",
    rf"[{re.escape(_QUOTES)}]",
    rf"[{re.escape(_CURRENCY)}]",
    r"[§#@&*]",
    r"\.\.\.|…",
    r"[-–—]",
)

_CLITICS = (
    r"(?:['’]s|['’]S|n['’]t|N['’]T"
    r"|['’]ll|['’]re|['’]ve|['’]m|['’]d"
    r"|['’]LL|['’]RE|['’]VE|['’]M|['’]D)"
)

DEFAULT_SUFFIXES: Sequence[str] = (
    rf"[{_CLOSERS}]",
    rf"[{re.escape(_QUOTES)}]",
    rf"[{re.escape(_CURRENCY)}]",         # 50€
    r"\.\.\.|…",
    r"[.,!?:;%°]",
    r"[-–—]",
    _CLITICS,
)

DEFAULT_INFIXES: Sequence[str] = (
    r"\.\.\.|…",
    r"--+|[–—]",
    r"[\(\)\[\]\{\}<>]",                  # mid-chunk brackets: foo(bar)
    r"(?<=[a-zA-Z])[-](?=[a-zA-Z])",      # well-known -> well - known
    r"(?<=\w)[,;:!?](?=\w)",              # missing space after punctuation
    r"(?<=[a-z0-9])\.(?=[A-Z])",          # sentence glue: end.Next
    r"(?<=[a-zA-Z])[/](?=[a-zA-Z])",      # either/or
    # symbol glue: price=5, x^2, a|b — deliberately NOT & or + or *, which
    # live inside real tokens (AT&T, R&D, 1e+5, C*-algebra)
    r"(?<=\w)[=~^|](?=\w)",
)

# kept whole regardless of punctuation inside (spaCy's token_match/url_match).
# URLs must not end in terminal punctuation, so "see https://x.io/a," still
# sheds the comma via the suffix rule before the URL matches on recursion.
DEFAULT_TOKEN_MATCH = (
    r"^(?:https?://|www\.)\S*[^\s.,!?;:'\"\)\]\}]$"  # URLs
    r"|^[\w.+-]+@[\w-]+(?:\.[\w-]+)+$"     # emails
    r"|^\d+(?:[.,]\d+)*$"                  # numbers incl. 1,000.5
    r"|^(?:[A-Za-z]\.){2,}$"               # U.S., e.g., i.e.
)


def _english_exceptions() -> Dict[str, List[str]]:
    """Contractions + abbreviations; pieces must concatenate to the key."""
    exc: Dict[str, List[str]] = {}
    # irregular contractions (spelling changes across the split point)
    for base, pieces in {
        "can't": ["ca", "n't"], "won't": ["wo", "n't"], "shan't": ["sha", "n't"],
        "cannot": ["can", "not"], "gonna": ["gon", "na"], "gotta": ["got", "ta"],
        "lemme": ["lem", "me"], "wanna": ["wan", "na"], "'cause": ["'cause"],
    }.items():
        exc[base] = pieces
        exc[base.capitalize()] = [pieces[0].capitalize()] + pieces[1:]
    # abbreviations that end in '.' (must not lose the period to suffixing)
    for abbr in (
        "etc.", "vs.", "v.s.", "Mr.", "Mrs.", "Ms.", "Dr.", "Prof.", "St.",
        "Ave.", "Inc.", "Ltd.", "Co.", "Corp.", "No.", "approx.", "est.",
        "a.m.", "p.m.", "Jan.", "Feb.", "Mar.", "Apr.", "Jun.", "Jul.",
        "Aug.", "Sep.", "Sept.", "Oct.", "Nov.", "Dec.",
    ):
        exc[abbr] = [abbr]
    return exc


class Tokenizer:
    def __init__(
        self,
        exceptions: Optional[Dict[str, List[str]]] = None,
        prefixes: Optional[Sequence[str]] = None,
        suffixes: Optional[Sequence[str]] = None,
        infixes: Optional[Sequence[str]] = None,
        token_match: Optional[str] = None,
    ):
        self.exceptions = dict(
            exceptions if exceptions is not None else _english_exceptions()
        )
        for key, pieces in self.exceptions.items():
            if "".join(pieces) != key:
                raise ValueError(
                    f"tokenizer exception {key!r} pieces {pieces} do not "
                    "concatenate to the key (would break text alignment)"
                )
        self._prefix_re = re.compile(
            "|".join(prefixes if prefixes is not None else DEFAULT_PREFIXES)
        )
        suf = suffixes if suffixes is not None else DEFAULT_SUFFIXES
        self._suffix_re = re.compile("(?:" + "|".join(suf) + ")$")
        self._infix_re = re.compile(
            "|".join(infixes if infixes is not None else DEFAULT_INFIXES)
        )
        self._token_match_re = re.compile(
            token_match if token_match is not None else DEFAULT_TOKEN_MATCH
        )

    # ------------------------------------------------------------------
    def __call__(self, text: str) -> Doc:
        words: List[str] = []
        spaces: List[bool] = []
        for m in re.finditer(r"\S+", text):
            chunk = m.group(0)
            end = m.end()
            pieces = self._tokenize_chunk(chunk)
            for i, piece in enumerate(pieces):
                words.append(piece)
                spaces.append(
                    (end < len(text)) if i == len(pieces) - 1 else False
                )
        return Doc(words=words, spaces=spaces)

    # ------------------------------------------------------------------
    def _tokenize_chunk(self, chunk: str, depth: int = 0) -> List[str]:
        if not chunk:
            return []
        if depth > 2 * len(chunk) + 8:  # defensive: rules must consume chars
            return [chunk]
        if chunk in self.exceptions:
            return list(self.exceptions[chunk])
        if self._token_match_re.match(chunk):
            return [chunk]
        m = self._prefix_re.match(chunk)
        if m and 0 < m.end() < len(chunk):
            return [m.group(0)] + self._tokenize_chunk(chunk[m.end():], depth + 1)
        if m and m.end() == len(chunk):
            return [chunk]  # the whole chunk is one prefix-class token
        m = self._suffix_re.search(chunk)
        if m and 0 < m.start() < len(chunk):
            return self._tokenize_chunk(chunk[: m.start()], depth + 1) + [m.group(0)]
        if m and m.start() == 0:
            return [chunk]
        pieces: List[tuple] = []  # (text, is_infix_token)
        pos = 0
        for im in self._infix_re.finditer(chunk):
            if im.start() == 0 or im.end() == im.start():
                continue
            if im.start() > pos:
                pieces.append((chunk[pos : im.start()], False))
            pieces.append((im.group(0), True))
            pos = im.end()
        if pos == 0:
            return [chunk]
        if pos < len(chunk):
            pieces.append((chunk[pos:], False))
        # re-tokenize the non-infix pieces fully: "it's,fine" must split the
        # clitic in "it's" exactly as it would with a space after it
        out: List[str] = []
        for piece, is_infix in pieces:
            if is_infix:
                out.append(piece)
            else:
                out.extend(self._tokenize_chunk(piece, depth + 1))
        return out


@registry.tokenizers("spacy.Tokenizer.v1")
def create_tokenizer() -> Tokenizer:
    return Tokenizer()
