"""Pipeline parallelism: GPipe-style SPMD schedule over the ``pipe`` axis.

Absent from the reference (SURVEY.md §2.2 row PP: "NO"); here it is a
first-class mesh axis for deep trunks whose layer stack exceeds one
device's HBM. TPU-idiomatic formulation — no per-stage processes, no
send/recv runtime: ALL devices run the same compiled program
(``shard_map``), each holding ``depth/S`` of the stacked layer parameters
(leading dim sharded over ``pipe``), and activations hop stage→stage+1
via ``lax.ppermute`` over ICI inside a ``lax.scan`` of ``M + S - 1``
ticks for M microbatches:

    tick t: stage s processes microbatch (t - s); stage 0 feeds microbatch
    t in; stage S-1 writes microbatch (t - S + 1) out.

The bubble fraction is (S-1)/(M+S-1) — pick M >= S. Everything is
differentiable (ppermute/psum transpose), so the same schedule runs the
backward pass in reverse. Composes with the ``data`` axis and — on jax
with partial-manual shard_map (``axis_names``) — with the ``model`` axis
(the stage body stays automatic over data/model, so TP sharding
constraints inside the layers apply) AND with the ``context`` axis: ring
attention nests inside the stage body as a second partial-manual region,
manual over ``context`` only (parallel/ring_attention.py). Older jax
without ``axis_names`` falls back to a fully manual region with
constraints disabled (pipe x data only, no context).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp

from jax.sharding import PartitionSpec as P

from . import context as pctx

# partial-manual shard_map (manual over `pipe` only, other axes stay
# automatic) lets sharding constraints inside the stage body keep working,
# so PP composes with tensor parallelism — and with ring attention's
# nested `context` region (smap.py holds the shared capability probe)
from .smap import CHECK_KW as _CHECK_KW, PARTIAL_MANUAL, shard_map

AXIS = "pipe"


def spmd_pipeline(
    stage_fn: Callable,
    stacked_params: Any,
    microbatches: jnp.ndarray,
    masks: jnp.ndarray,
    rng: jax.Array,
) -> "tuple[jnp.ndarray, jnp.ndarray]":
    """Run the pipelined layer stack.

    stage_fn(local_params, x, mask, rng) -> (y, aux) applies ONE STAGE's
    layers to one microbatch (local_params leaves have leading dim
    depth/S); aux is a scalar auxiliary loss for that stage+microbatch
    (e.g. the MoE router's load-balancing term; 0.0 when unused).

    stacked_params: pytree, leaves [depth, ...] (sharded over 'pipe' here).
    microbatches:   [M, mb, T, D] activations (embedding+positions done).
    masks:          [M, mb, T].
    Returns ([M, mb, T, D] replicated over the pipe axis, aux) where aux
    is the MEAN over microbatches of the per-microbatch aux sums across
    all stages. Drain ticks (a stage holding stale data) are masked out
    of the accumulation. NOTE: for a nonlinear aux (the MoE router's
    load-balance term) mean-of-per-microbatch values is the standard
    pipelined formulation (Switch/GShard practice) but is NOT numerically
    identical to the dense loop's full-batch aux — activations ARE
    dense-equal, the regularizer differs at O(1/M).
    """
    mesh = pctx.current_mesh()
    assert mesh is not None and AXIS in mesh.shape, "spmd_pipeline needs a pipe axis"
    S = int(mesh.shape[AXIS])
    M = int(microbatches.shape[0])
    param_spec = P(AXIS)  # leading (stacked-depth) dim -> stages

    if PARTIAL_MANUAL:
        # manual over `pipe` only: activations keep their global (auto)
        # batch semantics, so data/model constraints inside stage_fn apply
        x_spec = P()
        mask_spec = P()
        aux_spec = P()  # aux is global under automatic data semantics
        sm_kwargs: dict = {"axis_names": frozenset({AXIS})}
    else:  # older jax: fully manual fallback
        data = "data" if "data" in mesh.shape and mesh.shape["data"] > 1 else None
        x_spec = P(None, data, None, None)  # [M, mb/data, T, D]
        mask_spec = P(None, data, None)
        # the region returns aux as a [1] vector (a bare scalar cannot be
        # concatenated across shards); each data shard contributes its own
        # value, averaged outside — standard data-parallel aggregation of
        # the (already approximate, see docstring) pipelined aux
        aux_spec = P(data)
        sm_kwargs = {}

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(param_spec, x_spec, mask_spec, P()),
        out_specs=(x_spec, aux_spec),
        **{_CHECK_KW: False},
        **sm_kwargs,
    )
    def run(local_params, xs, ms, key):
        stage = jax.lax.axis_index(AXIS)
        state = jnp.zeros_like(xs[0])
        outputs = jnp.zeros_like(xs)
        aux_acc = jnp.float32(0.0)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def body(carry, t):
            state, outputs, aux_acc = carry
            # stage 0 ingests microbatch t (clipped: harmless compute on
            # stale data during drain ticks, results never written)
            feed = xs[jnp.clip(t, 0, M - 1)]
            x = jnp.where(stage == 0, feed, state)
            # the microbatch THIS stage processes at tick t is (t - stage)
            mb_idx = t - stage
            mask = ms[jnp.clip(mb_idx, 0, M - 1)]
            y, aux = stage_fn(local_params, x, mask, jax.random.fold_in(key, t))
            # drain ticks run on stale data: their aux must not count
            valid = (mb_idx >= 0) & (mb_idx < M)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
            out_idx = t - (S - 1)
            write = (stage == S - 1) & (out_idx >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outputs, y, jnp.clip(out_idx, 0, M - 1), 0
            )
            outputs = jnp.where(write, updated, outputs)
            state = jax.lax.ppermute(y, AXIS, perm)
            return (state, outputs, aux_acc), None

        (state, outputs, aux_acc), _ = jax.lax.scan(
            body, (state, outputs, aux_acc), jnp.arange(M + S - 1)
        )
        # finished microbatches live on the last stage; broadcast so the
        # (pipe-replicated) heads downstream see them everywhere
        outputs = jax.lax.psum(
            jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)), AXIS
        )
        # every stage contributed its own layers' aux: sum over the ring,
        # mean over microbatches (the dense loop computes each layer's aux
        # once over the full batch)
        aux_total = jax.lax.psum(aux_acc, AXIS) / jnp.float32(M)
        return outputs, aux_total.reshape(1)

    outputs, aux_vec = run(stacked_params, microbatches, masks, rng)
    # [1] under partial-manual (global aux); [n_data] under the fully
    # manual fallback (one value per data shard) — mean restores a scalar
    return outputs, jnp.mean(aux_vec)
