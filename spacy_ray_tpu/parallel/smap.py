"""Shared shard_map import + capability probe.

One place answers "which shard_map does this jax have, and does it support
partial-manual regions?" so the pipeline (`pipe` axis) and ring attention
(`context` axis) can't drift apart on the answer — PP x CP works only when
BOTH regions can be partial-manual (nested), and both modules gate on the
same flag.
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map  # jax >= 0.7 (replication check kwarg: check_vma)

    CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore[no-redef]

    CHECK_KW = "check_rep"

# partial-manual shard_map: manual over only the axes named in
# ``axis_names``, every other mesh axis stays automatic (GSPMD) — the
# mechanism that lets sharding constraints keep working inside a manual
# region and lets manual regions nest over disjoint axis sets
PARTIAL_MANUAL = "axis_names" in inspect.signature(shard_map).parameters

__all__ = ["shard_map", "CHECK_KW", "PARTIAL_MANUAL"]
