"""Active-mesh context: lets model code apply TP/CP sharding constraints.

The reference has no tensor/sequence parallelism at all (SURVEY.md §2.2
rows TP/SP: "NO"); here they are first-class mesh axes. Model code can't
take a mesh argument through the generic Model.apply signature, so the
train-step builder installs the mesh here and layers consult it:

* ``tp_active()``  — "model" axis > 1: shard attention heads + FFN dim
* ``context_parallel_active()`` — "context" axis > 1: ring attention +
  sequence-dim sharding
"""

from __future__ import annotations

import contextvars
from contextlib import contextmanager
from typing import Iterator, Optional

from jax.sharding import Mesh

# context-local (not process-global): concurrent traces over different
# meshes must not see each other's mesh
_MESH: "contextvars.ContextVar[Optional[Mesh]]" = contextvars.ContextVar(
    "spacy_ray_tpu_mesh", default=None
)


def current_mesh() -> Optional[Mesh]:
    return _MESH.get()


@contextmanager
def use_mesh(mesh: Optional[Mesh]) -> Iterator[None]:
    token = _MESH.set(mesh)
    try:
        yield
    finally:
        _MESH.reset(token)


def _axis_size(name: str) -> int:
    mesh = _MESH.get()
    if mesh is None:
        return 1
    return int(mesh.shape.get(name, 1))


def tp_active() -> bool:
    return _axis_size("model") > 1


def context_parallel_active() -> bool:
    return _axis_size("context") > 1


def pipeline_active() -> bool:
    return _axis_size("pipe") > 1
