"""The sharded train step: one compiled XLA program per (B, T) bucket.

This single function replaces the reference's entire L3/L4 communication
machinery (SURVEY.md §1): forward, backward, gradient all-reduce over ICI,
optimizer update, and a sharded update phase — where the reference does
per-parameter RPC push/broadcast with version gates and quorums (reference
proxies.py:54-133, worker.py:117-132), here GSPMD insert collectives from
sharding annotations and the whole exchange compiles into the step
(SURVEY.md §2.2: "synchronous allreduce is strictly better on TPU ICI").

Update-phase sharding (``[training] update_sharding``, subsuming the old
``zero1`` bool):

* ``"replicated"`` — every replica holds the full optimizer state and
  applies the full update (the original layout).
* ``"zero1"`` — optimizer STATE is sharded over the data axis
  (:func:`~..mesh.zero1_spec`); where the update math runs is left to
  GSPMD's placement inference.
* ``"full"`` — the update COMPUTATION itself is sharded (arXiv
  2004.13336 "Automatic Cross-Replica Sharding of Weight Update in
  Data-Parallel Training", the TPU-native completion of the reference's
  owner-applies-the-update scheme): each replica applies the optimizer
  chain only to its owned param shard and the updated params are
  allgathered back to the replicated data-parallel layout. Bit-exactness
  with ``"replicated"`` is engineered, not hoped for: the all-reduced
  gradients are pinned replicated behind an ``optimization_barrier``
  (XLA must not rewrite the all-reduce into a reduce-scatter, whose
  different accumulation order changes last-ulp values) so any global
  reduction inside the optimizer (grad-clip global norm) sees the same
  full arrays in the same order, and everything downstream is elementwise
  — identical per element whether computed on a shard or the whole leaf.
  tests/test_update_sharding.py asserts full == replicated to EQUALITY,
  the same discipline as the fused==optax tests.

Gradient accumulation: the reference folds ``accumulate_gradient`` into its
distributed quorum (reference worker.py:151-155,182 — with the dead-code bug
noted in SURVEY.md §2.4); here it is an explicit ``lax.scan`` over stacked
microbatches, numerically equivalent to a quorum of exactly
``num_workers × accumulate_gradient`` with zero staleness.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import context as pctx
from .mesh import replicated, zero1_spec

# the full [training] update_sharding knob surface; "auto" resolves via
# resolve_update_sharding before any of the functions below see it
UPDATE_SHARDING_MODES = ("auto", "replicated", "zero1", "full")


def resolve_update_sharding(
    mode: str,
    *,
    zero1: bool = False,
    n_data: int = 1,
    backend: Optional[str] = None,
) -> str:
    """Resolve the ``[training] update_sharding`` knob to a concrete mode.

    ``zero1`` is the legacy bool knob, kept as an accepted alias:
    ``zero1 = true`` under ``update_sharding = "auto"`` resolves to
    ``"zero1"`` (existing configs keep their exact behavior). An explicit
    non-auto ``update_sharding`` wins over the alias. ``"auto"`` without
    the alias arms ``"full"`` on accelerator backends with more than one
    data rank — the same platform-gating discipline as ``fused_update`` /
    ``bf16_shadow`` (PERF.md round 7: CPU measures the mega-rewrites at
    parity-to-worse; accelerators are where the bandwidth/compute ratios
    pay) — and stays ``"replicated"`` on CPU or single-replica meshes.
    """
    if mode not in UPDATE_SHARDING_MODES:
        raise ValueError(
            f"update_sharding must be one of {UPDATE_SHARDING_MODES}, "
            f"got {mode!r}"
        )
    if mode != "auto":
        return mode
    if zero1:
        return "zero1"
    if backend is None:
        backend = jax.default_backend()
    if backend != "cpu" and n_data > 1:
        return "full"
    return "replicated"


def update_sharding_status(mode: str, mesh: Optional[Mesh] = None) -> str:
    """Honest-labeling string for bench records / ``info --probe``: what
    the update phase ACTUALLY does, the same discipline as
    ``fused_update``'s label — a single-replica mesh must not masquerade
    as a sharded update."""
    n_data = int(mesh.shape["data"]) if mesh is not None else 1
    if mode == "replicated" or n_data <= 1:
        degenerate = mode != "replicated" and n_data <= 1
        return "replicated" + (
            f" ({mode} degenerates: 1 data rank)" if degenerate else ""
        )
    if mode == "zero1":
        return f"zero1 (state sharded {n_data}-way, apply placement free)"
    return (
        f"full (state + apply sharded {n_data}-way, params allgathered)"
    )


def _mode_of(zero1_or_mode: Any) -> str:
    """Accept the legacy bool OR a resolved mode string."""
    if isinstance(zero1_or_mode, str):
        if zero1_or_mode == "auto":
            raise ValueError(
                "update_sharding 'auto' must be resolved before use "
                "(resolve_update_sharding)"
            )
        if zero1_or_mode not in UPDATE_SHARDING_MODES:
            raise ValueError(
                f"unknown update_sharding mode {zero1_or_mode!r}"
            )
        return zero1_or_mode
    return "zero1" if zero1_or_mode else "replicated"


def _constrain_owner_shards(tree: Any, mesh: Mesh) -> Any:
    """with_sharding_constraint every leaf to its owner-shard spec."""
    return jax.tree_util.tree_map(
        lambda x: jax.lax.with_sharding_constraint(x, zero1_spec(x, mesh)),
        tree,
    )


def _constrain_replicated(tree: Any, mesh: Mesh) -> Any:
    repl_sh = replicated(mesh)
    return jax.tree_util.tree_map(
        lambda x: jax.lax.with_sharding_constraint(x, repl_sh), tree
    )


def shard_opt_state(opt_state: Any, mesh: Mesh, zero1: Any) -> Any:
    """Place optimizer state per mode (bool = legacy ZeRO-1 alias):
    sharded over the data axis for ``"zero1"``/``"full"``, replicated
    otherwise. Input leaves may be host arrays from ANY saved mesh shape
    (the checkpoint's canonical unsharded layout) — placement here is
    what re-shards a resumed state to the CURRENT mesh."""
    if _mode_of(zero1) == "replicated":
        return jax.device_put(opt_state, replicated(mesh))
    return jax.tree_util.tree_map(
        lambda leaf: jax.device_put(leaf, zero1_spec(leaf, mesh)), opt_state
    )


def opt_state_shardings(opt_state: Any, mesh: Mesh, zero1: Any) -> Any:
    if _mode_of(zero1) == "replicated":
        return jax.tree_util.tree_map(lambda _: replicated(mesh), opt_state)
    return jax.tree_util.tree_map(lambda leaf: zero1_spec(leaf, mesh), opt_state)


def overlay_shadow(params: Any, shadow: Any) -> Any:
    """Overlay a (sub-structure) shadow tree onto params: positions present
    in ``shadow`` are taken from it (bf16 copies of the trunk's matmul
    weights — models/transformer.py build_param_shadow), the rest from
    ``params``. The forward then consumes the shadow leaves directly, so
    the layer stack's per-step ``astype(compute_dtype)`` is a no-op."""
    if not isinstance(shadow, dict):
        return shadow
    out = dict(params)
    for k, v in shadow.items():
        out[k] = overlay_shadow(params[k], v)
    return out


def refresh_shadow(new_params: Any, shadow: Any) -> Any:
    """Re-derive the shadow from freshly updated master params — ONE cast
    per shadowed leaf, fused into the same jitted update (the donated old
    shadow buffer is reused; no second host-visible traversal)."""
    if not isinstance(shadow, dict):
        return new_params.astype(shadow.dtype)
    return {k: refresh_shadow(new_params[k], v) for k, v in shadow.items()}


def _cast_like(tree: Any, like: Any) -> Any:
    """Cast shadow-leaf cotangents (bf16) back to the master dtype. The
    VALUES match the cast-per-step path up to one bf16 rounding: the
    baseline program's backward may elide the f32->bf16->f32 double
    rounding inside the weight-grad matmul, so the two trajectories agree
    to ~1e-8/step rather than bitwise (forward IS bit-exact — asserted by
    tests/test_fused_update.py)."""
    return jax.tree_util.tree_map(
        lambda x, ref: x.astype(ref.dtype), tree, like
    )


def make_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    accumulate_gradient: int = 1,
    zero1: Any = False,
    update_sharding: Optional[str] = None,
    opt_state_template: Any = None,
    donate: bool = True,
    shadow: bool = False,
    multi_dispatch: bool = False,
) -> Callable:
    """Build the jitted sharded update.

    loss_fn(params, tokens, targets, rng) -> (loss, metrics).

    Returns update(params, opt_state, tokens, targets, rng) ->
    (params, opt_state, loss, metrics). When accumulate_gradient > 1,
    tokens/targets leaves carry a leading [A] microbatch dim and the batch
    dim is sharded at position 1; otherwise position 0.

    ``shadow=True``: the update takes (params, opt_state, shadow, tokens,
    targets, rng) and returns (params, opt_state, shadow, loss, metrics).
    The forward runs on ``overlay_shadow(params, shadow)`` (bf16 trunk
    weights read directly — no per-step cast), gradients are cast back to
    the master dtype before accumulation/optimizer, and the shadow is
    refreshed from the new params inside the same program (all three
    state arguments donated).

    ``multi_dispatch=True``: tokens/targets leaves carry a leading [K]
    per-dispatch dim; the update runs K full train steps as one
    ``lax.scan`` (ONE host round-trip) and returns (params, opt_state,
    [shadow,] rng, losses[K], metrics[K]) — ``rng`` is carried through
    the scan with the same ``jax.random.split`` chain the host performs
    at K=1, so K steps are bit-identical to K single dispatches. K is
    read from the input shape: each distinct K compiles once.

    ``update_sharding``: a RESOLVED mode ("replicated" | "zero1" |
    "full"); when None the legacy ``zero1`` bool decides. "full" shards
    the optimizer apply itself across the data axis and allgathers the
    updated params (module docstring) — with ``shadow=True`` the bf16
    shadow is refreshed SHARD-LOCAL from the still-sharded new params
    before its own allgather, so the refresh cast costs 1/n_data of the
    work and the gather moves bf16 bytes.
    """
    accum = max(int(accumulate_gradient), 1)
    mode = _mode_of(update_sharding if update_sharding is not None else zero1)
    # a 1-rank data axis makes every owner-shard spec replicated: skip the
    # constraint/barrier scaffolding entirely (bit-identical either way)
    multi_replica = int(mesh.shape["data"]) > 1
    full_sharded = mode == "full" and multi_replica
    # Gradients are pinned fully replicated behind an optimization_barrier
    # in BOTH "replicated" and "full" modes: the two programs then share an
    # identical region up to the barrier (same all-reduce, same
    # accumulation order), which is what makes full == replicated hold to
    # EQUALITY rather than tolerance. "zero1" deliberately keeps its
    # pre-knob unpinned program byte-for-byte (GSPMD placement freedom —
    # it was never bit-compared against replicated, only rtol-tested).
    pin_grads = multi_replica and mode in ("replicated", "full")

    def _to_owner_shards(tree):
        return _constrain_owner_shards(tree, mesh)

    def _to_replicated(tree):
        return _constrain_replicated(tree, mesh)

    def grads_of(params, tokens, targets, rng):
        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, tokens, targets, rng
        )
        return loss, metrics, grads

    applies_updates = bool(getattr(tx, "applies_updates", False))

    def step_once(params, opt_state, shadow_t, tokens, targets, rng):
        fwd_params = (
            overlay_shadow(params, shadow_t) if shadow_t is not None else params
        )
        if accum == 1:
            loss, metrics, grads = grads_of(fwd_params, tokens, targets, rng)
            if shadow_t is not None:
                # bf16 cotangents at shadow leaves -> f32 master grads (the
                # same values the cast-per-step path produces via the
                # cast's transpose)
                grads = _cast_like(grads, params)
        else:
            def body(carry, micro):
                acc_grads, rng = carry
                rng, sub = jax.random.split(rng)
                m_tokens, m_targets = micro
                loss, metrics, grads = grads_of(fwd_params, m_tokens, m_targets, sub)
                if shadow_t is not None:
                    grads = _cast_like(grads, acc_grads)
                acc_grads = jax.tree_util.tree_map(jnp.add, acc_grads, grads)
                return (acc_grads, rng), (loss, metrics)

            zero_grads = jax.tree_util.tree_map(jnp.zeros_like, params)
            (grads, _), (losses, metricses) = jax.lax.scan(
                body, (zero_grads, rng), (tokens, targets)
            )
            grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
            loss = jnp.mean(losses)
            metrics = jax.tree_util.tree_map(jnp.mean, metricses)
        if pin_grads:
            # pin the all-reduced grads REPLICATED and fence them: XLA must
            # not rewrite the gradient all-reduce into a reduce-scatter
            # (a different accumulation order drifts last-ulp values), and
            # any global reduction inside the optimizer (grad-clip norm)
            # then sees the identical full arrays — the two properties the
            # full==replicated equality test stands on
            grads = jax.lax.optimization_barrier(_to_replicated(grads))
        upd_params = _to_owner_shards(params) if full_sharded else params
        if applies_updates:
            # fused path (ops/fused_update.py): the whole optimizer chain
            # plus apply_updates in one traversal
            new_params, new_opt_state = tx.update(grads, opt_state, upd_params)
        else:
            updates, new_opt_state = tx.update(grads, opt_state, upd_params)
            if full_sharded:
                updates = _to_owner_shards(updates)
            new_params = optax.apply_updates(upd_params, updates)
        if full_sharded:
            # shard-local results; the shadow refresh happens PRE-allgather
            # (each rank casts only its owned shard, and the gather moves
            # bf16 bytes); then the ONE allgather returns the updated
            # params to the replicated data-parallel layout
            new_params = _to_owner_shards(new_params)
            new_shadow = None
            if shadow_t is not None:
                new_shadow = _to_replicated(
                    _to_owner_shards(refresh_shadow(new_params, shadow_t))
                )
            new_params = _to_replicated(new_params)
        else:
            new_shadow = (
                refresh_shadow(new_params, shadow_t)
                if shadow_t is not None
                else None
            )
        if pin_grads:
            # same partitioner-proof reduction the fused clip uses, so the
            # reported norm is identical across modes and mesh shapes (the
            # free-floating optax.global_norm compiles to a different
            # accumulation order per program — ops/fused_update.py)
            from ..ops.fused_update import stable_global_norm

            grad_norm = stable_global_norm(grads)
        else:
            grad_norm = optax.global_norm(grads)
        metrics = dict(metrics)
        metrics["grad_norm"] = grad_norm
        return new_params, new_opt_state, new_shadow, loss, metrics

    if multi_dispatch:
        def multi_core(params, opt_state, shadow_t, tokens, targets, rng):
            def body(carry, batch):
                params, opt_state, shadow_t, rng = carry
                rng, sub = jax.random.split(rng)
                b_tokens, b_targets = batch
                params, opt_state, shadow_t, loss, metrics = step_once(
                    params, opt_state, shadow_t, b_tokens, b_targets, sub
                )
                return (params, opt_state, shadow_t, rng), (loss, metrics)

            (params, opt_state, shadow_t, rng), (losses, metricses) = (
                jax.lax.scan(
                    body, (params, opt_state, shadow_t, rng), (tokens, targets)
                )
            )
            return params, opt_state, shadow_t, rng, losses, metricses

        if shadow:
            update = multi_core
        else:
            def update(params, opt_state, tokens, targets, rng):
                p, o, _, rng, losses, metricses = multi_core(
                    params, opt_state, None, tokens, targets, rng
                )
                return p, o, rng, losses, metricses
    elif shadow:
        def update(params, opt_state, shadow_t, tokens, targets, rng):
            p, o, s, loss, metrics = step_once(
                params, opt_state, shadow_t, tokens, targets, rng
            )
            return p, o, s, loss, metrics
    else:
        def update(params, opt_state, tokens, targets, rng):
            p, o, _, loss, metrics = step_once(
                params, opt_state, None, tokens, targets, rng
            )
            return p, o, loss, metrics

    # Sharding layout, DECLARED to jit (not left to placement inference):
    # params replicated; batch sharded over `data`; opt state replicated or
    # ZeRO-1 per-leaf. out_shardings pin the updated opt state to the same
    # layout so a ZeRO-1 state stays sharded across steps instead of being
    # replicated back by GSPMD.
    repl = replicated(mesh)
    batch_dims = (1 if multi_dispatch else 0) + (1 if accum > 1 else 0)
    batch_shard = NamedSharding(mesh, P(*([None] * batch_dims), "data"))
    if opt_state_template is not None:
        opt_sh: Any = opt_state_shardings(opt_state_template, mesh, mode)
    else:
        opt_sh = repl  # prefix: whole subtree replicated

    in_sh: Tuple[Any, ...] = (repl, opt_sh)
    out_sh: Tuple[Any, ...] = (repl, opt_sh)
    donate_argnums: Tuple[int, ...] = (0, 1)
    if shadow:
        in_sh += (repl,)
        out_sh += (repl,)
        donate_argnums += (2,)  # the old shadow buffer backs the refresh
    in_sh += (batch_shard, batch_shard, repl)
    if multi_dispatch:
        out_sh += (repl, repl, repl)  # rng, losses [K], metrics [K]
    else:
        out_sh += (repl, repl)  # loss, metrics

    jit_kwargs: Dict[str, Any] = {
        "in_shardings": in_sh,
        "out_shardings": out_sh,
    }
    if donate:
        jit_kwargs["donate_argnums"] = donate_argnums

    jitted = jax.jit(update, **jit_kwargs)

    def run(*args):
        # install the mesh so model code (transformer TP/CP constraints,
        # ring attention) can consult it at trace time
        with pctx.use_mesh(mesh):
            return jitted(*args)

    def lower(*args):
        # same mesh install as ``run``: model code consults the mesh at
        # trace time, and lowering traces without executing (used by
        # bench.py for XLA cost analysis — FLOPs/step for MFU accounting)
        with pctx.use_mesh(mesh):
            return jitted.lower(*args)

    run.mesh = mesh
    run.batch_shard = batch_shard
    run.replicated = repl
    run.opt_shardings = opt_sh
    run.lower = lower
    run.takes_shadow = shadow
    run.multi_dispatch = multi_dispatch
    run.update_sharding = mode
    return run


def make_update_only(
    tx: Any,
    mesh: Mesh,
    update_sharding: Any,
    opt_state_template: Any,
    *,
    donate: bool = True,
    gather: bool = True,
) -> Callable:
    """Jitted optimizer-update-ONLY program (no forward/backward): takes
    (params, opt_state, grads) and returns (params, opt_state).

    This is the microbench path (``bench.py --update-only --sharded``)
    and it shares the exact mode semantics of :func:`make_train_step`'s
    update section — pin-the-grads barrier, owner-shard apply, final
    allgather — so the A/B measures the program the training loop runs,
    not a bench-only approximation. ``gather=False`` (only meaningful
    under "full") stops BEFORE the params allgather and returns
    owner-sharded params: the bench's isolated "apply" phase.
    """
    mode = _mode_of(update_sharding)
    multi_replica = int(mesh.shape["data"]) > 1
    full_sharded = mode == "full" and multi_replica
    pin_grads = multi_replica and mode in ("replicated", "full")
    applies_updates = bool(getattr(tx, "applies_updates", False))

    def update(params, opt_state, grads):
        if pin_grads:
            grads = jax.lax.optimization_barrier(
                _constrain_replicated(grads, mesh)
            )
        upd_params = (
            _constrain_owner_shards(params, mesh) if full_sharded else params
        )
        if applies_updates:
            new_params, new_opt_state = tx.update(grads, opt_state, upd_params)
        else:
            import optax as _optax

            updates, new_opt_state = tx.update(grads, opt_state, upd_params)
            if full_sharded:
                updates = _constrain_owner_shards(updates, mesh)
            new_params = _optax.apply_updates(upd_params, updates)
        if full_sharded:
            new_params = _constrain_owner_shards(new_params, mesh)
            if gather:
                new_params = _constrain_replicated(new_params, mesh)
        return new_params, new_opt_state

    repl = replicated(mesh)
    opt_sh = opt_state_shardings(opt_state_template, mesh, mode)
    jit_kwargs: Dict[str, Any] = {
        "in_shardings": (repl, opt_sh, repl),
    }
    if gather or not full_sharded:
        jit_kwargs["out_shardings"] = (repl, opt_sh)
    # gather=False: no out_shardings — the in-program owner-shard
    # constraints fully pin the (sharded) output placement
    if donate:
        jit_kwargs["donate_argnums"] = (0, 1)
    jitted = jax.jit(update, **jit_kwargs)

    def run(*args):
        with pctx.use_mesh(mesh):
            return jitted(*args)

    run.mesh = mesh
    run.update_sharding = mode
    run.gather = gather
    return run


def make_shard_apply(tx: Any, *, donate: bool = True) -> Callable:
    """Jitted single-shard optimizer apply: ``(params, opt_state, grads)
    -> (params, opt_state)`` over ONE owner's slice tree, no mesh.

    This is the trainer fleet's apply entry point (training/fleet/): the
    cross-process analogue of ``make_update_only`` where the "shard" is
    the nested slice tree a fleet worker owns (ownership.py) rather than
    a mesh-sharded leaf — the owner applies the optimizer to exactly the
    parameters it owns, at quorum, and nothing else (PAPER.md §L3
    owner-applies-the-update). ``tx`` may be the fused transformation
    (``applies_updates`` — ops/fused_update.py on the owned slice, as in
    the in-mesh "full" mode) or a plain optax chain. State and params
    are donated: the owner holds exactly one live copy of its shard.

    Wire compression is invisible here: compressed gradient pushes are
    dequantized to f32 at the wire boundary (fleet/wire.decode_grads)
    BEFORE the quorum buffer, so this apply always consumes plain f32
    grad trees — same numerics whatever codec carried them.
    """
    applies_updates = bool(getattr(tx, "applies_updates", False))

    def update(params, opt_state, grads):
        if applies_updates:
            new_params, new_opt_state = tx.update(grads, opt_state, params)
        else:
            import optax as _optax

            updates, new_opt_state = tx.update(grads, opt_state, params)
            new_params = _optax.apply_updates(params, updates)
        return new_params, new_opt_state

    jit_kwargs: Dict[str, Any] = {}
    if donate:
        jit_kwargs["donate_argnums"] = (0, 1)
    jitted = jax.jit(update, **jit_kwargs)

    def run(params, opt_state, grads):
        return jitted(params, opt_state, grads)

    run.update_sharding = "fleet-owner-shard"
    return run


def place_batch(batch_tree: Any, mesh: Mesh, accum: bool = False) -> Any:
    """Place batch leaves with the batch dim sharded over the ``data`` axis.

    Pads are already in the arrays; B must be divisible by the data-axis
    size (the batcher guarantees it via bucket_batch_size + mesh multiple).

    Single-process: a plain sharded device_put (the local array IS the
    global batch). Multi-process: every host collated a DIFFERENT local
    batch (the stream is sharded by host in the loop), so device_put with a
    global sharding would treat each host's array as the same global value
    and silently drop every row outside that host's global shard slice —
    most of the corpus. Instead the global batch is assembled with
    ``jax.make_array_from_process_local_data``: global B = per-host B ×
    process_count, each host contributing all of its local rows.
    """
    sh = NamedSharding(mesh, P(None, "data") if accum else P("data"))
    if jax.process_count() == 1:
        return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch_tree)

    bdim = 1 if accum else 0

    def make_global(x):
        x = np.asarray(x)
        global_shape = (
            x.shape[:bdim]
            + (x.shape[bdim] * jax.process_count(),)
            + x.shape[bdim + 1 :]
        )
        return jax.make_array_from_process_local_data(sh, x, global_shape)

    return jax.tree_util.tree_map(make_global, batch_tree)


def place_replicated(tree: Any, mesh: Mesh) -> Any:
    sh = replicated(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), tree)
