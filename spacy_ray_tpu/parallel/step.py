"""The sharded train step: one compiled XLA program per (B, T) bucket.

This single function replaces the reference's entire L3/L4 communication
machinery (SURVEY.md §1): forward, backward, gradient all-reduce over ICI,
optimizer update, and (with ``zero1=True``) sharded optimizer state — where
the reference does per-parameter RPC push/broadcast with version gates and
quorums (reference proxies.py:54-133, worker.py:117-132), here GSPMD insert
collectives from sharding annotations and the whole exchange compiles into
the step (SURVEY.md §2.2: "synchronous allreduce is strictly better on TPU
ICI").

Gradient accumulation: the reference folds ``accumulate_gradient`` into its
distributed quorum (reference worker.py:151-155,182 — with the dead-code bug
noted in SURVEY.md §2.4); here it is an explicit ``lax.scan`` over stacked
microbatches, numerically equivalent to a quorum of exactly
``num_workers × accumulate_gradient`` with zero staleness.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import context as pctx
from .mesh import replicated, zero1_spec


def shard_opt_state(opt_state: Any, mesh: Mesh, zero1: bool) -> Any:
    """Place optimizer state: ZeRO-1 sharded over data axis, or replicated."""
    if not zero1:
        return jax.device_put(opt_state, replicated(mesh))
    return jax.tree_util.tree_map(
        lambda leaf: jax.device_put(leaf, zero1_spec(leaf, mesh)), opt_state
    )


def opt_state_shardings(opt_state: Any, mesh: Mesh, zero1: bool) -> Any:
    if not zero1:
        return jax.tree_util.tree_map(lambda _: replicated(mesh), opt_state)
    return jax.tree_util.tree_map(lambda leaf: zero1_spec(leaf, mesh), opt_state)


def overlay_shadow(params: Any, shadow: Any) -> Any:
    """Overlay a (sub-structure) shadow tree onto params: positions present
    in ``shadow`` are taken from it (bf16 copies of the trunk's matmul
    weights — models/transformer.py build_param_shadow), the rest from
    ``params``. The forward then consumes the shadow leaves directly, so
    the layer stack's per-step ``astype(compute_dtype)`` is a no-op."""
    if not isinstance(shadow, dict):
        return shadow
    out = dict(params)
    for k, v in shadow.items():
        out[k] = overlay_shadow(params[k], v)
    return out


def refresh_shadow(new_params: Any, shadow: Any) -> Any:
    """Re-derive the shadow from freshly updated master params — ONE cast
    per shadowed leaf, fused into the same jitted update (the donated old
    shadow buffer is reused; no second host-visible traversal)."""
    if not isinstance(shadow, dict):
        return new_params.astype(shadow.dtype)
    return {k: refresh_shadow(new_params[k], v) for k, v in shadow.items()}


def _cast_like(tree: Any, like: Any) -> Any:
    """Cast shadow-leaf cotangents (bf16) back to the master dtype. The
    VALUES match the cast-per-step path up to one bf16 rounding: the
    baseline program's backward may elide the f32->bf16->f32 double
    rounding inside the weight-grad matmul, so the two trajectories agree
    to ~1e-8/step rather than bitwise (forward IS bit-exact — asserted by
    tests/test_fused_update.py)."""
    return jax.tree_util.tree_map(
        lambda x, ref: x.astype(ref.dtype), tree, like
    )


def make_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    accumulate_gradient: int = 1,
    zero1: bool = False,
    opt_state_template: Any = None,
    donate: bool = True,
    shadow: bool = False,
    multi_dispatch: bool = False,
) -> Callable:
    """Build the jitted sharded update.

    loss_fn(params, tokens, targets, rng) -> (loss, metrics).

    Returns update(params, opt_state, tokens, targets, rng) ->
    (params, opt_state, loss, metrics). When accumulate_gradient > 1,
    tokens/targets leaves carry a leading [A] microbatch dim and the batch
    dim is sharded at position 1; otherwise position 0.

    ``shadow=True``: the update takes (params, opt_state, shadow, tokens,
    targets, rng) and returns (params, opt_state, shadow, loss, metrics).
    The forward runs on ``overlay_shadow(params, shadow)`` (bf16 trunk
    weights read directly — no per-step cast), gradients are cast back to
    the master dtype before accumulation/optimizer, and the shadow is
    refreshed from the new params inside the same program (all three
    state arguments donated).

    ``multi_dispatch=True``: tokens/targets leaves carry a leading [K]
    per-dispatch dim; the update runs K full train steps as one
    ``lax.scan`` (ONE host round-trip) and returns (params, opt_state,
    [shadow,] rng, losses[K], metrics[K]) — ``rng`` is carried through
    the scan with the same ``jax.random.split`` chain the host performs
    at K=1, so K steps are bit-identical to K single dispatches. K is
    read from the input shape: each distinct K compiles once.
    """
    accum = max(int(accumulate_gradient), 1)

    def grads_of(params, tokens, targets, rng):
        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, tokens, targets, rng
        )
        return loss, metrics, grads

    applies_updates = bool(getattr(tx, "applies_updates", False))

    def step_once(params, opt_state, shadow_t, tokens, targets, rng):
        fwd_params = (
            overlay_shadow(params, shadow_t) if shadow_t is not None else params
        )
        if accum == 1:
            loss, metrics, grads = grads_of(fwd_params, tokens, targets, rng)
            if shadow_t is not None:
                # bf16 cotangents at shadow leaves -> f32 master grads (the
                # same values the cast-per-step path produces via the
                # cast's transpose)
                grads = _cast_like(grads, params)
        else:
            def body(carry, micro):
                acc_grads, rng = carry
                rng, sub = jax.random.split(rng)
                m_tokens, m_targets = micro
                loss, metrics, grads = grads_of(fwd_params, m_tokens, m_targets, sub)
                if shadow_t is not None:
                    grads = _cast_like(grads, acc_grads)
                acc_grads = jax.tree_util.tree_map(jnp.add, acc_grads, grads)
                return (acc_grads, rng), (loss, metrics)

            zero_grads = jax.tree_util.tree_map(jnp.zeros_like, params)
            (grads, _), (losses, metricses) = jax.lax.scan(
                body, (zero_grads, rng), (tokens, targets)
            )
            grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
            loss = jnp.mean(losses)
            metrics = jax.tree_util.tree_map(jnp.mean, metricses)
        if applies_updates:
            # fused path (ops/fused_update.py): the whole optimizer chain
            # plus apply_updates in one traversal
            new_params, new_opt_state = tx.update(grads, opt_state, params)
        else:
            updates, new_opt_state = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
        new_shadow = (
            refresh_shadow(new_params, shadow_t)
            if shadow_t is not None
            else None
        )
        grad_norm = optax.global_norm(grads)
        metrics = dict(metrics)
        metrics["grad_norm"] = grad_norm
        return new_params, new_opt_state, new_shadow, loss, metrics

    if multi_dispatch:
        def multi_core(params, opt_state, shadow_t, tokens, targets, rng):
            def body(carry, batch):
                params, opt_state, shadow_t, rng = carry
                rng, sub = jax.random.split(rng)
                b_tokens, b_targets = batch
                params, opt_state, shadow_t, loss, metrics = step_once(
                    params, opt_state, shadow_t, b_tokens, b_targets, sub
                )
                return (params, opt_state, shadow_t, rng), (loss, metrics)

            (params, opt_state, shadow_t, rng), (losses, metricses) = (
                jax.lax.scan(
                    body, (params, opt_state, shadow_t, rng), (tokens, targets)
                )
            )
            return params, opt_state, shadow_t, rng, losses, metricses

        if shadow:
            update = multi_core
        else:
            def update(params, opt_state, tokens, targets, rng):
                p, o, _, rng, losses, metricses = multi_core(
                    params, opt_state, None, tokens, targets, rng
                )
                return p, o, rng, losses, metricses
    elif shadow:
        def update(params, opt_state, shadow_t, tokens, targets, rng):
            p, o, s, loss, metrics = step_once(
                params, opt_state, shadow_t, tokens, targets, rng
            )
            return p, o, s, loss, metrics
    else:
        def update(params, opt_state, tokens, targets, rng):
            p, o, _, loss, metrics = step_once(
                params, opt_state, None, tokens, targets, rng
            )
            return p, o, loss, metrics

    # Sharding layout, DECLARED to jit (not left to placement inference):
    # params replicated; batch sharded over `data`; opt state replicated or
    # ZeRO-1 per-leaf. out_shardings pin the updated opt state to the same
    # layout so a ZeRO-1 state stays sharded across steps instead of being
    # replicated back by GSPMD.
    repl = replicated(mesh)
    batch_dims = (1 if multi_dispatch else 0) + (1 if accum > 1 else 0)
    batch_shard = NamedSharding(mesh, P(*([None] * batch_dims), "data"))
    if opt_state_template is not None:
        opt_sh: Any = opt_state_shardings(opt_state_template, mesh, zero1)
    else:
        opt_sh = repl  # prefix: whole subtree replicated

    in_sh: Tuple[Any, ...] = (repl, opt_sh)
    out_sh: Tuple[Any, ...] = (repl, opt_sh)
    donate_argnums: Tuple[int, ...] = (0, 1)
    if shadow:
        in_sh += (repl,)
        out_sh += (repl,)
        donate_argnums += (2,)  # the old shadow buffer backs the refresh
    in_sh += (batch_shard, batch_shard, repl)
    if multi_dispatch:
        out_sh += (repl, repl, repl)  # rng, losses [K], metrics [K]
    else:
        out_sh += (repl, repl)  # loss, metrics

    jit_kwargs: Dict[str, Any] = {
        "in_shardings": in_sh,
        "out_shardings": out_sh,
    }
    if donate:
        jit_kwargs["donate_argnums"] = donate_argnums

    jitted = jax.jit(update, **jit_kwargs)

    def run(*args):
        # install the mesh so model code (transformer TP/CP constraints,
        # ring attention) can consult it at trace time
        with pctx.use_mesh(mesh):
            return jitted(*args)

    def lower(*args):
        # same mesh install as ``run``: model code consults the mesh at
        # trace time, and lowering traces without executing (used by
        # bench.py for XLA cost analysis — FLOPs/step for MFU accounting)
        with pctx.use_mesh(mesh):
            return jitted.lower(*args)

    run.mesh = mesh
    run.batch_shard = batch_shard
    run.replicated = repl
    run.opt_shardings = opt_sh
    run.lower = lower
    run.takes_shadow = shadow
    run.multi_dispatch = multi_dispatch
    return run


def place_batch(batch_tree: Any, mesh: Mesh, accum: bool = False) -> Any:
    """Place batch leaves with the batch dim sharded over the ``data`` axis.

    Pads are already in the arrays; B must be divisible by the data-axis
    size (the batcher guarantees it via bucket_batch_size + mesh multiple).

    Single-process: a plain sharded device_put (the local array IS the
    global batch). Multi-process: every host collated a DIFFERENT local
    batch (the stream is sharded by host in the loop), so device_put with a
    global sharding would treat each host's array as the same global value
    and silently drop every row outside that host's global shard slice —
    most of the corpus. Instead the global batch is assembled with
    ``jax.make_array_from_process_local_data``: global B = per-host B ×
    process_count, each host contributing all of its local rows.
    """
    sh = NamedSharding(mesh, P(None, "data") if accum else P("data"))
    if jax.process_count() == 1:
        return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch_tree)

    bdim = 1 if accum else 0

    def make_global(x):
        x = np.asarray(x)
        global_shape = (
            x.shape[:bdim]
            + (x.shape[bdim] * jax.process_count(),)
            + x.shape[bdim + 1 :]
        )
        return jax.make_array_from_process_local_data(sh, x, global_shape)

    return jax.tree_util.tree_map(make_global, batch_tree)


def place_replicated(tree: Any, mesh: Mesh) -> Any:
    sh = replicated(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), tree)
