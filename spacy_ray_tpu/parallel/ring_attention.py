"""Ring attention: exact attention over sequences sharded on the ``context``
mesh axis.

Long-context sequence parallelism — absent from the reference (SURVEY.md
§5.7: sequence scaling there is document segmentation only) but first-class
here: each device holds a [B, T/n] slice of the sequence; key/value blocks
rotate around the ring via ``lax.ppermute`` over ICI while queries stay
put, with an online-softmax accumulator so the result is EXACT attention
(numerically identical to the dense computation), memory O(T/n) per device,
and communication overlapped block-by-block.

Implemented with ``shard_map`` over the mesh (per-device code + explicit
collectives), the idiomatic JAX pattern for collective-permute pipelines.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import context as pctx
from .smap import CHECK_KW as _CHECK_KW, PARTIAL_MANUAL, shard_map

AXIS = "context"


def _ring_body(carry, _, *, q, scale, axis_name, n_shards):
    k, v, kmask, m, num, den = carry
    # scores over the current key block: [B, H, Tq, Tk]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    neg = jnp.float32(-1e30)
    scores = jnp.where(kmask[:, None, None, :], scores, neg)
    block_max = jnp.max(scores, axis=-1)  # [B, H, Tq]
    new_m = jnp.maximum(m, block_max)
    correction = jnp.exp(m - new_m)
    p = jnp.exp(scores - new_m[..., None])  # [B, H, Tq, Tk]
    p = jnp.where(kmask[:, None, None, :], p, 0.0)
    corr_q = correction.transpose(0, 2, 1)[..., None]  # [B, Tq, H, 1]
    num = num * corr_q + jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    den = den * correction + jnp.sum(p, axis=-1)
    # rotate k/v/mask to the next ring position
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    k = jax.lax.ppermute(k, axis_name, perm)
    v = jax.lax.ppermute(v, axis_name, perm)
    kmask = jax.lax.ppermute(kmask, axis_name, perm)
    return (k, v, kmask, new_m, num, den), None


def ring_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """q/k/v [B, T, H, Dh] (T logically sharded over 'context'), mask [B, T].

    Returns [B, T, H, Dh] in q.dtype. Must be called under jit with the
    active mesh (parallel/context.py) carrying a 'context' axis.
    """
    mesh = pctx.current_mesh()
    assert mesh is not None and AXIS in mesh.shape, "ring_attention needs a context axis"
    n_shards = int(mesh.shape[AXIS])
    Dh = q.shape[-1]
    scale = 1.0 / (Dh ** 0.5)
    out_dtype = q.dtype

    sm_mesh = mesh
    if PARTIAL_MANUAL:
        # manual over `context` ONLY: data/model dims keep their automatic
        # (GSPMD) semantics, so the body's einsums still partition over
        # them — and the whole region can nest inside another partial-
        # manual shard_map (the pipeline's `pipe` region). When already
        # inside such a region, shard_map must receive the AMBIENT abstract
        # mesh (whose enclosing axes are marked Manual), not the concrete
        # mesh it was built from.
        qkv_spec = P(None, AXIS, None, None)
        mask_spec = P(None, AXIS)
        sm_kwargs: dict = {"axis_names": frozenset({AXIS})}
        try:
            from jax.sharding import get_abstract_mesh

            am = get_abstract_mesh()
            if am is not None and AXIS in (am.shape or {}):
                sm_mesh = am
        except Exception:  # pragma: no cover - API drift: concrete mesh
            pass
    else:  # pragma: no cover - older jax: fully manual over the whole mesh
        data = "data" if "data" in mesh.shape else None
        model = "model" if "model" in mesh.shape and mesh.shape["model"] > 1 else None
        qkv_spec = P(data, AXIS, model, None)
        mask_spec = P(data, AXIS)
        sm_kwargs = {}

    @partial(
        shard_map,
        mesh=sm_mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        **{_CHECK_KW: False},
        **sm_kwargs,
    )
    def inner(q, k, v, kmask):
        B, Tq, H, _ = q.shape
        m = jnp.full((B, H, Tq), -1e30, jnp.float32)
        num = jnp.zeros((B, Tq, H, Dh), jnp.float32)
        den = jnp.zeros((B, H, Tq), jnp.float32)
        body = partial(
            _ring_body, q=q, scale=scale, axis_name=AXIS, n_shards=n_shards
        )
        (k, v, kmask, m, num, den), _ = jax.lax.scan(
            body, (k, v, kmask, m, num, den), None, length=n_shards
        )
        den_t = den.transpose(0, 2, 1)[..., None]  # [B, Tq, H, 1]
        return (num / jnp.maximum(den_t, 1e-9)).astype(out_dtype)

    return inner(q, k, v, mask)
