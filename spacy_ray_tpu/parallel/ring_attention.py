"""Ring attention: exact attention over sequences sharded on the ``context``
mesh axis.

Long-context sequence parallelism — absent from the reference (SURVEY.md
§5.7: sequence scaling there is document segmentation only) but first-class
here: each device holds a [B, T/n] slice of the sequence; key/value blocks
rotate around the ring via ``lax.ppermute`` over ICI while queries stay
put, with an online-softmax accumulator so the result is EXACT attention
(numerically identical to the dense computation), memory O(T/n) per device,
and communication overlapped block-by-block.

Implemented with ``shard_map`` over the mesh (per-device code + explicit
collectives), the idiomatic JAX pattern for collective-permute pipelines.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import context as pctx
from .smap import CHECK_KW as _CHECK_KW, PARTIAL_MANUAL, shard_map

AXIS = "context"


def _use_flash_blocks(t_block: int, head_dim: int) -> bool:
    """Static host-side gate: run each ring block through the pallas flash
    kernel (ops/flash_attention.py) instead of the dense jnp score block.
    Exact either way; flash keeps the per-block [B, H, Tq, Tk] score tensor
    out of HBM, which matters once the per-device sequence slice is long —
    the whole point of the context axis."""
    from ..ops import flash_attention as fa

    return fa.flash_attention_enabled() and fa.attention_vmem_ok(
        t_block, fa._dp(head_dim)
    )


def _ring_flash(q, k, v, kmask, *, scale, n_shards, out_dtype):
    """Per-device ring loop with pallas flash blocks: q is laid out for the
    kernel once; the RAW k/v/kmask rotate around the ring (padding them per
    step is a fused VPU op, while rotating padded tensors would inflate
    per-step ppermute ICI traffic by the pad ratio). Each block's (output,
    logsumexp) pair merges associatively into a running pair — the flash
    merge, differentiable end-to-end because the block kernel's VJP accepts
    an lse cotangent (_make_flash_lse)."""
    from ..ops import flash_attention as fa

    B, T, H, Dh = q.shape
    qk = fa._to_kernel_layout(q)
    fl = fa._make_flash_lse(scale)

    _, _, Tp, DP = qk.shape
    o_acc = jnp.zeros((B, H, Tp, DP), jnp.float32)
    lse_acc = jnp.full((B, H, Tp), fa.NEG, jnp.float32)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    def body(carry, _):
        k, v, kmask, o_acc, lse_acc = carry
        o_b, lse_b = fl(
            qk, fa._to_kernel_layout(k), fa._to_kernel_layout(v),
            fa._mask_to_bias(kmask),
        )
        m = jnp.maximum(lse_acc, lse_b)
        w_acc = jnp.exp(lse_acc - m)
        w_b = jnp.exp(lse_b - m)
        den = w_acc + w_b
        o_acc = (
            o_acc * (w_acc / den)[..., None]
            + o_b.astype(jnp.float32) * (w_b / den)[..., None]
        )
        lse_acc = m + jnp.log(den)
        k = jax.lax.ppermute(k, AXIS, perm)
        v = jax.lax.ppermute(v, AXIS, perm)
        kmask = jax.lax.ppermute(kmask, AXIS, perm)
        return (k, v, kmask, o_acc, lse_acc), None

    (_, _, _, o_acc, _), _ = jax.lax.scan(
        body, (k, v, kmask, o_acc, lse_acc), None, length=n_shards
    )
    return o_acc[:, :, :T, :Dh].transpose(0, 2, 1, 3).astype(out_dtype)


def _ring_body(carry, _, *, q, scale, axis_name, n_shards):
    k, v, kmask, m, num, den = carry
    # scores over the current key block: [B, H, Tq, Tk]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    neg = jnp.float32(-1e30)
    scores = jnp.where(kmask[:, None, None, :], scores, neg)
    block_max = jnp.max(scores, axis=-1)  # [B, H, Tq]
    new_m = jnp.maximum(m, block_max)
    correction = jnp.exp(m - new_m)
    p = jnp.exp(scores - new_m[..., None])  # [B, H, Tq, Tk]
    p = jnp.where(kmask[:, None, None, :], p, 0.0)
    corr_q = correction.transpose(0, 2, 1)[..., None]  # [B, Tq, H, 1]
    num = num * corr_q + jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    den = den * correction + jnp.sum(p, axis=-1)
    # rotate k/v/mask to the next ring position
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    k = jax.lax.ppermute(k, axis_name, perm)
    v = jax.lax.ppermute(v, axis_name, perm)
    kmask = jax.lax.ppermute(kmask, axis_name, perm)
    return (k, v, kmask, new_m, num, den), None


def ring_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """q/k/v [B, T, H, Dh] (T logically sharded over 'context'), mask [B, T].

    Returns [B, T, H, Dh] in q.dtype. Must be called under jit with the
    active mesh (parallel/context.py) carrying a 'context' axis.
    """
    mesh = pctx.current_mesh()
    assert mesh is not None and AXIS in mesh.shape, "ring_attention needs a context axis"
    n_shards = int(mesh.shape[AXIS])
    B_g, T_g, H_g, Dh = q.shape
    scale = 1.0 / (Dh ** 0.5)
    out_dtype = q.dtype
    n_data = int(mesh.shape.get("data", 1))
    n_model = int(mesh.shape.get("model", 1))
    # flash blocks run a pallas_call per device shard; the gate is decided
    # HERE because under partial-manual the region's manual axis set depends
    # on it (pallas_call has no GSPMD partitioning rule, so every mesh axis
    # its operands are sharded over must be manual — see
    # flash_attention._sharded_flash_attention for the single-chip analogue)
    flash = _use_flash_blocks(T_g // n_shards, Dh)

    sm_mesh = mesh
    if PARTIAL_MANUAL:
        # manual over `context` ONLY by default: data/model dims keep their
        # automatic (GSPMD) semantics, so the dense body's einsums still
        # partition over them — and the whole region can nest inside another
        # partial-manual shard_map (the pipeline's `pipe` region). The flash
        # path instead goes manual over data/model TOO (its kernel covers
        # the whole per-device computation; nothing is left to partition),
        # falling back to dense when the layout doesn't divide. When already
        # inside such a region, shard_map must receive the AMBIENT abstract
        # mesh (whose enclosing axes are marked Manual), not the concrete
        # mesh it was built from.
        manual = {AXIS}
        if flash and (n_data > 1 or n_model > 1):
            if B_g % max(n_data, 1) or H_g % max(n_model, 1):
                flash = False  # indivisible layout: dense partitions cleanly
            else:
                manual |= {a for a, n in (("data", n_data), ("model", n_model)) if n > 1}
        data_ax = "data" if "data" in manual else None
        model_ax = "model" if "model" in manual else None
        qkv_spec = P(data_ax, AXIS, model_ax, None)
        mask_spec = P(data_ax, AXIS)
        sm_kwargs: dict = {"axis_names": frozenset(manual)}
        try:
            from jax.sharding import get_abstract_mesh

            am = get_abstract_mesh()
            if am is not None and all(a in (am.shape or {}) for a in manual):
                sm_mesh = am
        except Exception:  # pragma: no cover - API drift: concrete mesh
            pass
    else:  # older jax: fully manual over the whole mesh
        # the manual region shards B over `data` (and H over `model`) only
        # when the dims actually divide — an indivisible layout falls back
        # to replicating that dim on every shard (each device computes the
        # full extent; wasteful but exact), instead of tripping shard_map's
        # divisibility check
        data = "data" if n_data > 1 and B_g % n_data == 0 else None
        model = "model" if n_model > 1 and H_g % n_model == 0 else None
        qkv_spec = P(data, AXIS, model, None)
        mask_spec = P(data, AXIS)
        sm_kwargs = {}

    @partial(
        shard_map,
        mesh=sm_mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        **{_CHECK_KW: False},
        **sm_kwargs,
    )
    def inner(q, k, v, kmask):
        B, Tq, H, _ = q.shape
        if flash:
            return _ring_flash(
                q, k, v, kmask,
                scale=scale, n_shards=n_shards, out_dtype=out_dtype,
            )
        m = jnp.full((B, H, Tq), -1e30, jnp.float32)
        num = jnp.zeros((B, Tq, H, Dh), jnp.float32)
        den = jnp.zeros((B, H, Tq), jnp.float32)
        body = partial(
            _ring_body, q=q, scale=scale, axis_name=AXIS, n_shards=n_shards
        )
        (k, v, kmask, m, num, den), _ = jax.lax.scan(
            body, (k, v, kmask, m, num, den), None, length=n_shards
        )
        den_t = den.transpose(0, 2, 1)[..., None]  # [B, Tq, H, 1]
        return (num / jnp.maximum(den_t, 1e-9)).astype(out_dtype)

    return inner(q, k, v, mask)
