"""Device mesh construction: the TPU-native replacement for the Ray cluster.

Capability parity with the reference's worker topology (reference
train_cli.py:66-82: ``ray.init`` + N actor spawn; SURVEY.md §5.8): here
"workers" are mesh positions. Axes:

* ``data`` — batch sharding + gradient all-reduce over ICI (replaces the
  RayPeerProxy grad push/param broadcast protocol, reference
  proxies.py:71-109);
* ``model`` — tensor parallelism for large trunks (transformer);
* ``context`` — sequence/context parallelism (ring attention);
* ``pipe`` — pipeline parallelism (GPipe schedule, parallel/pipeline.py).

``--n-workers N`` from the CLI (reference train_cli.py:27) maps to the data
axis size.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "model", "context", "pipe")


def build_mesh(
    n_data: Optional[int] = None,
    n_model: int = 1,
    n_context: int = 1,
    n_pipe: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    n_total = len(devices)
    if n_data is None:
        n_data = n_total // (n_model * n_context * n_pipe)
    want = n_data * n_model * n_context * n_pipe
    if want > n_total:
        raise ValueError(
            f"Mesh {n_data}x{n_model}x{n_context}x{n_pipe} needs "
            f"{want} devices, have {n_total}"
        )
    dev_array = np.array(devices[:want]).reshape(n_data, n_model, n_context, n_pipe)
    return Mesh(dev_array, AXES)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, rank: int = 0) -> NamedSharding:
    """Shard dim `rank` over the data axis."""
    spec = [None] * (rank + 1)
    spec[rank] = "data"
    return NamedSharding(mesh, P(*spec))


def batch_spec(accumulate: bool = False):
    """PartitionSpec for a batch leaf: [B, ...] or [A, B, ...] with accum."""
    return P(None, "data") if accumulate else P("data")


def zero1_spec(leaf: "jax.Array", mesh: Mesh) -> NamedSharding:
    """Data-axis ownership sharding for one optimizer-state or parameter
    leaf: shard the first axis divisible by the data-axis size; replicate
    otherwise.

    The GSPMD version of the reference's parameter-ownership split
    (reference util.py:57-75 ``divide_params`` + owner-applied updates at
    proxies.py:111-133): ownership becomes a sharding annotation and the
    update math is compiled with its collectives (SURVEY.md §2.2 row
    "Optimizer/param-state sharding"). The SAME spec describes both ZeRO-1
    state sharding and the ``update_sharding = "full"`` shard-local apply
    (arXiv:2004.13336): a param leaf and its Adam moments share one spec,
    so the owner of a state shard is the owner of the param shard it
    updates.

    Works on tracers too (only ``shape`` is consulted), so the train step
    can apply it as a ``with_sharding_constraint`` inside jit.
    """
    n_data = mesh.shape["data"]
    shape = getattr(leaf, "shape", ())
    for axis, dim in enumerate(shape):
        if dim % n_data == 0 and dim >= n_data:
            spec = [None] * len(shape)
            spec[axis] = "data"
            return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P())


# alias with the ownership reading: "the shard of this leaf one data-rank
# owns" — the update_sharding="full" vocabulary for the same layout
owner_shard_spec = zero1_spec


def owner_shard_specs(tree, mesh: Mesh):
    """Per-leaf :func:`owner_shard_spec` over a whole pytree."""
    import jax as _jax

    return _jax.tree_util.tree_map(
        lambda leaf: owner_shard_spec(leaf, mesh), tree
    )
