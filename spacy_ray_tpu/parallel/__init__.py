"""Distribution layer: mesh, sharded train step, collectives (SURVEY.md §5.8)."""
