"""Native extension loader: builds murmur.cpp with g++ on first import.

Binding is ctypes (no pybind11 in the image); a pure-Python fallback keeps
every feature working when no compiler is available. The .so is cached next
to the source and rebuilt when the source is newer.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import List, Optional, Sequence

import numpy as np

_HERE = Path(__file__).parent
_SRC = _HERE / "murmur.cpp"
_SO = _HERE / "libsrt_native.so"
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", str(_SO), str(_SRC)],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def load() -> Optional[ctypes.CDLL]:
    """Return the native lib, building it if needed; None if unavailable."""
    global _LIB, _TRIED
    if _LIB is not None:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        try:
            needs_build = (not _SO.exists()) or (
                _SRC.stat().st_mtime > _SO.stat().st_mtime
            )
            if needs_build and not _build():
                return None
            lib = ctypes.CDLL(str(_SO))
            lib.murmur3_u64.restype = ctypes.c_uint64
            lib.murmur3_u64.argtypes = [
                ctypes.c_char_p,
                ctypes.c_int64,
                ctypes.c_uint32,
            ]
            lib.murmur3_u64_batch.restype = None
            lib.murmur3_u64_batch.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_uint64),
            ]
            _LIB = lib
        except Exception:
            _LIB = None
        return _LIB


def available() -> bool:
    return load() is not None


def hash_strings_u64(strings: Sequence[str], seed: int = 0) -> np.ndarray:
    """Batch 64-bit murmur of utf-8 strings. Native when possible."""
    lib = load()
    if lib is None:
        from ..ops.hashing import hash_string_u64

        return np.array(
            [hash_string_u64(s, seed) for s in strings], dtype=np.uint64
        )
    encoded = [s.encode("utf8") for s in strings]
    n = len(encoded)
    offsets = np.zeros(n + 1, dtype=np.int64)
    for i, b in enumerate(encoded):
        offsets[i + 1] = offsets[i] + len(b)
    blob = b"".join(encoded)
    out = np.zeros(n, dtype=np.uint64)
    lib.murmur3_u64_batch(
        blob,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        seed & 0xFFFFFFFF,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    return out
