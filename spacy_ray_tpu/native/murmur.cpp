// Native batch hashing for host-side featurization.
//
// Role parity: the reference's feature hashing comes from the murmurhash C
// dependency used by its embedding stack (SURVEY.md §2.3 rows "murmurhash /
// preshed"). Here the hot host path — hashing 4 lexical-attribute strings
// per token before shipping keys to the TPU — runs through this batch
// kernel instead of per-string Python.
//
// MurmurHash3 x86_128 (public-domain algorithm, Austin Appleby), truncated
// to 64 bits as (h2 << 32) | h1 — MUST stay bit-identical to the Python
// fallback in ops/hashing.py (_murmur3_x86_128_bytes), which tests enforce.
//
// Build: g++ -O3 -shared -fPIC -o libsrt_native.so murmur.cpp

#include <cstdint>
#include <cstring>

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6bU;
  h ^= h >> 13;
  h *= 0xc2b2ae35U;
  h ^= h >> 16;
  return h;
}

static inline uint32_t getblock32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);  // little-endian hosts only (x86/arm LE)
  return v;
}

extern "C" {

// 64-bit truncated murmur3_x86_128 of one byte string.
uint64_t murmur3_u64(const uint8_t* data, int64_t len, uint32_t seed) {
  const int64_t nblocks = len / 16;
  uint32_t h1 = seed, h2 = seed, h3 = seed, h4 = seed;
  const uint32_t c1 = 0x239b961bU, c2 = 0xab0e9789U, c3 = 0x38b34ae5U,
                 c4 = 0xa1e38b93U;

  for (int64_t i = 0; i < nblocks; i++) {
    const uint8_t* block = data + i * 16;
    uint32_t k1 = getblock32(block);
    uint32_t k2 = getblock32(block + 4);
    uint32_t k3 = getblock32(block + 8);
    uint32_t k4 = getblock32(block + 12);

    k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2; h1 ^= k1;
    h1 = rotl32(h1, 19); h1 += h2; h1 = h1 * 5 + 0x561ccd1bU;
    k2 *= c2; k2 = rotl32(k2, 16); k2 *= c3; h2 ^= k2;
    h2 = rotl32(h2, 17); h2 += h3; h2 = h2 * 5 + 0x0bcaa747U;
    k3 *= c3; k3 = rotl32(k3, 17); k3 *= c4; h3 ^= k3;
    h3 = rotl32(h3, 15); h3 += h4; h3 = h3 * 5 + 0x96cd1c35U;
    k4 *= c4; k4 = rotl32(k4, 18); k4 *= c1; h4 ^= k4;
    h4 = rotl32(h4, 13); h4 += h1; h4 = h4 * 5 + 0x32ac3b17U;
  }

  const uint8_t* tail = data + nblocks * 16;
  const int64_t t = len & 15;
  uint32_t k1 = 0, k2 = 0, k3 = 0, k4 = 0;
  // byte-accumulate the tail exactly like the reference implementation
  switch (t) {
    case 15: k4 ^= (uint32_t)tail[14] << 16; [[fallthrough]];
    case 14: k4 ^= (uint32_t)tail[13] << 8; [[fallthrough]];
    case 13: k4 ^= (uint32_t)tail[12] << 0;
             k4 *= c4; k4 = rotl32(k4, 18); k4 *= c1; h4 ^= k4; [[fallthrough]];
    case 12: k3 ^= (uint32_t)tail[11] << 24; [[fallthrough]];
    case 11: k3 ^= (uint32_t)tail[10] << 16; [[fallthrough]];
    case 10: k3 ^= (uint32_t)tail[9] << 8; [[fallthrough]];
    case 9:  k3 ^= (uint32_t)tail[8] << 0;
             k3 *= c3; k3 = rotl32(k3, 17); k3 *= c4; h3 ^= k3; [[fallthrough]];
    case 8:  k2 ^= (uint32_t)tail[7] << 24; [[fallthrough]];
    case 7:  k2 ^= (uint32_t)tail[6] << 16; [[fallthrough]];
    case 6:  k2 ^= (uint32_t)tail[5] << 8; [[fallthrough]];
    case 5:  k2 ^= (uint32_t)tail[4] << 0;
             k2 *= c2; k2 = rotl32(k2, 16); k2 *= c3; h2 ^= k2; [[fallthrough]];
    case 4:  k1 ^= (uint32_t)tail[3] << 24; [[fallthrough]];
    case 3:  k1 ^= (uint32_t)tail[2] << 16; [[fallthrough]];
    case 2:  k1 ^= (uint32_t)tail[1] << 8; [[fallthrough]];
    case 1:  k1 ^= (uint32_t)tail[0] << 0;
             k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2; h1 ^= k1;
  }

  h1 ^= (uint32_t)len; h2 ^= (uint32_t)len;
  h3 ^= (uint32_t)len; h4 ^= (uint32_t)len;
  h1 += h2 + h3 + h4;
  h2 += h1; h3 += h1; h4 += h1;
  h1 = fmix32(h1); h2 = fmix32(h2); h3 = fmix32(h3); h4 = fmix32(h4);
  h1 += h2 + h3 + h4;
  h2 += h1;
  return ((uint64_t)h2 << 32) | (uint64_t)h1;
}

// Hash n concatenated strings: string i is data[offsets[i], offsets[i+1]).
void murmur3_u64_batch(const uint8_t* data, const int64_t* offsets, int64_t n,
                       uint32_t seed, uint64_t* out) {
  for (int64_t i = 0; i < n; i++) {
    out[i] = murmur3_u64(data + offsets[i], offsets[i + 1] - offsets[i], seed);
  }
}

}  // extern "C"
