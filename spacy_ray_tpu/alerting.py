"""In-process alert engine: declarative rules evaluated over telemetry
snapshots, with the Prometheus alerting state machine (inactive →
pending → firing → resolved) and the SRE-workbook multi-window
error-budget burn-rate rule as a first-class citizen.

PR 10 made every process *scrapeable* (``MetricsRegistry`` snapshots on
``/metrics``); nothing *watched* those numbers — an SLO breach was only
ever noticed by the autoscaler, and a silently-unscrapable replica just
incremented a counter. This module is the watcher. It is deliberately
in-process and stdlib-only (no Alertmanager dependency, jax-free): each
process — serving replica, fleet router, trainer — evaluates its OWN
rule set against its OWN snapshots on a slow cadence (seconds), and the
resulting alert state is exported everywhere the metrics already go:

* Prometheus series (``srt_alert_state{alert,severity}`` 0/1/2 and
  ``srt_alert_fired_total{alert}``) via :meth:`AlertEngine.add_prometheus`;
* the ``/admin/alerts`` endpoint (``AlertEngine.states()``);
* an ``alerts`` summary block in the ``/metrics`` JSON payload, which
  ``telemetry top`` renders as its alert column;
* a JSONL sink (one row per state transition — the durable record);
* ``resilience.log_event`` (so transitions land in the operator log);
* an ``on_firing`` hook the flight recorder uses to dump the last N
  seconds into an incident bundle (see :mod:`~spacy_ray_tpu.incidents`).

Three rule kinds (the issue's "burn rate, threshold, signal absence"):

* :class:`BurnRateRule` — multi-window error-budget burn rate in the
  Google SRE style: with an SLO of ``slo`` (say 0.99), the error budget
  is ``1 - slo``; the burn rate over a window is (observed error rate /
  budget). A ``(long_s, short_s, factor)`` window pair is breached when
  BOTH windows burn at ≥ ``factor`` — the long window proves the budget
  is really being spent, the short window proves it is STILL being
  spent (so the alert resolves promptly on recovery). Any breached pair
  activates the rule; a fast pair (high factor, short windows) pages on
  budget-exhausting incidents in minutes while a slow pair (low factor,
  long windows) catches smoldering burns.
* :class:`ThresholdRule` — an instantaneous snapshot value (or, with
  ``window_s``, a counter delta over the trailing window) compared
  against a bound.
* :class:`AbsenceRule` — fires when a counter STOPS MOVING for
  ``stale_s`` (a stalled training loop, a wedged dispatch thread): the
  failure mode where every threshold rule goes quiet exactly because
  the signal died.

Clock injection end to end: tests drive every window combination
deterministically with a fake clock, the same discipline as the
autoscaler and the canary guard.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from pathlib import Path
from typing import (
    Any, Callable, Dict, List, Optional, Sequence, Tuple, Union,
)

__all__ = [
    "SnapshotHistory",
    "AlertRule",
    "ThresholdRule",
    "AbsenceRule",
    "BurnRateRule",
    "AlertState",
    "AlertEngine",
    "STATE_VALUES",
    "DEFAULT_BURN_WINDOWS",
    "default_serving_rules",
    "default_router_rules",
    "default_training_rules",
    "process_rules",
]

# numeric encoding of the alert state for the Prometheus gauge — the
# same 0/1/2 convention Prometheus's own ALERTS series implies
STATE_VALUES = {"inactive": 0, "pending": 1, "firing": 2}

# (long_s, short_s, factor) pairs, SRE-workbook shape scaled to this
# repo's process lifetimes (a serving replica lives minutes-to-days, not
# the 30-day SLO month the book's 14.4x/6x factors assume): the fast
# pair pages when ~a quarter of the budget burns within a minute; the
# slow pair tickets a smolder that would exhaust the budget in tens of
# minutes. Both windows of a pair must burn — that is what makes the
# alert resolve quickly once the bleeding stops.
DEFAULT_BURN_WINDOWS: Tuple[Tuple[float, float, float], ...] = (
    (300.0, 60.0, 14.4),
    (1800.0, 300.0, 6.0),
)


def _lookup(snapshot: Optional[Dict[str, Any]], path: str) -> Optional[float]:
    """Dotted-path numeric lookup (``"counters.requests"``,
    ``"router.slo_window.request_latency_p99"``); None when any segment
    is missing or the leaf is not a number."""
    cur: Any = snapshot
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return float(cur) if isinstance(cur, (int, float)) else None


class SnapshotHistory:
    """Bounded time-series of the values the rules actually read.

    The engine does NOT retain whole registry snapshots (a burn-rate
    rule with a 30-minute window at a 2 s cadence would pin ~900 full
    histogram snapshots): at append time it extracts only the paths its
    rules reference, so each retained sample is a handful of floats.
    """

    def __init__(self, paths: Sequence[str], *, max_samples: int = 4096):
        self.paths = tuple(dict.fromkeys(paths))  # de-duped, order kept
        self._samples: "deque[Tuple[float, Dict[str, Optional[float]]]]" = (
            deque(maxlen=int(max_samples))
        )
        self._latest: Optional[Dict[str, Any]] = None

    def append(self, now: float, snapshot: Dict[str, Any]) -> None:
        self._latest = snapshot
        self._samples.append(
            (float(now), {p: _lookup(snapshot, p) for p in self.paths})
        )

    def __len__(self) -> int:
        return len(self._samples)

    def value(self, path: str) -> Optional[float]:
        """The path's value in the NEWEST snapshot (full-snapshot lookup,
        so threshold rules may read paths outside the extracted set)."""
        return _lookup(self._latest, path)

    def _at_or_before(self, t: float) -> Optional[Dict[str, Optional[float]]]:
        """Newest sample with timestamp <= t; None when history does not
        reach back that far (an honest no-signal, never a guess)."""
        found = None
        for ts, values in self._samples:
            if ts <= t:
                found = values
            else:
                break
        return found

    def span_s(self, now: float) -> float:
        """Seconds of history retained (0 when empty)."""
        if not self._samples:
            return 0.0
        return max(float(now) - self._samples[0][0], 0.0)

    def delta(
        self,
        path: str,
        window_s: float,
        now: float,
        *,
        allow_partial: bool = False,
    ) -> Optional[float]:
        """Counter increase over the trailing ``window_s``: newest value
        minus the value at (now - window_s). When the history does not
        reach back that far, None — unless ``allow_partial``, which
        falls back to the OLDEST sample: a count over a shorter span
        understates the window total, but a RATIO of two same-span
        partial deltas (the burn rate) is unbiased, and without it a
        process failing 100% of its requests from boot would be
        page-blind for its first ``window_s`` seconds."""
        if not self._samples:
            return None
        base = self._at_or_before(now - float(window_s))
        if base is None:
            if not allow_partial:
                return None
            base = self._samples[0][1]
        cur = self._samples[-1][1].get(path)
        if cur is None:
            return None
        prev = base.get(path)
        if prev is None:
            # the counter was born INSIDE the window (its instrument is
            # created lazily, after the base snapshot was taken): its
            # oldest observed value is the honest base. Without this, a
            # rule watching a lazily-created counter stays no-signal for
            # as long as the window reaches back past the birth — the
            # fleet divergence_flags counter hit exactly this.
            for _, values in self._samples:
                v = values.get(path)
                if v is not None:
                    prev = v
                    break
        if prev is None:
            return None
        # counter resets (process restart feeding one engine) clamp to 0
        return max(cur - prev, 0.0)


class AlertRule:
    """Base: name, severity, for-duration. Subclasses implement
    ``evaluate(history, now) -> (active, value, detail)`` where
    ``active`` is True/False, or None for "no signal" (not enough
    history / no traffic) — treated as not-active by the state machine
    but reported honestly in the detail string."""

    def __init__(
        self,
        name: str,
        *,
        severity: str = "page",
        for_s: float = 0.0,
        labels: Optional[Dict[str, str]] = None,
    ) -> None:
        self.name = str(name)
        self.severity = str(severity)
        self.for_s = float(for_s)
        self.labels = dict(labels or {})

    def paths(self) -> List[str]:
        """Snapshot paths this rule reads (what the history retains)."""
        return []

    def evaluate(
        self, history: SnapshotHistory, now: float
    ) -> Tuple[Optional[bool], Optional[float], str]:
        raise NotImplementedError


_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


class ThresholdRule(AlertRule):
    """``value(path) OP threshold`` — or, with ``window_s``, the
    counter's trailing-window increase compared against the bound (the
    scrape-failure rule: "this counter moved N times in the last W
    seconds" is an event-rate condition, not a level).

    ``arm_when=(op, value)`` keeps the rule no-signal until the path has
    EVER satisfied that precondition — the "it must have worked once
    before its absence is an incident" gate. The no-ready-replica rule
    uses it: during a fleet cold start every replica legitimately
    answers 503 "warming" for however long the bucket compile sweep
    takes (minutes), and paging on every clean boot would train
    operators to ignore the page that matters. Arming is persistent.

    ``partial=True`` (only meaningful with ``window_s``) judges the
    delta over however much history exists when the full window isn't
    retained yet — the same boot-blindness fix the burn rules carry: a
    partial-span count can only UNDERSTATE the window total, so a
    ``>=`` rule fires earlier but never spuriously. The
    fleet-worker-diverging rule uses it (a worker diverging in a run's
    first ``window_s`` must not be page-blind).
    """

    def __init__(
        self,
        name: str,
        path: str,
        op: str,
        threshold: float,
        *,
        window_s: Optional[float] = None,
        arm_when: Optional[Tuple[str, float]] = None,
        partial: bool = False,
        **kw: Any,
    ) -> None:
        super().__init__(name, **kw)
        if op not in _OPS:
            raise ValueError(f"op must be one of {sorted(_OPS)}, got {op!r}")
        self.path = str(path)
        self.op = op
        self.threshold = float(threshold)
        self.window_s = float(window_s) if window_s else None
        self.partial = bool(partial)
        if arm_when is not None and arm_when[0] not in _OPS:
            raise ValueError(
                f"arm_when op must be one of {sorted(_OPS)}, "
                f"got {arm_when[0]!r}"
            )
        self.arm_when = (
            (arm_when[0], float(arm_when[1])) if arm_when else None
        )
        self._armed = arm_when is None

    def paths(self) -> List[str]:
        return [self.path]

    def evaluate(
        self, history: SnapshotHistory, now: float
    ) -> Tuple[Optional[bool], Optional[float], str]:
        if self.window_s is not None:
            v = history.delta(
                self.path, self.window_s, now, allow_partial=self.partial
            )
            what = f"Δ{self.window_s:.0f}s({self.path})"
        else:
            v = history.value(self.path)
            what = self.path
        if v is None:
            return None, None, f"{what}: no signal"
        if not self._armed:
            op, bound = self.arm_when  # type: ignore[misc]
            if _OPS[op](v, bound):
                self._armed = True
            else:
                return None, v, (
                    f"{what} = {v:.6g}: not armed (never {op} {bound:g})"
                )
        active = _OPS[self.op](v, self.threshold)
        return active, v, f"{what} = {v:.6g} {self.op} {self.threshold:.6g}"


class AbsenceRule(AlertRule):
    """Fires when the watched counter has not CHANGED for ``stale_s`` —
    the signal-died failure mode. A path that was never observed at all
    is no-signal (the subsystem may simply not be running); staleness
    only starts counting once the signal has existed.

    ``arm_above``: stay no-signal until the value has EVER exceeded this
    bound — ThresholdRule's ``arm_when`` gate for the absence shape. The
    fleet push-stalled rule uses it: a topology that legitimately never
    pushes to peers (a fleet of one; peers that own no shards) exports a
    counter frozen at 0, and "it must have moved once before its freeze
    is an incident" is the difference between that and a wedged peer
    loop. Arming is persistent."""

    def __init__(
        self,
        name: str,
        path: str,
        stale_s: float,
        *,
        arm_above: Optional[float] = None,
        **kw: Any,
    ) -> None:
        super().__init__(name, **kw)
        self.path = str(path)
        self.stale_s = float(stale_s)
        self.arm_above = float(arm_above) if arm_above is not None else None
        self._armed = arm_above is None
        self._last_value: Optional[float] = None
        self._last_change: Optional[float] = None

    def paths(self) -> List[str]:
        return [self.path]

    def evaluate(
        self, history: SnapshotHistory, now: float
    ) -> Tuple[Optional[bool], Optional[float], str]:
        v = history.value(self.path)
        if not self._armed:
            if v is not None and v > self.arm_above:
                self._armed = True
            else:
                return None, v, (
                    f"{self.path}: not armed (never > {self.arm_above:g})"
                )
        if v is not None and v != self._last_value:
            self._last_value = v
            self._last_change = now
        if self._last_change is None:
            return None, None, f"{self.path}: never observed"
        age = now - self._last_change
        return (
            age >= self.stale_s,
            age,
            f"{self.path} unchanged for {age:.1f}s "
            f"(stale after {self.stale_s:.0f}s)",
        )


class BurnRateRule(AlertRule):
    """Multi-window error-budget burn rate (SRE workbook ch. 5).

    ``bad`` counters over ``total`` give the error rate; dividing by the
    budget ``1 - slo`` gives the burn rate (burn 1.0 = spending the
    budget exactly as fast as the SLO allows). A window pair activates
    when BOTH its long and short windows burn at ≥ ``factor``; the rule
    is active when ANY pair is. Zero traffic in a window is no-signal
    for that pair (no requests burn no budget), and the rule only
    reports no-signal when EVERY pair lacks signal.

    Early-life semantics: once the history spans a pair's SHORT window,
    its long-window burn is computed over whatever span exists (the
    ratio is unbiased; Prometheus ``increase()`` extrapolates the same
    way) — a replica failing everything from boot pages after
    ``short_s``, not after ``long_s`` of blindness. Before the short
    window is spanned the pair is no-signal.
    """

    def __init__(
        self,
        name: str,
        *,
        total: Union[str, Sequence[str]],
        bad: Union[str, Sequence[str]],
        slo: float = 0.99,
        windows: Sequence[Tuple[float, float, float]] = DEFAULT_BURN_WINDOWS,
        **kw: Any,
    ) -> None:
        super().__init__(name, **kw)
        if not 0.0 < slo < 1.0:
            raise ValueError(f"slo must be in (0, 1), got {slo}")
        # total may be a LIST summed like bad: when a telemetry surface
        # counts rejected work in separate counters that never reach the
        # main requests counter (a pre-admission 429 is still a request
        # the caller made), the denominator must include them or a
        # 100%-rejection outage reads as "no traffic, no burn"
        self.total = (
            [total] if isinstance(total, str) else [str(t) for t in total]
        )
        self.bad = [bad] if isinstance(bad, str) else [str(b) for b in bad]
        self.slo = float(slo)
        self.budget = 1.0 - self.slo
        self.windows = tuple(
            (float(l), float(s), float(f)) for l, s, f in windows
        )
        if not self.windows:
            raise ValueError("windows must name at least one pair")
        for long_s, short_s, factor in self.windows:
            if short_s > long_s:
                raise ValueError(
                    f"short window {short_s} exceeds long window {long_s}"
                )
            if factor <= 0:
                raise ValueError(f"factor must be > 0, got {factor}")

    def paths(self) -> List[str]:
        return [*self.total, *self.bad]

    def _burn(
        self, history: SnapshotHistory, window_s: float, now: float
    ) -> Optional[float]:
        d_total: Optional[float] = None
        for path in self.total:
            d = history.delta(path, window_s, now, allow_partial=True)
            if d is not None:
                d_total = (d_total or 0.0) + d
        if d_total is None or d_total <= 0:
            return None  # no traffic in the window: no burn signal
        d_bad = 0.0
        for path in self.bad:
            d = history.delta(path, window_s, now, allow_partial=True)
            if d is not None:
                d_bad += d
        return (d_bad / d_total) / self.budget

    def evaluate(
        self, history: SnapshotHistory, now: float
    ) -> Tuple[Optional[bool], Optional[float], str]:
        any_signal = False
        active = False
        worst: Optional[float] = None
        details: List[str] = []
        span = history.span_s(now)
        for long_s, short_s, factor in self.windows:
            if span < short_s:
                # too young to judge even the short window: one bad
                # request at tick 2 must not page anyone
                details.append(
                    f"{long_s:.0f}s/{short_s:.0f}s: no signal "
                    f"(history {span:.0f}s < {short_s:.0f}s)"
                )
                continue
            b_long = self._burn(history, long_s, now)
            b_short = self._burn(history, short_s, now)
            if b_long is None or b_short is None:
                details.append(f"{long_s:.0f}s/{short_s:.0f}s: no signal")
                continue
            any_signal = True
            pair_hit = b_long >= factor and b_short >= factor
            active = active or pair_hit
            candidate = min(b_long, b_short)  # the pair's binding burn
            if worst is None or candidate > worst:
                worst = candidate
            details.append(
                f"{long_s:.0f}s/{short_s:.0f}s: burn {b_long:.2f}/"
                f"{b_short:.2f} vs {factor:g}x"
            )
        if not any_signal:
            return None, None, "; ".join(details)
        return active, worst, "; ".join(details)


class AlertState:
    """One rule's live state: the Prometheus alerting lifecycle plus the
    bookkeeping the exports read."""

    __slots__ = (
        "state", "since", "value", "detail", "fired_count",
        "last_transition", "last_fired", "last_resolved",
    )

    def __init__(self) -> None:
        self.state = "inactive"
        self.since: Optional[float] = None
        self.value: Optional[float] = None
        self.detail = ""
        self.fired_count = 0
        self.last_transition: Optional[float] = None
        self.last_fired: Optional[float] = None
        self.last_resolved: Optional[float] = None


class AlertEngine:
    """Evaluate a rule set against a stream of snapshots; hold per-rule
    state machines; export and emit transitions.

    ``evaluate(snapshot)`` is the one entry point — the owning process's
    observer ticker (serving replica / fleet router) or the training
    loop's rate-limited boundary hook calls it every few seconds. With
    telemetry disabled the engine is never constructed at all (the
    repo-wide zero-calls contract, guard-tested).
    """

    def __init__(
        self,
        rules: Sequence[AlertRule],
        *,
        clock: Callable[[], float] = time.monotonic,
        unix: Callable[[], float] = time.time,
        sink_path: Optional[Path] = None,
        on_firing: Optional[Callable[[AlertRule, AlertState], Any]] = None,
        max_samples: int = 4096,
        source: str = "",
    ) -> None:
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {sorted(names)}")
        self.rules = list(rules)
        self.clock = clock
        self.unix = unix
        self.sink_path = Path(sink_path) if sink_path is not None else None
        self.on_firing = on_firing
        self.source = str(source)
        self.history = SnapshotHistory(
            [p for r in self.rules for p in r.paths()],
            max_samples=max_samples,
        )
        self._states: Dict[str, AlertState] = {
            r.name: AlertState() for r in self.rules
        }
        self._lock = threading.Lock()
        self.evaluations = 0
        self.transitions = 0

    # -- evaluation ----------------------------------------------------
    def evaluate(self, snapshot: Dict[str, Any]) -> List[str]:
        """One pass over every rule; returns the names of rules that
        TRANSITIONED this pass (diagnostic convenience for tests)."""
        now = self.clock()
        changed: List[str] = []
        fired: List[Tuple[AlertRule, AlertState]] = []
        emits: List[Tuple[AlertRule, str, str, bool, Any, str]] = []
        with self._lock:
            self.evaluations += 1
            self.history.append(now, snapshot)
            for rule in self.rules:
                st = self._states[rule.name]
                active, value, detail = rule.evaluate(self.history, now)
                st.value = value
                st.detail = detail
                if active:
                    if st.state == "inactive":
                        if rule.for_s > 0:
                            self._transition(rule, st, "pending", now, emits)
                            changed.append(rule.name)
                        else:
                            self._transition(rule, st, "firing", now, emits)
                            changed.append(rule.name)
                            fired.append((rule, st))
                    elif (
                        st.state == "pending"
                        and st.since is not None
                        and now - st.since >= rule.for_s
                    ):
                        self._transition(rule, st, "firing", now, emits)
                        changed.append(rule.name)
                        fired.append((rule, st))
                else:
                    # not-active AND no-signal both resolve: an alert
                    # held open on a dead signal would never page anyone
                    # about the right thing (AbsenceRule exists for the
                    # dead-signal case itself)
                    if st.state in ("pending", "firing"):
                        self._transition(rule, st, "inactive", now, emits)
                        changed.append(rule.name)
        # emission (sink-file I/O, log_event) and hooks run OUTSIDE the
        # engine lock: a slow disk under the sink, or the flight
        # recorder re-entering states()/summary(), must never stall the
        # /metrics and /admin/alerts readers that share this lock
        for rule, old, new, resolved, value, detail in emits:
            self._emit(rule, old, new, resolved, value, detail)
        for rule, st in fired:
            if self.on_firing is not None:
                try:
                    self.on_firing(rule, st)
                except Exception:
                    pass  # an incident dump must never break evaluation
        return changed

    def _transition(
        self,
        rule: AlertRule,
        st: AlertState,
        new: str,
        now: float,
        emits: List[Tuple[AlertRule, str, str, bool, Any, str]],
    ) -> None:
        old = st.state
        st.state = new
        st.since = now
        st.last_transition = now
        self.transitions += 1
        if new == "firing":
            st.fired_count += 1
            st.last_fired = now
        resolved = old == "firing" and new == "inactive"
        if resolved:
            st.last_resolved = now
        # the emit payload is captured NOW (st can be re-evaluated by a
        # racing pass once the lock drops); the I/O happens after release
        emits.append((rule, old, new, resolved, st.value, st.detail))

    def _emit(
        self,
        rule: AlertRule,
        old: str,
        new: str,
        resolved: bool,
        value: Any,
        detail: str,
    ) -> None:
        event = "alert-resolved" if resolved else f"alert-{new}"
        row = {
            "kind": "alert",
            "alert": rule.name,
            "severity": rule.severity,
            "from": old,
            "to": new,
            "value": value,
            "detail": detail,
            "unix_time": round(self.unix(), 3),
        }
        if self.source:
            row["source"] = self.source
        if rule.labels:
            row["labels"] = dict(rule.labels)
        try:
            from .training.resilience import log_event

            log_event(
                event,
                f"{rule.name} [{rule.severity}] {old} -> {new}: {detail}",
                alert=rule.name,
                severity=rule.severity,
                value=value,
            )
        except Exception:
            pass
        if self.sink_path is not None:
            try:
                self.sink_path.parent.mkdir(parents=True, exist_ok=True)
                with open(self.sink_path, "a", encoding="utf8") as f:
                    f.write(json.dumps(row, default=str) + "\n")
            except OSError:
                pass  # a full disk must not take the serving path down

    # -- exports -------------------------------------------------------
    def states(self) -> List[Dict[str, Any]]:
        """The ``/admin/alerts`` payload: one row per rule, firing
        first, then pending, then inactive (each alphabetical)."""
        with self._lock:
            rows = [
                {
                    "alert": rule.name,
                    "severity": rule.severity,
                    "state": st.state,
                    "since": st.since,
                    "value": st.value,
                    "detail": st.detail,
                    "fired_count": st.fired_count,
                    "last_resolved": st.last_resolved,
                    **({"labels": dict(rule.labels)} if rule.labels else {}),
                }
                for rule in self.rules
                for st in (self._states[rule.name],)
            ]
        rows.sort(
            key=lambda r: (-STATE_VALUES[r["state"]], r["alert"])
        )
        return rows

    def summary(self) -> Dict[str, Any]:
        """The compact block the ``/metrics`` JSON payload carries (and
        ``telemetry top`` renders): counts plus the firing names."""
        with self._lock:
            states = {
                name: st.state for name, st in self._states.items()
            }
        firing = sorted(n for n, s in states.items() if s == "firing")
        pending = sorted(n for n, s in states.items() if s == "pending")
        return {
            "rules": len(states),
            "firing": len(firing),
            "pending": len(pending),
            "firing_names": firing,
            "pending_names": pending,
        }

    def add_prometheus(self, fam: Any) -> None:
        """Append the alert series to a ``PromFamilies``: state gauge
        (0 inactive / 1 pending / 2 firing) and fired-count counter,
        labeled by alert name and severity."""
        with self._lock:
            rows = [
                (rule, self._states[rule.name]) for rule in self.rules
            ]
        for rule, st in rows:
            labels = {"alert": rule.name, "severity": rule.severity}
            fam.add(
                "srt_alert_state", "gauge", STATE_VALUES[st.state], labels
            )
            fam.add(
                "srt_alert_fired_total", "counter", st.fired_count,
                {"alert": rule.name},
            )


# ----------------------------------------------------------------------
# Default rule sets (documented in docs/OBSERVABILITY.md)
# ----------------------------------------------------------------------


def process_rules(
    *,
    rss_growth_bytes: float = 256 * 1024 * 1024,
    rss_window_s: float = 600.0,
    fd_limit: float = 512.0,
    fd_for_s: float = 60.0,
) -> List[AlertRule]:
    """The host-resource leak detectors every role set carries, reading
    the ``process`` block hoststats injects into each role's alert
    snapshot (docs/OBSERVABILITY.md "Host resources & the run ledger"):

    * ``process-rss-growth`` — NET RSS growth beyond
      ``rss_growth_bytes`` inside the trailing ``rss_window_s``. The
      windowed delta clamps decreases to zero, so a sawtooth allocator
      that keeps returning memory stays quiet while a monotone leak
      accumulates; no ``partial``, so a process younger than the window
      is no-signal — a short-lived CLI run can't page.
    * ``process-fd-leak`` — open fds above ``fd_limit`` held for
      ``fd_for_s``, ARMED only after the process has been seen healthy
      (fd count at or below half the limit): a deliberately fd-hungry
      deployment that BOOTS above the gate never arms (that's its
      normal, not a leak), short-lived processes rarely live long
      enough to arm-then-breach, and a missing ``/proc`` surface is
      plain no-signal.

    Both are tickets, not pages: a leak is a trend to fix this week,
    not an outage to wake someone for — the watchdog and the burn rules
    own the acute failure modes.
    """
    return [
        ThresholdRule(
            "process-rss-growth",
            "process.rss_bytes",
            ">=",
            float(rss_growth_bytes),
            window_s=float(rss_window_s),
            severity="ticket",
        ),
        ThresholdRule(
            "process-fd-leak",
            "process.open_fds",
            ">",
            float(fd_limit),
            arm_when=("<=", float(fd_limit) / 2.0),
            for_s=float(fd_for_s),
            severity="ticket",
        ),
    ]


def default_serving_rules(
    *,
    p99_target_s: float = 0.5,
    slo: float = 0.99,
    windows: Sequence[Tuple[float, float, float]] = DEFAULT_BURN_WINDOWS,
) -> List[AlertRule]:
    """A serving replica's defaults, evaluated over its own
    ``ServingTelemetry.snapshot()``: the request-success error budget
    (typed rejects + errors over requests), the sliding-window p99
    against the SLO target, and the host-resource leak detectors
    (:func:`process_rules`)."""
    return [
        BurnRateRule(
            "serving-error-budget-burn",
            # `requests` only counts ADMITTED requests; queue-full 429s
            # are rejected BEFORE admission and land only in their own
            # counter — the denominator must include them, or a replica
            # rejecting 100% of its traffic would read as "no traffic,
            # no burn" and the page would sleep through the outage
            total=[
                "counters.requests",
                "counters.rejected_queue_full",
            ],
            bad=[
                "counters.errors",
                "counters.deadline_exceeded",
                "counters.rejected_queue_full",
            ],
            slo=slo,
            windows=windows,
            severity="page",
        ),
        ThresholdRule(
            "serving-latency-slo",
            "slo_window.request_latency_p99",
            ">",
            float(p99_target_s),
            for_s=30.0,
            severity="page",
        ),
    ] + process_rules()


def default_router_rules(
    *,
    p99_target_s: float = 0.5,
    slo: float = 0.99,
    windows: Sequence[Tuple[float, float, float]] = DEFAULT_BURN_WINDOWS,
) -> List[AlertRule]:
    """The fleet router's defaults, evaluated over the composite
    ``{"router": RouterTelemetry.snapshot(), "replicas": [...]}``
    snapshot the fleet observer builds every tick."""
    return [
        # shed requests ARE the error budget at the fleet edge: a 503
        # no_replica storm is the fleet-down signal
        BurnRateRule(
            "fleet-reject-burn",
            total="router.counters.requests",
            bad=[
                "router.counters.rejected_no_replica",
                "router.counters.rejected_draining",
            ],
            slo=slo,
            windows=windows,
            severity="page",
        ),
        # armed only once the fleet has been ready at least once: a cold
        # start's minutes-long warmup (every replica 503 "warming") is a
        # boot, not an outage — paging on every clean start would bury
        # the real one
        ThresholdRule(
            "no-ready-replica",
            "router.gauges.ready_replicas",
            "<",
            1.0,
            for_s=10.0,
            arm_when=(">=", 1.0),
            severity="page",
        ),
        # the PR 10 satellite grown into a page: a READY replica whose
        # /metrics scrape keeps failing is an observability hole exactly
        # where an SLO breach would hide — 3+ failed scrapes inside two
        # minutes is a pattern, not a blip
        ThresholdRule(
            "replica-unscrapable",
            "router.counters.scrape_failures",
            ">=",
            3.0,
            window_s=120.0,
            for_s=0.0,
            severity="page",
        ),
        ThresholdRule(
            "fleet-latency-slo",
            "router.slo.router_latency_p99",
            ">",
            float(p99_target_s),
            for_s=30.0,
            severity="page",
        ),
        # the router's own host truth rides the composite snapshot at
        # top level (fleet.observe_tick), same dotted paths as the
        # other roles
    ] + process_rules()


def default_training_rules(
    *,
    stall_s: float = 300.0,
    anomaly_burst: int = 5,
    fleet: bool = False,
    push_stall_s: float = 120.0,
    discard_rate: float = 0.30,
    discard_window_s: float = 120.0,
) -> List[AlertRule]:
    """The trainer's defaults, evaluated over its registry snapshot at
    (rate-limited) step boundaries: a stalled step counter — the
    watchdog's signal, visible BEFORE the watchdog's hard exit — and an
    anomaly-detector burst.

    ``fleet=True`` (each trainer-fleet worker's engine) adds the async
    plane's two failure modes:

    * ``fleet-grad-push-stalled`` — this worker's grad-push counter
      stopped moving: a wedged peer loop pages on wall time BEFORE the
      watchdog's rc-79 hard exit (the same before-the-watchdog
      discipline as training-stalled, but on the fleet's own signal —
      a worker can be stepping-by-the-clock yet pushing nothing when
      its peers are gone).
    * ``fleet-discard-burn`` — the stale-gradient discard RATE burns
      past ``discard_rate`` (default >30% of received gradients
      discarded inside ``discard_window_s``): the quorum/staleness
      knobs are mis-set for this fleet's speed skew, and most of the
      compute is being thrown away. Expressed as a single-pair
      burn-rate rule (the ratio machinery) with budget ``discard_rate``
      and factor 1.0 — burn ≥ 1 ⟺ discards/received ≥ the threshold.
    * ``fleet-worker-diverging`` — the lead's cross-worker convergence
      watch (``FleetDivergenceDetector``: loss z-outlier vs the peer
      median, NaN training, one-worker discard outlier) flagged a
      worker inside the trailing window. Only the lead's
      ``divergence_flags`` counter ever moves, so the rule is silent on
      every other worker's engine; the flag's anomaly row + incident
      bundle name the diverging worker.
    * ``fleet-owner-evicted`` — the lease verdict fired: the acting
      lead declared a peer dead and bumped the membership epoch
      (RESILIENCE.md "Ownership failover"). Training continues on the
      survivors by design, but an eviction is capacity loss plus an
      optimizer-moment restore on every re-sharded slice — a human
      should know within the window. Only the acting lead's
      ``evictions`` counter moves (``partial=True`` keeps the other
      engines silent); the eviction's structured event and the
      ``fleet-membership.jsonl`` ledger row name the evicted worker.
    """
    rules: List[AlertRule] = [
        AbsenceRule(
            "training-stalled",
            "counters.steps",
            stale_s=float(stall_s),
            severity="page",
        ),
        ThresholdRule(
            "anomaly-burst",
            "counters.anomalies",
            ">=",
            float(anomaly_burst),
            window_s=600.0,
            severity="ticket",
        ),
    ]
    if fleet:
        rules.extend(
            [
                AbsenceRule(
                    "fleet-grad-push-stalled",
                    "counters.grad_pushed",
                    stale_s=float(push_stall_s),
                    # counts PEER deliveries only (self-submit excluded —
                    # worker.py), so a frozen value means this worker
                    # stopped talking to its fleet; arm_above keeps a
                    # topology that never pushes (fleet of one) silent
                    arm_above=0.0,
                    severity="page",
                ),
                BurnRateRule(
                    "fleet-discard-burn",
                    total=["counters.grad_received"],
                    bad=["counters.grad_discarded"],
                    slo=1.0 - float(discard_rate),
                    windows=(
                        (
                            float(discard_window_s),
                            float(discard_window_s) / 4.0,
                            1.0,
                        ),
                    ),
                    severity="page",
                ),
                ThresholdRule(
                    "fleet-worker-diverging",
                    "counters.divergence_flags",
                    ">=",
                    1.0,
                    window_s=600.0,
                    for_s=0.0,
                    partial=True,
                    severity="page",
                ),
                ThresholdRule(
                    "fleet-owner-evicted",
                    "counters.evictions",
                    ">=",
                    1.0,
                    window_s=600.0,
                    for_s=0.0,
                    partial=True,
                    severity="page",
                ),
            ]
        )
    rules.extend(process_rules())
    return rules
