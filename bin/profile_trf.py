"""Profile the trf train step: MFU-vs-shape sweep + per-op-class breakdown.

VERDICT r4 weak #2 / next #3: "trf MFU ~0.04 is unexplained ... no per-op
profile of the trf step exists and no MFU-vs-shape sweep shows where
utilization goes." This tool produces both, reusing bench.py's exact
pipeline/step construction and MFU accounting so its numbers are directly
comparable to BENCH_SESSION.jsonl records:

  python bin/profile_trf.py --sweep             # MFU vs (B, T) table
  python bin/profile_trf.py --trace --B 4 --T 32  # op-class time breakdown

The breakdown parses the jax.profiler Chrome trace (CPU backend emits one
event per HLO op / fusion) and buckets op time into matmul (dot/conv),
gather/scatter, reduce, and elementwise/fusion classes — the direct answer
to "is the missing time in matmuls-too-small, or in non-MXU ops?".

Output: one JSON line per measurement (committed analysis lives in
PERF.md §MFU).
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import numpy as np


def build_step(spec_name: str, B: int, T: int, compute_dtype: str = "auto",
               fused: bool = False, shadow: bool = False):
    """Build (update, state...) exactly as bench.run_one does. ``fused``
    swaps the optax chain for the fused update (ops/fused_update.py);
    ``shadow`` enables the bf16 parameter shadow (needs a bf16-compute
    trunk — pin ``compute_dtype="bfloat16"`` on CPU)."""
    import jax

    import bench
    from spacy_ray_tpu.config import Config
    from spacy_ray_tpu.parallel.mesh import build_mesh
    from spacy_ray_tpu.parallel.step import (
        make_train_step,
        place_batch,
        place_replicated,
        shard_opt_state,
    )
    from spacy_ray_tpu.pipeline.language import Pipeline
    from spacy_ray_tpu.registry import registry

    spec = {s["name"]: s for s in bench._configs("cpu")}[spec_name]
    cfg_text = spec["cfg"]
    if compute_dtype != "auto":
        # pin the trunk's matmul dtype (e.g. to reproduce the pre-round-5
        # bf16-on-CPU traces now that "auto" resolves to f32 there)
        anchor = '@architectures = "spacy_ray_tpu.TransformerEncoder.v1"'
        assert anchor in cfg_text, f"{spec_name} has no transformer trunk"
        cfg_text = cfg_text.replace(
            anchor, f'{anchor}\ncompute_dtype = "{compute_dtype}"'
        )
    nlp = Pipeline.from_config(Config.from_str(cfg_text))
    # same corpus size as bench.run_one: the label inventory (and so the
    # head params + program flops) must match BENCH_SESSION.jsonl records
    examples = bench._corpus(spec["kinds"], max(2 * B, 512))
    nlp.initialize(lambda: iter(examples), seed=0)
    mesh = build_mesh(n_data=1)
    tx = registry.get("optimizers", "Adam.v1")(learn_rate=0.001)
    if fused:
        from spacy_ray_tpu.training.optimizers import fuse_optimizer

        tx = fuse_optimizer(tx)
    params = place_replicated(nlp.params, mesh)
    opt_state = shard_opt_state(tx.init(params), mesh, zero1=False)
    shadow_tree = None
    if shadow:
        from spacy_ray_tpu.models.transformer import (
            build_param_shadow,
            pipeline_shadow_dtype,
        )

        sdt = pipeline_shadow_dtype(nlp)
        assert sdt is not None, (
            '--shadow needs a bf16-compute trunk: add --compute-dtype bfloat16'
        )
        shadow_tree = build_param_shadow(params, sdt)
    update = make_train_step(
        nlp.make_loss_fn(), tx, mesh, opt_state_template=opt_state,
        shadow=shadow_tree is not None,
    )
    batch = nlp.collate(examples[:B], pad_batch_to=B, pad_len_to=T)
    tokens = place_batch(batch["tokens"], mesh)
    targets = place_batch(batch["targets"], mesh)
    n_params = int(
        sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
    )
    return (update, params, opt_state, shadow_tree, tokens, targets, n_params,
            int(batch["n_words"]))


def _make_stepper(update, state):
    """state = {"params", "opt", "shadow"}; returns step(tokens, targets,
    sub) -> loss, carrying state through whichever update signature."""

    def step(tokens, targets, sub):
        if state["shadow"] is not None:
            (state["params"], state["opt"], state["shadow"], loss, _) = update(
                state["params"], state["opt"], state["shadow"], tokens,
                targets, sub,
            )
        else:
            state["params"], state["opt"], loss, _ = update(
                state["params"], state["opt"], tokens, targets, sub
            )
        return loss

    return step


def measure(spec_name: str, B: int, T: int, steps: int, reps: int,
            compute_dtype: str = "auto", fused: bool = False,
            shadow: bool = False):
    import jax

    import bench

    (update, params, opt_state, shadow_tree, tokens, targets, n_params,
     n_words) = build_step(spec_name, B, T, compute_dtype, fused, shadow)
    rng = jax.random.PRNGKey(0)
    flops_args = (
        (params, opt_state, shadow_tree, tokens, targets, rng)
        if shadow_tree is not None
        else (params, opt_state, tokens, targets, rng)
    )
    flops, flops_kind = bench._program_flops(update, flops_args, n_params, B * T)
    peak, peak_kind = bench._peak_flops_per_chip("cpu")

    state = {"params": params, "opt": opt_state, "shadow": shadow_tree}
    step_fn = _make_stepper(update, state)
    t0 = time.perf_counter()
    rng, sub = jax.random.split(rng)
    loss = step_fn(tokens, targets, sub)
    jax.block_until_ready(loss)
    compile_seconds = time.perf_counter() - t0

    rep_secs = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(steps):
            rng, sub = jax.random.split(rng)
            loss = step_fn(tokens, targets, sub)
        jax.block_until_ready(loss)
        rep_secs.append((time.perf_counter() - t0) / steps)
    step_seconds = float(np.median(rep_secs))
    return {
        "name": spec_name,
        "B": B,
        "T": T,
        "compute_dtype": compute_dtype,
        "fused_update": bool(fused),
        "param_shadow": bool(shadow),
        "tokens_per_step": B * T,
        "n_params": n_params,
        "words_per_step": n_words,
        "compile_seconds": round(compile_seconds, 1),
        "step_seconds": round(step_seconds, 4),
        "step_seconds_min": round(min(rep_secs), 4),
        "step_seconds_max": round(max(rep_secs), 4),
        "n_reps": reps,
        "steps_per_rep": steps,
        "flops_per_step": flops,
        "flops_kind": flops_kind,
        "wps": round(n_words / step_seconds, 1),
        "mfu": round(flops / step_seconds / peak, 5),
        "peak_tflops": round(peak / 1e12, 3),
        "peak_kind": peak_kind,
        "state": (update, state, tokens, targets),
    }


# HLO-op event classification: ordered substring rules, first match wins.
# "cast" must precede "matmul": a bare "conv" pattern would swallow
# "convert" ops and overstate the matmul share (the exact number this
# tool exists to get right).
OP_CLASSES = [
    ("cast", ("convert", "bitcast_convert")),
    ("matmul", ("dot_general", "dot.", "dot", "convolution")),
    ("gather_scatter", ("gather", "scatter", "dynamic-slice", "dynamic_slice",
                        "dynamic-update", "dynamic_update")),
    ("reduce", ("reduce", "sort", "top-k", "topk", "cumsum")),
    ("rng", ("rng", "threefry", "bit_generator", "erf_inv")),
    ("transpose_copy", ("transpose", "copy", "concatenate", "reshape",
                        "broadcast.", "slice", "pad")),
]


def classify(name: str) -> str:
    low = name.lower()
    for cls, pats in OP_CLASSES:
        if any(p in low for p in pats):
            return cls
    return "elementwise_fusion"


def _is_hlo_event(name: str) -> bool:
    if name.startswith("$") or name.startswith("#"):
        return False  # python / metadata tracks
    for prefix in ("Pjit", "PjRt", "Thunk", "XlaModule", "process_", "Intra",
                   "EventLoop", "Queue", "run_", "block_until", "try_to_block"):
        if name.startswith(prefix):
            return False
    return True


def trace_breakdown(meas: dict, steps: int) -> dict:
    """Capture a jax.profiler trace of `steps` steps and bucket HLO-op time
    by class. Returns {class: seconds} plus coverage stats."""
    import jax

    update, state, tokens, targets = meas["state"]
    step_fn = _make_stepper(update, state)
    rng = jax.random.PRNGKey(1)
    trace_dir = tempfile.mkdtemp(prefix="trf_trace_")
    with jax.profiler.trace(trace_dir):
        for _ in range(steps):
            rng, sub = jax.random.split(rng)
            loss = step_fn(tokens, targets, sub)
        jax.block_until_ready(loss)
    files = glob.glob(trace_dir + "/**/*.trace.json.gz", recursive=True)
    if not files:
        return {"error": f"no trace produced under {trace_dir}"}
    events = json.loads(gzip.open(files[0]).read()).get("traceEvents", [])
    by_class: dict = {}
    by_op: dict = {}
    for e in events:
        name = e.get("name", "")
        if e.get("ph") != "X" or "dur" not in e or not _is_hlo_event(name):
            continue
        cls = classify(name)
        by_class[cls] = by_class.get(cls, 0.0) + e["dur"]
        key = name.split(".")[0]
        by_op[key] = by_op.get(key, 0.0) + e["dur"]
    total = sum(by_class.values())
    wall = meas["step_seconds"] * steps
    top_ops = sorted(by_op.items(), key=lambda kv: -kv[1])[:12]
    return {
        "trace_dir": trace_dir,
        "steps_traced": steps,
        "wall_seconds": round(wall, 3),
        "op_seconds_total": round(total / 1e6, 3),
        # op events are per-thread; XLA CPU runs ops on a thread pool, so
        # op_seconds_total can exceed wall (parallelism) or undershoot it
        # (untraced host gaps) — the CLASS SHARES are the signal here
        "class_share": {
            k: round(v / total, 4)
            for k, v in sorted(by_class.items(), key=lambda kv: -kv[1])
        },
        "class_seconds": {
            k: round(v / 1e6, 3)
            for k, v in sorted(by_class.items(), key=lambda kv: -kv[1])
        },
        "top_ops_seconds": {k: round(v / 1e6, 3) for k, v in top_ops},
    }


def load_records(path: Path) -> list:
    """One JSON object per line (this tool's own output format)."""
    return [
        json.loads(line)
        for line in Path(path).read_text(encoding="utf8").splitlines()
        if line.strip()
    ]


def compare(before_path: Path, after_path: Path) -> None:
    """``--compare before.json after.json``: per-op-class share/seconds
    delta table between two --trace runs, matched by (config, B, T). The
    PERF.md round-7 op-class evidence is this table, not hand math."""
    before = {(r["name"], r["B"], r["T"]): r for r in load_records(before_path)}
    after = {(r["name"], r["B"], r["T"]): r for r in load_records(after_path)}
    for key in sorted(set(before) & set(after)):
        b, a = before[key], after[key]
        name, B, T = key
        print(f"\n## {name} B={B} T={T}")
        print(
            f"step_seconds: {b['step_seconds']} -> {a['step_seconds']} "
            f"({(a['step_seconds'] / b['step_seconds'] - 1) * 100:+.1f}%)  "
            f"[before: fused={b.get('fused_update')} shadow={b.get('param_shadow')} "
            f"dtype={b.get('compute_dtype')}; after: fused={a.get('fused_update')} "
            f"shadow={a.get('param_shadow')} dtype={a.get('compute_dtype')}]"
        )
        bb = (b.get("breakdown") or {})
        ab = (a.get("breakdown") or {})
        if "class_share" not in bb or "class_share" not in ab:
            print("(no --trace breakdown on one side; shares skipped)")
            continue
        classes = sorted(
            set(bb["class_share"]) | set(ab["class_share"]),
            key=lambda c: -(bb["class_share"].get(c, 0.0)),
        )
        print(f"{'class':<20}{'before':>10}{'after':>10}{'Δshare':>10}"
              f"{'before s':>10}{'after s':>10}")
        for c in classes:
            bs = bb["class_share"].get(c, 0.0)
            as_ = ab["class_share"].get(c, 0.0)
            print(
                f"{c:<20}{bs:>10.1%}{as_:>10.1%}{as_ - bs:>+10.1%}"
                f"{bb['class_seconds'].get(c, 0.0):>10.3f}"
                f"{ab['class_seconds'].get(c, 0.0):>10.3f}"
            )
    missing = set(before) ^ set(after)
    if missing:
        print(f"\n# unmatched (config, B, T) keys skipped: {sorted(missing)}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="trf",
                    help="bench config name (trf, trf_tagger, sm_pipeline, ...)")
    ap.add_argument("--B", type=int, default=4)
    ap.add_argument("--T", type=int, default=32)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--sweep", action="store_true",
                    help="MFU vs (B,T) over an ascending shape ladder")
    ap.add_argument("--trace", action="store_true",
                    help="capture a jax.profiler trace and print the "
                    "per-op-class time breakdown")
    ap.add_argument("--compute-dtype", default="auto",
                    choices=["auto", "bfloat16", "float32"],
                    help="pin the trunk matmul dtype (auto = platform "
                    "default: bf16 on accelerators, f32 on CPU)")
    ap.add_argument("--fused", action="store_true",
                    help="use the fused optimizer update "
                    "(ops/fused_update.py) instead of the optax chain")
    ap.add_argument("--shadow", action="store_true",
                    help="enable the bf16 parameter shadow (pair with "
                    "--compute-dtype bfloat16 on CPU)")
    ap.add_argument("--compare", nargs=2, metavar=("BEFORE", "AFTER"),
                    type=Path, default=None,
                    help="two files of this tool's JSON lines: print the "
                    "per-op-class share delta table (PERF.md evidence)")
    args = ap.parse_args()

    if args.compare is not None:
        compare(args.compare[0], args.compare[1])
        return

    import jax

    jax.config.update("jax_platforms", "cpu")

    shapes = (
        [(2, 32), (4, 32), (8, 64), (16, 128), (32, 128)]
        if args.sweep else [(args.B, args.T)]
    )
    for B, T in shapes:
        meas = measure(args.config, B, T, args.steps, args.reps,
                       args.compute_dtype, fused=args.fused,
                       shadow=args.shadow)
        out = {k: v for k, v in meas.items() if k != "state"}
        if args.trace:
            out["breakdown"] = trace_breakdown(meas, max(2, args.steps // 2))
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
