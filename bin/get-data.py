#!/usr/bin/env python
"""Produce a training corpus (the reference's bin/get-data.sh role,
reference bin/get-data.sh:1-13: download NER jsonl + `spacy convert`).

This environment is zero-egress, so instead of downloading, this generates
the synthetic corpora used by tests/bench, or converts a local jsonl/conllu
file into the binary corpus format.

Usage:
  python bin/get-data.py synth <out_dir> [--kind tagger|parser|ner|textcat|spancat] [--n 1000]
  python bin/get-data.py convert <in.jsonl|in.conllu> <out.msgdoc>
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))


def main() -> int:
    parser = argparse.ArgumentParser()
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_synth = sub.add_parser("synth")
    p_synth.add_argument("out_dir", type=Path)
    p_synth.add_argument("--kind", default="tagger")
    p_synth.add_argument("--n", type=int, default=1000)
    p_conv = sub.add_parser("convert")
    p_conv.add_argument("input_path", type=Path)
    p_conv.add_argument("output_path", type=Path)
    args = parser.parse_args()

    if args.cmd == "synth":
        from spacy_ray_tpu.util import write_synth_jsonl

        args.out_dir.mkdir(parents=True, exist_ok=True)
        write_synth_jsonl(args.out_dir / "train.jsonl", args.n, kind=args.kind, seed=0)
        write_synth_jsonl(args.out_dir / "dev.jsonl", max(args.n // 5, 20), kind=args.kind, seed=1)
        print(f"Wrote {args.kind} corpus to {args.out_dir}/(train|dev).jsonl")
    else:
        from spacy_ray_tpu.cli import convert_command

        return convert_command([str(args.input_path), str(args.output_path)])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
