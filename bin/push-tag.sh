#!/usr/bin/env bash
# Tag a release from the version in pyproject.toml and push the tag
# (the reference's bin/push-tag.sh:1-14 role, reading setup.cfg there).
set -euo pipefail
version=$(grep -m1 '^version' pyproject.toml | sed 's/.*"\(.*\)"/\1/')
git tag "v${version}"
git push origin "v${version}"
echo "pushed tag v${version}"
