# Convenience targets; the canonical commands live in README.md / PERF.md.

.PHONY: test test-fast test-slow resilience telemetry observability serving fleet multi-model live train-fleet train-fleet-obs train-fleet-chaos bench bench-gate baseline profile step-perf serve-perf serve-perf3 update-shard dryrun

test:
	python -m pytest tests/ -q

test-fast:
	python -m pytest tests/ -q -m "not slow"

test-slow:
	python -m pytest tests/ -q -m slow

# fault-injection / checkpoint-fallback / watchdog suite (docs/RESILIENCE.md)
resilience:
	python -m pytest tests/test_resilience.py tests/test_checkpoint_fallback.py -q

# telemetry suite: trace validity, registry thread-safety, anomaly
# detectors, the telemetry-enabled smoke train (docs/OBSERVABILITY.md)
telemetry:
	python -m pytest tests/test_telemetry.py -q

# cross-process observability plane (docs/OBSERVABILITY.md): Prometheus
# exposition golden-format + bucket merge, request-id propagation +
# concurrent-load header equality, trace-collector clock-anchor merge,
# slow-request exemplars, trainer /metrics endpoint, `telemetry top`,
# serving-row summarize — plus the PR 12 diagnosis layer (alert engine
# burn-rate/threshold/absence matrix under a fake clock, flight
# recorder, crash bundles, `telemetry postmortem`) — then the real-fleet
# acceptance pair: the sigterm test (one request's spans across router +
# replica tracks in one merged Perfetto file) and the sigkill test (a
# killed replica's crash postmortem bundle)
observability:
	JAX_PLATFORMS=cpu python -m pytest tests/test_observability.py tests/test_telemetry.py tests/test_alerting.py tests/test_incidents.py -q -m "not slow"
	JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q -k "sigterm or sigkill"

# online-serving suite: batcher/engine/HTTP correctness under load,
# SIGTERM graceful drain, SLO telemetry, bench records (docs/SERVING.md);
# the heavy open-loop load variant is slow-marked and excluded here
serving:
	JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py -q -m "not slow"

# multi-replica fleet suite: router balancing/health/retry, response
# cache, metrics aggregation, supervisor restarts, autoscaler hysteresis,
# whole-fleet SIGTERM drain (docs/SERVING.md "Fleet"); the real-load
# crash-recovery and bench-record variants are slow-marked and excluded
fleet:
	JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q -m "not slow"

# multi-tenant multi-model suite (docs/SERVING.md "Multi-model fleet",
# docs/TUNING.md §23): manifest registry + path/header routing matrix,
# WFQ weight-ratio convergence + per-class expiry, token-bucket quotas
# under a fake clock + the typed-429 matrix, residency LRU (pinned
# default, leader-election cold load, zero post-load compiles),
# placement hysteresis, per-model cache/merge/top surfaces, the
# zero-telemetry guard, and the 2-model HTTP end-to-end — then the
# isolation bench: a saturating quota-metered burst on model alpha must
# not move model beta's gold-class window p99 past target (zero 5xx;
# the committed record names per-model p99 / cache hit rate / quota
# rejects / residency swaps)
multi-model:
	JAX_PLATFORMS=cpu python -m pytest tests/test_multimodel.py -q -m "not slow"
	JAX_PLATFORMS=cpu python bench.py --serving --multi-model

# live continuous-learning suite (docs/SERVING.md "Continuous learning"):
# Checkpoints reader API + writer-protocol contract, watcher torn-skip,
# swap-at-dispatch-boundary bit-exactness, rollback, canary guard +
# fleet rollout controller (incl. forced-regression auto-rollback), the
# train+fleet integration and train-and-serve SIGTERM drain — then the
# hot-swap tail-latency bench at the committed offered rate
live:
	JAX_PLATFORMS=cpu python -m pytest tests/test_live.py -q -m "not slow"
	JAX_PLATFORMS=cpu python bench.py --serving --swap

# asynchronous trainer fleet (docs/TUNING.md §19–20, RESILIENCE.md
# "Trainer fleet crash semantics"): ownership/wire/quorum/staleness
# units + the wire-compression suite (int8/bf16 codecs, error-feedback
# telescoping + ablation, delta-pull chain, mixed-codec interop) + the
# thread-driven 2-worker integration and v2 owner-part round trip, then
# the subprocess drills — the real CLI fleet, the SIGKILL
# crash-and-rejoin recovery, and the bounded-staleness convergence
# acceptance (S∈{0,1,2} vs the synchronous loop, compression ON) — then
# the 1/2/4-worker pinned scaling spec and the f32-vs-compressed wire
# A/B (records land in BENCH_SESSION.jsonl with the per-phase
# breakdown, the discard-counter ledger, and the wire-byte columns)
train-fleet:
	JAX_PLATFORMS=cpu python -m pytest tests/test_training_fleet.py tests/test_fleet_wire.py -q -m "not slow"
	JAX_PLATFORMS=cpu python -m pytest tests/test_training_fleet.py -q -m slow
	JAX_PLATFORMS=cpu python bench.py --training-fleet
	JAX_PLATFORMS=cpu python bench.py --fleet-wire-ab

# trainer-fleet observability plane (docs/OBSERVABILITY.md "Training
# fleet"): srt_training_* dynamics-histogram golden grammar +
# exactly-summing buckets across fake workers, the fake-clock fleet
# divergence-detector matrix, fleet-aware `telemetry summarize` /
# `report`, collect-trace --fleet-base-port expansion, the top columns,
# the zero-telemetry fleet guard — then the real 2-worker acceptance
# pair from tests/test_training_fleet.py (subprocess fleet → ONE merged
# Perfetto timeline + markdown run report; thread-fleet forced-
# divergence drill → alert + incident bundle naming the worker)
train-fleet-obs:
	JAX_PLATFORMS=cpu python -m pytest tests/test_fleet_obs.py -q -m "not slow"
	JAX_PLATFORMS=cpu python -m pytest tests/test_training_fleet.py -q -m "not slow" -k "obs_acceptance or divergence"

# elastic-membership chaos drills (docs/RESILIENCE.md "Ownership
# failover", docs/TUNING.md §21): the fake-clock lease matrix (a
# merely-slow worker is provably never evicted), re-shard / epoch-fence
# / rejoin units, PeerServer malformed-input fuzz (typed 400s, never a
# traceback), then the slow subprocess drills — owner SIGKILL past its
# restart budget → lease eviction → epoch-fenced re-shard → the
# survivors keep training (zero NaN, zero lost lineage, degraded-success
# rc=0) and the wire-chaos matrix (corrupt/delay/dup/partition at every
# wire site; a healed zombie's stale-epoch pushes all fenced)
train-fleet-chaos:
	JAX_PLATFORMS=cpu python -m pytest tests/test_fleet_membership.py -q -m "not slow"
	JAX_PLATFORMS=cpu python -m pytest tests/test_fleet_membership.py -q -m slow

bench:
	python bench.py

# regression sentry (docs/OBSERVABILITY.md "Host resources & the run
# ledger"): one fast bench smoke appends its fresh record to a scratch
# session (SRT_BENCH_SESSION keeps throwaway runs OUT of committed
# history), then `telemetry ledger regress` judges it against the latest
# clean committed record for the same (spec, shape, platform, labels)
# key. Exits 1 only on a confirmed clean-vs-clean regression beyond the
# measurement's own noise band; a contended host makes the verdict
# "untrusted", never red. The JSON verdict is the CI artifact.
bench-gate:
	rm -f .bench-gate-fresh.jsonl
	SRT_BENCH_SESSION=.bench-gate-fresh.jsonl JAX_PLATFORMS=cpu python bench.py --configs cnn_tagger
	JAX_PLATFORMS=cpu python -m spacy_ray_tpu telemetry ledger regress \
		--record .bench-gate-fresh.jsonl --session BENCH_SESSION.jsonl \
		--json-out bench-gate-verdict.json

baseline:
	python bench.py --measure-baseline

profile:
	python bin/profile_trf.py --sweep

# per-step fixed-cost floor (PERF.md round 7): optimizer-update-only bench
# (naive vs fused) + the MFU-vs-shape profile sweep. Compare two --trace
# runs with: python bin/profile_trf.py --compare before.json after.json
step-perf:
	JAX_PLATFORMS=cpu python bench.py --update-only
	JAX_PLATFORMS=cpu python bin/profile_trf.py --sweep

# per-replica serving speed A/Bs (PERF.md rounds 9 + 13): window vs
# continuous admission, and the f32 vs bf16 vs int8 precision-overlay
# arms (the int8 arm self-forces SRT_PALLAS_INT8=1 on CPU so the pallas
# kernel runs interpret-mode with an honest "forced" label), each
# open-loop at FIXED offered rates (committed baseline + saturation
# points) — then the Zipfian edge-cache spec through the real fleet at
# the armed cache default (hit-rate x window p99, zero rejects/5xx).
# Records append to BENCH_SESSION.jsonl with honest batching/precision
# labels. The tier-1 smoke of the same harness lives in
# tests/test_serving.py; interpret-mode int8 kernel tests run in tier-1
# (tests/test_int8.py, CPU-only, fast) like the other pallas suites.
serve-perf:
	JAX_PLATFORMS=cpu python bench.py --serving-ab
	JAX_PLATFORMS=cpu python bench.py --serving
	JAX_PLATFORMS=cpu python bench.py --serving --zipfian

# serving data plane (PR 20, docs/SERVING.md "Data plane"): the fast-tier
# data-plane tests (conditional 304s + ETag/generation interaction,
# length-affinity policy, pooled-connection stale-retry), then the
# length-routing A/B through the real 2-replica fleet (pad share must
# strictly drop), the Zipfian spec whose conditional arm commits 304
# share + bytes saved, and the router-ceiling spec (pooled vs fresh-dial
# arms against stub replicas, naming which side bounds the fleet)
serve-perf3:
	JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q -m 'not slow' \
		-k "conditional or suppressed or passthrough or length_ or stale_pooled or aux_conns"
	JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py -q -m 'not slow' \
		-k "etag or conditional or pad or batch_span"
	JAX_PLATFORMS=cpu python bench.py --serving --length-mix
	JAX_PLATFORMS=cpu python bench.py --serving --zipfian
	JAX_PLATFORMS=cpu python bench.py --serving --router-ceiling

# cross-replica update sharding (PERF.md "Update sharding (round 11)"):
# the full==replicated equality suite + v2 owner-shard checkpoint format +
# elastic (8->4->1) resume bit-exactness, then the sharded update-only A/B
# (replicated vs zero1 vs full at 1/2/4/8 virtual devices, with the
# grad-reduce/apply/allgather phase split on every record)
update-shard:
	python -m pytest tests/test_update_sharding.py -q
	python bench.py --update-only --sharded

dryrun:
	XLA_FLAGS="--xla_force_host_platform_device_count=8" python __graft_entry__.py
