# Convenience targets; the canonical commands live in README.md / PERF.md.

.PHONY: test test-fast test-slow bench baseline profile dryrun

test:
	python -m pytest tests/ -q

test-fast:
	python -m pytest tests/ -q -m "not slow"

test-slow:
	python -m pytest tests/ -q -m slow

bench:
	python bench.py

baseline:
	python bench.py --measure-baseline

profile:
	python bin/profile_trf.py --sweep

dryrun:
	python __graft_entry__.py
