"""Round-7 fixed-cost-floor contracts (ISSUE 5): fused optimizer update ==
optax reference, bf16 parameter shadow == cast-per-step forward,
steps_per_dispatch == K single dispatches, and the donation audit.

The equality discipline mirrors PERF.md's honesty rules: everything that
CAN be bitwise is asserted bitwise (fused-vs-optax under jit, the shadow
forward, multi-dispatch vs singles); the one thing that can't — the shadow
TRAJECTORY, where the baseline program elides a bf16 double-rounding in
its weight-grad matmuls — is pinned at a 1e-6 tolerance with the forward
still exact.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import spacy_ray_tpu.ops.fused_update as fu
from spacy_ray_tpu.config import Config
from spacy_ray_tpu.models.transformer import (
    build_param_shadow,
    pipeline_shadow_dtype,
)
from spacy_ray_tpu.parallel.mesh import build_mesh
from spacy_ray_tpu.parallel.step import (
    make_train_step,
    overlay_shadow,
    place_batch,
    place_replicated,
    refresh_shadow,
    shard_opt_state,
)
from spacy_ray_tpu.pipeline.language import Pipeline
from spacy_ray_tpu.registry import registry
from spacy_ray_tpu.training import optimizers as O
from spacy_ray_tpu.training.loop import train, validate_training
from spacy_ray_tpu.util import synth_corpus, write_synth_jsonl


def _tree(seed=0):
    r = np.random.default_rng(seed)
    return {
        "a": jnp.asarray(r.standard_normal((64, 32)), jnp.float32),
        "b": {"w": jnp.asarray(r.standard_normal((128,)), jnp.float32),
              "c": jnp.asarray(r.standard_normal((8, 8)), jnp.float32)},
    }


def _leaves(t):
    return jax.tree_util.tree_leaves(t)


# ---------------------------------------------------------------- fused tx


@pytest.mark.parametrize(
    "factory,kw",
    [
        (O.Adam, dict(learn_rate=0.001)),  # default grad_clip=1.0, wd
        (O.Adam, dict(learn_rate=0.01, L2=0.02, grad_clip=0.5)),
        (O.Adam, dict(learn_rate=0.01, L2=0.02, L2_is_weight_decay=False,
                      grad_clip=0.0)),
        (O.RAdam, dict(learn_rate=0.003, weight_decay=0.01)),
    ],
)
def test_fused_matches_optax_bitwise(factory, kw):
    """The fused single-traversal update equals the reference optax chain
    BITWISE under jit (same expressions, same order — ops/fused_update.py
    mirrors the installed optax's formulas), params and state both."""
    tx = factory(**kw)
    fused = O.fuse_optimizer(tx)
    assert fused is not None and fused.applies_updates
    params = _tree()

    @jax.jit
    def step_ref(p, s, g):
        u, s = tx.update(g, s, p)
        return optax.apply_updates(p, u), s

    step_fused = jax.jit(lambda p, s, g: fused.update(g, s, p))
    s_ref, s_f = tx.init(params), fused.init(params)
    # identical state STRUCTURE: checkpoints survive knob flips
    assert jax.tree_util.tree_structure(s_ref) == jax.tree_util.tree_structure(s_f)
    p_ref, p_f = params, params
    for i in range(6):
        grads = jax.tree_util.tree_map(lambda p: p * 0.1 + 0.01 * i, params)
        p_ref, s_ref = step_ref(p_ref, s_ref, grads)
        p_f, s_f = step_fused(p_f, s_f, grads)
    for a, b in zip(_leaves((p_ref, s_ref)), _leaves((p_f, s_f))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fused_matches_optax_with_schedule():
    """Schedule counts live in the chain's ScaleByScheduleState: the fused
    update must read the PRE-increment count like optax does."""
    sched = registry.get("schedules", "warmup_linear.v1")(
        initial_rate=0.01, warmup_steps=3, total_steps=20
    )
    tx = O.Adam(learn_rate=sched)
    fused = O.fuse_optimizer(tx)
    params = _tree(1)

    @jax.jit
    def step_ref(p, s, g):
        u, s = tx.update(g, s, p)
        return optax.apply_updates(p, u), s

    step_fused = jax.jit(lambda p, s, g: fused.update(g, s, p))
    s_ref, s_f = tx.init(params), fused.init(params)
    p_ref, p_f = params, params
    for i in range(6):  # crosses the warmup boundary
        grads = jax.tree_util.tree_map(lambda p: p * 0.05, params)
        p_ref, s_ref = step_ref(p_ref, s_ref, grads)
        p_f, s_f = step_fused(p_f, s_f, grads)
    for a, b in zip(_leaves((p_ref, s_ref)), _leaves((p_f, s_f))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_frozen_masked_optimizer_is_not_fusable():
    """mask_frozen (frozen_ leaves) drops the fusable metadata — the loop's
    "auto" mode keeps the reference chain there."""
    tx = O.Adam(learn_rate=0.01)
    params = {"frozen_vectors": jnp.ones((4,)), "w": jnp.ones((4,))}
    masked = O.mask_frozen(tx, params)
    assert O.fuse_optimizer(masked) is None
    # nothing frozen: metadata survives
    assert O.fuse_optimizer(O.mask_frozen(tx, {"w": jnp.ones((4,))})) is not None


def test_pallas_kernel_matches_xla_math_interpret():
    """The pallas kernel (CPU interpret mode) reproduces the XLA leaf math
    — the same probe that gates the kernel on TPU at startup."""
    assert fu._probe_kernel(interpret=True)


def test_fused_status_labels():
    tx = O.Adam(learn_rate=0.01)
    assert fu.fused_status(tx) == "off (optax chain)"
    fused = O.fuse_optimizer(tx)
    # CPU: the kernel probe is off -> the label must say the path is XLA
    assert fu.fused_status(fused).startswith("active (")
    assert "pallas" not in fu.fused_status(fused) or fu._PROBED is True
    # multi-device mesh: the kernel gate (_single_mesh) keeps pallas off,
    # so the label must downgrade even when the probe passed — a multi-chip
    # bench record must never claim "active (pallas)" (honest labeling)
    import jax

    from spacy_ray_tpu.parallel.mesh import build_mesh

    mesh = build_mesh(n_data=len(jax.devices()))
    old = fu._PROBED
    fu._PROBED = True
    try:
        if int(mesh.size) > 1:
            assert "pallas" not in fu.fused_status(fused, mesh)
        assert fu.fused_status(fused, None) == "active (pallas)"
    finally:
        fu._PROBED = old


# ------------------------------------------------------------------ shadow


TRF_CFG = """
[nlp]
lang = "en"
pipeline = ["transformer","tagger"]
[components.transformer]
factory = "transformer"
[components.transformer.model]
@architectures = "spacy_ray_tpu.TransformerEncoder.v1"
width = 32
depth = 2
n_heads = 2
embed_size = 500
compute_dtype = "bfloat16"
[components.tagger]
factory = "tagger"
[components.tagger.model]
@architectures = "spacy.Tagger.v2"
[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 32
"""


@pytest.fixture(scope="module")
def trf_setup():
    nlp = Pipeline.from_config(Config.from_str(TRF_CFG))
    egs = synth_corpus(32, "tagger", seed=0)
    nlp.initialize(lambda: iter(egs), seed=0)
    host_params = jax.tree_util.tree_map(np.asarray, nlp.params)
    mesh = build_mesh(n_data=1)
    batch = nlp.collate(egs[:4], pad_batch_to=4, pad_len_to=16)
    tokens = place_batch(batch["tokens"], mesh)
    targets = place_batch(batch["targets"], mesh)
    return nlp, host_params, mesh, tokens, targets


def _fresh(host_params, mesh, tx):
    p = place_replicated(
        jax.tree_util.tree_map(jnp.asarray, host_params), mesh
    )
    s = shard_opt_state(tx.init(p), mesh, False)
    return p, s


def test_shadow_selects_trunk_matmul_weights(trf_setup):
    nlp, host_params, mesh, _, _ = trf_setup
    assert pipeline_shadow_dtype(nlp) == jnp.bfloat16
    sh = build_param_shadow(nlp.params)
    leaves = jax.tree_util.tree_leaves(sh)
    assert leaves and all(x.dtype == jnp.bfloat16 for x in leaves)
    # 2 layers x 8 dense-layer tensors; LN params must NOT be shadowed
    assert len(leaves) == 16
    flat = sh["transformer"]["layer_0"]
    assert "ln1_g" not in flat and "qkv_W" in flat
    # a CPU-auto (f32) trunk yields no shadow: "auto" is a no-op there
    cpu_cfg = TRF_CFG.replace('compute_dtype = "bfloat16"', "")
    cpu_nlp = Pipeline.from_config(Config.from_str(cpu_cfg))
    assert pipeline_shadow_dtype(cpu_nlp) is None


def test_shadow_forward_bit_exact(trf_setup):
    """overlay_shadow(params, cast(params)) through the loss == the
    cast-per-step loss, bitwise (the astype the layer stack applies to an
    already-bf16 leaf is the identity)."""
    nlp, host_params, mesh, tokens, targets = trf_setup
    loss_fn = nlp.make_loss_fn(dropout=0.0)
    p = place_replicated(
        jax.tree_util.tree_map(jnp.asarray, host_params), mesh
    )
    rng = jax.random.PRNGKey(0)
    l_base, _ = jax.jit(loss_fn)(p, tokens, targets, rng)
    l_shadow, _ = jax.jit(
        lambda p_, sh_, t, g, r: loss_fn(overlay_shadow(p_, sh_), t, g, r)
    )(p, build_param_shadow(p), tokens, targets, rng)
    assert float(l_base) == float(l_shadow)


def test_shadow_training_trajectory_and_sync(trf_setup):
    """Shadow-enabled training stays within 1e-6 of the cast-per-step
    trajectory over several steps (exactness bound: the baseline backward
    may skip one bf16 rounding in weight-grad matmuls), and the shadow is
    ALWAYS exactly cast(master params) — it never drifts."""
    nlp, host_params, mesh, tokens, targets = trf_setup
    tx = registry.get("optimizers", "Adam.v1")(learn_rate=0.01)
    loss_fn = nlp.make_loss_fn(dropout=0.0)
    p0, s0 = _fresh(host_params, mesh, tx)
    upd = make_train_step(loss_fn, tx, mesh, opt_state_template=s0)
    p1, s1 = _fresh(host_params, mesh, tx)
    sh = build_param_shadow(p1)
    upd_s = make_train_step(
        loss_fn, tx, mesh, opt_state_template=s1, shadow=True
    )
    rng = jax.random.PRNGKey(0)
    for i in range(4):
        rng, sub = jax.random.split(rng)
        p0, s0, l0, _ = upd(p0, s0, tokens, targets, sub)
        p1, s1, sh, l1, _ = upd_s(p1, s1, sh, tokens, targets, sub)
    for a, b in zip(_leaves(p0), _leaves(p1)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-5
        )
    # shadow integrity: exactly the bf16 cast of the current masters
    ref = refresh_shadow(p1, build_param_shadow(p1))
    for a, b in zip(_leaves(sh), _leaves(ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ----------------------------------------------------- multi-step dispatch


def test_multi_dispatch_bit_exact_vs_singles(trf_setup):
    """K stacked steps through the scan == K host-dispatched singles:
    params, opt state, rng chain, and per-step losses all bitwise equal
    (the scan continues the identical jax.random.split chain)."""
    nlp, host_params, mesh, tokens, targets = trf_setup
    tx = registry.get("optimizers", "Adam.v1")(learn_rate=0.01)
    loss_fn = nlp.make_loss_fn(dropout=0.0)
    p0, s0 = _fresh(host_params, mesh, tx)
    upd = make_train_step(loss_fn, tx, mesh, opt_state_template=s0)
    rng = jax.random.PRNGKey(7)
    r = rng
    losses = []
    for _ in range(3):
        r, sub = jax.random.split(r)
        p0, s0, loss, _ = upd(p0, s0, tokens, targets, sub)
        losses.append(float(loss))
    p1, s1 = _fresh(host_params, mesh, tx)
    upd_m = make_train_step(
        loss_fn, tx, mesh, opt_state_template=s1, multi_dispatch=True
    )
    stack = lambda t: jax.tree_util.tree_map(  # noqa: E731
        lambda x: jnp.stack([x, x, x]), t
    )
    p1, s1, r_out, losses_m, metrics_m = upd_m(
        p1, s1, stack(tokens), stack(targets), rng
    )
    for a, b in zip(_leaves((p0, s0)), _leaves((p1, s1))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(r_out), np.asarray(r))
    np.testing.assert_array_equal(
        np.asarray(losses_m), np.asarray(losses, np.float32)
    )
    # per-step metrics keep the leading [K] dim for telemetry fan-out
    assert all(v.shape[0] == 3 for v in metrics_m.values())


# ---------------------------------------------------------- donation audit


def test_update_donates_params_opt_state_and_shadow(trf_setup):
    """The jitted update must DONATE its state buffers: a stray copy would
    silently reintroduce the O(n_params) traversal the round-7 tentpole
    removes. Donated jax arrays report is_deleted() after the call."""
    nlp, host_params, mesh, tokens, targets = trf_setup
    tx = O.fuse_optimizer(registry.get("optimizers", "Adam.v1")(learn_rate=0.01))
    loss_fn = nlp.make_loss_fn(dropout=0.0)
    p, s = _fresh(host_params, mesh, tx)
    sh = build_param_shadow(p)
    upd = make_train_step(loss_fn, tx, mesh, opt_state_template=s, shadow=True)
    out = upd(p, s, sh, tokens, targets, jax.random.PRNGKey(0))
    jax.block_until_ready(out[0])
    for leaf in _leaves((p, sh)):
        assert leaf.is_deleted(), "params/shadow buffer was not donated"
    # float opt-state moments must donate too (tiny int counts may not
    # alias across dtypes on all backends — the bytes that matter do)
    for leaf in _leaves(s):
        if leaf.dtype == jnp.float32 and leaf.size > 1:
            assert leaf.is_deleted(), "opt-state moment buffer not donated"


def test_avg_step_donates_accumulator():
    """loop._avg_step must donate its running-mean accumulator instead of
    allocating a fresh full-size tree every step (ISSUE 5 satellite)."""
    from spacy_ray_tpu.training.loop import _avg_step

    avg = {"w": jnp.ones((256, 256))}
    params = {"w": jnp.full((256, 256), 2.0)}
    out = _avg_step(avg, params, 2)
    jax.block_until_ready(out["w"])
    assert avg["w"].is_deleted(), "avg accumulator was not donated"
    np.testing.assert_allclose(np.asarray(out["w"]), 1.5)


# ------------------------------------------------------------- loop knobs


def test_training_knob_validation():
    validate_training({"fused_update": "auto", "bf16_shadow": "off",
                       "steps_per_dispatch": 4})
    with pytest.raises(ValueError, match="fused_update"):
        validate_training({"fused_update": True})
    with pytest.raises(ValueError, match="bf16_shadow"):
        validate_training({"bf16_shadow": "always"})
    with pytest.raises(ValueError, match="steps_per_dispatch"):
        validate_training({"steps_per_dispatch": 0})


@pytest.mark.slow
def test_train_loop_steps_per_dispatch_equivalence(tmp_path):
    """train() with steps_per_dispatch=3 reproduces the K=1 run exactly:
    same eval history (scores + losses), and the telemetry metrics file
    still carries one step row PER INNER STEP."""
    write_synth_jsonl(tmp_path / "train.jsonl", 200, kind="tagger", seed=0)
    write_synth_jsonl(tmp_path / "dev.jsonl", 40, kind="tagger", seed=1)
    base = """
[nlp]
lang = "en"
pipeline = ["tok2vec","tagger"]
[components.tok2vec]
factory = "tok2vec"
[components.tok2vec.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = 32
depth = 1
embed_size = 300
[components.tagger]
factory = "tagger"
[components.tagger.model]
@architectures = "spacy.Tagger.v2"
[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 32
[corpora.train]
@readers = "spacy.JsonlCorpus.v1"
path = "{train}"
[corpora.dev]
@readers = "spacy.JsonlCorpus.v1"
path = "{dev}"
[training]
seed = 1
max_steps = 8
eval_frequency = 4
dropout = 0.0
prefetch_batches = 0
steps_per_dispatch = {K}
[training.batcher]
@batchers = "spacy.batch_by_words.v1"
size = 200
tolerance = 0.2
"""
    hist = {}
    for K in (1, 3):
        cfg = Config.from_str(base.format(
            train=tmp_path / "train.jsonl", dev=tmp_path / "dev.jsonl", K=K
        ))
        out = tmp_path / f"out{K}"
        _, res = train(cfg, out, stdout_log=False, metrics_dir=out / "m")
        rows = [json.loads(line)
                for line in (out / "m" / "metrics.jsonl").read_text().splitlines()]
        step_rows = [r["step"] for r in rows if r["kind"] == "step"]
        assert step_rows == list(range(1, res.final_step + 1))
        hist[K] = [(h["step"], h["score"], h["losses"]) for h in res.history]
    assert hist[1] == hist[3]


@pytest.mark.slow
def test_update_only_bench_records(tmp_path, monkeypatch):
    """bench.py --update-only appends naive + fused records with the
    honest fused_update label and a reprobe stamp."""
    import bench

    from spacy_ray_tpu.presets import CNN_TAGGER_CFG

    monkeypatch.setattr(bench, "SESSION_FILE", tmp_path / "session.jsonl")
    monkeypatch.setattr(bench, "MIN_REP_SECONDS", 0.05)
    monkeypatch.setattr(bench, "N_REPS", 1)
    tiny = [("tiny", CNN_TAGGER_CFG.format(width=32, depth=1, embed_size=200),
             ["tagger"])]
    bench.run_update_only("cpu", configs=tiny)
    recs = [json.loads(line)
            for line in (tmp_path / "session.jsonl").read_text().splitlines()]
    names = {r["name"] for r in recs}
    assert names == {"update_only_tiny", "update_only_tiny_fused"}
    for r in recs:
        assert r["unit"] == "seconds/update" and r["value"] > 0
        assert r["peak_reprobe_ratio"] is not None
        assert r["fused_update"].startswith(
            "active" if r["name"].endswith("_fused") else "off"
        )
