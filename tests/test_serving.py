"""Online serving subsystem (spacy_ray_tpu/serving/): dynamic batcher
admission/coalescing/deadlines, engine warmup + dispatch correctness
under concurrent load (responses == single-request predict_docs, and
occupancy > 1 proves coalescing), HTTP API surface, SIGTERM graceful
drain in a real subprocess, the telemetry-disabled zero-calls contract,
and the bench.py --serving load spec's session records."""

import json
import http.client
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent.parent))  # for `import bench`

from spacy_ray_tpu.config import Config
from spacy_ray_tpu.pipeline.language import Pipeline
from spacy_ray_tpu.serving import (
    DeadlineExceeded,
    Draining,
    DynamicBatcher,
    InferenceEngine,
    QueueFull,
    RequestTooLarge,
    Server,
    ServeRequest,
    ServingTelemetry,
    warmup_buckets,
)
from spacy_ray_tpu.serving.batcher import (
    cache_key_for,
    etag_for,
    if_none_match_hit,
)
from spacy_ray_tpu.util import synth_corpus

SERVE_CFG = """
[nlp]
lang = "en"
pipeline = ["tok2vec","tagger"]

[components]

[components.tok2vec]
factory = "tok2vec"

[components.tok2vec.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = 64
depth = 2
embed_size = 512

[components.tagger]
factory = "tagger"

[components.tagger.model]
@architectures = "spacy.Tagger.v2"

[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 64
"""

TEXTS = [
    "the cat runs fast today",
    "a dog sleeps near the door",
    "birds sing loudly in the morning",
    "the quick brown fox jumps high",
    "a lazy dog naps all afternoon",
    "rain falls softly on the roof",
    "the child reads an old book",
    "wind moves through the tall trees",
    "a boat drifts down the river",
    "stars shine over the quiet town",
]


def _post(host, port, payload, timeout=30.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        body = json.dumps(payload).encode("utf8")
        conn.request(
            "POST", "/v1/parse", body, {"Content-Type": "application/json"}
        )
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _get(host, port, path, timeout=10.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


# ----------------------------------------------------------------------
# DynamicBatcher: admission, coalescing, deadlines, drain
# ----------------------------------------------------------------------


def _req(n_docs=1, deadline_in=10.0, clock=time.monotonic):
    now = clock()
    return ServeRequest(["d"] * n_docs, deadline=now + deadline_in, enqueued_at=now)


def test_batcher_rejects_when_queue_full():
    b = DynamicBatcher(max_queue_docs=4, max_batch_docs=4, max_wait_s=0.0)
    b.submit(_req(3))
    with pytest.raises(QueueFull):
        b.submit(_req(2))
    assert b.rejected_full == 1
    b.submit(_req(1))  # exactly at the limit is admitted


def test_batcher_rejects_oversized_request():
    b = DynamicBatcher(max_queue_docs=8, max_batch_docs=4, max_wait_s=0.0)
    with pytest.raises(RequestTooLarge):
        b.submit(_req(5))


def test_batcher_drain_rejects_new_but_serves_queued():
    b = DynamicBatcher(max_queue_docs=8, max_batch_docs=4, max_wait_s=0.0)
    queued = _req(2)
    b.submit(queued)
    b.begin_drain()
    with pytest.raises(Draining):
        b.submit(_req(1))
    assert b.rejected_draining == 1
    batch = b.next_batch()
    assert batch == [queued]  # admitted-before-drain still dispatches


def test_batcher_expired_request_completed_not_dispatched():
    b = DynamicBatcher(max_queue_docs=8, max_batch_docs=4, max_wait_s=0.0)
    dead = _req(1, deadline_in=-0.5)  # already past its deadline
    live = _req(1)
    b.submit(dead)
    b.submit(live)
    batch = b.next_batch()
    assert batch == [live]
    assert dead.done and isinstance(dead.error, DeadlineExceeded)
    assert b.expired == 1


def test_batcher_coalesces_within_window():
    b = DynamicBatcher(max_queue_docs=32, max_batch_docs=8, max_wait_s=0.25)
    for _ in range(3):
        b.submit(_req(2))
    t0 = time.monotonic()
    batch = b.next_batch()
    assert sum(len(r.docs) for r in batch) == 6
    # full-batch early exit: 6 < 8 so the window ran — but queued
    # requests were all there at entry, so the first pop got them
    assert time.monotonic() - t0 < 5.0


def test_batcher_full_batch_skips_wait():
    b = DynamicBatcher(max_queue_docs=32, max_batch_docs=4, max_wait_s=30.0)
    b.submit(_req(2))
    b.submit(_req(2))
    t0 = time.monotonic()
    batch = b.next_batch()
    # a full batch must dispatch immediately, not sit out max_wait_s
    assert time.monotonic() - t0 < 5.0
    assert sum(len(r.docs) for r in batch) == 4


def test_batcher_close_unblocks_dispatcher():
    b = DynamicBatcher(max_queue_docs=8, max_batch_docs=4, max_wait_s=0.0)
    got = []
    th = threading.Thread(target=lambda: got.append(b.next_batch()))
    th.start()
    b.close()
    th.join(timeout=5.0)
    assert got == [None]


# ----------------------------------------------------------------------
# Continuous admission: slot-based assembly, no window timer
# ----------------------------------------------------------------------


class _FakeClock:
    """Deterministic clock; also counts reads so a test can assert a
    code path never even consulted time."""

    def __init__(self, t=100.0):
        self.t = t
        self.reads = 0

    def __call__(self):
        self.reads += 1
        return self.t

    def advance(self, dt):
        self.t += dt


def test_continuous_dispatches_partial_batch_immediately():
    """The defining property: a partial batch dispatches the instant the
    dispatch thread asks, never sitting out the window timer (here an
    absurd 30 s — a timer-waiting implementation would hang)."""
    b = DynamicBatcher(
        max_queue_docs=32, max_batch_docs=8, max_wait_s=30.0,
        mode="continuous",
    )
    b.submit(_req(2))
    t0 = time.monotonic()
    batch = b.next_batch()
    assert time.monotonic() - t0 < 5.0
    assert sum(len(r.docs) for r in batch) == 2  # partial, not full


def test_continuous_no_queued_request_waits_for_inflight_drain():
    """Property (the tentpole's contract): while a batch is IN FLIGHT
    (popped, not completed), newly queued requests are admitted into the
    next dispatch's free slots the moment the dispatch thread returns —
    with a fake clock, zero simulated time passes between the in-flight
    handoff and the follow-up's admission into a batch."""
    clock = _FakeClock()
    b = DynamicBatcher(
        max_queue_docs=32, max_batch_docs=4, max_wait_s=30.0,
        mode="continuous", clock=clock,
    )
    b.submit(_req(4, clock=clock))
    inflight = b.next_batch()  # handed to the "device", never completed
    assert sum(len(r.docs) for r in inflight) == 4
    # requests landing while the device runs
    late = [_req(1, clock=clock), _req(2, clock=clock)]
    for r in late:
        b.submit(r)
    batch = b.next_batch()  # dispatch thread frees up
    assert batch == late  # all queued slots filled at once
    assert all(r.started_at == clock.t for r in late)
    # the in-flight batch was NEVER completed — its drain was not a
    # precondition for admitting the follow-ups
    assert not any(r.done for r in inflight)


def test_continuous_typed_rejects_still_fire():
    b = DynamicBatcher(
        max_queue_docs=4, max_batch_docs=4, max_wait_s=0.0,
        mode="continuous",
    )
    with pytest.raises(RequestTooLarge):
        b.submit(_req(5))
    b.submit(_req(3))
    with pytest.raises(QueueFull):
        b.submit(_req(2))
    assert b.rejected_full == 1
    b.begin_drain()
    with pytest.raises(Draining):
        b.submit(_req(1))
    assert b.rejected_draining == 1


def test_continuous_deadlines_honored_before_and_after_admission():
    """An already-expired request never reaches a batch (pre-admission
    check), and a request whose deadline passes while it sits queued
    behind an in-flight batch gets its typed DeadlineExceeded at the
    next slot-fill, not a response nobody reads."""
    clock = _FakeClock()
    b = DynamicBatcher(
        max_queue_docs=32, max_batch_docs=4, max_wait_s=0.0,
        mode="continuous", clock=clock,
    )
    dead = _req(1, deadline_in=-0.5, clock=clock)
    live = _req(1, deadline_in=10.0, clock=clock)
    b.submit(dead)
    b.submit(live)
    assert b.next_batch() == [live]
    assert dead.done and isinstance(dead.error, DeadlineExceeded)
    # queued during an in-flight batch, expires before the slots free up
    expiring = _req(1, deadline_in=1.0, clock=clock)
    survivor = _req(1, deadline_in=60.0, clock=clock)
    b.submit(expiring)
    b.submit(survivor)
    clock.advance(5.0)  # the in-flight batch ran long
    assert b.next_batch() == [survivor]
    assert expiring.done and isinstance(expiring.error, DeadlineExceeded)
    assert b.expired == 2


def test_continuous_drain_completes_requests_mid_assembly():
    """begin_drain with requests queued (mid-assembly for the next
    dispatch): admission closes, but every queued request still
    dispatches — the graceful-drain contract is mode-independent."""
    b = DynamicBatcher(
        max_queue_docs=32, max_batch_docs=2, max_wait_s=0.0,
        mode="continuous",
    )
    queued = [_req(2), _req(2), _req(1)]
    for r in queued:
        b.submit(r)
    b.begin_drain()
    with pytest.raises(Draining):
        b.submit(_req(1))
    served = []
    while True:
        batch = b.next_batch(poll_s=0.01)
        served.extend(batch)
        if len(served) == len(queued):
            break
    assert served == queued  # FIFO, whole requests, none dropped
    b.close()
    assert b.next_batch() is None


def test_batcher_rejects_unknown_mode():
    with pytest.raises(ValueError):
        DynamicBatcher(mode="adaptive")


def test_warmup_bucket_grid_uses_trainer_tables():
    grid = warmup_buckets(8, 32, (16, 32, 64))
    assert grid == [(1, 16), (1, 32), (2, 16), (2, 32), (4, 16), (4, 32),
                    (8, 16), (8, 32)]
    # caps round up through the trainer's own bucket functions
    assert (16, 64) in warmup_buckets(12, 40, (16, 32, 64))


def test_warmup_bucket_grid_is_complete_beyond_table_top():
    """The warmed-shape contract: EVERY length bucket admission can
    produce for a doc of 1..max_doc_len tokens is in the grid —
    including the overflow region beyond the table's top bucket, where
    bucket_length emits multiples of the top. A hole here is a live
    mid-traffic XLA compile."""
    from spacy_ray_tpu.training.batcher import bucket_length

    buckets = (16, 32, 64)
    grid_ts = {t for _, t in warmup_buckets(2, 1500, buckets)}
    admissible = {bucket_length(n, buckets) for n in range(1, 1501)}
    assert admissible <= grid_ts, sorted(admissible - grid_ts)


# ----------------------------------------------------------------------
# Engine + HTTP server
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def serve_nlp():
    nlp = Pipeline.from_config(Config.from_str(SERVE_CFG))
    egs = synth_corpus(64, "tagger", seed=0)
    nlp.initialize(lambda: iter(egs), seed=0)
    return nlp


@pytest.fixture(scope="module")
def served(serve_nlp):
    tel = ServingTelemetry()
    engine = InferenceEngine(
        serve_nlp,
        max_batch_docs=8,
        max_wait_s=0.05,
        max_queue_docs=64,
        timeout_s=30.0,
        max_doc_len=32,
        telemetry=tel,
    )
    engine.start(warmup=True)
    server = Server(engine, "127.0.0.1", 0, telemetry=tel)
    host, port = server.start()
    yield engine, tel, host, port
    server.request_shutdown()
    assert server.wait() == 0


def test_concurrent_load_matches_single_request_and_coalesces(
    served, serve_nlp
):
    """Acceptance: N>=8 concurrent clients through the HTTP API; every
    response equals the single-request predict_docs output, and recorded
    occupancy > 1 proves the requests shared device batches instead of
    running as N serial batches of 1."""
    engine, tel, host, port = served
    n_clients = 10
    barrier = threading.Barrier(n_clients)
    results = [None] * n_clients

    def client(i):
        barrier.wait()  # release all clients at once: coalescing window
        results[i] = _post(host, port, {"texts": [TEXTS[i % len(TEXTS)]]})

    threads = [threading.Thread(target=client, args=(i,)) for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)

    assert all(r is not None and r[0] == 200 for r in results), results
    occupancies = [r[1]["batch"]["occupancy"] for r in results]
    assert max(occupancies) > 1, (
        f"no coalescing happened: occupancies {occupancies}"
    )
    # single-request ground truth, computed after the load so the jit
    # cache is only ever touched by one thread at a time
    for i, (status, payload) in enumerate(results):
        doc = serve_nlp.tokenizer(TEXTS[i % len(TEXTS)])
        serve_nlp.predict_docs([doc])
        [got] = payload["docs"]
        assert got["tokens"] == doc.words
        assert got["tags"] == doc.tags, (
            f"batched response diverged from single-request predict for "
            f"text {i}: {got['tags']} != {doc.tags}"
        )
    # the telemetry surface saw the same story
    occ_hist = tel.registry.histogram("batch_occupancy").snapshot()
    assert occ_hist["max"] > 1
    snap = tel.snapshot()
    assert snap["slo"]["request_latency_p50"] is not None
    assert snap["counters"]["requests"] >= n_clients


def test_healthz_and_metrics_endpoints(served):
    _, _, host, port = served
    status, health = _get(host, port, "/healthz")
    assert status == 200
    assert health["status"] == "ok"
    assert health["pipeline"] == ["tok2vec", "tagger"]
    assert health["warmed_buckets"] == 8  # (1|2|4|8) x (16|32)
    # honest labels: the default admission discipline and the precision
    # the device actually runs (CPU auto resolves the overlay OFF)
    assert health["batching"] == "continuous"
    assert health["precision"] == "f32"
    assert "precision_label" in health
    status, metrics = _get(host, port, "/metrics")
    assert status == 200
    assert {"counters", "gauges", "histograms", "slo"} <= set(metrics)
    assert {"request_latency_p50", "request_latency_p95",
            "request_latency_p99"} <= set(metrics["slo"])
    status, _ = _get(host, port, "/nope")
    assert status == 404


def test_bad_requests_get_400(served):
    _, _, host, port = served
    assert _post(host, port, {"texts": []})[0] == 400
    assert _post(host, port, {"texts": "not a list"})[0] == 400
    assert _post(host, port, {})[0] == 400
    conn = http.client.HTTPConnection(host, port, timeout=10)
    try:
        conn.request("POST", "/v1/parse", b"{not json",
                     {"Content-Type": "application/json"})
        assert conn.getresponse().status == 400
    finally:
        conn.close()


def test_too_long_doc_rejected_413(served):
    _, _, host, port = served
    status, payload = _post(
        host, port, {"texts": ["word " * 60]}  # 60 tokens > max_doc_len 32
    )
    assert status == 413
    assert payload["error"] == "request_too_large"


# ----------------------------------------------------------------------
# Conditional responses (ETag / If-None-Match) and pad accounting
# ----------------------------------------------------------------------


def _post_raw(host, port, payload, headers=None, timeout=30.0):
    """Like _post but returns (status, body_bytes, response_headers)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        body = json.dumps(payload).encode("utf8")
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        conn.request("POST", "/v1/parse", body, hdrs)
        resp = conn.getresponse()
        return resp.status, resp.read(), dict(resp.getheaders())
    finally:
        conn.close()


def test_etag_helpers_are_model_and_generation_sensitive():
    texts = ["the cat runs fast today"]
    base = etag_for(texts, "", 0)
    assert base.startswith('"') and base.endswith('"')
    # same inputs -> same tag; any axis change -> different tag
    assert etag_for(texts, "", 0) == base
    assert etag_for(texts, "alpha", 0) != base
    assert etag_for(texts, "", 1) != base
    assert etag_for(["other text"], "", 0) != base
    # the text digest is the shared response-cache key
    assert cache_key_for(texts, "alpha") != cache_key_for(texts, "beta")
    # If-None-Match grammar: exact, list, weak validator, wildcard
    assert if_none_match_hit(base, base)
    assert if_none_match_hit(f'"nope", {base}', base)
    assert if_none_match_hit(f"W/{base}", base)
    assert if_none_match_hit("*", base)
    assert not if_none_match_hit(None, base)
    assert not if_none_match_hit('"nope"', base)


def test_replica_etag_and_conditional_304(served):
    """A replica stamps a strong ETag on every 200; a matching
    If-None-Match is answered 304 with no body at admission (before the
    queue), counted as not_modified; a stale tag gets the full 200."""
    engine, tel, host, port = served
    texts = [TEXTS[0]]
    status, body, headers = _post_raw(host, port, {"texts": texts})
    assert status == 200
    etag = headers["ETag"]
    assert etag == etag_for(texts, "", engine.serving_generation)

    before = tel.snapshot()["counters"].get("not_modified", 0)
    status, body, headers = _post_raw(
        host, port, {"texts": texts}, headers={"If-None-Match": etag}
    )
    assert status == 304
    assert body == b""
    assert headers["ETag"] == etag
    assert tel.snapshot()["counters"]["not_modified"] == before + 1

    # a non-matching validator is ignored: full response, no 304 count
    status, body, _ = _post_raw(
        host, port, {"texts": texts}, headers={"If-None-Match": '"stale"'}
    )
    assert status == 200
    assert json.loads(body)["docs"]
    assert tel.snapshot()["counters"]["not_modified"] == before + 1


def test_pad_and_real_token_counters_on_dispatch(served):
    """Every dispatched batch contributes real_tokens (sum of doc lens)
    and pad_tokens (B*T - real) to the serving counters."""
    engine, tel, host, port = served
    before = tel.snapshot()["counters"]
    status, _ = _post(host, port, {"texts": ["a short doc"]})
    assert status == 200
    after = tel.snapshot()["counters"]
    assert after["real_tokens"] > before.get("real_tokens", 0)
    # a 3-token doc in a padded bucket always pads something
    assert after["pad_tokens"] > before.get("pad_tokens", 0)


def test_batch_span_pad_accounting_unit():
    tel = ServingTelemetry()
    with tel.batch_span(2, 4, 32, real_tokens=50):
        pass
    counters = tel.snapshot()["counters"]
    assert counters["real_tokens"] == 50
    assert counters["pad_tokens"] == 4 * 32 - 50
    # real_tokens omitted -> pad counters stay at zero
    tel2 = ServingTelemetry()
    with tel2.batch_span(1, 1, 16):
        pass
    c2 = tel2.snapshot()["counters"]
    assert c2["real_tokens"] == 0
    assert c2["pad_tokens"] == 0


def test_request_deadline_maps_to_504(serve_nlp):
    """A deadline shorter than the coalescing window must come back as a
    typed 504, not hang: the dispatcher completes expired requests
    before spending device time. Window mode pinned explicitly — it is
    the window that guarantees the deadline passes pre-dispatch
    (continuous admission would race the 1 ms deadline)."""
    engine = InferenceEngine(
        serve_nlp,
        max_batch_docs=4,
        max_wait_s=0.3,
        batching="window",
        timeout_s=30.0,
        max_doc_len=32,
    )
    engine.start(warmup=False)  # shapes already compiled by other tests
    server = Server(engine, "127.0.0.1", 0)
    host, port = server.start()
    try:
        status, payload = _post(
            host, port, {"texts": ["the cat"], "timeout_ms": 1}
        )
        assert status == 504
        assert payload["error"] == "deadline_exceeded"
    finally:
        server.request_shutdown()
        assert server.wait() == 0


def test_draining_server_rejects_with_503(serve_nlp):
    engine = InferenceEngine(
        serve_nlp, max_batch_docs=4, max_wait_s=0.0, max_doc_len=32
    )
    engine.start(warmup=False)
    server = Server(engine, "127.0.0.1", 0)
    host, port = server.start()
    server.httpd.draining = True  # gate flips before the drain completes
    status, payload = _post(host, port, {"texts": ["the cat"]})
    assert status == 503
    assert payload["error"] == "draining"
    status, health = _get(host, port, "/healthz")
    assert status == 503 and health["status"] == "draining"
    server.request_shutdown()
    assert server.wait() == 0


def test_healthz_warming_until_warmup_completes(serve_nlp):
    """Readiness gating regression: a replica whose bucket warmup sweep
    has not completed must answer 503 (not 200) on /healthz — and 503
    "warming" on /v1/parse — so a router never sends traffic into a
    mid-warmup compile. Only after the sweep does it report 200 ok."""
    engine = InferenceEngine(
        serve_nlp, max_batch_docs=4, max_wait_s=0.0, max_doc_len=32
    )
    server = Server(engine, "127.0.0.1", 0)
    host, port = server.start()
    try:
        # listener up, engine NOT started: the pre-ready window
        status, health = _get(host, port, "/healthz")
        assert status == 503 and health["status"] == "warming", health
        status, payload = _post(host, port, {"texts": ["the cat runs"]})
        assert status == 503 and payload["error"] == "warming", payload
        # warmup completes (shapes already compiled by the module's other
        # tests, so warmup=False stands in for the finished sweep)
        engine.start(warmup=False)
        status, health = _get(host, port, "/healthz")
        assert status == 200 and health["status"] == "ok"
        status, payload = _post(host, port, {"texts": ["the cat runs"]})
        assert status == 200 and payload["docs"][0]["tags"]
    finally:
        server.request_shutdown()
        assert server.wait() == 0


def test_disabled_telemetry_makes_zero_calls(serve_nlp, monkeypatch):
    """The training loop's contract, enforced for serving too: with no
    ServingTelemetry, the engine/server construct NOTHING from
    telemetry.py — any registry/trace construction raises."""
    from spacy_ray_tpu.training import telemetry as telemetry_mod

    def _boom(*a, **k):
        raise AssertionError("telemetry constructed on the disabled path")

    monkeypatch.setattr(telemetry_mod.MetricsRegistry, "__init__", _boom)
    monkeypatch.setattr(telemetry_mod.TraceBuffer, "__init__", _boom)
    # PR 12's diagnosis layer obeys the same contract: no telemetry =
    # no alert engine, no flight recorder, no observer ticker
    from spacy_ray_tpu import alerting as alerting_mod
    from spacy_ray_tpu import incidents as incidents_mod

    monkeypatch.setattr(alerting_mod.AlertEngine, "__init__", _boom)
    monkeypatch.setattr(incidents_mod.FlightRecorder, "__init__", _boom)
    # PR 18: no facade = no host sampler, no /proc reads, and the
    # /metrics reply carries no srt_process_* family
    from spacy_ray_tpu.training import hoststats as hoststats_mod

    monkeypatch.setattr(hoststats_mod.ProcessSampler, "__init__", _boom)
    engine = InferenceEngine(
        serve_nlp, max_batch_docs=4, max_wait_s=0.01, max_doc_len=32
    )
    engine.start(warmup=False)
    server = Server(engine, "127.0.0.1", 0)
    host, port = server.start()
    try:
        status, payload = _post(host, port, {"texts": [TEXTS[0]]})
        assert status == 200
        assert payload["docs"][0]["tags"]
        status, metrics = _get(host, port, "/metrics")
        # generation/swap_count are engine state, not telemetry — they
        # ride along even with the telemetry surface disabled
        assert status == 200 and metrics == {
            "telemetry": "disabled", "generation": None, "swap_count": 0,
        }
        # the distributed-tracing surfaces make zero telemetry calls on
        # the disabled path too: request IDs are protocol (the header
        # still echoes), but spans/exemplars/trace buffers must not
        # exist — the monkeypatched constructors above prove it by
        # raising on any construction
        import http.client as _hc

        conn = _hc.HTTPConnection(host, port, timeout=30.0)
        try:
            conn.request(
                "POST", "/v1/parse",
                json.dumps({"texts": [TEXTS[0]]}).encode("utf8"),
                {"Content-Type": "application/json",
                 "X-SRT-Request-Id": "client-id-42"},
            )
            resp = conn.getresponse()
            resp.read()
            assert resp.status == 200
            assert resp.getheader("X-SRT-Request-Id") == "client-id-42"
        finally:
            conn.close()
        status, exemplars = _get(host, port, "/admin/exemplars")
        assert status == 200 and exemplars == {"exemplars": "disabled"}
        status, trace = _get(host, port, "/trace")
        assert status == 200 and trace == {"trace": "disabled"}
        conn = _hc.HTTPConnection(host, port, timeout=30.0)
        try:
            conn.request("GET", "/metrics?format=prometheus")
            resp = conn.getresponse()
            body = resp.read().decode("utf8")
            assert resp.status == 200
            assert body == "# srt telemetry disabled\n"
        finally:
            conn.close()
    finally:
        server.request_shutdown()
        assert server.wait() == 0


# ----------------------------------------------------------------------
# Graceful drain: SIGTERM against a real `serve` subprocess
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def model_dir(serve_nlp, tmp_path_factory):
    out = tmp_path_factory.mktemp("serve_model") / "model"
    serve_nlp.to_disk(out)
    return out


def test_sigterm_graceful_drain_subprocess(model_dir):
    """Acceptance: SIGTERM mid-load completes the in-flight request,
    rejects new admissions, and the process exits 0. The in-flight
    request is HELD in the coalescing window (max_wait 600ms — window
    mode pinned: continuous admission would dispatch it before the
    signal) when the signal lands, so the drain provably finishes
    admitted-but-not-dispatched work."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    metrics_dir = model_dir.parent / "serve_metrics"
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "spacy_ray_tpu", "serve", str(model_dir),
            "--device", "cpu", "--port", "0",
            "--max-batch", "4", "--batching", "window",
            "--max-wait-ms", "600",
            "--max-doc-len", "16", "--drain-timeout-s", "30",
            "--metrics-dir", str(metrics_dir),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
    )
    lines = []
    addr = [None]

    def reader():
        for line in proc.stdout:
            lines.append(line)
            if line.startswith("serving on http://"):
                hostport = line.strip().rsplit("/", 1)[-1]
                host, port = hostport.rsplit(":", 1)
                addr[0] = (host, int(port))

    rt = threading.Thread(target=reader, daemon=True)
    rt.start()
    try:
        deadline = time.monotonic() + 180.0
        while addr[0] is None and time.monotonic() < deadline:
            if proc.poll() is not None:
                pytest.fail(f"serve exited early:\n{''.join(lines)}")
            time.sleep(0.1)
        assert addr[0] is not None, f"no banner:\n{''.join(lines)}"
        host, port = addr[0]

        # listener-first startup: the banner (and the port) appear BEFORE
        # the bucket warmup sweep; /healthz answers 503 "warming" until
        # the sweep completes — poll for readiness exactly like a fleet
        # router would
        ready_deadline = time.monotonic() + 150.0
        while True:
            status, health = _get(host, port, "/healthz", timeout=30.0)
            if status == 200:
                assert health["status"] == "ok"
                break
            assert status == 503 and health["status"] == "warming", health
            assert time.monotonic() < ready_deadline, (
                f"never became ready:\n{''.join(lines)}"
            )
            time.sleep(0.2)

        # in-flight request: sits in the 600ms coalescing window
        inflight = {}

        def one_request():
            try:
                inflight["result"] = _post(
                    host, port, {"texts": ["the cat runs"]}, timeout=60.0
                )
            except Exception as e:  # noqa: BLE001 — recorded for the assert
                inflight["result"] = e

        t = threading.Thread(target=one_request)
        t.start()
        time.sleep(0.2)  # inside the window: admitted, not yet dispatched
        proc.send_signal(signal.SIGTERM)

        t.join(timeout=60.0)
        result = inflight.get("result")
        assert isinstance(result, tuple) and result[0] == 200, (
            f"in-flight request not completed through the drain: {result!r}"
        )
        assert result[1]["docs"][0]["tags"]

        # new admissions after SIGTERM: typed 503 or (post-exit) refused
        try:
            status, payload = _post(
                host, port, {"texts": ["another request"]}, timeout=10.0
            )
            assert status == 503, (status, payload)
        except OSError:
            pass  # listener already closed — also a rejection

        rc = proc.wait(timeout=60.0)
        assert rc == 0, f"drain exit {rc}:\n{''.join(lines)}"
        assert any("drained; exiting 0" in l for l in lines), lines

        # --metrics-dir shutdown artifacts: the serving snapshot lands as
        # a `kind: "serving"` metrics.jsonl row that `telemetry
        # summarize` digests with the training-file contract
        from spacy_ray_tpu.training.telemetry import summarize_metrics

        rows = [
            json.loads(l)
            for l in open(metrics_dir / "metrics.jsonl", encoding="utf8")
        ]
        serving_rows = [r for r in rows if r.get("kind") == "serving"]
        assert serving_rows, rows
        assert serving_rows[-1]["counters"]["requests"] >= 1
        summary = summarize_metrics(metrics_dir / "metrics.jsonl")
        assert "serving: requests" in summary
        assert (metrics_dir / "serving_trace.json").exists()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10.0)


# ----------------------------------------------------------------------
# bench.py --serving session records
# ----------------------------------------------------------------------


def test_bench_serving_appends_session_records(tmp_path, monkeypatch):
    """Acceptance: --serving appends closed- and open-loop records with
    req/s, occupancy, and p50/p95/p99 latency to BENCH_SESSION.jsonl."""
    import bench

    session = tmp_path / "session.jsonl"
    monkeypatch.setattr(bench, "SESSION_FILE", session)
    records = bench.run_serving(
        "cpu", duration_s=0.6, clients=4, max_batch=4, max_wait_ms=3.0
    )
    assert [r["name"] for r in records] == ["serving_closed", "serving_open"]
    on_disk = [json.loads(l) for l in session.read_text().splitlines()]
    assert [r["name"] for r in on_disk] == ["serving_closed", "serving_open"]
    for rec in on_disk:
        assert rec["value"] > 0 and rec["unit"] == "req/s"
        assert rec["requests_ok"] > 0
        assert rec["latency_ms_p50"] is not None
        assert rec["latency_ms_p95"] is not None
        assert rec["latency_ms_p99"] is not None
        assert rec["batches"] and rec["occupancy_mean"] is not None
    closed, open_ = on_disk
    assert closed["clients"] == 4
    assert open_["offered_rps"] > 0


def test_committed_session_value_selects_matching_record(tmp_path, monkeypatch):
    """The open-loop offered rate derives from the matching committed
    record for the spec being run (latest wins, skips and mismatched
    shapes filtered) — never from a cross-methodology record. This is
    the PERF.md cross-round caveat closed in code."""
    import bench

    session = tmp_path / "session.jsonl"
    rows = [
        {"name": "serving_open", "offered_rps": 40.0,
         "max_batch_docs": 16, "texts_per_request": 2},
        {"name": "serving_open", "offered_rps": 99.0,
         "max_batch_docs": 8, "texts_per_request": 2},   # wrong shape
        {"name": "serving_open", "skipped": True, "offered_rps": 77.0,
         "max_batch_docs": 16, "texts_per_request": 2},  # skip record
        {"name": "serving_open", "offered_rps": 47.3,
         "max_batch_docs": 16, "texts_per_request": 2},  # newest match
        {"name": "serving_fleet_open", "offered_rps": 18.1, "replicas": 1,
         "max_batch_docs": 16, "texts_per_request": 2},
    ]
    session.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    monkeypatch.setattr(bench, "SESSION_FILE", session)
    assert bench._committed_session_value(
        "serving_open", max_batch_docs=16, texts_per_request=2
    ) == (47.3, "committed:serving_open.offered_rps")
    # the fleet spec at n=1 matches ITS pinned record, not the
    # single-engine one
    assert bench._committed_session_value(
        "serving_fleet_open", replicas=1, max_batch_docs=16,
        texts_per_request=2,
    ) == (18.1, "committed:serving_fleet_open.offered_rps")
    assert bench._committed_session_value(
        "serving_fleet_open", replicas=4, max_batch_docs=16,
        texts_per_request=2,
    ) is None
    monkeypatch.setattr(bench, "SESSION_FILE", tmp_path / "missing.jsonl")
    assert bench._committed_session_value("serving_open") is None


def test_bench_serving_ab_smoke(tmp_path, monkeypatch):
    """--serving-ab smoke: both admission arms run open-loop AT THE SAME
    committed offered rates (baseline + saturation), records carry the
    honest batching/precision labels and the rate's provenance."""
    import bench

    session = tmp_path / "session.jsonl"
    seed_rows = [
        {"name": "serving_open", "platform": "cpu", "offered_rps": 10.0,
         "max_batch_docs": 4, "texts_per_request": 2},
        {"name": "serving_closed", "platform": "cpu", "value": 18.0,
         "max_batch_docs": 4, "texts_per_request": 2},
        # a closed-loop record from ANOTHER backend must never set this
        # platform's operating point
        {"name": "serving_closed", "platform": "tpu", "value": 500.0,
         "max_batch_docs": 4, "texts_per_request": 2},
    ]
    session.write_text("\n".join(json.dumps(r) for r in seed_rows) + "\n")
    monkeypatch.setattr(bench, "SESSION_FILE", session)
    records = bench.run_serving_ab(
        "cpu", duration_s=0.5, max_batch=4, max_doc_len=32,
        skip_precision=True,
    )
    assert [(r["batching"], r["rate_point"]) for r in records] == [
        ("window", "baseline"), ("window", "saturation"),
        ("continuous", "baseline"), ("continuous", "saturation"),
    ]
    for rec in records:
        assert rec["name"] == "serving_ab_open"
        assert rec["precision"] == "f32"  # CPU: auto resolves OFF
        assert rec["requests_ok"] > 0
        assert rec["latency_ms_p99"] is not None
        assert rec["dispatch_wait_ms_p99"] is not None
    # both arms measured at the SAME fixed points, from committed records
    baselines = [r for r in records if r["rate_point"] == "baseline"]
    assert {r["offered_rps"] for r in baselines} == {10.0}
    assert {r["offered_rate_source"] for r in baselines} == {
        "committed:serving_open.offered_rps"
    }
    sats = [r for r in records if r["rate_point"] == "saturation"]
    assert {r["offered_rps"] for r in sats} == {18.0}
    # saturation pinning: once the A/B's own saturation record exists, a
    # newer closed-loop record (e.g. measured under continuous admission,
    # which saturates far higher) can no longer move the operating point
    with open(session, "a") as f:
        f.write(json.dumps({
            "name": "serving_closed", "platform": "cpu", "value": 99.0,
            "max_batch_docs": 4, "texts_per_request": 2,
        }) + "\n")
    assert bench._committed_session_value(
        "serving_ab_open", rate_point="saturation", platform="cpu",
        max_batch_docs=4, texts_per_request=2,
    ) == (18.0, "committed:serving_ab_open.offered_rps")


@pytest.mark.slow
def test_bench_serving_ab_with_precision_arms(tmp_path, monkeypatch):
    """Heavy variant: the full A/B including the trf precision arms —
    on CPU the f32 arm is auto-resolved and the bf16 arm carries the
    forced-overlay label (the honest-labeling acceptance)."""
    import bench

    session = tmp_path / "session.jsonl"
    monkeypatch.setattr(bench, "SESSION_FILE", session)
    records = bench.run_serving_ab(
        "cpu", duration_s=1.0, max_batch=4, max_doc_len=32,
    )
    precision = [r for r in records if r["name"] == "serving_precision_open"]
    assert [r["requested_precision"] for r in precision] == ["f32", "bf16"]
    f32_rec, bf16_rec = precision
    assert f32_rec["precision"] == "f32"
    assert bf16_rec["precision"] == "bf16"
    assert "forced" in bf16_rec["precision_label"]
    assert f32_rec["offered_rps"] == bf16_rec["offered_rps"]  # fixed rate
    for rec in precision:
        assert rec["requests_ok"] > 0


@pytest.mark.slow
def test_bench_serving_sustained_load(tmp_path, monkeypatch):
    """Heavy open/closed-loop variant at the real default shape (16-doc
    batches, 8 clients, 3s per loop) — the tier-2 version of the smoke
    above; occupancy must exceed 1 under saturation or dynamic batching
    is not actually batching."""
    import bench

    session = tmp_path / "session.jsonl"
    monkeypatch.setattr(bench, "SESSION_FILE", session)
    records = bench.run_serving("cpu", duration_s=3.0, clients=8)
    closed = records[0]
    assert closed["requests_ok"] >= 8
    assert closed["occupancy_max"] > 1, closed
