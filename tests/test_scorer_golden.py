"""Scorer golden tests: every scorer pinned to hand-computed P/R/F under
spaCy's exact Scorer conventions (SURVEY.md §7 "Scorer parity"; VERDICT r2
missing #3; reference worker.py:209-217 evaluates through spaCy's Scorer).

Each fixture's expected numbers are derived by hand in the comments —
edge cases covered: empty predictions, zero-division, per-type vs micro,
unannotated-doc skipping, punct exclusion in UAS/LAS, sentence spans as
two-boundary matches, None-when-no-gold."""

import pytest

from spacy_ray_tpu.pipeline.doc import Doc, Example, Span
from spacy_ray_tpu.pipeline import scoring
from spacy_ray_tpu.training.loop import weighted_score


def ex(gold: Doc, pred: Doc) -> Example:
    return Example(predicted=pred, reference=gold)


# ----------------------------------------------------------------------
# span scoring (ents / spancat)
# ----------------------------------------------------------------------


def _ents_score(examples):
    return scoring.score_spans(
        examples, "ents", lambda d: d.ents,
        has_annotation=lambda d: d.has_ents_annotation,
    )


def test_ents_micro_per_type_and_skip_unannotated():
    w = ["a"] * 5
    # doc1 annotated: gold A(0,1), B(2,4); pred A(0,1) [tp], B(2,3) [fp+fn]
    d1g = Doc(words=w, ents=[Span(0, 1, "A"), Span(2, 4, "B")])
    d1p = Doc(words=w, ents=[Span(0, 1, "A"), Span(2, 3, "B")])
    # doc2 UNANNOTATED gold: its prediction must NOT count as fp
    d2g = Doc(words=w, ents_annotated=False)
    d2p = Doc(words=w, ents=[Span(0, 1, "A")])
    # doc3 annotated with ZERO entities: its prediction IS an fp
    d3g = Doc(words=w, ents=[], ents_annotated=True)
    d3p = Doc(words=w, ents=[Span(1, 2, "A")])
    s = _ents_score([ex(d1g, d1p), ex(d2g, d2p), ex(d3g, d3p)])
    # micro: tp=1, fp=2, fn=1 -> p=1/3, r=1/2, f=0.4
    assert s["ents_p"] == pytest.approx(1 / 3)
    assert s["ents_r"] == pytest.approx(1 / 2)
    assert s["ents_f"] == pytest.approx(0.4)
    # per-type A: tp=1 (d1), fp=1 (d3) -> p=1/2, r=1, f=2/3
    assert s["ents_per_type"]["A"]["p"] == pytest.approx(0.5)
    assert s["ents_per_type"]["A"]["r"] == pytest.approx(1.0)
    assert s["ents_per_type"]["A"]["f"] == pytest.approx(2 / 3)
    assert s["ents_f_A"] == pytest.approx(2 / 3)
    # per-type B: tp=0, fp=1, fn=1 -> all 0.0 (zero-division convention)
    assert s["ents_per_type"]["B"] == {"p": 0.0, "r": 0.0, "f": 0.0}


def test_ents_empty_predictions_zero_not_crash():
    g = Doc(words=["a", "b"], ents=[Span(0, 1, "A"), Span(1, 2, "B")])
    p = Doc(words=["a", "b"])
    s = _ents_score([ex(g, p)])
    # tp=0, fp=0, fn=2: p=0/0 -> 0.0, r=0, f=0
    assert (s["ents_p"], s["ents_r"], s["ents_f"]) == (0.0, 0.0, 0.0)


def test_ents_none_when_no_gold_annotation():
    g = Doc(words=["a"], ents_annotated=False)
    p = Doc(words=["a"], ents=[Span(0, 1, "A")])
    s = _ents_score([ex(g, p)])
    assert s["ents_p"] is None and s["ents_r"] is None and s["ents_f"] is None
    assert s["ents_per_type"] is None


def test_spancat_missing_key_skipped_but_empty_key_counts():
    w = ["a"] * 4
    g1 = Doc(words=w)  # no "sc" key at all: skipped
    p1 = Doc(words=w)
    p1.spans["sc"] = [Span(0, 1, "X")]
    g2 = Doc(words=w)
    g2.spans["sc"] = []  # key present, no spans: predictions are fp
    p2 = Doc(words=w)
    p2.spans["sc"] = [Span(1, 2, "X")]
    s = scoring.score_spans(
        [ex(g1, p1), ex(g2, p2)], "spans_sc",
        lambda d: d.spans.get("sc", []),
        has_annotation=lambda d: "sc" in d.spans,
    )
    # only doc2 scored: tp=0, fp=1, fn=0
    assert (s["spans_sc_p"], s["spans_sc_r"], s["spans_sc_f"]) == (0.0, 0.0, 0.0)


def test_spancat_overlapping_spans_all_count():
    w = ["a"] * 6
    g = Doc(words=w)
    g.spans["sc"] = [Span(0, 3, "X"), Span(1, 3, "X"), Span(2, 3, "Y")]
    p = Doc(words=w)
    p.spans["sc"] = [Span(0, 3, "X"), Span(1, 3, "X")]
    s = scoring.score_spans(
        [ex(g, p)], "spans_sc", lambda d: d.spans.get("sc", []),
        has_annotation=lambda d: "sc" in d.spans,
    )
    # tp=2, fp=0, fn=1 -> p=1, r=2/3, f=0.8
    assert s["spans_sc_p"] == pytest.approx(1.0)
    assert s["spans_sc_r"] == pytest.approx(2 / 3)
    assert s["spans_sc_f"] == pytest.approx(0.8)


# ----------------------------------------------------------------------
# token accuracy (tag / pos / morph / lemma)
# ----------------------------------------------------------------------


def test_tag_acc_missing_gold_excluded():
    g = Doc(words=["a", "b", "c", "d"], tags=["N", "V", "", "A"])
    p = Doc(words=["a", "b", "c", "d"], tags=["N", "X", "Y", "A"])
    s = scoring.score_token_acc([ex(g, p)], "tag_acc", lambda d: d.tags)
    # scored positions: 0 (N==N), 1 (V!=X), 3 (A==A) -> 2/3
    assert s["tag_acc"] == pytest.approx(2 / 3)


def test_tag_acc_none_when_unannotated():
    g = Doc(words=["a"], tags=None)
    p = Doc(words=["a"], tags=["N"])
    assert scoring.score_token_acc([ex(g, p)], "tag_acc", lambda d: d.tags) == {
        "tag_acc": None
    }


def test_tag_acc_short_prediction_counts_as_wrong():
    g = Doc(words=["a", "b"], tags=["N", "V"])
    p = Doc(words=["a", "b"], tags=["N"])  # truncated prediction
    s = scoring.score_token_acc([ex(g, p)], "tag_acc", lambda d: d.tags)
    assert s["tag_acc"] == pytest.approx(1 / 2)


# ----------------------------------------------------------------------
# dependency scoring (UAS / LAS, punct exclusion)
# ----------------------------------------------------------------------


def test_deps_punct_excluded_and_case_insensitive():
    w = ["He", "runs", ",", "fast"]
    g = Doc(words=w, heads=[1, 1, 1, 1], deps=["nsubj", "ROOT", "punct", "obj"])
    # pred: head of token3 wrong label only; token2 predicted punct (excluded
    # on both sides); 'root' lowercase must match gold 'ROOT'
    p = Doc(words=w, heads=[1, 1, 1, 1], deps=["nsubj", "root", "punct", "iobj"])
    s = scoring.score_deps([ex(g, p)])
    # gold set (punct dropped): {(0,1,nsubj),(1,1,root),(3,1,obj)}
    # pred set:                 {(0,1,nsubj),(1,1,root),(3,1,iobj)}
    # labeled: tp=2 fp=1 fn=1 -> f=2/3 ; unlabeled: all 3 heads right -> 1.0
    assert s["dep_uas"] == pytest.approx(1.0)
    assert s["dep_las"] == pytest.approx(2 / 3)
    assert s["dep_las_per_type"]["obj"] == {"p": 0.0, "r": 0.0, "f": 0.0}
    assert s["dep_las_per_type"]["nsubj"]["f"] == pytest.approx(1.0)


def test_deps_gold_punct_mispredicted_is_false_positive():
    w = ["a", "b", "."]
    g = Doc(words=w, heads=[1, 1, 1], deps=["nsubj", "ROOT", "punct"])
    # token2: gold punct (dropped from gold set) but PREDICTED nsubj ->
    # stays in the pred set -> false positive (spaCy's per-side exclusion)
    p = Doc(words=w, heads=[1, 1, 1], deps=["nsubj", "root", "nsubj"])
    s = scoring.score_deps([ex(g, p)])
    # gold: {(0,1,nsubj),(1,1,root)}; pred: {(0,1,nsubj),(1,1,root),(2,1,nsubj)}
    # labeled tp=2 fp=1 fn=0 -> p=2/3, r=1, f=0.8
    assert s["dep_las"] == pytest.approx(0.8)
    assert s["dep_uas"] == pytest.approx(0.8)


def test_deps_none_when_no_gold_heads():
    g = Doc(words=["a"])
    p = Doc(words=["a"], heads=[0], deps=["ROOT"])
    s = scoring.score_deps([ex(g, p)])
    assert s["dep_uas"] is None and s["dep_las"] is None


# ----------------------------------------------------------------------
# sentence scoring (span-based, both boundaries)
# ----------------------------------------------------------------------


def test_sents_scored_as_spans_not_boundaries():
    w = ["a"] * 6
    # gold sentences: (0,3), (3,6); pred: (0,2), (2,3), (3,6)
    g = Doc(words=w, sent_starts=[1, 0, 0, 1, 0, 0])
    p = Doc(words=w, sent_starts=[1, 0, 1, 1, 0, 0])
    s = scoring.score_sents([ex(g, p)])
    # tp=1 ((3,6)), fp=2, fn=1 -> p=1/3, r=1/2, f=0.4
    assert s["sents_p"] == pytest.approx(1 / 3)
    assert s["sents_r"] == pytest.approx(1 / 2)
    assert s["sents_f"] == pytest.approx(0.4)
    # NOTE: per-boundary scoring would give tp=1 fp=1 fn=1 (f=0.5) — the
    # span convention is strictly different and this pin catches a regression


def test_sents_exact_match_and_none_when_unannotated():
    w = ["a"] * 4
    g = Doc(words=w, sent_starts=[1, 0, 1, 0])
    p = Doc(words=w, sent_starts=[1, 0, 1, 0])
    assert scoring.score_sents([ex(g, p)])["sents_f"] == pytest.approx(1.0)
    g2 = Doc(words=w)  # no sent annotation
    p2 = Doc(words=w, sent_starts=[1, 0, 1, 0])
    assert scoring.score_sents([ex(g2, p2)])["sents_f"] is None


# ----------------------------------------------------------------------
# morphology per-feature
# ----------------------------------------------------------------------


def test_morph_per_feat_golden():
    w = ["a", "b"]
    g = Doc(words=w, morphs=["Number=Sing|Person=3", "Number=Plur"])
    p = Doc(words=w, morphs=["Number=Sing", "Number=Sing|Person=3"])
    s = scoring.score_morph_per_feat([ex(g, p)])
    per = s["morph_per_feat"]
    # Number: tok0 match (tp), tok1 Plur vs Sing (fp+fn) -> p=r=f=0.5
    assert per["Number"] == {"p": 0.5, "r": 0.5, "f": 0.5}
    # Person: tok0 gold-only (fn), tok1 pred-only (fp) -> 0.0
    assert per["Person"] == {"p": 0.0, "r": 0.0, "f": 0.0}


# ----------------------------------------------------------------------
# textcat
# ----------------------------------------------------------------------


def _textcat(exclusive=False, threshold=0.5, labels=("A", "B")):
    from spacy_ray_tpu.pipeline.components.textcat import TextCatComponent

    c = TextCatComponent("textcat", {}, exclusive=exclusive, threshold=threshold)
    c.labels = list(labels)
    return c


def test_cats_micro_macro_auc_golden():
    c = _textcat()
    w = ["x"]
    egs = [
        # d1: A gold+ pred+ (tp); B gold- pred- (nothing)
        ex(Doc(words=w, cats={"A": 1.0, "B": 0.0}),
           Doc(words=w, cats={"A": 0.9, "B": 0.2})),
        # d2: A gold- pred+ (fp); B gold+ pred- (fn)
        ex(Doc(words=w, cats={"A": 0.0, "B": 1.0}),
           Doc(words=w, cats={"A": 0.7, "B": 0.4})),
        # d3: no gold cats -> skipped entirely
        ex(Doc(words=w), Doc(words=w, cats={"A": 1.0, "B": 1.0})),
    ]
    s = c.score(egs)
    # micro: tp=1 fp=1 fn=1 -> p=r=f=0.5
    assert s["cats_micro_p"] == pytest.approx(0.5)
    assert s["cats_micro_r"] == pytest.approx(0.5)
    assert s["cats_micro_f"] == pytest.approx(0.5)
    # per-type: A tp=1 fp=1 -> f=2/3 ; B fn=1 -> f=0 ; macro = 1/3
    assert s["cats_f_per_type"]["A"]["f"] == pytest.approx(2 / 3)
    assert s["cats_f_per_type"]["B"]["f"] == pytest.approx(0.0)
    assert s["cats_macro_f"] == pytest.approx(1 / 3)
    # AUC: A gold [1,0] scores [.9,.7] -> 1.0 ; B gold [0,1] scores [.2,.4]
    # -> 1.0 ; macro 1.0
    assert s["cats_macro_auc"] == pytest.approx(1.0)


def test_cats_none_when_no_gold():
    c = _textcat()
    egs = [ex(Doc(words=["x"]), Doc(words=["x"], cats={"A": 1.0}))]
    s = c.score(egs)
    assert s["cats_micro_f"] is None
    assert s["cats_score"] is None
    assert s["cats_f_per_type"] is None


def test_cats_exclusive_accuracy():
    c = _textcat(exclusive=True)
    w = ["x"]
    egs = [
        ex(Doc(words=w, cats={"A": 1.0, "B": 0.0}),
           Doc(words=w, cats={"A": 0.8, "B": 0.2})),
        ex(Doc(words=w, cats={"A": 0.0, "B": 1.0}),
           Doc(words=w, cats={"A": 0.6, "B": 0.4})),
    ]
    s = c.score(egs)
    assert s["cats_acc"] == pytest.approx(0.5)
    assert s["cats_score"] == pytest.approx(0.5)


def test_rank_auc_ties_and_single_class():
    assert scoring.rank_auc([1, 0], [0.5, 0.5]) == pytest.approx(0.5)
    assert scoring.rank_auc([1, 1], [0.9, 0.1]) is None
    assert scoring.rank_auc([1, 0, 0], [0.9, 0.1, 0.95]) == pytest.approx(0.5)


# ----------------------------------------------------------------------
# final-score aggregation
# ----------------------------------------------------------------------


def test_weighted_score_excludes_none():
    # spaCy: a None score is excluded, NOT counted as zero
    s = weighted_score({"tag_acc": None, "dep_las": 0.8}, {"tag_acc": 0.5, "dep_las": 0.5})
    assert s == pytest.approx(0.4)
    # fallback mean ignores None and nested dicts
    s2 = weighted_score({"a": 0.4, "b": None, "c": {"x": 1.0}}, {})
    assert s2 == pytest.approx(0.4)


# ----------------------------------------------------------------------
# component scorers route through the shared conventions
# ----------------------------------------------------------------------


def test_lemma_acc_is_case_sensitive():
    from spacy_ray_tpu.pipeline.components.edit_tree_lemmatizer import (
        EditTreeLemmatizerComponent,
    )

    g = Doc(words=["Dogs"], lemmas=["dog"])
    p = Doc(words=["Dogs"], lemmas=["Dog"])  # case differs: wrong in spaCy
    comp = EditTreeLemmatizerComponent.__new__(EditTreeLemmatizerComponent)
    assert comp.score([ex(g, p)])["lemma_acc"] == 0.0


def test_docbin_preserves_annotated_empty_ents(tmp_path):
    # round-trip the 0-vs-2 distinction through the .spacy format
    from spacy_ray_tpu.training import spacy_docbin as SD

    annotated_empty = Doc(words=["a", "b"], ents=[], ents_annotated=True)
    missing = Doc(words=["a", "b"])
    SD.write_docbin(tmp_path / "x.spacy", [annotated_empty, missing])
    d1, d2 = list(SD.read_docbin(tmp_path / "x.spacy"))
    assert d1.has_ents_annotation is True
    assert d2.has_ents_annotation is False


# ----------------------------------------------------------------------
# external oracles (sklearn is in-image): pin our implementations to the
# canonical library, not just to hand-derived goldens
# ----------------------------------------------------------------------


def test_rank_auc_matches_sklearn():
    import pytest

    sk = pytest.importorskip("sklearn.metrics")
    import random

    from spacy_ray_tpu.pipeline.scoring import rank_auc

    rng = random.Random(0)
    for trial in range(20):
        n = rng.randint(4, 60)
        gold = [rng.random() < 0.4 for _ in range(n)]
        # quantized scores force ties — the half-credit convention must
        # match sklearn's trapezoidal handling
        scores = [round(rng.random(), 1) for _ in range(n)]
        ours = rank_auc([int(g) for g in gold], scores)
        if len(set(gold)) < 2:
            assert ours is None
            continue
        want = sk.roc_auc_score(gold, scores)
        assert ours == pytest.approx(want, abs=1e-9), (trial, gold, scores)


def test_prf_matches_sklearn():
    import pytest

    sk = pytest.importorskip("sklearn.metrics")
    import random

    from spacy_ray_tpu.pipeline.scoring import PRF

    rng = random.Random(1)
    for trial in range(20):
        n = rng.randint(5, 80)
        universe = list(range(n))
        pred = {i for i in universe if rng.random() < 0.5}
        gold = {i for i in universe if rng.random() < 0.5}
        prf = PRF()
        prf.score_sets(pred, gold)
        y_true = [i in gold for i in universe]
        y_pred = [i in pred for i in universe]
        p, r, f, _ = sk.precision_recall_fscore_support(
            y_true, y_pred, average="binary", zero_division=0
        )
        assert prf.precision == pytest.approx(p, abs=1e-9)
        assert prf.recall == pytest.approx(r, abs=1e-9)
        assert prf.fscore == pytest.approx(f, abs=1e-9)
