"""Background batch prefetch (training/prefetch.py)."""

import threading
import time

import pytest

from spacy_ray_tpu.training.prefetch import prefetch_iter


def test_yields_everything_in_order():
    assert list(prefetch_iter(iter(range(100)), size=4)) == list(range(100))


def test_size_below_two_is_passthrough():
    it = iter([1, 2, 3])
    assert prefetch_iter(it, size=1) is it


def test_producer_exception_reraises_at_consumer():
    def gen():
        yield 1
        raise ValueError("boom")

    out = prefetch_iter(gen(), size=2)
    assert next(out) == 1
    with pytest.raises(ValueError, match="boom"):
        next(out)


def test_producer_runs_ahead_bounded():
    produced = []

    def gen():
        for i in range(10):
            produced.append(i)
            yield i

    out = prefetch_iter(gen(), size=2)
    deadline = time.time() + 5.0
    # producer should buffer up to size items without any consumption…
    while len(produced) < 2 and time.time() < deadline:
        time.sleep(0.01)
    time.sleep(0.05)
    assert 2 <= len(produced) <= 3  # size in queue (+1 in-flight at the put)
    # …and the consumer still sees the full ordered stream
    assert list(out) == list(range(10))
