"""Background batch prefetch (training/prefetch.py) and the parallel
input pipeline built on top of it (training/collate_pool.py): ordered
multi-worker collation, the epoch-level collation cache, and the
training-loop integration (augmentation bypass, exact resume through the
pool)."""

import threading
import time

import numpy as np
import pytest

from spacy_ray_tpu.training.collate_pool import (
    CollateCache,
    OrderedPool,
    PipelineStats,
    ordered_map,
)
from spacy_ray_tpu.training.prefetch import prefetch_iter


def test_yields_everything_in_order():
    assert list(prefetch_iter(iter(range(100)), size=4)) == list(range(100))


def test_size_below_two_is_passthrough():
    it = iter([1, 2, 3])
    assert prefetch_iter(it, size=1) is it


def test_producer_exception_reraises_at_consumer():
    def gen():
        yield 1
        raise ValueError("boom")

    out = prefetch_iter(gen(), size=2)
    assert next(out) == 1
    with pytest.raises(ValueError, match="boom"):
        next(out)


def test_producer_runs_ahead_bounded():
    produced = []

    def gen():
        for i in range(10):
            produced.append(i)
            yield i

    out = prefetch_iter(gen(), size=2)
    deadline = time.time() + 5.0
    # producer should buffer up to size items without any consumption…
    while len(produced) < 2 and time.time() < deadline:
        time.sleep(0.01)
    time.sleep(0.05)
    assert 2 <= len(produced) <= 3  # size in queue (+1 in-flight at the put)
    # …and the consumer still sees the full ordered stream
    assert list(out) == list(range(10))


# ----------------------------------------------------------------------
# OrderedPool: multi-worker collation with preserved order
# ----------------------------------------------------------------------


def test_ordered_pool_preserves_order_under_uneven_work():
    # every third item is SLOW: fast items finish first on other workers
    # but must still be yielded in submission order
    def fn(i):
        if i % 3 == 0:
            time.sleep(0.02)
        return i * 2

    out = list(ordered_map(iter(range(40)), fn, workers=4))
    assert out == [i * 2 for i in range(40)]


def test_ordered_pool_below_two_workers_is_inline():
    threads = []

    def fn(i):
        threads.append(threading.current_thread())
        return i

    assert list(ordered_map(iter(range(5)), fn, workers=1)) == list(range(5))
    assert all(t is threading.current_thread() for t in threads)


def test_ordered_pool_fn_exception_propagates_in_order():
    def fn(i):
        if i == 3:
            raise ValueError("boom3")
        return i

    it = ordered_map(iter(range(10)), fn, workers=4)
    assert [next(it) for _ in range(3)] == [0, 1, 2]
    with pytest.raises(ValueError, match="boom3"):
        next(it)
    with pytest.raises(StopIteration):  # pool closed after the error
        next(it)


def test_ordered_pool_source_exception_propagates():
    def gen():
        yield 1
        yield 2
        raise RuntimeError("src boom")

    it = ordered_map(gen(), lambda x: x, workers=2)
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(RuntimeError, match="src boom"):
        next(it)


def test_ordered_pool_close_stops_feeder_and_closes_source():
    source_closed = []

    def gen():
        try:
            for i in range(100000):
                yield i
        finally:
            source_closed.append(True)

    pool = OrderedPool(gen(), lambda x: x, workers=2)
    assert next(pool) == 0
    pool.close()
    deadline = time.time() + 5.0
    while pool._feeder.is_alive() and time.time() < deadline:
        time.sleep(0.01)
    assert not pool._feeder.is_alive()
    assert source_closed == [True]
    with pytest.raises(StopIteration):
        next(pool)
    pool.close()  # idempotent


def test_ordered_pool_runs_ahead_bounded():
    produced = []

    def gen():
        for i in range(100):
            produced.append(i)
            yield i

    pool = OrderedPool(gen(), lambda x: x, workers=2)
    deadline = time.time() + 5.0
    while len(produced) < 4 and time.time() < deadline:
        time.sleep(0.01)
    time.sleep(0.1)
    # bounded run-ahead: queue size (2*workers) + workers in flight + one
    # blocked at the put — never the whole epoch
    assert 4 <= len(produced) <= 8
    assert list(pool) == list(range(100))


# ----------------------------------------------------------------------
# CollateCache: identity-keyed, byte-capped LRU
# ----------------------------------------------------------------------


class _Eg:  # stand-in Example: the cache only uses identity
    pass


def test_collate_cache_hit_miss_and_identity():
    cache = CollateCache(1 << 20)
    egs = [_Eg(), _Eg()]
    value = {"x": np.ones(10)}
    assert cache.get(egs, 8, 16) is None  # cold miss
    cache.put(egs, 8, 16, value)
    assert cache.get(egs, 8, 16) is value  # hit: same objects, same bucket
    assert cache.get(egs, 8, 32) is None  # different bucket shape
    assert cache.get(egs[:1], 8, 16) is None  # different batch
    assert cache.hits == 1 and cache.misses == 3


def test_collate_cache_byte_budget_evicts_lru():
    cache = CollateCache(3000)
    batches = [[_Eg()] for _ in range(4)]
    for b in batches:
        cache.put(b, 1, 1, {"a": np.zeros(1000, np.uint8)})
    # 4000 bytes > 3000 budget: the oldest entry was evicted
    assert cache.evictions == 1
    assert cache.get(batches[0], 1, 1) is None
    assert cache.get(batches[3], 1, 1) is not None
    assert cache.nbytes <= 3000


def test_collate_cache_oversized_entry_rejected():
    cache = CollateCache(100)
    b = [_Eg()]
    cache.put(b, 1, 1, {"a": np.zeros(1000, np.uint8)})
    assert len(cache) == 0  # one oversized batch must not flush the cache
    assert cache.get(b, 1, 1) is None


def test_collate_cache_thread_safety_smoke():
    cache = CollateCache(1 << 16)
    batches = [[_Eg()] for _ in range(16)]
    errors = []

    def worker():
        try:
            for _ in range(200):
                for b in batches:
                    if cache.get(b, 4, 8) is None:
                        cache.put(b, 4, 8, {"a": np.zeros(128, np.uint8)})
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert cache.nbytes <= 1 << 16


def test_pipeline_stats_snapshot_shape():
    stats = PipelineStats()
    with stats.timer("collate"):
        pass
    stats.add("read", 0.5)
    stats.hit()
    stats.miss()
    snap = stats.snapshot()
    assert set(snap["stage_seconds"]) == {"read", "collate", "transfer",
                                          "queue_wait"}
    assert snap["stage_counts"]["read"] == 1
    assert snap["cache"] == {"enabled": False, "hits": 1, "misses": 1}


# ----------------------------------------------------------------------
# Training-loop integration: pool + cache + exact resume
# ----------------------------------------------------------------------

POOL_CFG = """
[paths]
train = null
dev = null

[nlp]
lang = "en"
pipeline = ["tok2vec","tagger"]

[components.tok2vec]
factory = "tok2vec"

[components.tok2vec.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = 32
depth = 1
embed_size = 128

[components.tagger]
factory = "tagger"

[components.tagger.model]
@architectures = "spacy.Tagger.v2"

[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 32

[corpora.train]
@readers = "spacy.Corpus.v1"
path = ${paths.train}
shuffle = true
seed = 3

[corpora.dev]
@readers = "spacy.JsonlCorpus.v1"
path = ${paths.dev}

[training]
seed = 0
patience = 0
max_steps = 16
eval_frequency = 4
collate_workers = 3
collate_cache_mb = 32

[training.optimizer]
@optimizers = "Adam.v1"
learn_rate = 0.01

[training.batcher]
@batchers = "spacy.batch_by_words.v1"
size = 150
tolerance = 0.2
"""


def _pool_cfg(tmp_path, **over):
    from spacy_ray_tpu.config import Config
    from spacy_ray_tpu.util import write_synth_jsonl

    train_path = tmp_path / "train.jsonl"
    if not train_path.exists():
        write_synth_jsonl(train_path, 40, kind="tagger", seed=0)
        write_synth_jsonl(tmp_path / "dev.jsonl", 12, kind="tagger", seed=1)
    return Config.from_str(POOL_CFG).apply_overrides(
        {
            "paths.train": str(train_path),
            "paths.dev": str(tmp_path / "dev.jsonl"),
            **over,
        }
    )


def test_pooled_cached_training_matches_inline_exactly(tmp_path):
    """collate_workers + collate_cache must be pure plumbing: identical
    params to the single-threaded uncached path, batch for batch."""
    import jax

    from spacy_ray_tpu.training.loop import train

    # shuffle OFF: epochs repeat the same batches, so the identity-keyed
    # cache actually hits (under shuffle the batch composition changes
    # every epoch and the cache only churns — see docs/TUNING.md)
    stable = {"corpora.train.shuffle": False}
    nlp_pool, res_pool = train(
        _pool_cfg(tmp_path, **stable), n_workers=1, stdout_log=False
    )
    snap = res_pool.history[-1]["input_pipeline"]
    assert snap["workers"] == 3
    assert snap["cache"]["enabled"] is True
    assert snap["cache"]["hits"] > 0  # epoch 2+ re-collations hit
    nlp_inline, _ = train(
        _pool_cfg(
            tmp_path,
            **{
                "training.collate_workers": 0,
                "training.collate_cache_mb": 0,
                **stable,
            },
        ),
        n_workers=1,
        stdout_log=False,
    )
    la = jax.tree_util.tree_leaves(nlp_pool.params)
    lb = jax.tree_util.tree_leaves(nlp_inline.params)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resume_exact_through_pool_and_cache(tmp_path):
    """Data-position tags (batches_in_epoch / corpus_epoch) must survive
    the pool: straight-through vs checkpoint+resume end bit-identical
    with collate_workers + cache enabled and a shuffled corpus."""
    import jax

    from spacy_ray_tpu.training.loop import train

    nlp_a, _ = train(
        _pool_cfg(tmp_path),
        output_path=tmp_path / "a",
        n_workers=1,
        stdout_log=False,
    )
    _, rb1 = train(
        _pool_cfg(tmp_path, **{"training.max_steps": 8}),
        output_path=tmp_path / "b",
        n_workers=1,
        stdout_log=False,
    )
    assert rb1.final_step == 8
    nlp_b, rb2 = train(
        _pool_cfg(tmp_path),
        output_path=tmp_path / "b",
        n_workers=1,
        resume=True,
        stdout_log=False,
    )
    assert rb2.final_step == 16
    la = jax.tree_util.tree_leaves(nlp_a.params)
    lb = jax.tree_util.tree_leaves(nlp_b.params)
    for a, b in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_augmentation_bypasses_collate_cache(tmp_path):
    """An active augmenter yields FRESH Example copies per epoch — the
    identity-keyed cache can never hit, so the loop must disable it."""
    from spacy_ray_tpu.training.loop import train

    cfg = _pool_cfg(tmp_path, **{"training.max_steps": 8})
    cfg["corpora"]["train"]["augmenter"] = {
        "@augmenters": "spacy.lower_case.v1",
        "level": 0.5,
    }
    _, res = train(cfg, n_workers=1, stdout_log=False)
    snap = res.history[-1]["input_pipeline"]
    assert snap["cache"]["enabled"] is False
    assert snap["cache"]["hits"] == 0 and snap["cache"]["misses"] == 0


def test_shuffle_bypasses_collate_cache(tmp_path):
    """POOL_CFG shuffles the corpus: batch membership changes every epoch,
    so the identity-keyed cache could never hit — the loop must disable
    it (Corpus.stable_identity) rather than churn the LRU."""
    from spacy_ray_tpu.training.loop import train

    _, res = train(
        _pool_cfg(tmp_path, **{"training.max_steps": 8}),
        n_workers=1,
        stdout_log=False,
    )
    snap = res.history[-1]["input_pipeline"]
    assert snap["cache"]["enabled"] is False
    assert snap["cache"]["hits"] == 0 and snap["cache"]["misses"] == 0
