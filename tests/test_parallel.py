"""Distribution-layer tests on a REAL 8-device CPU mesh.

The sync-protocol coverage the reference never had (SURVEY.md §4: the proxy
versioning/quorum machinery is "entirely untested", which let the
get_quorum dead-code bug survive): here the equivalent exchange — gradient
all-reduce + ZeRO-1 sharded update — runs as compiled SPMD programs on 8
virtual devices and is checked for numerical equivalence against the
single-device step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spacy_ray_tpu.config import Config
from spacy_ray_tpu.parallel.mesh import build_mesh, zero1_spec
from spacy_ray_tpu.parallel.step import (
    make_train_step,
    place_batch,
    place_replicated,
    shard_opt_state,
)
from spacy_ray_tpu.pipeline.language import Pipeline
from spacy_ray_tpu.registry import registry
from spacy_ray_tpu.util import synth_corpus


def _fixed_len_examples(n, length=16, seed=0):
    """Docs padded/cut to exactly `length` tokens for equivalence tests."""
    import random

    from spacy_ray_tpu.pipeline.doc import Doc, Example
    from spacy_ray_tpu.util import _POS_VOCAB

    rng = random.Random(seed)
    out = []
    pos_names = list(_POS_VOCAB)
    for _ in range(n):
        words, tags = [], []
        for _ in range(length):
            p = rng.choice(pos_names)
            words.append(rng.choice(_POS_VOCAB[p]))
            tags.append(p)
        out.append(Example.from_gold(Doc(words=words, tags=tags)))
    return out


@pytest.fixture(scope="module")
def small_nlp():
    cfg = Config.from_str(
        """
[nlp]
lang = "en"
pipeline = ["tok2vec","tagger"]

[components.tok2vec]
factory = "tok2vec"

[components.tok2vec.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = 32
depth = 2
embed_size = 256

[components.tagger]
factory = "tagger"

[components.tagger.model]
@architectures = "spacy.Tagger.v2"

[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 32
"""
    )
    nlp = Pipeline.from_config(cfg)
    nlp.initialize(lambda: iter(_fixed_len_examples(64)), seed=0)
    return nlp


def _run_steps(nlp, n_data, n_steps=2, zero1=False, B=16):
    examples = _fixed_len_examples(B * n_steps, seed=1)
    mesh = build_mesh(n_data=n_data)
    tx = registry.get("optimizers", "Adam.v1")(learn_rate=0.01)
    params = place_replicated(nlp.params, mesh)
    opt_state = shard_opt_state(tx.init(params), mesh, zero1=zero1)
    update = make_train_step(
        nlp.make_loss_fn(), tx, mesh, zero1=zero1,
        opt_state_template=opt_state, donate=False,
    )
    rng = jax.random.PRNGKey(42)
    losses = []
    for s in range(n_steps):
        batch = nlp.collate(
            examples[s * B : (s + 1) * B], pad_batch_to=B, pad_len_to=16
        )
        tokens = place_batch(batch["tokens"], mesh)
        targets = place_batch(batch["targets"], mesh)
        # fixed rng per step (not split) so dropout noise matches across runs
        params, opt_state, loss, metrics = update(
            params, opt_state, tokens, targets, jax.random.fold_in(rng, s)
        )
        losses.append(float(loss))
    return jax.device_get(params), losses


@pytest.mark.slow
def test_dp8_matches_single_device(small_nlp):
    """Gradient all-reduce over 8 devices == single-device step (the
    correctness property the reference's async quorum only approximates)."""
    p1, l1 = _run_steps(small_nlp, n_data=1)
    p8, l8 = _run_steps(small_nlp, n_data=8)
    np.testing.assert_allclose(l1, l8, rtol=2e-4)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p8)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-2, atol=1e-5)


@pytest.mark.slow
def test_zero1_matches_replicated(small_nlp):
    """ZeRO-1 sharded optimizer state must be a pure layout change."""
    p_repl, l_repl = _run_steps(small_nlp, n_data=8, zero1=False)
    p_z1, l_z1 = _run_steps(small_nlp, n_data=8, zero1=True)
    np.testing.assert_allclose(l_repl, l_z1, rtol=2e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(p_repl), jax.tree_util.tree_leaves(p_z1)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-2, atol=1e-5)


def test_zero1_spec_shards_divisible_leaves(mesh8):
    leaf = jnp.zeros((64, 32))
    spec = tuple(zero1_spec(leaf, mesh8).spec)
    assert "data" in spec and spec[0] == "data"
    odd = jnp.zeros((7, 3))
    assert "data" not in tuple(zero1_spec(odd, mesh8).spec)


def test_zero1_opt_state_is_sharded(small_nlp, mesh8):
    tx = registry.get("optimizers", "Adam.v1")(learn_rate=0.01)
    params = place_replicated(small_nlp.params, mesh8)
    opt_state = shard_opt_state(tx.init(params), mesh8, zero1=True)
    shardings = [
        leaf.sharding
        for leaf in jax.tree_util.tree_leaves(opt_state)
        if hasattr(leaf, "sharding") and hasattr(leaf, "shape") and leaf.ndim >= 1
    ]
    sharded = [
        s for s in shardings if s.spec != jax.sharding.PartitionSpec()
    ]
    # the big moment tensors (embed tables: 256 rows % 8 == 0) must be sharded
    assert len(sharded) > 0


@pytest.mark.slow
def test_grad_accumulation_equivalence(small_nlp):
    """accum=2 over two equal microbatches == one step over their union."""
    examples = _fixed_len_examples(32, seed=3)
    mesh = build_mesh(n_data=1)
    tx = registry.get("optimizers", "SGD.v1")(learn_rate=0.1, grad_clip=0.0)
    rng = jax.random.PRNGKey(0)

    # run A: one batch of 32
    params = place_replicated(small_nlp.params, mesh)
    opt = tx.init(params)
    upd1 = make_train_step(
        small_nlp.make_loss_fn(), tx, mesh, opt_state_template=opt, donate=False
    )
    batch = small_nlp.collate(examples, pad_batch_to=32, pad_len_to=16)
    pA, _, lossA, _ = upd1(
        params, opt,
        place_batch(batch["tokens"], mesh), place_batch(batch["targets"], mesh),
        rng,
    )

    # run B: two microbatches of 16 under scan accumulation
    params = place_replicated(small_nlp.params, mesh)
    opt = tx.init(params)
    upd2 = make_train_step(
        small_nlp.make_loss_fn(), tx, mesh, accumulate_gradient=2,
        opt_state_template=opt, donate=False,
    )
    c1 = small_nlp.collate(examples[:16], pad_batch_to=16, pad_len_to=16)
    c2 = small_nlp.collate(examples[16:], pad_batch_to=16, pad_len_to=16)
    tokens = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), c1["tokens"], c2["tokens"]
    )
    targets = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), c1["targets"], c2["targets"]
    )
    pB, _, lossB, _ = upd2(
        params, opt,
        place_batch(tokens, mesh, accum=True), place_batch(targets, mesh, accum=True),
        rng,
    )
    # equal-sized, fully-valid microbatches -> identical mean gradient
    for a, b in zip(jax.tree_util.tree_leaves(pA), jax.tree_util.tree_leaves(pB)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


@pytest.mark.slow
def test_train_loop_non_power_of_two_workers(tagger_config_text, tmp_path):
    """B padding must round to a multiple of the data-axis size (n=3)."""
    from spacy_ray_tpu.training.loop import train
    from spacy_ray_tpu.util import write_synth_jsonl

    write_synth_jsonl(tmp_path / "train.jsonl", 60, kind="tagger", seed=0)
    write_synth_jsonl(tmp_path / "dev.jsonl", 12, kind="tagger", seed=1)
    cfg = Config.from_str(tagger_config_text).apply_overrides(
        {
            "paths.train": str(tmp_path / "train.jsonl"),
            "paths.dev": str(tmp_path / "dev.jsonl"),
            "training.max_steps": 4,
            "training.eval_frequency": 2,
        }
    )
    _, result = train(cfg, n_workers=3, stdout_log=False)
    assert result.final_step == 4


@pytest.mark.slow
def test_train_loop_8_workers_learns(tagger_config_text, tmp_path):
    from spacy_ray_tpu.training.loop import train
    from spacy_ray_tpu.util import write_synth_jsonl

    write_synth_jsonl(tmp_path / "train.jsonl", 300, kind="tagger", seed=0)
    write_synth_jsonl(tmp_path / "dev.jsonl", 60, kind="tagger", seed=1)
    cfg = Config.from_str(tagger_config_text).apply_overrides(
        {
            "paths.train": str(tmp_path / "train.jsonl"),
            "paths.dev": str(tmp_path / "dev.jsonl"),
            "training.max_steps": 40,
            "training.eval_frequency": 20,
            "training.zero1": True,
        }
    )
    _, result = train(cfg, n_workers=8, stdout_log=False)
    assert result.final_step == 40
    assert result.best_score > 0.7