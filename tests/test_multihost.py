"""Real 2-process jax.distributed test of the multi-host training paths.

The multi-host-only branches in training/loop.py (startup digest
assertion, per-step shape sync, collective loop termination) and the
global-batch assembly in parallel/step.py:place_batch never execute under
the single-process 8-virtual-device harness — jax.process_count() is 1.
Here two REAL processes form a jax.distributed group (local coordinator,
CPU platform, 4 devices each = 8 global) and run train() end-to-end; the
child asserts data placement, rank-symmetric results, and global word
accounting (see tests/multihost_child.py). Removing any of the three
host-allgathers in the loop deadlocks or fails this test.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path
from typing import Optional, Tuple

import pytest

from spacy_ray_tpu.util import write_synth_jsonl

CHILD = Path(__file__).parent / "multihost_child.py"
TIMEOUT = 600


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ----------------------------------------------------------------------
# Capability gate: multi-process CPU collectives
# ----------------------------------------------------------------------
#
# The whole test needs a jax build whose CPU backend implements
# cross-process computations (a gloo/mpi collectives layer). Builds
# without it fail every cross-host psum with "Multiprocess computations
# aren't implemented on the CPU backend" — an environment capability
# gap, not a regression in the code under test, so it must read as a
# SKIP with the probe's evidence, not as a red test every full run
# carries. The probe is the minimal form of the capability: two real
# processes form a jax.distributed group and run one process_allgather.

_PROBE_CHILD = """
import sys
import jax
rank, port = int(sys.argv[1]), sys.argv[2]
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    f"127.0.0.1:{port}", num_processes=2, process_id=rank
)
import jax.numpy as jnp
from jax.experimental import multihost_utils
multihost_utils.process_allgather(jnp.ones((1,)))
print("COLLECTIVES_OK")
"""

_collectives_probe: Optional[Tuple[bool, str]] = None


def multiprocess_cpu_collectives_supported() -> Tuple[bool, str]:
    """(supported, evidence) — cached per test session; the probe costs
    two interpreter boots + one distributed init (~30s), paid at most
    once and only when the slow tier actually reaches this module."""
    global _collectives_probe
    if _collectives_probe is not None:
        return _collectives_probe
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE_CHILD, str(rank), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for rank in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out = "<probe timeout>"
        outs.append(out)
    ok = all(p.returncode == 0 for p in procs) and all(
        "COLLECTIVES_OK" in out for out in outs
    )
    if ok:
        _collectives_probe = (True, "probe passed")
    else:
        # the probe's last traceback line is the capability evidence
        # (e.g. "Multiprocess computations aren't implemented on the
        # CPU backend")
        tails = [
            line for out in outs
            for line in out.strip().splitlines()[-1:]
        ]
        _collectives_probe = (False, " | ".join(tails) or "probe failed")
    return _collectives_probe


@pytest.mark.slow
def test_two_process_train(tmp_path):
    supported, evidence = multiprocess_cpu_collectives_supported()
    if not supported:
        pytest.skip(
            "this jax build lacks multi-process CPU collectives "
            f"(capability probe: {evidence})"
        )
    # Odd doc count -> unequal per-host shards -> the hosts' streams end on
    # different steps, forcing the collective-termination path to do real
    # work (a host that breaks alone deadlocks the other in psum).
    write_synth_jsonl(tmp_path / "train.jsonl", 151, kind="tagger", seed=0)
    write_synth_jsonl(tmp_path / "dev.jsonl", 30, kind="tagger", seed=1)

    # For the per-rank resume check: 9 SAME-LENGTH docs round-robin over 2
    # hosts -> always 5 vs 4 docs/epoch -> different batches-per-epoch ->
    # the ranks' (epoch, position) drift apart deterministically after the
    # first epoch rollover, whatever the shuffle order.
    import json as _json
    import random as _random

    from spacy_ray_tpu.training.corpus import _doc_to_json
    from spacy_ray_tpu.util import synth_tagged_doc

    _rng = _random.Random(7)
    with open(tmp_path / "resume_train.jsonl", "w") as f:
        for _ in range(9):
            doc = synth_tagged_doc(_rng, min_len=20, max_len=20)
            f.write(_json.dumps(_doc_to_json(doc)) + "\n")

    # KB for the consuming-annotation config (written once, read by both
    # ranks and by this process's single-process parity run below).
    from multihost_child import make_linker_kb

    make_linker_kb().to_disk(tmp_path / "kb.npz")

    # Children pick their own platform/device count via jax.config (the
    # reliable seam on this image); scrub the parent harness's env so the
    # conftest's 8-device setting doesn't leak into them.
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)

    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, str(CHILD), str(rank), str(port), str(tmp_path)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=str(Path(__file__).parent.parent),
        )
        for rank in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=TIMEOUT)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                out, _ = p.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                out = "<unterminated>"
            outs.append(out)
        pytest.fail(
            "multi-host children deadlocked (collective termination / shape "
            "sync broken?):\n" + "\n----\n".join(outs)
        )

    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"CHILD_OK rank={rank}" in out, f"rank {rank} output:\n{out}"

    # Both ranks must report the same global stats (words are a global sum).
    line0 = [l for l in outs[0].splitlines() if l.startswith("CHILD_OK")][0]
    line1 = [l for l in outs[1].splitlines() if l.startswith("CHILD_OK")][0]
    assert line0.split("rank=0 ")[1] == line1.split("rank=1 ")[1]

    # annotating_components multi-host vs single-process (VERDICT r3 next
    # #2): the same annotating config trained in THIS process (one host,
    # 8 virtual devices, unsharded stream) must land in the same quality
    # band as the 2-process run — batches differ (per-host sharding), so
    # the comparison is converged-score proximity, not bit identity.
    mh_ann = float(line0.split("ann_score=")[1].split()[0])
    from multihost_child import CFG_TEMPLATE

    from spacy_ray_tpu.config import Config
    from spacy_ray_tpu.training.loop import train as sp_train

    cfg = CFG_TEMPLATE.format(data_dir=tmp_path)
    cfg = cfg.replace("[training]\n", '[training]\nannotating_components = ["tagger"]\n', 1)
    _, sp_res = sp_train(Config.from_str(cfg), stdout_log=False)
    assert abs(sp_res.best_score - mh_ann) <= 0.1, (
        f"single-process annotating score {sp_res.best_score} vs "
        f"multi-host {mh_ann}"
    )

    # CONSUMING annotation score parity (VERDICT r4 next #4): the linker
    # trained on the NER's predicted mentions under 2 processes must land
    # in the same quality band as the identical single-process run — this
    # fails if the multi-host host-local annotation handoff produces wrong
    # annotations (the no-op tagger check can't see that).
    mh_cons = float(line0.split("cons_score=")[1].split()[0])
    from multihost_child import CONSUMING_CFG_TEMPLATE, register_linker_reader

    register_linker_reader()
    _, sp_cons = sp_train(
        Config.from_str(CONSUMING_CFG_TEMPLATE.format(data_dir=tmp_path)),
        stdout_log=False,
    )
    assert sp_cons.best_score > 0.9, (
        f"single-process consuming run failed to learn: {sp_cons.best_score}"
    )
    assert abs(sp_cons.best_score - mh_cons) <= 0.1, (
        f"single-process consuming score {sp_cons.best_score} vs "
        f"multi-host {mh_cons}"
    )
