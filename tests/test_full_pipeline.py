"""End-to-end multi-component pipeline through the training loop —
the en_core_web_sm shape (BASELINE.json config #2): tagger + parser + NER
over one shared CNN tok2vec, multi-task gradients summed into the trunk."""

import json

import pytest

pytestmark = pytest.mark.slow  # compile-heavy: full tier only

from spacy_ray_tpu.config import Config
from spacy_ray_tpu.training.loop import train
from spacy_ray_tpu.training.corpus import _doc_to_json
from spacy_ray_tpu.util import synth_corpus

FULL_CFG = """
[paths]
train = null
dev = null

[nlp]
lang = "en"
pipeline = ["tok2vec","tagger","parser","ner"]

[components.tok2vec]
factory = "tok2vec"

[components.tok2vec.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = 64
depth = 2
embed_size = 512

[components.tagger]
factory = "tagger"

[components.tagger.model]
@architectures = "spacy.Tagger.v2"

[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 64

[components.parser]
factory = "parser"

[components.parser.model]
@architectures = "spacy.TransitionBasedParser.v2"
state_type = "parser"
hidden_width = 64
maxout_pieces = 2

[components.parser.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 64

[components.ner]
factory = "ner"

[components.ner.model]
@architectures = "spacy.TransitionBasedParser.v2"
state_type = "ner"
hidden_width = 64
maxout_pieces = 2

[components.ner.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 64

[corpora.train]
@readers = "spacy.JsonlCorpus.v1"
path = ${paths.train}
shuffle = true

[corpora.dev]
@readers = "spacy.JsonlCorpus.v1"
path = ${paths.dev}

[training]
seed = 0
max_steps = 120
eval_frequency = 40
patience = 0

[training.optimizer]
@optimizers = "Adam.v1"
learn_rate = 0.005

[training.batcher]
@batchers = "spacy.batch_by_words.v1"
size = 800

[training.score_weights]
tag_acc = 0.34
dep_las = 0.33
ents_f = 0.33
"""


def _write_mixed(path, n, seed):
    """Mixed corpus: parsed docs (tags+heads+deps) + NER docs (ents).
    Each component learns from the docs carrying its annotation."""
    egs = synth_corpus(n // 2, "parser", seed=seed) + synth_corpus(
        n // 2, "ner", seed=seed + 1
    )
    with open(path, "w", encoding="utf8") as f:
        for eg in egs:
            f.write(json.dumps(_doc_to_json(eg.reference)) + "\n")


def test_full_pipeline_multitask(tmp_path):
    _write_mixed(tmp_path / "train.jsonl", 400, seed=0)
    _write_mixed(tmp_path / "dev.jsonl", 80, seed=7)
    cfg = Config.from_str(FULL_CFG).apply_overrides(
        {
            "paths.train": str(tmp_path / "train.jsonl"),
            "paths.dev": str(tmp_path / "dev.jsonl"),
        }
    )
    nlp, result = train(cfg, output_path=tmp_path / "out", n_workers=2, stdout_log=False)
    assert result.final_step == 120
    last = result.history[-1]["other_scores"]
    assert last["tag_acc"] > 0.85, last
    assert last["dep_uas"] > 0.6, last
    assert last["ents_f"] > 0.5, last
    # model roundtrip with all components
    from spacy_ray_tpu.pipeline.language import Pipeline

    reloaded = Pipeline.from_disk(tmp_path / "out" / "best-model")
    doc = reloaded("Alice Smith sees the green tree")
    assert doc.tags and len(doc.tags) == len(doc.words)
    assert doc.heads and len(doc.heads) == len(doc.words)


TRF_TRUNK_BLOCK = """
[components.tok2vec]
factory = "transformer"

[components.tok2vec.model]
@architectures = "spacy_ray_tpu.TransformerEncoder.v1"
width = 64
depth = 2
n_heads = 4
ffn_mult = 2
dropout = 0.1
max_len = 64
embed_size = 512
remat = false
"""


def test_full_pipeline_trf_trunk_reaches_scores(tmp_path):
    """The en_core_web_trf SHAPE (BASELINE.json config #4, scaled down):
    tagger + parser + NER sharing a transformer trunk, through the REAL
    training loop to real eval scores — evidence the trf path trains to
    useful scores, not just that its loss moves (round-1 VERDICT weak #8)."""
    import re

    _write_mixed(tmp_path / "train.jsonl", 400, seed=0)
    _write_mixed(tmp_path / "dev.jsonl", 80, seed=7)
    trf_cfg = re.sub(
        r"\[components\.tok2vec\]\nfactory = \"tok2vec\"\n\n"
        r"\[components\.tok2vec\.model\]\n"
        r"@architectures = \"spacy\.HashEmbedCNN\.v2\"\n"
        r"width = 64\ndepth = 2\nembed_size = 512\n",
        TRF_TRUNK_BLOCK.strip() + "\n",
        FULL_CFG,
    )
    assert "TransformerEncoder" in trf_cfg, "config rewrite failed"
    cfg = Config.from_str(trf_cfg).apply_overrides(
        {
            "paths.train": str(tmp_path / "train.jsonl"),
            "paths.dev": str(tmp_path / "dev.jsonl"),
            "training.max_steps": 150,
            "training.eval_frequency": 50,
            "training.optimizer.learn_rate": 0.003,
        }
    )
    nlp, result = train(cfg, output_path=None, n_workers=2, stdout_log=False)
    assert result.final_step == 150
    last = result.history[-1]["other_scores"]
    assert last["tag_acc"] > 0.8, last
    assert last["dep_uas"] > 0.5, last
    assert last["ents_f"] > 0.4, last
