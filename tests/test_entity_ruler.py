"""Entity ruler: phrase/token patterns, OP quantifiers, model-ent merging."""

from spacy_ray_tpu.config import Config
from spacy_ray_tpu.pipeline.components.entity_ruler import EntityRulerComponent
from spacy_ray_tpu.pipeline.doc import Doc, Span
from spacy_ray_tpu.pipeline.language import Pipeline
from spacy_ray_tpu.pipeline.matcher import match_pattern


def _match_token_pattern(pattern, words, start):
    return match_pattern(Doc(words=list(words)), pattern, start)


def _ruler(patterns, **kw):
    return EntityRulerComponent("entity_ruler", None, patterns=patterns, **kw)


def test_phrase_pattern():
    r = _ruler([{"label": "ORG", "pattern": "Acme Corp"}])
    doc = Doc(words=["I", "work", "at", "Acme", "Corp", "now"])
    r.set_annotations([doc], None, [6])
    assert [(s.start, s.end, s.label) for s in doc.ents] == [(3, 5, "ORG")]


def test_token_pattern_with_ops():
    pat = [{"LOWER": "new"}, {"LOWER": "york"}, {"LOWER": "city", "OP": "?"}]
    assert _match_token_pattern(pat, ["New", "York", "City"], 0) == 3  # longest
    assert _match_token_pattern(pat, ["new", "york", "state"], 0) == 2
    assert _match_token_pattern(pat, ["old", "york"], 0) is None
    plus = [{"IS_DIGIT": True, "OP": "+"}]
    assert _match_token_pattern(plus, ["12", "34", "x"], 0) == 2
    assert _match_token_pattern(plus, ["x"], 0) is None


def test_longest_match_wins_and_no_overlap():
    r = _ruler(
        [
            {"label": "SHORT", "pattern": "New York"},
            {"label": "LONG", "pattern": [{"LOWER": "new"}, {"LOWER": "york"}, {"LOWER": "city"}]},
        ]
    )
    doc = Doc(words=["New", "York", "City"])
    r.set_annotations([doc], None, [3])
    assert [(s.start, s.end, s.label) for s in doc.ents] == [(0, 3, "LONG")]


def test_merge_with_model_ents():
    r = _ruler([{"label": "ORG", "pattern": "Acme Corp"}])
    doc = Doc(words=["Acme", "Corp", "hired", "Alice"])
    doc.ents = [Span(0, 1, "PERSON"), Span(3, 4, "PERSON")]  # model output
    r.set_annotations([doc], None, [4])
    # model ents win by default: overlapping rule match dropped
    assert [(s.start, s.end, s.label) for s in doc.ents] == [
        (0, 1, "PERSON"),
        (3, 4, "PERSON"),
    ]
    r2 = _ruler([{"label": "ORG", "pattern": "Acme Corp"}], overwrite_ents=True)
    doc2 = Doc(words=["Acme", "Corp", "hired", "Alice"])
    doc2.ents = [Span(0, 1, "PERSON"), Span(3, 4, "PERSON")]
    r2.set_annotations([doc2], None, [4])
    assert [(s.start, s.end, s.label) for s in doc2.ents] == [
        (0, 2, "ORG"),
        (3, 4, "PERSON"),
    ]


def test_in_pipeline_and_serializes(tmp_path):
    cfg = Config.from_str(
        """
[nlp]
lang = "en"
pipeline = ["tok2vec","tagger","entity_ruler"]

[components.tok2vec]
factory = "tok2vec"

[components.tok2vec.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = 32
depth = 1
embed_size = 128

[components.tagger]
factory = "tagger"

[components.tagger.model]
@architectures = "spacy.Tagger.v2"

[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 32

[components.entity_ruler]
factory = "entity_ruler"
patterns = [{"label": "ORG", "pattern": "Acme Corp"}]
"""
    )
    nlp = Pipeline.from_config(cfg)
    from spacy_ray_tpu.pipeline.doc import Example

    gold = [Example.from_gold(Doc(words=["a", "b"], tags=["X", "Y"]))]
    nlp.initialize(lambda: iter(gold), seed=0)
    doc = nlp("we visited Acme Corp today")
    assert [(s.start, s.end, s.label) for s in doc.ents] == [(2, 4, "ORG")]
    nlp.to_disk(tmp_path / "m")
    reloaded = Pipeline.from_disk(tmp_path / "m")
    doc2 = reloaded("we visited Acme Corp today")
    assert [(s.start, s.end, s.label) for s in doc2.ents] == [(2, 4, "ORG")]


def test_phrase_with_punctuation_matches():
    r = _ruler([{"label": "GPE", "pattern": "U.S."}])
    # doc tokenized the same way the pattern is
    from spacy_ray_tpu.pipeline.tokenizer import Tokenizer

    doc = Tokenizer()("Made in the U.S. today")
    r.set_annotations([doc], None, [len(doc)])
    assert any(s.label == "GPE" for s in doc.ents), doc.ents


def test_ner_respects_preset_entities():
    """ruler-before-ner order: NER must not clobber preset entities."""
    from spacy_ray_tpu.pipeline.components.ner import NERComponent

    comp = NERComponent("ner", {"@architectures": "spacy.TransitionBasedParser.v2",
                                 "state_type": "ner"})
    comp.labels = ["ORG"]
    doc = Doc(words=["Acme", "Corp", "hired", "Alice"])
    doc.ents = [Span(0, 2, "PRODUCT")]  # preset by an earlier ruler
    import numpy as np

    # model predicts B-ORG L-ORG O U-ORG (overlapping + new)
    actions = np.array([[1, 3, 0, 4]])
    comp.set_annotations([doc], {"actions": actions}, [4])
    assert [(s.start, s.end, s.label) for s in doc.ents] == [
        (0, 2, "PRODUCT"),  # preset kept
        (3, 4, "ORG"),  # non-overlapping model ent added
    ]
