"""Shared token-pattern matcher (pipeline/matcher.py): spaCy Matcher
pattern-language parity — predicate dicts (REGEX/IN/NOT_IN/comparisons),
LENGTH, TAG/POS keys, and the full OP set including ! and {n,m} ranges.
VERDICT r1 #8."""

import pytest

from spacy_ray_tpu.pipeline.components.attribute_ruler import AttributeRulerComponent
from spacy_ray_tpu.pipeline.components.entity_ruler import EntityRulerComponent
from spacy_ray_tpu.pipeline.doc import Doc
from spacy_ray_tpu.pipeline.matcher import match_pattern, validate_token_patterns


def M(pattern, words, start=0, **doc_kw):
    return match_pattern(Doc(words=list(words), **doc_kw), pattern, start)


def test_regex_predicate():
    pat = [{"TEXT": {"REGEX": r"^[A-Z]{2,4}$"}}]
    assert M(pat, ["NASA"]) == 1
    assert M(pat, ["NASAX"]) is None
    assert M(pat, ["nasa"]) is None


def test_in_not_in():
    pat = [{"LOWER": {"IN": ["inc", "corp", "ltd"]}}]
    assert M(pat, ["Corp"]) == 1
    assert M(pat, ["LLC"]) is None
    pat2 = [{"LOWER": {"NOT_IN": ["the", "a"]}}]
    assert M(pat2, ["cat"]) == 1
    assert M(pat2, ["the"]) is None


def test_length_comparisons():
    assert M([{"LENGTH": 3}], ["cat"]) == 1
    assert M([{"LENGTH": 3}], ["cats"]) is None
    assert M([{"LENGTH": {">=": 10}}], ["internationalization"]) == 1
    assert M([{"LENGTH": {">=": 10}}], ["intl"]) is None
    assert M([{"LENGTH": {">": 2, "<": 5}}], ["cats"]) == 1


def test_negation_op():
    # "not followed by 'york'": ! negates the constraint for one token
    pat = [{"LOWER": "new"}, {"LOWER": "york", "OP": "!"}]
    assert M(pat, ["new", "jersey"]) == 2
    assert M(pat, ["new", "york"]) is None
    assert M(pat, ["new"]) is None  # ! still consumes a token


def test_range_ops():
    digit = {"IS_DIGIT": True}
    assert M([dict(digit, OP="{2}")], ["1", "2", "3"]) == 2
    assert M([dict(digit, OP="{2}")], ["1", "x"]) is None
    assert M([dict(digit, OP="{1,3}")], ["1", "2", "3", "4"]) == 3  # greedy, capped
    assert M([dict(digit, OP="{2,}")], ["1"]) is None
    assert M([dict(digit, OP="{2,}")], ["1", "2", "3"]) == 3
    assert M([dict(digit, OP="{,2}")], ["x"]) == 0  # zero-width ok
    # backtracking across a range: {1,3} must give back one token
    pat = [dict(digit, OP="{1,3}"), {"IS_DIGIT": True}]
    assert M(pat, ["1", "2", "3"]) == 3


def test_tag_pos_keys():
    doc = Doc(
        words=["green", "ideas", "sleep"],
        tags=["ADJ", "NOUN", "VERB"],
        pos=["ADJ", "NOUN", "VERB"],
    )
    assert match_pattern(doc, [{"TAG": "ADJ"}, {"POS": "NOUN"}], 0) == 2
    assert match_pattern(doc, [{"TAG": "NOUN"}], 0) is None
    assert match_pattern(doc, [{"TAG": {"IN": ["NOUN", "PROPN"]}}], 1) == 2


def test_validation_rejects_bad_patterns():
    with pytest.raises(ValueError, match="Unsupported OP"):
        validate_token_patterns([[{"TEXT": "x", "OP": "**"}]])
    with pytest.raises(ValueError, match="Unsupported predicate"):
        validate_token_patterns([[{"TEXT": {"LIKE": "x"}}]])
    with pytest.raises(Exception):  # invalid regex fails at validation time
        validate_token_patterns([[{"TEXT": {"REGEX": "["}}]])
    with pytest.raises(ValueError, match="wants a list"):
        validate_token_patterns([[{"LOWER": {"IN": "abc"}}]])
    # all the new syntax validates cleanly
    validate_token_patterns(
        [[{"TEXT": {"REGEX": "^a"}, "OP": "{1,3}"}, {"LENGTH": {">=": 2}, "OP": "!"}]]
    )


def test_entity_ruler_with_regex_and_ranges():
    r = EntityRulerComponent(
        "entity_ruler",
        None,
        patterns=[
            {"label": "TICKER", "pattern": [{"TEXT": {"REGEX": r"^[A-Z]{2,5}$"}}]},
            {"label": "CODE", "pattern": [{"IS_DIGIT": True, "OP": "{3}"}]},
        ],
    )
    doc = Doc(words=["buy", "GOOG", "at", "1", "2", "3"])
    r.set_annotations([doc], None, [6])
    got = {(s.start, s.end, s.label) for s in doc.ents}
    assert got == {(1, 2, "TICKER"), (3, 6, "CODE")}


def test_attribute_ruler_tag_keyed_retagging():
    # the canonical spaCy use: retag by POS context — requires the doc's
    # predicted tags, i.e. the component runs after the tagger
    ar = AttributeRulerComponent(
        "attribute_ruler",
        None,
        patterns=[
            {
                "patterns": [[{"TAG": "VERB"}, {"LOWER": "not"}]],
                "attrs": {"TAG": "PART"},
                "index": 1,
            }
        ],
    )
    doc = Doc(words=["did", "not", "go"], tags=["VERB", "ADV", "VERB"])
    ar.set_annotations([doc], None, [3])
    assert doc.tags == ["VERB", "PART", "VERB"]


def test_attribute_ruler_matches_before_applying():
    # spaCy semantics: one matcher pass over the ORIGINAL annotations, then
    # apply — a rule's own rewrites must not suppress later matches
    ar = AttributeRulerComponent(
        "attribute_ruler",
        None,
        patterns=[
            {
                "patterns": [[{"TAG": "VBZ"}, {"TAG": "VBZ"}]],
                "attrs": {"TAG": "X"},
                "index": 1,
            }
        ],
    )
    doc = Doc(words=["a", "b", "c"], tags=["VBZ", "VBZ", "VBZ"])
    ar.set_annotations([doc], None, [3])
    assert doc.tags == ["VBZ", "X", "X"]


def test_comparison_arg_types_validated_eagerly():
    with pytest.raises(ValueError, match="wants a number"):
        validate_token_patterns([[{"LENGTH": {">=": "10"}}]])
    with pytest.raises(ValueError, match="wants a string"):
        validate_token_patterns([[{"TEXT": {">=": 10}}]])
