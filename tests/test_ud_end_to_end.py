"""Realistic-corpus end-to-end fixture (VERDICT r2 next #6): the
deterministic pseudo-UD generator (zipfian vocab, multi-sentence docs,
punctuation, ~7%-per-sentence non-projective trees, rare labels) run
through the FULL user loop — convert → train (sm-style shared-trunk
pipeline) → evaluate → package → load — pinned against frozen GOLDEN
scores (VERDICT r3 next #5), not learned-nothing floors: a ~5-point
component-quality regression fails, not just a total collapse."""

import json
import sys

import pytest

from spacy_ray_tpu.config import Config
from spacy_ray_tpu.udgen import synth_ud_corpus, write_ud_jsonl

pytestmark = pytest.mark.slow  # full train loop: the fast tier skips it


UD_SM_CFG = """
[nlp]
lang = "en"
pipeline = ["tok2vec","tagger","parser","ner"]

[components.tok2vec]
factory = "tok2vec"

[components.tok2vec.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = 64
depth = 2
embed_size = 2000

[components.tagger]
factory = "tagger"

[components.tagger.model]
@architectures = "spacy.Tagger.v2"

[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 64

[components.parser]
factory = "parser"

[components.parser.model]
@architectures = "spacy.TransitionBasedParser.v2"
state_type = "parser"
hidden_width = 64
maxout_pieces = 2

[components.parser.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 64

[components.ner]
factory = "ner"

[components.ner.model]
@architectures = "spacy.TransitionBasedParser.v2"
state_type = "ner"
hidden_width = 64
maxout_pieces = 2

[components.ner.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 64

[corpora]

[corpora.train]
@readers = "spacy.JsonlCorpus.v1"
path = ${paths.train}

[corpora.dev]
@readers = "spacy.JsonlCorpus.v1"
path = ${paths.dev}

[paths]
train = null
dev = null

[training]
seed = 0
max_steps = 180
eval_frequency = 60
patience = 0
dropout = 0.1

[training.optimizer]
@optimizers = "Adam.v1"
learn_rate = 0.01

[training.batcher]
@batchers = "spacy.batch_by_words.v1"
size = 1200
tolerance = 0.2

[training.score_weights]
tag_acc = 0.3
dep_uas = 0.2
dep_las = 0.2
ents_f = 0.3
"""


# Frozen golden scores, measured once from a 1500-step converged run of
# this exact config/corpus (seed 0, CPU, 2026-07-29; the task plateaus
# from ~step 60 — full trajectory minima over 25 evals: tag 0.990,
# uas 0.980, las 0.979, ents_f 0.938):
#   step  180: tag_acc 0.9911  dep_uas 0.9813  dep_las 0.9807  ents_f 0.9381
#   step 1500: tag_acc 0.9926  dep_uas 0.9870  dep_las 0.9864  ents_f 0.9381
# Tolerance 0.04 absorbs cross-version XLA jitter while still failing a
# 5-point quality regression (the old learned-nothing floors let anything
# above tag 0.8 / uas 0.55 / las 0.5 / ents_f 0.5 pass silently).
GOLDEN_180 = {"tag_acc": 0.991, "dep_uas": 0.981, "dep_las": 0.981, "ents_f": 0.938}
GOLDEN_CONVERGED = {
    "tag_acc": 0.993, "dep_uas": 0.987, "dep_las": 0.986, "ents_f": 0.938
}
GOLDEN_TOL = 0.04


def test_ud_corpus_full_loop(tmp_path):
    from spacy_ray_tpu.cli import main as cli_main

    # --- data: jsonl, then `convert` to the real .spacy byte format ---
    write_ud_jsonl(tmp_path / "train.jsonl", 400, seed=0)
    write_ud_jsonl(tmp_path / "dev.jsonl", 60, seed=1)
    for split in ("train", "dev"):
        assert cli_main([
            "convert",
            str(tmp_path / f"{split}.jsonl"),
            str(tmp_path / f"{split}.spacy"),
        ]) == 0

    # --- train on the CONVERTED corpus (the reference's data path) ---
    from spacy_ray_tpu.training.loop import train

    cfg = Config.from_str(UD_SM_CFG).apply_overrides(
        {
            "paths.train": str(tmp_path / "train.spacy"),
            "paths.dev": str(tmp_path / "dev.spacy"),
        }
    )
    nlp, result = train(cfg, output_path=tmp_path / "out", n_workers=1, stdout_log=False)
    scores = result.history[-1]["other_scores"]

    # --- golden-band trajectory pins (VERDICT r3 next #5) ---
    for key, golden in GOLDEN_180.items():
        assert scores[key] >= golden - GOLDEN_TOL, (
            f"{key}={scores[key]:.4f} regressed below golden "
            f"{golden} - {GOLDEN_TOL} (see frozen goldens above)"
        )
    # the rare label must at least be scorable (per-type table exists)
    assert "ents_per_type" in scores

    # --- evaluate the saved best model via the CLI ---
    metrics_path = tmp_path / "metrics.json"
    assert cli_main([
        "evaluate",
        str(tmp_path / "out" / "best-model"),
        str(tmp_path / "dev.spacy"),
        "--device", "cpu",
        "--output", str(metrics_path),
    ]) == 0
    saved_scores = json.loads(metrics_path.read_text())
    assert saved_scores["tag_acc"] == pytest.approx(scores["tag_acc"], abs=0.05)

    # --- package -> load -> predict ---
    from spacy_ray_tpu.packaging import package

    project = package(
        tmp_path / "out" / "best-model", tmp_path / "pkg", name="ud_fixture"
    )
    pkg_dir = project / "en_ud_fixture"
    assert pkg_dir.is_dir()
    sys.path.insert(0, str(project))
    try:
        import spacy_ray_tpu

        loaded = spacy_ray_tpu.load("en_ud_fixture")
    finally:
        sys.path.remove(str(project))
    dev = synth_ud_corpus(20, seed=1)
    reloaded_scores = loaded.evaluate(dev)
    assert reloaded_scores["tag_acc"] == pytest.approx(
        scores["tag_acc"], abs=0.08
    )
    doc = loaded("the fefa tote runs .")
    assert doc.tags is not None and len(doc.tags) == 5


UD_TRF_CFG = """
[nlp]
lang = "en"
pipeline = ["transformer","tagger","ner"]

[components.transformer]
factory = "transformer"

[components.transformer.model]
@architectures = "spacy_ray_tpu.TransformerEncoder.v1"
width = 64
depth = 2
n_heads = 4
ffn_mult = 2
dropout = 0.1
max_len = 128
embed_size = 2000
remat = false

[components.tagger]
factory = "tagger"

[components.tagger.model]
@architectures = "spacy.Tagger.v2"

[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 64

[components.ner]
factory = "ner"

[components.ner.model]
@architectures = "spacy.TransitionBasedParser.v2"
state_type = "ner"
hidden_width = 64
maxout_pieces = 2

[components.ner.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 64

[corpora]

[corpora.train]
@readers = "spacy.JsonlCorpus.v1"
path = ${paths.train}

[corpora.dev]
@readers = "spacy.JsonlCorpus.v1"
path = ${paths.dev}

[paths]
train = null
dev = null

[training]
seed = 0
max_steps = 180
eval_frequency = 60
patience = 0
dropout = 0.1

[training.optimizer]
@optimizers = "Adam.v1"
learn_rate = 0.003

[training.batcher]
@batchers = "spacy.batch_by_words.v1"
size = 1200
tolerance = 0.2

[training.score_weights]
tag_acc = 0.5
ents_f = 0.5
"""

# Frozen goldens for the TRANSFORMER path (VERDICT r4 next #5: the trf
# trunk's only quality assertion was a tag_acc > 0.8 floor). Measured once
# from a 900-step run of this exact config/corpus (seed 0, CPU,
# 2026-07-30); the task plateaus from the FIRST eval at step 60 — full
# trajectory: tag_acc 0.956-0.963 (min at step 180), ents_f 0.959-0.969
# (min at step 180), flat thereafter:
#   step 180: tag_acc 0.9563  ents_f 0.9588
#   step 900: tag_acc 0.9616  ents_f 0.9691
# Tolerance 0.04 absorbs XLA jitter while failing a 5-point trf-trunk
# quality regression that would still clear the old 0.8 floor.
GOLDEN_TRF = {"tag_acc": 0.962, "ents_f": 0.969}
GOLDEN_TRF_TOL = 0.04


def _best_scores(history, keys):
    """Max over the run's evals for each golden key (plateau pins compare
    against the best the trajectory reached, not the possibly-noisy last)."""
    best = {}
    for h in history:
        for key in keys:
            value = h["other_scores"].get(key)
            if value is not None:
                best[key] = max(best.get(key, 0.0), value)
    return best


def test_ud_trf_matches_golden(tmp_path):
    """trf-trunk analogue of the CNN golden pins: a tiny 2-layer
    transformer tagger+NER trained to its (early) plateau must land within
    GOLDEN_TRF_TOL of the frozen goldens on both components."""
    from spacy_ray_tpu.training.loop import train

    write_ud_jsonl(tmp_path / "train.jsonl", 400, seed=0)
    write_ud_jsonl(tmp_path / "dev.jsonl", 60, seed=1)
    cfg = Config.from_str(UD_TRF_CFG).apply_overrides(
        {
            "paths.train": str(tmp_path / "train.jsonl"),
            "paths.dev": str(tmp_path / "dev.jsonl"),
        }
    )
    _, result = train(cfg, n_workers=1, stdout_log=False)
    best = _best_scores(result.history, GOLDEN_TRF)
    for key, golden in GOLDEN_TRF.items():
        assert best.get(key, 0.0) >= golden - GOLDEN_TRF_TOL, (
            f"{key}={best.get(key)} below trf golden {golden} - "
            f"{GOLDEN_TRF_TOL} (see frozen goldens above)"
        )


def test_ud_converged_matches_golden(tmp_path):
    """Converged-run pin: 360 steps (the task plateaus from ~step 60) must
    land within GOLDEN_TOL of the frozen converged goldens on every
    component — a quality regression that still "learns something" fails
    here even if it would have cleared the old floors."""
    from spacy_ray_tpu.training.loop import train

    write_ud_jsonl(tmp_path / "train.jsonl", 400, seed=0)
    write_ud_jsonl(tmp_path / "dev.jsonl", 60, seed=1)
    cfg = Config.from_str(UD_SM_CFG).apply_overrides(
        {
            "paths.train": str(tmp_path / "train.jsonl"),
            "paths.dev": str(tmp_path / "dev.jsonl"),
            "training.max_steps": 360,
        }
    )
    _, result = train(cfg, n_workers=1, stdout_log=False)
    best = _best_scores(result.history, GOLDEN_CONVERGED)
    for key, golden in GOLDEN_CONVERGED.items():
        assert best.get(key, 0.0) >= golden - GOLDEN_TOL, (
            f"{key}={best.get(key)} below converged golden {golden} - {GOLDEN_TOL}"
        )
