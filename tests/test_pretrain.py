"""`pretrain` command: tok2vec pretraining on raw text (characters
objective), weights round-tripping into training via [initialize]
init_tok2vec — the `spacy pretrain` capability surface, TPU-first (the
objective is one jitted make_train_step program over the data axis)."""

import json

import numpy as np
import pytest

from spacy_ray_tpu.config import Config
from spacy_ray_tpu.training.checkpoint import _flatten
from spacy_ray_tpu.training.pretrain import char_targets, pretrain


CFG = """
[paths]
raw_text = "{raw}"

[nlp]
lang = "en"
pipeline = ["tok2vec","tagger"]

[components.tok2vec]
factory = "tok2vec"

[components.tok2vec.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = 64
depth = 2
embed_size = 300
window_size = 1
maxout_pieces = 2
subword_features = true
pretrained_vectors = null

[components.tagger]
factory = "tagger"

[components.tagger.model]
@architectures = "spacy.Tagger.v2"

[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 64

[corpora.pretrain]
@readers = "spacy.JsonlCorpus.v1"
path = ${{paths.raw_text}}

[pretraining]
max_steps = 12
batch_size = 8
corpus = "corpora.pretrain"

[pretraining.objective]
type = "characters"
n_characters = 3

[pretraining.optimizer]
@optimizers = "Adam.v1"
learn_rate = 0.01
"""


@pytest.fixture(scope="module")
def raw_jsonl(tmp_path_factory):
    path = tmp_path_factory.mktemp("pretrain") / "raw.jsonl"
    texts = [
        "The quick brown fox jumps over the lazy dog.",
        "Pretraining predicts characters from context vectors.",
        "TPU meshes shard the batch over the data axis.",
        "Hash embeddings use murmur keys for subword features.",
    ] * 8
    with open(path, "w", encoding="utf8") as f:
        for t in texts:
            f.write(json.dumps({"text": t}) + "\n")
    return path


def test_char_targets_bytes():
    class Ref:
        words = ["abc", "hello", "x"]

    class Eg:
        reference = Ref()

    out = char_targets([Eg()], B=2, T=4, n=2)
    assert out.shape == (2, 4, 4)
    # "abc": first 2 = a,b ; last 2 = b,c (byte + 1)
    assert list(out[0, 0]) == [ord("a") + 1, ord("b") + 1, ord("b") + 1, ord("c") + 1]
    # "x": shorter than window -> absent (0) padding
    assert list(out[0, 2]) == [ord("x") + 1, 0, ord("x") + 1, 0]
    # batch row 1 is padding -> all absent
    assert out[1].sum() == 0


@pytest.mark.slow
def test_pretrain_learns_and_roundtrips(tmp_path, raw_jsonl):
    cfg = Config.from_str(CFG.format(raw=str(raw_jsonl)))
    out = tmp_path / "pretrain_out"
    stats = pretrain(cfg, out)
    assert stats["steps"] == 12
    assert np.isfinite(stats["loss"])
    assert (out / "model-last.npz").exists()

    # round-trip: a fresh pipeline initialized with init_tok2vec must carry
    # EXACTLY the pretrained trunk params
    from spacy_ray_tpu.pipeline.language import Pipeline
    from spacy_ray_tpu.util import synth_corpus

    cfg2 = Config.from_str(CFG.format(raw=str(raw_jsonl)))
    cfg2.setdefault("initialize", {})["init_tok2vec"] = str(out / "model-last.npz")
    nlp = Pipeline.from_config(cfg2.interpolate())
    examples = synth_corpus(20, "tagger", seed=0)
    params = nlp.initialize(lambda: iter(examples), seed=0)

    from spacy_ray_tpu.training.checkpoint import load_params

    saved = _flatten(load_params(out / "model-last.npz"))
    got = _flatten(params["tok2vec"])
    assert set(saved) == set(got)
    for k in saved:
        np.testing.assert_array_equal(np.asarray(saved[k]), np.asarray(got[k]))


@pytest.mark.slow
def test_pretrain_partial_batch_divides_mesh(tmp_path, raw_jsonl):
    # batch_size 5 over 32 texts leaves a final partial batch of 2; every
    # batch must still collate to a multiple of the 8-device data axis
    cfg = Config.from_str(CFG.format(raw=str(raw_jsonl)))
    cfg["pretraining"]["batch_size"] = 5
    cfg["pretraining"]["max_steps"] = 7
    stats = pretrain(cfg, tmp_path / "pt_partial")
    assert stats["steps"] == 7
    assert np.isfinite(stats["loss"])


def test_pretrain_empty_corpus_is_loud(tmp_path):
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    cfg = Config.from_str(CFG.format(raw=str(empty)))
    with pytest.raises(ValueError, match="no batches"):
        pretrain(cfg, tmp_path / "pt_empty")


def test_init_tok2vec_shape_mismatch_is_loud(tmp_path, raw_jsonl):
    cfg = Config.from_str(CFG.format(raw=str(raw_jsonl)))
    cfg["pretraining"]["max_steps"] = 1
    out = tmp_path / "pt"
    pretrain(cfg, out)

    # a DIFFERENT trunk width must refuse the weights, not silently misload
    bad = CFG.replace("width = 64", "width = 96").replace(
        "width = 64", "width = 96"
    )
    cfg2 = Config.from_str(bad.format(raw=str(raw_jsonl)))
    cfg2.setdefault("initialize", {})["init_tok2vec"] = str(out / "model-last.npz")
    from spacy_ray_tpu.pipeline.language import Pipeline
    from spacy_ray_tpu.util import synth_corpus

    nlp = Pipeline.from_config(cfg2.interpolate())
    examples = synth_corpus(10, "tagger", seed=0)
    with pytest.raises(ValueError, match="init_tok2vec"):
        nlp.initialize(lambda: iter(examples), seed=0)
