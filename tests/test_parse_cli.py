"""`parse` command: bulk parallel inference over a corpus (the reference
README advertises `spacy ray parse` as planned surface, README.md:15).
Covers: training a model, parsing .spacy input sharded over the 8-device
mesh, raw-.txt input through the tokenizer, and jsonl/.spacy outputs."""

import json

import pytest

from spacy_ray_tpu.cli import main as cli_main
from spacy_ray_tpu.config import Config
from spacy_ray_tpu.util import write_synth_jsonl

pytestmark = pytest.mark.slow  # trains a model first


@pytest.fixture(scope="module")
def trained_model(tagger_config_text, tmp_path_factory):
    tmp = tmp_path_factory.mktemp("parse_model")
    write_synth_jsonl(tmp / "train.jsonl", 120, kind="tagger", seed=0)
    write_synth_jsonl(tmp / "dev.jsonl", 30, kind="tagger", seed=1)
    from spacy_ray_tpu.training.loop import train

    cfg = Config.from_str(tagger_config_text).apply_overrides(
        {
            "paths.train": str(tmp / "train.jsonl"),
            "paths.dev": str(tmp / "dev.jsonl"),
            "training.max_steps": 40,
        }
    )
    train(cfg, output_path=tmp / "out", n_workers=1, stdout_log=False)
    return tmp / "out" / "best-model"


def test_parse_spacy_input_jsonl_output(trained_model, tmp_path):
    write_synth_jsonl(tmp_path / "in.jsonl", 40, kind="tagger", seed=2)
    assert cli_main([
        "convert", str(tmp_path / "in.jsonl"), str(tmp_path / "in.spacy"),
    ]) == 0
    assert cli_main([
        "parse", str(trained_model), str(tmp_path / "in.spacy"),
        str(tmp_path / "out.jsonl"), "--device", "cpu",
    ]) == 0
    rows = [json.loads(l) for l in (tmp_path / "out.jsonl").read_text().splitlines()]
    assert len(rows) == 40
    # predictions, not gold: every doc must carry model-assigned tags
    assert all(r.get("tags") and all(t for t in r["tags"]) for r in rows)


def test_parse_txt_input_docbin_output(trained_model, tmp_path):
    (tmp_path / "raw.txt").write_text("the cat runs .\nthe dog sleeps .\n")
    assert cli_main([
        "parse", str(trained_model), str(tmp_path / "raw.txt"),
        str(tmp_path / "out.spacy"), "--device", "cpu",
    ]) == 0
    from spacy_ray_tpu.training.corpus import _iter_path

    docs = list(_iter_path(tmp_path / "out.spacy"))
    assert len(docs) == 2
    assert [t for t in docs[0].words] == ["the", "cat", "runs", "."]
    assert all(docs[0].tags), docs[0].tags


def test_benchmark_speed_and_accuracy(trained_model, tmp_path, capsys):
    """`benchmark speed` reports median/min/max words/s over reps;
    `benchmark accuracy` is the spaCy-CLI name for evaluate."""
    write_synth_jsonl(tmp_path / "dev.jsonl", 20, kind="tagger", seed=4)
    rc = cli_main([
        "benchmark", "speed", str(trained_model), str(tmp_path / "dev.jsonl"),
        "--device", "cpu", "--n-reps", "2",
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "words/s: median" in out and "min" in out and "max" in out

    rc = cli_main([
        "benchmark", "accuracy", str(trained_model),
        str(tmp_path / "dev.jsonl"), "--device", "cpu",
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "tag_acc" in out

    rc = cli_main(["benchmark", "nope"])
    assert rc == 1
    assert "speed,accuracy" in capsys.readouterr().err


def test_debug_diff_config(tmp_path, tagger_config_text, capsys):
    """debug-diff-config classifies [training] keys: customized vs
    redundant restatements vs implicit defaults."""
    cfg = tmp_path / "cfg.cfg"
    # the fixture already covers all three classes: patience = 0 is
    # customized (default 1600), dropout = 0.1 restates the default, and
    # untouched keys (e.g. logger) are implicit defaults
    text = tagger_config_text
    cfg.write_text(text)
    rc = cli_main(["debug-diff-config", str(cfg)])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "customized" in out
    assert "implicit default" in out
    lines = {l.split()[0]: l for l in out.splitlines() if l.strip()}
    assert "redundant" in lines.get("dropout", "")  # 0.1 IS the default

    # an invalid config still fails loudly before any diffing
    bad = tmp_path / "bad.cfg"
    bad.write_text(text.replace("patience = 0", "patiance = 0"))
    import pytest as _pytest

    with _pytest.raises(ValueError, match="patiance"):
        cli_main(["debug-diff-config", str(bad)])


def test_apply_alias_and_debug_profile(trained_model, tmp_path, capsys):
    """`apply` is spaCy's name for bulk annotation (same command as
    parse); `debug-profile` prints a host-side cProfile table."""
    write_synth_jsonl(tmp_path / "in.jsonl", 12, kind="tagger", seed=5)
    rc = cli_main([
        "apply", str(trained_model), str(tmp_path / "in.jsonl"),
        str(tmp_path / "applied.jsonl"), "--device", "cpu",
    ])
    assert rc == 0
    rows = [json.loads(l)
            for l in (tmp_path / "applied.jsonl").read_text().splitlines()]
    assert len(rows) == 12 and all(r.get("tags") for r in rows)
    capsys.readouterr()

    rc = cli_main([
        "debug-profile", str(trained_model), str(tmp_path / "in.jsonl"),
        "--device", "cpu", "--n-rows", "10",
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "cumtime" in out and "predict_docs" in out


def test_parse_empty_input_fails_loudly(trained_model, tmp_path):
    (tmp_path / "empty.txt").write_text("")
    assert cli_main([
        "parse", str(trained_model), str(tmp_path / "empty.txt"),
        str(tmp_path / "out.jsonl"), "--device", "cpu",
    ]) == 1
    assert not (tmp_path / "out.jsonl").exists()  # no empty artifact


def test_parse_empty_rank_slice_succeeds(trained_model, tmp_path, monkeypatch):
    """world > n_docs: a rank whose round-robin slice is empty must still
    exit 0 and write its (empty) part file — only a genuinely empty CORPUS
    is an error (the pre-streaming behavior, kept across the rewrite)."""
    import jax

    (tmp_path / "three.txt").write_text("a b\nc d\ne f\n")
    monkeypatch.setattr(jax, "process_index", lambda: 3)
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    rc = cli_main([
        "parse", str(trained_model), str(tmp_path / "three.txt"),
        str(tmp_path / "out.jsonl"), "--device", "cpu",
    ])
    assert rc == 0
    part = tmp_path / "out.part3.jsonl"
    assert part.exists() and part.read_text() == ""


def test_parse_failure_leaves_no_truncated_artifact(trained_model, tmp_path,
                                                    monkeypatch):
    """A mid-corpus prediction failure must not leave a well-formed-looking
    truncated output at the final path (the .tmp is cleaned up instead)."""
    from spacy_ray_tpu.pipeline.language import Pipeline

    write_synth_jsonl(tmp_path / "in.jsonl", 40, kind="tagger", seed=3)
    calls = {"n": 0}
    real = Pipeline.predict_docs

    def boom(self, docs, **kw):
        calls["n"] += 1
        if calls["n"] >= 2:
            raise RuntimeError("synthetic mid-corpus failure")
        return real(self, docs, **kw)

    monkeypatch.setattr(Pipeline, "predict_docs", boom)
    with pytest.raises(RuntimeError, match="mid-corpus"):
        cli_main([
            "parse", str(trained_model), str(tmp_path / "in.jsonl"),
            str(tmp_path / "out.jsonl"), "--device", "cpu",
            "--batch-size", "8",
        ])
    assert not (tmp_path / "out.jsonl").exists()
    assert not (tmp_path / "out.jsonl.tmp").exists()


TEXTCAT_CFG = """
[paths]
train = null
dev = null

[nlp]
lang = "en"
pipeline = ["tok2vec","textcat_multilabel"]

[components]
[components.tok2vec]
factory = "tok2vec"
[components.tok2vec.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = 32
depth = 1
embed_size = 256
[components.textcat_multilabel]
factory = "textcat_multilabel"
[components.textcat_multilabel.model]
@architectures = "spacy.TextCatCNN.v2"
[components.textcat_multilabel.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 32

[corpora]
[corpora.train]
@readers = "spacy.JsonlCorpus.v1"
path = ${paths.train}
[corpora.dev]
@readers = "spacy.JsonlCorpus.v1"
path = ${paths.dev}

[training]
seed = 0
max_steps = 60
eval_frequency = 30
patience = 0
"""


def test_find_threshold_sweeps_and_reports_best(tmp_path, capsys, monkeypatch):
    """find-threshold: sweep textcat_multilabel's threshold on dev data,
    report the best value by the component's default positive score key
    (spaCy's find-threshold surface) — and leave the component's threshold
    attribute at its ORIGINAL value afterwards (round-4 advisor: the sweep
    must not park it at the last trial value, t=1.0, where any future
    in-process save would persist it)."""
    write_synth_jsonl(tmp_path / "train.jsonl", 120, kind="textcat", seed=0)
    write_synth_jsonl(tmp_path / "dev.jsonl", 40, kind="textcat", seed=1)
    from spacy_ray_tpu.pipeline.language import Pipeline
    from spacy_ray_tpu.training.loop import train

    cfg = Config.from_str(TEXTCAT_CFG).apply_overrides(
        {
            "paths.train": str(tmp_path / "train.jsonl"),
            "paths.dev": str(tmp_path / "dev.jsonl"),
        }
    )
    train(cfg, output_path=tmp_path / "out", n_workers=1, stdout_log=False)

    captured = {}
    real_from_disk = Pipeline.from_disk.__func__

    def spy(cls, path):
        nlp = real_from_disk(cls, path)
        comp = nlp.components["textcat_multilabel"]
        captured["comp"], captured["before"] = comp, comp.threshold
        return nlp

    monkeypatch.setattr(Pipeline, "from_disk", classmethod(spy))
    rc = cli_main([
        "find-threshold", str(tmp_path / "out" / "best-model"),
        str(tmp_path / "dev.jsonl"), "textcat_multilabel",
        "--device", "cpu", "--n-trials", "5",
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    # 5 sweep rows + a Best line naming the config key to set
    assert out.count("threshold=") >= 5
    assert "Best: threshold=" in out
    assert "cats_score=" in out
    # the sweep restored the component's original threshold
    assert captured["comp"].threshold == captured["before"]


def test_find_threshold_unknown_pipe_fails(tmp_path, trained_model):
    write_synth_jsonl(tmp_path / "dev.jsonl", 10, kind="tagger", seed=1)
    rc = cli_main([
        "find-threshold", str(trained_model), str(tmp_path / "dev.jsonl"),
        "nope", "--device", "cpu",
    ])
    assert rc == 1


def test_init_config_pipeline_composition_trains(tmp_path):
    """init-config --pipeline composes an arbitrary component list over a
    shared trunk into a config that ACTUALLY TRAINS (score weights come
    from the components' default_score_weights since the section is left
    empty)."""
    cfg_path = tmp_path / "composed.cfg"
    assert cli_main([
        "init-config", str(cfg_path),
        "--pipeline", "tagger,senter,entity_ruler",
    ]) == 0
    write_synth_jsonl(tmp_path / "train.jsonl", 60, kind="tagger", seed=0)
    write_synth_jsonl(tmp_path / "dev.jsonl", 20, kind="tagger", seed=1)
    from spacy_ray_tpu.config import Config
    from spacy_ray_tpu.training.loop import train

    cfg = Config.from_str(cfg_path.read_text()).apply_overrides(
        {
            "paths.train": str(tmp_path / "train.jsonl"),
            "paths.dev": str(tmp_path / "dev.jsonl"),
            "training.max_steps": 20,
            "training.eval_frequency": 10,
        }
    )
    nlp, result = train(cfg, n_workers=1, stdout_log=False)
    assert nlp.pipe_names == ["tok2vec", "tagger", "senter", "entity_ruler"]
    assert result.best_score >= 0  # eval ran with derived score weights


def test_init_config_pipeline_rejects_unknown(tmp_path):
    rc = cli_main([
        "init-config", str(tmp_path / "x.cfg"), "--pipeline", "tagger,entity_linker",
    ])
    assert rc == 1


def test_init_config_preset_still_works(tmp_path):
    assert cli_main([
        "init-config", str(tmp_path / "p.cfg"), "--preset", "sm",
    ]) == 0
    from spacy_ray_tpu.config import Config

    Config.from_str((tmp_path / "p.cfg").read_text())


def test_info_command(trained_model, capsys):
    assert cli_main(["info"]) == 0
    out = capsys.readouterr().out
    assert "spacy-ray-tpu" in out and "jax" in out
    assert cli_main(["info", str(trained_model)]) == 0
    out = capsys.readouterr().out
    assert "components" in out and "tagger" in out
    assert cli_main(["info", "/nonexistent/model"]) == 1


def test_debug_model_prints_shapes(tmp_path, capsys):
    cfg_path = tmp_path / "dm.cfg"
    assert cli_main(["init-config", str(cfg_path), "--pipeline", "tagger,entity_ruler"]) == 0
    write_synth_jsonl(tmp_path / "t.jsonl", 30, kind="tagger", seed=0)
    rc = cli_main([
        "debug-model", str(cfg_path),
        "--paths.train", str(tmp_path / "t.jsonl"),
        "--paths.dev", str(tmp_path / "t.jsonl"),
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "[tok2vec]" in out and "[tagger]" in out
    assert "host-side component" in out  # entity_ruler has no device params
    assert "TOTAL:" in out
    # component filter + unknown component
    assert cli_main([
        "debug-model", str(cfg_path), "tagger",
        "--paths.train", str(tmp_path / "t.jsonl"),
        "--paths.dev", str(tmp_path / "t.jsonl"),
    ]) == 0
    assert cli_main([
        "debug-model", str(cfg_path), "nope",
        "--paths.train", str(tmp_path / "t.jsonl"),
        "--paths.dev", str(tmp_path / "t.jsonl"),
    ]) == 1


def test_fill_config_completes_partial(tmp_path, capsys):
    """fill-config materializes every [training] default into the written
    file, the filled config trains, and bad keys still fail loudly."""
    partial = tmp_path / "partial.cfg"
    partial.write_text("""
[nlp]
lang = "en"
pipeline = ["tok2vec","tagger"]

[components.tok2vec]
factory = "tok2vec"
[components.tok2vec.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = 32
depth = 1
embed_size = 128
[components.tagger]
factory = "tagger"
[components.tagger.model]
@architectures = "spacy.Tagger.v2"
[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 32

[corpora]
[corpora.train]
@readers = "spacy.JsonlCorpus.v1"
path = ${paths.train}
[corpora.dev]
@readers = "spacy.JsonlCorpus.v1"
path = ${paths.dev}

[training]
dropout = 0.25
""")
    filled = tmp_path / "filled.cfg"
    assert cli_main(["fill-config", str(partial), str(filled)]) == 0
    out = capsys.readouterr().out
    assert "added:" in out
    from spacy_ray_tpu.config import Config

    cfg = Config.from_str(filled.read_text())
    t = cfg["training"]
    assert t["dropout"] == 0.25          # user value preserved
    assert t["patience"] == 1600         # default materialized
    assert "optimizer" in t and "batcher" in t and "logger" in t
    # the filled config actually trains
    write_synth_jsonl(tmp_path / "t.jsonl", 40, kind="tagger", seed=0)
    from spacy_ray_tpu.training.loop import train

    cfg2 = cfg.apply_overrides(
        {
            "paths.train": str(tmp_path / "t.jsonl"),
            "paths.dev": str(tmp_path / "t.jsonl"),
            "training.max_steps": 10,
            "training.eval_frequency": 5,
        }
    )
    _, result = train(cfg2, n_workers=1, stdout_log=False)
    assert result.final_step == 10

    # typo'd keys are rejected at fill time, not silently filled around
    bad = tmp_path / "bad.cfg"
    bad.write_text(partial.read_text().replace("dropout = 0.25", "dropot = 0.25"))
    import pytest as _pytest

    with _pytest.raises(ValueError, match="dropot"):
        cli_main(["fill-config", str(bad), str(tmp_path / "x.cfg")])


def test_find_threshold_rejects_non_numeric_attr(trained_model, tmp_path):
    write_synth_jsonl(tmp_path / "dev.jsonl", 10, kind="tagger", seed=1)
    rc = cli_main([
        "find-threshold", str(trained_model), str(tmp_path / "dev.jsonl"),
        "tagger", "--threshold-key", "score", "--device", "cpu",
    ])
    assert rc == 1  # bound method, not a numeric attribute


def test_init_config_pipeline_rejects_duplicates(tmp_path):
    rc = cli_main([
        "init-config", str(tmp_path / "d.cfg"), "--pipeline", "tagger,tagger",
    ])
    assert rc == 1
