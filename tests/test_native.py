"""Native extension tests: bit-parity with the Python murmur implementation
(the device-side jnp murmur is itself tested against the numpy oracle in
test_ops.py, so the whole chain host-C++ -> host-python -> device-jnp agrees)."""

import random
import string

import numpy as np

from spacy_ray_tpu.native import available, hash_strings_u64
from spacy_ray_tpu.ops.hashing import hash_string_u64
from spacy_ray_tpu.pipeline.vocab import Vocab


def test_native_matches_python_bitwise():
    rng = random.Random(0)
    strings_ = [
        "".join(rng.choices(string.printable, k=rng.randint(0, 40)))
        for _ in range(500)
    ]
    strings_ += ["", "a", "ab", "norm=the", "日本語テキスト", "x" * 15, "x" * 16, "x" * 17]
    got = hash_strings_u64(strings_)
    want = np.array([hash_string_u64(s) for s in strings_], dtype=np.uint64)
    np.testing.assert_array_equal(got, want)


def test_native_builds_in_this_image():
    # the toolchain is part of the environment contract; if this fails the
    # fallback still works but we want to KNOW the native path regressed
    assert available()


def test_vocab_featurize_batch_matches_single():
    v1, v2 = Vocab(), Vocab()
    words = ["The", "cat", "sat", "on", "THE", "mat", "cat"]
    batch = v1.featurize(words)
    single = np.stack([v2.token_features(w) for w in words])
    np.testing.assert_array_equal(batch, single)
    # cache hit path: second call identical
    np.testing.assert_array_equal(v1.featurize(words), batch)
