"""Live continuous-learning subsystem (spacy_ray_tpu/serving/live/ +
engine hot-swap): the Checkpoints read-only API and its reader-vs-writer
protocol, the checkpoint watcher's torn-generation skip semantics,
swap-at-dispatch-boundary bit-exactness under concurrent load, instant
rollback, the /admin endpoints, generation-tagged fleet metrics, the
router's canary traffic split, the guard's promote/rollback policy, the
fleet rollout controller (including a forced-regression auto-rollback),
and the train-and-serve orchestration end to end."""

import json
import http.client
import os
import re
import signal
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import numpy as np
import pytest

from spacy_ray_tpu.config import Config
from spacy_ray_tpu.pipeline.language import Pipeline
from spacy_ray_tpu.serving import (
    InferenceEngine,
    Server,
    ServingTelemetry,
    SwapFailed,
)
from spacy_ray_tpu.serving.fleet.replica import ReplicaHandle
from spacy_ray_tpu.serving.fleet.router import Router, RouterTelemetry
from spacy_ray_tpu.serving.live import (
    CanaryGuard,
    CheckpointWatcher,
    GenerationStats,
    LiveFleetController,
    scan_intact_generations,
)
from spacy_ray_tpu.training import resilience
from spacy_ray_tpu.training.checkpoint import (
    CheckpointCorrupt,
    Checkpoints,
    TrainCheckpoint,
)
from spacy_ray_tpu.training.resilience import FaultPlan
from spacy_ray_tpu.training.telemetry import merge_serving_snapshots
from spacy_ray_tpu.util import synth_corpus, write_synth_jsonl

SERVE_CFG = """
[nlp]
lang = "en"
pipeline = ["tok2vec","tagger"]

[components]

[components.tok2vec]
factory = "tok2vec"

[components.tok2vec.model]
@architectures = "spacy.HashEmbedCNN.v2"
width = 64
depth = 2
embed_size = 512

[components.tagger]
factory = "tagger"

[components.tagger.model]
@architectures = "spacy.Tagger.v2"

[components.tagger.model.tok2vec]
@architectures = "spacy.Tok2VecListener.v1"
width = 64
"""

TEXTS = [
    "the cat runs fast today",
    "a dog sleeps near the door",
    "birds sing loudly in the morning",
    "the quick brown fox jumps high",
    "rain falls softly on the roof",
    "stars shine over the quiet town",
]


def _post(host, port, payload, timeout=30.0, path="/v1/parse"):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        body = json.dumps(payload).encode("utf8")
        conn.request("POST", path, body, {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _post_raw(host, port, payload, timeout=30.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        body = json.dumps(payload).encode("utf8")
        conn.request("POST", "/v1/parse", body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _get(host, port, path, timeout=10.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


@pytest.fixture(autouse=True)
def _no_fault_plan():
    prev = resilience.set_fault_plan(None)
    yield
    resilience.set_fault_plan(prev)


def _save_generation(path, params, stamp, keep=8):
    """One engine-compatible TrainCheckpoint generation (tiny opt stub:
    the serving side only reads params)."""
    TrainCheckpoint.save(
        path,
        params=params,
        opt_state={"note": np.zeros(1, np.float32)},
        step=stamp,
        epoch=0,
        rng=np.zeros(2, np.uint32),
        best_score=0.0,
        best_step=0,
        keep=keep,
    )


TINY_PARAMS = {"w": {"kernel": np.ones((2, 2), np.float32)}}


# ----------------------------------------------------------------------
# Checkpoints: the read-only concurrent-reader API
# ----------------------------------------------------------------------


def test_checkpoints_generations_and_latest_intact(tmp_path):
    ckpts = Checkpoints(tmp_path)
    assert ckpts.generations() == []
    assert ckpts.latest_intact_generation() is None
    _save_generation(tmp_path, TINY_PARAMS, 10)
    _save_generation(tmp_path, TINY_PARAMS, 20)
    assert ckpts.generations() == [10, 20]
    assert ckpts.latest_intact_generation() == 20
    state = ckpts.load_generation(10)
    assert state["step"] == 10
    assert np.asarray(state["params"]["w"]["kernel"]).shape == (2, 2)
    # the serving path's params-only load: verified, no opt_state touched
    lean = ckpts.load_generation_params(10)
    assert set(lean) == {"params", "step"} and lean["step"] == 10
    np.testing.assert_array_equal(
        np.asarray(lean["params"]["w"]["kernel"]),
        np.asarray(state["params"]["w"]["kernel"]),
    )
    # a torn opt_state does NOT block a params-only swap load...
    (tmp_path / "opt_state-10.pkl").write_bytes(b"torn")
    assert ckpts.load_generation_params(10)["step"] == 10
    # ...but torn params do
    (tmp_path / "params-10.npz").write_bytes(b"torn")
    with pytest.raises(CheckpointCorrupt):
        ckpts.load_generation_params(10)


def test_checkpoints_torn_generation_falls_back_and_raises(tmp_path):
    _save_generation(tmp_path, TINY_PARAMS, 10)
    _save_generation(tmp_path, TINY_PARAMS, 20)
    # tear the newest generation's params (torn-write simulation, the
    # same drill the PR 2 fallback tests use)
    torn = tmp_path / "params-20.npz"
    torn.write_bytes(torn.read_bytes()[:-7])
    ckpts = Checkpoints(tmp_path)
    assert ckpts.latest_intact_generation() == 10
    with pytest.raises(CheckpointCorrupt):
        ckpts.verify_generation(20)
    with pytest.raises(CheckpointCorrupt):
        ckpts.load_generation(20)
    with pytest.raises(CheckpointCorrupt):
        ckpts.load_generation(999)  # never existed


def test_reader_never_sees_partial_generation(tmp_path):
    """The reader-vs-writer contract, enumerated: replay the writer's
    file sequence for a new generation one step at a time; at EVERY
    prefix the reader reports either the old generation or (only once
    the per-generation meta landed — the commit point) the new one."""
    _save_generation(tmp_path, TINY_PARAMS, 10)
    staging = tmp_path / "staging"
    _save_generation(staging, TINY_PARAMS, 20)
    ckpts = Checkpoints(tmp_path)
    # the writer's order (TrainCheckpoint.save): params tmp -> params ->
    # opt tmp -> opt -> gen meta -> pointer meta
    steps = [
        ("params-20.npz.tmp.npz", "params-20.npz", False),
        ("params-20.npz", "params-20.npz", False),
        ("opt_state-20.pkl.tmp", "opt_state-20.pkl", False),
        ("opt_state-20.pkl", "opt_state-20.pkl", False),
        ("train_meta-20.json", "train_meta-20.json", True),   # commit
        ("train_meta.json", "train_meta.json", True),
    ]
    for dst_name, src_name, committed in steps:
        (tmp_path / dst_name).write_bytes(
            (staging / src_name).read_bytes()
        )
        got = ckpts.latest_intact_generation()
        assert got == (20 if committed else 10), (dst_name, got)
        assert scan_intact_generations(tmp_path)[-1] == got


def test_scan_intact_generations_matches_checkpoints(tmp_path):
    _save_generation(tmp_path, TINY_PARAMS, 5)
    _save_generation(tmp_path, TINY_PARAMS, 15)
    assert scan_intact_generations(tmp_path) == [5, 15]
    # pre-hash filters: a control loop's idle tick verifies NOTHING
    assert scan_intact_generations(tmp_path, newer_than=15) == []
    assert scan_intact_generations(tmp_path, newer_than=5, skip={15}) == []
    assert scan_intact_generations(tmp_path, newer_than=5) == [15]
    (tmp_path / "opt_state-15.pkl").write_bytes(b"torn")
    assert scan_intact_generations(tmp_path) == [5]
    # params-only scope (the serving-swap question): torn opt is fine
    assert scan_intact_generations(tmp_path, params_only=True) == [5, 15]
    assert Checkpoints(tmp_path).latest_intact_generation() == 5
    assert Checkpoints(tmp_path).latest_intact_generation(
        params_only=True
    ) == 15
    assert scan_intact_generations(tmp_path / "nope") == []


# ----------------------------------------------------------------------
# CheckpointWatcher: delivery + torn-skip semantics
# ----------------------------------------------------------------------


def test_watcher_delivers_newest_once(tmp_path):
    got = []
    w = CheckpointWatcher(tmp_path, lambda s, st: got.append((s, st["step"])))
    assert w.poll_once() is None  # empty dir: nothing, no crash
    _save_generation(tmp_path, TINY_PARAMS, 10)
    _save_generation(tmp_path, TINY_PARAMS, 20)
    assert w.poll_once() == 20  # newest wins; 10 is never replayed
    assert w.poll_once() is None  # no redelivery
    _save_generation(tmp_path, TINY_PARAMS, 30)
    assert w.poll_once() == 30
    assert got == [(20, 20), (30, 30)]
    assert w.delivered == 2 and w.current == 30


def test_watcher_skips_torn_generation_with_one_event(tmp_path):
    _save_generation(tmp_path, TINY_PARAMS, 10)
    _save_generation(tmp_path, TINY_PARAMS, 20)
    (tmp_path / "params-20.npz").write_bytes(b"not a zipfile")
    got = []
    w = CheckpointWatcher(tmp_path, lambda s, st: got.append(s))
    resilience.drain_events()
    assert w.poll_once() == 10  # torn 20 skipped, intact 10 delivered
    events = [e for e in resilience.drain_events()
              if e["event"] == "live-generation-skipped"]
    assert len(events) == 1 and events[0]["stamp"] == 20
    # later polls re-check but do NOT re-emit the event (no storm)
    assert w.poll_once() is None
    assert not [e for e in resilience.drain_events()
                if e["event"] == "live-generation-skipped"]
    # the writer eventually commits an intact newer generation
    _save_generation(tmp_path, TINY_PARAMS, 30)
    assert w.poll_once() == 30
    assert got == [10, 30] and w.skipped >= 1


def test_watcher_retries_generation_when_subscriber_fails(tmp_path):
    """A transiently-failing subscriber (device hiccup mid-stage) must
    NOT burn the generation: delivery happens before the floor
    advances, so the next poll retries the same stamp."""
    _save_generation(tmp_path, TINY_PARAMS, 10)
    calls = []

    def flaky(stamp, state):
        calls.append(stamp)
        if len(calls) == 1:
            raise RuntimeError("transient staging failure")

    w = CheckpointWatcher(tmp_path, flaky)
    with pytest.raises(RuntimeError):
        w.poll_once()
    assert w.current is None and w.delivered == 0
    assert w.poll_once() == 10  # retried, not skipped forever
    assert calls == [10, 10] and w.current == 10


def test_watcher_faultplan_killed_save_is_invisible(tmp_path):
    """FaultPlan drill at the checkpoint-write site: a save killed by an
    injected fault commits NOTHING (the crash-safe protocol), so the
    watcher sees no new generation — and no partial state either."""
    _save_generation(tmp_path, TINY_PARAMS, 10)
    w = CheckpointWatcher(tmp_path, lambda s, st: None)
    assert w.poll_once() == 10
    resilience.set_fault_plan(FaultPlan.parse("checkpoint-write:1:runtime"))
    with pytest.raises(resilience.FaultInjected):
        _save_generation(tmp_path, TINY_PARAMS, 20)
    resilience.set_fault_plan(None)
    assert Checkpoints(tmp_path).generations() == [10]
    assert w.poll_once() is None and w.current == 10


# ----------------------------------------------------------------------
# Engine hot-swap: dispatch-boundary bit-exactness + rollback
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def serve_nlp():
    nlp = Pipeline.from_config(Config.from_str(SERVE_CFG))
    egs = synth_corpus(64, "tagger", seed=0)
    nlp.initialize(lambda: iter(egs), seed=0)
    return nlp


@pytest.fixture(scope="module")
def params_b(serve_nlp):
    """A second param tree with the same structure but different values
    (seed 1) — predictions must differ somewhere or swap tests could
    pass vacuously (asserted below)."""
    nlp = Pipeline.from_config(Config.from_str(SERVE_CFG))
    egs = synth_corpus(64, "tagger", seed=0)
    nlp.initialize(lambda: iter(egs), seed=1)
    return nlp.params


def _ground_truth(nlp, params, texts):
    out = {}
    for t in texts:
        doc = nlp.tokenizer(t)
        nlp.predict_docs([doc], params=params)
        out[t] = list(doc.tags)
    return out


def test_swap_at_dispatch_boundary_bit_exact_under_load(
    serve_nlp, params_b
):
    """The tentpole's core contract: under concurrent HTTP load, every
    response equals the ground truth of EXACTLY the generation stamped
    on it — before the flip all old, after all new, never mixed — and
    both generations are observed (the swap really landed mid-load)."""
    tags_a = _ground_truth(serve_nlp, serve_nlp.params, TEXTS)
    tags_b = _ground_truth(serve_nlp, params_b, TEXTS)
    assert tags_a != tags_b, "seed-1 params predict identically to seed-0"
    tel = ServingTelemetry()
    engine = InferenceEngine(
        serve_nlp, max_batch_docs=4, max_doc_len=16, timeout_s=30.0,
        telemetry=tel,
    )
    engine.start(warmup=False)
    server = Server(engine, "127.0.0.1", 0, telemetry=tel)
    host, port = server.start()
    results = []
    lock = threading.Lock()
    stop = threading.Event()

    def client(idx):
        i = 0
        while not stop.is_set():
            text = TEXTS[(idx + i) % len(TEXTS)]
            status, payload = _post(host, port, {"texts": [text]})
            with lock:
                results.append((text, status, payload))
            i += 1

    threads = [threading.Thread(target=client, args=(i,)) for i in range(4)]
    try:
        for t in threads:
            t.start()
        # let traffic flow on generation None, then flip mid-load
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            with lock:
                if len(results) >= 12:
                    break
            time.sleep(0.02)
        engine.swap_params(params_b, 7, source="test")
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            with lock:
                n_new = sum(
                    1 for _, s, p in results
                    if s == 200 and p["batch"]["generation"] == 7
                )
            if n_new >= 12:
                break
            time.sleep(0.02)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
        server.request_shutdown()
        assert server.wait() == 0

    assert all(s == 200 for _, s, _ in results), (
        [s for _, s, _ in results if s != 200]
    )
    gens = {p["batch"]["generation"] for _, _, p in results}
    assert gens == {None, 7}, gens  # the swap landed under live load
    for text, _, payload in results:
        gen = payload["batch"]["generation"]
        expect = tags_a[text] if gen is None else tags_b[text]
        assert payload["docs"][0]["tags"] == expect, (
            f"generation {gen} response diverged from that generation's "
            f"ground truth for {text!r}"
        )
    snap = tel.snapshot()
    assert snap["counters"]["swaps"] == 1
    assert snap["histograms"]["swap_flip_seconds"]["count"] == 1
    assert snap["histograms"]["swap_stage_seconds"]["max"] is not None
    assert snap["gauges"]["serving_generation"] == 7


def test_rollback_restores_byte_identical_responses(serve_nlp, params_b):
    engine = InferenceEngine(
        serve_nlp, max_batch_docs=4, max_doc_len=16, timeout_s=30.0
    )
    engine.start(warmup=False)
    server = Server(engine, "127.0.0.1", 0)
    host, port = server.start()
    try:
        payload = {"texts": [TEXTS[0]]}
        status, before = _post_raw(host, port, payload)
        assert status == 200
        engine.swap_params(params_b, 3)
        status, swapped = _post_raw(host, port, payload)
        assert status == 200
        engine.rollback()
        status, after = _post_raw(host, port, payload)
        assert status == 200
        assert after == before, "rollback did not restore byte-identical output"
        assert swapped != before  # and the swap really changed something
        # rollback is its own inverse: one more call re-seats generation 3
        assert engine.rollback()["generation"] == 3
    finally:
        server.request_shutdown()
        assert server.wait() == 0


def test_swap_refuses_mismatched_tree_and_keeps_serving(serve_nlp):
    engine = InferenceEngine(
        serve_nlp, max_batch_docs=4, max_doc_len=16, timeout_s=30.0
    )
    engine.start(warmup=False)
    try:
        doc = serve_nlp.tokenizer(TEXTS[0])
        before = list(
            engine.submit_docs([serve_nlp.tokenizer(TEXTS[0])]).docs[0].tags
        )
        with pytest.raises(SwapFailed):
            engine.swap_params({"garbage": np.zeros(3, np.float32)}, 99)
        with pytest.raises(SwapFailed):
            engine.rollback()  # a refused swap leaves nothing to roll to
        assert engine.serving_generation is None and engine.swap_count == 0
        req = engine.submit_docs([doc])
        assert list(req.docs[0].tags) == before
    finally:
        engine.stop()


# ----------------------------------------------------------------------
# HTTP surface: /healthz + /metrics generation fields, /admin endpoints
# ----------------------------------------------------------------------


def test_admin_swap_and_rollback_over_http(serve_nlp, params_b, tmp_path):
    _save_generation(tmp_path, params_b, 5)
    tel = ServingTelemetry()
    engine = InferenceEngine(
        serve_nlp, max_batch_docs=4, max_doc_len=16, timeout_s=30.0,
        telemetry=tel,
    )
    engine.start(warmup=False)
    server = Server(
        engine, "127.0.0.1", 0, telemetry=tel,
        swap_dirs=[str(tmp_path)],
    )
    host, port = server.start()
    try:
        status, health = _get(host, port, "/healthz")
        assert status == 200
        assert health["generation"] is None and health["swap_count"] == 0

        # only the allowlisted directory may be swapped from: an open
        # port must not load weights from arbitrary client paths
        status, res = _post(
            host, port, {"dir": "/somewhere/else"}, path="/admin/swap"
        )
        assert status == 403 and res["error"] == "forbidden"

        status, res = _post(
            host, port, {"dir": str(tmp_path)}, path="/admin/swap"
        )
        assert status == 200, res
        assert res["generation"] == 5 and res["swap_count"] == 1
        assert res["flip_s"] < 0.5  # the flip is pointers, not params

        status, health = _get(host, port, "/healthz")
        assert health["generation"] == 5 and health["swap_count"] == 1
        status, metrics = _get(host, port, "/metrics")
        assert metrics["generation"] == 5 and metrics["swap_count"] == 1
        assert metrics["counters"]["swaps"] == 1

        # responses carry the generation stamp
        status, payload = _post(host, port, {"texts": [TEXTS[0]]})
        assert status == 200 and payload["batch"]["generation"] == 5

        status, res = _post(host, port, {}, path="/admin/rollback")
        assert status == 200 and res["generation"] is None
        status, health = _get(host, port, "/healthz")
        assert health["generation"] is None and health["swap_count"] == 2

        # typed failures: unknown generation, torn generation, bad body
        status, res = _post(
            host, port, {"dir": str(tmp_path), "generation": 999},
            path="/admin/swap",
        )
        assert status == 409 and res["error"] == "swap_failed"
        _save_generation(tmp_path, params_b, 6)
        (tmp_path / "params-6.npz").write_bytes(b"torn")
        status, res = _post(
            host, port, {"dir": str(tmp_path), "generation": 6},
            path="/admin/swap",
        )
        assert status == 409 and res["error"] == "swap_failed"
        # dir-latest selection skips the torn newest: picks 5 again
        status, res = _post(
            host, port, {"dir": str(tmp_path)}, path="/admin/swap"
        )
        assert status == 200 and res["generation"] == 5
        status, res = _post(host, port, {"nope": 1}, path="/admin/swap")
        assert status == 400
    finally:
        server.request_shutdown()
        assert server.wait() == 0


def test_admin_surface_disabled_without_configured_dir(serve_nlp, tmp_path):
    engine = InferenceEngine(
        serve_nlp, max_batch_docs=4, max_doc_len=16, timeout_s=30.0
    )
    engine.start(warmup=False)
    server = Server(engine, "127.0.0.1", 0)  # no --watch/--swap-dir
    host, port = server.start()
    try:
        status, res = _post(
            host, port, {"dir": str(tmp_path)}, path="/admin/swap"
        )
        assert status == 403 and "disabled" in res["message"]
        # rollback is gated by the SAME config: an ungated rollback on
        # an open port would let any client revert/toggle generations
        status, res = _post(host, port, {}, path="/admin/rollback")
        assert status == 403 and res["error"] == "forbidden"
    finally:
        server.request_shutdown()
        assert server.wait() == 0


# ----------------------------------------------------------------------
# Fleet metrics: per-generation splitting
# ----------------------------------------------------------------------


def test_merge_snapshots_by_generation():
    def snap(rid, gen, requests, errors, p99, samples):
        return {
            "replica_id": rid,
            "generation": gen,
            "counters": {"requests": requests, "errors": errors},
            "histograms": {
                "request_latency_seconds": {
                    "count": samples, "sum": 1.0, "min": 0.001, "max": p99,
                    "p50": p99 / 2, "p95": p99, "p99": p99,
                },
            },
            "slo": {"request_latency_p99": p99},
            "slo_window": {
                "window_s": 30.0, "samples": samples,
                "request_latency_p50": p99 / 2,
                "request_latency_p95": p99,
                "request_latency_p99": p99,
            },
        }

    merged = merge_serving_snapshots([
        snap(0, None, 100, 0, 0.010, 50),
        snap(1, None, 100, 2, 0.012, 50),
        snap(2, 40, 30, 9, 0.200, 25),
    ])
    assert merged["counters"]["requests"] == 230
    by_gen = merged["by_generation"]
    assert sorted(by_gen) == ["40", "none"]
    base, canary = by_gen["none"], by_gen["40"]
    assert base["counters"]["requests"] == 200
    assert base["counters"]["errors"] == 2
    assert canary["counters"] == {"requests": 30, "errors": 9}
    # the split percentiles are each side's own, not blended
    assert canary["slo_window"]["request_latency_p99"] == pytest.approx(0.2)
    assert base["slo_window"]["request_latency_p99"] < 0.05
    assert canary["generation"] == 40 and base["generation"] is None
    # nothing tagged -> no by_generation block (old payloads unchanged)
    assert "by_generation" not in merge_serving_snapshots([
        {"replica_id": 0, "counters": {"requests": 1}},
    ])


# ----------------------------------------------------------------------
# Router: generation-weighted canary split
# ----------------------------------------------------------------------


def _stub_handles(gens):
    handles = []
    for i, gen in enumerate(gens):
        h = ReplicaHandle(i)
        h.set_address("127.0.0.1", 9000 + i)
        h.ready = True
        h.generation = gen
        handles.append(h)
    return handles


def test_router_canary_split_exact_fraction():
    handles = _stub_handles([None, None, 40])
    tel = RouterTelemetry()
    router = Router(
        lambda: handles, telemetry=tel, canary_fraction=0.25
    )
    router.canary_generation = 40  # controller declares the rollout
    picks = [router.pick() for _ in range(100)]
    canary = sum(1 for h in picks if h.generation == 40)
    assert canary == 25  # error-diffusion accumulator: exact, not approx
    snap = tel.snapshot()
    assert snap["counters"]["routed_canary"] == 25
    assert snap["counters"]["routed_baseline"] == 75


def test_router_split_only_during_declared_rollout():
    """Regression: generation heterogeneity WITHOUT an active rollout —
    e.g. one replica crash-restarted onto the disk model — must not
    redirect traffic (the stale singleton would otherwise absorb
    1-fraction of the whole fleet's load as the 'baseline')."""
    handles = _stub_handles([None, 40, 40])  # replica 0 restarted stale
    tel = RouterTelemetry()
    router = Router(lambda: handles, telemetry=tel, canary_fraction=0.25)
    # plain least-outstanding across ALL replicas: with the stale one
    # busiest, traffic goes to the healthy pair — under a (wrongly)
    # active split it would instead be the one-node "baseline" pool
    # receiving 75% of picks regardless of load
    handles[0].outstanding = 3
    picks = [router.pick().replica_id for _ in range(30)]
    assert picks.count(0) == 0
    assert tel.snapshot()["counters"].get("routed_canary", 0) == 0
    # the controller finishing a rollout turns the split off again
    router.canary_generation = 40
    router.pick()
    router.canary_generation = None
    tel2 = RouterTelemetry()
    router.tel = tel2
    for _ in range(10):
        router.pick()
    assert tel2.snapshot()["counters"].get("routed_canary", 0) == 0


def test_router_canary_split_prefers_least_outstanding_within_side():
    handles = _stub_handles([None, 40, 40])
    handles[1].outstanding = 5
    router = Router(lambda: handles, canary_fraction=1.0)  # always canary
    router.canary_generation = 40
    assert router.pick().replica_id == 2  # least-outstanding canary


# ----------------------------------------------------------------------
# CanaryGuard: promote / rollback policy
# ----------------------------------------------------------------------


def _stats(gen, requests, errors, p99=None, samples=0):
    return GenerationStats(
        generation=gen, requests=requests, errors=errors,
        window_samples=samples, p99_s=p99,
    )


def test_guard_promotes_after_clean_ticks_with_traffic():
    g = CanaryGuard(min_canary_requests=10, good_consecutive=2,
                    bad_consecutive=2)
    base0 = _stats(None, 1000, 5, p99=0.02, samples=100)
    canary0 = _stats(40, 500, 3)  # pre-swap lifetime counters
    g.begin(base0, canary0)
    # not enough canary traffic yet: silence is not evidence
    assert g.observe(base0, _stats(40, 505, 3)) is None
    assert g.observe(
        _stats(None, 1100, 5, p99=0.02, samples=100),
        _stats(40, 515, 3, p99=0.022, samples=30),
    ) is None  # first clean tick with traffic
    assert g.observe(
        _stats(None, 1200, 5, p99=0.02, samples=100),
        _stats(40, 530, 3, p99=0.021, samples=40),
    ) == "promote"
    assert g.decisions[-1]["verdict"] == "promote"


def test_guard_rolls_back_on_error_rate():
    g = CanaryGuard(min_canary_requests=10, bad_consecutive=2,
                    error_rate_high=0.05)
    g.begin(_stats(None, 1000, 0), _stats(40, 500, 100))
    bad = lambda extra: _stats(40, 500 + 40 + extra, 100 + 20 + extra)  # noqa: E731
    assert g.observe(_stats(None, 1050, 0), bad(0)) is None
    assert g.observe(_stats(None, 1100, 0), bad(5)) == "rollback"
    d = g.decisions[-1]
    assert d["verdict"] == "rollback" and d["canary_error_rate"] > 0.05
    # pre-canary errors (the 100 baked into begin) were NOT counted:
    # the rate came from post-begin deltas only
    assert d["canary_error_rate"] < 0.6


def test_guard_rolls_back_on_p99_regression():
    g = CanaryGuard(min_canary_requests=5, bad_consecutive=2,
                    p99_frac=1.5, min_window_samples=10)
    g.begin(_stats(None, 0, 0), _stats(40, 0, 0))
    slow = _stats(40, 50, 0, p99=0.9, samples=30)
    fast = _stats(None, 500, 0, p99=0.01, samples=100)
    assert g.observe(fast, slow) is None
    assert g.observe(fast, slow) == "rollback"
    assert "p99" in g.decisions[-1]["why"]


def test_guard_counts_timeouts_as_errors():
    """Regression: a canary that blows every deadline produces no 500s
    AND no latency samples (timed-out requests never reach the
    histogram) — deadline_exceeded must feed the error rate or a
    100%-timeout generation would look clean and get promoted."""
    block = {
        "generation": 40,
        "counters": {"requests": 100.0, "errors": 0.0,
                     "deadline_exceeded": 60.0},
        "slo_window": {"window_s": 30.0, "samples": 0},
    }
    stats = GenerationStats.from_merged(block)
    assert stats.errors == 60.0
    g = CanaryGuard(min_canary_requests=10, bad_consecutive=2)
    g.begin(_stats(None, 0, 0), GenerationStats(generation=40))
    base = _stats(None, 500, 0)
    assert g.observe(base, stats) is None
    assert g.observe(base, stats) == "rollback"


def test_guard_silence_does_not_promote_against_live_baseline():
    """Regression: with a baseline that HAS latency signal, a canary
    whose window is too thin to compare must hold (and eventually hit
    the verdict timeout), not rack up 'clean' ticks to a promote."""
    g = CanaryGuard(min_canary_requests=10, good_consecutive=2,
                    min_window_samples=20)
    g.begin(_stats(None, 0, 0), _stats(40, 0, 0))
    live_base = _stats(None, 1000, 0, p99=0.02, samples=100)
    thin_canary = _stats(40, 50, 0, p99=0.5, samples=3)  # 3 samples only
    for _ in range(6):
        assert g.observe(live_base, thin_canary) is None


def test_guard_holds_without_comparable_signal():
    g = CanaryGuard(min_canary_requests=10, bad_consecutive=1,
                    min_window_samples=20)
    g.begin(_stats(None, 0, 0), _stats(40, 0, 0))
    # canary slow BUT baseline window too thin to compare: hold, not kill
    assert g.observe(
        _stats(None, 100, 0, p99=0.01, samples=5),
        _stats(40, 50, 0, p99=0.9, samples=30),
    ) is None
    # an error-free, latency-incomparable canary still promotes on
    # sustained clean traffic (good_consecutive default 3)
    assert g.observe(_stats(None, 150, 0), _stats(40, 80, 0)) is None
    assert g.observe(_stats(None, 200, 0), _stats(40, 110, 0)) == "promote"


# ----------------------------------------------------------------------
# LiveFleetController against stub replicas (deterministic rollouts)
# ----------------------------------------------------------------------


class _StubReplicaServer:
    """A scriptable replica: /healthz + /metrics reflect mutable state;
    /admin/swap + /admin/rollback record calls and flip the advertised
    generation — the controller's entire contract, without jax."""

    def __init__(self):
        self.state = {
            "generation": None,
            "swap_count": 0,
            "requests": 0.0,
            "errors": 0.0,
            "p99": 0.01,
            "samples": 50,
            "refuse_swap": False,
            "admin_log": [],
        }
        state = self.state

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, status, payload):
                body = json.dumps(payload).encode("utf8")
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._reply(200, {
                        "status": "ok",
                        "generation": state["generation"],
                        "swap_count": state["swap_count"],
                    })
                else:
                    self._reply(200, {
                        "generation": state["generation"],
                        "swap_count": state["swap_count"],
                        "counters": {
                            "requests": state["requests"],
                            "errors": state["errors"],
                        },
                        "histograms": {
                            "request_latency_seconds": {
                                "count": state["samples"],
                            },
                        },
                        "slo_window": {
                            "window_s": 30.0,
                            "samples": state["samples"],
                            "request_latency_p99": state["p99"],
                        },
                    })

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n) or b"{}")
                state["admin_log"].append((self.path, body))
                if self.path == "/admin/swap":
                    if state["refuse_swap"]:
                        self._reply(409, {"error": "swap_failed",
                                          "message": "scripted refusal"})
                        return
                    state["prev"] = state["generation"]
                    state["generation"] = body.get("generation")
                    state["swap_count"] += 1
                    self._reply(200, {"generation": state["generation"],
                                      "swap_count": state["swap_count"]})
                elif self.path == "/admin/rollback":
                    state["generation"] = state.get("prev")
                    state["swap_count"] += 1
                    self._reply(200, {"generation": state["generation"]})
                else:
                    self._reply(404, {"error": "not_found"})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()

    @property
    def port(self):
        return self.httpd.server_address[1]

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def stub_fleet():
    stubs = [_StubReplicaServer() for _ in range(2)]
    handles = []
    for i, s in enumerate(stubs):
        h = ReplicaHandle(i)
        h.set_address("127.0.0.1", s.port)
        h.ready = True
        handles.append(h)
    router = Router(lambda: handles, canary_fraction=0.5,
                    probe_timeout_s=5.0)
    yield stubs, handles, router
    for s in stubs:
        s.close()


def test_controller_canary_then_promote(stub_fleet, tmp_path):
    stubs, handles, router = stub_fleet
    _save_generation(tmp_path, TINY_PARAMS, 40)
    guard = CanaryGuard(min_canary_requests=10, good_consecutive=2,
                        bad_consecutive=2)
    ctl = LiveFleetController(
        tmp_path, router, canary_fraction=0.5, guard=guard,
        verdict_timeout_s=300.0,
    )
    assert ctl.poll_once() == "canary"
    # the youngest replica (highest id) canaries
    assert ctl.canary_ids == [1]
    assert router.canary_generation == 40  # split active for the rollout
    assert [p for p, _ in stubs[1].state["admin_log"]] == ["/admin/swap"]
    assert stubs[0].state["admin_log"] == []
    assert handles[1].generation == 40 and handles[0].generation is None
    # healthy canary traffic accrues on the stub's counters
    for _ in range(2):
        stubs[1].state["requests"] += 20
        stubs[0].state["requests"] += 20
        if ctl.poll_once() == "promote":
            break
    assert ctl.phase == "idle" and ctl.current == 40
    assert ctl.promotes == 1
    assert router.canary_generation is None  # split off outside rollouts
    # promote swapped the baseline replica too
    assert ("/admin/swap", {"dir": str(tmp_path), "generation": 40}) in \
        stubs[0].state["admin_log"]
    assert handles[0].generation == 40


def test_controller_forced_regression_auto_rollback(stub_fleet, tmp_path):
    """ISSUE acceptance: a forced-regression canary is auto-rolled-back
    by the guard — the canary replica starts throwing errors after the
    swap, the guard's error-rate trigger fires, the controller rolls the
    canary back and rejects the stamp."""
    stubs, handles, router = stub_fleet
    _save_generation(tmp_path, TINY_PARAMS, 50)
    guard = CanaryGuard(min_canary_requests=10, bad_consecutive=2,
                        error_rate_high=0.05)
    ctl = LiveFleetController(
        tmp_path, router, canary_fraction=0.5, guard=guard,
        verdict_timeout_s=300.0,
    )
    resilience.drain_events()
    assert ctl.poll_once() == "canary"
    # forced regression: the new generation errors on half its traffic
    for _ in range(2):
        stubs[1].state["requests"] += 30
        stubs[1].state["errors"] += 15
        stubs[0].state["requests"] += 30
        verdict = ctl.poll_once()
    assert verdict == "rollback"
    assert ctl.phase == "idle" and ctl.current is None
    assert router.canary_generation is None
    assert ctl.rollbacks == 1 and 50 in ctl.rejected
    assert ("/admin/rollback", {}) in stubs[1].state["admin_log"]
    assert handles[1].generation is None  # restored by the rollback reply
    events = {e["event"] for e in resilience.drain_events()}
    assert "canary-rollback" in events and "live-rollback" in events
    # the rejected stamp is never retried...
    assert ctl.poll_once() is None and ctl.phase == "idle"
    # ...but a NEWER generation is
    _save_generation(tmp_path, TINY_PARAMS, 60)
    assert ctl.poll_once() == "canary" and ctl.target == 60


def test_controller_canary_disappearance_aborts_without_reject(
    stub_fleet, tmp_path
):
    """Regression: if every canary replica leaves the fleet mid-rollout
    (autoscaler scale-down takes the highest ids — exactly the canary
    choice — or they crash), the rollout aborts but the stamp stays
    eligible: a healthy generation must not be rejected for evidence
    that never existed."""
    stubs, handles, router = stub_fleet
    _save_generation(tmp_path, TINY_PARAMS, 70)
    ctl = LiveFleetController(
        tmp_path, router, canary_fraction=0.5,
        guard=CanaryGuard(min_canary_requests=10),
    )
    assert ctl.poll_once() == "canary" and ctl.canary_ids == [1]
    handles[1].ready = False  # scale-down / crash takes the canary
    resilience.drain_events()
    assert ctl.poll_once() is None
    assert ctl.phase == "idle" and 70 not in ctl.rejected
    assert ctl.target is None and router.canary_generation is None
    assert any(
        e["event"] == "live-canary-aborted"
        for e in resilience.drain_events()
    )
    # the canary replica comes back: the SAME stamp rolls out fresh
    handles[1].ready = True
    assert ctl.poll_once() == "canary" and ctl.target == 70


def test_controller_direct_rollout_and_straggler_heal(tmp_path):
    stub = _StubReplicaServer()
    try:
        h = ReplicaHandle(0)
        h.set_address("127.0.0.1", stub.port)
        h.ready = True
        router = Router(lambda: [h])
        _save_generation(tmp_path, TINY_PARAMS, 40)
        ctl = LiveFleetController(tmp_path, router, canary_fraction=0.25)
        # one replica: round(0.25 * 1) -> canary set == whole fleet ->
        # direct rollout, no canary phase
        assert ctl.poll_once() == "promote"
        assert ctl.current == 40 and ctl.phase == "idle"
        # replica crash-restarts from the disk model: heal it back
        stub.state["generation"] = None
        h.generation = None
        assert ctl.poll_once() == "heal"
        assert h.generation == 40
    finally:
        stub.close()


def test_controller_409_rejects_stamp(tmp_path):
    stub = _StubReplicaServer()
    try:
        stub.state["refuse_swap"] = True
        h = ReplicaHandle(0)
        h.set_address("127.0.0.1", stub.port)
        h.ready = True
        router = Router(lambda: [h])
        _save_generation(tmp_path, TINY_PARAMS, 40)
        ctl = LiveFleetController(tmp_path, router, canary_fraction=0.0)
        assert ctl.poll_once() is None
        assert 40 in ctl.rejected  # replica's 409 is permanent
        assert ctl.poll_once() is None  # not retried
    finally:
        stub.close()


# ----------------------------------------------------------------------
# Integration: real fleet tracks a real training run; scored traffic
# improves across a hot-swap with zero 5xx
# ----------------------------------------------------------------------


def _train_config_text(tmp_path, max_steps=30, eval_frequency=10):
    write_synth_jsonl(tmp_path / "train.jsonl", 200, kind="tagger", seed=0)
    write_synth_jsonl(tmp_path / "dev.jsonl", 40, kind="tagger", seed=1)
    base = SERVE_CFG + f"""
[paths]
train = "{(tmp_path / 'train.jsonl').as_posix()}"
dev = "{(tmp_path / 'dev.jsonl').as_posix()}"

[corpora]

[corpora.train]
@readers = "spacy.JsonlCorpus.v1"
path = ${{paths.train}}

[corpora.dev]
@readers = "spacy.JsonlCorpus.v1"
path = ${{paths.dev}}

[training]
seed = 0
dropout = 0.1
accumulate_gradient = 1
patience = 0
max_epochs = 0
max_steps = {max_steps}
eval_frequency = {eval_frequency}

[training.optimizer]
@optimizers = "Adam.v1"
learn_rate = 0.01

[training.batcher]
@batchers = "spacy.batch_by_words.v1"
size = 600
tolerance = 0.2

[training.score_weights]
tag_acc = 1.0
"""
    return base


def test_integration_fleet_tracks_training_scored_traffic_improves(
    tmp_path,
):
    """ISSUE acceptance: the fleet serves continuously while a real
    training subprocess writes generations into the shared checkpoint
    directory; at least one hot-swap occurs under live load with zero
    5xx responses, and accuracy-scored traffic (tags vs synthetic gold)
    measurably improves across the swap."""
    from spacy_ray_tpu.serving.fleet import Fleet, FleetConfig

    cfg_text = _train_config_text(tmp_path)
    (tmp_path / "cfg.cfg").write_text(cfg_text, encoding="utf8")
    # bootstrap model: same config + same corpus (identical labels =>
    # identical tree), but UNTRAINED — the serving quality floor
    nlp = Pipeline.from_config(Config.from_str(cfg_text))
    nlp.initialize(
        lambda: iter(synth_corpus(200, "tagger", seed=0)), seed=0
    )
    model_dir = tmp_path / "model"
    nlp.to_disk(model_dir)
    gold = synth_corpus(40, "tagger", seed=1)
    gold_by_text = {
        " ".join(ex.reference.words): list(ex.reference.tags) for ex in gold
    }
    texts = list(gold_by_text)

    out = tmp_path / "out"
    config = FleetConfig(
        model_path=str(model_dir),
        port=0,
        device="cpu",
        replicas=2,
        max_replicas=2,
        max_batch=4,
        max_doc_len=32,
        probe_interval_s=0.2,
        watch_dir=str(out / "last-model"),
        watch_interval_s=0.3,
        canary_fraction=0.5,
        guard_min_samples=8,
        guard_error_rate=0.2,
        guard_p99_frac=50.0,  # latency on this shared container is noise
        guard_bad_consecutive=3,
        guard_good_consecutive=2,
        guard_verdict_timeout_s=90.0,
        replica_drain_timeout_s=20.0,
        drain_timeout_s=30.0,
    )
    fleet = Fleet(config)
    results = []
    lock = threading.Lock()
    stop = threading.Event()
    train_proc = None
    try:
        host, port = fleet.start()
        assert fleet.wait_ready(2, timeout_s=240.0), "fleet never ready"

        def load(idx):
            i = idx
            while not stop.is_set():
                text = texts[i % len(texts)]
                try:
                    status, payload = _post(
                        host, port, {"texts": [text]}, timeout=60.0
                    )
                except OSError:
                    with lock:
                        results.append((text, -1, None))
                    continue
                with lock:
                    results.append((text, status, payload))
                i += 1
                time.sleep(0.01)

        threads = [
            threading.Thread(target=load, args=(i,), daemon=True)
            for i in range(2)
        ]
        for t in threads:
            t.start()
        # some baseline traffic on the untrained generation first
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            with lock:
                if len(results) >= 20:
                    break
            time.sleep(0.05)

        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        train_proc = subprocess.Popen(
            [
                sys.executable, "-m", "spacy_ray_tpu", "train",
                str(tmp_path / "cfg.cfg"), "--output", str(out),
                "--device", "cpu",
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        # wait for the controller to promote a trained generation, then
        # collect post-swap traffic
        deadline = time.monotonic() + 240.0
        while time.monotonic() < deadline:
            if fleet.controller.current is not None:
                break
            time.sleep(0.2)
        assert fleet.controller.current is not None, (
            "no generation was ever promoted; controller state: "
            f"phase={fleet.controller.phase} rejected="
            f"{fleet.controller.rejected}"
        )
        promoted = fleet.controller.current
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            with lock:
                n_new = sum(
                    1 for _, s, p in results
                    if s == 200 and p["batch"]["generation"] == promoted
                )
            if n_new >= 20:
                break
            time.sleep(0.1)
        assert n_new >= 20, f"only {n_new} post-swap responses"
        # stop the load BEFORE the drain: a post landing after the drain
        # gate flips would record the drain's own (correct) 503 and
        # muddy the zero-5xx-under-swap claim this test is about
        stop.set()
        for t in threads:
            t.join(timeout=90.0)
    finally:
        stop.set()
        if train_proc is not None:
            try:
                train_proc.wait(timeout=120.0)
            except subprocess.TimeoutExpired:
                train_proc.kill()
            if train_proc.stdout is not None:
                train_proc.stdout.read()
                train_proc.stdout.close()
        fleet.request_shutdown()
        rc = fleet.wait()

    assert rc == 0, "fleet drain was not clean"
    statuses = [s for _, s, _ in results]
    assert all(200 <= s < 500 for s in statuses), (
        f"5xx/failed responses under live swap: "
        f"{[s for s in statuses if not 200 <= s < 500][:10]}"
    )
    gens = {p["batch"]["generation"] for _, s, p in results if s == 200}
    assert None in gens and promoted in gens, (
        f"swap did not happen under live load: generations {gens}"
    )

    def accuracy(gen):
        correct = total = 0
        for text, s, p in results:
            if s != 200 or p["batch"]["generation"] != gen:
                continue
            tags = p["docs"][0]["tags"]
            for got, want in zip(tags, gold_by_text[text]):
                correct += got == want
                total += 1
        return correct / max(total, 1), total

    acc_before, n_before = accuracy(None)
    acc_after, n_after = accuracy(promoted)
    assert n_before > 0 and n_after > 0
    assert acc_after > 0.9, f"trained generation scored {acc_after:.3f}"
    assert acc_after >= acc_before + 0.2, (
        f"scored traffic did not improve across the swap: "
        f"{acc_before:.3f} (untrained, n={n_before}) -> "
        f"{acc_after:.3f} (gen {promoted}, n={n_after})"
    )


# ----------------------------------------------------------------------
# train-and-serve: subprocess SIGTERM drains trainer AND fleet, rc=0
# ----------------------------------------------------------------------


def test_train_and_serve_sigterm_drains_both_rc0(tmp_path):
    """ISSUE satellite: the orchestrated CLI — one SIGTERM drains the
    training subprocess (checkpoint + preempted-clean exit) and the
    serving fleet (finish in-flight, replicas exit 0) — whole tree
    exits 0. Exercises the bootstrap path too: the fleet's model is
    snapshotted from the run's first best-model save."""
    cfg_text = _train_config_text(
        tmp_path, max_steps=5000, eval_frequency=10
    )
    (tmp_path / "cfg.cfg").write_text(cfg_text, encoding="utf8")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "spacy_ray_tpu", "train-and-serve",
            str(tmp_path / "cfg.cfg"), "--output", str(tmp_path / "out"),
            "--device", "cpu", "--replicas", "1", "--port", "0",
            "--max-batch", "4", "--max-doc-len", "16",
            "--watch-interval-s", "0.5", "--drain-timeout-s", "60",
        ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env,
    )
    lines = []
    ready = threading.Event()

    def reader():
        for line in proc.stdout:
            lines.append(line)
            if line.startswith("fleet ready:"):
                ready.set()

    threading.Thread(target=reader, daemon=True).start()
    try:
        assert ready.wait(timeout=420.0), (
            f"train-and-serve never became ready:\n{''.join(lines)}"
        )
        time.sleep(1.0)  # live: trainer still running, fleet serving
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=180.0)
        out = "".join(lines)
        assert rc == 0, f"train-and-serve exit {rc}:\n{out}"
        assert "train-and-serve drained" in out, out
        assert "trainer rc 75 = preempted-clean" in out or (
            "trainer rc 0" in out
        ), out
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10.0)
