"""In-process alert engine (spacy_ray_tpu/alerting.py): burn-rate
window-pair matrix under a fake clock (fast-fires, slow-confirms,
both-windows gate, resolve-on-recovery), threshold for-duration
lifecycle, signal-absence, the scrape-failure page (PR 10's counter
grown into a first-class rule), the JSONL sink + Prometheus export, and
the acceptance path: a synthetic SLO breach driven pending → firing →
resolved with the state visible in Prometheus exposition, /admin/alerts
over real HTTP, and `telemetry top`.
"""

import json
import threading

import pytest

from spacy_ray_tpu.alerting import (
    AbsenceRule,
    AlertEngine,
    BurnRateRule,
    SnapshotHistory,
    ThresholdRule,
    default_router_rules,
    default_serving_rules,
    default_training_rules,
    process_rules,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)


def _counters(**kw):
    return {"counters": dict(kw)}


def _drive(engine, clock, steps, dt, make_snapshot):
    """Advance `steps` ticks of `dt` seconds, evaluating after each."""
    for i in range(steps):
        clock.advance(dt)
        engine.evaluate(make_snapshot(i))


# ----------------------------------------------------------------------
# SnapshotHistory
# ----------------------------------------------------------------------


def test_history_delta_requires_window_span():
    h = SnapshotHistory(["counters.x"])
    h.append(0.0, _counters(x=10))
    h.append(5.0, _counters(x=20))
    # history spans only 5s: a 60s delta would overstate freshness
    assert h.delta("counters.x", 60.0, 5.0) is None
    assert h.delta("counters.x", 5.0, 5.0) == 10.0
    # counter reset clamps to zero, never a negative burn
    h.append(10.0, _counters(x=3))
    assert h.delta("counters.x", 5.0, 10.0) == 0.0


def test_history_value_reads_full_snapshot_paths():
    h = SnapshotHistory(["counters.x"])
    h.append(0.0, {"slo_window": {"p99": 0.25}, "counters": {"x": 1}})
    assert h.value("slo_window.p99") == 0.25
    assert h.value("slo_window.missing") is None


# ----------------------------------------------------------------------
# Threshold: pending -> firing -> resolved under for-duration
# ----------------------------------------------------------------------


def test_threshold_for_duration_lifecycle():
    clock = FakeClock()
    eng = AlertEngine(
        [ThresholdRule("p99-slo", "slo_window.p99", ">", 0.5, for_s=30.0)],
        clock=clock,
    )
    eng.evaluate({"slo_window": {"p99": 0.1}})
    assert eng.states()[0]["state"] == "inactive"
    clock.advance(10.0)
    eng.evaluate({"slo_window": {"p99": 0.9}})  # breach begins
    assert eng.states()[0]["state"] == "pending"
    clock.advance(10.0)
    eng.evaluate({"slo_window": {"p99": 0.9}})  # 10s < for_s
    assert eng.states()[0]["state"] == "pending"
    clock.advance(25.0)
    eng.evaluate({"slo_window": {"p99": 0.9}})  # 35s >= for_s: confirmed
    st = eng.states()[0]
    assert st["state"] == "firing" and st["fired_count"] == 1
    clock.advance(5.0)
    eng.evaluate({"slo_window": {"p99": 0.2}})  # recovery resolves
    st = eng.states()[0]
    assert st["state"] == "inactive"
    assert st["last_resolved"] == clock.t


def test_threshold_pending_cancelled_by_recovery_never_fires():
    clock = FakeClock()
    eng = AlertEngine(
        [ThresholdRule("p99-slo", "slo_window.p99", ">", 0.5, for_s=30.0)],
        clock=clock,
    )
    eng.evaluate({"slo_window": {"p99": 0.9}})
    assert eng.states()[0]["state"] == "pending"
    clock.advance(10.0)
    eng.evaluate({"slo_window": {"p99": 0.1}})  # blip, not an incident
    st = eng.states()[0]
    assert st["state"] == "inactive" and st["fired_count"] == 0


def test_threshold_no_signal_is_inactive():
    clock = FakeClock()
    eng = AlertEngine(
        [ThresholdRule("p99-slo", "slo_window.p99", ">", 0.5)], clock=clock
    )
    eng.evaluate({})  # the path does not exist: no signal, no alert
    st = eng.states()[0]
    assert st["state"] == "inactive" and "no signal" in st["detail"]


def test_threshold_window_delta_mode():
    """window_s turns the rule into an event-rate condition: counter
    increase over the trailing window vs the bound."""
    clock = FakeClock()
    eng = AlertEngine(
        [ThresholdRule("burst", "counters.x", ">=", 3.0, window_s=60.0)],
        clock=clock,
    )
    x = 0
    # quiet minute to span the window
    for _ in range(7):
        clock.advance(10.0)
        eng.evaluate(_counters(x=x))
    assert eng.states()[0]["state"] == "inactive"
    x += 3  # three events inside one window
    clock.advance(10.0)
    eng.evaluate(_counters(x=x))
    assert eng.states()[0]["state"] == "firing"
    # the window slides past the burst: resolves
    for _ in range(7):
        clock.advance(10.0)
        eng.evaluate(_counters(x=x))
    assert eng.states()[0]["state"] == "inactive"


# ----------------------------------------------------------------------
# Absence: the signal-died failure mode
# ----------------------------------------------------------------------


def test_absence_fires_on_stalled_counter_and_resolves():
    clock = FakeClock()
    eng = AlertEngine(
        [AbsenceRule("stalled", "counters.steps", stale_s=60.0)],
        clock=clock,
    )
    eng.evaluate(_counters(steps=1))
    for _ in range(5):
        clock.advance(10.0)
        eng.evaluate(_counters(steps=1))  # unchanged 50s: not yet stale
    assert eng.states()[0]["state"] == "inactive"
    clock.advance(15.0)
    eng.evaluate(_counters(steps=1))  # 65s unchanged
    assert eng.states()[0]["state"] == "firing"
    clock.advance(1.0)
    eng.evaluate(_counters(steps=2))  # progress resolves instantly
    assert eng.states()[0]["state"] == "inactive"


def test_absence_never_observed_is_no_signal():
    clock = FakeClock()
    eng = AlertEngine(
        [AbsenceRule("stalled", "counters.steps", stale_s=60.0)],
        clock=clock,
    )
    clock.advance(500.0)
    eng.evaluate({})  # the subsystem never ran: silence is not a stall
    assert eng.states()[0]["state"] == "inactive"


# ----------------------------------------------------------------------
# Burn rate: the window-pair matrix, fake clock
# ----------------------------------------------------------------------

FAST = (300.0, 60.0, 14.4)
SLOW = (1800.0, 300.0, 6.0)


def _burn_engine(clock, windows, slo=0.99):
    return AlertEngine(
        [
            BurnRateRule(
                "budget-burn",
                total="counters.requests",
                bad="counters.errors",
                slo=slo,
                windows=windows,
            )
        ],
        clock=clock,
    )


class _Traffic:
    """Deterministic request/error stream: rate per tick, error fraction
    switchable mid-run."""

    def __init__(self):
        self.requests = 0
        self.errors = 0

    def tick(self, n=100, error_frac=0.0):
        bad = int(n * error_frac)
        self.requests += n
        self.errors += bad
        return _counters(requests=self.requests, errors=self.errors)


def test_burn_fast_pair_fires_on_total_outage():
    clock = FakeClock()
    eng = _burn_engine(clock, (FAST,))
    tr = _Traffic()
    _drive(eng, clock, 35, 10.0, lambda i: tr.tick())  # clean 350s
    assert eng.states()[0]["state"] == "inactive"
    # 100% errors: burn = 100x budget >> 14.4 in BOTH windows fast
    _drive(eng, clock, 7, 10.0, lambda i: tr.tick(error_frac=1.0))
    st = eng.states()[0]
    assert st["state"] == "firing", st
    assert st["value"] > 14.4


def test_burn_below_factor_never_fires_fast_pair():
    clock = FakeClock()
    eng = _burn_engine(clock, (FAST,))
    tr = _Traffic()
    # 8% errors = 8x budget: real burn, but under the 14.4x page bar
    _drive(eng, clock, 80, 10.0, lambda i: tr.tick(error_frac=0.08))
    assert eng.states()[0]["state"] == "inactive"


def test_burn_slow_pair_confirms_moderate_sustained_burn():
    """The 8x burn the fast pair ignores (8 < 14.4) is exactly what the
    slow pair exists for: it fires — but only once its SHORT window
    (300s) is spanned, never from a young process's first bad ticks."""
    clock = FakeClock()
    eng = _burn_engine(clock, (FAST, SLOW))
    tr = _Traffic()
    fired_at = None
    for i in range(200):  # 2000s at 10s ticks
        clock.advance(10.0)
        eng.evaluate(tr.tick(error_frac=0.08))
        if eng.states()[0]["state"] == "firing" and fired_at is None:
            fired_at = (i + 1) * 10.0
    assert fired_at is not None, "slow pair never confirmed"
    # gated on the slow pair's short window (300s); the sustained-burn
    # ratio over the partial long window is what confirms it
    assert 300.0 <= fired_at <= 700.0, fired_at


def test_burn_boot_time_outage_pages_after_short_window():
    """Early-life semantics: a replica failing EVERYTHING from boot must
    page once the fast pair's short window is spanned — not sit
    page-blind for the long window's full 300s."""
    clock = FakeClock()
    eng = _burn_engine(clock, (FAST,))
    tr = _Traffic()
    fired_at = None
    for i in range(12):  # 120s at 10s ticks, 100% errors throughout
        clock.advance(10.0)
        eng.evaluate(tr.tick(error_frac=1.0))
        if eng.states()[0]["state"] == "firing" and fired_at is None:
            fired_at = (i + 1) * 10.0
    assert fired_at is not None and 60.0 <= fired_at <= 90.0, fired_at
    # ...but before the short window is spanned: no signal, no page
    clock2 = FakeClock()
    eng2 = _burn_engine(clock2, (FAST,))
    tr2 = _Traffic()
    clock2.advance(10.0)
    eng2.evaluate(tr2.tick(error_frac=1.0))  # one bad tick, 10s old
    st = eng2.states()[0]
    assert st["state"] == "inactive" and "no signal" in st["detail"]


def test_burn_short_burst_does_not_sustain_long_window():
    """Both windows must burn: a 60s error burst inside an otherwise
    clean 300s long window lights the short window only — no page."""
    clock = FakeClock()
    eng = _burn_engine(clock, ((300.0, 60.0, 50.0),))
    tr = _Traffic()
    _drive(eng, clock, 35, 10.0, lambda i: tr.tick())
    # 60s at 60% errors: short burn 60x >= 50, long burn ~12x < 50
    _drive(eng, clock, 6, 10.0, lambda i: tr.tick(error_frac=0.6))
    assert eng.states()[0]["state"] == "inactive"


def test_burn_resolves_on_recovery_while_long_window_still_hot():
    """The short window is the resolve lever: once the bleeding stops,
    the alert clears within ~short_s even though the long window still
    remembers the incident."""
    clock = FakeClock()
    eng = _burn_engine(clock, (FAST,))
    tr = _Traffic()
    _drive(eng, clock, 35, 10.0, lambda i: tr.tick())
    _drive(eng, clock, 12, 10.0, lambda i: tr.tick(error_frac=1.0))
    assert eng.states()[0]["state"] == "firing"
    resolved_after = None
    for i in range(30):
        clock.advance(10.0)
        eng.evaluate(tr.tick())
        if eng.states()[0]["state"] == "inactive":
            resolved_after = (i + 1) * 10.0
            break
    assert resolved_after is not None
    # within roughly the short window, NOT the long one
    assert resolved_after <= 120.0, resolved_after
    # the long window alone is indeed still over the factor right then
    rule = eng.rules[0]
    assert rule._burn(eng.history, 300.0, clock.t) >= 14.4


def test_burn_zero_traffic_is_no_signal():
    clock = FakeClock()
    eng = _burn_engine(clock, (FAST,))
    for _ in range(40):
        clock.advance(10.0)
        eng.evaluate(_counters(requests=0, errors=0))
    st = eng.states()[0]
    assert st["state"] == "inactive" and "no signal" in st["detail"]


def test_burn_rule_validation():
    with pytest.raises(ValueError):
        BurnRateRule("x", total="a", bad="b", slo=1.5)
    with pytest.raises(ValueError):
        BurnRateRule("x", total="a", bad="b", windows=())
    with pytest.raises(ValueError):
        BurnRateRule("x", total="a", bad="b", windows=((60.0, 300.0, 2.0),))
    with pytest.raises(ValueError):
        BurnRateRule("x", total="a", bad="b", windows=((300.0, 60.0, 0.0),))


# ----------------------------------------------------------------------
# Engine: sink, hooks, export
# ----------------------------------------------------------------------


def test_engine_rejects_duplicate_rule_names():
    with pytest.raises(ValueError):
        AlertEngine(
            [
                ThresholdRule("dup", "a", ">", 1.0),
                AbsenceRule("dup", "b", stale_s=1.0),
            ]
        )


def test_engine_sink_rows_record_every_transition(tmp_path):
    clock = FakeClock()
    sink = tmp_path / "alerts.jsonl"
    eng = AlertEngine(
        [ThresholdRule("slo", "gauges.v", ">", 1.0, for_s=10.0)],
        clock=clock,
        sink_path=sink,
        source="test",
    )
    eng.evaluate({"gauges": {"v": 5.0}})  # -> pending
    clock.advance(15.0)
    eng.evaluate({"gauges": {"v": 5.0}})  # -> firing
    clock.advance(5.0)
    eng.evaluate({"gauges": {"v": 0.0}})  # -> resolved
    rows = [
        json.loads(line)
        for line in sink.read_text(encoding="utf8").splitlines()
    ]
    assert [(r["from"], r["to"]) for r in rows] == [
        ("inactive", "pending"),
        ("pending", "firing"),
        ("firing", "inactive"),
    ]
    assert all(r["kind"] == "alert" and r["source"] == "test" for r in rows)


def test_on_firing_hook_may_reenter_engine_without_deadlock():
    """Regression: the production wiring points on_firing at the flight
    recorder, whose dump captures the alert states via states() — which
    takes the engine lock. The hook therefore MUST run outside the
    evaluation lock, or the first real firing self-deadlocks the
    observer thread (and every /metrics reader behind it)."""
    clock = FakeClock()
    captured = []
    eng = AlertEngine(
        [ThresholdRule("slo", "gauges.v", ">", 1.0)],
        clock=clock,
        on_firing=lambda rule, st: captured.append(
            (eng.states(), eng.summary())  # re-enters the engine
        ),
    )
    done = []
    t = threading.Thread(
        target=lambda: done.append(eng.evaluate({"gauges": {"v": 5.0}}))
    )
    t.start()
    t.join(timeout=10.0)
    assert done, "evaluate() deadlocked inside the on_firing hook"
    states, summary = captured[0]
    assert states[0]["state"] == "firing" and summary["firing"] == 1


def test_on_firing_hook_called_once_per_firing():
    clock = FakeClock()
    fired = []
    eng = AlertEngine(
        [ThresholdRule("slo", "gauges.v", ">", 1.0)],
        clock=clock,
        on_firing=lambda rule, st: fired.append(rule.name),
    )
    for v in (5.0, 5.0, 5.0):  # stays firing: hook fires once
        clock.advance(1.0)
        eng.evaluate({"gauges": {"v": v}})
    eng.evaluate({"gauges": {"v": 0.0}})
    clock.advance(1.0)
    eng.evaluate({"gauges": {"v": 5.0}})  # re-fires after resolve
    assert fired == ["slo", "slo"]


def test_prometheus_export_states_and_fired_totals():
    from spacy_ray_tpu.training.prometheus import PromFamilies

    clock = FakeClock()
    eng = AlertEngine(
        [
            ThresholdRule("hot", "gauges.v", ">", 1.0),
            ThresholdRule("cold", "gauges.v", "<", -1.0),
        ],
        clock=clock,
    )
    eng.evaluate({"gauges": {"v": 5.0}})
    fam = PromFamilies()
    eng.add_prometheus(fam)
    text = fam.render()
    assert 'srt_alert_state{alert="hot",severity="page"} 2' in text
    assert 'srt_alert_state{alert="cold",severity="page"} 0' in text
    assert 'srt_alert_fired_total{alert="hot"} 1' in text


def test_summary_block_shape():
    clock = FakeClock()
    eng = AlertEngine(
        [
            ThresholdRule("hot", "gauges.v", ">", 1.0),
            ThresholdRule("warm", "gauges.v", ">", 1.0, for_s=60.0),
        ],
        clock=clock,
    )
    eng.evaluate({"gauges": {"v": 5.0}})
    s = eng.summary()
    assert s["rules"] == 2 and s["firing"] == 1 and s["pending"] == 1
    assert s["firing_names"] == ["hot"]


# ----------------------------------------------------------------------
# Satellite: the scrape-failure counter grown into a first-class page
# ----------------------------------------------------------------------


def _router_snap(*, requests=0, no_replica=0, draining=0, ready=2,
                 scrape_failures=0, p99=None):
    return {
        "router": {
            "counters": {
                "requests": requests,
                "rejected_no_replica": no_replica,
                "rejected_draining": draining,
                "scrape_failures": scrape_failures,
            },
            "gauges": {"ready_replicas": ready},
            "slo": {"router_latency_p99": p99},
        },
    }


def test_scrape_failure_rule_pages_on_repeated_failures():
    clock = FakeClock()
    eng = AlertEngine(default_router_rules(), clock=clock)

    def state(name):
        return next(r for r in eng.states() if r["alert"] == name)

    failures = 0
    # quiet 130s so the 120s delta window is spanned
    for _ in range(13):
        clock.advance(10.0)
        eng.evaluate(_router_snap(scrape_failures=failures))
    assert state("replica-unscrapable")["state"] == "inactive"
    # one transient failed scrape: increments, but no page
    failures += 1
    clock.advance(10.0)
    eng.evaluate(_router_snap(scrape_failures=failures))
    assert state("replica-unscrapable")["state"] == "inactive"
    # a replica that KEEPS failing its scrape: 3 within the window pages
    for _ in range(2):
        failures += 1
        clock.advance(10.0)
        eng.evaluate(_router_snap(scrape_failures=failures))
    assert state("replica-unscrapable")["state"] == "firing"
    # failures stop; the window slides past them and the page resolves
    for _ in range(15):
        clock.advance(10.0)
        eng.evaluate(_router_snap(scrape_failures=failures))
    assert state("replica-unscrapable")["state"] == "inactive"


def test_no_ready_replica_rule_arms_after_first_ready():
    """A fleet cold start legitimately has zero ready replicas for the
    whole bucket-warmup sweep (minutes): the rule must NOT page on
    boot, only once the fleet has been ready at least once."""
    clock = FakeClock()
    eng = AlertEngine(default_router_rules(), clock=clock)

    def state(name):
        return next(r for r in eng.states() if r["alert"] == name)

    # cold start: minutes of ready=0 never arm a page
    for _ in range(20):
        clock.advance(15.0)
        eng.evaluate(_router_snap(ready=0))
    st = state("no-ready-replica")
    assert st["state"] == "inactive" and "not armed" in st["detail"]
    # fleet becomes ready: the rule arms
    clock.advance(5.0)
    eng.evaluate(_router_snap(ready=2))
    assert state("no-ready-replica")["state"] == "inactive"
    # NOW a total loss of ready replicas pages after the for-duration
    clock.advance(5.0)
    eng.evaluate(_router_snap(ready=0))
    assert state("no-ready-replica")["state"] == "pending"
    clock.advance(15.0)
    eng.evaluate(_router_snap(ready=0))
    assert state("no-ready-replica")["state"] == "firing"
    clock.advance(1.0)
    eng.evaluate(_router_snap(ready=2))
    assert state("no-ready-replica")["state"] == "inactive"


def test_serving_burn_pages_on_full_rejection_outage():
    """Regression: `requests` only counts ADMITTED requests — a replica
    rejecting 100% of its traffic with queue-full 429s increments only
    the reject counter. The denominator includes it, so the outage burns
    instead of reading as 'no traffic'."""
    clock = FakeClock()
    eng = AlertEngine(default_serving_rules(), clock=clock)

    def state(name):
        return next(r for r in eng.states() if r["alert"] == name)

    admitted, rejected = 0, 0
    # healthy minute+ to span the fast pair's short window
    for _ in range(8):
        clock.advance(10.0)
        admitted += 100
        eng.evaluate(
            {"counters": {"requests": admitted,
                          "rejected_queue_full": rejected}}
        )
    assert state("serving-error-budget-burn")["state"] == "inactive"
    # total outage: zero admissions, every request rejected 429
    for _ in range(7):
        clock.advance(10.0)
        rejected += 100
        eng.evaluate(
            {"counters": {"requests": admitted,
                          "rejected_queue_full": rejected}}
        )
    assert state("serving-error-budget-burn")["state"] == "firing"


def test_default_rule_sets_construct():
    # every documented default set builds and carries unique names
    for rules in (
        default_serving_rules(),
        default_router_rules(),
        default_training_rules(),
    ):
        AlertEngine(rules)


def test_default_rule_sets_carry_process_rules():
    # PR 18: every role set watches its own process for rss/fd leaks
    for rules in (
        default_serving_rules(),
        default_router_rules(),
        default_training_rules(),
    ):
        names = {r.name for r in rules}
        assert {"process-rss-growth", "process-fd-leak"} <= names


# ----------------------------------------------------------------------
# Process leak rules (PR 18): rss growth + fd leak lifecycles
# ----------------------------------------------------------------------

MB = 1024 * 1024


def _proc_snap(rss_mb, fds=10):
    return {"process": {"rss_bytes": rss_mb * MB, "open_fds": fds}}


def _proc_state(eng, name):
    return next(r for r in eng.states() if r["alert"] == name)


def test_process_rss_growth_fires_on_monotone_leak():
    clock = FakeClock()
    eng = AlertEngine(process_rules(), clock=clock)
    # a steady process spanning the 600s window: net growth 0, quiet
    rss = 500
    for _ in range(11):
        clock.advance(60.0)
        eng.evaluate(_proc_snap(rss))
    assert _proc_state(eng, "process-rss-growth")["state"] == "inactive"
    # a monotone leak: +50MB/min accumulates past 256MB inside 600s
    for _ in range(6):
        clock.advance(60.0)
        rss += 50
        eng.evaluate(_proc_snap(rss))
    st = _proc_state(eng, "process-rss-growth")
    assert st["state"] == "firing" and st["severity"] == "ticket"
    # the leak stops (plateau): the window slides past it and resolves
    for _ in range(11):
        clock.advance(60.0)
        eng.evaluate(_proc_snap(rss))
    assert _proc_state(eng, "process-rss-growth")["state"] == "inactive"


def test_process_rss_sawtooth_allocator_stays_quiet():
    # an allocator that borrows and RETURNS memory (batch buffers):
    # net-delta clamping keeps the windowed growth under the bound
    clock = FakeClock()
    eng = AlertEngine(process_rules(), clock=clock)
    for i in range(30):
        clock.advance(60.0)
        eng.evaluate(_proc_snap(500 + (100 if i % 2 else 0)))
        assert _proc_state(eng, "process-rss-growth")["state"] == "inactive"


def test_process_rss_short_lived_process_is_no_signal():
    # younger than the window: no partial fallback, no false ticket on
    # a CLI run that legitimately allocates its working set at boot
    clock = FakeClock()
    eng = AlertEngine(process_rules(), clock=clock)
    eng.evaluate(_proc_snap(100))
    clock.advance(30.0)
    eng.evaluate(_proc_snap(500))  # +400MB, but only 30s of history
    assert _proc_state(eng, "process-rss-growth")["state"] == "inactive"


def test_process_fd_leak_arms_only_after_healthy_baseline():
    clock = FakeClock()
    eng = AlertEngine(process_rules(), clock=clock)
    # boots already above the limit: that's its normal, never arms
    for _ in range(10):
        clock.advance(30.0)
        eng.evaluate(_proc_snap(100, fds=600))
    st = _proc_state(eng, "process-fd-leak")
    assert st["state"] == "inactive" and "not armed" in st["detail"]
    # seen healthy once (<= limit/2): the rule arms
    clock.advance(30.0)
    eng.evaluate(_proc_snap(100, fds=40))
    # a real leak: above the limit, held past for_s -> ticket
    clock.advance(30.0)
    eng.evaluate(_proc_snap(100, fds=700))
    assert _proc_state(eng, "process-fd-leak")["state"] == "pending"
    clock.advance(90.0)
    eng.evaluate(_proc_snap(100, fds=700))
    st = _proc_state(eng, "process-fd-leak")
    assert st["state"] == "firing" and st["severity"] == "ticket"
    # fds come back down: resolved
    clock.advance(10.0)
    eng.evaluate(_proc_snap(100, fds=50))
    assert _proc_state(eng, "process-fd-leak")["state"] == "inactive"


def test_process_rules_missing_proc_surface_is_no_signal():
    # a hostile /proc (or a platform without one): both rules no-signal
    clock = FakeClock()
    eng = AlertEngine(process_rules(), clock=clock)
    for _ in range(25):
        clock.advance(60.0)
        eng.evaluate({"process": {"rss_bytes": None, "open_fds": None}})
    for name in ("process-rss-growth", "process-fd-leak"):
        st = _proc_state(eng, name)
        assert st["state"] == "inactive"


# ----------------------------------------------------------------------
# Acceptance: synthetic SLO breach, state visible on every surface
# ----------------------------------------------------------------------


def test_synthetic_slo_breach_visible_everywhere(tmp_path):
    """The ISSUE 12 acceptance path: a fake-clock-driven latency-SLO
    breach runs pending → firing → resolved, and while firing the state
    is readable in (a) Prometheus exposition, (b) /admin/alerts over a
    real router listener, and (c) the `telemetry top` rendering."""
    from spacy_ray_tpu.serving.fleet import Router, RouterHTTPServer
    from spacy_ray_tpu.top import TopModel, render

    clock = FakeClock()
    eng = AlertEngine(
        default_router_rules(p99_target_s=0.5), clock=clock,
        sink_path=tmp_path / "alerts.jsonl",
    )
    router = Router(lambda: [])
    router.alerts = eng

    # breach: window p99 3x the target, confirmed over for_s
    eng.evaluate(_router_snap(p99=1.5))
    assert any(r["state"] == "pending" for r in eng.states())
    clock.advance(31.0)
    eng.evaluate(_router_snap(p99=1.5))
    firing = [r for r in eng.states() if r["state"] == "firing"]
    assert [r["alert"] for r in firing] == ["fleet-latency-slo"]

    httpd = RouterHTTPServer(("127.0.0.1", 0), router)
    threading.Thread(
        target=httpd.serve_forever, kwargs={"poll_interval": 0.05},
        daemon=True,
    ).start()
    host, port = httpd.server_address[:2]
    try:
        import http.client

        conn = http.client.HTTPConnection(host, port, timeout=10.0)
        try:
            conn.request("GET", "/admin/alerts")
            resp = conn.getresponse()
            payload = json.loads(resp.read())
        finally:
            conn.close()
        assert resp.status == 200
        row = payload["alerts"][0]  # firing sorts first
        assert row["alert"] == "fleet-latency-slo"
        assert row["state"] == "firing"

        conn = http.client.HTTPConnection(host, port, timeout=10.0)
        try:
            conn.request("GET", "/metrics?format=prometheus")
            resp = conn.getresponse()
            text = resp.read().decode("utf8")
        finally:
            conn.close()
        assert (
            'srt_alert_state{alert="fleet-latency-slo",severity="page"} 2'
            in text
        )

        # telemetry top renders the alert column from the /metrics JSON
        conn = http.client.HTTPConnection(host, port, timeout=10.0)
        try:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            metrics = json.loads(resp.read())
        finally:
            conn.close()
        assert metrics["alerts"]["firing_names"] == ["fleet-latency-slo"]
        model = TopModel()
        screen = render([model.update("http://x", metrics, 0.0)])
        assert "FIRING fleet-latency-slo" in screen
    finally:
        httpd.shutdown()
        httpd.server_close()

    # recovery resolves
    clock.advance(5.0)
    eng.evaluate(_router_snap(p99=0.1))
    assert all(r["state"] == "inactive" for r in eng.states())
    rows = [
        json.loads(line)
        for line in (tmp_path / "alerts.jsonl").read_text().splitlines()
        if json.loads(line)["alert"] == "fleet-latency-slo"
    ]
    assert [(r["from"], r["to"]) for r in rows] == [
        ("inactive", "pending"),
        ("pending", "firing"),
        ("firing", "inactive"),
    ]
